"""Multi-host training support (the reference's distributed runtime).

The reference runs one CLI process per machine connected by a
hand-rolled socket/MPI collective layer (src/network/linkers_socket.cpp
full-mesh TCP, network.cpp ring/halving collectives). The TPU-native
equivalent is JAX's multi-controller runtime: one process per host,
`jax.distributed.initialize` forms the cluster, and every collective in
the growers (psum / all_gather / psum_scatter) rides ICI within a slice
and DCN across hosts through the SAME code path as single-host — no
separate network layer.

This module maps the reference's network configuration
(`machines` / `machine_list_filename` / `num_machines` /
`local_listen_port`, config.h network params; python
`lgb.set_network`) onto `jax.distributed.initialize`, and provides the
pre-partitioned data assembly (`pre_partition=true` semantics,
dataset_loader.cpp:210: each rank holds its own row shard):

- `init_distributed(...)`: join/form the cluster.
- `allgather_binning_sample(sample)`: the reference's distributed
  binning (dataset_loader.cpp:1174: per-rank FindBin samples are
  allgathered so every rank builds IDENTICAL bin mappers).
- `global_rows(host_array, mesh, row_axis)`: assemble a process-local
  row shard into one global device array over the mesh
  (jax.make_array_from_process_local_data).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def resolve_rank(machines: Sequence[str], local_listen_port: int) -> int:
    """Best-effort self-rank discovery by local address match (the
    reference matches local IPs against the machine list,
    linkers_socket.cpp:38-49); falls back to the JAX_PROCESS_ID env."""
    import os
    import socket

    env = os.environ.get("JAX_PROCESS_ID")
    if env is not None:
        return int(env)
    local_names = {socket.gethostname(), "localhost", "127.0.0.1"}
    try:
        local_names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    for i, m in enumerate(machines):
        host, _, port = m.partition(":")
        if host in local_names and (not port or int(port) == local_listen_port):
            return i
    raise RuntimeError(
        "cannot determine this process's rank: no machine entry matches a "
        "local address; set JAX_PROCESS_ID or pass machine_rank"
    )


def init_distributed(
    machines: Optional[str] = None,
    machine_list_file: Optional[str] = None,
    num_machines: Optional[int] = None,
    local_listen_port: int = 12400,
    machine_rank: Optional[int] = None,
) -> int:
    """Join the multi-host cluster from reference-style network params.

    The first machine in the list is the coordinator (the reference has
    no coordinator — its socket mesh is symmetric — but rank 0 is the
    canonical choice). Returns this process's rank. No-op when the
    cluster is already initialized.
    """
    import jax

    # NOTE: no jax.process_count()/devices() probe here — touching the
    # backend before jax.distributed.initialize() poisons it
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "client", None) is not None:
        return jax.process_index()
    mlist = []
    if machine_list_file:
        with open(machine_list_file) as f:
            mlist = [ln.strip() for ln in f if ln.strip()]
    elif machines:
        mlist = [m.strip() for m in machines.split(",") if m.strip()]
    if not mlist:
        raise ValueError("init_distributed needs machines or machine_list_file")
    n = num_machines or len(mlist)
    rank = machine_rank if machine_rank is not None else resolve_rank(
        mlist, local_listen_port
    )
    coord = mlist[0]
    if ":" not in coord:
        coord = f"{coord}:{local_listen_port}"
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=n, process_id=rank
    )
    return rank


def allgather_binning_sample(sample: np.ndarray) -> np.ndarray:
    """Concatenate every process's binning sample (rows) so all ranks
    derive identical BinMappers (dataset_loader.cpp:1174-1250)."""
    import jax
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return sample
    gathered = multihost_utils.process_allgather(sample)
    return np.asarray(gathered).reshape(-1, sample.shape[-1])


def global_rows(arr: np.ndarray, mesh, axis: int = 0):
    """Assemble per-process row shards into one global array sharded
    over the mesh's 'data' axis (pre_partition semantics: this
    process's rows are its shard; shards concatenate in process order).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * arr.ndim
    spec[axis] = "data"
    sharding = NamedSharding(mesh, P(*spec))
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)
