"""Feature-parallel tree learner over a 1-D mesh.

The reference's feature-parallel design
(src/treelearner/feature_parallel_tree_learner.cpp, decl
parallel_tree_learner.h:26): every rank holds ALL rows, features are
partitioned across ranks, each rank scans only its own features, and
the global best split is an allreduce-max (SyncUpGlobalBestSplit) —
no histogram traffic at all, only one small split record plus (here)
one per-row bit-vector psum from the winning shard.

TPU formulation: shard_map over a ("feature",) mesh with the FLAT
grower (grower.py spec.feature_axis) — rows replicated, the bin
matrix sharded on its feature axis, per-feature tables sharded
alongside. The feature axis is padded with trivial 1-bin columns to a
multiple of the mesh size (a 1-bin feature has no valid threshold, so
padding can never win a split).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..learner.grower import GrowerSpec, TreeArrays, grow_tree
from ..learner.split import SplitParams
from .data_parallel import shard_map_compat


class FeatureParallelGrower:
    """Wraps the flat grower in shard_map over a 1-D feature mesh."""

    def __init__(self, mesh: Mesh, spec: GrowerSpec, axis_name: str = "feature"):
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_dev = mesh.devices.size
        self.spec = spec._replace(
            partition="flat", feature_axis=axis_name, axis_name=None
        )

        fshard = P(axis_name)  # per-feature tables
        bins_spec = P(axis_name, None)  # (F, N): features on axis 0
        rep = P()

        def fn(bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
               feat_mask, params, valid):
            tree, row_leaf = grow_tree(
                bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
                feat_mask, params, self.spec, valid=valid,
            )
            # tree state is identical on every shard (built from the
            # all-gathered winner records); mark it replicated
            tree = jax.tree.map(
                lambda a: jax.lax.pmean(a, axis_name)
                if jnp.issubdtype(a.dtype, jnp.floating) else a,
                tree,
            )
            return tree, row_leaf

        in_specs = (bins_spec, fshard, fshard, fshard, fshard,
                    rep, rep, rep, fshard, rep, rep)
        self._fn = jax.jit(
            shard_map_compat(
                fn,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=(rep, rep),
                check_vma=False,
            )
        )

    # ------------------------------------------------------------------
    def padded_features(self, f: int) -> int:
        d = self.n_dev
        return ((f + d - 1) // d) * d

    def shard_inputs(self, dev: dict) -> dict:
        """Pad the feature axis to a mesh multiple and device_put with
        feature shardings. Padding columns are trivial 1-bin features."""
        f, n = dev["bins"].shape
        fp = self.padded_features(f)
        pad = fp - f
        out = dict(dev)
        bins = np.asarray(dev["bins"])
        if pad:
            bins = np.concatenate(
                [bins, np.zeros((pad, n), bins.dtype)], axis=0
            )
        host = {
            "bins": bins,
            "nan_bin": np.concatenate(
                [np.asarray(dev["nan_bin"]), np.full(pad, -1, np.int32)]
            ),
            "num_bins": np.concatenate(
                [np.asarray(dev["num_bins"]), np.ones(pad, np.int32)]
            ),
            "mono": np.concatenate(
                [np.asarray(dev["mono"]), np.zeros(pad, np.int32)]
            ),
            "is_cat": np.concatenate(
                [np.asarray(dev["is_cat"]), np.zeros(pad, bool)]
            ),
        }
        fs = NamedSharding(self.mesh, P(self.axis_name))
        out["bins"] = jax.device_put(
            host["bins"], NamedSharding(self.mesh, P(self.axis_name, None))
        )
        for k in ("nan_bin", "num_bins", "mono", "is_cat"):
            out[k] = jax.device_put(host[k], fs)
        rep = NamedSharding(self.mesh, P())
        out["valid"] = jax.device_put(dev["valid"], rep)
        return out

    def __call__(self, bins, nan_bin, num_bins, mono, is_cat, grad, hess,
                 mask, feat_mask, params: SplitParams, valid, bundle=None,
                 rng_key=None, group_mat=None, cegb=None, forced=None,
                 gh_scale=None) -> Tuple[TreeArrays, jax.Array]:
        del bundle, rng_key, group_mat, cegb, forced  # unsupported (warned)
        del gh_scale  # quantized rounds mode never routes here
        fp = bins.shape[0]
        pad = fp - feat_mask.shape[0]
        if pad:
            feat_mask = jnp.concatenate([feat_mask, jnp.zeros(pad, bool)])
        fs = NamedSharding(self.mesh, P(self.axis_name))
        feat_mask = jax.device_put(feat_mask, fs)
        return self._fn(
            bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
            feat_mask, params, valid,
        )
