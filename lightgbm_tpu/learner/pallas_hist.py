"""Pallas TPU histogram-construction kernel.

The histogram is the reference's single hottest loop
(src/io/dense_bin.hpp:99-174 ConstructHistogram on CPU, shared-memory
atomics in src/treelearner/cuda/cuda_histogram_constructor.cu on CUDA).
A TPU has no vector scatter, so the kernel reformulates scatter-add as
a one-hot contraction — but unlike a plain XLA einsum, the one-hot
matrix only ever exists one (B, HIST_BLK) tile at a time in VMEM,
never in HBM. Per grid step (one row block):

    bins tile (F, blk) int32, gh tile (8, blk) f32    -> VMEM
    for each feature f (static unroll):
        ohT = (bins[f:f+1, :] == iota_B^T)             (B, blk) bf16
        out[:, f*B:(f+1)*B] += gh . ohT^T              MXU NT dot_general

Inputs are feature-major (rows on the LANE axis) because TPU memory
tiles pad the minor-most dim to 128 lanes — a row-major (N, 28) matrix
would physically occupy 4.5x its size in HBM. The one-hot is built
TRANSPOSED in that same layout and contracted with an NT dot_general;
an earlier version transposed the bins tile per block, which cost
~2 ms/pass and serialized against the int8 MXU stream (1.75x on the
quantized path). The channel axis is padded 3 -> 8 (bf16x2-split
grad/hess + count, see histogram.build_gh8) to match the f32 sublane
tile; accumulation rides the grid-constant output block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram import CH, HIST_BLK, NAT_CH


def _accum_hist_nt(bins_ref, lhs, out_ref, *, F, B, blk, dt, acc_t,
                   iota_bT=None):
    """Shared accumulate loop: one NT matmul per feature, the one-hot
    built TRANSPOSED (B, blk) directly from the bins tile's native
    (F, blk) layout — the former per-block (blk, F) int32 transpose
    cost ~2 ms/pass at 1M rows and serialized against the int8 MXU
    stream. Grouping features into wider matmuls was tried and measured
    SLOWER (lane-axis concat of one-hots cost more than the larger
    matmul saved: 4.75 -> 3.71 trees/s end to end; 3D->2D reshapes onto
    the lane axis don't lower in Mosaic at all).

    `iota_bT` passes the (B, blk) row-iota from a VMEM scratch buffer
    written once at grid step 0 (see _oh_iota_init) so the constant is
    block-resident instead of re-materialized every step x feature."""
    if iota_bT is None:
        iota_bT = lax.broadcasted_iota(jnp.int32, (B, blk), 0)
    for f in range(F):
        ohT = (bins_ref[f : f + 1, :] == iota_bT).astype(dt)  # (B, blk)
        out_ref[:, f * B : (f + 1) * B] += lax.dot_general(
            lhs, ohT, (((1,), (1,)), ((), ())),
            preferred_element_type=acc_t,
        )


def _oh_iota_shape(B: int, blk: int, int8: bool,
                   int4: bool = False) -> tuple:
    """Shape of the persistent one-hot iota scratch (one VMEM buffer
    per kernel invocation, written at grid step 0 and reused by every
    later step): the compare path persists the (B, blk) row iota, the
    byte-SWAR path the packed (ceil(B/4), blk) byte iota, the
    nibble-SWAR (int4) path a (2*ceil(B/8), blk) stack of the packed
    nibble iota and the per-row hi-block index."""
    if int8 and int4:
        return (2 * (-(-B // 8)), blk)
    if int8:
        return (-(-B // 4), blk)
    return (B, blk)


def _oh_iota_init(shape: tuple, int8: bool, int4: bool = False):
    """Value for the persistent iota scratch (see _oh_iota_shape)."""
    if int8 and int4:
        half = shape[0] // 2
        bg = lax.broadcasted_iota(jnp.int32, (half, shape[1]), 0)
        iota_nib = (bg & 1) * _SWAR4_M8 + 0x76543210
        return jnp.concatenate([iota_nib, bg >> 1], axis=0)
    bg = lax.broadcasted_iota(jnp.int32, shape, 0)
    if int8:
        return bg * (4 * _SWAR_REP) + 0x03020100
    return bg


def _nat_kernel(bins_ref, gh_ref, slot_ref, out_ref, iota_ref,
                *, F: int, B: int, blk: int, S: int, nat_ch: int,
                int8: bool = False, oh_shift: int = 0,
                int4: bool = False):
    """Slot-packed natural-order histogram: rows carry a slot id; the
    weight matrix W packs (slot x channel) onto the MXU's M axis —
    W[(s, c), r] = gh[c, r] * (slot[r] == s) — so one (S*nat_ch, blk) @
    (blk, B) matmul per feature accumulates ALL slots' histograms. With
    S*nat_ch ~ 125 of the MXU's 128 M rows useful, up to 25 slots (42
    under quantized training's 3 integer channels) cost the wall time
    the single-leaf kernel spends on 8 rows.

    The output block is grid-constant (index_map (0, 0)) so it stays
    VMEM-resident across grid steps — accumulate into it directly
    instead of a scratch copy (a separate scratch doubled the scoped
    VMEM footprint and capped S at ~25 of the 16 MB budget).

    With `int8` (quantized training, levels within +/-127): W and the
    one-hot are s8, the MXU accumulates s32 — twice the bf16 rate on
    v5e and the block sums are exact integers (the TPU analog of the
    reference's int16/int32 histogram buffers, bin.h:63-81). Worst-case
    block sum 127 * blk << 2^31; cross-block accumulation rides the s32
    output block."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)
        iota_ref[...] = _oh_iota_init(iota_ref.shape, int8, int4)

    iota = iota_ref[...]  # VMEM-persistent one-hot iota (step-invariant)
    slot = slot_ref[0, :]  # (blk,) int32
    gh = gh_ref[...]  # (CH, blk) f32; rows 0..nat_ch-1 are live
    iota_s = lax.broadcasted_iota(jnp.int32, (S, blk), 0)
    if int8:
        # Mosaic has no elementwise i8 multiply (only the MXU dot is
        # int8-legal): mask the levels in i32, then narrow to s8
        sl32 = (slot[None, :] == iota_s).astype(jnp.int32)  # (S, blk)
        g32 = gh[:nat_ch, :].astype(jnp.int32)  # (nat_ch, blk)
        W = (sl32[:, None, :] * g32[None, :, :]).reshape(
            S * nat_ch, blk
        ).astype(jnp.int8)
        # SWAR one-hot (see _swar_onehot): 1.65x the compare+cast rate
        # on the VPU-bound end; sums come out scaled by the byte value
        # (nibble value on the experimental int4 variant)
        for f in range(F):
            if int4:
                oh = _swar_onehot4(bins_ref[f:f + 1, :], B, blk,
                                   iota2=iota)
            else:
                oh = _swar_onehot(bins_ref[f:f + 1, :], B, blk, oh_shift,
                                  iota_p=iota)
            out_ref[:, f * B:(f + 1) * B] += lax.dot_general(
                W, oh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
        return
    sl = (slot[None, :] == iota_s).astype(jnp.bfloat16)  # (S, blk)
    g5 = gh[:nat_ch, :].astype(jnp.bfloat16)  # (nat_ch, blk)
    W = (sl[:, None, :] * g5[None, :, :]).reshape(S * nat_ch, blk)

    _accum_hist_nt(bins_ref, W, out_ref, F=F, B=B, blk=blk,
                   dt=jnp.bfloat16, acc_t=jnp.float32, iota_bT=iota)


def _swar_divisor(oh_shift: int) -> float:
    """SWAR one-hot byte value: -128 unshifted (0x80 as s8), else
    positive 128 >> shift."""
    return -128.0 if oh_shift == 0 else float(128 >> oh_shift)


# nibble-SWAR (int4) one-hot marker: 0x8 per nibble, always positive
# after the even/odd plane split (see _swar_onehot4)
_SWAR4_DIVISOR = 8.0

# the histogram grid walks row blocks accumulating into grid-constant
# output blocks: steps are NOT parallelizable, tell Mosaic so instead
# of letting it infer (the chip-resident schedule contract, ISSUE 12)
_ARBITRARY = pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",))


@functools.partial(
    jax.jit,
    static_argnames=("num_slots", "num_bins", "blk", "interpret", "nat_ch",
                     "int8", "oh_shift", "int4"),
)
def hist_nat_tpu(
    bins_fm: jax.Array,  # (F, N) int32, natural row order
    gh8: jax.Array,  # (CH, N) f32
    slot: jax.Array,  # (N,) int32 in [0, num_slots]
    num_slots: int,
    num_bins: int,
    blk: int = HIST_BLK,
    interpret: bool = False,
    nat_ch: int = NAT_CH,
    int8: bool = False,
    oh_shift: int = 0,
    int4: bool = False,
) -> jax.Array:
    """(S*nat_ch, F*B) f32 packed per-slot channel histograms (exact
    integer sums computed in s32 when int8). `int4` (int8 path only,
    LGBM_TPU_INT4_OH=1) swaps the byte-SWAR one-hot for the nibble
    variant: 8 bins per i32 lane, marker 8 — see _swar_onehot4 for the
    evaluation verdict."""
    F, N = bins_fm.shape
    assert N % blk == 0, (N, blk)
    assert gh8.shape == (CH, N), gh8.shape
    B = num_bins
    S = num_slots
    nb = N // blk
    out = pl.pallas_call(
        functools.partial(_nat_kernel, F=F, B=B, blk=blk, S=S, nat_ch=nat_ch,
                          int8=int8, oh_shift=oh_shift, int4=int4),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((CH, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (S * nat_ch, F * B), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(
            (S * nat_ch, F * B), jnp.int32 if int8 else jnp.float32
        ),
        scratch_shapes=[
            pltpu.VMEM(_oh_iota_shape(B, blk, int8, int4), jnp.int32),
        ],
        compiler_params=_ARBITRARY,
        interpret=interpret,
    )(bins_fm, gh8, slot.reshape(1, N))
    if not int8:
        return out
    div = _SWAR4_DIVISOR if int4 else _swar_divisor(oh_shift)
    return out.astype(jnp.float32) * (1.0 / div)


_SWAR_REP = 0x01010101
_SWAR_M7 = 0x7F7F7F7F
_SWAR_M8 = -2139062144  # 0x80808080 as i32


def _swar_onehot(bins_row, B: int, blk: int, oh_shift: int, iota_p=None):
    """(1, blk) i32 bin values -> (B, blk) s8 one-hot, 4 bins per i32.

    The straight `bins == iota` compare + s8 cast costs ~4.4 ms per
    1M x 28 x 256 pass — the VPU floor of every histogram pass (i32
    vectors hold 1024 elements; s8/i16/bf16 compares don't lower in
    this Mosaic). This packs FOUR bin rows into each i32 lane (byte j
    of packed row bg is bin 4bg+j), replicates the row's bin value
    into all four bytes, and marks equal bytes with a carry-free SWAR
    zero-byte test:

        t  = (bins * 0x01010101) ^ iota_packed
        oh = ~(((t & 0x7F7F7F7F) + 0x7F7F7F7F) | t) & 0x80808080

    (the textbook `(t - REP) & ~t & M8` test is WRONG here: a hit at
    even byte j borrows into byte j+1, falsely marking bins^iota == 1,
    i.e. every even-bin hit would also count its odd neighbor). The
    i32 result bitcasts to (B, blk) s8 — pltpu.bitcast unpacks bytes
    onto sublanes exactly in bin order — with value -0x80 >> oh_shift
    at hits; callers divide the s32 sums by -(128 >> oh_shift).
    Measured 1.65x faster than compare+cast (2.45 vs 4.05 ms/pass).

    oh_shift trades VPU ops for s32 headroom: 0 keeps bytes at +/-128
    (fastest, sums scaled 128x), 4 shifts to +/-8 (two extra ops,
    16x more accumulation headroom).

    `iota_p` passes the packed byte iota from a VMEM scratch written at
    grid step 0 (_oh_iota_init) instead of re-materializing the
    constant every step x feature."""
    B4 = -(-B // 4)  # pad to a byte multiple; extra rows sliced off
    if iota_p is None:
        bg = lax.broadcasted_iota(jnp.int32, (B4, blk), 0)
        iota_p = bg * (4 * _SWAR_REP) + 0x03020100
    t = (bins_row * _SWAR_REP) ^ iota_p
    z = ~(((t & _SWAR_M7) + _SWAR_M7) | t) & _SWAR_M8
    if oh_shift:
        # arithmetic >> smears the top byte's sign bit; the mask keeps
        # only the intended per-byte marker bit
        z = (z >> oh_shift) & (_SWAR_REP * (0x80 >> oh_shift))
    oh = pltpu.bitcast(z, jnp.int8)
    return oh if 4 * B4 == B else oh[:B, :]


_SWAR4_REP = 0x11111111
_SWAR4_M7 = 0x77777777
_SWAR4_M8 = -2004318072  # 0x88888888 as i32


def _swar_onehot4(bins_row, B: int, blk: int, iota2=None):
    """(1, blk) i32 bin values -> (B, blk) s8 one-hot via NIBBLE (int4)
    SWAR packing: EIGHT bins per i32 lane (ISSUE 12 evaluation).

    Packed row j covers bins 8j..8j+7, which always share one 16-bin
    block (hi nibble j >> 1), so equality splits into a nibble zero
    test on the LOW nibble against the packed nibble iota (row j even:
    0x76543210, odd: 0xFEDCBA98) AND a whole-lane hi-block match:

        t = ((bins & 15) * 0x11111111) ^ iota_nib
        z = ~(((t & 0x77777777) + 0x77777777) | t) & 0x88888888
        z = where(bins >> 4 == j >> 1, z, 0)

    (the same carry-free masked test as the byte variant — (t & 7) + 7
    cannot carry across nibbles). Marker 0x8 per matching nibble.

    EVALUATION VERDICT (kept opt-in, LGBM_TPU_INT4_OH=1): this
    toolchain's pltpu.bitcast cannot widen i32 -> 8 x i4 (it rejects
    the 4-bit element reinterpret), so the unpack degrades to an
    even/odd nibble-plane split — two masked shifts, two i32 -> s8
    byte bitcasts and a sublane interleave. The halved one-hot VMEM
    footprint survives only up to that unpack; the extra VPU work eats
    most of the packing win, and the MXU dot still runs s8. The
    nibble TEST itself (3 ops for 8 bins vs 3 ops for 4) is the part
    worth keeping if a true i4 reinterpret lands.

    `iota2` passes the (2*ceil(B/8), blk) VMEM scratch stack
    [iota_nib; row_hi] (_oh_iota_init). Marker is always 8 (the s32
    headroom of the byte path's oh_shift=4), divisor _SWAR4_DIVISOR."""
    B8 = -(-B // 8)
    if iota2 is None:
        bg = lax.broadcasted_iota(jnp.int32, (B8, blk), 0)
        iota_nib = (bg & 1) * _SWAR4_M8 + 0x76543210
        row_hi = bg >> 1
    else:
        iota_nib = iota2[:B8, :]
        row_hi = iota2[B8:, :]
    lo = (bins_row & 15) * _SWAR4_REP
    t = lo ^ iota_nib
    z = ~(((t & _SWAR4_M7) + _SWAR4_M7) | t) & _SWAR4_M8
    z = jnp.where((bins_row >> 4) == row_hi, z, 0)
    # nibble-plane split: even bins live in low nibbles, odd in high;
    # each plane is a byte-plane the toolchain CAN bitcast to s8
    ze = pltpu.bitcast(z & 0x0F0F0F0F, jnp.int8)  # (4*B8, blk) bins 2r
    zo = pltpu.bitcast((z >> 4) & 0x0F0F0F0F, jnp.int8)  # bins 2r+1
    oh = jnp.stack([ze, zo], axis=1).reshape(8 * B8, blk)
    return oh if 8 * B8 == B else oh[:B, :]


def _round_kernel(
    params_ref, coh_ref, cat_ref, bins_ref, gh_ref, pleaf_ref,  # inputs
    out_ref, pl_out_ref,  # outputs
    *scratch,  # persistent one-hot iota buffers (mode-dependent)
    F: int, B: int, blk: int, S: int, nat_ch: int, int8: bool,
    oh_shift: int, efb: bool, has_cat: bool,
):
    """Fused round step: partition decision + slot-packed histograms
    in ONE data pass (VERDICT r4 item 2).

    Compile-time contracts (no host callbacks, no f64, jaxpr size
    budget) are enforced by the `hist_round_fused` entry of
    analysis/jaxpr_audit.py — the trace is audited abstractly on CPU,
    so kernel drift fails tier-1 before it ever reaches hardware.

    The rounds grower's per-round extras — the (G, N) split-column
    select (2.2 ms), the (N, S) membership matmul, the row->leaf
    update and the histogram-slot assignment — all touch the same
    bins/pleaf data this kernel already streams. Fusing them in makes
    them free:

    - `fb[s, r]` (each row's split-column bin) is a tiny in-kernel
      (S, F) @ (F, blk) f32 MXU contraction against the per-slot
      column one-hot — no dynamic sublane loads, exact to 2^24;
    - membership/threshold/default-direction/EFB-decode are (S, blk)
      vector ops against per-slot scalar columns of `params_ref`;
    - the new row->leaf vector is written as a second blocked output;
    - the smaller-child side picks each row's histogram slot, and the
      slot-packed W build + one-hot contraction proceed as in
      _nat_kernel (SWAR one-hot on the int8 path).

    params columns (S, 16) i32: 0 sel_leaf, 1 device column, 2
    threshold bin, 3 default_left, 4 NaN bin (-1 none), 5 left-smaller,
    6 new leaf id, 7 efb off_lo, 8 efb mfb (-1 direct), 9 efb width.
    Pad slots carry sel_leaf = L (matched only by invalid rows, whose
    gh channels are zero and whose new id is L: harmless by
    construction, same argument as the XLA path in rounds.py)."""
    i = pl.program_id(0)
    # scratch layout (_round_scratch_shapes): int8 -> one byte-SWAR
    # iota (shared by the bins one-hots and the cat one-hot); bf16
    # with cat -> compare iota + byte-SWAR iota; bf16 without -> just
    # the compare iota. All written once at step 0, VMEM-resident after.
    if int8:
        iota_swar_ref, = scratch
        iota_cmp_ref = None
    elif has_cat:
        iota_cmp_ref, iota_swar_ref = scratch
    else:
        iota_cmp_ref, = scratch
        iota_swar_ref = None

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)
        if iota_cmp_ref is not None:
            iota_cmp_ref[...] = _oh_iota_init(iota_cmp_ref.shape, False)
        if iota_swar_ref is not None:
            iota_swar_ref[...] = _oh_iota_init(iota_swar_ref.shape, True)

    iota_swar = None if iota_swar_ref is None else iota_swar_ref[...]
    pleaf = pleaf_ref[...]  # (1, blk) i32
    gh = gh_ref[...]  # (CH, blk) f32
    sel = params_ref[:, 0:1]  # (S, 1) i32
    thr = params_ref[:, 2:3].astype(jnp.float32)
    dl = params_ref[:, 3:4] != 0
    nanb = params_ref[:, 4:5].astype(jnp.float32)
    small = params_ref[:, 5:6] != 0
    new_id = params_ref[:, 6:7]

    memb = pleaf == sel  # (S, blk)
    fb = lax.dot_general(
        coh_ref[...], bins_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )  # (S, blk) — slot s's split-column bin per row
    if efb:
        lo = params_ref[:, 7:8].astype(jnp.float32)
        mfb = params_ref[:, 8:9].astype(jnp.float32)
        wid = params_ref[:, 9:10].astype(jnp.float32)
        t = fb - lo
        in_r = (t >= 0.0) & (t < wid)
        dec = jnp.where(in_r, t + (t >= mfb).astype(jnp.float32), mfb)
        fb = jnp.where(mfb >= 0.0, dec, fb)
    gl = (fb <= thr) | (dl & (fb == nanb))  # (S, blk)
    if has_cat:
        # categorical slots: go left iff the row's bin is in the
        # slot's category set. The row's OWN split-column bin (merge
        # over disjoint memberships) gets a single-feature one-hot and
        # one (S, B) @ (B, blk) contraction against the per-slot masks
        # — the (L*B,) flat gather this replaces costs ~10 ms at 1M
        # rows (tools/tpu_gather_probe.py).
        is_cat_s = params_ref[:, 10:11] != 0  # (S, 1)
        fb_own = jnp.sum(jnp.where(memb, fb, 0.0), axis=0,
                         keepdims=True)  # (1, blk) f32 integer-valued
        ohfb = _swar_onehot(fb_own.astype(jnp.int32), B, blk, 7,
                            iota_p=iota_swar)  # 0/1 s8
        hits = lax.dot_general(
            cat_ref[...], ohfb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (S, blk): mask[s, fb_own[r]]
        gl = jnp.where(is_cat_s, hits > 0, gl)

    # new per-row leaf ids: memberships are disjoint, so summing the
    # masked deltas over the slot axis applies at most one update
    delta = jnp.where(memb & ~gl, new_id - pleaf, 0)
    pl_out_ref[...] = pleaf + jnp.sum(delta, axis=0, keepdims=True)

    side = memb & (gl == small)  # rows feeding slot s's histogram
    if int8:
        side_i = side.astype(jnp.int32)
        g32 = gh[:nat_ch, :].astype(jnp.int32)
        W = (side_i[:, None, :] * g32[None, :, :]).reshape(
            S * nat_ch, blk).astype(jnp.int8)
        for f in range(F):
            oh = _swar_onehot(bins_ref[f:f + 1, :], B, blk, oh_shift,
                              iota_p=iota_swar)
            out_ref[:, f * B:(f + 1) * B] += lax.dot_general(
                W, oh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
    else:
        sideb = side.astype(jnp.bfloat16)
        gb = gh[:nat_ch, :].astype(jnp.bfloat16)
        W = (sideb[:, None, :] * gb[None, :, :]).reshape(S * nat_ch, blk)
        _accum_hist_nt(bins_ref, W, out_ref, F=F, B=B, blk=blk,
                       dt=jnp.bfloat16, acc_t=jnp.float32,
                       iota_bT=iota_cmp_ref[...])


@functools.partial(
    jax.jit,
    static_argnames=("num_slots", "num_bins", "nat_ch", "int8", "oh_shift",
                     "efb", "blk", "interpret"),
)
def hist_round_tpu(
    bins_fm: jax.Array,  # (F, N) int32, natural row order
    gh8: jax.Array,  # (CH, N) f32
    pleaf: jax.Array,  # (N,) int32 row -> leaf
    params: jax.Array,  # (S, 16) int32 per-slot split params
    col_onehot: jax.Array,  # (S, F) f32 one-hot of the split column
    num_slots: int,
    num_bins: int,
    nat_ch: int,
    int8: bool = False,
    oh_shift: int = 0,
    efb: bool = False,
    cat_mask=None,  # (S, B) s8 per-slot category sets, or None
    blk: int = HIST_BLK,
    interpret: bool = False,
):
    """One fused pass -> ((S*nat_ch, F*B) histograms, (N,) new row->leaf).

    int8 histogram sums come back scaled by -(128 >> oh_shift) (SWAR
    one-hot bytes); callers divide once on the (S*ch, F*B) output."""
    F, N = bins_fm.shape
    assert N % blk == 0, (N, blk)
    S = num_slots
    nb = N // blk
    has_cat = cat_mask is not None
    if cat_mask is None:
        cat_mask = jnp.zeros((S, num_bins), jnp.int8)
    # persistent one-hot iota scratch (see _round_kernel): part of the
    # kernel's explicit VMEM block schedule, accounted against the
    # scoped budget by histogram._round_caps callers
    if int8:
        scratch = [pltpu.VMEM(_oh_iota_shape(num_bins, blk, True),
                              jnp.int32)]
    else:
        scratch = [pltpu.VMEM(_oh_iota_shape(num_bins, blk, False),
                              jnp.int32)]
        if has_cat:
            scratch.append(pltpu.VMEM(_oh_iota_shape(num_bins, blk, True),
                                      jnp.int32))
    out, pl_new = pl.pallas_call(
        functools.partial(
            _round_kernel, F=F, B=num_bins, blk=blk, S=S, nat_ch=nat_ch,
            int8=int8, oh_shift=oh_shift, efb=efb, has_cat=has_cat,
        ),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((S, 16), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((S, F), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((S, num_bins), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((F, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((CH, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((S * nat_ch, F * num_bins), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((S * nat_ch, F * num_bins),
                                 jnp.int32 if int8 else jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.int32),
        ],
        scratch_shapes=scratch,
        compiler_params=_ARBITRARY,
        interpret=interpret,
    )(params, col_onehot, cat_mask, bins_fm, gh8, pleaf.reshape(1, N))
    return out, pl_new.reshape(N)


def _take_kernel(idx_ref, tab_ref, out_ref, *, L: int, k: int, blk: int):
    """out[:, r] = tab[:, idx[r]] as a one-hot MXU contraction.

    A (N,) vector gather from a small table costs ~1 ms per 1M rows on
    TPU (no vector-gather hardware); this does the same lookup as
    (k, L) @ (L, blk) one-hot matmuls per tile, ~0.1 ms for the whole
    array (tools/tpu_gather_probe.py). HIGHEST precision: table VALUES
    are arbitrary f32 (leaf outputs) and the default TPU matmul would
    round them to bf16; with a 0/1 one-hot operand the HIGHEST-precision
    product is exact."""
    idx = idx_ref[0, :]  # (blk,) int32
    iota_l = lax.broadcasted_iota(jnp.int32, (L, blk), 0)
    onehot = (idx[None, :] == iota_l).astype(jnp.float32)  # (L, blk)
    out_ref[...] = lax.dot_general(
        tab_ref[...], onehot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )


@functools.partial(jax.jit, static_argnames=("blk", "interpret"))
def take_small_tpu(
    tab: jax.Array,  # (k, L) f32 — k table columns, L entries each
    idx: jax.Array,  # (N,) int32; out-of-range rows produce 0
    blk: int = HIST_BLK,
    interpret: bool = False,
) -> jax.Array:
    """(k, N) f32: tab[:, idx] via per-tile one-hot contraction."""
    k, L = tab.shape
    N = idx.shape[0]
    assert N % blk == 0, (N, blk)
    nb = N // blk
    return pl.pallas_call(
        functools.partial(_take_kernel, L=L, k=k, blk=blk),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, L), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, blk), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, N), jnp.float32),
        interpret=interpret,
    )(idx.reshape(1, N), tab)


def _segsum_kernel(idx_ref, val_ref, out_ref, *, L: int, k: int, blk: int):
    """out[:, l] += sum over rows r with idx[r] == l of val[:, r] —
    per-leaf reductions (RenewTreeOutput sums) as a one-hot MXU
    contraction instead of an XLA scatter-add (which serializes on TPU).
    Out-of-range idx (invalid rows, idx == L or -1) match nothing.
    HIGHEST precision: values are arbitrary f32 gradients."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    idx = idx_ref[0, :]  # (blk,) int32
    iota_l = lax.broadcasted_iota(jnp.int32, (blk, L), 1)
    onehot = (idx[:, None] == iota_l).astype(jnp.float32)  # (blk, L)
    out_ref[...] += lax.dot_general(
        val_ref[...], onehot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )


@functools.partial(jax.jit, static_argnames=("num_out", "blk", "interpret"))
def seg_sum_tpu(
    vals: jax.Array,  # (k, N) f32
    idx: jax.Array,  # (N,) int32; out-of-range rows contribute nothing
    num_out: int,
    blk: int = HIST_BLK,
    interpret: bool = False,
) -> jax.Array:
    """(k, num_out) f32 per-index sums of vals columns."""
    k, N = vals.shape
    assert N % blk == 0, (N, blk)
    nb = N // blk
    return pl.pallas_call(
        functools.partial(_segsum_kernel, L=num_out, k=k, blk=blk),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, num_out), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, num_out), jnp.float32),
        interpret=interpret,
    )(idx.reshape(1, N), vals)


def _hist_kernel(bins_ref, gh_ref, out_ref, *, F: int, B: int, blk: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    g = gh_ref[...].astype(jnp.bfloat16)  # (CH, blk)
    _accum_hist_nt(bins_ref, g, out_ref, F=F, B=B, blk=blk,
                   dt=jnp.bfloat16, acc_t=jnp.float32)


def _hist_slots_kernel(
    vblock_ref, vslot_ref, vlo_ref, vhi_ref,  # scalar prefetch
    bins_ref, gh_ref, out_ref, acc_ref, *, F: int, B: int, blk: int
):
    """One visit = (row block, slot, in-block row range). Visits arrive
    sorted by slot; acc accumulates a slot's histogram across its visits
    and flushes to the slot's output block on the slot's last visit."""
    v = pl.program_id(0)
    slot = vslot_ref[v]
    prev_slot = vslot_ref[jnp.maximum(v - 1, 0)]

    @pl.when((v == 0) | (slot != prev_slot))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    lo = vlo_ref[v]
    hi = vhi_ref[v]
    iota_r = lax.broadcasted_iota(jnp.int32, (CH, blk), 1)
    g = jnp.where((iota_r >= lo) & (iota_r < hi), gh_ref[...], 0.0).astype(
        jnp.bfloat16
    )
    _accum_hist_nt(bins_ref, g, acc_ref, F=F, B=B, blk=blk,
                   dt=jnp.bfloat16, acc_t=jnp.float32)

    # vslot has a trailing sentinel, so v+1 is always readable
    @pl.when(vslot_ref[v + 1] != slot)
    def _flush():
        out_ref[...] = acc_ref[...][None]


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "num_slots", "blk", "dense_visits",
                     "interpret"),
)
def hist_slots_tpu(
    bins_fm: jax.Array,  # (F, N) int32, rows POSITION-grouped by slot
    gh8: jax.Array,  # (CH, N) f32
    begins: jax.Array,  # (num_slots,) int32 — slot segment starts
    counts: jax.Array,  # (num_slots,) int32 — slot segment lengths
    num_bins: int,
    num_slots: int,
    blk: int = HIST_BLK,
    dense_visits: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Per-slot histograms in ONE data pass: (num_slots+1, CH, F*B).

    Each slot is a contiguous row segment [begin, begin+count); segments
    must be disjoint but need not cover all rows (total visited blocks
    is bounded by nb//2 + 2*num_slots — callers use this for the
    smaller-children of one round, whose total is <= N/2). The +1 slot
    is a trash row absorbing padding visits; slot s of the output is
    garbage when counts[s] == 0 AND no visit wrote it — callers must
    mask by counts > 0.
    """
    F, N = bins_fm.shape
    assert N % blk == 0, (N, blk)
    B = num_bins
    nb = N // blk
    S = num_slots
    # visit budget: sum(counts) <= N/2 (smaller children) + 2 boundary
    # blocks per slot; sharded runs can exceed N/2 locally -> dense
    V = (nb if dense_visits else nb // 2) + 2 * S + 2

    cnt1 = jnp.maximum(counts, 1)  # empty slots still get one zero visit
    blk0 = begins // blk
    blk1 = (begins + cnt1 - 1) // blk
    nblk = jnp.clip(blk1 - blk0 + 1, 1, nb)
    offs = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(nblk)])
    iota_v = jnp.arange(V, dtype=jnp.int32)
    s_of_v = (
        jnp.searchsorted(offs, iota_v, side="right").astype(jnp.int32) - 1
    )
    pad = s_of_v >= S
    s_clip = jnp.clip(s_of_v, 0, S - 1)
    vblock = jnp.clip(
        blk0[s_clip] + iota_v - offs[s_clip], 0, nb - 1
    ).astype(jnp.int32)
    bstart = vblock * blk
    vlo = jnp.clip(begins[s_clip] - bstart, 0, blk)
    vhi = jnp.clip(begins[s_clip] + counts[s_clip] - bstart, 0, blk)
    vslot = jnp.where(pad, S, s_of_v).astype(jnp.int32)
    vlo = jnp.where(pad, 0, vlo).astype(jnp.int32)
    vhi = jnp.where(pad, 0, vhi).astype(jnp.int32)
    vslot_s = jnp.concatenate([vslot, jnp.full(1, S + 1, jnp.int32)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(V,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda v, vb, vs, lo, hi: (0, vb[v])),
            pl.BlockSpec((CH, blk), lambda v, vb, vs, lo, hi: (0, vb[v])),
        ],
        out_specs=pl.BlockSpec(
            (1, CH, F * B), lambda v, vb, vs, lo, hi: (vs[v], 0, 0)
        ),
        scratch_shapes=[pltpu.VMEM((CH, F * B), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_hist_slots_kernel, F=F, B=B, blk=blk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S + 1, CH, F * B), jnp.float32),
        interpret=interpret,
    )(vblock, vslot_s, vlo, vhi, bins_fm, gh8)
    return out


@functools.partial(jax.jit, static_argnames=("num_bins", "blk", "interpret"))
def hist_tpu(
    bins_fm: jax.Array, gh8: jax.Array, num_bins: int, blk: int = HIST_BLK,
    interpret: bool = False,
) -> jax.Array:
    """(F, N) int32 bins + (CH, N) f32 channels -> (CH, F, B) f32.

    N must be a multiple of blk; callers pad rows with gh == 0.
    """
    F, N = bins_fm.shape
    assert N % blk == 0, (N, blk)
    assert gh8.shape == (CH, N), gh8.shape
    B = num_bins
    nb = N // blk

    out = pl.pallas_call(
        functools.partial(_hist_kernel, F=F, B=B, blk=blk),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((F, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((CH, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((CH, F * B), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((CH, F * B), jnp.float32),
        interpret=interpret,
    )(bins_fm, gh8)
    return out.reshape(CH, F, B)
