"""Evaluation metrics (reference src/metric/*.hpp + factory metric.cpp:21).

Metrics are host-side numpy over (label, converted score) — eval is not in
the training hot path and runs on unpadded arrays. Each metric reports
(name, value, higher_better) matching the reference names so callback and
early-stopping code behaves identically. In distributed mode each rank
evaluates its local shard, as in the reference (SURVEY §2.7).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import log
from .config import Config


class Metric:
    name = ""
    higher_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, label: np.ndarray, weight: Optional[np.ndarray], group: Optional[np.ndarray]) -> None:
        self.label = label
        self.weight = weight
        self.group = group

    def eval(self, score: np.ndarray) -> List[Tuple[str, float, bool]]:
        """score is the RAW margin (num_class, N) or (N,); metric applies
        its own transform as the reference metrics do."""
        raise NotImplementedError

    def _avg(self, values: np.ndarray) -> float:
        if self.weight is None:
            return float(np.mean(values))
        return float(np.sum(values * self.weight) / np.sum(self.weight))


def _sigmoid(x: np.ndarray, s: float = 1.0) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-s * x))


class _PointwiseMetric(Metric):
    def point(self, label: np.ndarray, score: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, score: np.ndarray) -> np.ndarray:
        return score

    def eval(self, score):
        return [(self.name, self._avg(self.point(self.label, self.transform(score))), self.higher_better)]


class L2Metric(_PointwiseMetric):
    name = "l2"

    def point(self, y, s):
        return (y - s) ** 2


class RMSEMetric(_PointwiseMetric):
    name = "rmse"

    def eval(self, score):
        mse = self._avg((self.label - score) ** 2)
        return [(self.name, float(np.sqrt(mse)), False)]


class R2Metric(Metric):
    """Coefficient of determination (the one member of the reference
    metric.cpp:21 regression family previously missing here):
    R^2 = 1 - sum(w * (y - s)^2) / sum(w * (y - ybar_w)^2) with the
    weighted label mean ybar_w; constant labels yield 0 like the
    degenerate-denominator convention in sklearn."""

    name = "r2"
    higher_better = True

    def eval(self, score):
        y = self.label.astype(np.float64)
        w = (
            self.weight.astype(np.float64)
            if self.weight is not None
            else np.ones_like(y)
        )
        ybar = np.sum(w * y) / np.sum(w)
        ss_res = np.sum(w * (y - score) ** 2)
        ss_tot = np.sum(w * (y - ybar) ** 2)
        val = 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0
        return [(self.name, float(val), True)]


class L1Metric(_PointwiseMetric):
    name = "l1"

    def point(self, y, s):
        return np.abs(y - s)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def point(self, y, s):
        a = self.config.alpha
        d = y - s
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def point(self, y, s):
        a = self.config.alpha
        d = np.abs(s - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def point(self, y, s):
        c = self.config.fair_c
        x = np.abs(s - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def transform(self, score):
        return np.exp(score)

    def point(self, y, s):
        eps = 1e-10
        return s - y * np.log(np.maximum(s, eps))


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    def point(self, y, s):
        return np.abs((y - s) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseMetric):
    name = "gamma"

    def transform(self, score):
        return np.exp(score)

    def point(self, y, s):
        psi = y / s - np.log(np.maximum(y / np.maximum(s, 1e-10), 1e-10)) - 1.0
        return psi


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def transform(self, score):
        return np.exp(score)

    def point(self, y, s):
        eps = 1e-10
        return 2.0 * (np.log(np.maximum(s, eps) / np.maximum(y, eps)) + y / np.maximum(s, eps) - 1.0)


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def transform(self, score):
        return np.exp(score)

    def point(self, y, s):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(s, eps)
        return -y * np.power(s, 1.0 - rho) / (1.0 - rho) + np.power(s, 2.0 - rho) / (2.0 - rho)


class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def transform(self, score):
        return _sigmoid(score, self.config.sigmoid)

    def point(self, y, p):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def transform(self, score):
        return _sigmoid(score, self.config.sigmoid)

    def point(self, y, p):
        return ((p > 0.5) != (y > 0.5)).astype(np.float64)


class AUCMetric(Metric):
    name = "auc"
    higher_better = True

    def eval(self, score):
        y = self.label
        w = self.weight if self.weight is not None else np.ones_like(y)
        order = np.argsort(score, kind="mergesort")
        ys, ws, ss = y[order], w[order], score[order]
        # sum of positive-weight ranks with tie handling
        pos_w = np.sum(ws * (ys > 0))
        neg_w = np.sum(ws * (ys <= 0))
        if pos_w <= 0 or neg_w <= 0:
            return [(self.name, 1.0, True)]
        # accumulate over tie groups
        boundaries = np.nonzero(np.diff(ss))[0] + 1
        groups = np.split(np.arange(len(ss)), boundaries)
        auc_sum = 0.0
        cum_neg = 0.0
        for gidx in groups:
            gp = np.sum(ws[gidx] * (ys[gidx] > 0))
            gn = np.sum(ws[gidx] * (ys[gidx] <= 0))
            auc_sum += gp * (cum_neg + gn * 0.5)
            cum_neg += gn
        return [(self.name, float(auc_sum / (pos_w * neg_w)), True)]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    higher_better = True

    def eval(self, score):
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones_like(y)
        order = np.argsort(-score, kind="mergesort")
        ys, ws = y[order], w[order]
        tp = np.cumsum(ys * ws)
        total = np.cumsum(ws)
        prec = tp / total
        pos = np.sum(ys * ws)
        if pos <= 0:
            return [(self.name, 1.0, True)]
        ap = float(np.sum(prec * ys * ws) / pos)
        return [(self.name, ap, True)]


class AucMuMetric(Metric):
    """Multi-class AUC-mu (src/metric/multiclass_metric.hpp:183,
    Kleiman & Page 2019): for each class pair (i, j) rank the pair's
    rows by the separating direction v = w_i - w_j projected onto the
    prediction vectors, compute the pairwise AUC with the reference's
    kEpsilon tie handling, and average over pairs."""

    name = "auc_mu"
    higher_better = True

    def eval(self, score):
        K = self.config.num_class
        y = self.label.astype(np.int64)
        N = len(y)
        w = self.weight
        # weights matrix (config.cpp:225 GetAucMuWeights)
        amw = list(self.config.auc_mu_weights)
        if amw:
            W = np.asarray(amw, np.float64).reshape(K, K)
            np.fill_diagonal(W, 0.0)
        else:
            W = np.ones((K, K)) - np.eye(K)
        S = np.asarray(score, np.float64).reshape(K, N)
        eps = 1e-15  # reference kEpsilon
        total = 0.0
        for i in range(K):
            for j in range(i + 1, K):
                sel = (y == i) | (y == j)
                if not np.any(y[sel] == i) or not np.any(y[sel] == j):
                    continue
                v = W[i] - W[j]
                t1 = v[i] - v[j]
                d = t1 * (v @ S[:, sel])
                lab = y[sel]
                ws = w[sel] if w is not None else np.ones(sel.sum())
                # ascending distance; exact ties put class j first
                order = np.lexsort((-lab, d))
                d, lab, ws = d[order], lab[order], ws[order]
                s_ij = num_j = num_cur_j = 0.0
                last_j = 0.0
                for k in range(len(d)):
                    tie = abs(d[k] - last_j) < eps
                    if lab[k] == i:
                        s_ij += ws[k] * (
                            num_j - 0.5 * num_cur_j if tie else num_j
                        )
                    else:
                        num_j += ws[k]
                        if tie:
                            num_cur_j += ws[k]
                        else:
                            last_j = d[k]
                            num_cur_j = ws[k]
                wi = np.sum(ws[lab == i])
                wj = np.sum(ws[lab == j])
                total += (s_ij / wi) / wj
        val = 2.0 * total / K / (K - 1)
        return [(self.name, float(val), True)]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score):
        # score (K, N) raw -> softmax
        e = np.exp(score - np.max(score, axis=0, keepdims=True))
        p = e / np.sum(e, axis=0, keepdims=True)
        idx = self.label.astype(int)
        eps = 1e-15
        ll = -np.log(np.clip(p[idx, np.arange(p.shape[1])], eps, 1.0))
        return [(self.name, self._avg(ll), False)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score):
        k = self.config.multi_error_top_k
        idx = self.label.astype(int)
        true_score = score[idx, np.arange(score.shape[1])]
        rank = np.sum(score > true_score[None, :], axis=0)
        err = (rank >= k).astype(np.float64)
        return [(self.name + (f"@{k}" if k > 1 else ""), self._avg(err), False)]


class CrossEntropyMetric(_PointwiseMetric):
    name = "cross_entropy"

    def transform(self, score):
        return _sigmoid(score)

    def point(self, y, p):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class CrossEntropyLambdaMetric(Metric):
    """reference xentropy_metric.hpp:165 CrossEntropyLambdaMetric
    (alias xentlambda): weights enter the loss itself (intensity
    weighting via hhat), and the average is over num_data, NOT the
    weight sum."""

    name = "cross_entropy_lambda"

    def eval(self, score):
        eps = 1e-12
        hhat = np.log1p(np.exp(score))  # xentlambda ConvertOutput
        w = self.weight if self.weight is not None else 1.0
        p = np.clip(1.0 - np.exp(-w * hhat), eps, 1.0 - eps)
        y = self.label
        loss = -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        return [(self.name, float(np.mean(loss)), False)]


class KullbackLeiblerMetric(_PointwiseMetric):
    """reference xentropy_metric.hpp:249 KullbackLeiblerDivergence:
    cross-entropy plus the (weight-averaged, score-independent) label
    entropy offset — KL(y || p) = CE(y, p) - H(y)."""

    name = "kullback_leibler"

    def transform(self, score):
        return _sigmoid(score)

    def point(self, y, p):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))

    def eval(self, score):
        y = self.label.astype(np.float64)
        yent = np.zeros_like(y)
        m = y > 0
        yent[m] += y[m] * np.log(y[m])
        q = 1.0 - y
        mq = q > 0
        yent[mq] += q[mq] * np.log(q[mq])
        offset = self._avg(yent)
        ce = self._avg(self.point(y, self.transform(score)))
        return [(self.name, float(offset + ce), False)]


class NDCGMetric(Metric):
    name = "ndcg"
    higher_better = True

    def eval(self, score):
        if self.group is None:
            log.fatal("ndcg metric requires query information")
        qb = np.concatenate([[0], np.cumsum(self.group)]).astype(int)
        ks = list(self.config.eval_at) or [1, 2, 3, 4, 5]
        gains_cfg = list(self.config.label_gain)
        max_label = int(self.label.max())
        if not gains_cfg:
            gains_cfg = [(1 << i) - 1 for i in range(max_label + 1)]
        lg = np.asarray(gains_cfg, dtype=np.float64)
        results = {k: [] for k in ks}
        for q in range(len(qb) - 1):
            lab = self.label[qb[q]: qb[q + 1]].astype(int)
            sc = score[qb[q]: qb[q + 1]]
            order = np.argsort(-sc, kind="stable")
            ideal = np.sort(lab)[::-1]
            for k in ks:
                kk = min(k, len(lab))
                disc = 1.0 / np.log2(np.arange(kk) + 2.0)
                dcg = np.sum(lg[lab[order[:kk]]] * disc)
                idcg = np.sum(lg[ideal[:kk]] * disc)
                results[k].append(dcg / idcg if idcg > 0 else 1.0)
        return [(f"ndcg@{k}", float(np.mean(results[k])), True) for k in ks]


class MapMetric(Metric):
    name = "map"
    higher_better = True

    def eval(self, score):
        if self.group is None:
            log.fatal("map metric requires query information")
        qb = np.concatenate([[0], np.cumsum(self.group)]).astype(int)
        ks = list(self.config.eval_at) or [1, 2, 3, 4, 5]
        results = {k: [] for k in ks}
        for q in range(len(qb) - 1):
            # reference map_metric.hpp CalMapAtK: relevance is
            # label > 0.5, the normalizer is min(TOTAL positives in the
            # query, k) — not positives within the top k — and queries
            # with no positives count as 1.0
            lab = (self.label[qb[q]: qb[q + 1]] > 0.5).astype(np.float64)
            sc = score[qb[q]: qb[q + 1]]
            order = np.argsort(-sc, kind="stable")
            rel = lab[order]
            npos = float(np.sum(rel))
            for k in ks:
                kk = min(k, len(rel))
                hits = np.cumsum(rel[:kk])
                if npos > 0:
                    ap = (np.sum(hits / np.arange(1, kk + 1) * rel[:kk])
                          / min(npos, kk))
                else:
                    ap = 1.0
                results[k].append(ap)
        return [(f"map@{k}", float(np.mean(results[k])), True) for k in ks]


_METRICS: Dict[str, type] = {
    "l2": L2Metric, "mean_squared_error": L2Metric, "mse": L2Metric,
    "regression": L2Metric, "regression_l2": L2Metric,
    "rmse": RMSEMetric, "root_mean_squared_error": RMSEMetric, "l2_root": RMSEMetric,
    "r2": R2Metric, "r_squared": R2Metric,
    "l1": L1Metric, "mean_absolute_error": L1Metric, "mae": L1Metric,
    "regression_l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "cross_entropy": CrossEntropyMetric, "xentropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "xentlambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KullbackLeiblerMetric,
    "kldiv": KullbackLeiblerMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric, "rank_xendcg": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
}

# metric implied by each objective when metric param is empty (metric.cpp)
_DEFAULT_METRIC = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape",
    "gamma": "gamma", "tweedie": "tweedie", "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg",
    "rank_xendcg": "ndcg",
}


def create_metrics(config: Config) -> List[Metric]:
    names = [m for m in config.metric if m not in ("", "none", "null", "na", "custom")]
    if not names:
        default = _DEFAULT_METRIC.get(config.objective)
        names = [default] if default else []
    out = []
    for n in names:
        key = n.strip().lower()
        if key in ("none", "null", "na", "custom", ""):
            continue
        if key not in _METRICS:
            log.warning(f"Unknown metric {n}, ignored")
            continue
        out.append(_METRICS[key](config))
    return out
