"""Device-side EFB bundle support for the growers.

The bin matrix on device holds one column per BUNDLE (bundling.py);
split finding and partitioning still speak per-feature. Two traced
helpers bridge the gap:

- `expand_hist`: bundle histogram (3, G, Bc) -> per-feature histogram
  (3, F, Bf) by gather, recovering each merged feature's most-frequent
  bin from the leaf totals (the reference FixHistogram,
  include/LightGBM/dataset.h:768 — same trick, same reason: the
  most-frequent bin is not stored).
- `decode_feature_bins`: bundle column values -> original bins of one
  feature (used by the partition step in place of a direct column read).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class BundleInfo(NamedTuple):
    """Traced bundle arrays (built host-side in dataset.py)."""

    bundle_of: jax.Array  # (F,) int32 — device column per feature
    off_lo: jax.Array  # (F,) int32 — merged-range start (0 for direct)
    mfb: jax.Array  # (F,) int32 — excluded most-freq bin; -1 = direct
    expand_idx: jax.Array  # (F, Bf) int32 — flat (G*Bc) index or -1
    width: jax.Array  # (F,) int32 — merged-range length (num_bin - 1)


def expand_hist(hist_g: jax.Array, g: jax.Array, h: jax.Array, c: jax.Array,
                binfo: BundleInfo) -> jax.Array:
    """(3, G, Bc) bundle histogram -> (3, F, Bf) per-feature histogram.

    g/h/c are the leaf totals used to recover the most-frequent slot:
    hist[f, mfb] = total - sum(stored bins of f).
    """
    F, Bf = binfo.expand_idx.shape
    flat = hist_g.reshape(3, -1)
    safe = jnp.clip(binfo.expand_idx, 0, flat.shape[1] - 1)
    out = jnp.take(flat, safe.reshape(-1), axis=1).reshape(3, F, Bf)
    out = jnp.where(binfo.expand_idx[None] >= 0, out, 0.0)
    has_mfb = binfo.mfb >= 0
    totals = jnp.stack([g, h, c]).astype(jnp.float32)  # (3,)
    missing = totals[:, None] - jnp.sum(out, axis=2)  # (3, F)
    onehot = (
        (jnp.arange(Bf, dtype=jnp.int32)[None, :] == binfo.mfb[:, None])
        & has_mfb[:, None]
    )  # (F, Bf)
    return out + onehot[None].astype(jnp.float32) * missing[:, :, None]


def decode_feature_bins(bcol: jax.Array, f: jax.Array,
                        binfo: BundleInfo) -> jax.Array:
    """Bundle-column values -> feature f's original bins.

    Direct columns (mfb == -1) pass through unchanged; merged features
    map their range [off_lo, off_lo + width) back (re-inserting the
    skipped most-frequent slot) and everything else to mfb. `f` may be
    a scalar or a per-row vector matching bcol (all ops elementwise) —
    the single home of this decode; keep traversal/partition callers on
    it."""
    m = binfo.mfb[f]
    lo = binfo.off_lo[f]
    t = bcol - lo
    in_range = (t >= 0) & (t < binfo.width[f])
    decoded = jnp.where(in_range, t + (t >= m), m)
    return jnp.where(m >= 0, decoded, bcol)
