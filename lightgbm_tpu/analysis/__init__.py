"""Trace-safety static analysis suite (the USE_DEBUG build analog).

The reference ships a `USE_DEBUG` build whose internal assertions
(`CheckSplit`, serial_tree_learner.h:174) catch learner drift at the
iteration it happens. Our failure modes are different — silent
retraces, dtype widening on the int32 quantized wire, stale device
constants baked into cached traced steps — and every one of them is
detectable BEFORE runtime by inspecting source ASTs and jaxprs. Three
cooperating passes (docs/STATIC_ANALYSIS.md):

- `lint`        AST linter for JAX hazards inside traced code paths
- `jaxpr_audit` abstract-traces the hot entry points and asserts
                machine-checkable contracts (int32 wire, no host
                callbacks, executable-size budgets)
- `retrace`     runtime jit-cache-miss guard (context manager + pytest
                fixture) with `jax.checking_leaks` wired in

Run `python -m lightgbm_tpu.analysis --strict` (CI hook), or use the
pieces directly:

    from lightgbm_tpu.analysis import lint_package, run_audits
    from lightgbm_tpu.analysis.retrace import retrace_guard
"""

from .lint import Finding, RULES, lint_package, lint_source, format_findings

__all__ = [
    "Finding",
    "RULES",
    "lint_package",
    "lint_source",
    "format_findings",
    "run_audits",
]


def run_audits(*args, **kwargs):
    """Lazy forward to jaxpr_audit.run_audits (imports jax)."""
    from .jaxpr_audit import run_audits as _run

    return _run(*args, **kwargs)
