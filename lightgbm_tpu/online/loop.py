"""The online train-and-serve loop (docs/RESILIENCE.md "Online loop").

Serves v(n) from the model registry while microbatches stream in
through the serving transports' ``ingest`` op; each verdict cycle
refits a candidate v(n+1) from the spooled rows (warm-started via
``init_score`` = v(n)'s raw margins, spliced with
``boosting.splice_continued``), judges it on a fixed holdout shard
with the device metrics (online/gate.py), and atomically promotes —
or rejects / auto-reverts — recording the verdict durably.

Crash consistency — the restart invariant is "the last PERSISTED
promotion serves":

======================  ==============================================
kill -9 at…             restart state
======================  ==============================================
``loop_ingest``         v(n) serves; spool intact; cycle replays
``loop_refit``          v(n) serves; offset un-advanced; refit reruns
``loop_eval``           v(n) serves; candidate text durable but
                        unreferenced; cycle replays and overwrites it
``loop_promote``        verdict not yet persisted: v(n) serves, cycle
                        replays (an in-memory registry swap that beat
                        the kill died with the process)
mid state-write         ``os.replace`` atomicity: old or new verdict,
                        never torn
======================  ==============================================

Every phase passes a named ``resilience.fault_point`` site
(``loop_ingest`` / ``loop_refit`` / ``loop_eval`` / ``loop_promote``,
indexed by the ABSOLUTE cycle), so tools/chaos.sh can kill, raise, or
delay deterministically at each edge.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .. import log
from ..config import Config
from ..obs import metrics as obs_metrics
from ..obs.anomaly import AnomalyAbort
from ..resilience.errors import CheckpointError
from ..resilience.faultinject import fault_point
from ..resilience.heartbeat import HeartbeatWriter, health_report
from . import gate as gate_mod
from . import state as state_mod
from .ingest import IngestSpool, spool_path, stack_batches

EVENTS_NAME = "loop_events.jsonl"

# refit anomaly policy mapping: the loop IS the rollback mechanism, so
# ``rollback`` (engine-level retry with a decayed lr — it would retrain
# on the same poisoned rows) maps to ``abort``, and ``off`` maps to
# ``warn`` so the sentinel always runs and the gate always sees trips
_REFIT_POLICY = {"off": "warn", "warn": "warn",
                 "abort": "abort", "rollback": "abort"}


class OnlineLoop:
    """One train-and-serve loop over a durable loop directory.

    ``params`` are ordinary training params (objective, metric,
    num_leaves, …) plus the ``loop_*`` knobs; ``holdout`` is the fixed
    ``(X, y)`` or ``(X, y, weight)`` shard the gate judges on;
    ``initial_model`` (Booster, model text, or path) seeds v0 when the
    loop directory has no state yet — a directory WITH state resumes
    from it and ``initial_model`` is ignored.
    """

    def __init__(self, params: Dict[str, Any], holdout,
                 initial_model=None):
        self._params = dict(params)
        self._cfg = Config(params)
        self.loop_dir = self._cfg.loop_dir
        os.makedirs(self.loop_dir, exist_ok=True)
        self.min_rows = int(self._cfg.loop_min_rows)
        self.rounds = int(self._cfg.loop_rounds)
        self.margin = float(self._cfg.loop_gate_margin)
        self.poll_s = float(self._cfg.loop_poll_s)
        self.spool = IngestSpool(spool_path(self.loop_dir))
        self._lock = threading.Lock()
        # default run() stop signal (an embedder may pass its own)
        self.stop_event = threading.Event()
        self._registry = None
        self._model_name = self._cfg.serve_model_name

        hx, hy = holdout[0], holdout[1]
        self._hx = np.asarray(hx, dtype=np.float64)
        self._hy = np.asarray(hy, dtype=np.float64)
        self._hw = (np.asarray(holdout[2], dtype=np.float64)
                    if len(holdout) > 2 and holdout[2] is not None
                    else None)

        sp = state_mod.state_path(self.loop_dir)
        if os.path.exists(sp):
            self.state = state_mod.load_state(sp)
            text = self._read_model_text(self.state["model_path"])
        else:
            if initial_model is None:
                raise ValueError(
                    f"online loop: {self.loop_dir} has no state and no "
                    "initial_model was provided"
                )
            text = self._model_text_of(initial_model)
            st = state_mod.fresh_state()
            st["model_path"] = state_mod.model_path(self.loop_dir, 0)
            # model text durable BEFORE the state that references it
            state_mod.atomic_write_text(st["model_path"], text)
            state_mod.save_state(sp, st)
            self.state = st
        self._incumbent_text = text
        self._incumbent = self._booster_of(text)
        k = self._incumbent._gbdt.num_class
        self._eval_names, self._eval_hb, self._eval_fn = (
            gate_mod.make_holdout_evaluator(
                self._cfg, self._hy, weight=self._hw, num_class=k))

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _model_text_of(source) -> str:
        if hasattr(source, "model_to_string"):
            return source.model_to_string()
        s = str(source)
        if "\n" not in s and os.path.exists(s):
            with open(s) as f:
                return f.read()
        return s

    @staticmethod
    def _read_model_text(path: str) -> str:
        try:
            with open(path) as f:
                return f.read()
        except OSError as e:
            raise CheckpointError(
                f"loop state references model {path} which cannot be "
                f"read: {e}"
            ) from e

    @staticmethod
    def _booster_of(text: str):
        from ..basic import Booster

        return Booster(model_str=text)

    # ----------------------------------------------------------- registry
    def attach(self, registry, name: Optional[str] = None) -> None:
        """Wire a ModelRegistry/ModelFleet: the incumbent becomes the
        active version of ``name``, the spool becomes the transports'
        ``ingest`` sink, and ``health()`` backs ``/healthz``."""
        with self._lock:
            self._registry = registry
            if name:
                self._model_name = name
        registry.ingest_sink = self.spool
        registry.health_probe = self.health
        registry.load(self._model_name, self.state["model_path"],
                      activate=True)

    # ------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        """Loop liveness for /healthz: durable state + heartbeat report
        (an operator sees a wedged refit from the serving endpoint)."""
        with self._lock:
            st = dict(self.state)
        report = health_report(
            self.loop_dir, expected=1,
            stale_after_s=max(30.0, 10.0 * self.poll_s))
        offset = int(st["ingest_offset"])
        return {
            "loop": {
                "version": int(st["version"]),
                "cycle": int(st["cycle"]),
                "ingest_offset": offset,
                "spool_backlog_bytes": max(self.spool.size() - offset, 0),
                "counts": dict(st["counts"]),
                "last_outcome": st.get("last_outcome"),
            },
            "workers": report,
            "healthy": bool(report["healthy"]),
        }

    # -------------------------------------------------------------- cycle
    def cycle(self) -> Optional[str]:
        """One verdict attempt. Returns the outcome (``promoted`` /
        ``rejected`` / ``rolled_back``) or None when the spool has
        fewer than ``loop_min_rows`` new rows. An ``InjectedFault``
        from a fault plan propagates (chaos tests kill instead)."""
        with self._lock:
            st = dict(self.state)
        c = int(st["cycle"])
        fault_point("loop_ingest", c)
        batches, end = self.spool.read_from(int(st["ingest_offset"]))
        nrows = sum(len(b["labels"]) for b in batches)
        if not batches or nrows < self.min_rows:
            return None
        X, y, w = stack_batches(batches)
        init_kn = gate_mod.raw_margins(self._incumbent, X)

        fault_point("loop_refit", c)
        trips: Dict[str, int] = {}
        reason_extra = ""
        cand_text = None
        try:
            delta = self._train_delta(X, y, w, init_kn)
            trips = dict(
                (getattr(delta, "anomaly_summary", None) or {})
                .get("trips", {}))
        except AnomalyAbort as e:
            trips = {"abort": 1}
            reason_extra = str(e)
            delta = None
        except log.LightGBMError as e:
            # a microbatch the trainer itself rejects (bad labels,
            # degenerate features) is poison by definition: absorb it
            # as a rollback verdict — the loop must outlive bad data
            trips = {"refit_error": 1}
            reason_extra = str(e)
            delta = None

        cand_version = int(st["version"]) + 1
        cand_path = state_mod.model_path(self.loop_dir, cand_version)
        cand = None
        if delta is not None:
            cand_text = self._splice(delta)
            state_mod.atomic_write_text(cand_path, cand_text)
            cand = self._booster_of(cand_text)

        fault_point("loop_eval", c)
        inc_m = st.get("incumbent_metrics")
        if inc_m is None:
            inc_m = gate_mod.evaluate(
                self._eval_fn,
                gate_mod.raw_margins(self._incumbent, self._hx))
        cand_m = None
        if cand is not None:
            cand_m = gate_mod.evaluate(
                self._eval_fn, gate_mod.raw_margins(cand, self._hx))
            outcome, reason = gate_mod.decide(
                cand_m, inc_m, self._eval_names, self._eval_hb,
                self.margin, trips)
        else:
            outcome, reason = "rolled_back", (
                f"refit aborted, keeping v{st['version']}: {reason_extra}")

        fault_point("loop_promote", c)
        promoted = outcome == "promoted"
        if promoted and self._registry is not None:
            # the registry swap is atomic under ITS lock; keep it (and
            # the device warmup it may trigger) outside the loop lock
            self._registry.load(self._model_name, cand_path,
                                activate=True)
        with self._lock:
            if promoted:
                self._incumbent = cand
                self._incumbent_text = cand_text
            new = dict(self.state)
            new["counts"] = dict(new["counts"])
            new["counts"][outcome] = new["counts"].get(outcome, 0) + 1
            new["cycle"] = c + 1
            new["ingest_offset"] = int(end)
            new["last_outcome"] = outcome
            if promoted:
                new["version"] = cand_version
                new["model_path"] = cand_path
                new["incumbent_metrics"] = cand_m
            else:
                new["incumbent_metrics"] = inc_m
            state_mod.save_state(
                state_mod.state_path(self.loop_dir), new)
            self.state = new
        self._record_verdict(new, c, outcome, reason, nrows,
                             int(st["ingest_offset"]), int(end),
                             cand_version, cand_m, inc_m, trips)
        log.info(
            f"online loop cycle {c}: {outcome} ({reason}); serving "
            f"v{new['version']}"
        )
        return outcome

    # ------------------------------------------------------------- phases
    def _train_delta(self, X, y, w, init_kn):
        """Refit a FRESH delta booster over the microbatch rows with
        init_score = v(n)'s margins (class-major flattened, the layout
        boosting._init_score_arr reshapes back)."""
        from .. import engine
        from ..basic import Dataset

        p = dict(self._params)
        for k in ("task", "data", "valid", "valid_data", "input_model",
                  "output_model", "resume", "resume_from",
                  "checkpoint_file"):
            p.pop(k, None)
        p["snapshot_freq"] = 0
        p["num_iterations"] = self.rounds
        p["anomaly_policy"] = _REFIT_POLICY[self._cfg.anomaly_policy]
        p.setdefault("record_file",
                     os.path.join(self.loop_dir, "refit_record.jsonl"))
        # engine.train re-runs faultinject.configure from ITS params:
        # carry the plan through or a mid-loop refit would disarm it
        p["fault_plan"] = self._cfg.fault_plan
        ds = Dataset(
            X, label=y, weight=w,
            init_score=np.asarray(init_kn, np.float64).reshape(-1))
        return engine.train(p, ds, num_boost_round=self.rounds)

    def _splice(self, delta) -> str:
        from ..boosting import splice_continued
        from ..model_io import load_model_string, save_model_string

        base_cfg, base_gbdt = load_model_string(self._incumbent_text)
        splice_continued(base_gbdt, delta._gbdt)
        return save_model_string(base_gbdt, base_cfg)

    def _record_verdict(self, st, cycle, outcome, reason, nrows,
                        off0, off1, cand_version, cand_m, inc_m,
                        trips) -> None:
        """Verdict provenance: the loop's own flight-record stream plus
        a run manifest snapshot, and the /metrics counters."""
        event = {
            "t_unix": time.time(),
            "cycle": int(cycle),
            "outcome": outcome,
            "reason": reason,
            "serving_version": int(st["version"]),
            "candidate_version": int(cand_version),
            "rows": int(nrows),
            "spool_span": [int(off0), int(off1)],
            "metrics": {"names": self._eval_names,
                        "candidate": cand_m, "incumbent": inc_m},
            "anomaly_trips": trips,
        }
        try:
            with open(os.path.join(self.loop_dir, EVENTS_NAME), "a") as f:
                f.write(json.dumps(event) + "\n")
                f.flush()
        except OSError as e:
            log.warning(f"online loop: cannot append event log: {e}")
        obs_metrics.record_promotion_event(outcome)
        obs_metrics.record_loop_progress(
            int(st["version"]), int(st["cycle"]),
            int(st["ingest_offset"]))
        try:
            from ..obs.manifest import write_manifest

            write_manifest(
                os.path.join(self.loop_dir, "run_manifest.json"),
                config=self._cfg,
                extra={"online_loop": {k: v for k, v in st.items()
                                       if k != "schema"}},
            )
        except Exception as e:  # manifest is advisory provenance
            log.warning(f"online loop: manifest write failed: {e}")

    # ---------------------------------------------------------------- run
    def run(self, max_cycles: Optional[int] = None,
            stop: Optional[threading.Event] = None) -> int:
        """Drive verdict cycles until ``max_cycles`` verdicts land
        (``loop_max_cycles``; 0/None = forever) or ``stop`` is set.
        Heartbeats cover the whole run so a wedged refit shows as
        ``stale`` in ``health()``. Returns the number of verdicts."""
        if max_cycles is None:
            max_cycles = int(self._cfg.loop_max_cycles)
        stop = stop or self.stop_event
        hb = HeartbeatWriter(self.loop_dir, rank=0,
                             interval_s=min(self.poll_s, 5.0)).start()
        verdicts = 0
        try:
            while not stop.is_set():
                outcome = self.cycle()
                if outcome is not None:
                    verdicts += 1
                    if max_cycles and verdicts >= max_cycles:
                        break
                    continue
                stop.wait(self.poll_s)
        finally:
            hb.stop()
        return verdicts
