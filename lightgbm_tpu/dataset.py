"""Binned dataset: host construction + device residency.

Equivalent of the reference Dataset/FeatureGroup/Metadata stack
(include/LightGBM/dataset.h:487, src/io/dataset.cpp, src/io/metadata.cpp),
reshaped for TPU:

- all features are stored as ONE dense feature-major bin matrix
  (num_used_features, num_rows_padded) in the narrowest integer dtype,
  padded on the row axis to a block multiple so histogram matmuls tile
  cleanly onto the MXU;
- trivial (constant) features are dropped up front (feature_pre_filter);
- metadata (label/weight/group/init_score/position, reference
  dataset.h:48-399) is validated host-side and shipped as device arrays.

There is no FixHistogram equivalent: the reference omits each feature's
most-frequent bin from sparse storage and reconstructs it from parent
sums (dataset.h:768); our dense device matrix stores every bin, so
histograms are complete by construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import log
from .binning import BinMapper, BinType, MissingType
from .config import Config

from .learner.histogram import HIST_BLK

DEFAULT_ROW_BLOCK = HIST_BLK  # pallas histogram row block


def _choose_bin_dtype(max_num_bin: int) -> Any:
    if max_num_bin <= 256:
        return np.uint8
    if max_num_bin <= 65536:
        return np.uint16
    return np.int32


def bin_chunk(proto: "BinnedDataset", chunk: np.ndarray, dtype) -> np.ndarray:
    """Bin one (rows, features) float chunk with a constructed dataset's
    mappers (+ EFB encode) -> (G, rows) device-column matrix. Shared by
    the Sequence streaming path and the two_round text loader — the
    chunked second pass of the reference's two-pass extract
    (dataset_loader.cpp:1399)."""
    used = proto.used_features
    sub = np.empty((len(used), chunk.shape[0]), dtype=dtype)
    for i, f in enumerate(used):
        sub[i] = proto.mappers[f].values_to_bins(chunk[:, f]).astype(dtype)
    if proto.bundle_layout is not None:
        from .bundling import encode

        um = [proto.mappers[f] for f in used]
        sub, _ = encode(
            sub, proto.bundle_layout,
            [m.num_bin for m in um],
            [m.most_freq_bin for m in um],
            dtype,
        )
    return sub


@dataclass
class Metadata:
    """Labels/weights/query groups/init scores (reference dataset.h:48)."""

    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None  # per-query sizes (reference convention)
    init_score: Optional[np.ndarray] = None
    position: Optional[np.ndarray] = None

    def query_boundaries(self) -> Optional[np.ndarray]:
        if self.group is None:
            return None
        return np.concatenate([[0], np.cumsum(self.group)]).astype(np.int64)

    def check(self, num_data: int) -> None:
        if self.label is not None and len(self.label) != num_data:
            log.fatal(f"label length {len(self.label)} != num_data {num_data}")
        if self.weight is not None and len(self.weight) != num_data:
            log.fatal(f"weight length {len(self.weight)} != num_data {num_data}")
        if self.group is not None and int(np.sum(self.group)) != num_data:
            log.fatal("sum of query group sizes != num_data")


@dataclass
class BinnedDataset:
    """Host-side binned dataset + on-demand device arrays."""

    bins: np.ndarray  # (num_used_features, num_rows) int
    mappers: List[BinMapper]  # one per ORIGINAL feature
    used_features: np.ndarray  # original indices of non-trivial features
    num_data: int
    metadata: Metadata
    feature_names: List[str]
    max_num_bin: int  # uniform bin-axis size on device
    row_block: int
    monotone_constraints: Optional[np.ndarray] = None  # per used feature, in {-1,0,1}
    raw_data: Optional[np.ndarray] = None  # kept for linear trees / refit
    # EFB (bundling.py): when set, `bins` holds BUNDLE columns (G, N)
    # and these describe the feature -> column mapping
    bundle_layout: Optional[Any] = None
    bundle_expand: Optional[np.ndarray] = None  # (F, max_num_bin) int32
    _device: Optional[Dict[str, Any]] = field(default=None, repr=False)

    # ---------------- construction ----------------
    @staticmethod
    def from_numpy(
        data: np.ndarray,
        config: Config,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        position: Optional[np.ndarray] = None,
        categorical_feature: Optional[Sequence[int]] = None,
        feature_names: Optional[Sequence[str]] = None,
        reference: Optional["BinnedDataset"] = None,
        keep_raw: bool = False,
    ) -> "BinnedDataset":
        """Build bin mappers from a sample and bin the full matrix.

        Mirrors DatasetLoader::ConstructFromSampleData semantics
        (src/io/dataset_loader.cpp:1079): sample up to
        bin_construct_sample_cnt rows, FindBin per feature, then bin all
        rows. With `reference`, reuse its mappers (python-package aligned
        valid-set behavior, basic.py Dataset reference semantics).
        """
        data = np.asarray(data)
        if data.ndim != 2:
            log.fatal("data must be 2-dimensional")
        if data.dtype not in (np.float32, np.float64):
            data = data.astype(np.float64)
        num_data, num_features = data.shape
        cat_set = set(int(c) for c in (categorical_feature or ()))

        if feature_names is None:
            feature_names = [f"Column_{i}" for i in range(num_features)]
        feature_names = list(feature_names)

        if reference is not None:
            mappers = reference.mappers
            if len(mappers) != num_features:
                log.fatal("reference dataset has different number of features")
            used = reference.used_features.copy()
            max_num_bin = reference.max_num_bin
            mono = reference.monotone_constraints
        else:
            rng = np.random.RandomState(config.data_random_seed)
            sample_cnt = min(num_data, config.bin_construct_sample_cnt)
            if sample_cnt < num_data:
                sample_idx = np.sort(rng.choice(num_data, sample_cnt, replace=False))
                sample = data[sample_idx]
            else:
                sample = data
            max_bin_by_feature = list(config.max_bin_by_feature)
            from .binning import load_forced_bins

            forced_map = load_forced_bins(
                config.forcedbins_filename, num_features
            )
            mappers = []
            for f in range(num_features):
                mb = (
                    max_bin_by_feature[f]
                    if f < len(max_bin_by_feature)
                    else config.max_bin
                )
                col = sample[:, f]
                mappers.append(
                    BinMapper.from_sample(
                        col,
                        total_sample_cnt=len(sample),
                        # the reference passes config max_bin straight to
                        # FindBin (dataset_loader.cpp:652) — num_bin ends
                        # <= max_bin, NOT max_bin+1
                        max_bin=mb,
                        min_data_in_bin=config.min_data_in_bin,
                        use_missing=config.use_missing,
                        zero_as_missing=config.zero_as_missing,
                        bin_type=BinType.CATEGORICAL if f in cat_set else BinType.NUMERICAL,
                        max_cat_threshold=config.max_cat_threshold,
                        forced_bounds=forced_map.get(f),
                    )
                )
            used = np.array(
                [f for f in range(num_features) if not mappers[f].is_trivial],
                dtype=np.int64,
            )
            if len(used) == 0:
                log.fatal("cannot construct Dataset: all features are constant")
            max_num_bin = max(mappers[f].num_bin for f in used)
            mono = None
            mc = list(config.monotone_constraints)
            if mc:
                if len(mc) != num_features:
                    log.fatal("monotone_constraints length must equal num features")
                mono = np.array([mc[f] for f in used], dtype=np.int8)

        # bin the full matrix, feature-major
        dtype = _choose_bin_dtype(max_num_bin)
        bins = np.empty((len(used), num_data), dtype=dtype)
        for i, f in enumerate(used):
            bins[i] = mappers[f].values_to_bins(data[:, f]).astype(dtype)

        # EFB bundling (dataset.cpp:111 FindGroups / :250
        # FastFeatureBundling): merge near-exclusive sparse features into
        # shared columns. A reference dataset's layout is reused verbatim
        # (valid sets must bin + bundle identically).
        bundle_layout = None
        bundle_expand = None
        if reference is not None:
            bundle_layout = reference.bundle_layout
            bundle_expand = reference.bundle_expand
            if bundle_layout is not None:
                from .bundling import encode

                um = [mappers[f] for f in used]
                merged, _ = encode(
                    bins, bundle_layout,
                    [m.num_bin for m in um],
                    [m.most_freq_bin for m in um],
                    _choose_bin_dtype(bundle_layout.col_bins),
                )
                bins = merged
        elif config.enable_bundle and len(used) > 1:
            from .bundling import bundle_features

            um = [mappers[f] for f in used]
            res = bundle_features(bins, um, config.max_bin)
            if res is not None:
                bins, bundle_layout, bundle_expand = res
                log.info(
                    f"EFB: bundled {len(used)} features into "
                    f"{bundle_layout.num_columns} columns "
                    f"(col bins={bundle_layout.col_bins})"
                )

        meta = Metadata(
            label=None if label is None else np.asarray(label, dtype=np.float32).ravel(),
            weight=None if weight is None else np.asarray(weight, dtype=np.float32).ravel(),
            group=None if group is None else np.asarray(group, dtype=np.int64).ravel(),
            init_score=None if init_score is None else np.asarray(init_score, dtype=np.float64).ravel(),
            position=None if position is None else np.asarray(position, dtype=np.int32).ravel(),
        )
        meta.check(num_data)

        row_block = config.tpu_row_block or DEFAULT_ROW_BLOCK
        if row_block % HIST_BLK != 0:
            # non-HIST_BLK-multiple padding would silently route every
            # histogram to the einsum fallback on TPU; round up instead
            rounded = ((row_block + HIST_BLK - 1) // HIST_BLK) * HIST_BLK
            log.warning(
                f"tpu_row_block={row_block} is not a multiple of the pallas "
                f"histogram block ({HIST_BLK}); rounding up to {rounded}"
            )
            row_block = rounded
        return BinnedDataset(
            bins=bins,
            mappers=mappers,
            used_features=used,
            num_data=num_data,
            metadata=meta,
            feature_names=feature_names,
            max_num_bin=max_num_bin,
            row_block=row_block,
            monotone_constraints=mono,
            raw_data=data if keep_raw else None,
            bundle_layout=bundle_layout,
            bundle_expand=bundle_expand,
        )

    @staticmethod
    def from_csr(
        data,  # scipy sparse matrix (any format with tocsc/tocsr)
        config: Config,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        position: Optional[np.ndarray] = None,
        feature_names: Optional[Sequence[str]] = None,
        reference: Optional["BinnedDataset"] = None,
    ) -> "BinnedDataset":
        """Sparse construction WITHOUT densifying the raw matrix.

        The reference keeps sparse columns delta-encoded
        (sparse_bin.hpp:73) and streams Criteo-scale text via two_round
        (dataset_loader.cpp:210). Here: mappers bin each column's
        NONZERO values (implicit zeros inferred from row counts — the
        same inference FindBin does for its zero-omitting sample), EFB
        conflict counts are sorted row-index intersections
        (bundling.find_groups_sparse), and only the BUNDLED (G, N) bin
        matrix is ever materialized — host peak is O(nnz) + the int
        bundle matrix, never the 8-byte dense (N, F). Categorical
        features and linear trees ride the dense path."""
        csc = data.tocsc()
        csc.sort_indices()
        num_data, num_features = csc.shape

        if reference is not None:
            mappers = reference.mappers
            if len(mappers) != num_features:
                log.fatal("reference dataset has different number of features")
            used = reference.used_features.copy()
            max_num_bin = reference.max_num_bin
            mono = reference.monotone_constraints
        else:
            rng = np.random.RandomState(config.data_random_seed)
            sample_cnt = min(num_data, config.bin_construct_sample_cnt)
            if sample_cnt < num_data:
                idx = np.sort(rng.choice(num_data, sample_cnt, replace=False))
                s_csc = data.tocsr()[idx].tocsc()
            else:
                s_csc = csc
            mb_list = list(config.max_bin_by_feature)
            from .binning import load_forced_bins

            forced_map = load_forced_bins(
                config.forcedbins_filename, num_features
            )
            mappers = []
            for f in range(num_features):
                vals = s_csc.data[s_csc.indptr[f]: s_csc.indptr[f + 1]]
                mb = mb_list[f] if f < len(mb_list) else config.max_bin
                mappers.append(
                    BinMapper.from_sample(
                        vals,
                        total_sample_cnt=s_csc.shape[0],
                        max_bin=mb,
                        min_data_in_bin=config.min_data_in_bin,
                        use_missing=config.use_missing,
                        zero_as_missing=config.zero_as_missing,
                        forced_bounds=forced_map.get(f),
                    )
                )
            used = np.array(
                [f for f in range(num_features) if not mappers[f].is_trivial],
                dtype=np.int64,
            )
            if len(used) == 0:
                log.fatal("cannot construct Dataset: all features are constant")
            max_num_bin = max(mappers[f].num_bin for f in used)
            mono = None
            mc = list(config.monotone_constraints)
            if mc:
                if len(mc) != num_features:
                    log.fatal(
                        "monotone_constraints length must equal num features"
                    )
                mono = np.array([mc[f] for f in used], dtype=np.int8)

        # per-used-feature nonzero (rows, bins) + non-default row sets
        nz = []
        nd_rows: List[Optional[np.ndarray]] = []
        for f in used:
            f = int(f)
            lo, hi = csc.indptr[f], csc.indptr[f + 1]
            rows = csc.indices[lo:hi]
            b = mappers[f].values_to_bins(csc.data[lo:hi])
            nz.append((rows, b))
            m = mappers[f]
            # mergeable only when the implicit zeros sit in the
            # most-freq bin (merged columns never store that bin)
            if m.most_freq_bin == m.default_bin:
                nd_rows.append(np.asarray(rows[b != m.most_freq_bin]))
            else:
                nd_rows.append(None)

        from .bundling import (
            build_expand_idx,
            build_layout,
            find_groups_sparse,
        )

        um = [mappers[int(f)] for f in used]
        u_bins = [m.num_bin for m in um]
        if reference is not None:
            bundle_layout = reference.bundle_layout
            bundle_expand = reference.bundle_expand
            groups = (
                bundle_layout.groups if bundle_layout is not None
                else [[i] for i in range(len(used))]
            )
            layout = bundle_layout
        elif config.enable_bundle and len(used) > 1:
            groups = find_groups_sparse(
                nd_rows, u_bins, num_data,
                max(config.max_bin + 1, 256),  # same cap as the dense path
            )
            if all(len(g) == 1 for g in groups):
                layout = None
                groups = [[i] for i in range(len(used))]
            else:
                layout = build_layout(groups, u_bins)
                log.info(
                    f"EFB (sparse): bundled {len(used)} features into "
                    f"{layout.num_columns} columns "
                    f"(col bins={layout.col_bins})"
                )
        else:
            layout = None
            groups = [[i] for i in range(len(used))]

        col_bins = layout.col_bins if layout is not None else max_num_bin
        dtype = _choose_bin_dtype(max(col_bins, max_num_bin))
        G = len(groups)
        bins = np.zeros((G, num_data), dtype=dtype)
        mfb = np.full(len(used), -1, np.int32)
        for gid, feats in enumerate(groups):
            if len(feats) == 1:
                i = feats[0]
                rows, b = nz[i]
                db = um[i].default_bin
                if db != 0:
                    bins[gid, :] = db
                bins[gid, rows] = b.astype(dtype)
                continue
            col = bins[gid]
            for i in feats:
                rows, b = nz[i]
                m = int(um[i].most_freq_bin)
                mfb[i] = m
                db = int(um[i].default_bin)
                if db != m:
                    # a reference layout built densely may merge a
                    # feature whose most-freq bin is NOT the zero bin;
                    # its IMPLICIT zero rows then carry default_bin and
                    # must be offset-encoded like any non-mfb bin
                    # (the fresh sparse path never merges such features)
                    imp = np.setdiff1d(
                        np.arange(num_data, dtype=rows.dtype), rows,
                        assume_unique=True,
                    )
                    col[imp] = dtype(
                        int(layout.off_lo[i]) + db - (db > m)
                    )
                ndm = b != m
                shifted = b[ndm].astype(np.int64) - (b[ndm] > m)
                col[rows[ndm]] = (layout.off_lo[i] + shifted).astype(dtype)
        bundle_layout = None
        bundle_expand = None
        if layout is not None:
            if reference is None:
                layout = layout._replace(mfb=mfb)
                bundle_expand = build_expand_idx(layout, u_bins, max_num_bin)
            else:
                bundle_expand = reference.bundle_expand
            bundle_layout = layout

        meta = Metadata(
            label=None if label is None else np.asarray(label, dtype=np.float32).ravel(),
            weight=None if weight is None else np.asarray(weight, dtype=np.float32).ravel(),
            group=None if group is None else np.asarray(group, dtype=np.int64).ravel(),
            init_score=None if init_score is None else np.asarray(init_score, dtype=np.float64).ravel(),
            position=None if position is None else np.asarray(position, dtype=np.int32).ravel(),
        )
        meta.check(num_data)

        row_block = config.tpu_row_block or DEFAULT_ROW_BLOCK
        if row_block % HIST_BLK != 0:
            row_block = ((row_block + HIST_BLK - 1) // HIST_BLK) * HIST_BLK
        return BinnedDataset(
            bins=bins,
            mappers=mappers,
            used_features=used,
            num_data=num_data,
            metadata=meta,
            feature_names=(
                list(feature_names) if feature_names is not None
                else [f"Column_{i}" for i in range(num_features)]
            ),
            max_num_bin=max_num_bin,
            row_block=row_block,
            monotone_constraints=mono,
            raw_data=None,
            bundle_layout=bundle_layout,
            bundle_expand=bundle_expand,
        )

    @staticmethod
    def from_sequences(
        seqs: Sequence[Any],
        config: Config,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        position: Optional[np.ndarray] = None,
        categorical_feature: Optional[Sequence[int]] = None,
        feature_names: Optional[Sequence[str]] = None,
    ) -> "BinnedDataset":
        """Two-pass streaming construction from random-access Sequences
        (reference python Sequence ABC basic.py:905 + streaming push
        APIs dataset.h:518-627): pass 1 samples rows across all
        sequences and builds the bin mappers; pass 2 streams
        batch-sized chunks straight into the int bin matrix — the full
        float64 matrix is never materialized (4-8x peak-memory saving,
        the reason the reference's two_round/push path exists).
        """
        lens = [len(s) for s in seqs]
        total = int(np.sum(lens))
        if total == 0:
            log.fatal("cannot construct Dataset from empty sequences")
        rng = np.random.RandomState(config.data_random_seed)
        n_sample = min(total, config.bin_construct_sample_cnt)
        idx = np.sort(rng.choice(total, n_sample, replace=False))
        bounds = np.concatenate([[0], np.cumsum(lens)])

        def _rows(global_rows: np.ndarray) -> np.ndarray:
            out = []
            for g in global_rows:
                s = int(np.searchsorted(bounds, g, side="right")) - 1
                row = np.asarray(seqs[s][int(g - bounds[s])], np.float64)
                out.append(row.reshape(-1))
            return np.asarray(out)

        sample = _rows(idx)
        # mappers/EFB layout from the sample; then stream-bin all rows
        proto = BinnedDataset.from_numpy(
            sample, config,
            categorical_feature=categorical_feature,
            feature_names=feature_names,
        )
        G = proto.bins.shape[0]
        dtype = proto.bins.dtype
        bins = np.empty((G, total), dtype=dtype)
        row0 = 0
        for s in seqs:
            bs = int(getattr(s, "batch_size", 4096) or 4096)
            for lo in range(0, len(s), bs):
                chunk = np.asarray(s[lo : lo + bs], np.float64)
                if chunk.ndim == 1:
                    chunk = chunk.reshape(1, -1)
                bins[:, row0 : row0 + chunk.shape[0]] = bin_chunk(
                    proto, chunk, dtype
                )
                row0 += chunk.shape[0]
        meta = Metadata(
            label=None if label is None else np.asarray(label, np.float32).ravel(),
            weight=None if weight is None else np.asarray(weight, np.float32).ravel(),
            group=None if group is None else np.asarray(group, np.int64).ravel(),
            init_score=None if init_score is None else np.asarray(init_score, np.float64).ravel(),
            position=None if position is None else np.asarray(position, np.int32).ravel(),
        )
        meta.check(total)
        return BinnedDataset(
            bins=bins,
            mappers=proto.mappers,
            used_features=proto.used_features,
            num_data=total,
            metadata=meta,
            feature_names=list(proto.feature_names),
            max_num_bin=proto.max_num_bin,
            row_block=proto.row_block,
            monotone_constraints=proto.monotone_constraints,
            raw_data=None,
            bundle_layout=proto.bundle_layout,
            bundle_expand=proto.bundle_expand,
        )

    def _subset_metadata(self, idx: np.ndarray) -> Metadata:
        """Slice metadata for a row subset (query-group aligned when
        possible). Shared by the in-RAM and streamed copy_subrow."""
        meta = self.metadata
        group = None
        if meta.group is not None:
            # only query-aligned subsets keep ranking metadata
            qb = meta.query_boundaries()
            starts = set(qb[:-1].tolist())
            sizes = []
            i = 0
            aligned = True
            while i < len(idx):
                if int(idx[i]) not in starts:
                    aligned = False
                    break
                q = int(np.searchsorted(qb, idx[i], side="right")) - 1
                qlen = int(qb[q + 1] - qb[q])
                if i + qlen > len(idx) or not np.array_equal(
                    idx[i : i + qlen], np.arange(idx[i], idx[i] + qlen)
                ):
                    aligned = False
                    break
                sizes.append(qlen)
                i += qlen
            if aligned:
                group = np.asarray(sizes, dtype=np.int64)
            else:
                log.warning(
                    "subset indices do not align with query boundaries; group info dropped"
                )
        return Metadata(
            label=None if meta.label is None else meta.label[idx],
            weight=None if meta.weight is None else meta.weight[idx],
            group=group,
            init_score=None if meta.init_score is None else meta.init_score[idx],
            position=None if meta.position is None else meta.position[idx],
        )

    def copy_subrow(self, indices: np.ndarray) -> "BinnedDataset":
        """Row subset sharing bin mappers (reference Dataset::CopySubrow,
        dataset.h — used by bagging-subset and python Dataset.subset)."""
        idx = np.asarray(indices, dtype=np.int64)
        sub_meta = self._subset_metadata(idx)
        return BinnedDataset(
            bins=np.ascontiguousarray(self.bins[:, idx]),
            mappers=self.mappers,
            used_features=self.used_features,
            num_data=len(idx),
            metadata=sub_meta,
            feature_names=self.feature_names,
            max_num_bin=self.max_num_bin,
            row_block=self.row_block,
            monotone_constraints=self.monotone_constraints,
            raw_data=None if self.raw_data is None else self.raw_data[idx],
            bundle_layout=self.bundle_layout,
            bundle_expand=self.bundle_expand,
        )

    # ---------------- derived host info ----------------
    @property
    def num_used_features(self) -> int:
        return len(self.used_features)

    @property
    def num_total_features(self) -> int:
        return len(self.mappers)

    def used_mappers(self) -> List[BinMapper]:
        return [self.mappers[f] for f in self.used_features]

    def num_rows_padded(self) -> int:
        b = self.row_block
        n = ((self.num_data + b - 1) // b) * b
        return max(n, getattr(self, "_min_padded_rows", 0))

    def ensure_min_padded_rows(self, target: int) -> None:
        """Force the padded row count up to `target` (a row_block
        multiple). Multi-host pre-partitioned training needs EQUAL
        per-rank shards for the global mesh sharding — ranks pad to the
        cluster-wide maximum (reference pre_partition keeps uneven
        shards because its collectives carry explicit sizes;
        NamedSharding tiles evenly)."""
        if target % self.row_block != 0:
            raise ValueError((target, self.row_block))
        if target > self.num_rows_padded():
            self._min_padded_rows = int(target)
            self.invalidate_device_cache()

    def ensure_row_block(self, blk: int) -> None:
        """Raise the device row padding so per-shard rows stay a pallas
        block multiple under a data mesh (data-parallel training). Must
        run before the first device push; drops any cached arrays."""
        if self.row_block % blk != 0:
            g = np.gcd(self.row_block, blk)
            self.row_block = self.row_block // g * blk
            self.invalidate_device_cache()

    def invalidate_device_cache(self) -> None:
        """Drop cached device arrays (next device_arrays() re-pushes).
        Used when padding changes or when a mesh booster keeps its own
        sharded copies and the unsharded ones would waste HBM."""
        self._device = None

    # ---------------- device arrays ----------------
    def device_arrays(self) -> Dict[str, Any]:
        """Push the bin matrix + per-feature info to device (cached).

        Returns dict with:
          bins      (F, Np) int32 — feature-major bin matrix, rows padded
                    with bin 0 to a row_block multiple; rows ride the
                    LANE axis (TPU memory tiles pad the minor-most dim to
                    128, so the long axis must be last)
          valid     (Np,)  float32  — 1.0 for real rows, 0.0 for padding
          nan_bin   (F,)   int32    — NaN bin index per feature, -1 if none
          num_bins  (F,)   int32    — per-feature bin count
          mono      (F,)   int32    — monotone constraint per feature
          is_cat    (F,)   bool     — categorical flag
        """
        if self._device is not None:
            return self._device
        import jax.numpy as jnp

        npad = self.num_rows_padded()
        f = self.num_used_features
        ncols = self.bins.shape[0]  # bundle columns (== f without EFB)
        bins_fm = np.zeros((ncols, npad), dtype=np.int32)
        bins_fm[:, : self.num_data] = self.bins
        um = self.used_mappers()
        nan_bin = np.array([m.nan_bin for m in um], dtype=np.int32)
        num_bins = np.array([m.num_bin for m in um], dtype=np.int32)
        is_cat = np.array([m.bin_type == BinType.CATEGORICAL for m in um])
        mono = (
            self.monotone_constraints.astype(np.int32)
            if self.monotone_constraints is not None
            else np.zeros(f, dtype=np.int32)
        )
        valid = np.zeros(npad, dtype=np.float32)
        valid[: self.num_data] = 1.0
        self._device = {
            "bins": jnp.asarray(bins_fm),
            "valid": jnp.asarray(valid),
            "nan_bin": jnp.asarray(nan_bin),
            "num_bins": jnp.asarray(num_bins),
            "mono": jnp.asarray(mono),
            "is_cat": jnp.asarray(is_cat),
            "bundle": self._bundle_info(),
        }
        return self._device

    def _bundle_info(self):
        """Device BundleInfo for the growers, or None without EFB."""
        if self.bundle_layout is None:
            return None
        import jax.numpy as jnp

        from .learner.bundle import BundleInfo

        lay = self.bundle_layout
        um = self.used_mappers()
        width = np.array(
            [m.num_bin - (1 if lay.mfb[i] >= 0 else 0) for i, m in enumerate(um)],
            dtype=np.int32,
        )
        return BundleInfo(
            bundle_of=jnp.asarray(lay.bundle_of),
            off_lo=jnp.asarray(lay.off_lo),
            mfb=jnp.asarray(lay.mfb),
            expand_idx=jnp.asarray(self.bundle_expand),
            width=jnp.asarray(width),
        )

    @property
    def col_bins(self) -> int:
        """Uniform device bin-axis size of the stored columns."""
        if self.bundle_layout is not None:
            return max(self.bundle_layout.col_bins, self.max_num_bin)
        return self.max_num_bin

    def padded(self, arr: Optional[np.ndarray], fill: float = 0.0, dtype=np.float32) -> np.ndarray:
        """Pad a per-row array to num_rows_padded."""
        npad = self.num_rows_padded()
        out = np.full(npad, fill, dtype=dtype)
        if arr is not None:
            out[: self.num_data] = arr
        return out

    def feature_infos(self) -> List[str]:
        return [m.feature_info_str() for m in self.mappers]
