"""Out-of-core data plane (docs/DATA_PLANE.md).

Dataset size bounded by disk, not host RAM (ROADMAP item 3b; the
reference streams Criteo-class text via two_round loading and the
Sequence ABC — this package generalizes that to ANY input kind):

- ``store``      — disk-backed chunked columnar store: fixed-row-count
                   chunks of feature columns in a spool directory with
                   an atomically-committed manifest; writable from
                   numpy arrays, the text parsers, any iterator of row
                   blocks, or Dask partitions (dask.py).
- ``streaming``  — two-pass binning over a store: pass 1 samples rows
                   to fit bin mappers + the EFB layout, pass 2 re-reads
                   chunks and spools the packed bin representation —
                   never two raw chunks resident at once.
- ``prefetch``   — double-buffered host->HBM chunk transfers behind a
                   bounded queue, feeding the streamed device-matrix
                   assembly in dataset/streaming.

One memory-budget knob governs the whole plane: ``ram_budget_mb``
(0 = the legacy 1 GB threshold the two_round size warning always
used). :func:`ram_budget_bytes` resolves it and
:func:`warn_over_budget` is the single warning path for any component
about to exceed it.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .. import log

# resolved default when ram_budget_mb is 0/unset — the 1 GB threshold
# the ad-hoc two_round text-size warning used before this knob existed
DEFAULT_RAM_BUDGET_MB = 1024


def ram_budget_bytes(ram_budget_mb: int) -> int:
    """Resolve the configured budget (MB, 0 = default) to bytes."""
    mb = int(ram_budget_mb) if ram_budget_mb else DEFAULT_RAM_BUDGET_MB
    return mb << 20


def warn_over_budget(what: str, nbytes: int, ram_budget_mb: int,
                     hint: str) -> bool:
    """THE memory-budget warning path: one format, one knob. Returns
    whether the warning fired (callers branch on it for tests)."""
    budget = ram_budget_bytes(ram_budget_mb)
    if nbytes <= budget:
        return False
    log.warning(
        f"{what} is {nbytes / (1 << 20):.0f} MB, over the "
        f"{budget >> 20} MB host RAM budget "
        f"(ram_budget_mb={int(ram_budget_mb) or 0}, 0 = "
        f"{DEFAULT_RAM_BUDGET_MB} MB default); {hint}"
    )
    return True


# ---------------------------------------------------------------------------
# data-plane run stats: the most recent ingestion's footprint, folded
# into the run manifest as manifest["data_plane"] (obs/manifest.py) —
# same last-run registry pattern as the flight recorder's
# last_summary(). Guarded by a lock: the prefetcher's reader thread
# reports per-chunk stats concurrently with the consumer.
# ---------------------------------------------------------------------------
_stats_lock = threading.Lock()
_last_stats: Optional[Dict[str, Any]] = None


def record_stats(section: str, payload: Dict[str, Any]) -> None:
    """Merge one section (spool/pass1/pass2/assemble/...) into the
    current data-plane record."""
    global _last_stats
    with _stats_lock:
        if _last_stats is None:
            _last_stats = {}
        _last_stats[section] = payload


def last_stats() -> Optional[Dict[str, Any]]:
    """The most recent data-plane record, or None when the chunked
    plane has not run in this process."""
    with _stats_lock:
        return None if _last_stats is None else dict(_last_stats)


def reset_stats() -> None:
    global _last_stats
    with _stats_lock:
        _last_stats = None
