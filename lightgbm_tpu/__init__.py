"""lightgbm_tpu — a TPU-native gradient-boosted decision tree framework.

A from-scratch reimplementation of the capabilities of LightGBM
(reference: /root/reference, v4.6.0.99) designed TPU-first:

- the dataset lives on device as a feature-major bin matrix,
- feature histograms are built as one-hot matmuls on the MXU
  (analog of reference src/treelearner/cuda/cuda_histogram_constructor.cu),
- split finding is a vectorized cumulative-sum + masked argmax over all
  (feature, threshold) pairs (analog of cuda_best_split_finder.cu),
- data partition is a flat per-row leaf-id vector updated with masked
  `where` (analog of cuda_data_partition.cu data_index_to_leaf_index),
- distributed training shards rows over a `jax.sharding.Mesh` and reduces
  histograms with `lax.psum`/`psum_scatter` over ICI (analog of
  src/network/ reduce-scatter in data_parallel_tree_learner.cpp).

The public Python API mirrors the reference python-package
(`lightgbm.train`, `Dataset`, `Booster`, sklearn wrappers) so user code
ports with an import change.
"""

from . import serving
from .basic import Booster, Dataset, Sequence, set_network
from .callback import early_stopping, log_evaluation, record_evaluation, reset_parameter
from .engine import CVBooster, cv, train
from .log import register_logger

from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
from .callback import EarlyStopException
from .dask import DaskLGBMClassifier, DaskLGBMRanker, DaskLGBMRegressor
from .plotting import (
    create_tree_digraph,
    plot_importance,
    plot_metric,
    plot_split_value_histogram,
    plot_tree,
)

__version__ = "0.1.0"

__all__ = [
    "Booster",
    "Dataset",
    "Sequence",
    "set_network",
    "CVBooster",
    "cv",
    "train",
    "early_stopping",
    "log_evaluation",
    "record_evaluation",
    "reset_parameter",
    "register_logger",
    "LGBMModel",
    "LGBMClassifier",
    "LGBMRegressor",
    "LGBMRanker",
    "DaskLGBMClassifier",
    "DaskLGBMRegressor",
    "DaskLGBMRanker",
    "EarlyStopException",
    "plot_importance",
    "plot_split_value_histogram",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
    "serving",
    "__version__",
]
