"""Streamed two_round text loading (dataset_loader.cpp:210 two_round +
:1399 two-pass extract; VERDICT r4 item 7): the whole-file loader
materializes O(file) host memory, the streamed path O(chunk) + the
binned matrix."""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb

REPO = Path(__file__).resolve().parent.parent


def _write_csv(path, n=20000, f=6, seed=0, group=False):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    w = rs.randn(f)
    y = (X @ w > 0).astype(np.float64)
    cols = [y] + [X[:, j] for j in range(f)]
    np.savetxt(path, np.column_stack(cols), delimiter=",", fmt="%.6f")
    return X, y


def test_two_round_matches_whole_file(tmp_path):
    """two_round=true must produce the SAME binned dataset and the same
    trained model as the whole-file loader."""
    p = tmp_path / "data.csv"
    _write_csv(p)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    ds_full = lgb.Dataset(str(p), params=dict(params))
    ds_full.construct()
    ds_stream = lgb.Dataset(str(p), params=dict(params, two_round=True))
    ds_stream.construct()
    np.testing.assert_array_equal(ds_full._binned.bins,
                                  ds_stream._binned.bins)
    np.testing.assert_array_equal(ds_full._binned.metadata.label,
                                  ds_stream._binned.metadata.label)

    b1 = lgb.train(dict(params), ds_full, num_boost_round=5)
    b2 = lgb.train(dict(params), ds_stream, num_boost_round=5)
    Xp = np.asarray(_write_csv(tmp_path / "probe.csv", n=200, seed=1)[0])
    np.testing.assert_allclose(b1.predict(Xp), b2.predict(Xp), rtol=1e-6)


def test_two_round_sidecars_and_header(tmp_path):
    p = tmp_path / "data.csv"
    X, y = _write_csv(p, n=3000)
    rs = np.random.RandomState(2)
    w = 0.5 + rs.rand(3000)
    np.savetxt(tmp_path / "data.csv.weight", w, fmt="%.5f")
    ds = lgb.Dataset(str(p), params={"two_round": True, "verbosity": -1})
    ds.construct()
    np.testing.assert_allclose(ds._binned.metadata.weight, w, atol=1e-4)


def test_two_round_bounded_memory(tmp_path):
    """A ~120 MB CSV whose float64 matrix is ~115 MB: the streamed
    loader's peak PYTHON-HEAP allocation (tracemalloc covers numpy
    buffers) must stay under half the matrix; the whole-file loader
    peaks at >= the matrix."""
    import tracemalloc

    p = tmp_path / "big.csv"
    rs = np.random.RandomState(0)
    f = 8
    n = 1_600_000
    with open(p, "w") as fh:
        chunk = 100_000
        wv = rs.randn(f)
        for lo in range(0, n, chunk):
            X = rs.randn(chunk, f)
            y = (X @ wv > 0).astype(np.float64)
            np.savetxt(fh, np.column_stack([y] + [X[:, j] for j in range(f)]),
                       delimiter=",", fmt="%.5f")
    mat_bytes = n * (f + 1) * 8

    def peak_of(two_round: bool) -> int:
        tracemalloc.start()
        ds = lgb.Dataset(str(p), params={"two_round": two_round,
                                         "verbosity": -1})
        ds.construct()
        assert ds._binned.num_data == n
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        return peak

    peak_stream = peak_of(True)
    peak_full = peak_of(False)
    # the whole-file loader must hold the float64 matrix; the streamed
    # one holds chunk buffers + the sample + the int bin matrix
    # (~78 MB measured vs ~134 MB, chunk_rows=65536)
    assert peak_full >= mat_bytes, (peak_full, mat_bytes)
    assert peak_stream < peak_full - mat_bytes // 3, (
        peak_stream, peak_full, mat_bytes)


def test_two_round_reference_falls_back_to_train_mappers(tmp_path):
    """A validation Dataset built from a file with reference= must be
    binned with the TRAINING set's mappers — the streamed path cannot
    honor that, so it must fall back to the whole-file loader."""
    ptr = tmp_path / "train.csv"
    pv = tmp_path / "valid.csv"
    _write_csv(ptr, n=4000, seed=0)
    _write_csv(pv, n=1000, seed=5)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "two_round": True}
    tr = lgb.Dataset(str(ptr), params=dict(params))
    tr.construct()
    va = lgb.Dataset(str(pv), params=dict(params), reference=tr)
    va.construct()
    va_plain = lgb.Dataset(str(pv), params={"verbosity": -1}, reference=tr)
    va_plain.construct()
    np.testing.assert_array_equal(va._binned.bins, va_plain._binned.bins)
    # same mappers object semantics: identical bin upper bounds
    for a, b in zip(va._binned.mappers, tr._binned.mappers):
        np.testing.assert_array_equal(
            np.asarray(a.upper_bounds), np.asarray(b.upper_bounds))


def test_no_auto_stream_above_1gb(tmp_path, monkeypatch, capsys):
    """Streaming requires EXPLICIT two_round=true (ADVICE r5 low): a
    text file crossing the 1 GB threshold must NOT silently switch bin
    boundaries to the reservoir-sampled streamed path — it keeps the
    whole-file loader and warns about the opt-in."""
    import os as _os

    p = tmp_path / "data.csv"
    _write_csv(p, n=4000)
    real_getsize = _os.path.getsize
    monkeypatch.setattr(
        _os.path, "getsize",
        lambda q: (2 << 30) if str(q) == str(p) else real_getsize(q),
    )
    streamed = []
    import lightgbm_tpu.parsers as parsers

    real_stream = parsers.load_text_file_two_round
    monkeypatch.setattr(
        parsers, "load_text_file_two_round",
        lambda *a, **k: streamed.append(1) or real_stream(*a, **k),
    )
    ds = lgb.Dataset(str(p), params={"verbosity": 1})
    ds.construct()
    assert not streamed, "auto-enabled streamed two_round without opt-in"
    err = capsys.readouterr()
    assert "two_round" in err.err + err.out  # the parity-deviation warning
    # explicit opt-in still streams
    ds2 = lgb.Dataset(str(p), params={"two_round": True, "verbosity": -1})
    ds2.construct()
    assert streamed
