"""GBDT boosting driver (reference src/boosting/gbdt.cpp).

Owns the training loop state: per-dataset device scores, the objective,
the sampling strategy, and the growing list of trees. Each iteration:

  gradients (device, objective)  ->  sampling mask (bagging/GOSS)
  ->  grow_tree (jit; one call per class-tree)  ->  leaf renewal for
  percentile objectives (RenewTreeOutput, objective_function.h:55)
  ->  score updates: train via the partition vector
  (score_updater.hpp AddScore fast path), valid via device tree
  traversal  ->  host Tree for the model list.

Boost-from-average follows gbdt.cpp:327-445: the initial score is added
to all scorers before the first iteration and folded into the first
tree's leaf values afterwards (Tree::AddBias), so saved models are
self-contained.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import log
from .config import Config
from .dataset import BinnedDataset
from .learner import GrowerSpec, grow_tree, make_split_params
from .learner.grower import TreeArrays, add_score
from .metrics import Metric, create_metrics
from .objectives import ObjectiveFunction, create_objective
from .sample_strategy import create_sample_strategy
from .tree import Tree, traverse_tree_bins


@dataclass
class _ScoreSet:
    dataset: BinnedDataset
    score: Any  # (K, Npad) device f32
    name: str
    metrics: List[Metric] = field(default_factory=list)


def _jit_traverse():
    import jax

    return jax.jit(traverse_tree_bins)


class GBDT:
    """Training driver (reference gbdt.h:37)."""

    def __init__(self, config: Config, train_set: Optional[BinnedDataset]):
        import jax.numpy as jnp

        self.config = config
        self.train_set = train_set
        self.objective: Optional[ObjectiveFunction] = create_objective(config)
        self.num_class = config.num_model_per_iteration
        self.shrinkage_rate = config.learning_rate
        self.models: List[Tree] = []  # flat, iteration-major (models_[it*K + k])
        self.device_trees: List[Tuple[TreeArrays, Any]] = []  # (arrays w/ final leaf values, None)
        self.iter_ = 0
        self.best_iteration = -1
        self.valids: List[_ScoreSet] = []
        self._traverse = _jit_traverse()

        if train_set is None:
            return  # prediction-only booster (model loaded from file)

        if self.objective is not None:
            self.objective.init(train_set)
        self.strategy = create_sample_strategy(config, train_set.num_data)
        self.dev = train_set.device_arrays()
        self.spec = GrowerSpec(
            num_leaves=config.num_leaves,
            num_bins=train_set.max_num_bin,
            max_depth=config.max_depth,
            axis_name=None,
        )
        self.params = make_split_params(config)
        self.train = _ScoreSet(
            train_set,
            self._init_score_arr(train_set),
            "training",
            [m for m in create_metrics(config)],
        )
        meta = train_set.metadata
        for m in self.train.metrics:
            m.init(meta.label, meta.weight, meta.group)
        self._boosted_from_average = False
        self._init_scores = [0.0] * self.num_class
        self._feat_rng = np.random.RandomState(config.feature_fraction_seed)
        self._label_dev = (
            jnp.asarray(train_set.padded(meta.label)) if meta.label is not None else None
        )

    # ------------------------------------------------------------------
    def _init_score_arr(self, ds: BinnedDataset):
        import jax.numpy as jnp

        npad = ds.num_rows_padded()
        score = np.zeros((self.num_class, npad), dtype=np.float32)
        init = ds.metadata.init_score
        if init is not None:
            init = np.asarray(init, dtype=np.float32)
            if init.size == ds.num_data * self.num_class:
                score[:, : ds.num_data] = init.reshape(self.num_class, ds.num_data)
            else:
                score[:, : ds.num_data] = init[None, :]
        return jnp.asarray(score)

    def add_valid(self, valid_set: BinnedDataset, name: str) -> None:
        ss = _ScoreSet(
            valid_set,
            self._init_score_arr(valid_set),
            name,
            [m for m in create_metrics(self.config)],
        )
        meta = valid_set.metadata
        for m in ss.metrics:
            m.init(meta.label, meta.weight, meta.group)
        self.valids.append(ss)

    @property
    def has_init_score(self) -> bool:
        return self.train_set.metadata.init_score is not None

    # ------------------------------------------------------------------
    def train_one_iter(
        self, grad: Optional[np.ndarray] = None, hess: Optional[np.ndarray] = None
    ) -> bool:
        """One boosting iteration; returns True when training should stop
        (no splittable leaf), matching GBDT::TrainOneIter (gbdt.cpp:352)."""
        import jax.numpy as jnp

        K = self.num_class
        ds = self.train_set
        init_scores = [0.0] * K

        if grad is None or hess is None:
            if self.objective is None:
                log.fatal("custom objective requires explicit grad/hess")
            # boost from average (first iteration only)
            if (
                not self.models
                and self.config.boost_from_average
                and not self.has_init_score
            ):
                for k in range(K):
                    init = self.objective.boost_from_score(k)
                    if abs(init) > 1e-15:
                        init_scores[k] = init
                        self.train.score = self.train.score.at[k].add(init)
                        for vs in self.valids:
                            vs.score = vs.score.at[k].add(init)
                        log.info(f"Start training from score {init:f}")
            score = self.train.score if K > 1 else self.train.score[0]
            g, h = self.objective.get_gradients(score)
            grad_dev = jnp.reshape(g, (K, -1)).astype(jnp.float32)
            hess_dev = jnp.reshape(h, (K, -1)).astype(jnp.float32)
        else:
            grad = np.asarray(grad, dtype=np.float32).reshape(K, ds.num_data)
            hess = np.asarray(hess, dtype=np.float32).reshape(K, ds.num_data)
            npad = ds.num_rows_padded()
            gp = np.zeros((K, npad), np.float32)
            hp = np.zeros((K, npad), np.float32)
            gp[:, : ds.num_data] = grad
            hp[:, : ds.num_data] = hess
            grad_dev, hess_dev = jnp.asarray(gp), jnp.asarray(hp)

        should_continue = False
        for k in range(K):
            gk, hk = grad_dev[k], hess_dev[k]
            mask, gk, hk = self.strategy.sample(
                self.iter_, gk, hk, self.dev["valid"], self._label_dev
            )
            feat_mask = self._sample_features()
            arrays, row_leaf = grow_tree(
                self.dev["bins"],
                self.dev["nan_bin"],
                self.dev["num_bins"],
                self.dev["mono"],
                self.dev["is_cat"],
                gk,
                hk,
                mask,
                feat_mask,
                self.params,
                self.spec,
                valid=self.dev["valid"],
            )
            n_nodes = int(arrays.num_nodes)
            if n_nodes > 0:
                should_continue = True
                if (
                    self.objective is not None
                    and self.objective.is_renew_tree_output
                ):
                    arrays = self._renew_tree_output(arrays, row_leaf, k, mask)
                # host tree applies shrinkage itself; device copy carries
                # the final (shrunk) leaf values for score updates
                tree = Tree.from_arrays(arrays, ds, self.shrinkage_rate)
                final_leaf = arrays.leaf_value * self.shrinkage_rate
                arrays = arrays._replace(leaf_value=final_leaf)
                one = jnp.float32(1.0)
                self.train.score = self.train.score.at[k].set(
                    add_score(self.train.score[k], row_leaf, final_leaf, one)
                )
                for vs in self.valids:
                    vdev = vs.dataset.device_arrays()
                    leaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"])
                    vs.score = vs.score.at[k].set(
                        add_score(vs.score[k], leaf, final_leaf, one)
                    )
                if abs(init_scores[k]) > 1e-15:
                    tree.leaf_value = tree.leaf_value + init_scores[k]  # AddBias
                self.device_trees.append((arrays, None))
                self.models.append(tree)
            else:
                # stump: constant tree (gbdt.cpp:429-441)
                bias = 0.0
                if len(self.models) < K:
                    if (
                        self.objective is not None
                        and not self.config.boost_from_average
                        and not self.has_init_score
                    ):
                        bias = self.objective.boost_from_score(k)
                        self.train.score = self.train.score.at[k].add(bias)
                        for vs in self.valids:
                            vs.score = vs.score.at[k].add(bias)
                    else:
                        bias = init_scores[k]
                t = Tree(num_leaves=1, shrinkage=1.0)
                t.leaf_value = np.array([bias], np.float64)
                self.models.append(t)
                self.device_trees.append((arrays, None))

        if not should_continue:
            log.warning(
                "Stopped training because there are no more leaves that meet the split requirements"
            )
            if len(self.models) > K:
                for _ in range(K):
                    self.models.pop()
                    self.device_trees.pop()
            return True
        self.iter_ += 1
        return False

    # ------------------------------------------------------------------
    def _sample_features(self):
        import jax.numpy as jnp

        F = self.train_set.num_used_features
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return jnp.ones(F, dtype=bool)
        n = max(1, int(np.ceil(frac * F)))
        chosen = self._feat_rng.choice(F, n, replace=False)
        m = np.zeros(F, dtype=bool)
        m[chosen] = True
        return jnp.asarray(m)

    def _renew_tree_output(self, arrays: TreeArrays, row_leaf, k: int, mask) -> TreeArrays:
        """Percentile leaf refit for l1/huber/quantile/mape
        (RegressionL1loss::RenewTreeOutput)."""
        import jax.numpy as jnp

        ds = self.train_set
        n = ds.num_data
        rl = np.asarray(row_leaf)[:n]
        bag = np.asarray(mask)[:n] > 0
        label = np.asarray(ds.metadata.label, dtype=np.float64)
        score = np.asarray(self.train.score[k])[:n].astype(np.float64)
        resid = label - score
        w = (
            np.asarray(ds.metadata.weight, dtype=np.float64)
            if ds.metadata.weight is not None
            else np.ones(n)
        )
        if hasattr(self.objective, "_label_weight"):  # mape
            w = np.asarray(self.objective._label_weight)[:n].astype(np.float64)
        alpha = self.objective.renew_percentile()
        lv = np.asarray(arrays.leaf_value).copy()
        n_leaves = int(arrays.num_nodes) + 1
        for leaf in range(n_leaves):
            sel = (rl == leaf) & bag
            if not np.any(sel):
                continue
            r, ww = resid[sel], w[sel]
            order = np.argsort(r)
            cw = np.cumsum(ww[order])
            t = alpha * cw[-1]
            idx = min(int(np.searchsorted(cw, t)), len(r) - 1)
            lv[leaf] = r[order][idx]
        return arrays._replace(leaf_value=jnp.asarray(lv))

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:462)."""
        if self.iter_ <= 0:
            return
        K = self.num_class
        for k in reversed(range(K)):
            tree = self.models.pop()
            arrays, _ = self.device_trees.pop()
            if tree.num_leaves > 1:
                leaf = self._traverse(arrays, self.dev["bins"], self.dev["nan_bin"])
                self.train.score = self.train.score.at[k].add(-arrays.leaf_value[leaf])
                for vs in self.valids:
                    vdev = vs.dataset.device_arrays()
                    vleaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"])
                    vs.score = vs.score.at[k].add(-arrays.leaf_value[vleaf])
            else:
                # stump: its constant (boost-from-score bias) was added to
                # the scores directly — remove it too
                bias = float(tree.leaf_value[0])
                if abs(bias) > 1e-15:
                    self.train.score = self.train.score.at[k].add(-bias)
                    for vs in self.valids:
                        vs.score = vs.score.at[k].add(-bias)
        self.iter_ -= 1

    # ------------------------------------------------------------------
    def eval_set(self, ss: _ScoreSet) -> List[Tuple[str, str, float, bool]]:
        n = ss.dataset.num_data
        score = np.asarray(ss.score)[:, :n].astype(np.float64)
        s = score if self.num_class > 1 else score[0]
        out = []
        for m in ss.metrics:
            for name, val, hb in m.eval(s):
                out.append((ss.name, name, val, hb))
        return out

    def eval_train(self):
        return self.eval_set(self.train)

    def eval_valid(self):
        out = []
        for vs in self.valids:
            out.extend(self.eval_set(vs))
        return out

    def get_score(self, ss: _ScoreSet) -> np.ndarray:
        n = ss.dataset.num_data
        return np.asarray(ss.score)[:, :n].astype(np.float64)

    # ------------------------------------------------------------------
    def num_trees(self) -> int:
        return len(self.models)

    def current_iteration(self) -> int:
        return self.iter_

    def predict_raw(
        self,
        X: np.ndarray,
        start_iteration: int = 0,
        num_iteration: int = -1,
    ) -> np.ndarray:
        """Raw margin prediction over host trees (gbdt_prediction.cpp)."""
        X = np.asarray(X, dtype=np.float64)
        K = self.num_class
        n_iters = len(self.models) // K
        end = n_iters if num_iteration <= 0 else min(n_iters, start_iteration + num_iteration)
        out = np.zeros((K, X.shape[0]))
        for it in range(start_iteration, end):
            for k in range(K):
                out[k] += self.models[it * K + k].predict(X)
        return out

    def predict(self, X, start_iteration=0, num_iteration=-1, raw_score=False):
        raw = self.predict_raw(X, start_iteration, num_iteration)
        if not raw_score and self.objective is not None:
            raw = self.objective.convert_output(raw)
        if self.num_class == 1:
            return raw[0]
        return raw.T  # (N, K)

    def predict_leaf_index(self, X, start_iteration=0, num_iteration=-1):
        X = np.asarray(X, dtype=np.float64)
        K = self.num_class
        n_iters = len(self.models) // K
        end = n_iters if num_iteration <= 0 else min(n_iters, start_iteration + num_iteration)
        cols = []
        for it in range(start_iteration, end):
            for k in range(K):
                cols.append(self.models[it * K + k].predict_leaf(X))
        return np.stack(cols, axis=1) if cols else np.zeros((X.shape[0], 0), np.int64)

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        nf = self.train_set.num_total_features if self.train_set else (
            max((int(np.max(t.split_feature)) for t in self.models if len(t.split_feature)), default=-1) + 1
        )
        imp = np.zeros(nf)
        for t in self.models:
            if importance_type == "gain":
                imp += t.feature_importance_gain(nf)
            else:
                imp += t.feature_importance_split(nf)
        return imp
