"""Path-dependent TreeSHAP feature contributions (pred_contrib).

Implements the Lundberg & Lee consistent feature-attribution algorithm
over our host trees, matching the reference semantics
(src/io/tree.cpp:872-1043 Tree::TreeSHAP/ExtendPath/UnwindPath/
UnwoundPathSum/ExpectedValue, surfaced as Booster.predict(pred_contrib=
True)): output has num_features + 1 columns per model, the last column
being the tree-ensemble expected value, and rows sum to the raw score.

The node-weight convention is the reference's: cover fractions come
from training data counts (internal_count / leaf_count).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .tree import Tree, _CAT_MASK, _DEFAULT_LEFT_MASK


def _expected_value(t: Tree) -> float:
    """Cover-weighted mean leaf output (tree.cpp:1035 ExpectedValue)."""
    if t.num_leaves == 1:
        return float(t.leaf_value[0])
    total = float(t.internal_count[0])
    if total <= 0:
        return float(np.mean(t.leaf_value))
    return float(np.dot(t.leaf_count / total, t.leaf_value))


class _Path:
    """The unique-feature path stack of the TreeSHAP recursion."""

    __slots__ = ("feature", "zero", "one", "pweight")

    def __init__(self, capacity: int):
        self.feature = np.zeros(capacity, np.int64)
        self.zero = np.zeros(capacity)
        self.one = np.zeros(capacity)
        self.pweight = np.zeros(capacity)

    def copy_from(self, other: "_Path", base: int, depth: int, off: int) -> None:
        sl = slice(base, base + depth + 1)
        dl = slice(off, off + depth + 1)
        self.feature[dl] = other.feature[sl]
        self.zero[dl] = other.zero[sl]
        self.one[dl] = other.one[sl]
        self.pweight[dl] = other.pweight[sl]


def _extend(p: _Path, base: int, depth: int, zero: float, one: float, feat: int) -> None:
    i = base + depth
    p.feature[i] = feat
    p.zero[i] = zero
    p.one[i] = one
    p.pweight[i] = 1.0 if depth == 0 else 0.0
    d1 = float(depth + 1)
    for j in range(depth - 1, -1, -1):
        p.pweight[base + j + 1] += one * p.pweight[base + j] * (j + 1) / d1
        p.pweight[base + j] = zero * p.pweight[base + j] * (depth - j) / d1


def _unwind(p: _Path, base: int, depth: int, idx: int) -> None:
    one = p.one[base + idx]
    zero = p.zero[base + idx]
    nxt = p.pweight[base + depth]
    d1 = float(depth + 1)
    for j in range(depth - 1, -1, -1):
        if one != 0:
            tmp = p.pweight[base + j]
            p.pweight[base + j] = nxt * d1 / ((j + 1) * one)
            nxt = tmp - p.pweight[base + j] * zero * (depth - j) / d1
        else:
            p.pweight[base + j] = p.pweight[base + j] * d1 / (zero * (depth - j))
    for j in range(idx, depth):
        p.feature[base + j] = p.feature[base + j + 1]
        p.zero[base + j] = p.zero[base + j + 1]
        p.one[base + j] = p.one[base + j + 1]


def _unwound_sum(p: _Path, base: int, depth: int, idx: int) -> float:
    one = p.one[base + idx]
    zero = p.zero[base + idx]
    nxt = p.pweight[base + depth]
    total = 0.0
    d1 = float(depth + 1)
    for j in range(depth - 1, -1, -1):
        if one != 0:
            tmp = nxt * d1 / ((j + 1) * one)
            total += tmp
            nxt = p.pweight[base + j] - tmp * zero * ((depth - j) / d1)
        else:
            total += (p.pweight[base + j] / zero) / ((depth - j) / d1)
    return total


def _tree_shap(
    t: Tree, x: np.ndarray, phi: np.ndarray, node: int, depth: int,
    path: _Path, parent_base: int, parent_zero: float, parent_one: float,
    parent_feat: int,
) -> None:
    # each call owns a fresh path segment starting past the parent's
    base = parent_base + depth
    if depth > 0:
        path.copy_from(path, parent_base, depth - 1, base)
    _extend(path, base, depth, parent_zero, parent_one, parent_feat)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, depth + 1):
            w = _unwound_sum(path, base, depth, i)
            phi[path.feature[base + i]] += (
                w * (path.one[base + i] - path.zero[base + i]) * t.leaf_value[leaf]
            )
        return

    hot = int(t.left_child[node]) if t.go_left(node, x) else int(t.right_child[node])
    cold = (
        int(t.right_child[node])
        if hot == int(t.left_child[node])
        else int(t.left_child[node])
    )

    def count(n: int) -> float:
        return float(t.internal_count[n]) if n >= 0 else float(t.leaf_count[~n])

    w = count(node)
    hot_zero = count(hot) / w
    cold_zero = count(cold) / w
    incoming_zero, incoming_one = 1.0, 1.0

    # if the feature was already on the path, undo its previous split
    feat = int(t.split_feature[node])
    path_idx = -1
    for i in range(1, depth + 1):
        if path.feature[base + i] == feat:
            path_idx = i
            break
    if path_idx >= 0:
        incoming_zero = path.zero[base + path_idx]
        incoming_one = path.one[base + path_idx]
        _unwind(path, base, depth, path_idx)
        depth -= 1

    _tree_shap(t, x, phi, hot, depth + 1, path, base,
               hot_zero * incoming_zero, incoming_one, feat)
    _tree_shap(t, x, phi, cold, depth + 1, path, base,
               cold_zero * incoming_zero, 0.0, feat)


def tree_contrib(t: Tree, x: np.ndarray, phi: np.ndarray,
                 path: "_Path" = None, expected: float = None) -> None:
    """Add one tree's SHAP contributions for row x into phi (F+1,).

    path/expected can be precomputed once per tree (see predict_contrib)
    and reused across rows; the recursion fully overwrites the segments
    it reads, so the buffer needs no re-zeroing.
    """
    phi[-1] += _expected_value(t) if expected is None else expected
    if t.num_leaves == 1:
        return
    if path is None:
        maxd = t.max_depth() + 2
        path = _Path((maxd + 2) * (maxd + 3))
    _tree_shap(t, x, phi, 0, 0, path, 0, 1.0, 1.0, -1)


def predict_contrib(
    models: Sequence[Tree],
    X: np.ndarray,
    num_features: int,
    num_class: int = 1,
    start_iteration: int = 0,
    num_iteration: int = -1,
    average_output: bool = False,
) -> np.ndarray:
    """SHAP contributions for every row: (N, num_class*(num_features+1)).

    Mirrors Booster.predict(pred_contrib=True) layout: per class, F
    feature columns then the expected-value bias column.
    """
    X = np.asarray(X, dtype=np.float64)
    N = X.shape[0]
    K = num_class
    n_iters = len(models) // K
    end = n_iters if num_iteration <= 0 else min(n_iters, start_iteration + num_iteration)
    out = np.zeros((N, K, num_features + 1))
    for it in range(start_iteration, end):
        for k in range(K):
            t = models[it * K + k]
            expected = _expected_value(t)
            maxd = t.max_depth() + 2
            path = _Path((maxd + 2) * (maxd + 3))
            for r in range(N):
                tree_contrib(t, X[r], out[r, k], path, expected)
    if average_output and end > start_iteration:
        out /= end - start_iteration
    return out.reshape(N, K * (num_features + 1))
