#!/usr/bin/env bash
# End-to-end serving smoke test (docs/SERVING.md): train a tiny model
# through the CLI, start the task=serve JSONL loop, score a batch
# through it, and assert parity against Booster.predict on the same
# model file. Runs on the CPU backend so it is safe anywhere.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

python - "$WORK" <<'EOF'
import sys
import numpy as np

work = sys.argv[1]
rs = np.random.RandomState(0)
X = rs.randn(800, 5)
y = (X[:, 0] + X[:, 1] > 0).astype(int)
np.savetxt(f"{work}/train.csv",
           np.column_stack([y, X]), delimiter=",", fmt="%.6g")
np.savetxt(f"{work}/score.csv", X[:64, :], delimiter=",", fmt="%.6g")
EOF

python -m lightgbm_tpu task=train "data=$WORK/train.csv" \
    objective=binary num_leaves=15 num_trees=10 verbosity=-1 \
    "output_model=$WORK/model.txt"

python - "$WORK" <<'EOF'
import io
import json
import subprocess
import sys

import numpy as np

work = sys.argv[1]
rows = np.loadtxt(f"{work}/score.csv", delimiter=",").tolist()
reqs = "\n".join(json.dumps(r) for r in [
    {"op": "ping"},
    {"op": "score", "model": "default", "rows": rows},
    {"op": "stats"},
    {"op": "quit"},
])
proc = subprocess.run(
    [sys.executable, "-m", "lightgbm_tpu", "task=serve",
     f"input_model={work}/model.txt", "serve_buckets=16,64",
     "verbosity=-1"],
    input=reqs, capture_output=True, text=True, timeout=300,
)
assert proc.returncode == 0, proc.stderr[-2000:]
resp = [json.loads(l) for l in proc.stdout.splitlines()
        if l.startswith("{")]
assert resp[0]["pong"], resp[0]
served = np.asarray(resp[1]["pred"])
assert resp[2]["stats"]["default"]["count"] >= 1

# parity vs the Python API on the same model file
import lightgbm_tpu as lgb

bst = lgb.Booster(model_file=f"{work}/model.txt")
host = bst.predict(np.asarray(rows))
err = float(np.abs(served - host).max())
assert err < 1e-5, f"serve/host mismatch: {err}"
print(f"serve_smoke: OK ({len(rows)} rows scored, max |diff| {err:.2e})")
EOF
