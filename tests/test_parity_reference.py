"""Cross-implementation parity vs the ACTUAL reference CLI.

Mirrors the reference's own consistency harness
(tests/python_package_test/test_consistency.py:12-47: train the Python
package with the CLI example configs and assert prediction closeness,
and test_dual.py:19-37: cross-device metric parity within tolerance).

The reference CLI is compiled from /root/reference by
tools/refbuild/build.sh (g++ direct build with vendored-submodule
shims). Tests skip if the toolchain can't produce the binary.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
REF = Path(os.environ.get("REFERENCE_DIR", "/root/reference"))
CLI = REPO / ".refbuild" / "lightgbm"


@pytest.fixture(scope="session")
def ref_cli() -> Path:
    if not CLI.exists():
        build = REPO / "tools" / "refbuild" / "build.sh"
        try:
            subprocess.run(
                ["bash", str(build)], check=True, capture_output=True,
                timeout=900,
            )
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
            pytest.skip(f"reference CLI build failed: {e}")
    if not CLI.exists():
        pytest.skip("reference CLI unavailable")
    return CLI


def run_cli(cli: Path, cwd: Path, *overrides: str) -> str:
    r = subprocess.run(
        [str(cli), *overrides], cwd=cwd, capture_output=True, text=True,
        timeout=600,
    )
    assert r.returncode == 0, f"reference CLI failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout


def load_tsv(path: Path):
    """Label-first TSV as in the reference examples (parser.hpp:56)."""
    data = np.loadtxt(path, delimiter="\t", dtype=np.float64)
    return data[:, 1:], data[:, 0]


@pytest.fixture(scope="session")
def binary_example(ref_cli, tmp_path_factory):
    """Train the reference CLI on examples/binary_classification."""
    work = tmp_path_factory.mktemp("ref_binary")
    ex = REF / "examples" / "binary_classification"
    for f in ("binary.train", "binary.test", "train.conf"):
        (work / f).write_bytes((ex / f).read_bytes())
    run_cli(
        ref_cli, work, "config=train.conf",
        "output_model=model.txt", "num_trees=50", "is_training_metric=false",
    )
    run_cli(
        ref_cli, work, "task=predict", "data=binary.test",
        "input_model=model.txt", "output_result=ref_pred.txt",
    )
    return work


def test_reference_model_loads_and_predicts_allclose(binary_example):
    """A reference-trained model file must load in model_io and produce
    the same predictions the reference CLI produces."""
    import lightgbm_tpu as lgb

    work = binary_example
    bst = lgb.Booster(model_file=work / "model.txt")
    X, _ = load_tsv(work / "binary.test")
    ours = bst.predict(np.ascontiguousarray(X))
    ref = np.loadtxt(work / "ref_pred.txt")
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_binary_train_auc_parity(binary_example):
    """Our training on the same data/params reaches the reference's AUC
    within 1e-2 absolute (stochastic tie-breaks differ; the north-star
    1e-4 bound applies to the same-model predictions above)."""
    from sklearn.metrics import roc_auc_score

    import lightgbm_tpu as lgb

    work = binary_example
    Xtr, ytr = load_tsv(work / "binary.train")
    Xte, yte = load_tsv(work / "binary.test")
    params = {
        "objective": "binary",
        "num_leaves": 63,
        "learning_rate": 0.1,
        "max_bin": 255,
        "metric": "auc",
        "verbosity": -1,
        "min_data_in_leaf": 50,  # examples/binary_classification/train.conf
        "min_sum_hessian_in_leaf": 5.0,
        "is_enable_sparse": True,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=50)
    auc_ours = roc_auc_score(yte, bst.predict(np.ascontiguousarray(Xte)))

    ref = np.loadtxt(work / "ref_pred.txt")
    auc_ref = roc_auc_score(yte, ref)
    assert auc_ours >= auc_ref - 1e-2, (auc_ours, auc_ref)


def test_our_model_loads_in_reference_cli(binary_example, ref_cli):
    """A model we save must load and predict in the reference CLI,
    matching our own predictions (the interop contract both ways)."""
    import lightgbm_tpu as lgb

    work = binary_example
    Xtr, ytr = load_tsv(work / "binary.train")
    Xte, _ = load_tsv(work / "binary.test")
    params = {
        "objective": "binary",
        "num_leaves": 31,
        "learning_rate": 0.1,
        "verbosity": -1,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=20)
    ours = bst.predict(np.ascontiguousarray(Xte))
    bst.save_model(work / "ours.txt")

    run_cli(
        ref_cli, work, "task=predict", "data=binary.test",
        "input_model=ours.txt", "output_result=ours_ref_pred.txt",
    )
    theirs = np.loadtxt(work / "ours_ref_pred.txt")
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)


@pytest.fixture(scope="session")
def regression_example(ref_cli, tmp_path_factory):
    work = tmp_path_factory.mktemp("ref_regression")
    ex = REF / "examples" / "regression"
    for f in ("regression.train", "regression.test", "train.conf"):
        (work / f).write_bytes((ex / f).read_bytes())
    run_cli(
        ref_cli, work, "config=train.conf",
        "output_model=model.txt", "num_trees=50", "is_training_metric=false",
    )
    run_cli(
        ref_cli, work, "task=predict", "data=regression.test",
        "input_model=model.txt", "output_result=ref_pred.txt",
    )
    return work


def test_regression_model_loads_and_predicts_allclose(regression_example):
    import lightgbm_tpu as lgb

    work = regression_example
    bst = lgb.Booster(model_file=work / "model.txt")
    X, _ = load_tsv(work / "regression.test")
    ours = bst.predict(np.ascontiguousarray(X))
    ref = np.loadtxt(work / "ref_pred.txt")
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_regression_train_l2_parity(regression_example):
    import lightgbm_tpu as lgb

    work = regression_example
    Xtr, ytr = load_tsv(work / "regression.train")
    Xte, yte = load_tsv(work / "regression.test")
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "learning_rate": 0.05,
        "metric": "l2",
        "verbosity": -1,
        "min_data_in_leaf": 100,  # examples/regression/train.conf
        "min_sum_hessian_in_leaf": 5.0,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=50)
    mse_ours = float(np.mean((bst.predict(np.ascontiguousarray(Xte)) - yte) ** 2))

    ref = np.loadtxt(work / "ref_pred.txt")
    mse_ref = float(np.mean((ref - yte) ** 2))
    assert mse_ours <= mse_ref * 1.1, (mse_ours, mse_ref)
