"""Serving concurrency linter (analysis/concurrency_lint.py): every
rule red-to-green on fixtures with known violations, the clean idioms
stay clean, suppression syntax, and the real package at zero
unsuppressed findings."""

from pathlib import Path

from lightgbm_tpu.analysis.concurrency_lint import (
    CONCURRENCY_RULES,
    concurrency_lint_package,
    concurrency_lint_source,
)
from lightgbm_tpu.analysis.lint import RULES, format_findings

REPO = Path(__file__).resolve().parents[1]

_VIOLATIONS = '''
import threading
import time

class Bad:
    def __init__(self):
        self._lock = threading.Lock()
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._cond = threading.Condition()
        self._items = []
        self.count = 0

    def locked_write(self):
        with self._lock:
            self._items.append(1)          # ownership: clean
            self.count += 1                # ownership: clean

    def unlocked_write(self):
        self._items.append(2)              # unlocked-write
        self.count = 5                     # unlocked-write

    def ab(self):
        with self._a:
            with self._b:                  # lock-order (vs ba below;
                pass                       # anchored at first edge)

    def ba(self):
        with self._b:
            with self._a:
                pass

    def relock(self):
        with self._lock:
            with self._lock:               # lock-order self-deadlock
                pass

    def fresh_lock(self):
        lk = threading.Lock()              # per-call-lock
        with lk:
            return 1

    def sleepy(self):
        with self._lock:
            time.sleep(1)                  # blocking-under-lock

    def waits_ok(self):
        with self._cond:
            self._cond.wait(0.1)           # held condition: clean

    def indirect(self):
        with self._lock:
            self.slow()                    # blocking-under-lock (call)

    def slow(self):
        time.sleep(2)

    def join_ok(self):
        with self._lock:
            return ",".join(["a", "b"])    # str.join: clean

    def join_bad(self, t):
        with self._lock:
            t.join()                       # blocking-under-lock


class BadProducer:
    def __init__(self):
        self.q = queue.Queue()             # unbounded-producer-queue
        self.t = threading.Thread(target=self._reader)

    def _reader(self):
        for i in range(10):
            x = jnp.asarray(i)             # jax-in-reader-thread
            self.q.put(x)
'''


def _rules_at(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


def test_each_rule_fires_on_fixture():
    fs = concurrency_lint_source(_VIOLATIONS)
    assert len(_rules_at(fs, "unlocked-write")) == 2
    assert len(_rules_at(fs, "lock-order")) == 2  # inversion + relock
    assert len(_rules_at(fs, "per-call-lock")) == 1
    assert len(_rules_at(fs, "blocking-under-lock")) == 3
    assert len(_rules_at(fs, "unbounded-producer-queue")) == 1
    assert len(_rules_at(fs, "jax-in-reader-thread")) == 1
    # every registered rule is exercised by this fixture
    assert {f.rule for f in fs} == set(CONCURRENCY_RULES)


def test_clean_idioms_stay_clean():
    fs = concurrency_lint_source(_VIOLATIONS)
    lines = {f.line for f in fs}
    for i, txt in enumerate(_VIOLATIONS.splitlines(), start=1):
        if "clean" in txt:
            assert i not in lines, f"false positive on line {i}: {txt}"


def test_reentrant_locks_not_flagged():
    """RLock re-acquisition (direct and via a sibling-method call —
    the ModelRegistry._entry pattern) is reentrant and clean; the
    cross-method re-acquire of a PLAIN Lock is the deadlock."""
    src = '''
import threading

class Registry:
    def __init__(self):
        self._lock = threading.RLock()
        self._plain = threading.Lock()

    def _entry(self):
        with self._lock:
            return 1

    def swap(self):
        with self._lock:
            return self._entry()           # RLock reentry: clean

    def bad(self):
        with self._plain:
            return self._helper()          # deadlock via call

    def _helper(self):
        with self._plain:
            return 2
'''
    fs = concurrency_lint_source(src)
    assert len(fs) == 1 and fs[0].rule == "lock-order", \
        format_findings(fs, label="concurrency")
    assert "_plain" in fs[0].message


def test_wait_in_helper_stays_exempt():
    """The coalescing idiom refactored into a helper: a callee that
    only waits on the condition the CALLER holds must stay clean
    (wait releases the lock); a helper waiting on a DIFFERENT
    condition still fires."""
    src = '''
import threading

class Q:
    def __init__(self):
        self._cond = threading.Condition()
        self._other = threading.Condition()

    def _linger(self):
        self._cond.wait(0.002)

    def _linger_other(self):
        self._other.wait(0.002)

    def drain(self):
        with self._cond:
            self._linger()                 # held-cond helper: clean

    def cross(self):
        with self._cond:
            self._linger_other()           # blocking-under-lock
'''
    fs = concurrency_lint_source(src)
    assert len(fs) == 1 and fs[0].rule == "blocking-under-lock", \
        format_findings(fs, label="concurrency")
    assert "_other" in fs[0].message
    assert "blocking-under-lock" in src.splitlines()[fs[0].line - 1]


def test_module_level_locks_tracked():
    """Module-scope primitives (the native/ and timer.py pattern):
    creation at module scope is clean; blocking under them — including
    transitively through a module function — is flagged."""
    src = '''
import threading
import subprocess

_lock = threading.Lock()


def _build():
    subprocess.run(["g++"], timeout=180)


def get_lib():
    with _lock:
        _build()                           # blocking-under-lock
'''
    fs = concurrency_lint_source(src)
    assert len(fs) == 1 and fs[0].rule == "blocking-under-lock", \
        format_findings(fs, label="concurrency")


def test_suppression_comment_and_file_allow():
    src = (
        "import threading\n"
        "import time\n"
        "_lk = threading.Lock()\n"
        "def f():\n"
        "    with _lk:\n"
        "        time.sleep(1)  # lint: allow[blocking-under-lock]\n"
    )
    fs = concurrency_lint_source(src)
    assert len(fs) == 1 and fs[0].suppressed
    src2 = "# lint: allow-file[blocking-under-lock]\n" + src.replace(
        "  # lint: allow[blocking-under-lock]", ""
    )
    fs2 = concurrency_lint_source(src2)
    assert len(fs2) == 1 and fs2[0].suppressed
    # an unrelated rule id does NOT suppress
    src3 = src.replace("blocking-under-lock", "per-call-lock")
    fs3 = concurrency_lint_source(src3)
    assert len(fs3) == 1 and not fs3[0].suppressed


def test_prefetch_idioms_stay_clean():
    """The data-plane prefetcher's contract (docs/DATA_PLANE.md) as a
    fixture: bounded queue + device_put-only looping reader is fully
    clean."""
    src = '''
import queue
import threading

class GoodPrefetcher:
    def __init__(self, depth):
        self._q = queue.Queue(maxsize=max(1, depth))   # bounded: clean
        self._t = threading.Thread(target=self._reader)

    def _reader(self):
        for i in range(100):
            buf = jax.device_put(i)        # transfer only: clean
            self._q.put(buf)
'''
    fs = [f for f in concurrency_lint_source(src) if not f.suppressed]
    assert not fs, format_findings(fs, label="concurrency")


def test_put_once_hedge_queue_stays_clean():
    """The gateway's hedged-attempt pattern: each thread puts at most
    ONCE, so its unbounded queue is bounded by the attempt count and
    must not trip unbounded-producer-queue — but jax work beyond the
    transfer on that producer thread still fires."""
    src = '''
import queue
import threading

class PutOnceHedge:
    def __init__(self):
        self._q = queue.Queue()            # put-once producer: clean

    def _spawn(self):
        threading.Thread(target=self._attempt).start()

    def _attempt(self):
        r = jnp.ones(3)                    # jax-in-reader-thread
        self._q.put(r)
'''
    fs = [f for f in concurrency_lint_source(src) if not f.suppressed]
    assert [f.rule for f in fs] == ["jax-in-reader-thread"], \
        format_findings(fs, label="concurrency")


def test_rule_ids_disjoint_from_trace_linter():
    """Both linters share one suppression namespace
    (`# lint: allow[...]`), so rule ids must never collide."""
    assert not set(RULES) & set(CONCURRENCY_RULES)


def test_real_package_is_concurrency_clean():
    """The acceptance bar: zero unsuppressed findings over the real
    package — the serving layer's lock discipline is machine-checked
    from here on (hazards get FIXED, like native.get_lib's
    build-under-lock, or annotated where intentional)."""
    fs = concurrency_lint_package(str(REPO / "lightgbm_tpu"))
    bad = [f for f in fs if not f.suppressed]
    assert not bad, "\n" + format_findings(bad, label="concurrency")
