"""Resilient serving gateway: cross-process scale-out front end.

One ``task=serve`` process is a single point of failure AND a single
point of slowness: BENCH_SERVE_r02 shows a churned tenant paying
~579 ms while residents answer in 2-5 ms, and any backend wedge or
restart is client-visible. This module is the host-side HTTP front end
that spreads traffic over N backend processes sharing ONE registry
directory as the hot-swap source of truth, and ties client latency to
the *fastest healthy* replica instead of the slowest (Dean & Barroso,
"The Tail at Scale"; PAPERS.md — the serving analog of the reference's
socket retry/re-link loops in network/linkers_socket.cpp).

Mechanisms (docs/RESILIENCE.md "Serving gateway"):

- **readiness-gated pool** — backends register by answering
  ``GET /readyz`` (liveness is ``/healthz``; readiness additionally
  means "models loaded, queue under cap, loop heartbeat fresh, not
  draining"). Only ready backends receive traffic.
- **least-outstanding-requests balancing** — each request goes to the
  ready backend with the fewest in-flight gateway requests.
- **retry with full jitter** — connect errors and 5xx on idempotent
  ops retry against another backend after
  ``resilience.backoff.full_jitter_delay`` (AWS full-jitter on the
  repo's one capped-exponential schedule).
- **hedged requests** — score/contrib attempts that outlive the
  rolling-pXX latency fire ONE duplicate attempt on a different
  backend; first answer wins, the loser's socket is closed and its
  breaker sees a cancel (not a failure). A hedge budget caps hedges to
  ``burst + budget_frac * requests`` so hedging can never melt an
  already-slow fleet.
- **per-backend circuit breaker** — closed -> open on consecutive
  failures OR window error rate, open -> half-open after a cooldown,
  half-open admits bounded probe traffic and closes on success,
  reopens on failure.
- **deadline propagation** — client ``deadline_ms`` (or the gateway
  default) becomes an absolute budget; expired work is shed with
  503 + Retry-After *before* it queues anywhere, and every backend
  attempt carries the REMAINING budget as its ``deadline_ms`` QoS.
- **graceful drain** — SIGTERM flips readiness off, sheds new work
  with 503 shutdown, finishes in-flight requests, then exits
  (tools/gateway_rolling.sh scripts the zero-downtime rolling
  restart).

Every decision point is a named fault-injection site (``gw_connect``,
``gw_backend_5xx``, ``gw_slow_backend``, ``gw_drain`` — see
resilience/faultinject.py), and ``GET /metrics`` on the gateway serves
the obs/aggregate.py pull-and-merge of its own ``lgbmtpu_gateway_*``
series plus every live backend, so the process group reads as one
fleet.

Pure host-side stdlib: importing this module must NOT import jax —
``task=gateway`` has no device work and must start instantly.
"""

from __future__ import annotations

import http.client
import json
import queue
import random
import threading
import time
import urllib.parse
import urllib.request
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import log
from ..obs import metrics as obs
from ..resilience.backoff import full_jitter_delay
from ..resilience.errors import InjectedFault
from ..resilience.faultinject import fault_point

# ops safe to retry/hedge (no observable side effect on a replay);
# score/contrib additionally hedge. load/swap/rollback FAN OUT to every
# ready backend instead — the shared registry directory makes the same
# op valid everywhere, and all replicas must agree on the active
# version. ingest is single-backend, no retry (an applied-but-unacked
# append would double rows in the spool).
IDEMPOTENT_OPS = frozenset(
    {"score", "contrib", "models", "stats", "fleet", "ping"})
HEDGED_OPS = frozenset({"score", "contrib"})
FANOUT_OPS = frozenset({"load", "swap", "rollback"})

BREAKER_STATES = ("closed", "open", "half_open")


class CircuitBreaker:
    """Per-backend breaker: closed -> open on consecutive failures or
    window error rate, open -> half-open after ``cooldown_s``,
    half-open admits ``half_open_max`` concurrent probes and closes on
    a probe success, reopens on a probe failure.

    Pure state machine on an injectable clock (``now``) — tier-1 tests
    drive it with a fake clock, no sleeps. Thread-safe; the
    ``on_transition(old, new)`` callback fires OUTSIDE the lock (it
    records metrics/logs and must not re-enter).
    """

    def __init__(self, *, failures: int = 5, error_rate: float = 0.5,
                 window: int = 20, cooldown_s: float = 2.0,
                 half_open_max: int = 1,
                 now: Callable[[], float] = time.monotonic,
                 on_transition: Optional[
                     Callable[[str, str], None]] = None):
        if failures < 1:
            raise ValueError(f"failures must be >= 1, got {failures}")
        self.failures = int(failures)
        self.error_rate = float(error_rate)
        self.window = int(window)
        self.cooldown_s = float(cooldown_s)
        self.half_open_max = int(half_open_max)
        self._now = now
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._results: deque = deque(maxlen=max(self.window, 1))
        self._opened_at = 0.0
        self._probes = 0

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            fire = self._age()
            st = self._state
        self._notify(fire)
        return st

    def _age(self) -> Optional[Tuple[str, str]]:
        # caller holds the lock; open ages into half_open lazily, so a
        # fake-clock test needs no background timer
        if (self._state == "open"
                and self._now() - self._opened_at >= self.cooldown_s):
            self._state = "half_open"
            self._probes = 0  # lint: allow[unlocked-write] — every caller holds _lock
            return ("open", "half_open")
        return None

    def _set(self, new: str) -> Optional[Tuple[str, str]]:
        old = self._state
        if old == new:
            return None
        self._state = new
        return (old, new)

    def _notify(self, fire: Optional[Tuple[str, str]]) -> None:
        if fire is not None and self._on_transition is not None:
            try:
                self._on_transition(*fire)
            except Exception as e:  # noqa: BLE001 — observer must not break the breaker
                log.warning(f"breaker transition observer failed: {e}")

    # ------------------------------------------------------------------
    def allow(self) -> bool:
        """May one request be sent through this breaker now?  A True
        answer in half-open claims a probe slot — the caller MUST
        follow with exactly one record_success / record_failure /
        record_cancel."""
        with self._lock:
            fire = self._age()
            st = self._state
            if st == "closed":
                ok = True
            elif st == "open":
                ok = False
            else:  # half_open: bounded probe admission
                ok = self._probes < self.half_open_max
                if ok:
                    self._probes += 1
        self._notify(fire)
        return ok

    def record_success(self) -> None:
        fire = None
        with self._lock:
            if self._state == "half_open":
                # probe succeeded: the backend is back
                self._probes = max(self._probes - 1, 0)
                fire = self._set("closed")
            self._consecutive = 0
            self._results.append(0)
        self._notify(fire)

    def record_failure(self) -> None:
        fire = None
        with self._lock:
            if self._state == "half_open":
                # probe failed: straight back to open, restart cooldown
                self._probes = max(self._probes - 1, 0)
                self._opened_at = self._now()
                fire = self._set("open")
            elif self._state == "closed":
                self._consecutive += 1
                self._results.append(1)
                trip = self._consecutive >= self.failures
                if not trip and len(self._results) >= self.window:
                    rate = sum(self._results) / len(self._results)
                    trip = rate >= self.error_rate
                if trip:
                    self._opened_at = self._now()
                    fire = self._set("open")
        self._notify(fire)

    def record_cancel(self) -> None:
        """A hedged loser was cancelled mid-flight: releases a probe
        slot but is NEITHER a success nor a failure — a cancel says
        nothing about backend health."""
        with self._lock:
            if self._state == "half_open":
                self._probes = max(self._probes - 1, 0)


class RollingLatency:
    """Fixed-window latency ring with a quantile read — feeds the hedge
    trigger delay. Thread-safe, tiny."""

    def __init__(self, window: int = 256):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(window), 1))

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._ring.append(float(seconds))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            vals = sorted(self._ring)
        if not vals:
            return None
        idx = min(int(q * len(vals)), len(vals) - 1)
        return vals[idx]


class HedgePolicy:
    """When and whether to fire a duplicate attempt.

    The trigger delay is the rolling ``quantile`` of observed attempt
    latencies (``default_delay_s`` until the ring warms up, never below
    ``min_delay_s``). The budget caps total hedges at
    ``burst + budget_frac * requests`` — the Dean & Barroso discipline
    that hedging may add only a few percent extra load. Pure state
    machine; fake-clock-free by construction (it never reads a clock).
    """

    def __init__(self, *, quantile: float = 0.95,
                 budget_frac: float = 0.05, min_delay_s: float = 0.001,
                 default_delay_s: float = 0.05, window: int = 256,
                 burst: int = 8):
        self.quantile = float(quantile)
        self.budget_frac = float(budget_frac)
        self.min_delay_s = float(min_delay_s)
        self.default_delay_s = float(default_delay_s)
        self.burst = int(burst)
        self.latency = RollingLatency(window)
        self._lock = threading.Lock()
        self._requests = 0
        self._hedges = 0

    def observe(self, seconds: float) -> None:
        self.latency.observe(seconds)

    def delay_s(self) -> float:
        q = self.latency.quantile(self.quantile)
        if q is None:
            q = self.default_delay_s
        return max(q, self.min_delay_s)

    def note_request(self) -> None:
        with self._lock:
            self._requests += 1

    def try_hedge(self) -> bool:
        """Claim budget for one hedge; False when spent (the caller
        must then wait out the slow primary instead of hedging)."""
        with self._lock:
            if self.budget_frac <= 0.0:
                return False
            cap = self.burst + self.budget_frac * self._requests
            if self._hedges + 1 > cap:
                return False
            self._hedges += 1
            return True

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {"requests": self._requests, "hedges": self._hedges}


class Backend:
    """One backend slot. All mutable fields are owned by
    ``BackendPool._lock`` — the pool is the only writer."""

    __slots__ = ("url", "name", "breaker", "outstanding", "alive",
                 "ready", "detail")

    def __init__(self, url: str, breaker: CircuitBreaker):
        self.url = url.rstrip("/")
        self.name = urllib.parse.urlsplit(self.url).netloc or self.url
        self.breaker = breaker
        self.outstanding = 0
        self.alive = False
        self.ready = False
        self.detail = ""


class BackendPool:
    """Readiness-gated backend set with least-outstanding acquire."""

    def __init__(self, urls: Sequence[str],
                 breaker_factory: Callable[[str], CircuitBreaker]):
        if not urls:
            raise ValueError("gateway needs at least one backend url")
        self._lock = threading.Lock()
        self.backends: List[Backend] = [
            Backend(u, breaker_factory(u)) for u in urls
        ]
        seen = set()
        for b in self.backends:
            if b.url in seen:
                raise ValueError(f"duplicate backend url {b.url!r}")
            seen.add(b.url)

    # ------------------------------------------------------------------
    def acquire(self, exclude: Sequence[Backend] = ()
                ) -> Optional[Backend]:
        """Least-outstanding ready backend whose breaker admits the
        request, or None. Breaker admission runs OUTSIDE the pool lock
        (each breaker has its own lock; no nested acquisition)."""
        with self._lock:
            ranked = sorted(
                (b for b in self.backends
                 if b.ready and b not in exclude),
                key=lambda b: b.outstanding,
            )
        for b in ranked:
            if b.breaker.allow():
                with self._lock:
                    b.outstanding += 1
                return b
        return None

    def release(self, backend: Backend) -> None:
        with self._lock:
            backend.outstanding = max(backend.outstanding - 1, 0)

    def set_health(self, backend: Backend, alive: bool, ready: bool,
                   detail: str = "") -> None:
        with self._lock:
            backend.alive = bool(alive)
            backend.ready = bool(ready)
            backend.detail = detail

    def counts(self) -> Tuple[int, int]:
        with self._lock:
            alive = sum(1 for b in self.backends if b.alive)
            ready = sum(1 for b in self.backends if b.ready)
        return alive, ready

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            rows = [
                {"url": b.url, "alive": b.alive, "ready": b.ready,
                 "outstanding": b.outstanding, "detail": b.detail}
                for b in self.backends
            ]
        for row, b in zip(rows, self.backends):
            row["breaker"] = b.breaker.state
        return rows


class _Attempt:
    """One in-flight backend attempt. Plain flags, written by one
    thread and read by the coordinator — cancellation is best-effort
    (closing the socket unblocks the read; a cancel that races the
    response just means the result is ignored)."""

    __slots__ = ("backend", "hedge", "conn", "cancelled", "done")

    def __init__(self, backend: Backend, hedge: bool):
        self.backend = backend
        self.hedge = hedge
        self.conn: Optional[http.client.HTTPConnection] = None
        self.cancelled = False
        self.done = False


class Gateway:
    """The balancing/retry/hedge/drain coordinator. Transport-neutral:
    ``handle(op, payload) -> (status, response)`` is the whole request
    path; ``gateway_http`` wraps it in the stdlib HTTP front end."""

    def __init__(self, backend_urls: Sequence[str], *,
                 retries: int = 2, backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 1.0,
                 hedge_quantile: float = 0.95,
                 hedge_budget: float = 0.05,
                 hedge_min_delay_s: float = 0.001,
                 hedge_default_delay_s: float = 0.05,
                 breaker_failures: int = 5,
                 breaker_error_rate: float = 0.5,
                 breaker_window: int = 20,
                 breaker_cooldown_s: float = 2.0,
                 default_deadline_ms: float = 0.0,
                 health_interval_s: float = 1.0,
                 probe_timeout_s: float = 5.0,
                 attempt_timeout_s: float = 30.0,
                 rng: Optional[random.Random] = None):
        self.retries = int(retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.default_deadline_ms = float(default_deadline_ms)
        self.health_interval_s = float(health_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.hedge = HedgePolicy(
            quantile=hedge_quantile, budget_frac=hedge_budget,
            min_delay_s=hedge_min_delay_s,
            default_delay_s=hedge_default_delay_s)
        self._rng = rng if rng is not None else random.Random()

        def _make_breaker(url: str) -> CircuitBreaker:
            name = urllib.parse.urlsplit(url.rstrip("/")).netloc or url
            return CircuitBreaker(
                failures=breaker_failures, error_rate=breaker_error_rate,
                window=breaker_window, cooldown_s=breaker_cooldown_s,
                on_transition=lambda old, new, n=name:
                    self._on_breaker(n, old, new))

        self.pool = BackendPool(backend_urls, _make_breaker)
        self._draining = threading.Event()
        self._stop = threading.Event()
        self._idle = threading.Condition()  # guards _inflight
        self._inflight = 0
        self._health_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------ health loop
    def _on_breaker(self, backend: str, old: str, new: str) -> None:
        log.warning(f"gateway breaker {backend}: {old} -> {new}")
        obs.record_gateway_breaker(backend, new)

    def _probe_backend(self, b: Backend) -> None:
        """One readiness probe: 200 /readyz = ready, live HTTP error =
        alive-not-ready, transport failure = dead. Plain urllib (NOT
        the fault-pointed attempt transport — a chaos plan aimed at
        request attempts must not corrupt health verdicts)."""
        alive = ready = False
        detail = ""
        try:
            with urllib.request.urlopen(
                    b.url + "/readyz", timeout=self.probe_timeout_s) as r:
                alive = True
                ready = 200 <= r.status < 300
        except urllib.error.HTTPError as e:
            alive = True  # a typed HTTP answer means the process is up
            detail = f"readyz {e.code}"
        except Exception as e:  # noqa: BLE001 — any transport failure = dead
            detail = f"{type(e).__name__}: {e}"
        self.pool.set_health(b, alive, ready, detail)

    def check_now(self) -> Tuple[int, int]:
        """Probe every backend once; returns (alive, ready) counts."""
        for b in self.pool.backends:
            self._probe_backend(b)
        alive, ready = self.pool.counts()
        obs.record_gateway_pool(alive, ready, len(self.pool.backends))
        return alive, ready

    def start(self, wait_ready_s: float = 0.0) -> None:
        """Initial probe sweep (optionally waiting for >=1 ready
        backend) then the periodic health loop."""
        deadline = time.monotonic() + float(wait_ready_s)
        while True:
            _, ready = self.check_now()
            if ready > 0 or time.monotonic() >= deadline:
                break
            if self._stop.wait(min(self.health_interval_s, 0.2)):
                break
        self._health_thread = threading.Thread(
            target=self._health_loop, name="gateway-health", daemon=True)
        self._health_thread.start()

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            self.check_now()

    def stop(self) -> None:
        self._stop.set()
        t = self._health_thread
        if t is not None:
            t.join(timeout=5.0)

    # ------------------------------------------------------------ drain
    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> None:
        """Stop accepting work (readyz goes 503, data ops shed)."""
        self._draining.set()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """begin_drain + wait for in-flight requests to finish. True
        when the gateway went idle inside the timeout."""
        self.begin_drain()
        fault_point("gw_drain")
        deadline = time.monotonic() + float(timeout_s)
        with self._idle:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(min(left, 0.25))
        return True

    def inflight(self) -> int:
        with self._idle:
            return self._inflight

    # ---------------------------------------------------------- request
    def handle(self, op: str,
               payload: Optional[Dict[str, Any]] = None
               ) -> Tuple[int, Dict[str, Any]]:
        """One client request -> (http status, response dict)."""
        payload = dict(payload or {})
        op = str(op or payload.get("op") or "score")
        payload.pop("op", None)
        t0 = time.monotonic()
        if self._draining.is_set():
            obs.record_gateway_request(op, "drain",
                                       time.monotonic() - t0)
            return 503, {"ok": False, "op": op,
                         "error": "gateway draining",
                         "error_kind": "shutdown", "retry_after_s": 1.0}
        with self._idle:
            self._inflight += 1
        try:
            status, resp, outcome = self._route(op, payload)
        finally:
            with self._idle:
                self._inflight -= 1
                if self._inflight == 0:
                    self._idle.notify_all()
        obs.record_gateway_request(op, outcome, time.monotonic() - t0)
        return status, resp

    def _route(self, op: str, payload: Dict[str, Any]
               ) -> Tuple[int, Dict[str, Any], str]:
        dl_ms = payload.get("deadline_ms")
        if dl_ms is None and self.default_deadline_ms > 0:
            dl_ms = self.default_deadline_ms
        deadline = (time.monotonic() + float(dl_ms) / 1000.0
                    if dl_ms else None)
        if op in FANOUT_OPS:
            return self._fanout(op, payload, deadline)
        return self._single(op, payload, deadline)

    @staticmethod
    def _remaining(deadline: Optional[float]) -> Optional[float]:
        return None if deadline is None else deadline - time.monotonic()

    @staticmethod
    def _shed(op: str) -> Tuple[int, Dict[str, Any], str]:
        # deadline budget exhausted before any backend work: shed with
        # 503 + Retry-After instead of queueing doomed work (ISSUE 17)
        return 503, {"ok": False, "op": op,
                     "error": "deadline budget exhausted at gateway",
                     "error_kind": "shed", "retry_after_s": 1.0}, "shed"

    @staticmethod
    def _unavailable(op: str) -> Tuple[int, Dict[str, Any], str]:
        return 503, {"ok": False, "op": op,
                     "error": "no ready backend admits traffic",
                     "error_kind": "overloaded",
                     "retry_after_s": 1.0}, "unavailable"

    # ----------------------------------------------------------- fanout
    def _fanout(self, op: str, payload: Dict[str, Any],
                deadline: Optional[float]
                ) -> Tuple[int, Dict[str, Any], str]:
        """Control ops (load/swap/rollback) broadcast to every ALIVE
        backend — the shared registry directory makes the op valid
        everywhere and all replicas must agree on the active version.
        Alive (not ready) is deliberate: a fresh backend is not ready
        BECAUSE it has no models, and the bootstrap ``load`` is how it
        becomes ready. No automatic retry (rollback is not
        replay-safe); the caller re-issues on partial failure."""
        targets = [b for b in self.pool.snapshot() if b["alive"]]
        backends = {b.url: b for b in self.pool.backends}
        if not targets:
            return self._unavailable(op)
        results: Dict[str, Any] = {}
        all_ok = True
        for row in targets:
            b = backends[row["url"]]
            rem = self._remaining(deadline)
            if rem is not None and rem <= 0:
                results[b.name] = {"ok": False, "error": "deadline",
                                   "error_kind": "shed"}
                all_ok = False
                continue
            att = _Attempt(b, hedge=False)
            try:
                status, resp = self._http_call(att, op, dict(payload))
            except Exception as e:  # noqa: BLE001 — report per-backend, never die
                b.breaker.record_failure()
                obs.record_gateway_attempt(b.name, "error")
                results[b.name] = {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                    "error_kind": "unreachable",
                }
                all_ok = False
                continue
            if status >= 500:
                b.breaker.record_failure()
                obs.record_gateway_attempt(b.name, "5xx")
            else:
                b.breaker.record_success()
                obs.record_gateway_attempt(b.name, "ok")
            results[b.name] = resp
            all_ok = all_ok and bool(resp.get("ok"))
        resp = {"ok": all_ok, "op": op, "fanout": len(targets),
                "results": results}
        return ((200, resp, "ok") if all_ok
                else (502, resp, "fanout_partial"))

    # ----------------------------------------------------- single + hedge
    def _single(self, op: str, payload: Dict[str, Any],
                deadline: Optional[float]
                ) -> Tuple[int, Dict[str, Any], str]:
        retriable = op in IDEMPOTENT_OPS
        hedgeable = op in HEDGED_OPS
        last_backend: Optional[Backend] = None
        attempt = 0
        while True:
            attempt += 1
            rem = self._remaining(deadline)
            if rem is not None and rem <= 0:
                return self._shed(op)
            exclude = (last_backend,) if last_backend is not None else ()
            backend = self.pool.acquire(exclude)
            if backend is None and exclude:
                # only the just-failed backend is available: use it
                backend = self.pool.acquire(())
            if backend is None:
                if not retriable or attempt > self.retries:
                    return self._unavailable(op)
                self._sleep_backoff(attempt, deadline)
                continue
            kind, status, resp = self._attempt_hedged(
                backend, op, payload, deadline, hedgeable)
            if kind == "ok":
                return int(status), resp, "ok"
            if kind == "deadline":
                return 504, {"ok": False, "op": op,
                             "error": "deadline expired in flight",
                             "error_kind": "deadline"}, "deadline"
            # backend failure (transport error or 5xx)
            last_backend = backend
            if not retriable or attempt > self.retries:
                if status is not None:
                    return int(status), resp, "failed"
                return 502, resp, "failed"
            self._sleep_backoff(attempt, deadline)

    def _sleep_backoff(self, attempt: int,
                       deadline: Optional[float]) -> None:
        obs.record_gateway_retry()
        d = full_jitter_delay(attempt, self.backoff_base_s,
                              self.backoff_cap_s, rand=self._rng.random)
        rem = self._remaining(deadline)
        if rem is not None:
            d = min(d, max(rem, 0.0))
        if d > 0:
            time.sleep(d)

    def _attempt_hedged(self, primary: Backend, op: str,
                        payload: Dict[str, Any],
                        deadline: Optional[float], hedgeable: bool):
        """Run one (possibly hedged) attempt round: primary now, one
        duplicate on a different backend if the primary outlives the
        rolling-pXX hedge delay and the budget allows. First answer
        wins; the loser's socket is closed and its breaker sees a
        cancel. Returns ("ok", status, resp) | ("fail", status, resp)
        | ("error", None, resp) | ("deadline", None, None)."""
        self.hedge.note_request()
        q: "queue.Queue" = queue.Queue()
        atts: List[_Attempt] = []
        self._spawn(primary, op, payload, deadline, q, atts, hedge=False)
        hedge_tried = False
        while True:
            rem = self._remaining(deadline)
            if rem is not None and rem <= 0:
                self._cancel(atts)
                return ("deadline", None, None)
            if hedgeable and not hedge_tried:
                wait = self.hedge.delay_s()
                if rem is not None:
                    wait = min(wait, rem)
            else:
                wait = rem if rem is not None else self.attempt_timeout_s
            try:
                att, kind, status, resp = q.get(timeout=max(wait, 0.001))
            except queue.Empty:
                if hedgeable and not hedge_tried:
                    hedge_tried = True
                    self._fire_hedge(op, payload, deadline, q, atts)
                continue
            pending = [a for a in atts if not a.done]
            if kind == "ok":
                self._cancel([a for a in atts if a is not att])
                if att.hedge:
                    obs.record_gateway_hedge("won")
                return ("ok", status, resp)
            if pending:
                continue  # the other racer may still win
            if kind == "cancelled":
                # only reachable when every attempt was cancelled with
                # no winner — treat as a transport failure
                kind, resp = "error", {
                    "ok": False, "op": op,
                    "error": "attempt cancelled",
                    "error_kind": "unreachable"}
            return (kind, status, resp)

    def _fire_hedge(self, op: str, payload: Dict[str, Any],
                    deadline: Optional[float], q: "queue.Queue",
                    atts: List[_Attempt]) -> None:
        second = self.pool.acquire(tuple(a.backend for a in atts))
        if second is None:
            obs.record_gateway_hedge("no_backend")
            return
        if not self.hedge.try_hedge():
            self.pool.release(second)
            obs.record_gateway_hedge("denied_budget")
            return
        obs.record_gateway_hedge("fired")
        self._spawn(second, op, payload, deadline, q, atts, hedge=True)

    def _spawn(self, backend: Backend, op: str, payload: Dict[str, Any],
               deadline: Optional[float], q: "queue.Queue",
               atts: List[_Attempt], hedge: bool) -> _Attempt:
        att = _Attempt(backend, hedge)
        atts.append(att)
        body = dict(payload)
        rem = self._remaining(deadline)
        if rem is not None:
            # deadline propagation: the backend sees what is LEFT of
            # the client budget, not the original figure
            body["deadline_ms"] = max(int(rem * 1000.0), 1)
        threading.Thread(
            target=self._run_attempt, args=(att, op, body, q),
            name=f"gw-attempt-{backend.name}", daemon=True,
        ).start()
        return att

    def _run_attempt(self, att: _Attempt, op: str,
                     body: Dict[str, Any], q: "queue.Queue") -> None:
        b = att.backend
        t0 = time.monotonic()
        try:
            status, resp = self._http_call(att, op, body)
        except BaseException as e:  # noqa: BLE001 — report, never kill the worker
            att.done = True
            self.pool.release(b)
            if att.cancelled:
                b.breaker.record_cancel()
                obs.record_gateway_attempt(b.name, "cancelled")
                q.put((att, "cancelled", None, None))
            else:
                b.breaker.record_failure()
                obs.record_gateway_attempt(b.name, "error")
                q.put((att, "error", None, {
                    "ok": False, "op": op,
                    "error": f"{type(e).__name__}: {e}",
                    "error_kind": "unreachable"}))
            return
        att.done = True
        self.pool.release(b)
        if status >= 500:
            b.breaker.record_failure()
            obs.record_gateway_attempt(b.name, "5xx")
            q.put((att, "fail", status, resp))
        else:
            b.breaker.record_success()
            self.hedge.observe(time.monotonic() - t0)
            obs.record_gateway_attempt(b.name, "ok")
            q.put((att, "ok", status, resp))

    def _http_call(self, att: _Attempt, op: str,
                   body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        """One POST /v1/<op> to the attempt's backend. The three
        request-path fault sites live here: ``gw_connect`` (before the
        socket opens), ``gw_slow_backend`` (a delay clause stalls the
        response read), ``gw_backend_5xx`` (a raise clause turns the
        answer into a backend failure)."""
        b = att.backend
        parsed = urllib.parse.urlsplit(b.url)
        fault_point("gw_connect")
        conn = http.client.HTTPConnection(
            parsed.hostname, parsed.port or 80,
            timeout=self.attempt_timeout_s)
        att.conn = conn
        try:
            if att.cancelled:
                raise InjectedFault("attempt cancelled before send")
            data = json.dumps(body).encode()
            conn.request("POST", "/v1/" + op, body=data,
                         headers={"Content-Type": "application/json"})
            fault_point("gw_slow_backend")
            r = conn.getresponse()
            raw = r.read()
            status = int(r.status)
        finally:
            conn.close()
        fault_point("gw_backend_5xx")
        try:
            resp = json.loads(raw.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            resp = {"ok": status < 400,
                    "raw": raw[:200].decode(errors="replace")}
        return status, resp

    @staticmethod
    def _cancel(atts: Sequence[_Attempt]) -> None:
        for a in atts:
            if a.done:
                continue
            a.cancelled = True
            conn = a.conn
            if conn is not None:
                try:
                    conn.close()  # unblocks the loser's response read
                except Exception:  # noqa: BLE001 — cancel is best-effort
                    pass

    # ------------------------------------------------------- status/obs
    def status(self) -> Dict[str, Any]:
        alive, ready = self.pool.counts()
        return {
            "ok": ready > 0 and not self._draining.is_set(),
            "draining": self._draining.is_set(),
            "alive": alive,
            "ready": ready,
            "inflight": self.inflight(),
            "hedge": self.hedge.counters(),
            "backends": self.pool.snapshot(),
        }

    def merged_metrics(self) -> Dict[str, Any]:
        """Own registry + a pull from every live backend, folded by
        obs/aggregate.merge — the whole process group as one fleet."""
        from ..obs import aggregate

        snaps = [aggregate.snapshot_dict(process=0)]
        rows = self.pool.snapshot()
        for i, row in enumerate(rows):
            if not row["alive"]:
                continue
            try:
                snaps.append(aggregate.pull_snapshot(
                    row["url"], timeout=self.probe_timeout_s,
                    process=i + 1, retries=0))
            except Exception as e:  # noqa: BLE001 — a dead backend must not kill the scrape
                log.warning(
                    f"gateway metrics pull {row['url']} failed: {e}")
        return aggregate.merge(snaps)

    def merged_metrics_text(self) -> str:
        from ..obs.aggregate import render_merged

        return render_merged(self.merged_metrics())


# ------------------------------------------------------------ transport
def gateway_http(gateway: Gateway, port: int, host: str = "127.0.0.1",
                 block: bool = True, max_body_mb: float = 64.0,
                 socket_timeout_s: float = 30.0):
    """HTTP front end over ``Gateway.handle`` — same shape as
    serving.server.serve_http (port=0 = ephemeral; block=False returns
    the bound httpd for the caller's own thread). Routes:

    - ``POST /v1/<op>`` — proxied/balanced protocol ops;
    - ``GET /healthz`` — gateway liveness (always 200 while up);
    - ``GET /readyz`` — 200 only when >=1 backend is ready and the
      gateway is not draining;
    - ``GET /v1/status`` — pool/breaker/hedge introspection;
    - ``GET /metrics`` — MERGED fleet exposition (gateway + every live
      backend via obs/aggregate).
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    max_body = int(max_body_mb * 1024 * 1024)

    class Handler(BaseHTTPRequestHandler):
        # hardened transport: a stalled/dead peer times the socket out
        # instead of pinning a handler thread forever
        timeout = socket_timeout_s

        def _reply(self, code: int, resp: Dict[str, Any]) -> None:
            body = json.dumps(resp).encode()
            self.send_response(int(code))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if code in (429, 503) and resp.get("retry_after_s"):
                self.send_header(
                    "Retry-After",
                    str(max(int(resp["retry_after_s"]), 1)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path in ("/healthz", "/health"):
                self._reply(200, {"ok": True, "role": "gateway"})
            elif self.path == "/readyz":
                st = gateway.status()
                self._reply(200 if st["ok"] else 503, st)
            elif self.path == "/v1/status":
                self._reply(200, gateway.status())
            elif self.path == "/metrics":
                try:
                    body = gateway.merged_metrics_text().encode()
                except Exception as e:  # noqa: BLE001 — scrape must answer
                    self._reply(500, {"ok": False,
                                      "error": f"{type(e).__name__}: {e}"})
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path in ("/v1/models", "/v1/stats", "/v1/fleet"):
                op = self.path[len("/v1/"):]
                status, resp = gateway.handle(op, {})
                self._reply(status, resp)
            else:
                self._reply(404, {"ok": False, "error": "not found"})

        def do_POST(self):  # noqa: N802 — http.server API
            try:
                n = int(self.headers.get("Content-Length", 0))
            except ValueError:
                self._reply(400, {"ok": False,
                                  "error": "bad Content-Length"})
                return
            if n > max_body:
                self._reply(413, {"ok": False,
                                  "error": f"body over {max_body} bytes"})
                return
            try:
                raw = self.rfile.read(n)
            except (OSError, TimeoutError) as e:
                # stalled client: socket timeout fired mid-body
                self._reply(408, {"ok": False,
                                  "error": f"body read: {e}"})
                return
            try:
                req = json.loads(raw or b"{}")
            except json.JSONDecodeError as e:
                self._reply(400, {"ok": False,
                                  "error": f"bad json: {e}"})
                return
            if not self.path.startswith("/v1/"):
                self._reply(404, {"ok": False, "error": "not found"})
                return
            op = self.path[len("/v1/"):] or str(req.get("op", "score"))
            if op == "quit":
                self._reply(400, {"ok": False,
                                  "error": "quit is not proxied"})
                return
            status, resp = gateway.handle(op, req)
            self._reply(status, resp)

        def log_message(self, fmt, *args):  # route through package log
            log.debug(f"gateway http: {fmt % args}")

    httpd = ThreadingHTTPServer((host, port), Handler)
    # non-daemon handlers: server_close joins them, so the SIGTERM
    # drain finishes in-flight responses (see serve_http)
    httpd.daemon_threads = False
    log.info(
        f"gateway on http://{host}:{httpd.server_address[1]}/v1 over "
        f"{len(gateway.pool.backends)} backends")
    if not block:
        return httpd
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return httpd
