"""Plotting surface: plot_importance / plot_split_value_histogram /
plot_metric / plot_tree / create_tree_digraph (reference
python-package/lightgbm/plotting.py; tests modeled on
tests/python_package_test/test_plotting.py)."""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def trained():
    rs = np.random.RandomState(7)
    X = rs.randn(400, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rs.randn(400) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    evals = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "metric": ["auc", "binary_logloss"], "verbosity": -1},
        ds,
        num_boost_round=12,
        valid_sets=[ds],
        valid_names=["train"],
        callbacks=[lgb.record_evaluation(evals)],
    )
    return bst, evals, X, y


def test_plot_importance(trained):
    bst, _, _, _ = trained
    ax = lgb.plot_importance(bst)
    assert ax.get_title() == "Feature importance"
    assert len(ax.patches) >= 1
    ax2 = lgb.plot_importance(bst, importance_type="gain",
                              max_num_features=3, title="t", xlabel="x",
                              ylabel="y", grid=False)
    assert len(ax2.patches) <= 3
    assert ax2.get_title() == "t"


def test_plot_importance_sklearn(trained):
    _, _, X, y = trained
    clf = lgb.LGBMClassifier(n_estimators=5, num_leaves=7, verbosity=-1)
    clf.fit(X, y)
    ax = lgb.plot_importance(clf)  # importance_type='auto' -> estimator's
    assert len(ax.patches) >= 1


def test_plot_split_value_histogram(trained):
    bst, _, _, _ = trained
    imp = bst.feature_importance("split")
    feat = int(np.argmax(imp))
    ax = lgb.plot_split_value_histogram(bst, feat)
    assert "index" in ax.get_title()
    name = bst.feature_name()[feat]
    ax2 = lgb.plot_split_value_histogram(bst, name, bins=5)
    assert "name" in ax2.get_title()
    unused = int(np.argmin(imp))
    if imp[unused] == 0:
        with pytest.raises(ValueError):
            lgb.plot_split_value_histogram(bst, unused)


def test_get_split_value_histogram(trained):
    bst, _, _, _ = trained
    feat = int(np.argmax(bst.feature_importance("split")))
    hist, edges = bst.get_split_value_histogram(feat)
    assert hist.sum() >= 1
    assert len(edges) == len(hist) + 1
    df = bst.get_split_value_histogram(feat, bins=3, xgboost_style=True)
    assert list(df.columns) == ["SplitValue", "Count"]
    assert (df["Count"] > 0).all()


def test_plot_metric(trained):
    bst, evals, X, y = trained
    ax = lgb.plot_metric(evals)
    assert ax.get_xlabel() == "Iterations"
    ax2 = lgb.plot_metric(evals, metric="auc", dataset_names=["train"])
    assert ax2.get_ylabel() == "auc"
    with pytest.raises(TypeError):
        lgb.plot_metric(bst)
    clf = lgb.LGBMClassifier(n_estimators=4, num_leaves=7, verbosity=-1)
    clf.fit(X, y, eval_set=[(X, y)])
    ax3 = lgb.plot_metric(clf)
    assert ax3 is not None


def test_create_tree_digraph(trained):
    bst, _, X, _ = trained
    g = lgb.create_tree_digraph(
        bst, tree_index=1,
        show_info=["split_gain", "internal_count", "leaf_count",
                   "data_percentage"],
    )
    src = g.source
    assert "digraph" in src
    assert "<=" in src
    assert "leaf" in src
    assert "count:" in src
    with pytest.raises(IndexError):
        lgb.create_tree_digraph(bst, tree_index=10_000)
    # example_case highlights the decision path
    g2 = lgb.create_tree_digraph(bst, example_case=X[:1])
    assert "blue" in g2.source


def test_dot_standin_matches_graphviz_surface():
    from lightgbm_tpu.plotting import _DotStandin

    d = _DotStandin("T", graph_attr={"rankdir": "LR"})
    d.node("n0", "root <= 1.5", shape="rectangle")
    d.node("n1", "leaf 0: 0.3")
    d.edge("n0", "n1", label="yes")
    src = d.source
    assert src.startswith("digraph T {") and src.endswith("}")
    assert 'n0 -> n1 [label="yes"]' in src


def test_plot_tree(trained):
    bst, _, X, _ = trained
    ax = lgb.plot_tree(bst, tree_index=0,
                       show_info=["internal_count", "leaf_count"])
    assert len(ax.texts) >= 3  # at least root + two children drawn
    ax2 = lgb.plot_tree(bst, orientation="vertical", example_case=X[:1])
    assert ax2 is not None
    with pytest.raises(IndexError):
        lgb.plot_tree(bst, tree_index=9_999)
