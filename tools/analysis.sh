#!/usr/bin/env bash
# CI wiring for the trace-safety static analysis suite
# (docs/STATIC_ANALYSIS.md). Strict mode: any unsuppressed lint
# violation or failed jaxpr contract exits nonzero. The python entry
# point forces jax onto a cpu 8-device mesh itself, so this is safe on
# hosts whose ambient JAX_PLATFORMS points at real accelerators.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m lightgbm_tpu.analysis --strict "$@"
