"""Subprocess worker for the SIGKILL crash/resume chaos test: run the
CLI train task in a real process so a ``round:N:kill`` fault plan
(LGBMTPU_FAULT_PLAN) can SIGKILL it mid-boosting — no atexit, no
finally, no flush — and a second invocation with ``resume=auto`` must
reproduce the uninterrupted model bit for bit."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    # same persistent compile cache as tests/conftest.py — the crash,
    # resume, and clean runs would otherwise each pay the cold compile
    from lightgbm_tpu._cache import machine_tag

    jax.config.update(
        "jax_compilation_cache_dir",
        f"/root/.cache/jax_comp_cache_{machine_tag()}",
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from lightgbm_tpu.cli import main as cli_main

    return cli_main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
