"""Ground-truth device timing: run K chained iterations + one device_get;
slope over K = true per-iteration device cost (readback constant cancels)."""
import os, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N, F, B, L = 1_048_576, 28, 256, 255
from lightgbm_tpu.learner.histogram import build_gh8
from lightgbm_tpu.learner.pallas_hist import hist_tpu
from lightgbm_tpu.learner.split import best_split
from lightgbm_tpu.learner import make_split_params
from lightgbm_tpu.config import Config

rs = np.random.RandomState(0)
bins = jnp.asarray(rs.randint(0, B-1, size=(F, N)).astype(np.int32))
gh8 = jnp.asarray(rs.randn(8, N).astype(np.float32))
nan_bin = jnp.full(F, -1, jnp.int32); num_bins = jnp.full(F, B, jnp.int32)
mono = jnp.zeros(F, jnp.int32); is_cat = jnp.zeros(F, bool); fm = jnp.ones(F, bool)
params = make_split_params(Config({"num_leaves": L}))

def slope(name, make_fn, k_small=1, k_big=11):
    f_s, f_b = make_fn(k_small), make_fn(k_big)
    for f in (f_s, f_b):
        _ = jax.device_get(f())  # compile + warm
    ts = []
    for f, k in ((f_s, k_small), (f_b, k_big), (f_s, k_small), (f_b, k_big)):
        t0 = time.time(); _ = jax.device_get(f()); ts.append(time.time() - t0)
    per = ((ts[1] + ts[3]) - (ts[0] + ts[2])) / (2 * (k_big - k_small))
    base = (ts[0] + ts[2]) / 2
    print(f"{name}: {per*1000:.3f} ms/iter (1-iter wall {base*1000:.0f} ms)")

# pallas hist full-N
def mk_hist(k):
    @jax.jit
    def f():
        def body(i, acc):
            h = hist_tpu(bins, gh8 * (1.0 + acc[0, 0] * 1e-30), B)
            return acc + h[:, 0, :1]
        return lax.fori_loop(0, k, body, jnp.zeros((8, 1), jnp.float32))
    return f
slope("pallas hist full-N", mk_hist)

# elementwise (8,N)
def mk_ew(k):
    @jax.jit
    def f():
        def body(i, a): return a * 1.0000001 + 1.0
        return lax.fori_loop(0, k, body, gh8)[0, :4]
    return f
slope("elementwise (8,N)", mk_ew)

# best_split
h0 = jax.device_get(jax.jit(lambda: hist_tpu(bins, gh8, B))())
h0j = jnp.asarray(h0[:3].reshape(3, F, B))
def mk_bs(k):
    @jax.jit
    def f():
        def body(i, acc):
            r = best_split(h0j * (1.0 + acc * 1e-30), jnp.float32(0.), jnp.float32(N), jnp.float32(N),
                           num_bins, nan_bin, mono, is_cat, params, fm)
            return acc + r.gain
        return lax.fori_loop(0, k, body, jnp.float32(0.))
    return f
slope("best_split", mk_bs, 1, 21)

# loop floor trivial
def mk_triv(k):
    @jax.jit
    def f():
        def body(i, a): return a * 1.0000001 + 1.0
        return lax.fori_loop(0, k, body, jnp.float32(0.0))
    return f
slope("loop floor (scalar arith)", mk_triv, 10, 1010)

# gather full-N lane axis
perm = jnp.asarray(rs.permutation(N).astype(np.int32))
def mk_gat(k):
    @jax.jit
    def f():
        def body(i, p): return jnp.take(p, perm)
        return lax.fori_loop(0, k, body, perm)[:4]
    return f
slope("gather 1-D (N,)", mk_gat, 1, 5)

# dynamic_update_slice (8, N) at dynamic offset (partition write pattern)
def mk_dus(k):
    @jax.jit
    def f():
        def body(i, a):
            chunk = lax.dynamic_slice(a, (0, i * 128), (8, 65536)) * 1.0000001
            return lax.dynamic_update_slice(a, chunk, (0, i * 128))
        return lax.fori_loop(0, k, body, gh8)[0, :4]
    return f
slope("dynslice+update (8,64K)", mk_dus, 1, 11)
