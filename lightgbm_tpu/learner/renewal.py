"""Device-resident percentile leaf renewal for l1/huber/quantile/mape.

The reference refits each leaf's output to a weighted percentile of the
residuals of its in-bag rows (RegressionL1loss::RenewTreeOutput,
regression_objective.hpp:251; gbdt.cpp:418 RenewTreeOutput before
shrinkage).

TPU formulation (round 5 — VERDICT r4 item 8): the previous version
sorted (leaf, residual) with `lax.sort`, which costs 0.3-2 s at 1M rows
on this backend (plus minutes of per-shape compile) and knocked the
renewal objectives off the fast path. This one never sorts: it runs a
fixed number of HISTOGRAM REFINEMENT passes — each pass bins every
row's residual into 256 fixed bins of its leaf's current bracket
(per-row bracket parameters via the one-hot `take_cols` contraction),
accumulates per-leaf weighted bin histograms with the slot-packed MXU
kernel (`hist_nat_slots`, the same machinery as split finding), and
narrows each leaf's bracket to the bin where the cumulative weight
crosses alpha * total. Four passes resolve the crossing element to
2^-32 of the residual range — below f32 resolution — matching the
sorted version's "first element whose cumulative weight reaches the
target" convention (the reference's interpolation between adjacent
order statistics, regression_objective.hpp:18, is not replicated by
either formulation; documented deviation). Cost: ~10 ms/tree at 1M
rows vs 0.3-2 s for the sort.
"""

from __future__ import annotations


def renew_leaf_values(leaf_value, row_leaf, resid, w, alpha,
                      num_leaves: int, passes: int = 4,
                      num_bins: int = 256):
    """Weighted-percentile residual per leaf (traced, sort-free).

    leaf_value: (L,) current outputs (kept where a leaf has no rows)
    row_leaf:   (N,) int32 leaf id per row; negative = not in any leaf
    resid:      (N,) f32 residuals (label - score)
    w:          (N,) f32 weights; 0 excludes a row (padding / out-of-bag)
    alpha:      percentile in [0, 1] (0.5 = median)
    """
    import jax.numpy as jnp

    from .histogram import build_gh8, hist_nat_slots, seg_sum, take_cols

    L = num_leaves
    B = num_bins
    incl = (w > 0) & (row_leaf >= 0)
    key = jnp.where(incl, row_leaf, L).astype(jnp.int32)
    wv = jnp.where(incl, w, 0.0).astype(jnp.float32)
    rv = resid.astype(jnp.float32)

    # global residual range seeds every leaf's bracket
    rmin = jnp.min(jnp.where(incl, rv, jnp.inf))
    rmax = jnp.max(jnp.where(incl, rv, -jnp.inf))
    rmin = jnp.where(jnp.isfinite(rmin), rmin, 0.0)
    rmax = jnp.where(jnp.isfinite(rmax), rmax, 0.0)
    span = jnp.maximum(rmax - rmin, 1e-20)
    lo = jnp.full(L, rmin, jnp.float32)
    # exclusive upper edge: the max element must land in bin B-1
    hi = jnp.full(L, rmax + span * 1e-6, jnp.float32)

    totals = seg_sum(wv[None, :], key, L)[0]  # (L,)
    target = alpha * totals
    base = jnp.zeros(L, jnp.float32)  # cumulative weight below lo

    for _ in range(passes):
        # late passes can shrink a bracket to hi == lo (below ulp of
        # lo); clamping keeps inv_w finite — a degenerate bracket then
        # just stops moving instead of poisoning the pass with inf*0
        inv_w = B / jnp.maximum(hi - lo, 1e-30)
        tab = jnp.stack([lo, inv_w])  # (2, L)
        pr = take_cols(tab, key)  # (2, N); rows outside any leaf -> 0
        binp = jnp.floor((rv - pr[0]) * pr[1]).astype(jnp.int32)
        # rows outside the current bracket are already accounted for in
        # `base` (below) or above the target (beyond) — drop them
        inb = (binp >= 0) & (binp < B) & incl
        slot = jnp.where(inb, key, L).astype(jnp.int32)
        bins = jnp.where(inb, binp, 0)[None, :]  # (1, N)
        gh8 = build_gh8(wv, jnp.zeros_like(wv),
                        inb.astype(jnp.float32))
        h = hist_nat_slots(bins, gh8, slot, L, B)[:, 0, 0]  # (L, B) w-sums
        cum = jnp.cumsum(h, axis=1)
        cb = base[:, None] + cum
        bstar = jnp.clip(
            jnp.sum(cb < target[:, None], axis=1), 0, B - 1
        ).astype(jnp.int32)
        below = jnp.where(
            bstar > 0,
            jnp.take_along_axis(
                cum, jnp.maximum(bstar - 1, 0)[:, None], axis=1
            )[:, 0],
            0.0,
        )
        width = (hi - lo) * (1.0 / B)
        base = base + below
        lo = lo + bstar.astype(jnp.float32) * width
        hi = lo + width

    val = (lo + hi) * 0.5
    return jnp.where(totals > 0, val, leaf_value)
