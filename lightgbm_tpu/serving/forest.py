"""Tensorized forest predictor: the trained model as device tables.

The host predictors (tree.py vectorized walk, native/ fp_predict) walk
pointer-shaped trees row by row; on TPU that shape is hostile — the
win comes from giving every (row, tree) lane the same dense program.
This module lifts the flat per-tree arrays (the same layout
``native.PackedModel`` packs for the C++ walker: feature index,
threshold, decision type, children, leaf values, categorical bitsets,
linear-leaf coefficients) into rectangular ``(T, max_nodes)`` /
``(T, max_leaves)`` tables and traverses **all rows x all trees in
lockstep** under one ``jit``:

- per level, every lane's node parameters come from ONE packed-table
  gather (``take_cols`` — the MXU one-hot contraction training's
  validation traversal already uses, histogram.py:380);
- each lane's split-feature value is a ``take_along_axis`` row gather;
- the loop is a ``lax.while_loop`` bounded by the forest's max depth
  (every lane advances one level per pass, like traverse_tree_bins);
- per-class accumulation is a single ``(N, T) @ (T, K)`` one-hot
  matmul, with a ``(T,)`` weight vector implementing
  ``start_iteration`` / ``num_iteration`` truncation WITHOUT a
  retrace (the weights are an argument, not a static).

Decision semantics mirror ``tree.py`` ``Tree.go_left`` bit for bit
(missing types None/Zero/NaN, default direction, categorical bitsets,
linear-leaf NaN fallback); the parity tests in
tests/test_serving.py assert agreement with the native walker across
model families. Tables ride the jit boundary as ARGUMENTS, so two
models with the same (T, M, L) shapes share one executable — hot-swap
in the registry does not recompile.

All tables are f32/int32: the scoring jaxpr carries the same
no-f64 / no-host-callback contracts as the training entry points
(analysis/jaxpr_audit.py ``serving_forest`` entry).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

# reference include/LightGBM/bin.h kZeroThreshold (tree.h Decision) —
# the zero-as-missing band, shared with the host walk via binning
from ..binning import K_ZERO_THRESHOLD as _K_ZERO


def pack_forest_tables(models, num_class: int) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Host packing: list of Tree -> rectangular numpy tables + static
    metadata. The numpy side of the split so the jit side is pure
    device math (and so the audit can trace it from shapes alone)."""
    T = len(models)
    K = max(int(num_class), 1)
    n_nodes = [max(t.num_leaves - 1, 0) for t in models]
    M = max(n_nodes + [1])
    L = max([t.num_leaves for t in models] + [1])
    depth = max([t.max_depth() for t in models] + [1])

    feature = np.zeros((T, M), np.int32)
    threshold = np.zeros((T, M), np.float32)
    miss_type = np.zeros((T, M), np.int32)
    default_left = np.zeros((T, M), bool)
    is_cat = np.zeros((T, M), bool)
    # padding nodes route straight to leaf 0 so a runaway lane terminates
    left = np.full((T, M), -1, np.int32)
    right = np.full((T, M), -1, np.int32)
    leaf_value = np.zeros((T, L), np.float32)
    cat_lo = np.zeros((T, M), np.int32)
    cat_nw = np.zeros((T, M), np.int32)
    catw_parts: List[np.ndarray] = []
    wbase = 0
    any_cat = False
    any_linear = any(t.is_linear for t in models)
    Ck = 1
    if any_linear:
        Ck = max(
            (len(f) for t in models if t.is_linear for f in t.leaf_features),
            default=1,
        ) or 1
    leaf_const = np.zeros((T, L), np.float32)
    leaf_nf = np.zeros((T, L), np.int32)
    leaf_feat = np.zeros((T, L, Ck), np.int32)
    leaf_coeff = np.zeros((T, L, Ck), np.float32)
    init_node = np.zeros(T, np.int32)
    max_feature = -1

    for ti, t in enumerate(models):
        n = n_nodes[ti]
        if n == 0:
            init_node[ti] = -1  # stump: lane starts AT leaf 0 (~0 == -1)
        else:
            feature[ti, :n] = t.split_feature[:n]
            # directed f64->f32 cast: never round a threshold UP across
            # its f64 value, or an exactly-f32 feature value in
            # (thr, f32(thr)] would flip from right to left vs the f64
            # host walker — a whole-leaf divergence, not 1e-5 noise
            thr64 = np.asarray(t.threshold[:n], np.float64)
            t32 = thr64.astype(np.float32)
            up = t32.astype(np.float64) > thr64
            t32[up] = np.nextafter(t32[up], np.float32(-np.inf))
            threshold[ti, :n] = t32
            dt = np.asarray(t.decision_type[:n], np.int64)
            miss_type[ti, :n] = (dt >> 2) & 3
            default_left[ti, :n] = (dt & 2) != 0
            is_cat[ti, :n] = (dt & 1) != 0
            left[ti, :n] = t.left_child[:n]
            right[ti, :n] = t.right_child[:n]
            max_feature = max(max_feature, int(np.max(t.split_feature[:n])))
            cat_k = np.flatnonzero(is_cat[ti, :n])
            if len(cat_k):
                any_cat = True
                cb = np.asarray(t.cat_boundaries, np.int64)
                words = np.asarray(t.cat_threshold, np.uint32)
                catw_parts.append(words)
                ci = np.asarray(t.threshold, np.float64)[cat_k].astype(np.int64)
                cat_lo[ti, cat_k] = wbase + cb[ci]
                cat_nw[ti, cat_k] = cb[ci + 1] - cb[ci]
                wbase += len(words)
        lv = np.asarray(t.leaf_value, np.float32)
        leaf_value[ti, : len(lv)] = lv
        leaf_const[ti, : len(lv)] = lv  # non-linear: lin path == leaf_value
        if t.is_linear:
            lc = np.asarray(t.leaf_const, np.float32)
            leaf_const[ti, : len(lc)] = lc
            for li, feats in enumerate(t.leaf_features):
                k = len(feats)
                leaf_nf[ti, li] = k
                if k:
                    leaf_feat[ti, li, :k] = feats
                    leaf_coeff[ti, li, :k] = np.asarray(
                        t.leaf_coeff[li], np.float32
                    )
                    max_feature = max(max_feature, max(feats))

    catw = (
        np.concatenate(catw_parts).astype(np.uint32)
        if catw_parts else np.zeros(1, np.uint32)
    )
    # per-node packed parameter table for the single take_cols gather:
    # every field is exact in f32 (ints < 2^24, thresholds already f32)
    pack = np.stack([
        feature.reshape(-1).astype(np.float32),       # 0
        threshold.reshape(-1),                        # 1
        miss_type.reshape(-1).astype(np.float32),     # 2
        default_left.reshape(-1).astype(np.float32),  # 3
        is_cat.reshape(-1).astype(np.float32),        # 4
        left.reshape(-1).astype(np.float32),          # 5
        right.reshape(-1).astype(np.float32),         # 6
        cat_lo.reshape(-1).astype(np.float32),        # 7
        cat_nw.reshape(-1).astype(np.float32),        # 8
    ])
    class_onehot = np.zeros((T, K), np.float32)
    class_onehot[np.arange(T), np.arange(T) % K] = 1.0

    tables = {
        "pack": pack,                         # (9, T*M) f32
        "catw": catw.view(np.int32),          # (W,) int32 bit-patterns
        "leaf_value": leaf_value,             # (T, L) f32
        "leaf_const": leaf_const,             # (T, L) f32
        "leaf_nf": leaf_nf,                   # (T, L) int32
        "leaf_feat": leaf_feat,               # (T, L, Ck) int32
        "leaf_coeff": leaf_coeff,             # (T, L, Ck) f32
        "init_node": init_node,               # (T,) int32
        "class_onehot": class_onehot,         # (T, K) f32
    }
    meta = {
        "num_trees": T, "num_class": K, "max_nodes": M, "max_leaves": L,
        "max_depth": int(depth), "has_cat": bool(any_cat),
        "linear": bool(any_linear), "max_feature": int(max_feature),
    }
    return tables, meta


def forest_apply(tables, X, tree_w, *, has_cat: bool = True,
                 linear: bool = False, max_depth: int = 0):
    """Device traversal: (N, F) rows x all T trees -> per-class raw
    scores (N, K) and per-tree leaf indices (N, T).

    `tables` is the pack_forest_tables pytree (jnp arrays); `tree_w`
    is the (T,) f32 per-tree weight implementing iteration truncation.
    Pure jax — jit/shard_map wrapping happens in TensorForest.
    """
    import jax.numpy as jnp
    from jax import lax

    from ..learner.histogram import take_cols

    T, L = tables["leaf_value"].shape
    M = tables["pack"].shape[1] // T
    N = X.shape[0]
    tpos = (jnp.arange(T, dtype=jnp.int32) * M)[None, :]  # (1, T)
    cur0 = jnp.broadcast_to(tables["init_node"][None, :], (N, T))
    # every lane descends one edge per pass, so the forest's max depth
    # (pack_forest_tables meta) bounds the loop tighter than the node
    # count; <=0 falls back to M
    bound = M if max_depth <= 0 else min(int(max_depth), M)

    def cond(s):
        it, cur = s
        return (it < bound) & jnp.any(cur >= 0)

    def body(s):
        it, cur = s
        node = jnp.maximum(cur, 0)  # leaf lanes compute a dead decision
        flat = (tpos + node).reshape(-1)  # (N*T,)
        v = take_cols(tables["pack"], flat)  # (9, N*T)
        v = v.reshape(9, N, T)
        f = v[0].astype(jnp.int32)
        thr = v[1]
        mt = v[2].astype(jnp.int32)
        dl = v[3] > 0.5
        x = jnp.take_along_axis(X, f, axis=1)  # (N, T)
        isna = jnp.isnan(x)
        # missing != NaN: NaN behaves as 0.0 (tree.h Decision)
        xv = jnp.where(isna & (mt != 2), 0.0, x)
        miss = jnp.where(
            mt == 2, isna, (mt == 1) & (jnp.abs(xv) <= _K_ZERO)
        )
        go_left = jnp.where(miss, dl, xv <= thr)
        if has_cat:
            nw = v[8].astype(jnp.int32)
            iv = jnp.nan_to_num(x, nan=-1.0, posinf=-1.0, neginf=-1.0)
            iv = iv.astype(jnp.int32)
            ok = (~isna) & (iv >= 0) & (iv < 32 * nw)
            widx = v[7].astype(jnp.int32) + jnp.maximum(iv, 0) // 32
            W = tables["catw"].shape[0]
            w = tables["catw"][jnp.clip(widx, 0, W - 1)]
            bit = lax.shift_right_logical(w, jnp.maximum(iv, 0) % 32) & 1
            go_left = jnp.where(v[4] > 0.5, ok & (bit == 1), go_left)
        child = jnp.where(go_left, v[5], v[6]).astype(jnp.int32)
        cur = jnp.where(cur >= 0, child, cur)
        return it + 1, cur

    _, cur = lax.while_loop(cond, body, (jnp.int32(0), cur0))
    leaf = jnp.where(cur < 0, ~cur, 0)  # (N, T)
    lflat = (jnp.arange(T, dtype=jnp.int32) * L)[None, :] + leaf
    val = tables["leaf_value"].reshape(-1)[lflat]  # (N, T)
    if linear:
        Ck = tables["leaf_feat"].shape[2]
        const = tables["leaf_const"].reshape(-1)[lflat]
        nf = tables["leaf_nf"].reshape(-1)[lflat]
        fidx = tables["leaf_feat"].reshape(-1, Ck)[lflat]    # (N, T, Ck)
        co = tables["leaf_coeff"].reshape(-1, Ck)[lflat]
        xg = X[jnp.arange(N, dtype=jnp.int32)[:, None, None], fidx]
        kmask = jnp.arange(Ck, dtype=jnp.int32)[None, None, :] < nf[..., None]
        contrib = jnp.sum(jnp.where(kmask, co * xg, 0.0), axis=-1)
        anynan = jnp.any(kmask & jnp.isnan(xg), axis=-1)
        # linear semantics (tree.cpp:137-153): const + coeffs . x,
        # rows with NaN in a used feature fall back to leaf_value
        val = jnp.where(anynan, val, const + contrib)
    score = (val * tree_w[None, :]) @ tables["class_onehot"]  # (N, K)
    return score, leaf


_APPLY_JIT = None


def _forest_apply_jit():
    """Shared module-level jit of forest_apply (lazy so importing the
    package never initializes a backend): every non-mesh TensorForest
    scores through this ONE callable, so same-shaped tables — model
    hot-swaps, registry versions — reuse one executable per bucket."""
    global _APPLY_JIT
    if _APPLY_JIT is None:
        import jax

        _APPLY_JIT = jax.jit(
            forest_apply, static_argnames=("has_cat", "linear", "max_depth")
        )
    return _APPLY_JIT


class TensorForest:
    """A trained forest compiled to device tables + a scoring callable.

    ``mesh=None`` (or a 1-device mesh) uses the shared module-level jit
    — model hot-swaps with identical table shapes reuse the executable.
    With a multi-device mesh the row axis is sharded over
    ``axis_name`` through the same ``shard_map_compat`` seam training
    uses (tables replicated); callers must pad rows to a multiple of
    the mesh size (``BucketDispatcher`` aligns its ladder for this).
    """

    def __init__(self, models, num_class: int = 1,
                 average_output: bool = False, mesh=None,
                 axis_name: str = "data"):
        import jax
        import jax.numpy as jnp

        if not models:
            raise ValueError("TensorForest needs at least one tree")
        tables, meta = pack_forest_tables(models, num_class)
        self.meta = meta
        # while_loop bound: true max depth rounded UP to a power of two
        # — max_depth is a static jit arg, so quantizing keeps the
        # hot-swap executable-reuse property for same-shaped models
        # with nearby depths (any bound >= true depth is correct)
        d = max(int(meta["max_depth"]), 1)
        self._depth_bound = 1 << (d - 1).bit_length()
        self.num_class = meta["num_class"]
        self.num_trees = meta["num_trees"]
        self.average_output = bool(average_output)
        self.max_feature = meta["max_feature"]
        self.mesh = None
        self.axis_name = axis_name
        n_dev = 1
        if mesh is not None and int(np.prod(mesh.devices.shape)) > 1:
            self.mesh = mesh
            n_dev = int(np.prod(mesh.devices.shape))
        self.num_devices = n_dev
        if self.mesh is None:
            self.tables = {k: jnp.asarray(v) for k, v in tables.items()}
            self._fn = _forest_apply_jit()
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.data_parallel import shard_map_compat

            rep = NamedSharding(self.mesh, P())
            self.tables = {
                k: jax.device_put(jnp.asarray(v), rep)
                for k, v in tables.items()
            }
            has_cat, linear = meta["has_cat"], meta["linear"]
            max_depth = self._depth_bound

            def fn(tables, X, tree_w):
                return forest_apply(tables, X, tree_w,
                                    has_cat=has_cat, linear=linear,
                                    max_depth=max_depth)

            tspec = jax.tree.map(lambda _: P(), self.tables)
            self._sharded = jax.jit(shard_map_compat(
                fn, mesh=self.mesh,
                in_specs=(tspec, P(axis_name, None), P()),
                out_specs=(P(axis_name, None), P(axis_name, None)),
                check_vma=False,
            ))
            self._fn = None

    # ------------------------------------------------------------------
    @classmethod
    def from_booster(cls, booster, mesh=None) -> "TensorForest":
        g = booster._gbdt
        return cls(
            list(g.models), g.num_class,
            average_output=bool(getattr(g, "average_output", False)),
            mesh=mesh,
        )

    @property
    def jit_entry(self):
        """The jitted scoring callable — hand this to retrace_guard
        entry_points to assert the compile-per-bucket contract."""
        return self._sharded if self.mesh is not None else self._fn

    def _tree_weights(self, start_iteration: int,
                      num_iteration: int) -> Tuple[np.ndarray, int, int]:
        K = self.num_class
        n_iters = self.num_trees // K
        end = n_iters if num_iteration <= 0 else min(
            n_iters, start_iteration + num_iteration
        )
        tw = np.zeros(self.num_trees, np.float32)
        tw[start_iteration * K: end * K] = 1.0
        return tw, start_iteration, end

    def _check_width(self, X: np.ndarray) -> None:
        if X.shape[1] <= self.max_feature:
            # keep the host walk's error semantics (tree.py predict_leaf
            # raises IndexError on narrow input)
            raise IndexError(
                f"input has {X.shape[1]} features but the model "
                f"references feature {self.max_feature}"
            )

    def apply(self, X, tree_w):
        """Raw device call on an already-padded f32 row block."""
        import jax.numpy as jnp

        tw = jnp.asarray(tree_w, jnp.float32)
        if self.mesh is not None:
            return self._sharded(self.tables, X, tw)
        return self._fn(
            self.tables, X, tw,
            has_cat=self.meta["has_cat"], linear=self.meta["linear"],
            max_depth=self._depth_bound,
        )

    def predict_raw(self, X: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1) -> np.ndarray:
        """(K, N) raw margins, matching GBDT.predict_raw layout."""
        import jax.numpy as jnp

        X = np.asarray(X, np.float32)
        self._check_width(X)
        tw, start, end = self._tree_weights(start_iteration, num_iteration)
        N = X.shape[0]
        pad = (-N) % max(self.num_devices, 1)
        if pad:
            X = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)])
        score, _ = self.apply(jnp.asarray(X), tw)
        out = np.asarray(score)[:N].T.astype(np.float64)  # (K, N)
        if self.average_output and end > start:
            out /= end - start
        return out

    def predict_leaf(self, X: np.ndarray, start_iteration: int = 0,
                     num_iteration: int = -1) -> np.ndarray:
        """(N, used_trees) leaf indices (Booster.predict pred_leaf)."""
        import jax.numpy as jnp

        X = np.asarray(X, np.float32)
        self._check_width(X)
        tw, start, end = self._tree_weights(start_iteration, num_iteration)
        N = X.shape[0]
        pad = (-N) % max(self.num_devices, 1)
        if pad:
            X = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)])
        _, leaf = self.apply(jnp.asarray(X), tw)
        K = self.num_class
        return np.asarray(leaf)[:N, start * K: end * K].astype(np.int64)
