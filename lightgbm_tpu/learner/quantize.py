"""Quantized-gradient training (use_quantized_grad).

Reference: src/treelearner/gradient_discretizer.cpp:22 — per-iteration
gradient/hessian discretization to num_grad_quant_bins levels with
stochastic rounding (truncation toward zero of x/scale +- u), scales
g_scale = max|g| / (bins/2), h_scale = max|h| / bins, and optional
true-gradient leaf renewal (quant_train_renew_leaf,
RenewIntGradTreeOutput).

TPU formulation: the quantized levels flow through the standard
histogram kernel as DEQUANTIZED f32 values (level * scale) — the
accumulated sums equal the reference's int-histogram sums times the
scales up to f32 addition rounding, so split decisions match the
quantized semantics without new kernels. The deferred perf half
(int8 one-hot matmuls on the MXU + int16 psum payloads, the analog of
bin.h:63-81 wire reducers) slots in behind this same interface.

Randomness is keyed on (seed, iteration) — the reference's
pre-generated random value table with a rotating start offset
(gradient_discretizer.cpp:25-41) serves the same purpose.
"""

from __future__ import annotations

from typing import Optional, Tuple

# internal discretization levels per hist_dtype policy: int16 channels
# carry 256 levels (g in [-128, 128], h in [0, 256] — bf16-exact ints
# and far inside the int16 accumulation range), int8 carries 127 so the
# slot kernel can run s8 x s8 -> s32 on the MXU (histogram.int8_oh_shift
# bounds the SWAR scale against s32 cell overflow)
HIST_DTYPE_LEVELS = {"int16": 256, "int8": 127}


def resolve_hist_dtype(
    requested: str,
    use_quantized_grad: bool,
    num_grad_quant_bins: int,
    use_rounds: bool,
    on_tpu: bool = True,
) -> Tuple[str, int, Optional[str]]:
    """Resolve the tpu_hist_dtype policy to the histogram channel
    layout one tree actually accumulates with.

    Returns (resolved, internal_levels, warning):

    - resolved: "bf16x2" | "int16" | "int8" — the channel layout;
    - internal_levels: discretization levels for the INTERNAL int-packed
      default path (0 when bf16x2 or when use_quantized_grad supplies
      its own levels);
    - warning: a message when an explicit request had to fall back.

    Under use_quantized_grad the quantized-API levels govern: the
    resolved name just reports what that path does (int8/int16 slot
    channels on the rounds grower, dequantized bf16x2 otherwise).
    Off the rounds growth path the int-packed channels do not exist
    (the sequential growers accumulate f32 hi/lo), so explicit
    int16/int8 requests fall back to bf16x2 with a warning.

    "auto" flips to int16 only when use_rounds AND on_tpu: off-chip
    rounds runs (tests, CPU fallbacks) keep the bit-exact bf16x2
    layout — same contract as tpu_growth_mode=auto, which keeps CPU
    runs reference-exact. An EXPLICIT int16/int8 request on the rounds
    path is honored on any backend (that is how the parity suites
    exercise the packed channels off-chip).
    """
    if use_quantized_grad:
        if use_rounds and num_grad_quant_bins <= 127:
            return "int8", 0, None
        if use_rounds and num_grad_quant_bins <= 256:
            return "int16", 0, None
        return "bf16x2", 0, None
    req = "bf16x2" if requested == "float32" else requested
    if req == "auto":
        req = "int16" if (use_rounds and on_tpu) else "bf16x2"
    if req in HIST_DTYPE_LEVELS and not use_rounds:
        return "bf16x2", 0, (
            f"tpu_hist_dtype={requested} needs the rounds growth path "
            "(tpu_growth_mode=rounds, or auto on TPU hardware); "
            "falling back to bf16x2 channels"
        )
    return req, HIST_DTYPE_LEVELS.get(req, 0), None


def discretize_gradients_int(
    grad,
    hess,
    key,
    num_bins: int,
    stochastic: bool,
):
    """(grad, hess) -> ((grad_q, hess_q) INTEGER levels, (2,) scales).

    Matches DiscretizeGradients: grad levels in [-bins/2, bins/2],
    hess levels in [0, bins]; stochastic rounding truncates toward zero
    after adding signed uniform noise, plain rounding truncates after
    adding 0.5. The integer levels feed the rounds grower's 3-channel
    exact-int histogram path (spec.quant)."""
    import jax
    import jax.numpy as jnp

    g_scale = jnp.maximum(jnp.max(jnp.abs(grad)), 1e-30) / (num_bins // 2)
    h_scale = jnp.maximum(jnp.max(jnp.abs(hess)), 1e-30) / num_bins
    if stochastic:
        kg, kh = jax.random.split(key)
        ug = jax.random.uniform(kg, grad.shape)
        uh = jax.random.uniform(kh, hess.shape)
    else:
        ug = 0.5
        uh = 0.5
    gq = jnp.trunc(grad / g_scale + jnp.sign(grad) * ug)
    hq = jnp.trunc(hess / h_scale + uh)  # hessians are non-negative
    return gq, hq, jnp.stack([g_scale, h_scale])


def discretize_gradients(
    grad,
    hess,
    key,
    num_bins: int,
    stochastic: bool,
):
    """(grad, hess) -> dequantized (grad_q, hess_q) at num_bins levels
    (level * scale), for the growers that consume plain f32 channels."""
    gq, hq, scale = discretize_gradients_int(
        grad, hess, key, num_bins, stochastic
    )
    return gq * scale[0], hq * scale[1]


def renew_leaf_with_true_gradients(leaf_value, row_leaf, grad, hess, mask,
                                   params, num_leaves: int):
    """quant_train_renew_leaf: recompute leaf outputs from the TRUE
    (unquantized) per-leaf gradient/hessian sums
    (gradient_discretizer RenewIntGradTreeOutput)."""
    import jax.numpy as jnp

    from .histogram import seg_sum
    from .split import leaf_output

    L = num_leaves
    idx = jnp.where((row_leaf >= 0) & (mask > 0), row_leaf, L)
    sums = seg_sum(jnp.stack([grad * mask, hess * mask]), idx, L)
    sum_g, sum_h = sums[0], sums[1]
    renewed = leaf_output(sum_g, sum_h, params)
    return jnp.where(sum_h > 0, renewed, leaf_value)
