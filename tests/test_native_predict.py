"""Native batch predictor (native/fastparse.cpp fp_predict) parity.

The threaded C++ walker must be BIT-identical to the numpy level walk
(tree.py predict_leaf) — categoricals, NaN routing, missing types,
stumps — and preserve the host path's error semantics for malformed
input. Mirrors the reference's expectation that all predictors agree
(src/io/tree.h Tree::Predict is the single source of truth there)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import native


def _fit(X, y, **params):
    ds = lgb.Dataset(
        X, label=y, free_raw_data=False,
        categorical_feature=params.pop("categorical_feature", None),
    )
    p = dict(objective="binary", num_leaves=31, verbosity=-1,
             min_data_in_leaf=5)
    p.update(params)
    return lgb.train(p, ds, num_boost_round=12)


@pytest.mark.skipif(native.get_lib() is None, reason="no native toolchain")
def test_native_predict_bit_identical_with_cat_and_nan():
    rs = np.random.RandomState(0)
    Xt = rs.randn(3000, 10)
    Xt[:, 4] = rs.randint(0, 20, 3000)
    Xt[rs.rand(3000) < 0.05, 2] = np.nan
    y = (np.nan_to_num(Xt[:, 0]) + (Xt[:, 4] % 3 == 0) > 0).astype(float)
    bst = _fit(Xt, y, categorical_feature=[4])

    X = rs.randn(20_000, 10)
    X[:, 4] = rs.randint(-3, 30, 20_000)  # incl. unseen/negative cats
    X[rs.rand(20_000) < 0.05, 2] = np.nan
    p_native = bst.predict(X)  # batch > 256 rows -> native path
    real = native.predict_packed
    native.predict_packed = lambda *a, **k: None
    try:
        p_host = bst.predict(X)
    finally:
        native.predict_packed = real
    np.testing.assert_array_equal(p_native, p_host)


@pytest.mark.skipif(native.get_lib() is None, reason="no native toolchain")
def test_native_predict_narrow_input_raises():
    rs = np.random.RandomState(1)
    X = rs.randn(1000, 8)
    y = (X[:, 0] > 0).astype(float)
    bst = _fit(X, y)
    with pytest.raises(IndexError):
        bst.predict(rs.randn(2000, 3))


@pytest.mark.skipif(native.get_lib() is None, reason="no native toolchain")
def test_native_predict_multiclass_noncontiguous():
    rs = np.random.RandomState(2)
    X = rs.randn(1500, 6)
    y = rs.randint(0, 3, 1500).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbosity": -1}, ds,
                    num_boost_round=6)
    Xf = np.asfortranarray(rs.randn(5000, 6))
    p = bst.predict(Xf)
    assert p.shape == (5000, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-6)


def test_native_binning_bit_exact_vs_python():
    """greedy_find_bin native/python parity on spiky distributions
    (both mirror reference bin.cpp:80 in double arithmetic)."""
    if native.get_lib() is None:
        pytest.skip("no native toolchain")
    from lightgbm_tpu import binning as B

    rs = np.random.RandomState(3)
    for trial in range(10):
        dv = np.unique(rs.randn(rs.randint(600, 20000)) * 50)
        cnt = rs.randint(1, 40, len(dv)).astype(np.int64)
        cnt[rs.randint(0, len(cnt), 2)] = rs.randint(5000, 500000)
        total = int(cnt.sum())
        mb = int(rs.choice([15, 63, 255]))
        mdib = int(rs.choice([1, 3, 20]))
        real = native.greedy_find_bin
        native.greedy_find_bin = lambda *a, **k: None
        try:
            py = B.greedy_find_bin(dv, cnt, mb, total, mdib)
        finally:
            native.greedy_find_bin = real
        nat = B.greedy_find_bin(dv, cnt, mb, total, mdib)
        assert len(py) == len(nat), trial
        np.testing.assert_array_equal(np.array(py), np.array(nat))
