"""Plotting: feature importance, split-value histograms, metric curves,
and tree visualization.

The user surface of the reference's ``python-package/lightgbm/plotting.py``
(plot_importance:37, plot_split_value_histogram:171, plot_metric:287,
create_tree_digraph:616, plot_tree:742) rebuilt on this package's own
model introspection (``Booster.dump_model`` / ``feature_importance``):

- ``plot_tree`` renders with pure matplotlib — no graphviz *binary*
  required (the reference's plot_tree shells out to ``dot`` and fails
  without it);
- ``create_tree_digraph`` emits DOT through the python ``graphviz``
  package when importable, else returns a minimal stand-in exposing the
  same ``.source`` / ``.save()`` surface.
"""

from __future__ import annotations

import math
from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

__all__ = [
    "plot_importance",
    "plot_split_value_histogram",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
]


# ----------------------------------------------------------------------
# helpers


def _plt():
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:  # pragma: no cover - matplotlib is baked in
        raise ImportError("matplotlib is required for plotting") from e
    return plt


def _to_booster(obj: Any):
    """Accept Booster or fitted LGBMModel; return the Booster."""
    from .basic import Booster
    from .sklearn import LGBMModel

    if isinstance(obj, LGBMModel):
        return obj.booster_
    if isinstance(obj, Booster):
        return obj
    raise TypeError(f"booster must be Booster or LGBMModel, got {type(obj)}")


def _fmt(value: float, precision: Optional[int]) -> str:
    if precision is None:
        return str(value)
    if isinstance(value, float):
        return f"{value:.{precision}g}"
    return str(value)


def _check_pair(obj: Any, name: str) -> None:
    if obj is not None and (not isinstance(obj, tuple) or len(obj) != 2):
        raise TypeError(f"{name} must be a tuple of 2 elements or None")


def _new_axes(ax, figsize, dpi):
    if ax is not None:
        return ax
    plt = _plt()
    _check_pair(figsize, "figsize")
    _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    return ax


# ----------------------------------------------------------------------
# plot_importance


def plot_importance(
    booster: Any,
    ax=None,
    height: float = 0.2,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Feature importance",
    xlabel: Optional[str] = "Feature importance",
    ylabel: Optional[str] = "Features",
    importance_type: str = "auto",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
    precision: Optional[int] = 3,
    **kwargs: Any,
):
    """Horizontal bar chart of per-feature importances
    (reference plotting.py:37). ``importance_type='auto'`` uses the
    estimator's ``importance_type`` for sklearn models and ``'split'``
    for raw Boosters."""
    from .sklearn import LGBMModel

    if importance_type == "auto":
        importance_type = (
            booster.importance_type if isinstance(booster, LGBMModel)
            else "split"
        )
    bst = _to_booster(booster)

    values = np.asarray(bst.feature_importance(importance_type))
    names = bst.feature_name()
    pairs = [
        (float(v), n) for v, n in zip(values, names)
        if not (ignore_zero and v == 0)
    ]
    if not pairs:
        raise ValueError("Booster's feature_importance is empty.")
    pairs.sort(key=lambda p: p[0])
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    vals = [p[0] for p in pairs]
    labels = [p[1] for p in pairs]

    ax = _new_axes(ax, figsize, dpi)
    ypos = np.arange(len(vals))
    ax.barh(ypos, vals, height=height, align="center", **kwargs)
    for y, v in zip(ypos, vals):
        ax.text(v + 1, y, _fmt(v, precision) if importance_type == "gain"
                else str(int(v)), va="center")
    ax.set_yticks(ypos)
    ax.set_yticklabels(labels)
    _check_pair(xlim, "xlim")
    ax.set_xlim(xlim if xlim is not None else (0, max(vals) * 1.1))
    _check_pair(ylim, "ylim")
    ax.set_ylim(ylim if ylim is not None else (-1, len(vals)))
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel.replace("@importance_type@", importance_type))
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


# ----------------------------------------------------------------------
# plot_split_value_histogram


def _iter_nodes(node: Dict[str, Any]):
    yield node
    for side in ("left_child", "right_child"):
        child = node.get(side)
        if isinstance(child, dict):
            yield from _iter_nodes(child)


def _split_values(bst, feature: Union[int, str]) -> List[float]:
    model = bst.dump_model()
    names = [f["name"] if isinstance(f, dict) else f
             for f in model.get("feature_names", [])]
    if isinstance(feature, str):
        try:
            fidx = names.index(feature)
        except ValueError:
            raise ValueError(f"unknown feature name {feature!r}")
    else:
        fidx = int(feature)
    out: List[float] = []
    for t in model["tree_info"]:
        root = t.get("tree_structure", {})
        for node in _iter_nodes(root):
            if (
                node.get("split_feature") == fidx
                and node.get("decision_type") == "<="
            ):
                out.append(float(node["threshold"]))
    return out


def plot_split_value_histogram(
    booster: Any,
    feature: Union[int, str],
    bins: Union[int, str, None] = None,
    ax=None,
    width_coef: float = 0.8,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Split value histogram for feature with "
                           "@index/name@ @feature@",
    xlabel: Optional[str] = "Feature split value",
    ylabel: Optional[str] = "Count",
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
    **kwargs: Any,
):
    """Histogram of the numeric thresholds the model chose for one
    feature across all trees (reference plotting.py:171)."""
    bst = _to_booster(booster)
    values = _split_values(bst, feature)
    if not values:
        raise ValueError(
            f"Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting"
        )
    if bins is None:
        bins = min(len(set(values)), 100) or 1
    hist, edges = np.histogram(np.asarray(values), bins=bins)
    centers = (edges[:-1] + edges[1:]) / 2
    width = width_coef * (edges[1] - edges[0]) if len(edges) > 1 else 1.0

    ax = _new_axes(ax, figsize, dpi)
    ax.bar(centers, hist, width=width, align="center", **kwargs)
    _check_pair(xlim, "xlim")
    if xlim is not None:
        ax.set_xlim(xlim)
    _check_pair(ylim, "ylim")
    ax.set_ylim(ylim if ylim is not None else (0, max(hist) * 1.1))
    if title:
        title = title.replace(
            "@index/name@", "index" if isinstance(feature, int) else "name"
        ).replace("@feature@", str(feature))
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


# ----------------------------------------------------------------------
# plot_metric


def plot_metric(
    booster: Any,
    metric: Optional[str] = None,
    dataset_names: Optional[List[str]] = None,
    ax=None,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Metric during training",
    xlabel: Optional[str] = "Iterations",
    ylabel: Optional[str] = "@metric@",
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
):
    """Plot one recorded eval metric over iterations, from a
    ``record_evaluation`` dict or a fitted sklearn estimator
    (reference plotting.py:287)."""
    from .basic import Booster
    from .sklearn import LGBMModel

    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif isinstance(booster, Booster):
        raise TypeError(
            "booster must be dict or LGBMModel; pass the dict filled by "
            "the record_evaluation() callback"
        )
    else:
        raise TypeError("booster must be dict or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")

    if dataset_names is None:
        use = list(eval_results.keys())
    else:
        use = [n for n in dataset_names if n in eval_results]
        if not use:
            raise ValueError("dataset_names has no matching recorded sets")

    first = eval_results[use[0]]
    if metric is None:
        if len(first) > 1:
            from .log import warning

            warning("More than one metric available, picking one to plot.")
        metric = next(iter(first))
    ax = _new_axes(ax, figsize, dpi)
    max_len = 0
    for name in use:
        if metric not in eval_results[name]:
            raise ValueError(f"metric {metric!r} not recorded for {name!r}")
        ys = eval_results[name][metric]
        max_len = max(max_len, len(ys))
        ax.plot(range(len(ys)), ys, label=name)
    ax.legend(loc="best")
    _check_pair(xlim, "xlim")
    ax.set_xlim(xlim if xlim is not None else (0, max_len))
    _check_pair(ylim, "ylim")
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


# ----------------------------------------------------------------------
# tree visualization


_SHOW_INFO = (
    "split_gain", "internal_value", "internal_count", "internal_weight",
    "leaf_count", "leaf_weight", "data_percentage",
)


def _node_label(
    node: Dict[str, Any],
    feature_names: List[str],
    show_info: List[str],
    precision: Optional[int],
    total_count: int,
    max_category_values: int,
) -> str:
    lines: List[str] = []
    if "split_feature" in node:
        f = node["split_feature"]
        name = (
            feature_names[f]
            if feature_names and f < len(feature_names)
            else f"Column_{f}"
        )
        if node.get("decision_type") == "==":
            cats = str(node["threshold"]).split("||")
            if len(cats) > max_category_values:
                cats = cats[:max_category_values] + ["..."]
            lines.append(f"{name} in {{{'|'.join(cats)}}}")
        else:
            lines.append(
                f"{name} <= {_fmt(float(node['threshold']), precision)}"
            )
        for key in ("split_gain", "internal_value", "internal_weight",
                    "internal_count"):
            if key in show_info and key in node:
                lines.append(f"{key.split('_')[-1]}: "
                             f"{_fmt(node[key], precision)}")
        if "data_percentage" in show_info and node.get("internal_count"):
            pct = 100.0 * node["internal_count"] / max(total_count, 1)
            lines.append(f"{_fmt(pct, precision)}% of data")
    else:
        lines.append(
            f"leaf {node.get('leaf_index', 0)}: "
            f"{_fmt(float(node.get('leaf_value', 0.0)), precision)}"
        )
        for key in ("leaf_weight", "leaf_count"):
            if key in show_info and key in node:
                lines.append(f"{key.split('_')[-1]}: "
                             f"{_fmt(node[key], precision)}")
        if "data_percentage" in show_info and node.get("leaf_count"):
            pct = 100.0 * node["leaf_count"] / max(total_count, 1)
            lines.append(f"{_fmt(pct, precision)}% of data")
    return "\n".join(lines)


def _decision_path(root: Dict[str, Any], row: np.ndarray) -> set:
    """ids(path) of nodes a single example visits (example_case)."""
    path = set()
    node = root
    while "split_feature" in node:
        path.add(id(node))
        fval = row[node["split_feature"]]
        missing = fval is None or (
            isinstance(fval, float) and math.isnan(fval)
        )
        if node.get("missing_type") == "Zero" and not missing:
            missing = fval == 0.0
        if node.get("decision_type") == "==":
            cats = str(node["threshold"]).split("||")
            left = (not missing) and str(int(fval)) in cats
        elif missing and node.get("missing_type") != "None":
            left = bool(node.get("default_left", True))
        else:
            v = 0.0 if missing else float(fval)
            left = v <= float(node["threshold"])
        node = node["left_child"] if left else node["right_child"]
    path.add(id(node))
    return path


class _DotStandin:
    """Minimal graphviz.Digraph lookalike (``.source`` / ``.save``) used
    when the python graphviz package is unavailable."""

    def __init__(self, name: str, graph_attr=None, **_kw):
        self._lines: List[str] = [f"digraph {name} {{"]
        for k, v in (graph_attr or {}).items():
            self._lines.append(f'\tgraph [{k}="{v}"]')

    def node(self, name: str, label: str = "", **attrs):
        a = "".join(
            f' {k}="{v}"' for k, v in attrs.items()
        )
        label = label.replace("\n", "\\n")
        self._lines.append(f'\t{name} [label="{label}"{a}]')

    def edge(self, a: str, b: str, label: str = "", **attrs):
        at = "".join(f' {k}="{v}"' for k, v in attrs.items())
        self._lines.append(f'\t{a} -> {b} [label="{label}"{at}]')

    @property
    def source(self) -> str:
        return "\n".join(self._lines + ["}"])

    def save(self, filename: str, directory: Optional[str] = None) -> str:
        import os

        path = os.path.join(directory or ".", filename)
        with open(path, "w") as f:
            f.write(self.source)
        return path


def create_tree_digraph(
    booster: Any,
    tree_index: int = 0,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    example_case: Optional[Any] = None,
    max_category_values: int = 10,
    **kwargs: Any,
):
    """DOT digraph of one tree (reference plotting.py:616). Returns a
    ``graphviz.Digraph`` when the package is importable, else a stand-in
    with the same ``.source``."""
    bst = _to_booster(booster)
    model = bst.dump_model()
    trees = model["tree_info"]
    if not 0 <= tree_index < len(trees):
        raise IndexError(f"tree_index {tree_index} out of range")
    root = trees[tree_index]["tree_structure"]
    feature_names = list(model.get("feature_names", []))
    show_info = [s for s in (show_info or []) if s in _SHOW_INFO]
    total_count = int(root.get("internal_count", root.get("leaf_count", 0)))

    highlighted: set = set()
    if example_case is not None:
        arr = np.asarray(example_case, dtype=object)
        if arr.ndim == 2:
            if arr.shape[0] != 1:
                raise ValueError("example_case must be one row")
            arr = arr[0]
        row = np.array(
            [np.nan if v is None else float(v) for v in arr], dtype=np.float64
        )
        highlighted = _decision_path(root, row)

    rankdir = "LR" if orientation == "horizontal" else "TB"
    try:
        from graphviz import Digraph

        graph = Digraph(name=f"Tree{tree_index}",
                        graph_attr={"rankdir": rankdir}, **kwargs)
    except ImportError:
        graph = _DotStandin(f"Tree{tree_index}",
                            graph_attr={"rankdir": rankdir}, **kwargs)

    counter = [0]

    def add(node: Dict[str, Any]) -> str:
        nid = f"n{counter[0]}"
        counter[0] += 1
        attrs = {"shape": "rectangle"}
        if id(node) in highlighted:
            attrs.update(color="blue", penwidth="3")
        graph.node(
            nid,
            _node_label(node, feature_names, show_info, precision,
                        total_count, max_category_values),
            **attrs,
        )
        if "split_feature" in node:
            missing_left = bool(node.get("default_left", True)) and \
                node.get("missing_type") != "None"
            lid = add(node["left_child"])
            rid = add(node["right_child"])
            graph.edge(nid, lid,
                       label="yes" + (" (missing)" if missing_left else ""))
            graph.edge(nid, rid,
                       label="no" + ("" if missing_left else " (missing)"))
        return nid

    add(root)
    return graph


def _layout(node: Dict[str, Any], depth: int, next_y: List[int],
            pos: Dict[int, Tuple[float, float]]) -> float:
    """leaves at consecutive y slots; parents centered over children."""
    if "split_feature" not in node:
        y = float(next_y[0])
        next_y[0] += 1
        pos[id(node)] = (float(depth), y)
        return y
    ly = _layout(node["left_child"], depth + 1, next_y, pos)
    ry = _layout(node["right_child"], depth + 1, next_y, pos)
    y = (ly + ry) / 2.0
    pos[id(node)] = (float(depth), y)
    return y


def plot_tree(
    booster: Any,
    ax=None,
    tree_index: int = 0,
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    example_case: Optional[Any] = None,
    **kwargs: Any,
):
    """Draw one tree with matplotlib (reference plotting.py:742 — but
    self-contained: the reference renders through the graphviz binary,
    this draws boxes and edges directly so it works anywhere
    matplotlib does)."""
    bst = _to_booster(booster)
    model = bst.dump_model()
    trees = model["tree_info"]
    if not 0 <= tree_index < len(trees):
        raise IndexError(f"tree_index {tree_index} out of range")
    root = trees[tree_index]["tree_structure"]
    feature_names = list(model.get("feature_names", []))
    show_info = [s for s in (show_info or []) if s in _SHOW_INFO]
    total_count = int(root.get("internal_count", root.get("leaf_count", 0)))

    highlighted: set = set()
    if example_case is not None:
        arr = np.asarray(example_case, dtype=object)
        if arr.ndim == 2:
            arr = arr[0]
        row = np.array(
            [np.nan if v is None else float(v) for v in arr], dtype=np.float64
        )
        highlighted = _decision_path(root, row)

    pos: Dict[int, Tuple[float, float]] = {}
    _layout(root, 0, [0], pos)
    horizontal = orientation == "horizontal"

    ax = _new_axes(ax, figsize, dpi)

    def draw(node: Dict[str, Any]):
        d, y = pos[id(node)]
        x, yy = (d, -y) if horizontal else (y, -d)
        is_path = id(node) in highlighted
        box = dict(
            boxstyle="round,pad=0.3",
            fc="#d8e8f8" if "split_feature" in node else "#e8f8d8",
            ec="blue" if is_path else "gray",
            lw=2.5 if is_path else 1.0,
        )
        ax.text(
            x, yy,
            _node_label(node, feature_names, show_info, precision,
                        total_count, max_category_values=10),
            ha="center", va="center", fontsize=8, bbox=box, zorder=3,
        )
        if "split_feature" in node:
            for side, lab in (("left_child", "yes"), ("right_child", "no")):
                child = node[side]
                cd, cy = pos[id(child)]
                cx, cyy = (cd, -cy) if horizontal else (cy, -cd)
                ax.plot([x, cx], [yy, cyy], "-", color="gray", lw=1,
                        zorder=1)
                ax.annotate(
                    lab, ((x + cx) / 2, (yy + cyy) / 2),
                    fontsize=7, color="gray", zorder=2,
                )
                draw(child)

    draw(root)
    ax.axis("off")
    return ax
