"""TPU-resident inference & serving subsystem.

The reference ships a dedicated fast-prediction layer
(src/boosting/gbdt_prediction.cpp, the CUDA predictor) because
inference is its own workload with its own shapes and latency budget;
this package is the TPU analog. Three pieces:

- ``forest``: a **tensorized predictor** — the trained forest compiled
  into dense ``(trees, nodes)`` device tables and traversed for all
  rows x trees with vectorized gathers under one ``jit`` (multi-chip
  row sharding through the same ``shard_map`` seam training uses);
- ``dispatch``: a **bucket-batched dispatcher** — incoming batches are
  padded to a small fixed ladder of shapes so the number of XLA
  compiles is bounded by the ladder length (retrace-guard-asserted),
  with warm-up precompilation and a thread-safe microbatch queue;
- ``registry``: a **model registry** — load / hot-swap / version
  multiple Boosters (text or JSON model format) behind one scoring
  entry point (optionally N predictor replicas per version), plus the
  ``ScoringServer`` loop ``cli.py`` exposes as ``task=serve``;
- ``fleet``: a **multi-tenant model fleet** — hundreds of registry
  models resident as stacked forest tables with LRU HBM paging,
  per-model QoS and metrics, and on-device TreeSHAP
  (``pred_contrib``) over the packed tables;
- ``gateway``: a **resilient scale-out front end** — health-checked
  least-outstanding balancing over N backend processes with retries,
  latency-triggered hedging, per-backend circuit breakers, deadline
  propagation, and zero-downtime drain (``task=gateway``,
  docs/RESILIENCE.md "Serving gateway").

See docs/SERVING.md for the architecture.
"""

from .dispatch import DEFAULT_BUCKETS, BucketDispatcher, MicroBatcher
from .fleet import ModelFleet
from .forest import TensorForest
from .gateway import (
    BackendPool,
    CircuitBreaker,
    Gateway,
    HedgePolicy,
    RollingLatency,
    gateway_http,
)
from .registry import ModelRegistry
from .server import ScoringServer, readiness, serve_http

__all__ = [
    "TensorForest",
    "BucketDispatcher",
    "MicroBatcher",
    "DEFAULT_BUCKETS",
    "ModelRegistry",
    "ModelFleet",
    "ScoringServer",
    "serve_http",
    "readiness",
    "Gateway",
    "gateway_http",
    "CircuitBreaker",
    "HedgePolicy",
    "RollingLatency",
    "BackendPool",
]
