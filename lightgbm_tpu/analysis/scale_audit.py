"""SPMD scaling-contract auditor (Pass 7): the D-ladder gate.

Every other jaxpr/cost contract is pinned at ONE mesh shape (the
forced 8-device host platform), so nothing in `--strict` could detect
a collective whose count, kind, or payload grows with device count —
exactly the failure mode that would sink pod-scale training (ROADMAP
3) and the 2D rows x features mesh (ROADMAP 5). This pass re-traces
every mesh-bearing entry in `jaxpr_audit.ENTRIES` at a device ladder
D in {1, 2, 4, 8} (sub-meshes of the forced 8-device CPU platform,
`jaxpr_audit._mesh(n)`) and proves scaling BEHAVIOR, not just
single-point budgets:

- **collective census** — the multiset of collective primitives
  (psum / reduce_scatter / all_gather / ...) must be D-invariant in
  kind and count above the entry's floor, and an all_gather may never
  appear where the entry declares none;
- **wire scaling law** — per-device collective payload bytes at each
  D are pinned EXACT (cost_audit's byte extraction) and checked
  against a declared law: `const` (payload independent of D), `1/D`
  (per-shard reduce-scatter bytes shrink exactly with the mesh),
  `elected` (flat AND strictly under the all-feature baseline wire —
  the PR 14 voting election), `bounded` (non-increasing in D);
- **eqn-count D-invariance** — the `chunk_c_invariance` pattern
  applied to mesh size: compiled program size cannot scale with the
  pod (small declared tolerance for shape-specialized simplification
  at the degenerate 1-shard rung);
- **sharding-spec verification** — a `match_partition_rules`-style
  declaration table checked against the actual shard_map
  in_names/out_names, so a per-row array silently falling back to
  full replication fails the gate instead of silently 8x-ing memory.

Pins live in `scale_budget.json` (exact, per entry per rung);
`python -m lightgbm_tpu.analysis --refresh-budgets` rewrites it and
prints an old->new diff. Tier-1 tests run the tiny D in {1, 2} ladder
in-process; `--strict` / tools/analysis.sh run the full ladder.
Traces are memoized per (entry, D) through `build_entry`, so the D=8
rung shares the trace the jaxpr/cost passes already paid for.
"""

from __future__ import annotations

import json
import re
from collections import Counter
from pathlib import Path
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

from .cost_audit import _aval_bytes, collect_wire
from .jaxpr_audit import (
    AuditResult,
    Contract,
    build_entry,
    iter_eqns,
    mesh_entry_names,
)

_BUDGET_PATH = Path(__file__).with_name("scale_budget.json")

# the full --strict ladder and the tiny tier-1 subset (the suite
# already runs ~770-860 s of its 870 s budget; D in {1, 2} catches a
# broken degenerate rung + the first real mesh while the 4/8 rungs
# ride tools/analysis.sh)
LADDER: Tuple[int, ...] = (1, 2, 4, 8)
TIER1_LADDER: Tuple[int, ...] = (1, 2)

_BUDGET_KEYS = ("census", "send_bytes", "rs_shard_bytes", "eqn_count")


class ShardRule(NamedTuple):
    """One row of a match_partition_rules-style table (SNIPPETS [3]):
    first rule whose regex fully matches a canonical array name wins;
    its expected spec must equal the rendered actual sharding."""
    label: str
    pattern: str   # fullmatch regex over "in/<i>/<dtype>[dims]" names
    expected: str  # "P(data)", "P(None, data)", ... or "replicated"


class ScaleSpec(NamedTuple):
    """Declared scaling contract for one mesh-bearing entry."""
    law: str                     # const | 1/D | elected | bounded
    floor: int = 1               # smallest D the law/census cover (rs
    #                              entries degrade to psum at D=1 by
    #                              design: use_rs needs axis_size > 1)
    allows_all_gather: bool = False
    baseline: Optional[str] = None   # elected law: entry to undercut
    eqn_tol: int = 0             # max-min eqn spread over D >= floor
    axis: str = "data"
    rules: Tuple[ShardRule, ...] = ()
    # symbol -> per-device rows; a global dim equal to rows*D renders
    # as the symbol so one rule covers every rung
    symbols: Dict[str, int] = {}


class ScaleSummary(NamedTuple):
    """Everything the contracts read off one (entry, D) trace.
    Tests fabricate these directly to drive the red paths."""
    census: Dict[str, int]       # collective prim -> count
    send_bytes: int              # per-device collective payload (sum
    #                              of collective operand bytes —
    #                              cost_audit's wire account)
    rs_shard_bytes: int          # reduce_scatter OUTPUT bytes: the
    #                              per-shard histogram slice
    eqn_count: int
    shardings: Tuple[Tuple[str, str], ...]  # (canonical name, spec)


# ------------------------------------------------------- declarations
# Shared rules for the data-parallel rounds entries: bins (F, N) and
# every per-row array ride the 'data' axis; the per-row leaf output
# must STAY sharded (a replicated row_leaf is the 8x-memory fallback
# this table exists to catch); everything else — split records, leaf
# values, scalar params — is replicated.
_ROUNDS_RULES: Tuple[ShardRule, ...] = (
    ShardRule("bins_rows_sharded", r"in/0/int32\[8,N\]", "P(None, data)"),
    ShardRule("per_row_grad_hess_mask", r"in/[5-7]/float32\[N\]", "P(data)"),
    ShardRule("row_leaf_stays_sharded", r"out/16/int32\[N\]", "P(data)"),
    ShardRule("records_and_params_replicated", r"(in|out)/.*", "replicated"),
)

# Feature-parallel flips the axes: per-feature metadata and the bin
# matrix shard over 'feature', rows are replicated BY DESIGN
# (parallel_tree_learner.h:26 — every rank holds all rows, only split
# records cross the wire), and outputs are replicated (pmean'd tree).
_FP_RULES: Tuple[ShardRule, ...] = (
    ShardRule("bins_features_sharded", r"in/0/int32\[16,512\]",
              "P(feature, None)"),
    ShardRule("per_feature_meta", r"in/[12348]/\w+\[16\]", "P(feature)"),
    ShardRule("rows_replicated_by_design",
              r"in/(5|6|7|24)/float32\[512\]", "replicated"),
    ShardRule("tree_outputs_replicated", r"(in|out)/.*", "replicated"),
)

# law notes, all measured on the 8-device host platform:
# - rs entries: send const for D >= 2 (each device ships its full
#   owned-block histogram once), reduce_scatter out exactly prop. 1/D;
#   floor 2 because use_rs needs axis_size > 1 (D=1 falls back to the
#   psum path — still pinned exactly via the budget, just outside the
#   law); eqn_tol covers XLA shape-specialized simplification wobble.
# - overflow: rs_exact_ok disables the wire at EVERY D — f32 psum
#   fallback, flat.
# - voting: elected int16 wire flat at every D and strictly under the
#   all-feature rounds_quant_rs wire (the whole point of the
#   election).
# - feature_parallel: record-only wire, non-increasing in D (a small
#   affine 1/D term from the per-rank bookkeeping).
SCALE_ENTRIES: Dict[str, ScaleSpec] = {
    "rounds_quant_rs": ScaleSpec(
        law="1/D", floor=2, allows_all_gather=True, eqn_tol=32,
        symbols={"N": 128}, rules=_ROUNDS_RULES,
    ),
    "rounds_quant_rs_int32": ScaleSpec(
        law="1/D", floor=2, allows_all_gather=True, eqn_tol=32,
        symbols={"N": 2048}, rules=_ROUNDS_RULES,
    ),
    "rounds_quant_rs_overflow": ScaleSpec(
        law="const", symbols={"N": 131072}, rules=_ROUNDS_RULES,
    ),
    "rounds_voting": ScaleSpec(
        law="elected", baseline="rounds_quant_rs",
        symbols={"N": 128}, rules=_ROUNDS_RULES,
    ),
    "feature_parallel": ScaleSpec(
        law="bounded", allows_all_gather=True, axis="feature",
        rules=_FP_RULES,
    ),
}


# --------------------------------------------------------- summarizer
def _render_spec(names: Dict[int, Tuple[str, ...]], ndim: int) -> str:
    """shard_map names dict -> "P(None, data)" style string;
    an array with NO bound axes renders as "replicated" (rank-blind:
    that is the property the rules declare)."""
    if not any(names.get(d) for d in range(ndim)):
        return "replicated"
    parts = []
    for d in range(ndim):
        ax = names.get(d, ())
        parts.append("+".join(ax) if ax else "None")
    return f"P({', '.join(parts)})"


def _canonical_dims(shape, symbols: Dict[str, int], n_devices: int) -> str:
    out = []
    for dim in shape:
        sym = next((s for s, rows in symbols.items()
                    if int(dim) == rows * n_devices), None)
        out.append(sym if sym is not None else str(int(dim)))
    return ",".join(out)


def extract_shardings(closed, spec: ScaleSpec,
                      n_devices: int) -> Tuple[Tuple[str, str], ...]:
    """(canonical name, rendered spec) for every in/out of every
    top-level shard_map eqn. Canonical names are
    "in/<i>/<dtype>[dims]" with declared symbols substituted
    (N = rows x D), so one rule table covers the whole ladder."""
    items: List[Tuple[str, str]] = []
    smaps = [e for e in closed.jaxpr.eqns
             if e.primitive.name == "shard_map"]
    for k, eqn in enumerate(smaps):
        prefix = "" if len(smaps) == 1 else f"smap{k}/"
        for kind, vs, nm in (("in", eqn.invars, eqn.params["in_names"]),
                             ("out", eqn.outvars, eqn.params["out_names"])):
            for i, (v, names) in enumerate(zip(vs, nm)):
                aval = getattr(v, "aval", None)
                if aval is None or not hasattr(aval, "shape"):
                    continue
                dims = _canonical_dims(aval.shape, spec.symbols, n_devices)
                name = f"{prefix}{kind}/{i}/{aval.dtype}[{dims}]"
                items.append((name, _render_spec(dict(names),
                                                 len(aval.shape))))
    return tuple(items)


def summarize_scale(closed, spec: ScaleSpec,
                    n_devices: int) -> ScaleSummary:
    """One (entry, D) trace -> the numbers the contracts read."""
    from .cost_audit import _COLLECTIVE_PRIMS

    census: Counter = Counter()
    rs_out = 0
    eqns = 0
    for eqn in iter_eqns(closed):
        eqns += 1
        p = eqn.primitive.name
        if p in _COLLECTIVE_PRIMS:
            census[p] += 1
        if p == "reduce_scatter":
            for v in eqn.outvars:
                nb = _aval_bytes(getattr(v, "aval", None))
                if nb is not None:
                    rs_out += nb
    return ScaleSummary(
        census=dict(census),
        send_bytes=sum(w.nbytes for w in collect_wire(closed)),
        rs_shard_bytes=rs_out,
        eqn_count=eqns,
        shardings=extract_shardings(closed, spec, n_devices),
    )


# ----------------------------------------------------------- contracts
def _fmt_census(c: Dict[str, int]) -> str:
    return "{" + ", ".join(f"{k}:{v}" for k, v in sorted(c.items())) + "}"


def _check_census(spec: ScaleSpec,
                  summaries: Dict[int, ScaleSummary]) -> List[Contract]:
    out: List[Contract] = []
    rungs = sorted(d for d in summaries if d >= spec.floor)
    censuses = {d: summaries[d].census for d in rungs}
    ref = censuses[rungs[0]]
    bad = [d for d in rungs if censuses[d] != ref]
    out.append(Contract(
        "census_D_invariant", not bad,
        (f"D>={spec.floor}: {_fmt_census(ref)} at every rung "
         f"{rungs}" if not bad else
         f"collective census varies with D: " + "; ".join(
             f"D={d}: {_fmt_census(censuses[d])}" for d in rungs)
         + " — a per-device collective crept into a mesh-sized loop?"),
    ))
    if not spec.allows_all_gather:
        offenders = {d: s.census.get("all_gather", 0)
                     for d, s in sorted(summaries.items())
                     if s.census.get("all_gather", 0)}
        out.append(Contract(
            "no_undeclared_all_gather", not offenders,
            "entry declares no all_gather; none found" if not offenders
            else f"undeclared all_gather eqn(s): {offenders} — "
            "gathering replicates a sharded array onto every device",
        ))
    return out


def _check_law(name: str, spec: ScaleSpec,
               summaries: Dict[int, ScaleSummary],
               baseline: Optional[Dict[int, ScaleSummary]],
               baseline_floor: int) -> List[Contract]:
    out: List[Contract] = []
    rungs = sorted(d for d in summaries if d >= spec.floor)
    send = {d: summaries[d].send_bytes for d in rungs}
    label = f"wire_law_{spec.law}"
    if spec.law in ("const", "elected"):
        flat = len(set(send.values())) == 1
        out.append(Contract(
            label, flat,
            f"per-device send bytes flat at {send[rungs[0]]} B over "
            f"D={rungs}" if flat else
            f"send bytes vary with D: {send} — payload no longer "
            "independent of mesh size",
        ))
    elif spec.law == "1/D":
        shard = {d: summaries[d].rs_shard_bytes for d in rungs}
        prods = {d: shard[d] * d for d in rungs}
        ok = (len(set(prods.values())) == 1 and all(shard.values())
              and len(set(send.values())) == 1)
        out.append(Contract(
            label, ok,
            (f"reduce_scatter shard bytes exactly prop. 1/D "
             f"({shard}, DxB={prods[rungs[0]]} const) and send flat "
             f"at {send[rungs[0]]} B" if ok else
             f"1/D law broken: shard bytes {shard} (DxB {prods}), "
             f"send {send} — per-shard histogram slice no longer "
             "shrinks with the mesh"),
        ))
    elif spec.law == "bounded":
        pairs = list(zip(rungs, rungs[1:]))
        ok = all(send[a] >= send[b] for a, b in pairs)
        out.append(Contract(
            label, ok,
            f"send bytes non-increasing in D: {send}" if ok else
            f"send bytes GROW with D: {send} — wire scales with the "
            "pod",
        ))
    else:
        out.append(Contract(label, False,
                            f"unknown scaling law {spec.law!r}"))
    if spec.law == "elected":
        if baseline is None:
            out.append(Contract(
                "elected_undercuts_baseline", False,
                f"baseline {spec.baseline!r} not measured this run",
            ))
        else:
            common = sorted(d for d in summaries
                            if d in baseline
                            and d >= max(spec.floor, baseline_floor))
            worse = {d: (summaries[d].send_bytes,
                         baseline[d].send_bytes)
                     for d in common
                     if summaries[d].send_bytes
                     >= baseline[d].send_bytes}
            out.append(Contract(
                "elected_undercuts_baseline", not worse and bool(common),
                (f"elected wire under {spec.baseline}'s all-feature "
                 f"wire at every common rung {common} "
                 f"({summaries[common[0]].send_bytes} < "
                 f"{baseline[common[0]].send_bytes} B)"
                 if common and not worse else
                 f"elected wire does NOT undercut {spec.baseline}: "
                 f"{worse or 'no common rungs'} — the election stopped "
                 "paying for itself"),
            ))
    return out


def _check_eqns(spec: ScaleSpec,
                summaries: Dict[int, ScaleSummary]) -> Contract:
    rungs = sorted(d for d in summaries if d >= spec.floor)
    counts = {d: summaries[d].eqn_count for d in rungs}
    spread = max(counts.values()) - min(counts.values())
    ok = spread <= spec.eqn_tol
    return Contract(
        "eqns_D_invariant", ok,
        f"eqn spread {spread} <= tol {spec.eqn_tol} over D={rungs} "
        f"({counts})" if ok else
        f"eqn count scales with D: {counts} (spread {spread} > tol "
        f"{spec.eqn_tol}) — program size grows with the pod",
    )


def _check_shardings(spec: ScaleSpec,
                     summaries: Dict[int, ScaleSummary]) -> Contract:
    """First-match-wins over the declared rule table, every array must
    match a rule, every rule must match at least one array (a stale
    rule proves nothing), and the matched spec must equal the
    declaration."""
    problems: List[str] = []
    used = set()
    for d, s in sorted(summaries.items()):
        for arr_name, got in s.shardings:
            rule = next((r for r in spec.rules
                         if re.fullmatch(r.pattern, arr_name)), None)
            if rule is None:
                problems.append(
                    f"D={d}: {arr_name} matches no sharding rule")
                continue
            used.add(rule.label)
            if got != rule.expected:
                problems.append(
                    f"D={d}: {arr_name} is {got}, rule "
                    f"'{rule.label}' declares {rule.expected}")
    stale = [r.label for r in spec.rules if r.label not in used]
    if spec.rules and summaries:
        problems += [f"rule '{lbl}' matched nothing (stale table?)"
                     for lbl in stale]
    ok = not problems
    return Contract(
        "sharding_rules", ok,
        f"{len(spec.rules)} rules verified against "
        f"{len(next(iter(summaries.values())).shardings)} arrays at "
        f"every rung" if ok else
        "; ".join(problems[:6]) + ("" if len(problems) <= 6 else
                                   f" (+{len(problems) - 6} more)"),
    )


def _check_budget(pinned: Optional[Dict[str, Any]],
                  summaries: Dict[int, ScaleSummary]) -> Contract:
    if pinned is None:
        return Contract(
            "scale_budget", False,
            "no checked-in scale budget — run "
            "`python -m lightgbm_tpu.analysis --refresh-budgets`",
        )
    problems: List[str] = []
    for d, s in sorted(summaries.items()):
        pin = pinned.get(str(d))
        if pin is None:
            problems.append(f"no pin for D={d} — run --refresh-budgets")
            continue
        got = {"census": s.census, "send_bytes": s.send_bytes,
               "rs_shard_bytes": s.rs_shard_bytes,
               "eqn_count": s.eqn_count}
        for key in _BUDGET_KEYS:
            if got[key] != pin.get(key):
                problems.append(
                    f"D={d} {key}: {got[key]} != pinned "
                    f"{pin.get(key)}")
    ok = not problems
    return Contract(
        "scale_budget", ok,
        f"census/send/shard/eqns EXACT at D={sorted(summaries)}"
        if ok else "; ".join(problems[:6])
        + ("" if len(problems) <= 6 else f" (+{len(problems) - 6} more)"),
    )


def audit_scale(name: str, spec: ScaleSpec,
                summaries: Dict[int, ScaleSummary],
                pinned: Optional[Dict[str, Any]],
                baseline: Optional[Dict[int, ScaleSummary]] = None,
                ) -> AuditResult:
    """Pure contract evaluation over pre-computed per-rung summaries —
    tests drive this directly with synthetic summaries (red paths:
    census growth, widened payload, replicated per-row array)."""
    baseline_floor = (SCALE_ENTRIES[spec.baseline].floor
                      if spec.baseline in SCALE_ENTRIES else 1)
    contracts = (
        _check_census(spec, summaries)
        + _check_law(name, spec, summaries, baseline, baseline_floor)
        + [_check_eqns(spec, summaries),
           _check_shardings(spec, summaries),
           _check_budget(pinned, summaries)]
    )
    return AuditResult(name, all(c.ok for c in contracts), contracts, 0)


# -------------------------------------------------------------- runner
def load_budgets() -> Dict[str, Dict[str, Any]]:
    if _BUDGET_PATH.exists():
        return json.loads(_BUDGET_PATH.read_text())
    return {}


def _pins_from(summaries: Dict[int, ScaleSummary]) -> Dict[str, Any]:
    return {
        str(d): {
            "census": {k: v for k, v in sorted(s.census.items())},
            "send_bytes": s.send_bytes,
            "rs_shard_bytes": s.rs_shard_bytes,
            "eqn_count": s.eqn_count,
        }
        for d, s in sorted(summaries.items())
    }


def _measure(name: str, ladder: Sequence[int]) -> Dict[int, ScaleSummary]:
    spec = SCALE_ENTRIES[name]
    return {
        d: summarize_scale(build_entry(name, n_devices=d), spec, d)
        for d in ladder
    }


def run_scale_audits(names: Optional[Sequence[str]] = None,
                     ladder: Sequence[int] = LADDER,
                     update_budget: bool = False) -> List[AuditResult]:
    """Audit the named mesh entries (default: all of them) over the
    rung ladder. update_budget rewrites the audited entries' pins for
    the measured rungs (refresh_scale_budget wraps this for the CLI
    diff)."""
    mesh_names = mesh_entry_names()
    if names is not None:
        unknown = set(names) - set(SCALE_ENTRIES)
        if unknown:
            raise KeyError(
                f"unknown scale-audit entr"
                f"{'y' if len(unknown) == 1 else 'ies'} {sorted(unknown)}; "
                f"known: {sorted(SCALE_ENTRIES)}"
            )
    audited = [n for n in SCALE_ENTRIES if names is None or n in names]
    out: List[AuditResult] = []
    # registry consistency: a new mesh entry without a declared
    # ScaleSpec (or a spec for a dead entry) must fail loudly, not
    # silently skip the ladder
    if set(SCALE_ENTRIES) != set(mesh_names):
        missing = sorted(set(mesh_names) - set(SCALE_ENTRIES))
        orphan = sorted(set(SCALE_ENTRIES) - set(mesh_names))
        out.append(AuditResult("scale_registry", False, [Contract(
            "specs_cover_mesh_entries", False,
            f"mesh entries without a ScaleSpec: {missing}; specs for "
            f"dead entries: {orphan}",
        )], 0))
    budgets = load_budgets()
    measured: Dict[str, Dict[int, ScaleSummary]] = {}
    for name in audited:
        measured[name] = _measure(name, ladder)
    new_budgets = {k: dict(v) for k, v in budgets.items()}
    if update_budget:
        for name in audited:
            new_budgets[name] = _pins_from(measured[name])
        new_budgets = {k: v for k, v in new_budgets.items()
                       if k in SCALE_ENTRIES}
        _BUDGET_PATH.write_text(
            json.dumps(new_budgets, indent=2, sort_keys=True) + "\n"
        )
    for name in audited:
        spec = SCALE_ENTRIES[name]
        baseline = None
        if spec.baseline is not None:
            if spec.baseline not in measured:
                # measured this run even when filtered out — an
                # undercut contract against a stale number proves
                # nothing (same posture as cost_audit drop pairs)
                measured[spec.baseline] = _measure(spec.baseline, ladder)
            baseline = measured[spec.baseline]
        out.append(audit_scale(
            name, spec, measured[name],
            new_budgets.get(name), baseline,
        ))
    return out


def refresh_scale_budget() -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Rewrite scale_budget.json from current full-ladder traces;
    returns (old, new) for the --refresh-budgets diff."""
    old = load_budgets()
    run_scale_audits(ladder=LADDER, update_budget=True)
    return old, load_budgets()


def format_scale_diff(old: Dict[str, Any], new: Dict[str, Any]) -> str:
    lines: List[str] = []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o == n:
            lines.append(f"  {name}: unchanged")
            continue
        if n is None:
            lines.append(f"- {name}: removed (entry no longer exists)")
            continue
        for d in sorted(set(o or {}) | set(n), key=int):
            op, np_ = (o or {}).get(d), n.get(d)
            if op == np_:
                continue
            for key in _BUDGET_KEYS:
                ov = (op or {}).get(key)
                nv = (np_ or {}).get(key)
                if ov != nv:
                    lines.append(f"~ {name}[D={d}].{key}: {ov} -> {nv}")
    return "\n".join(lines) if lines else "  (no budgets)"
