"""Durable loop state for the online train-and-serve loop.

One JSON file per loop directory carrying everything a restart needs
to come back consistent: which model version is promoted (and where
its text lives), how far into the ingest spool the loop has consumed,
and the verdict counters. Written with the SAME tmp + fsync +
``os.replace`` contract as training checkpoints
(resilience/checkpoint.py), so a SIGKILL at any fault point leaves
either the previous state or the next one — never a torn file — and
the restart invariant holds: the last PERSISTED promotion is the model
that serves.

Ordering contract (online/loop.py): a candidate's model text is made
durable (atomic write to its versioned path) BEFORE any state that
references it, and the ingest offset only advances in the same atomic
state write that records the cycle's verdict. A crash before the
verdict write replays the cycle from the spool; a crash after it
serves the verdict's outcome.
"""

from __future__ import annotations

import os
from typing import Any, Dict

from ..resilience.checkpoint import atomic_write_json
from ..resilience.errors import CheckpointError

SCHEMA = "lightgbm-tpu/online-loop/v1"

OUTCOMES = ("promoted", "rejected", "rolled_back")


def state_path(loop_dir: str) -> str:
    return os.path.join(loop_dir, "loop_state.json")


def model_path(loop_dir: str, version: int) -> str:
    return os.path.join(loop_dir, f"model_v{int(version)}.txt")


def fresh_state() -> Dict[str, Any]:
    return {
        "schema": SCHEMA,
        "version": 0,          # last promoted version number
        "model_path": "",      # its durable model text
        "ingest_offset": 0,    # spool bytes consumed through the last verdict
        "cycle": 0,            # verdict-carrying cycles completed
        "incumbent_metrics": None,  # holdout metrics of the promoted model
        "counts": {k: 0 for k in OUTCOMES},
        "last_outcome": None,
    }


def save_state(path: str, state: Dict[str, Any]) -> str:
    """Atomically publish the loop state (tmp + fsync + os.replace)."""
    return atomic_write_json(path, state)


def atomic_write_text(path: str, text: str) -> str:
    """Model texts get the same durability contract as the state file:
    a version path either holds a complete model or does not exist."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_state(path: str) -> Dict[str, Any]:
    """Read loop state back; CheckpointError on a torn or alien file
    (absent files are the caller's 'start fresh' decision)."""
    import json

    try:
        with open(path) as f:
            state = json.load(f)
    except OSError as e:
        raise CheckpointError(f"cannot read loop state {path}: {e}") from e
    except json.JSONDecodeError as e:
        raise CheckpointError(
            f"loop state {path} is corrupt (torn write outside the "
            f"atomic protocol?): {e}"
        ) from e
    if state.get("schema") != SCHEMA:
        raise CheckpointError(
            f"loop state {path} has schema {state.get('schema')!r}, "
            f"expected {SCHEMA!r}"
        )
    for key in ("version", "model_path", "ingest_offset", "counts"):
        if key not in state:
            raise CheckpointError(f"loop state {path} is missing {key!r}")
    return state
