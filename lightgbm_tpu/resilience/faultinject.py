"""Deterministic fault injection: a config/env-driven fault plan.

Chaos testing needs faults that happen at an EXACT, reproducible point
— "the trainer died at round 7", "the first device put failed", "one
serving request stalled 200 ms" — not whenever a signal happens to
land. A fault plan is a string of clauses

    <site>:<trigger>:<action>[:<param>]   joined by ';'

    round:7:kill            SIGKILL the process at boosting round 7
    round:5:raise           raise InjectedFault at round 5
    device_put:1:raise      fail the 1st serving device put
    serve_request:2:delay:0.25   stall the 2nd serving request 250 ms
    serve_request:3:raise   500 the 3rd serving request

armed through the ``fault_plan=`` config/CLI param or the
``LGBMTPU_FAULT_PLAN`` env var (``configure()``), or programmatically
(``arm()`` / ``disarm()`` — tests). Sites are host-side seams the
production code already passes through:

- ``round``       — engine.train, once per boosting round; ``trigger``
                    is the ABSOLUTE round index;
- ``device_put``  — serving/dispatch.py, before each bucketed device
                    call; ``trigger`` is the 1-based Nth hit;
- ``serve_request`` — serving/server.py, per protocol request;
                    ``trigger`` is the 1-based Nth hit;
- ``loop_ingest`` / ``loop_refit`` / ``loop_eval`` / ``loop_promote``
                    — online/loop.py, one per phase of each online
                    train-and-serve cycle; ``trigger`` is the ABSOLUTE
                    cycle index (0-based, like ``round``);
- ``gw_connect`` / ``gw_slow_backend`` / ``gw_backend_5xx``
                    — serving/gateway.py, per backend attempt: before
                    the socket opens / before the response read (a
                    ``delay`` clause stalls the backend answer) /
                    after the answer (a ``raise`` clause turns it into
                    a backend failure); ``trigger`` is the 1-based Nth
                    hit;
- ``gw_drain``      — serving/gateway.py, once per ``drain()`` call
                    (SIGTERM path); ``trigger`` is the Nth drain.

Actions: ``raise`` (InjectedFault), ``kill`` (SIGKILL — a real
no-cleanup crash for the checkpoint/resume tests), ``delay:<seconds>``
(sleep, then continue). Every clause fires ONCE and disarms itself, so
a plan is a finite, ordered script.

Zero overhead disarmed — the contract the static audit enforces
(analysis/jaxpr_audit.audit_faultinject): ``fault_point`` is a
module-global ``None`` check on the host, call sites exist only in
host-side modules (never inside traced code), and arming a plan adds
no equations to any audited jaxpr.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from .errors import InjectedFault

ENV_VAR = "LGBMTPU_FAULT_PLAN"
SITES = (
    "round", "device_put", "serve_request",
    "loop_ingest", "loop_refit", "loop_eval", "loop_promote",
    "gw_connect", "gw_backend_5xx", "gw_slow_backend", "gw_drain",
)
ACTIONS = ("raise", "kill", "delay")


class _Clause:
    __slots__ = ("site", "trigger", "action", "param", "done")

    def __init__(self, site: str, trigger: int, action: str, param: float):
        self.site = site
        self.trigger = trigger
        self.action = action
        self.param = param
        self.done = False

    def __repr__(self) -> str:
        p = f":{self.param:g}" if self.action == "delay" else ""
        return f"{self.site}:{self.trigger}:{self.action}{p}"


class FaultPlan:
    """Parsed plan; thread-safe (serving sites fire from request
    threads). ``visit`` matches one site hit against the clauses and
    executes at most one action."""

    def __init__(self, spec: str):
        self.spec = spec
        self.clauses: List[_Clause] = []
        self._hits = {s: 0 for s in SITES}
        self._lock = threading.Lock()
        for part in spec.replace(",", ";").split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) < 3:
                raise ValueError(
                    f"fault plan clause {part!r}: need site:trigger:action"
                )
            site, trigger, action = bits[0], bits[1], bits[2]
            if site not in SITES:
                raise ValueError(
                    f"fault plan clause {part!r}: unknown site {site!r} "
                    f"(known: {SITES})"
                )
            if action not in ACTIONS:
                raise ValueError(
                    f"fault plan clause {part!r}: unknown action "
                    f"{action!r} (known: {ACTIONS})"
                )
            param = 0.0
            if action == "delay":
                if len(bits) < 4:
                    raise ValueError(
                        f"fault plan clause {part!r}: delay needs seconds "
                        "(site:trigger:delay:<s>)"
                    )
                param = float(bits[3])
            self.clauses.append(_Clause(site, int(trigger), action, param))

    # ------------------------------------------------------------------
    def visit(self, site: str, index: Optional[int] = None) -> None:
        """One site hit. ``index`` (when given, e.g. the boosting round)
        is matched against the trigger directly; otherwise the site's
        1-based hit counter is."""
        with self._lock:
            self._hits[site] = self._hits.get(site, 0) + 1
            at = self._hits[site] if index is None else int(index)
            fire = None
            for c in self.clauses:
                if not c.done and c.site == site and c.trigger == at:
                    c.done = True
                    fire = c
                    break
        if fire is None:
            return
        if fire.action == "delay":
            time.sleep(fire.param)
            return
        if fire.action == "kill":
            import signal

            # real crash semantics: no atexit, no finally, no flush —
            # exactly what the crash-consistent checkpoints must survive
            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedFault(f"planned fault {fire!r} fired at {site}[{at}]")


_PLAN: Optional[FaultPlan] = None


def arm(spec: str) -> FaultPlan:
    """Install a plan for this process (replaces any previous one)."""
    global _PLAN
    plan = FaultPlan(spec)
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def configure(spec: str = "") -> Optional[FaultPlan]:
    """Entry-point hook (engine.train / cli task=serve): arm from the
    config param, else the env var, else disarm — each run's plan is
    exactly what ITS config says, never a leftover."""
    spec = (spec or "").strip() or os.environ.get(ENV_VAR, "").strip()
    if spec:
        return arm(spec)
    disarm()
    return None


def fault_point(site: str, index: Optional[int] = None) -> None:
    """Host-side fault seam. Disarmed (the default) this is one global
    load + None check — and it must NEVER be called from traced code
    (the audit proves no call site can reach a jaxpr)."""
    plan = _PLAN
    if plan is None:
        return
    plan.visit(site, index)
