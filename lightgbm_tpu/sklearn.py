"""scikit-learn estimator API (reference python-package/lightgbm/sklearn.py).

`LGBMModel` (sklearn.py:486) plus the three concrete estimators
`LGBMRegressor` (:1314), `LGBMClassifier` (:1424), `LGBMRanker` (:1679).
Constructor argument names, fit() keyword surface, fitted attributes
(`booster_`, `best_iteration_`, `feature_importances_`, `classes_`, ...)
and the sklearn-name → LightGBM-name parameter mapping
(reg_alpha→lambda_l1, subsample→bagging_fraction, ...) match the
reference so user code ports with an import change.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

try:  # sklearn is an optional dependency in the reference (compat.py)
    from sklearn.base import BaseEstimator as _LGBMModelBase
    from sklearn.base import ClassifierMixin as _LGBMClassifierBase
    from sklearn.base import RegressorMixin as _LGBMRegressorBase
    from sklearn.preprocessing import LabelEncoder as _LGBMLabelEncoder

    SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover
    _LGBMModelBase = object
    _LGBMClassifierBase = object
    _LGBMRegressorBase = object
    _LGBMLabelEncoder = None
    SKLEARN_INSTALLED = False

from . import callback as callback_mod
from .basic import Booster, Dataset, LightGBMError
from .engine import train as _train

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-style fobj(y_true, y_pred[, weight/group]) to the
    engine's fobj(preds, dataset) (reference sklearn.py:154)."""

    def __init__(self, func: Callable):
        self.func = func
        self._argc = len(inspect.signature(func).parameters)

    def __call__(self, preds: np.ndarray, dataset: Dataset):
        labels = dataset.get_label()
        argc = self._argc
        p = preds.T if preds.ndim == 2 else preds  # (N, K) for multiclass
        if argc == 2:
            grad, hess = self.func(labels, p)
        elif argc == 3:
            grad, hess = self.func(labels, p, dataset.get_weight())
        else:
            grad, hess = self.func(labels, p, dataset.get_weight(), dataset.get_group())
        grad = np.asarray(grad)
        hess = np.asarray(hess)
        if grad.ndim == 2:  # (N, K) -> flat (K*N,) class-major
            grad = grad.T.reshape(-1)
            hess = hess.T.reshape(-1)
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt sklearn-style feval(y_true, y_pred[, weight/group]) to the
    engine's feval(preds, dataset) (reference sklearn.py:241)."""

    def __init__(self, func: Callable):
        self.func = func
        self._argc = len(inspect.signature(func).parameters)

    def __call__(self, preds: np.ndarray, dataset: Dataset):
        labels = dataset.get_label()
        argc = self._argc
        p = preds.T if preds.ndim == 2 else preds
        if argc == 2:
            return self.func(labels, p)
        if argc == 3:
            return self.func(labels, p, dataset.get_weight())
        return self.func(labels, p, dataset.get_weight(), dataset.get_group())


class LGBMModel(_LGBMModelBase):
    """Base sklearn estimator (reference sklearn.py:486)."""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective: Optional[Union[str, Callable]] = None,
        class_weight: Optional[Union[Dict, str]] = None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state: Optional[int] = None,
        n_jobs: Optional[int] = None,
        importance_type: str = "split",
        **kwargs: Any,
    ):
        if not SKLEARN_INSTALLED:
            raise LightGBMError("scikit-learn is required for lightgbm_tpu.sklearn")
        self.boosting_type = boosting_type
        self.objective = objective
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self.class_weight = class_weight
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_score: Dict = {}
        self._best_iteration: int = -1
        self._objective = objective
        self._other_params: Dict[str, Any] = {}
        self._n_features: int = -1
        self._n_classes: int = -1
        self.set_params(**kwargs)

    # -- sklearn plumbing ------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep)
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, f"_{key}"):
                setattr(self, f"_{key}", value)
            self._other_params[key] = value
        return self

    def _more_tags(self):
        return {"allow_nan": True, "X_types": ["2darray", "sparse", "1dlabels"]}

    # -- parameter translation ------------------------------------------
    def _process_params(self, stage: str) -> Dict[str, Any]:
        """sklearn names → LightGBM params (reference sklearn.py:801)."""
        params = self.get_params()
        params.pop("objective", None)
        for alias in ("class_weight", "importance_type", "n_estimators", "n_jobs"):
            params.pop(alias, None)
        if isinstance(self._objective, str) or self._objective is None:
            params["objective"] = self._objective
        else:
            params["objective"] = "none"
        params["num_leaves"] = self.num_leaves
        params["max_depth"] = self.max_depth
        params["learning_rate"] = self.learning_rate
        params["min_gain_to_split"] = params.pop("min_split_gain", self.min_split_gain)
        params["min_sum_hessian_in_leaf"] = params.pop("min_child_weight", self.min_child_weight)
        params["min_data_in_leaf"] = params.pop("min_child_samples", self.min_child_samples)
        params["bagging_fraction"] = params.pop("subsample", self.subsample)
        params["bagging_freq"] = params.pop("subsample_freq", self.subsample_freq)
        params["feature_fraction"] = params.pop("colsample_bytree", self.colsample_bytree)
        params["lambda_l1"] = params.pop("reg_alpha", self.reg_alpha)
        params["lambda_l2"] = params.pop("reg_lambda", self.reg_lambda)
        params["max_bin"] = params.pop("max_bin", 255)
        params.pop("subsample_for_bin", None)
        params.pop("random_state", None)
        if self.random_state is not None:
            seed = self.random_state
            if not isinstance(seed, (int, np.integer)):
                seed = seed.randint(0, 2**31 - 1) if hasattr(seed, "randint") else 0
            params["seed"] = int(seed)
            params["bagging_seed"] = int(seed)
            params["feature_fraction_seed"] = int(seed)
        params["boosting"] = self.boosting_type
        if self._n_classes > 2 and params["objective"] in (None, "multiclass", "multiclassova"):
            params["num_class"] = self._n_classes
        if params.get("verbosity") is None and params.get("verbose") is None:
            params["verbosity"] = -1
        params = {k: v for k, v in params.items() if v is not None}
        return params

    # -- fit -------------------------------------------------------------
    def fit(
        self,
        X,
        y,
        sample_weight=None,
        init_score=None,
        group=None,
        eval_set=None,
        eval_names=None,
        eval_sample_weight=None,
        eval_class_weight=None,
        eval_init_score=None,
        eval_group=None,
        eval_metric=None,
        feature_name="auto",
        categorical_feature="auto",
        callbacks=None,
        init_model=None,
    ) -> "LGBMModel":
        params = self._process_params(stage="fit")

        fobj = None
        if callable(self._objective):
            fobj = _ObjectiveFunctionWrapper(self._objective)
        feval_list: List[Callable] = []
        if eval_metric is not None:
            metrics = eval_metric if isinstance(eval_metric, list) else [eval_metric]
            str_metrics = [m for m in metrics if isinstance(m, str)]
            call_metrics = [m for m in metrics if callable(m)]
            if str_metrics:
                # merge with the existing/default metric rather than replace
                # (reference sklearn.py:944 prepends eval metrics)
                original = params.get("metric")
                if original is None:
                    # objective-implied default metric stays evaluated
                    from .metrics import _DEFAULT_METRIC

                    obj = params.get("objective")
                    original = [_DEFAULT_METRIC[obj]] if obj in _DEFAULT_METRIC else []
                elif isinstance(original, str):
                    original = [original]
                merged = list(dict.fromkeys(str_metrics + list(original)))
                params["metric"] = merged
            feval_list = [_EvalFunctionWrapper(m) for m in call_metrics]

        y_arr = np.asarray(y).reshape(-1)
        X_arr = X
        self._n_features = np.shape(X)[1]

        # class_weight → per-row weights (reference uses compute_sample_weight)
        if self.class_weight is not None and sample_weight is None:
            from sklearn.utils.class_weight import compute_sample_weight

            sample_weight = compute_sample_weight(self.class_weight, y_arr)

        train_set = Dataset(
            X_arr,
            label=y_arr,
            weight=sample_weight,
            group=group,
            init_score=init_score,
            feature_name=feature_name,
            categorical_feature=categorical_feature,
            params=params,
            free_raw_data=False,
        )

        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                name = eval_names[i] if eval_names and i < len(eval_names) else f"valid_{i}"
                vy = np.asarray(vy).reshape(-1)
                if hasattr(self, "_le") and self._le is not None:
                    vy = self._le.transform(vy)
                if vx is X and vy.shape == y_arr.shape and np.array_equal(vy, y_arr):
                    valid_sets.append(train_set)
                else:
                    vw = eval_sample_weight[i] if eval_sample_weight else None
                    if eval_class_weight and i < len(eval_class_weight) and vw is None:
                        from sklearn.utils.class_weight import compute_sample_weight

                        vw = compute_sample_weight(eval_class_weight[i], vy)
                    vg = eval_group[i] if eval_group else None
                    vi = eval_init_score[i] if eval_init_score else None
                    valid_sets.append(
                        Dataset(
                            vx, label=vy, weight=vw, group=vg, init_score=vi,
                            reference=train_set, params=params, free_raw_data=False,
                        )
                    )
                valid_names.append(name)

        evals_result: Dict = {}
        callbacks = list(callbacks) if callbacks else []
        callbacks.append(callback_mod.record_evaluation(evals_result))

        self._Booster = _train(
            params,
            train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets,
            valid_names=valid_names,
            feval=feval_list if feval_list else None,
            init_model=init_model,
            callbacks=callbacks,
            fobj=fobj,
        )
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self.fitted_ = True
        return self

    # -- predict ---------------------------------------------------------
    def predict(
        self,
        X,
        raw_score: bool = False,
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        validate_features: bool = False,
        **kwargs: Any,
    ):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before predict")
        return self._Booster.predict(
            X,
            raw_score=raw_score,
            start_iteration=start_iteration,
            num_iteration=num_iteration,
            pred_leaf=pred_leaf,
            pred_contrib=pred_contrib,
            validate_features=validate_features,
            **kwargs,
        )

    # -- fitted attributes ----------------------------------------------
    @property
    def n_features_(self) -> int:
        if self._n_features < 0:
            raise LightGBMError("No n_features found. Need to call fit beforehand.")
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def best_iteration_(self) -> int:
        if self._Booster is None:
            raise LightGBMError("No best_iteration found. Need to call fit with early_stopping callback beforehand.")
        return self._best_iteration

    @property
    def objective_(self):
        return self._objective if self._objective is not None else self._fallback_objective()

    def _fallback_objective(self) -> str:
        return "regression"

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit beforehand.")
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("No feature_importances found. Need to call fit beforehand.")
        return self.booster_.feature_importance(importance_type=self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        if self._Booster is None:
            raise LightGBMError("No feature_name found. Need to call fit beforehand.")
        return self.booster_.feature_name()

    @property
    def feature_names_in_(self) -> np.ndarray:
        return np.asarray(self.feature_name_)


class LGBMRegressor(_LGBMRegressorBase, LGBMModel):
    """LightGBM regressor (reference sklearn.py:1314)."""

    def _fallback_objective(self) -> str:
        return "regression"

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMRegressor":
        if self._objective is None:
            self._objective = "regression"
        super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight, eval_init_score=eval_init_score,
            eval_metric=eval_metric, feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model,
        )
        return self


class LGBMClassifier(_LGBMClassifierBase, LGBMModel):
    """LightGBM classifier (reference sklearn.py:1424)."""

    def _fallback_objective(self) -> str:
        return "multiclass" if self._n_classes > 2 else "binary"

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_class_weight=None,
            eval_init_score=None, eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None) -> "LGBMClassifier":
        y_arr = np.asarray(y).reshape(-1)
        self._le = _LGBMLabelEncoder().fit(y_arr)
        y_enc = self._le.transform(y_arr)
        self._classes = self._le.classes_
        self._n_classes = len(self._classes)
        if self._objective is None:
            self._objective = "multiclass" if self._n_classes > 2 else "binary"
        # map eval metric aliases like the reference (sklearn.py:1510-1530)
        alias = {"logloss": "binary_logloss", "error": "binary_error"}
        if self._n_classes > 2:
            alias = {"logloss": "multi_logloss", "error": "multi_error"}
        if isinstance(eval_metric, str):
            eval_metric = alias.get(eval_metric, eval_metric)
        elif isinstance(eval_metric, list):
            eval_metric = [alias.get(m, m) if isinstance(m, str) else m for m in eval_metric]
        super().fit(
            X, y_enc, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_class_weight=eval_class_weight,
            eval_init_score=eval_init_score, eval_metric=eval_metric,
            feature_name=feature_name, categorical_feature=categorical_feature,
            callbacks=callbacks, init_model=init_model,
        )
        return self

    def predict(self, X, raw_score=False, start_iteration=0, num_iteration=None,
                pred_leaf=False, pred_contrib=False, validate_features=False, **kwargs):
        result = self.predict_proba(
            X, raw_score, start_iteration, num_iteration, pred_leaf, pred_contrib,
            validate_features, **kwargs,
        )
        if callable(self._objective) or raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 2:
            class_index = np.argmax(result, axis=1)
        else:
            class_index = (result > 0.5).astype(np.int64)
        return self._le.inverse_transform(class_index)

    def predict_proba(self, X, raw_score=False, start_iteration=0, num_iteration=None,
                      pred_leaf=False, pred_contrib=False, validate_features=False, **kwargs):
        result = super().predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features, **kwargs,
        )
        if callable(self._objective) or raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes > 2 or result.ndim == 2:
            return result
        return np.vstack((1.0 - result, result)).transpose()

    @property
    def classes_(self) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("No classes found. Need to call fit beforehand.")
        return self._classes

    @property
    def n_classes_(self) -> int:
        if self._Booster is None:
            raise LightGBMError("No classes found. Need to call fit beforehand.")
        return self._n_classes


class LGBMRanker(LGBMModel):
    """LightGBM ranker (reference sklearn.py:1679)."""

    def _fallback_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None) -> "LGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not None")
        if self._objective is None:
            self._objective = "lambdarank"
        self._other_params["eval_at"] = list(eval_at)
        super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score, group=group,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight, eval_init_score=eval_init_score,
            eval_group=eval_group, eval_metric=eval_metric,
            feature_name=feature_name, categorical_feature=categorical_feature,
            callbacks=callbacks, init_model=init_model,
        )
        return self
