// Build shim for the parity harness: the reference's linear-tree leaf
// solver needs Eigen, whose vendored submodule is not checked out in
// this image. The parity tests never enable linear_tree; any attempt
// to use it aborts loudly instead of silently degrading.
#include <LightGBM/utils/log.h>

#include "linear_tree_learner.h"  // via -I<reference>/src/treelearner

namespace LightGBM {

template <typename T>
void LinearTreeLearner<T>::Init(const Dataset* train_data,
                                bool is_constant_hessian) {
  T::Init(train_data, is_constant_hessian);
  Log::Fatal("linear_tree is unavailable in this shim build (no Eigen)");
}

template <typename T>
void LinearTreeLearner<T>::InitLinear(const Dataset*, const int) {
  Log::Fatal("linear_tree is unavailable in this shim build (no Eigen)");
}

template <typename T>
Tree* LinearTreeLearner<T>::Train(const score_t*, const score_t*, bool) {
  Log::Fatal("linear_tree is unavailable in this shim build (no Eigen)");
  return nullptr;
}

template <typename T>
Tree* LinearTreeLearner<T>::FitByExistingTree(const Tree*, const score_t*,
                                              const score_t*) const {
  Log::Fatal("linear_tree is unavailable in this shim build (no Eigen)");
  return nullptr;
}

template <typename T>
Tree* LinearTreeLearner<T>::FitByExistingTree(const Tree*,
                                              const std::vector<int>&,
                                              const score_t*,
                                              const score_t*) const {
  Log::Fatal("linear_tree is unavailable in this shim build (no Eigen)");
  return nullptr;
}

template <typename T>
void LinearTreeLearner<T>::GetLeafMap(Tree*) const {
  Log::Fatal("linear_tree is unavailable in this shim build (no Eigen)");
}

template <typename T>
template <bool HAS_NAN>
void LinearTreeLearner<T>::CalculateLinear(Tree*, bool, const score_t*,
                                           const score_t*, bool) const {
  Log::Fatal("linear_tree is unavailable in this shim build (no Eigen)");
}

template class LinearTreeLearner<SerialTreeLearner>;
template class LinearTreeLearner<GPUTreeLearner>;

}  // namespace LightGBM
