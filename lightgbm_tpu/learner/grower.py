"""Leaf-wise tree growth under jit.

Reimplements the reference's leaf-wise learner loop
(src/treelearner/serial_tree_learner.cpp:182-239 Train, CUDA analog
cuda_single_gpu_tree_learner.cpp) as a `lax.while_loop` with static
shapes:

- the partition is a flat per-row leaf-id vector updated with masked
  `where` (reference CUDA data_index_to_leaf_index,
  cuda_data_partition.cu:113) — no index lists, no compaction;
- per-leaf histograms live in a fixed (num_leaves, 3, F, B) tensor
  (the reference's HistogramPool, feature_histogram.hpp:1367, without
  eviction — recompute-free subtraction needs the parent kept);
- each split computes the smaller child's histogram by masked scan and
  derives the larger by subtraction (serial_tree_learner.cpp:411
  ConstructHistograms smaller-leaf trick);
- leaf numbering matches Tree::Split (src/io/tree.cpp): the left child
  keeps the parent leaf's id, the right child gets id = current number
  of leaves; internal node i is created by split i; children pointers
  use ~leaf (= -(leaf+1)) encoding;
- with `axis_name` set, histograms and root sums are `lax.psum`'d over
  the data mesh axis — the ICI equivalent of the reference's histogram
  reduce-scatter (data_parallel_tree_learner.cpp:286); every shard then
  computes identical splits and partitions its local rows in lockstep.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .bundle import BundleInfo, decode_feature_bins, expand_hist
from .histogram import (
    build_gh8,
    gather_gh8,
    gather_rows,
    hist_capacities,
    histogram,
    root_sums,
)
from .split import BIG, NEG_INF, SplitParams, SplitRecord, best_split, leaf_output


class GrowerSpec(NamedTuple):
    """Static (compile-time) growth configuration."""

    num_leaves: int
    num_bins: int  # uniform bin-axis size B
    max_depth: int  # <= 0 means unlimited
    axis_name: Optional[str] = None
    # static size of the data mesh axis (set by DataParallelGrower).
    # > 1 enables the reduce-scatter histogram wire on eligible paths
    # (rounds.py: integer dtype + per-rank feature ownership — the
    # reference's bin.h:63-81 + data_parallel_tree_learner.cpp:286).
    axis_size: int = 0
    # sorted-subset categorical splits (feature_histogram.hpp:449): set
    # when the dataset has categorical features wider than
    # max_cat_to_onehot; False keeps every categorical one-vs-rest and
    # skips the subset scan entirely (no cost for numerical data)
    cat_subset: bool = False
    # gathered smaller-child histograms: per-split cost tracks leaf size
    # instead of N (the reference's index-list construction,
    # data_partition.hpp); False = masked full scans (simpler, for debug)
    gather_hist: bool = True
    # "permuted": physically leaf-grouped rows, O(segment) per split
    # (permuted.py — the production path); "flat": per-row leaf-id vector,
    # O(N) per split (kept as the reference/debug implementation)
    partition: str = "permuted"
    # EFB (dataset.cpp:111 FindGroups): the bin matrix columns are
    # BUNDLES; histograms expand back to per-feature layout before split
    # finding and the partition decodes original bins (bundle.py).
    # col_bins = uniform device bin-axis size of the bundle columns
    # (>= num_bins); 0 means same as num_bins.
    efb: bool = False
    col_bins: int = 0
    # round-batched growth (permuted partition only, opt-in via
    # tpu_growth_rounds): split EVERY positive-gain leaf per step while
    # the budget allows — one stable sort partitions all leaves, one
    # multi-slot histogram pass covers all smaller children (the
    # reference CUDA kernel's all-leaves batching,
    # cuda_histogram_constructor.cu). NOT identical to sequential
    # leaf-wise greedy once the leaf budget binds: greedy may spend the
    # remaining budget on descendants of high-gain splits instead of
    # sibling leaves (best-first vs breadth-batched). Default off; the
    # sequential path is the reference-exact semantics.
    rounds: bool = False
    # feature parallel (tree_learner=feature, parallel_tree_learner.h:26):
    # the FLAT grower with the FEATURE axis sharded over this mesh axis —
    # every shard holds all rows (the reference's all-ranks-hold-all-data
    # design), finds the best split among its own features, and the
    # global best is an all-gather argmax (SyncUpGlobalBestSplit); the
    # winning shard broadcasts the per-row split decision with one psum.
    feature_axis: Optional[str] = None
    # voting parallel (tree_learner=voting, parallel_tree_learner.h:126):
    # each shard proposes its top-k features by LOCAL gain, a global
    # vote elects ~2k, and only elected feature columns are psum'd
    # across the mesh — the reference's bandwidth cap, applied to the
    # DCN-scale case (within one ICI slice a full psum is cheap and
    # tree_learner=data is the better choice). 0 = off.
    voting_k: int = 0
    # per-node extras (permuted sequential path only):
    # extra_trees: one random numerical threshold per feature per node
    extra_trees: bool = False
    # feature_fraction_bynode < 1: per-node feature subsample (ColSampler)
    ff_bynode: bool = False
    # CEGB penalties active (cost_effective_gradient_boosting.hpp)
    cegb: bool = False
    # number of interaction-constraint groups (0 = unconstrained)
    n_groups: int = 0
    # static length of the forced-split plan (forcedsplits_filename)
    n_forced: int = 0
    # natural-order round-batched growth (rounds.py, tpu_growth_mode):
    # > 0 = split the top-`rounds_slots` positive-gain leaves per device
    # step, smaller-child histograms from ONE slot-packed MXU pass keyed
    # by the row->leaf vector — no physical row movement at all. The TPU
    # fast path; 0 = off (sequential permuted growth).
    rounds_slots: int = 0
    # quantized-gradient channels in rounds mode (use_quantized_grad):
    # grad/hess arrive as INTEGER levels, histograms accumulate exact
    # int sums in 3 bf16 channels per slot (48 slots/pass vs 25), and
    # the split scan runs on scale-multiplied sums — the TPU analog of
    # the reference's int16/int32 histogram path (bin.h:63-81,
    # feature_histogram.hpp:1062 int threshold scan).
    quant: bool = False
    # quant levels fit int8 (num_grad_quant_bins <= 127): the slot-packed
    # kernel runs s8 x s8 -> s32 on the MXU — twice the bf16 rate on v5e
    # and bit-exact integer sums (bin.h:63-81 int histogram analog)
    quant_int8: bool = False
    # num_grad_quant_bins when quant: bounds the per-cell integer sums
    # for the SWAR one-hot scale policy (histogram.int8_oh_shift)
    quant_levels: int = 0
    # monotone constraint method (monotone_constraints_method):
    # 0 = basic (children bounded at the split midpoint, inherited);
    # 1 = intermediate (monotone_constraints.hpp:516): per-leaf bounds
    # recomputed every split from the OPPOSITE subtrees' actual output
    # extrema via an ancestry matrix, and every leaf's cached best
    # split re-searched under the new bounds — less conservative than
    # basic, still violation-free by induction;
    # 2 = advanced (monotone_constraints.hpp:858, rounds grower only):
    # the opposite-subtree extrema are further refined per constrained
    # leaf — only leaves whose per-feature bin ranges overlap the
    # constrained leaf's in every feature but the ancestor's split
    # feature can bound it (pairwise range-intersection tables kept in
    # the round state; strictly no looser than intermediate).
    # Intermediate runs on both the sequential permuted grower
    # (per-split recompute) and the rounds grower (per-round recompute
    # + same-round conflict guard, rounds.py).
    mono_mode: int = 0
    # dataset has at least one categorical feature: rounds-mode partition
    # updates need the per-row category-set test only then; all-numerical
    # datasets (the common benchmark shape) skip that machinery
    # statically — the (L*B,) mask gather it replaces costs ~10 ms/round
    # at 1M rows (tools/tpu_gather_probe.py)
    has_cat: bool = True


class CegbInfo(NamedTuple):
    """Traced CEGB penalty tables (DeltaGain inputs)."""

    coupled: jax.Array  # (F,) — one-time per-feature cost (model-wide)
    lazy: jax.Array  # (F,) — per-data cost, charged along each path
    used: jax.Array  # (F,) bool — features already used by earlier trees


class TreeArrays(NamedTuple):
    """Fixed-size tree (reference include/LightGBM/tree.h array layout).

    Node arrays have length num_leaves-1, leaf arrays num_leaves. Child
    pointers: >=0 internal node index, <0 leaf encoded as ~leaf_index.
    """

    num_nodes: jax.Array  # scalar int32 — actual splits performed
    node_feature: jax.Array
    node_bin: jax.Array
    node_gain: jax.Array
    node_default_left: jax.Array
    node_cat: jax.Array
    node_cat_mask: jax.Array  # (L-1, B) bool — cat bins going left
    node_left: jax.Array
    node_right: jax.Array
    node_value: jax.Array  # internal_value: output of the pre-split leaf
    node_weight: jax.Array  # internal_weight: hessian sum
    node_count: jax.Array  # internal_count
    leaf_value: jax.Array
    leaf_weight: jax.Array
    leaf_count: jax.Array
    leaf_depth: jax.Array


class _State(NamedTuple):
    i: jax.Array
    row_leaf: jax.Array
    hist: jax.Array  # (L, 3, F, B) — channel-leading, bins on lanes
    leaf_g: jax.Array
    leaf_h: jax.Array
    leaf_c: jax.Array
    leaf_parent: jax.Array
    leaf_min: jax.Array  # (L,) monotone-constraint interval per leaf
    leaf_max: jax.Array
    best: SplitRecord  # per-leaf arrays (L,)
    tree: TreeArrays


def make_split_params(cfg) -> SplitParams:
    """Build traced split params from a Config (host side)."""
    f = lambda v: jnp.float32(v)
    return SplitParams(
        lambda_l1=f(cfg.lambda_l1),
        lambda_l2=f(cfg.lambda_l2),
        min_data_in_leaf=f(cfg.min_data_in_leaf),
        min_sum_hessian_in_leaf=f(cfg.min_sum_hessian_in_leaf),
        min_gain_to_split=f(cfg.min_gain_to_split),
        max_delta_step=f(cfg.max_delta_step),
        path_smooth=f(cfg.path_smooth),
        cat_smooth=f(cfg.cat_smooth),
        cat_l2=f(cfg.cat_l2),
        max_cat_threshold=jnp.int32(cfg.max_cat_threshold),
        max_cat_to_onehot=jnp.int32(cfg.max_cat_to_onehot),
        min_data_per_group=f(cfg.min_data_per_group),
        cegb_tradeoff=f(cfg.cegb_tradeoff),
        cegb_penalty_split=f(cfg.cegb_penalty_split),
        feature_fraction_bynode=f(cfg.feature_fraction_bynode),
    )


def split_leaf_outputs(rec: SplitRecord, params: SplitParams, num_bins,
                       use_cat_subset: bool, parent_output, cmin, cmax):
    """Left/right child outputs for a chosen split: path smoothing toward
    the parent output, clamped to the PARENT's monotone interval
    (BasicLeafConstraints clone-then-update). Sorted-subset categorical
    splits regularize with l2 + cat_l2 (feature_histogram.cpp:251,346)."""
    if use_cat_subset:
        is_sub = rec.is_cat & (num_bins[rec.feature] > params.max_cat_to_onehot)
        p = params._replace(
            lambda_l2=params.lambda_l2 + jnp.where(is_sub, params.cat_l2, 0.0)
        )
    else:
        p = params
    lo = leaf_output(rec.left_g, rec.left_h, p, rec.left_c, parent_output,
                     cmin, cmax)
    ro = leaf_output(rec.right_g, rec.right_h, p, rec.right_c, parent_output,
                     cmin, cmax)
    return lo, ro


def monotone_child_intervals(rec: SplitRecord, mono, lo, ro, cur_min, cur_max):
    """BasicLeafConstraints::Update (monotone_constraints.hpp:489): a
    NUMERICAL split on a monotone feature tightens the children's output
    intervals around mid = (lo + ro) / 2; both children inherit the
    parent interval otherwise."""
    m = mono[rec.feature]
    upd = (~rec.is_cat) & (m != 0)
    mid = (lo + ro) / 2.0
    lmin = jnp.where(upd & (m < 0), jnp.maximum(cur_min, mid), cur_min)
    lmax = jnp.where(upd & (m > 0), jnp.minimum(cur_max, mid), cur_max)
    rmin = jnp.where(upd & (m > 0), jnp.maximum(cur_min, mid), cur_min)
    rmax = jnp.where(upd & (m < 0), jnp.minimum(cur_max, mid), cur_max)
    return lmin, lmax, rmin, rmax


def make_node_candidates(spec: GrowerSpec, params: SplitParams, feat_mask,
                         num_bins, nan_bin, rng_key, group_mat, cegb,
                         F: int):
    """Per-node split-candidate machinery shared by the permuted and
    rounds growers: interaction-group filtering (ColSampler,
    col_sampler.hpp), feature_fraction_bynode sampling, extra_trees
    random thresholds, and the CEGB DeltaGain penalty
    (cost_effective_gradient_boosting.hpp:79 — with the per-tree-path
    lazy approximation, see DESIGN_DECISIONS.md). Returns
    node_candidates(salt, child_groups, path_used_child, child_count,
    feat_used) -> (feat_mask, rand_bin, penalty), keyed on the node
    index so draws are deterministic per tree position."""

    def node_candidates(salt, child_groups, path_used_child, child_count,
                        feat_used):
        fm = feat_mask
        rb = None
        pen = None
        if spec.n_groups:
            fm = fm & jnp.any(group_mat & child_groups[:, None], axis=0)
        if spec.ff_bynode:
            # sample ceil(frac * currently-valid) from the VALID set
            # (ColSampler samples from used_feature_indices_, so a node
            # always keeps >= 1 candidate)
            k1 = jax.random.fold_in(rng_key, 2 * salt)
            u = jnp.where(fm, jax.random.uniform(k1, (F,)), jnp.inf)
            n_valid = jnp.sum(fm)
            n_pick = jnp.maximum(
                jnp.ceil(
                    params.feature_fraction_bynode * n_valid
                ).astype(jnp.int32),
                1,
            )
            rank = jnp.argsort(jnp.argsort(u))
            fm = fm & (rank < n_pick)
        if spec.extra_trees:
            k2 = jax.random.fold_in(rng_key, 2 * salt + 1)
            u = jax.random.uniform(k2, (F,))
            n_thr = jnp.maximum(num_bins - 1 - (nan_bin >= 0), 1)
            rb = jnp.floor(u * n_thr).astype(jnp.int32)
        if spec.cegb:
            pen = params.cegb_tradeoff * (
                params.cegb_penalty_split * child_count
                + cegb.coupled * (~feat_used).astype(jnp.float32)
                + cegb.lazy * child_count
                * (~path_used_child).astype(jnp.float32)
            )
        return fm, rb, pen

    return node_candidates


def _empty_best(L: int, B: int) -> SplitRecord:
    zi = jnp.zeros(L, jnp.int32)
    zf = jnp.zeros(L, jnp.float32)
    zb = jnp.zeros(L, bool)
    return SplitRecord(
        gain=jnp.full(L, NEG_INF),
        feature=zi, bin=zi, default_left=zb, is_cat=zb,
        cat_mask=jnp.zeros((L, B), bool),
        left_g=zf, left_h=zf, left_c=zf,
        right_g=zf, right_h=zf, right_c=zf,
    )


def _set_best(best: SplitRecord, l: jax.Array, rec: SplitRecord, gain: jax.Array) -> SplitRecord:
    return SplitRecord(
        gain=best.gain.at[l].set(gain),
        feature=best.feature.at[l].set(rec.feature),
        bin=best.bin.at[l].set(rec.bin),
        default_left=best.default_left.at[l].set(rec.default_left),
        is_cat=best.is_cat.at[l].set(rec.is_cat),
        cat_mask=best.cat_mask.at[l].set(rec.cat_mask),
        left_g=best.left_g.at[l].set(rec.left_g),
        left_h=best.left_h.at[l].set(rec.left_h),
        left_c=best.left_c.at[l].set(rec.left_c),
        right_g=best.right_g.at[l].set(rec.right_g),
        right_h=best.right_h.at[l].set(rec.right_h),
        right_c=best.right_c.at[l].set(rec.right_c),
    )


def _get_best(best: SplitRecord, l: jax.Array) -> SplitRecord:
    return jax.tree.map(lambda a: a[l], best)


def grow_tree(
    bins_fm: jax.Array,  # (F, N) int32 — feature-major bin matrix
    nan_bin: jax.Array,  # (F,)
    num_bins: jax.Array,  # (F,)
    mono: jax.Array,  # (F,)
    is_cat: jax.Array,  # (F,)
    grad: jax.Array,  # (N,) f32
    hess: jax.Array,  # (N,) f32
    mask: jax.Array,  # (N,) f32 — validity * bagging mask
    feat_mask: jax.Array,  # (F,) bool — per-tree feature_fraction sample
    params: SplitParams,
    spec: GrowerSpec,
    valid: Optional[jax.Array] = None,  # (N,) f32 — 1 for real rows; None = all
    bundle: Optional[BundleInfo] = None,
    rng_key: Optional[jax.Array] = None,  # extra_trees / ff_bynode sampling
    group_mat: Optional[jax.Array] = None,  # (NG, F) bool — interaction groups
    cegb: Optional[CegbInfo] = None,
    forced: Optional[Any] = None,  # ForcedSplits plan
    gh_scale: Optional[jax.Array] = None,  # (2,) quantized-level scales
) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree; returns (tree arrays, per-row leaf assignment).

    Dispatches on spec.rounds_slots / spec.partition: "rounds"
    (natural-order round-batched, rounds.py — the TPU fast path),
    "permuted" (leaf-grouped rows, O(segment) per split — the
    reference-exact production path) or "flat" (per-row leaf ids,
    O(N) per split — reference/debug)."""
    if spec.rounds_slots > 0:
        from .rounds import grow_tree_rounds

        return grow_tree_rounds(
            bins_fm, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
            feat_mask, params, spec, valid, bundle, gh_scale,
            rng_key=rng_key, group_mat=group_mat, cegb=cegb,
            forced=forced,
        )
    if spec.partition == "permuted":
        from .permuted import grow_tree_permuted

        return grow_tree_permuted(
            bins_fm, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
            feat_mask, params, spec, valid, bundle, rng_key, group_mat, cegb,
            forced
        )
    if (spec.extra_trees or spec.ff_bynode or spec.cegb or spec.n_groups
            or spec.n_forced):
        raise ValueError(
            "extra_trees / feature_fraction_bynode / cegb / interaction "
            "constraints ride the permuted grower only"
        )
    return _grow_tree_flat(
        bins_fm, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
        feat_mask, params, spec, valid, bundle
    )


@partial(jax.jit, static_argnames=("spec",))
def _grow_tree_flat(
    bins_fm: jax.Array,
    nan_bin: jax.Array,
    num_bins: jax.Array,
    mono: jax.Array,
    is_cat: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,
    feat_mask: jax.Array,
    params: SplitParams,
    spec: GrowerSpec,
    valid: Optional[jax.Array] = None,
    bundle: Optional[BundleInfo] = None,
) -> Tuple[TreeArrays, jax.Array]:
    """Flat row->leaf-id formulation (cuda_data_partition.cu style).

    Padding rows (valid == 0) carry leaf id -1 so they never join a leaf
    or occupy gather capacity; out-of-bag rows (mask 0 but valid 1) are
    partitioned normally for score updates but contribute zero to
    histograms via their zeroed gh channels.
    """
    L = spec.num_leaves
    B = spec.num_bins
    G, N = bins_fm.shape  # G = device columns (bundles when spec.efb)
    ax = spec.axis_name
    caps = hist_capacities(N)
    Bc = spec.col_bins if (spec.efb and spec.col_bins) else B

    fax = spec.feature_axis
    if fax is not None:
        if spec.efb or ax is not None:
            raise ValueError("feature_axis excludes EFB and a data axis")
        my_off = lax.axis_index(fax) * G
        # replicated global tables for winner-record lookups (tiny)
        num_bins_g = lax.all_gather(num_bins, fax).reshape(-1)
        mono_g = lax.all_gather(mono, fax).reshape(-1)
    else:
        my_off = 0
        num_bins_g, mono_g = num_bins, mono

    def select_global(rec: SplitRecord) -> SplitRecord:
        """All-gather each shard's best and keep the max-gain one
        (reference SyncUpGlobalBestSplit allreduce-max,
        parallel_tree_learner.h:209; ties resolve to the lowest shard =
        lowest global feature block)."""
        if fax is None:
            return rec
        rec = rec._replace(feature=rec.feature + my_off)
        stacked = jax.tree.map(lambda a: lax.all_gather(a, fax), rec)
        w = jnp.argmax(stacked.gain)
        return jax.tree.map(lambda a: a[w], stacked)

    def exp_hist(h, g_sum, h_sum, c_sum):
        """Bundle-space histogram -> per-feature for the split scan."""
        if spec.efb:
            return expand_hist(h, g_sum, h_sum, c_sum, bundle)
        return h

    gh8 = build_gh8(grad * mask, hess * mask, mask)  # (8, N)
    root = root_sums(gh8, ax)

    hist0 = histogram(bins_fm, gh8, Bc)
    if ax is not None:
        hist0 = lax.psum(hist0, ax)
    root_out = leaf_output(root[0], root[1], params)
    rec0 = select_global(
        best_split(exp_hist(hist0, root[0], root[1], root[2]),
                   root[0], root[1], root[2], num_bins, nan_bin,
                   mono, is_cat, params, feat_mask,
                   cat_subset=spec.cat_subset, parent_output=root_out))

    hist = jnp.zeros((L, 3, G, Bc), jnp.float32).at[0].set(hist0)
    best = _set_best(_empty_best(L, B), jnp.int32(0), rec0, rec0.gain)

    tree = TreeArrays(
        num_nodes=jnp.int32(0),
        node_feature=jnp.zeros(L - 1, jnp.int32),
        node_bin=jnp.zeros(L - 1, jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_default_left=jnp.zeros(L - 1, bool),
        node_cat=jnp.zeros(L - 1, bool),
        node_cat_mask=jnp.zeros((L - 1, B), bool),
        node_left=jnp.zeros(L - 1, jnp.int32),
        node_right=jnp.zeros(L - 1, jnp.int32),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(leaf_output(root[0], root[1], params)),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_depth=jnp.zeros(L, jnp.int32),
    )

    row_leaf0 = (
        jnp.zeros(N, jnp.int32)
        if valid is None
        else jnp.where(valid > 0, 0, -1).astype(jnp.int32)
    )
    state = _State(
        i=jnp.int32(0),
        row_leaf=row_leaf0,
        hist=hist,
        leaf_g=jnp.zeros(L, jnp.float32).at[0].set(root[0]),
        leaf_h=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_c=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_min=jnp.full(L, -BIG, jnp.float32),
        leaf_max=jnp.full(L, BIG, jnp.float32),
        best=best,
        tree=tree,
    )

    def cond(s: _State) -> jax.Array:
        return (s.i < L - 1) & (jnp.max(s.best.gain) > 0.0)

    def body(s: _State) -> _State:
        i = s.i
        t = s.tree
        l = jnp.argmax(s.best.gain).astype(jnp.int32)
        rec = _get_best(s.best, l)
        new = i + 1  # id of the new (right) leaf

        # ---- tree bookkeeping (Tree::Split semantics) ----
        p = s.leaf_parent[l]
        pc = jnp.maximum(p, 0)
        p_is_left = t.node_left[pc] == ~l
        node_left = t.node_left.at[pc].set(
            jnp.where((p >= 0) & p_is_left, i, t.node_left[pc])
        )
        node_right = t.node_right.at[pc].set(
            jnp.where((p >= 0) & ~p_is_left, i, t.node_right[pc])
        )
        node_left = node_left.at[i].set(~l)
        node_right = node_right.at[i].set(~new)

        pmin, pmax = s.leaf_min[l], s.leaf_max[l]
        lo, ro = split_leaf_outputs(rec, params, num_bins_g, spec.cat_subset,
                                    t.leaf_value[l], pmin, pmax)
        lmin, lmax, rmin, rmax = monotone_child_intervals(
            rec, mono_g, lo, ro, pmin, pmax
        )
        depth_new = t.leaf_depth[l] + 1

        tree_new = TreeArrays(
            num_nodes=new,
            node_feature=t.node_feature.at[i].set(rec.feature),
            node_bin=t.node_bin.at[i].set(rec.bin),
            node_gain=t.node_gain.at[i].set(rec.gain),
            node_default_left=t.node_default_left.at[i].set(rec.default_left),
            node_cat=t.node_cat.at[i].set(rec.is_cat),
            node_cat_mask=t.node_cat_mask.at[i].set(rec.cat_mask),
            node_left=node_left,
            node_right=node_right,
            node_value=t.node_value.at[i].set(t.leaf_value[l]),
            node_weight=t.node_weight.at[i].set(s.leaf_h[l]),
            node_count=t.node_count.at[i].set(s.leaf_c[l]),
            leaf_value=t.leaf_value.at[l].set(lo).at[new].set(ro),
            leaf_weight=t.leaf_weight.at[l].set(rec.left_h).at[new].set(rec.right_h),
            leaf_count=t.leaf_count.at[l].set(rec.left_c).at[new].set(rec.right_c),
            leaf_depth=t.leaf_depth.at[l].set(depth_new).at[new].set(depth_new),
        )

        # ---- partition: update per-row leaf ids (cuda_data_partition.cu) ----
        f = rec.feature  # GLOBAL feature id under feature_axis
        if fax is not None:
            f_loc = jnp.clip(f - my_off, 0, G - 1)
            fbins = lax.dynamic_slice_in_dim(bins_fm, f_loc, 1, axis=0).reshape(N)
            fnan = nan_bin[f_loc]
            gl = jnp.where(
                rec.is_cat,
                rec.cat_mask[fbins],
                (fbins <= rec.bin)
                | (rec.default_left & (fbins == fnan) & (fnan >= 0)),
            )
            mine = (f >= my_off) & (f < my_off + G)
            # only the owning shard's decision counts; broadcast it
            go_left = lax.psum(
                jnp.where(mine, gl, False).astype(jnp.int32), fax
            ) > 0
        else:
            col = bundle.bundle_of[f] if spec.efb else f
            fbins = lax.dynamic_slice_in_dim(bins_fm, col, 1, axis=0).reshape(N)
            if spec.efb:
                fbins = decode_feature_bins(fbins, f, bundle)
            fnan = nan_bin[f]
            go_left = jnp.where(
                rec.is_cat,
                rec.cat_mask[fbins],
                (fbins <= rec.bin)
                | (rec.default_left & (fbins == fnan) & (fnan >= 0)),
            )
        on_leaf = s.row_leaf == l
        row_leaf = jnp.where(on_leaf & ~go_left, new, s.row_leaf)

        # ---- child histograms: smaller by gather/scan, larger by subtraction
        parent_hist = s.hist[l]
        # choose the smaller child by ACTUAL partition counts (incl.
        # out-of-bag rows, which occupy gather capacity). The choice must
        # be GLOBAL when distributed — every shard must scan the same
        # child or the psum mixes left/right histograms.
        n_on_leaf = jnp.sum(on_leaf)
        n_left = jnp.sum(on_leaf & go_left)
        n_right = n_on_leaf - n_left
        if ax is not None:
            left_smaller = lax.psum(n_left, ax) <= lax.psum(n_right, ax)
        else:
            left_smaller = n_left <= n_right
        small_id = jnp.where(left_smaller, l, new)
        if spec.gather_hist:
            on_small = row_leaf == small_id
            # local row count of the globally-chosen child (may exceed N/2
            # on a skewed shard -> full-size fallback bucket)
            cnt_small = jnp.where(left_smaller, n_left, n_right)

            def mk_branch(cap: int):
                def branch(_):
                    idx = jnp.nonzero(on_small, size=cap, fill_value=N)[0]
                    bb = gather_rows(bins_fm, idx)  # (G, cap)
                    gg = gather_gh8(gh8, idx)  # (8, cap)
                    return histogram(bb, gg, Bc)

                return branch

            # smallest capacity >= cnt_small (caps are descending)
            caps_arr = jnp.asarray(caps, jnp.int32)
            bidx = jnp.clip(
                jnp.sum(caps_arr >= cnt_small) - 1, 0, len(caps) - 1
            )
            branches = [mk_branch(c) for c in caps]
            if ax is not None:
                # skewed shard: the globally-smaller child can exceed N/2
                # locally -> full-size fallback
                branches.append(mk_branch(N))
                bidx = jnp.where(cnt_small > caps[0], len(caps), bidx)
            small_hist = lax.switch(bidx, branches, None)
        else:
            on_small_f = (row_leaf == small_id).astype(gh8.dtype)
            small_hist = histogram(bins_fm, gh8 * on_small_f[None, :], Bc)
        if ax is not None:
            small_hist = lax.psum(small_hist, ax)
        large_hist = parent_hist - small_hist
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        hist = s.hist.at[l].set(left_hist).at[new].set(right_hist)

        # ---- best splits for both children ----
        bl = select_global(best_split(
            exp_hist(left_hist, rec.left_g, rec.left_h, rec.left_c),
            rec.left_g, rec.left_h, rec.left_c,
            num_bins, nan_bin, mono, is_cat, params, feat_mask,
            cat_subset=spec.cat_subset, parent_output=lo,
            cmin=lmin, cmax=lmax))
        br = select_global(best_split(
            exp_hist(right_hist, rec.right_g, rec.right_h, rec.right_c),
            rec.right_g, rec.right_h, rec.right_c,
            num_bins, nan_bin, mono, is_cat, params, feat_mask,
            cat_subset=spec.cat_subset, parent_output=ro,
            cmin=rmin, cmax=rmax))
        depth_ok = (spec.max_depth <= 0) | (depth_new < spec.max_depth)
        best2 = _set_best(s.best, l, bl, jnp.where(depth_ok, bl.gain, NEG_INF))
        best2 = _set_best(best2, new, br, jnp.where(depth_ok, br.gain, NEG_INF))

        return _State(
            i=new,
            row_leaf=row_leaf,
            hist=hist,
            leaf_g=s.leaf_g.at[l].set(rec.left_g).at[new].set(rec.right_g),
            leaf_h=s.leaf_h.at[l].set(rec.left_h).at[new].set(rec.right_h),
            leaf_c=s.leaf_c.at[l].set(rec.left_c).at[new].set(rec.right_c),
            leaf_parent=s.leaf_parent.at[l].set(i).at[new].set(i),
            leaf_min=s.leaf_min.at[l].set(lmin).at[new].set(rmin),
            leaf_max=s.leaf_max.at[l].set(lmax).at[new].set(rmax),
            best=best2,
            tree=tree_new,
        )

    final = lax.while_loop(cond, body, state)
    return final.tree, final.row_leaf


@jax.jit
def add_score(score: jax.Array, row_leaf: jax.Array, leaf_value: jax.Array,
              shrinkage: jax.Array) -> jax.Array:
    """ScoreUpdater::AddScore via the partition vector
    (reference score_updater.hpp:21 + data-partition fast path).

    The (N,) lookup from the (L,) leaf table rides the one-hot MXU
    contraction (take_cols): a plain take costs ~8 ms per 1M rows on
    TPU. Invalid rows (row_leaf == -1) contribute 0 on that path."""
    from .histogram import take_cols

    return score + shrinkage * take_cols(leaf_value[None, :], row_leaf)[0]
