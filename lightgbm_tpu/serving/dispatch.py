"""Bucket-batched scoring dispatcher + thread-safe microbatch queue.

Serving traffic arrives in arbitrary batch sizes; a jit cache keyed on
raw shapes would compile once per distinct size (the classic shape-
churn retrace). The dispatcher pads every request up to a small fixed
ladder of row counts, so the number of XLA compiles is bounded by the
ladder length — a contract the retrace guard asserts in
tests/test_serving.py across a 100-request mixed-size sequence
(analysis/retrace.py). Oversized batches are chunked into max-bucket
pieces, so no request shape ever escapes the ladder.

``warmup()`` precompiles every bucket up front (scoring zeros), moving
all compile latency out of the serving path — the analog of the
reference's SingleRowPredictor being built once per model
(c_api.cpp:66), but per shape instead of per row.

``MicroBatcher`` is the queueing half: callers ``submit()`` rows from
any thread and get a Future; a single worker drains the queue,
coalesces pending requests into one padded device call, and fans the
rows of the result back out. Under concurrent small-batch load this
turns q tiny dispatches into one bucket-sized dispatch.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import log
from ..config import DEFAULT_SERVE_BUCKETS as DEFAULT_BUCKETS
from ..obs.metrics import (
    record_bucket_dispatch,
    record_coalesce,
    record_host_fallback,
    record_queue_depth,
    record_serve_rejection,
)
from ..resilience.errors import (
    DeadlineExceeded,
    QueueOverflow,
    ShutdownError,
)
from ..resilience.faultinject import fault_point
from ..timer import latency_stats


class BucketDispatcher:
    """Pads requests to a fixed shape ladder and scores on device."""

    def __init__(self, forest, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 name: str = "serve", model: Optional[str] = None):
        if not buckets:
            raise ValueError("need at least one bucket size")
        n_dev = max(int(getattr(forest, "num_devices", 1)), 1)
        # every rung must shard evenly over the mesh row axis
        aligned = sorted({
            ((max(int(b), 1) + n_dev - 1) // n_dev) * n_dev for b in buckets
        })
        if list(aligned) != sorted(int(b) for b in buckets):
            log.warning(
                f"serving buckets {sorted(int(b) for b in buckets)} "
                f"realigned to {aligned} (mesh of {n_dev} devices needs "
                "row counts divisible by the device count)"
            )
        self.buckets: Tuple[int, ...] = tuple(aligned)
        self.forest = forest
        self.name = name
        # model tags this entry's /metrics series with {model=...}
        # (fleet tenants set it; docs/OBSERVABILITY.md cardinality note)
        self._stats = latency_stats(name, model=model)
        # degradation path (docs/RESILIENCE.md): when a device scoring
        # call faults, a chunk can be rescored by the host tree-walker
        # instead of failing the request. The registry installs this as
        # a closure over the source Booster: (chunk (n,F) f32, start,
        # end) -> (summed raw margins (n,K), leaf indices (n,T) with
        # the used range at columns [start*K, end*K)). None = fail fast.
        self.host_fallback: Optional[
            Callable[[np.ndarray, int, int],
                     Tuple[np.ndarray, np.ndarray]]
        ] = None
        self._fallback_warned = False

    # ------------------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n, else the largest (caller chunks)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def warmup(self, num_features: Optional[int] = None) -> None:
        """Precompile every rung (zeros through the real entry point).

        num_features defaults to the forest's widest referenced feature
        + 1 — pass the true dataset width when it is larger, otherwise
        the serving path would compile again on the first real batch.
        """
        import jax.numpy as jnp

        F = max(self.forest.max_feature + 1, 1) \
            if num_features is None else int(num_features)
        tw = np.ones(self.forest.num_trees, np.float32)
        for b in self.buckets:
            score, _leaf = self.forest.apply(
                jnp.zeros((b, F), jnp.float32), tw
            )
            score.block_until_ready()

    # ------------------------------------------------------------------
    def _bucketed_chunks(self, X: np.ndarray, tw: np.ndarray,
                         start: int = 0, end: int = 0):
        """Yield (score (n,K), leaf (n,T)) per max-bucket chunk, each
        scored at its padded ladder shape — EVERY device call in the
        dispatcher goes through here, so no request shape escapes the
        ladder (the bounded-compiles contract covers pred_leaf too).

        A device fault mid-chunk (the ``device_put`` fault-injection
        site models one) degrades THAT chunk to the host tree-walker
        when ``host_fallback`` is installed: slower, metric-counted,
        warned once — but the request still answers (parity is
        regression-tested in tests/test_resilience.py)."""
        import jax.numpy as jnp

        N = X.shape[0]
        top = self.buckets[-1]
        pos = 0
        while pos < N:
            chunk = X[pos: pos + top]
            rows = chunk.shape[0]
            b = self.bucket_for(rows)
            record_bucket_dispatch(self.name, b, rows)
            if rows < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - rows, X.shape[1]), np.float32)]
                )
            try:
                fault_point("device_put")
                score, leaf = self.forest.apply(jnp.asarray(chunk), tw)
                out = np.asarray(score)[:rows], np.asarray(leaf)[:rows]
            except Exception:  # noqa: BLE001 — any device-path fault
                if self.host_fallback is None:
                    raise
                if not self._fallback_warned:
                    self._fallback_warned = True
                    log.warning(
                        f"device scoring fault on entry "
                        f"{self.name!r}; degrading faulted chunks to "
                        "the host tree-walker (slower; counted in "
                        "lgbmtpu_serve_host_fallback_total)"
                    )
                record_host_fallback(self.name)
                s, lf = self.host_fallback(chunk[:rows], start, end)
                out = (
                    np.asarray(s, np.float32),
                    np.asarray(lf)[:rows],
                )
            yield out
            pos += top

    def _prep(self, X, start_iteration: int, num_iteration: int):
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        self.forest._check_width(X)
        tw, start, end = self.forest._tree_weights(
            start_iteration, num_iteration
        )
        return X, tw, start, end

    def score_raw(self, X: np.ndarray, start_iteration: int = 0,
                  num_iteration: int = -1) -> np.ndarray:
        """(K, N) raw margins via bucket-padded device calls."""
        X, tw, start, end = self._prep(X, start_iteration, num_iteration)
        if X.shape[0] == 0:  # filtered-empty request, not an error
            return np.zeros((self.forest.num_class, 0), np.float64)
        t0 = time.perf_counter()
        outs = [s for s, _ in self._bucketed_chunks(X, tw, start, end)]
        out = np.concatenate(outs).T.astype(np.float64)  # (K, N)
        if self.forest.average_output and end > start:
            out /= end - start
        self._stats.observe(time.perf_counter() - t0, X.shape[0])
        return out

    def predict_leaf(self, X: np.ndarray, start_iteration: int = 0,
                     num_iteration: int = -1) -> np.ndarray:
        """(N, used_trees) leaf indices through the same bucket ladder
        (a raw-shape forest.apply here would reintroduce the per-shape
        compile churn the ladder exists to bound)."""
        X, tw, start, end = self._prep(X, start_iteration, num_iteration)
        K = self.forest.num_class
        if X.shape[0] == 0:
            return np.zeros((0, (end - start) * K), np.int64)
        t0 = time.perf_counter()
        leaves = [lf for _, lf in self._bucketed_chunks(X, tw, start, end)]
        out = np.concatenate(leaves)[:, start * K: end * K]
        self._stats.observe(time.perf_counter() - t0, X.shape[0])
        return out.astype(np.int64)

    def predict_contrib(self, X: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1) -> np.ndarray:
        """(N, K*(F+1)) SHAP contributions (Booster pred_contrib
        layout) through the ladder. Contrib intermediates scale with
        rows x trees x leaves x path length, so the contrib ladder is
        capped at ``CONTRIB_MAX_ROWS`` — large requests chunk through
        the capped top rung. No host fallback: a device fault fails
        the explanation request (scoring traffic is the degradation-
        protected path; explanations re-raise)."""
        import jax.numpy as jnp

        X, tw, start, end = self._prep(X, start_iteration, num_iteration)
        F = X.shape[1]
        K = self.forest.num_class
        if X.shape[0] == 0:
            return np.zeros((0, K * (F + 1)), np.float64)
        t0 = time.perf_counter()
        top = min(self.buckets[-1], CONTRIB_MAX_ROWS)
        rungs = [b for b in self.buckets if b <= top] or [top]
        outs = []
        N, pos = X.shape[0], 0
        while pos < N:
            chunk = X[pos: pos + top]
            rows = chunk.shape[0]
            b = next((r for r in rungs if rows <= r), rungs[-1])
            record_bucket_dispatch(f"{self.name}:contrib", b, rows)
            if rows < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - rows, F), np.float32)]
                )
            out = self.forest.apply_contrib(jnp.asarray(chunk), tw)
            outs.append(np.asarray(out)[:rows])
            pos += top
        out = np.concatenate(outs).astype(np.float64)
        if self.forest.average_output and end > start:
            out /= end - start
        self._stats.observe(time.perf_counter() - t0, N)
        return out

    def stats(self) -> dict:
        return self._stats.snapshot()


# cap on rows per device TreeSHAP call: contrib intermediates are
# (rows, trees, leaves, path) tensors, ~leaves x path larger per row
# than scoring — the top scoring rung would not fit comfortably
CONTRIB_MAX_ROWS = 256


class MicroBatcher:
    """Thread-safe request queue in front of one or more
    BucketDispatchers.

    submit(rows) -> Future resolving to that request's (n, K) scores.
    One worker thread PER DISPATCHER drains a shared queue: everything
    pending (up to the largest bucket) coalesces into a single padded
    device call. With replica dispatchers this is the continuous-
    batching front: while replica 0's batch is in flight on its
    device, replica 1's worker is already coalescing and admitting the
    next batch — requests never wait for a previous batch to land
    (docs/SERVING.md "Fleet serving").

    Overload handling (docs/RESILIENCE.md "Serving degradation"):

    - ``queue_cap`` bounds the ROWS admitted to the queue; a submit
      past the cap fast-fails with :class:`QueueOverflow` in the
      caller's thread (the HTTP transport maps it to 503 +
      Retry-After) instead of growing an unbounded backlog whose tail
      latency is already hopeless.
    - ``deadline_s`` (per-instance default, overridable per submit)
      bounds time-in-queue: the worker sweeps expired requests on
      every drain and fails them with :class:`DeadlineExceeded` (HTTP
      504) without spending a device call on them. A request already
      coalesced into a device call is never cancelled.
    - ``close()`` fails everything still queued with
      :class:`ShutdownError` — a shutdown must never leave a caller
      blocked forever on ``Future.result()``.
    """

    def __init__(self, dispatcher, max_delay_s: float = 0.002,
                 deadline_s: float = 0.0,
                 queue_cap: int = 0):
        # a single dispatcher (anything duck-typing BucketDispatcher)
        # or a list/tuple of replicas sharing identical model + ladder
        # (the registry builds the replica list)
        if isinstance(dispatcher, (list, tuple)):
            self.dispatchers: Tuple[BucketDispatcher, ...] = tuple(dispatcher)
        else:
            self.dispatchers = (dispatcher,)
        if not self.dispatchers:
            raise ValueError("MicroBatcher needs at least one dispatcher")
        self.dispatcher = self.dispatchers[0]  # primary (stats, width)
        self.max_delay_s = float(max_delay_s)
        self.deadline_s = float(deadline_s)  # 0 = no default deadline
        self.queue_cap = int(queue_cap)      # rows; 0 = unbounded
        # entries are (X, future, expiry | None) in monotonic time
        self._pending: List[Tuple[np.ndarray, Future,
                                  Optional[float]]] = []
        self._pending_rows = 0
        self._cond = threading.Condition()
        self._closed = False
        self._workers = [
            threading.Thread(
                target=self._run, args=(d,),
                name=f"lgb-serve-microbatch-{i}", daemon=True,
            )
            for i, d in enumerate(self.dispatchers)
        ]
        for w in self._workers:
            w.start()

    def submit(self, X: np.ndarray,
               deadline_s: Optional[float] = None) -> Future:
        """Queue rows for coalesced default-parameter scoring; resolves
        to that request's (n, K) RAW margins. Non-default scoring
        options (truncation, pred_leaf) go through the dispatcher
        directly — requests in one coalesced batch must share one
        parameter set. ``deadline_s`` overrides the instance default
        (<= 0 disables the deadline for this request)."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        # validate in the submitter's thread: a malformed request must
        # fail ITS caller, never the innocent requests it would have
        # been coalesced with
        self.dispatcher.forest._check_width(X)
        dl = self.deadline_s if deadline_s is None else float(deadline_s)
        expiry = time.monotonic() + dl if dl > 0 else None
        fut: Future = Future()
        try:
            with self._cond:
                if self._closed:
                    raise ShutdownError("MicroBatcher is closed")
                # admission control: reject while a backlog exists (a
                # single request larger than the cap is still admitted
                # into an EMPTY queue — it chunks through the ladder)
                if (self.queue_cap > 0 and self._pending
                        and self._pending_rows + X.shape[0]
                        > self.queue_cap):
                    raise QueueOverflow(
                        f"microbatch queue full "
                        f"({self._pending_rows} rows queued, "
                        f"cap {self.queue_cap})"
                    )
                self._pending.append((X, fut, expiry))
                self._pending_rows += X.shape[0]
                depth = len(self._pending)
                self._cond.notify()
        except QueueOverflow:
            # counter outside the condition: the metrics registry has
            # its own lock and must not nest under the queue's
            record_serve_rejection(self.dispatcher.name, "overloaded")
            raise
        record_queue_depth(self.dispatcher.name, depth)
        return fut

    def close(self) -> None:
        """Stop the worker and fail anything still pending with
        ShutdownError. The worker drains the queue on the way out; the
        explicit sweep below only matters when it cannot finish within
        the join timeout (e.g. wedged mid-device-call) — futures must
        fail, not hang their callers forever."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for w in self._workers:
            w.join(timeout=5)
        with self._cond:
            leftovers = self._pending
            self._pending = []
            self._pending_rows = 0
        for _, fut, _ in leftovers:  # outside the lock: may run callbacks
            if not fut.done():
                fut.set_exception(
                    ShutdownError("MicroBatcher closed before scoring")
                )

    # ------------------------------------------------------------------
    def _sweep_expired_locked(
        self, now: float
    ) -> List[Tuple[np.ndarray, Future, Optional[float]]]:
        """Pop expired entries (caller holds the condition; the popped
        futures are failed OUTSIDE the lock — done-callbacks may run)."""
        expired = [e for e in self._pending
                   if e[2] is not None and now >= e[2]]
        if expired:
            # both callers hold self._cond (the _locked suffix is the
            # contract; the per-function lint cannot see the call sites)
            self._pending = [e for e in self._pending  # lint: allow[unlocked-write]
                             if e[2] is None or now < e[2]]
            self._pending_rows = sum(  # lint: allow[unlocked-write]
                e[0].shape[0] for e in self._pending
            )
        return expired

    def _run(self, dispatcher: BucketDispatcher) -> None:
        top = dispatcher.buckets[-1]
        while True:
            expired: List[Tuple[np.ndarray, Future, Optional[float]]] = []
            batch: List[Tuple[np.ndarray, Future]] = []
            rows = 0
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                expired = self._sweep_expired_locked(time.monotonic())
                # brief linger so near-simultaneous submitters coalesce
                if (len(self._pending) == 1
                        and self._pending[0][0].shape[0] < top
                        and not self._closed):
                    self._cond.wait(self.max_delay_s)
                    expired += self._sweep_expired_locked(
                        time.monotonic()
                    )
                if self._pending:
                    # coalesce only same-width requests (widths >= the
                    # model's widest feature are all valid, so a mixed
                    # queue would break np.concatenate); stragglers
                    # stay pending for the next drain
                    width = self._pending[0][0].shape[1]
                    while (self._pending and rows < top
                           and self._pending[0][0].shape[1] == width):
                        X, fut, _ = self._pending.pop(0)
                        self._pending_rows -= X.shape[0]
                        batch.append((X, fut))
                        rows += X.shape[0]
                depth = len(self._pending)
            for _, fut, _ in expired:
                record_serve_rejection(dispatcher.name, "deadline")
                if not fut.done():
                    fut.set_exception(DeadlineExceeded(
                        "request expired in the microbatch queue"
                    ))
            if not batch:
                continue
            record_queue_depth(dispatcher.name, depth)
            record_coalesce(dispatcher.name, len(batch), rows)
            try:
                Xall = np.concatenate([x for x, _ in batch]) \
                    if len(batch) > 1 else batch[0][0]
                out = dispatcher.score_raw(Xall)  # (K, N)
                pos = 0
                for X, fut in batch:
                    n = X.shape[0]
                    fut.set_result(out[:, pos: pos + n].T)  # (n, K)
                    pos += n
            except Exception as e:  # noqa: BLE001 — fan the error out
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
