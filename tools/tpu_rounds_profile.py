"""Bisect grow_tree_rounds device cost on a live chip.

The bench shows ~1.0 s/tree steady while the S=25 histogram pass is
only ~12.5 ms (tools/tpu_hist_sweep.py) — so ~0.8 s/tree lives in the
round body outside the hist kernel. This times each candidate in-jit
(R data-dependent reps, one readback), mirroring the sweep methodology.

Pieces:
  full_tree       — grow_tree_rounds end to end
  best_split_x50  — the vmapped child split search (2S = 50 leaves)
  partition_upd   — the per-row split decision + pleaf update
  hist_scatter    — the (L,3,G,Bc) pool double scatter
  traverse_valid  — validation-set tree traversal (per-tree loop cost)
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params
    from lightgbm_tpu.learner.histogram import build_gh8, hist_nat_slots
    from lightgbm_tpu.learner.split import best_split

    print(json.dumps({"platform": jax.devices()[0].platform}), flush=True)

    rs = np.random.RandomState(0)
    N, F, B, L, S = 999424, 28, 256, 255, 25
    X = rs.randn(N, F).astype(np.float32)
    cfg = Config({"max_bin": 255, "min_data_in_leaf": 20})
    ds = BinnedDataset.from_numpy(X, cfg)
    d = ds.device_arrays()
    Np = ds.num_rows_padded()
    grad = jnp.asarray(rs.randn(Np).astype(np.float32)) * d["valid"]
    hess = jnp.ones(Np, jnp.float32) * 0.25 * d["valid"]
    params = make_split_params(cfg)
    fm = jnp.ones(ds.num_used_features, bool)
    gh8 = build_gh8(grad, hess, d["valid"])
    slot = jnp.asarray(rs.randint(0, S + 1, Np).astype(np.int32))

    def timed(make_body, R=5):
        def loop():
            def body(_, acc):
                return make_body(acc)

            return lax.fori_loop(0, R, body, jnp.float32(0.0))

        f = jax.jit(loop)
        float(f())
        t0 = time.time()
        float(f())
        return (time.time() - t0) / R

    def report(name, t, note=""):
        print(json.dumps({"metric": name, "value": round(t * 1e3, 1),
                          "note": note}), flush=True)

    # baseline chain
    t_base = timed(lambda acc: acc + (grad + acc * 0.0)[0])
    report("baseline_ms", t_base)

    # ---- full tree ----
    spec = GrowerSpec(num_leaves=L, num_bins=ds.max_num_bin, max_depth=-1,
                      rounds_slots=S)

    def tree_body(acc):
        t_, rl = grow_tree(
            d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
            grad + acc * 0.0, hess, d["valid"], fm, params, spec,
            valid=d["valid"],
        )
        return acc + t_.leaf_value[0]

    report("full_tree_ms", timed(tree_body, R=3) - t_base, "255 leaves S=25")

    # ---- best_split vmapped over 50 children ----
    hist50 = jnp.asarray(rs.rand(50, 3, F, B).astype(np.float32))
    gsum = jnp.asarray(rs.randn(50).astype(np.float32))
    hsum = jnp.abs(jnp.asarray(rs.randn(50).astype(np.float32))) + 1.0
    csum = jnp.full(50, 1000.0)

    def bs_body(acc):
        h = hist50 + acc * 0.0

        def one(hh, g_, h_, c_):
            return best_split(hh, g_, h_, c_, d["num_bins"], d["nan_bin"],
                              d["mono"], d["is_cat"], params, fm,
                              cat_subset=spec.cat_subset,
                              parent_output=jnp.float32(0.0))

        rec = jax.vmap(one)(h, gsum, hsum, csum)
        return acc + rec.gain[0]

    report("best_split_x50_ms", timed(bs_body) - t_base)

    # ---- partition update (per-row decision) ----
    pleaf = jnp.asarray(rs.randint(0, L, Np).astype(np.int32))
    feat_of_leaf = jnp.asarray(rs.randint(0, F, L).astype(np.int32))
    bin_of_leaf = jnp.asarray(rs.randint(0, B, L).astype(np.int32))
    sel = jnp.zeros(L, bool).at[jnp.arange(S)].set(True)
    new_id = jnp.asarray(rs.randint(0, L, L).astype(np.int32))

    def part_body(acc):
        pl_c = jnp.minimum(pleaf + jnp.int32(acc * 0.0), L - 1)
        f_row = feat_of_leaf[pl_c]
        col_sel = f_row[None, :] == jnp.arange(F, dtype=jnp.int32)[:, None]
        fbins = jnp.sum(jnp.where(col_sel, d["bins"], 0), axis=0)
        go_left = fbins <= bin_of_leaf[pl_c]
        in_split = sel[pl_c]
        out = jnp.where(in_split & ~go_left, new_id[pl_c], pleaf)
        return acc + out[0].astype(jnp.float32)

    report("partition_upd_ms", timed(part_body) - t_base)

    # ---- hist pool scatter ----
    pool = jnp.zeros((L, 3, F, B), jnp.float32)
    block = jnp.asarray(rs.rand(S, 3, F, B).astype(np.float32))
    sel_leaf = jnp.asarray(rs.choice(L, S, replace=False).astype(np.int32))

    def scat_body(acc):
        p = pool.at[sel_leaf + jnp.int32(acc * 0.0)].set(block, mode="drop")
        p = p.at[jnp.minimum(sel_leaf + 1, L - 1)].set(block, mode="drop")
        return acc + p[0, 0, 0, 0]

    report("hist_scatter_ms", timed(scat_body) - t_base)

    # ---- nat hist pass (control; should match sweep) ----
    def hist_body(acc):
        out = hist_nat_slots(d["bins"], gh8 + acc * 0.0, slot, S, B)
        return acc + out[0, 0, 0, 0]

    report("hist_nat_S25_ms", timed(hist_body) - t_base)

    # ---- valid traversal ----
    from lightgbm_tpu.learner.grower import TreeArrays
    from lightgbm_tpu.boosting import traverse_tree_bins

    nv = 100_096
    Xv = rs.randn(nv, F).astype(np.float32)
    dsv = BinnedDataset.from_numpy(Xv, cfg)
    dv = dsv.device_arrays()
    tree = TreeArrays(
        num_nodes=jnp.int32(L - 1),
        node_feature=jnp.asarray(rs.randint(0, F, L - 1).astype(np.int32)),
        node_bin=jnp.asarray(rs.randint(0, B, L - 1).astype(np.int32)),
        node_gain=jnp.ones(L - 1, jnp.float32),
        node_default_left=jnp.zeros(L - 1, bool),
        node_cat=jnp.zeros(L - 1, bool),
        node_cat_mask=jnp.zeros((L - 1, B), bool),
        node_left=jnp.asarray((~np.arange(L - 1)).astype(np.int32)),
        node_right=jnp.asarray((~(np.arange(L - 1) + 1)).astype(np.int32)),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.ones(L - 1, jnp.float32),
        node_count=jnp.ones(L - 1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32),
        leaf_weight=jnp.ones(L, jnp.float32),
        leaf_count=jnp.ones(L, jnp.float32),
        leaf_depth=jnp.ones(L, jnp.int32),
    )

    def trav_body(acc):
        lf = traverse_tree_bins(
            tree._replace(leaf_value=tree.leaf_value + acc * 0.0),
            dv["bins"], dv["nan_bin"], dv.get("bundle"),
        )
        return acc + lf[0].astype(jnp.float32)

    report("traverse_valid100k_ms", timed(trav_body) - t_base)

    # ---- device AUC eval on the valid set ----
    from lightgbm_tpu.device_metrics import DeviceEvalSet

    yv = jnp.asarray((rs.rand(dsv.num_rows_padded()) > 0.5).astype(np.float32))
    des = DeviceEvalSet(cfg, ["auc"], [True], yv, None, dv["valid"], 1)
    sc = jnp.asarray(rs.randn(1, dsv.num_rows_padded()).astype(np.float32))

    def auc_body(acc):
        row = des(sc + acc * 0.0)
        return acc + row[0]

    report("device_auc100k_ms", timed(auc_body) - t_base)

    # ---- add_score (train-score update via row->leaf gather) ----
    from lightgbm_tpu.boosting import add_score

    score0 = jnp.zeros(Np, jnp.float32)
    lv = jnp.asarray(rs.randn(L).astype(np.float32))

    def addsc_body(acc):
        s = add_score(score0 + acc * 0.0, pleaf, lv, jnp.float32(1.0))
        return acc + s[0]

    report("add_score1M_ms", timed(addsc_body) - t_base)

    # ---- binary-objective-shaped gradients over 1M (sigmoid math) ----
    lab = jnp.asarray((rs.rand(Np) > 0.5).astype(np.float32))

    def grad_body(acc):
        s = score0 + acc * 0.0
        p = jax.nn.sigmoid(s)
        g_ = (p - lab)
        h_ = p * (1.0 - p)
        return acc + g_[0] + h_[0]

    report("binary_grads1M_ms", timed(grad_body) - t_base)


if __name__ == "__main__":
    main()
