"""Micro-benchmarks for the TPU histogram kernels and growers.

Run on a live chip; prints one JSON line per measurement. Used to tune
the slot-packed kernel and record per-phase timings in BENCH_NOTES.md.
"""

import json
import sys
import time

import numpy as np


def sync(x):
    import jax

    jax.block_until_ready(x)
    return x


def timeit(fn, *args, reps=5, warmup=2):
    for _ in range(warmup):
        sync(fn(*args))
    t0 = time.time()
    for _ in range(reps):
        sync(fn(*args))
    return (time.time() - t0) / reps


def main() -> None:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, "/root/repo")
    from lightgbm_tpu.learner.histogram import (
        HIST_BLK,
        build_gh8,
        build_gh8_quant,
        hist_nat_slots,
        histogram,
    )

    platform = jax.devices()[0].platform
    print(json.dumps({"metric": "platform", "value": platform}), flush=True)
    if platform != "tpu":
        return

    rs = np.random.RandomState(0)
    N = 489 * HIST_BLK  # ~1M rows, HIGGS-like
    F, B = 28, 256
    bins = jnp.asarray(rs.randint(0, 255, (F, N)).astype(np.int32))
    g = jnp.asarray(rs.randn(N).astype(np.float32))
    h = jnp.asarray((rs.rand(N) * 0.25).astype(np.float32))
    ones = jnp.ones(N, jnp.float32)
    gh8 = build_gh8(g, h, ones)
    slot25 = jnp.asarray(rs.randint(0, 26, N).astype(np.int32))
    slot1 = jnp.zeros(N, jnp.int32)

    t = timeit(lambda: histogram(bins, gh8, B))
    print(json.dumps({"metric": "hist_full_M8_ms", "value": round(t * 1e3, 2),
                      "note": f"{N}x{F} single-leaf pass"}), flush=True)

    t = timeit(lambda: hist_nat_slots(bins, gh8, slot25, 25, B))
    print(json.dumps({"metric": "hist_nat_25slots_ms",
                      "value": round(t * 1e3, 2),
                      "note": "slot-packed M=125"}), flush=True)

    t = timeit(lambda: hist_nat_slots(bins, gh8, slot1, 1, B))
    print(json.dumps({"metric": "hist_nat_1slot_ms",
                      "value": round(t * 1e3, 2)}), flush=True)

    gq = jnp.asarray(rs.randint(-2, 3, N).astype(np.float32))
    hq = jnp.asarray(rs.randint(0, 5, N).astype(np.float32))
    gh8q = build_gh8_quant(gq, hq, ones)
    slot42 = jnp.asarray(rs.randint(0, 43, N).astype(np.int32))
    t = timeit(lambda: hist_nat_slots(bins, gh8q, slot42, 42, B, quant=True))
    print(json.dumps({"metric": "hist_nat_quant_42slots_ms",
                      "value": round(t * 1e3, 2),
                      "note": "3 int channels M=126"}), flush=True)

    # one full tree: rounds grower vs exact at 255 leaves
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params

    X = rs.randn(N, F).astype(np.float32)
    w = rs.randn(F)
    cfg = Config({"max_bin": 255, "min_data_in_leaf": 20})
    ds = BinnedDataset.from_numpy(X, cfg)
    d = ds.device_arrays()
    Np = ds.num_rows_padded()
    grad = jnp.asarray(rs.randn(Np).astype(np.float32)) * d["valid"]
    hess = jnp.ones(Np, jnp.float32) * 0.25 * d["valid"]
    params = make_split_params(cfg)
    fm = jnp.ones(ds.num_used_features, bool)

    for name, kw in (
        ("tree_rounds25_ms", dict(rounds_slots=25)),
        ("tree_exact_ms", dict()),
    ):
        spec = GrowerSpec(num_leaves=255, num_bins=ds.max_num_bin,
                          max_depth=-1, **kw)

        def run(spec=spec):
            t_, rl = grow_tree(
                d["bins"], d["nan_bin"], d["num_bins"], d["mono"],
                d["is_cat"], grad, hess, d["valid"], fm, params, spec,
                valid=d["valid"],
            )
            return rl

        t = timeit(run, reps=3, warmup=1)
        print(json.dumps({"metric": name, "value": round(t * 1e3, 1),
                          "note": "255 leaves, 1M x 28"}), flush=True)


if __name__ == "__main__":
    main()
