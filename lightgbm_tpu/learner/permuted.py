"""Leaf-wise tree growth over a physically permuted bin matrix.

This is the TPU formulation of the reference's index-list partition
(src/treelearner/data_partition.hpp: rows stored grouped by leaf as one
permuted array + per-leaf (begin, count)): the bin matrix, channel
matrix, and a row-origin vector are kept PHYSICALLY reordered so every
leaf occupies a contiguous segment. Each split then costs O(parent
segment), not O(N):

- stable partition of the parent segment (ParallelPartitionRunner /
  cuda_data_partition.cu SplitInner): two `nonzero` compactions over a
  static-capacity slice + one gather + one dynamic_update_slice;
- the smaller child's histogram reads a CONTIGUOUS slice (no row
  gather, no full-N mask), the larger sibling comes from parent
  subtraction as in serial_tree_learner.cpp:411;
- total per-tree work matches the reference's sum-of-segment-sizes
  (~depth x N), where the flat row->leaf formulation pays O(N) per
  split (254x N for a 255-leaf tree).

Static shapes come from a capacity ladder (N, N/2, ..., HIST_BLK):
every segment operation runs at the smallest capacity that covers the
segment, with rows outside the segment masked / passed through
untouched.

With `axis_name` set, rows are sharded; histograms and the
smaller-child choice are psum'd (data_parallel_tree_learner.cpp:286)
while each shard stable-partitions its local segment in lockstep.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import HIST_BLK, build_gh8, histogram, root_sums
from .split import BIG, NEG_INF, SplitParams, SplitRecord, best_split, leaf_output
from .grower import (
    GrowerSpec,
    TreeArrays,
    _empty_best,
    _get_best,
    _set_best,
    monotone_child_intervals,
    split_leaf_outputs,
)


def segment_caps(n_rows: int) -> tuple:
    """Static ladder of segment capacities: N, N/2, ..., >= HIST_BLK,
    all HIST_BLK multiples (n_rows itself must already be one)."""
    caps = []
    c = n_rows
    while c >= HIST_BLK:
        caps.append(((c + HIST_BLK - 1) // HIST_BLK) * HIST_BLK)
        c //= 2
    if not caps:
        caps.append(n_rows)
    return tuple(caps)


class _PState(NamedTuple):
    i: jax.Array
    pbins: jax.Array  # (F, N) int32, leaf-grouped along the row (lane) axis
    pgh: jax.Array  # (8, N) f32, leaf-grouped (build_gh8 channels)
    pperm: jax.Array  # (N,) int32 — original row index at each position
    seg_begin: jax.Array  # (L,) int32; unused leaves = N (sorts last)
    seg_count: jax.Array  # (L,) int32
    hist: jax.Array  # (L, 3, F, B) — channel-leading, bins on lanes
    leaf_g: jax.Array
    leaf_h: jax.Array
    leaf_c: jax.Array
    leaf_parent: jax.Array
    leaf_min: jax.Array  # (L,) monotone-constraint interval per leaf
    leaf_max: jax.Array
    best: SplitRecord
    tree: TreeArrays


def _go_left(fbins, rec, fnan):
    return jnp.where(
        rec.is_cat,
        rec.cat_mask[fbins],
        (fbins <= rec.bin) | (rec.default_left & (fbins == fnan) & (fnan >= 0)),
    )


@partial(jax.jit, static_argnames=("spec",))
def grow_tree_permuted(
    bins_fm: jax.Array,  # (F, N) int32
    nan_bin: jax.Array,
    num_bins: jax.Array,
    mono: jax.Array,
    is_cat: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,  # validity * bagging
    feat_mask: jax.Array,
    params: SplitParams,
    spec: GrowerSpec,
    valid: Optional[jax.Array] = None,
) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree; returns (tree arrays, natural-order row->leaf)."""
    L = spec.num_leaves
    B = spec.num_bins
    F, N = bins_fm.shape
    ax = spec.axis_name
    caps = segment_caps(N)

    gh8 = build_gh8(grad * mask, hess * mask, mask)  # (8, N)
    root = root_sums(gh8, ax)

    hist0 = histogram(bins_fm, gh8, B)
    if ax is not None:
        hist0 = lax.psum(hist0, ax)
    root_out = leaf_output(root[0], root[1], params)
    rec0 = best_split(hist0, root[0], root[1], root[2], num_bins, nan_bin,
                      mono, is_cat, params, feat_mask,
                      cat_subset=spec.cat_subset, parent_output=root_out)

    hist = jnp.zeros((L, 3, F, B), jnp.float32).at[0].set(hist0)
    best = _set_best(_empty_best(L, B), jnp.int32(0), rec0, rec0.gain)

    tree = TreeArrays(
        num_nodes=jnp.int32(0),
        node_feature=jnp.zeros(L - 1, jnp.int32),
        node_bin=jnp.zeros(L - 1, jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_default_left=jnp.zeros(L - 1, bool),
        node_cat=jnp.zeros(L - 1, bool),
        node_cat_mask=jnp.zeros((L - 1, B), bool),
        node_left=jnp.zeros(L - 1, jnp.int32),
        node_right=jnp.zeros(L - 1, jnp.int32),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(leaf_output(root[0], root[1], params)),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_depth=jnp.zeros(L, jnp.int32),
    )

    valid_f = jnp.ones(N, jnp.float32) if valid is None else valid
    n_valid = jnp.sum(valid_f > 0).astype(jnp.int32)  # local (shard) count

    state = _PState(
        i=jnp.int32(0),
        pbins=bins_fm,
        pgh=gh8,
        pperm=jnp.arange(N, dtype=jnp.int32),
        seg_begin=jnp.full(L, N, jnp.int32).at[0].set(0),
        seg_count=jnp.zeros(L, jnp.int32).at[0].set(n_valid),
        hist=hist,
        leaf_g=jnp.zeros(L, jnp.float32).at[0].set(root[0]),
        leaf_h=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_c=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_min=jnp.full(L, -BIG, jnp.float32),
        leaf_max=jnp.full(L, BIG, jnp.float32),
        best=best,
        tree=tree,
    )

    def cond(s: _PState) -> jax.Array:
        return (s.i < L - 1) & (jnp.max(s.best.gain) > 0.0)

    def body(s: _PState) -> _PState:
        i = s.i
        t = s.tree
        l = jnp.argmax(s.best.gain).astype(jnp.int32)
        rec = _get_best(s.best, l)
        new = i + 1

        # ---- tree bookkeeping (Tree::Split semantics, same as flat) ----
        p = s.leaf_parent[l]
        pc = jnp.maximum(p, 0)
        p_is_left = t.node_left[pc] == ~l
        node_left = t.node_left.at[pc].set(
            jnp.where((p >= 0) & p_is_left, i, t.node_left[pc])
        )
        node_right = t.node_right.at[pc].set(
            jnp.where((p >= 0) & ~p_is_left, i, t.node_right[pc])
        )
        node_left = node_left.at[i].set(~l)
        node_right = node_right.at[i].set(~new)

        pmin, pmax = s.leaf_min[l], s.leaf_max[l]
        lo, ro = split_leaf_outputs(rec, params, num_bins, spec.cat_subset,
                                    t.leaf_value[l], pmin, pmax)
        lmin, lmax, rmin, rmax = monotone_child_intervals(
            rec, mono, lo, ro, pmin, pmax
        )
        depth_new = t.leaf_depth[l] + 1

        tree_new = TreeArrays(
            num_nodes=new,
            node_feature=t.node_feature.at[i].set(rec.feature),
            node_bin=t.node_bin.at[i].set(rec.bin),
            node_gain=t.node_gain.at[i].set(rec.gain),
            node_default_left=t.node_default_left.at[i].set(rec.default_left),
            node_cat=t.node_cat.at[i].set(rec.is_cat),
            node_cat_mask=t.node_cat_mask.at[i].set(rec.cat_mask),
            node_left=node_left,
            node_right=node_right,
            node_value=t.node_value.at[i].set(t.leaf_value[l]),
            node_weight=t.node_weight.at[i].set(s.leaf_h[l]),
            node_count=t.node_count.at[i].set(s.leaf_c[l]),
            leaf_value=t.leaf_value.at[l].set(lo).at[new].set(ro),
            leaf_weight=t.leaf_weight.at[l].set(rec.left_h).at[new].set(rec.right_h),
            leaf_count=t.leaf_count.at[l].set(rec.left_c).at[new].set(rec.right_c),
            leaf_depth=t.leaf_depth.at[l].set(depth_new).at[new].set(depth_new),
        )

        b = s.seg_begin[l]
        c = s.seg_count[l]
        fnan = nan_bin[rec.feature]

        # ---- stable partition of segment [b, b+c) at capacity cap ----
        def mk_part(cap: int):
            def part(_):
                start = jnp.clip(b, 0, N - cap)
                off = b - start
                sbins = lax.dynamic_slice(s.pbins, (jnp.int32(0), start), (F, cap))
                sgh = lax.dynamic_slice(s.pgh, (jnp.int32(0), start), (8, cap))
                sperm = lax.dynamic_slice(s.pperm, (start,), (cap,))
                iota = jnp.arange(cap, dtype=jnp.int32)
                in_seg = (iota >= off) & (iota < off + c)
                fcol = lax.dynamic_slice(
                    sbins, (rec.feature, jnp.int32(0)), (1, cap)
                ).reshape(cap)
                gl = _go_left(fcol, rec, fnan)
                sel_l = in_seg & gl
                n_l = jnp.sum(sel_l).astype(jnp.int32)
                lidx = jnp.nonzero(sel_l, size=cap, fill_value=cap)[0]
                ridx = jnp.nonzero(in_seg & ~gl, size=cap, fill_value=cap)[0]
                rel = iota - off
                src = jnp.where(
                    rel < n_l,
                    jnp.take(lidx, jnp.clip(rel, 0, cap - 1), mode="clip"),
                    jnp.take(ridx, jnp.clip(rel - n_l, 0, cap - 1), mode="clip"),
                )
                src = jnp.where(in_seg, src, iota)
                nb = jnp.take(sbins, src, axis=1, mode="clip")
                ng = jnp.take(sgh, src, axis=1, mode="clip")
                npm = jnp.take(sperm, src, mode="clip")
                pbins = lax.dynamic_update_slice(s.pbins, nb, (jnp.int32(0), start))
                pgh = lax.dynamic_update_slice(s.pgh, ng, (jnp.int32(0), start))
                pperm = lax.dynamic_update_slice(s.pperm, npm, (start,))
                return pbins, pgh, pperm, n_l

            return part

        caps_arr = jnp.asarray(caps, jnp.int32)
        pidx = jnp.clip(jnp.sum(caps_arr >= c) - 1, 0, len(caps) - 1)
        pbins, pgh, pperm, n_l = lax.switch(
            pidx, [mk_part(cp) for cp in caps], None
        )
        n_r = c - n_l

        # ---- children segments; smaller child by GLOBAL count ----
        if ax is not None:
            left_smaller = lax.psum(n_l, ax) <= lax.psum(n_r, ax)
        else:
            left_smaller = n_l <= n_r
        # left child keeps leaf id l at [b, b+n_l); right child (id `new`)
        # occupies [b+n_l, b+c)
        seg_begin = s.seg_begin.at[l].set(b).at[new].set(b + n_l)
        seg_count = s.seg_count.at[l].set(n_l).at[new].set(n_r)

        small_begin = jnp.where(left_smaller, b, b + n_l)
        small_cnt = jnp.where(left_smaller, n_l, n_r)

        # ---- smaller-child histogram over its contiguous slice ----
        def mk_hist(cap: int):
            def h(_):
                start = jnp.clip(small_begin, 0, N - cap)
                off = small_begin - start
                hb = lax.dynamic_slice(pbins, (jnp.int32(0), start), (F, cap))
                hg = lax.dynamic_slice(pgh, (jnp.int32(0), start), (8, cap))
                iota = jnp.arange(cap, dtype=jnp.int32)
                m = ((iota >= off) & (iota < off + small_cnt)).astype(jnp.float32)
                return histogram(hb, hg * m[None, :], B)

            return h

        hidx = jnp.clip(jnp.sum(caps_arr >= small_cnt) - 1, 0, len(caps) - 1)
        small_hist = lax.switch(hidx, [mk_hist(cp) for cp in caps], None)
        if ax is not None:
            small_hist = lax.psum(small_hist, ax)

        parent_hist = s.hist[l]
        large_hist = parent_hist - small_hist
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        hist = s.hist.at[l].set(left_hist).at[new].set(right_hist)

        # ---- best splits for both children ----
        bl = best_split(left_hist, rec.left_g, rec.left_h, rec.left_c,
                        num_bins, nan_bin, mono, is_cat, params, feat_mask,
                        cat_subset=spec.cat_subset, parent_output=lo,
                        cmin=lmin, cmax=lmax)
        br = best_split(right_hist, rec.right_g, rec.right_h, rec.right_c,
                        num_bins, nan_bin, mono, is_cat, params, feat_mask,
                        cat_subset=spec.cat_subset, parent_output=ro,
                        cmin=rmin, cmax=rmax)
        depth_ok = (spec.max_depth <= 0) | (depth_new < spec.max_depth)
        best2 = _set_best(s.best, l, bl, jnp.where(depth_ok, bl.gain, NEG_INF))
        best2 = _set_best(best2, new, br, jnp.where(depth_ok, br.gain, NEG_INF))

        return _PState(
            i=new,
            pbins=pbins,
            pgh=pgh,
            pperm=pperm,
            seg_begin=seg_begin,
            seg_count=seg_count,
            hist=hist,
            leaf_g=s.leaf_g.at[l].set(rec.left_g).at[new].set(rec.right_g),
            leaf_h=s.leaf_h.at[l].set(rec.left_h).at[new].set(rec.right_h),
            leaf_c=s.leaf_c.at[l].set(rec.left_c).at[new].set(rec.right_c),
            leaf_parent=s.leaf_parent.at[l].set(i).at[new].set(i),
            leaf_min=s.leaf_min.at[l].set(lmin).at[new].set(rmin),
            leaf_max=s.leaf_max.at[l].set(lmax).at[new].set(rmax),
            best=best2,
            tree=tree_new,
        )

    final = lax.while_loop(cond, body, state)

    # ---- natural-order row -> leaf from the leaf segments ----
    # order leaves by segment begin (unused slots and locally-EMPTY
    # leaves — possible on a shard — get begin == N so they sort last
    # and never shadow a sibling sharing their begin); position p then
    # belongs to the last leaf with begin <= p
    eff_begin = jnp.where(final.seg_count > 0, final.seg_begin, N)
    order = jnp.argsort(eff_begin)
    sorted_begin = eff_begin[order]
    pos = jnp.arange(N, dtype=jnp.int32)
    leaf_of_pos = order[
        jnp.clip(jnp.searchsorted(sorted_begin, pos, side="right") - 1, 0, L - 1)
    ].astype(jnp.int32)
    row_leaf = jnp.zeros(N, jnp.int32).at[final.pperm].set(leaf_of_pos)
    if valid is not None:
        row_leaf = jnp.where(valid > 0, row_leaf, -1)
    return final.tree, row_leaf
