"""Online train-and-serve loop (docs/RESILIENCE.md "Online loop").

Closes the loop between training and serving: the registry serves
v(n) while microbatches stream in through the serving ``ingest`` op,
each cycle refits a warm-started candidate, judges it on a holdout
shard with the device metrics, and promotes / rejects / auto-reverts
— all crash-consistently (``cli.py task=loop``).
"""

from .gate import decide, make_holdout_evaluator
from .ingest import IngestSpool, spool_path, stack_batches
from .loop import OnlineLoop
from .state import (
    fresh_state,
    load_state,
    model_path,
    save_state,
    state_path,
)

__all__ = [
    "OnlineLoop",
    "IngestSpool",
    "spool_path",
    "stack_batches",
    "decide",
    "make_holdout_evaluator",
    "fresh_state",
    "load_state",
    "save_state",
    "state_path",
    "model_path",
]
