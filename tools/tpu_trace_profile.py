"""Where does the fused step's ~50 s first-call cost go?

Builds a real Booster on the bench workload shapes, then times the
jit stages of the fused step separately: trace (jaxpr), lower
(StableHLO), compile (XLA; persistent-cache eligible). The trace+lower
share is what every new Booster pays even with a warm compile cache —
it is the part worth shrinking (or memoizing across Boosters).

Usage: python tools/tpu_trace_profile.py [rows]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main() -> None:
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    feats, leaves = 28, 255

    import jax

    import lightgbm_tpu as lgb

    rs = np.random.RandomState(17)
    X = rs.randn(rows, feats).astype(np.float32)
    y = (X[:, 0] + rs.randn(rows) > 0).astype(np.float32)
    Xv = rs.randn(rows // 10, feats).astype(np.float32)
    yv = (Xv[:, 0] + rs.randn(rows // 10) > 0).astype(np.float32)

    params = {
        "objective": "binary", "num_leaves": leaves, "max_bin": 255,
        "metric": "auc", "verbosity": -1,
    }
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    ds.construct()
    vs = lgb.Dataset(Xv, label=yv, reference=ds, free_raw_data=False)

    from lightgbm_tpu.basic import Booster

    t0 = time.time()
    bst = Booster(params=dict(params), train_set=ds)
    bst.add_valid(vs, "v")
    g = bst._gbdt
    g.train.name = "training"
    g.fused_start(track_train=False)
    print(json.dumps({"stage": "setup_s",
                      "value": round(time.time() - t0, 1)}), flush=True)

    state = g._fstate
    data = g._f_data
    step = g._f_step

    t0 = time.time()
    traced = step.trace(state, data)
    t_trace = time.time() - t0
    print(json.dumps({"stage": "trace_s", "value": round(t_trace, 1)}),
          flush=True)

    t0 = time.time()
    lowered = traced.lower()
    t_lower = time.time() - t0
    print(json.dumps({"stage": "lower_s", "value": round(t_lower, 1)}),
          flush=True)

    t0 = time.time()
    compiled = lowered.compile()
    t_compile = time.time() - t0
    print(json.dumps({"stage": "compile_s", "value": round(t_compile, 1),
                      "note": "persistent-cache eligible"}), flush=True)

    # steady-state: run a few steps with one readback at the end
    t0 = time.time()
    n = 10
    for _ in range(n):
        state, trees, eval_row = compiled(state, data)
    jax.device_get(eval_row)
    t = (time.time() - t0) / n
    print(json.dumps({"stage": "steady_step_ms",
                      "value": round(t * 1e3, 1),
                      "note": f"{n} fused steps, one readback"}), flush=True)


if __name__ == "__main__":
    main()
