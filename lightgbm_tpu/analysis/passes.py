"""Registry of every analysis pass the `--strict` gate must run.

One table, consumed by `__main__.py` (the CLI) and asserted by the
meta-test in tests/test_cost_audit.py: a new auditor registered here is
automatically part of the strict gate, and an auditor removed from the
strict path without being removed here fails the meta-test — the gate
cannot silently shed passes.

Each pass runs independently and returns a PassResult; `needs_jax`
splits the pure-AST passes (runnable anywhere, `--lint-only`) from the
trace/compile passes that need the multi-device CPU backend
(`--audit-only` skips the AST side instead).
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence


class PassResult(NamedTuple):
    name: str
    ok: bool
    report: str


def _run_lint(pkg_root: Optional[str], show_suppressed: bool) -> PassResult:
    from .lint import format_findings, lint_package

    findings = lint_package(pkg_root) if pkg_root else lint_package()
    return PassResult(
        "lint",
        not any(not f.suppressed for f in findings),
        format_findings(findings, show_suppressed=show_suppressed),
    )


def _run_concurrency(pkg_root: Optional[str],
                     show_suppressed: bool) -> PassResult:
    from .concurrency_lint import concurrency_lint_package
    from .lint import format_findings

    findings = concurrency_lint_package(pkg_root) \
        if pkg_root else concurrency_lint_package()
    return PassResult(
        "concurrency",
        not any(not f.suppressed for f in findings),
        format_findings(findings, show_suppressed=show_suppressed,
                        label="concurrency"),
    )


def _run_jaxpr(pkg_root: Optional[str], show_suppressed: bool) -> PassResult:
    from .jaxpr_audit import run_audits

    results = run_audits()
    return PassResult(
        "jaxpr",
        all(r.ok for r in results),
        "\n".join(r.format() for r in results),
    )


def _run_cost(pkg_root: Optional[str], show_suppressed: bool) -> PassResult:
    from .cost_audit import run_cost_audits

    results = run_cost_audits()
    return PassResult(
        "cost",
        all(r.ok for r in results),
        "\n".join(r.format() for r in results),
    )


def _run_bench_gate(pkg_root: Optional[str],
                    show_suppressed: bool) -> PassResult:
    from .bench_gate import run_gate

    result = run_gate()
    return PassResult("bench_gate", result.ok, result.format())


def _run_scale(pkg_root: Optional[str], show_suppressed: bool) -> PassResult:
    from .scale_audit import run_scale_audits

    results = run_scale_audits()  # full D-ladder: the strict gate
    return PassResult(
        "scale",
        all(r.ok for r in results),
        "\n".join(r.format() for r in results),
    )


class AnalysisPass(NamedTuple):
    name: str
    needs_jax: bool
    doc: str
    run: Callable[[Optional[str], bool], PassResult]


PASSES: Dict[str, AnalysisPass] = {
    "lint": AnalysisPass(
        "lint", False,
        "trace-safety AST linter (lint.py)", _run_lint,
    ),
    "concurrency": AnalysisPass(
        "concurrency", False,
        "lock-discipline linter for the threaded serving layer "
        "(concurrency_lint.py)", _run_concurrency,
    ),
    "jaxpr": AnalysisPass(
        "jaxpr", True,
        "jaxpr invariant auditor: wire dtype / callbacks / f64 / eqn "
        "budgets (jaxpr_audit.py)", _run_jaxpr,
    ),
    "cost": AnalysisPass(
        "cost", True,
        "XLA cost/memory budgets + collective wire-bytes accounting "
        "(cost_audit.py)", _run_cost,
    ),
    "bench_gate": AnalysisPass(
        "bench_gate", False,
        "BENCH_r*/BENCH_SERVE_r* trajectory regression gate against "
        "bench_budget.json pins (bench_gate.py)", _run_bench_gate,
    ),
    "scale": AnalysisPass(
        "scale", True,
        "SPMD scaling-contract auditor: collective census, wire "
        "scaling laws, and sharding-spec verification over the "
        "D in {1,2,4,8} mesh ladder (scale_audit.py)", _run_scale,
    ),
}


def run_passes(names: Optional[Sequence[str]] = None,
               pkg_root: Optional[str] = None,
               show_suppressed: bool = False) -> List[PassResult]:
    """Run the named passes (default: every registered pass, the
    strict-gate set). Unknown names raise — a typoed pass must not
    pass vacuously."""
    if names is None:
        names = list(PASSES)
    unknown = set(names) - set(PASSES)
    if unknown:
        raise KeyError(
            f"unknown analysis pass(es) {sorted(unknown)}; "
            f"registered: {sorted(PASSES)}"
        )
    return [
        PASSES[n].run(pkg_root, show_suppressed)
        for n in PASSES if n in set(names)
    ]
