"""Benchmark: Higgs-1M-like GBDT training throughput on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's published Higgs result — 500 iterations of
255-leaf trees over 10.5M x 28 in 130.094 s on 2xE5-2690v4
(reference docs/Experiments.rst:104-121, see BASELINE.md). Scaled
linearly to this bench's row count (histogram GBDT cost is ~linear in
rows), i.e. baseline trees/sec at R rows = (500 / 130.094) * (10.5e6 / R).

Robustness (three rounds of driver benches produced no valid artifact —
r2/r3 died on TPU-tunnel hangs and timeouts):
- the accelerator backend is probed in a SUBPROCESS with a hard timeout
  before jax is imported here; on probe failure the bench falls back to
  JAX_PLATFORMS=cpu instead of hanging;
- on CPU fallback the workload DOWNSHIFTS (rows capped at
  BENCH_CPU_ROWS, default 100k; trees at 30) so the run completes
  inside the driver budget;
- SIGTERM/SIGINT/SIGALRM all trigger the final JSON line, built from
  whatever partial results exist at that moment (stage field says how
  far it got); partial state is also persisted to a per-run file under
  a tmp run dir (BENCH_RUN_DIR, default <tmpdir>/lightgbm_tpu_bench/)
  as training advances — never to the repo root, and the partial is
  removed on a clean finish so aborted runs cannot leave stale
  artifacts behind for the next session to misread;
- the last builder-verified on-chip number (BENCH_NOTES.md) rides along
  in "last_tpu_verified" so a CPU-fallback artifact still carries the
  hardware result.

The timed loop trains WITH per-iteration validation metrics enabled
(device-resident eval on a held-out set) — deliberately a heavier
workload than the baseline's bare training time, because sustained
trees/sec with live eval is the number that matters for users.

Env overrides: BENCH_ROWS, BENCH_FEATURES, BENCH_LEAVES, BENCH_TREES,
BENCH_WARMUP, BENCH_MAX_BIN, BENCH_PROBE_TIMEOUT (s), BENCH_PROBE_RETRIES,
BENCH_FORCE_CPU, BENCH_CPU_ROWS, BENCH_GROWTH_MODE,
BENCH_BUDGET (s, SIGALRM deadline), BENCH_RUN_DIR (partial-state dir).
Voting segment (needs a multi-device mesh, e.g.
XLA_FLAGS=--xla_force_host_platform_device_count=8 on CPU):
BENCH_SKIP_VOTING, BENCH_VOTING_TREES, BENCH_VOTING_EXACT_TREES,
BENCH_VOTING_LEAVES, BENCH_VOTING_TOPK.
Chunk-scan segment (tpu_chunk_scan=auto vs off, same run):
BENCH_SKIP_CHUNK_SCAN, BENCH_CHUNK_TREES.
Ingest segment (out-of-core data plane, docs/DATA_PLANE.md):
BENCH_SKIP_INGEST, BENCH_INGEST_ROWS, BENCH_INGEST_TREES,
BENCH_INGEST_BUDGET_MB, BENCH_INGEST_CHUNK_ROWS.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))


def _load_backoff():
    """Load resilience/backoff.py by FILE PATH: the bench must not
    import the lightgbm_tpu package (that pulls in jax) before the
    subprocess backend probe, and backoff.py is pure stdlib by design
    (docs/RESILIENCE.md)."""
    path = os.path.join(REPO, "lightgbm_tpu", "resilience", "backoff.py")
    spec = importlib.util.spec_from_file_location("_bench_backoff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


backoff_delay = _load_backoff().backoff_delay


def _partial_path() -> str:
    """Per-run partial-state file under a tmp run dir — NOT the repo
    root (an aborted run once left a stale bench_partial.json checked
    in, which read as a fresh artifact forever after)."""
    run_dir = os.environ.get("BENCH_RUN_DIR") or os.path.join(
        tempfile.gettempdir(), "lightgbm_tpu_bench"
    )
    try:
        os.makedirs(run_dir, exist_ok=True)
    except OSError:
        run_dir = tempfile.gettempdir()
    return os.path.join(run_dir, f"bench_partial_{os.getpid()}.json")


_PARTIAL_PATH = _partial_path()

# last builder-verified on-chip measurement (see BENCH_NOTES.md);
# updated whenever a live-chip run lands a better sustained number
LAST_TPU_VERIFIED = {
    "metric": "higgs_synth_1000k_255leaves_trees_per_sec",
    "value": 6.0125,
    "unit": "trees/sec",
    "vs_baseline": 0.149,
    "platform": "tpu",
    "round": 5,
    "auc_valid": 0.98421,
    "quantized_trees_per_sec": 13.994,
    "quantized_vs_baseline": 0.3468,
    "quantized_auc_valid": 0.9857,
    "note": "steady-state over the last fused chunk; default config; "
            "quantized = use_quantized_grad int8 MXU path",
}

_PROBE_SRC = r"""
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print(jax.devices()[0].platform)
"""


def probe_backend(timeout_s: float, retries: int = 1) -> str:
    """Run a tiny jit in a subprocess; return its platform or 'cpu'.

    The axon tunnel wedges transiently (multi-minute init hangs that
    clear on a later attempt — observed rounds 2-4), so a probe that
    FAILS (nonzero rc, import error) is retried with exponential
    backoff (10s, 20s, 40s, ... capped at 120s). A probe that TIMES
    OUT fails fast to the CPU fallback instead: a second identical
    wait on a wedged tunnel just burns another full timeout_s of the
    driver budget with the same outcome (BENCH_r05 spent 620 s on two
    serial 300 s timeouts before its first measurement)."""
    for attempt in range(1, retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1]
            sys.stderr.write(
                f"[bench] backend probe {attempt}/{retries} "
                f"rc={r.returncode}: {r.stderr.strip()[-500:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"[bench] backend probe {attempt}/{retries} timed out "
                f"({timeout_s}s) — failing fast to cpu\n"
            )
            return "cpu"
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(
                f"[bench] backend probe {attempt}/{retries} failed: {e}\n"
            )
        if attempt < retries:
            # shared backoff schedule (resilience/backoff.py) — one
            # implementation for bench probe, fleet scrape, cluster join
            backoff = backoff_delay(attempt, base_s=10.0, cap_s=120.0)
            sys.stderr.write(f"[bench] retrying probe in {backoff:.0f}s\n")
            time.sleep(backoff)
    return "cpu"


_STATE = {"stage": "init"}
_FINAL_PRINTED = False


def _tpu_verified():
    """Chip numbers annotated with the ONE staleness rule (shared by
    the partial and final json so they cannot drift): stale=true when
    this run did not actually execute on the TPU, so a dead tunnel can
    no longer ship carried-forward numbers as if fresh."""
    return dict(LAST_TPU_VERIFIED, stale=_STATE.get("platform") != "tpu")


def _final_json():
    """Build the single stdout JSON line from whatever state exists."""
    rows = _STATE.get("rows", 0) or 1
    leaves = _STATE.get("leaves", 0)
    baseline_tps = (500.0 / 130.094) * (10.5e6 / rows)
    tps = _STATE.get("trees_per_sec")
    out = {
        "metric": f"higgs_synth_{rows // 1000}k_{leaves}leaves_trees_per_sec",
        "value": round(tps, 4) if tps else 0.0,
        "unit": "trees/sec",
        "vs_baseline": round(tps / baseline_tps, 4) if tps else 0.0,
        "platform": _STATE.get("platform", "unknown"),
        "stage": _STATE.get("stage", "unknown"),
        "last_tpu_verified": _tpu_verified(),
    }
    if _STATE.get("quantized_trees_per_sec"):
        out["quantized_vs_baseline"] = round(
            _STATE["quantized_trees_per_sec"] / baseline_tps, 4
        )
    for k in ("auc_valid", "trees_done", "warmup_s", "growth_mode",
              "total_trees_per_sec", "quantized", "quantized_trees_per_sec",
              "quantized_total_trees_per_sec", "quantized_auc_valid",
              "voting_trees_per_sec", "voting_exact_trees_per_sec",
              "voting_speedup_vs_exact", "voting_auc_valid",
              "voting_leaves", "voting_devices",
              "chunk_scan_trees_per_sec", "chunk_scan_off_trees_per_sec",
              "chunk_scan_speedup", "chunk_scan_dispatches",
              "chunk_scan_off_dispatches", "chunk_scan_host_ms_per_tree",
              "chunk_scan_off_host_ms_per_tree",
              "ingest_rows", "ingest_features", "ingest_chunks",
              "ingest_ram_budget_mb", "ingest_spool_rows_per_sec",
              "ingest_bin_rows_per_sec", "ingest_fit_trees_per_sec",
              "ingest_peak_rss_mb", "ingest_rss_spread_mb",
              "run_id", "run_manifest"):
        if k in _STATE:
            out[k] = _STATE[k]
    return out


def write_run_manifest(params) -> None:
    """Provenance link (docs/OBSERVABILITY.md): write a run manifest
    (config, device topology, versions, metrics snapshot) and stamp
    its path + run id into the BENCH json, so every trajectory point
    the bench gate reads traces back to what exactly ran."""
    try:
        from lightgbm_tpu.obs.manifest import write_manifest

        # manifest lives under the tmp run dir (BENCH_RUN_DIR — the
        # same treatment bench partials got): writing it at the repo
        # root once left a stale run_manifest_bench.json checked in.
        # The run_id inside ties it to its artifact; BENCH_MANIFEST_OUT
        # overrides when a durable copy is wanted.
        mpath = os.environ.get("BENCH_MANIFEST_OUT") or os.path.join(
            os.path.dirname(_PARTIAL_PATH), "run_manifest_bench.json"
        )
        write_manifest(mpath, config=dict(params), extra={
            "bench": "train", "run_id": _STATE["run_id"],
        })
        save_partial(run_manifest=mpath)
    except Exception as e:  # noqa: BLE001 — provenance must not kill the bench
        sys.stderr.write(f"[bench] run manifest not written: {e}\n")


def _emit_final(*_args):
    global _FINAL_PRINTED
    if _FINAL_PRINTED:
        return
    _FINAL_PRINTED = True
    print(json.dumps(_final_json()), flush=True)


def _signal_exit(signum, _frame):
    sys.stderr.write(f"[bench] caught signal {signum}; emitting partials\n")
    _emit_final()
    # deliberate rc=0: the artifact IS valid (stage field marks how far
    # the run got); the driver only needs a parseable stdout line
    os._exit(0)


def _watchdog(deadline: float):
    """Python signal handlers only run between bytecodes of the main
    thread — a hang inside a native XLA/libtpu call (the documented
    r2/r3 failure mode) never delivers them. This daemon thread fires
    regardless of what the main thread is stuck in."""
    import threading

    def run():
        while True:
            left = deadline - time.time()
            if left <= 0:
                break
            time.sleep(min(left, 5.0))
        sys.stderr.write("[bench] watchdog deadline hit; emitting partials\n")
        _emit_final()
        os._exit(0)

    t = threading.Thread(target=run, daemon=True)
    t.start()


def save_partial(**kw):
    _STATE.update(kw)
    try:
        with open(_PARTIAL_PATH, "w") as f:
            json.dump(
                dict(_STATE, last_tpu_verified=_tpu_verified()), f
            )
    except OSError:
        pass


def _cleanup_partial():
    """Drop the partial file on a clean finish (the final JSON line on
    stdout is the artifact); an aborted run keeps its partial in the
    tmp run dir for postmortem, where it can't be mistaken for output."""
    try:
        os.remove(_PARTIAL_PATH)
    except OSError:
        pass


def main() -> None:
    _STATE["run_id"] = f"{int(time.time())}-{os.getpid()}"
    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGALRM):
        signal.signal(sig, _signal_exit)
    budget = float(os.environ.get("BENCH_BUDGET", 0) or 0)
    if budget > 0:
        signal.alarm(int(budget))
        _watchdog(time.time() + budget + 2.0)

    rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    leaves = int(os.environ.get("BENCH_LEAVES", 255))
    trees = int(os.environ.get("BENCH_TREES", 100))
    warmup = int(os.environ.get("BENCH_WARMUP", 2))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 255))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))
    probe_retries = int(os.environ.get("BENCH_PROBE_RETRIES", 2))
    growth_mode = os.environ.get("BENCH_GROWTH_MODE", "auto")

    if os.environ.get("BENCH_FORCE_CPU"):
        platform = "cpu"
    elif os.environ.get("JAX_PLATFORMS") == "cpu":
        platform = "cpu"
    else:
        # probe even when JAX_PLATFORMS=axon (the default env): the probe
        # exists precisely to detect a dead TPU tunnel before hanging.
        # When the env already NAMES a backend, the caller has made the
        # placement decision — the probe only needs to confirm the
        # tunnel is alive, so probe ONCE with a short timeout instead
        # of the full multi-attempt schedule (BENCH_r05 burned 620 s on
        # two serial 300 s timeouts before measuring anything).
        if os.environ.get("JAX_PLATFORMS"):
            probe_timeout = float(
                os.environ.get("BENCH_PROBE_FAST_TIMEOUT", 60)
            )
            probe_retries = 1
        t0 = time.time()
        platform = probe_backend(probe_timeout, probe_retries)
        sys.stderr.write(
            f"[bench] backend probe -> {platform} in {time.time()-t0:.0f}s\n"
        )
    if platform == "cpu":
        # the CPU fallback exists to prove the bench pipeline end-to-end,
        # not to measure 1M rows on a host core — downshift so it
        # FINISHES inside the driver budget (r3 died compiling the 1M
        # warmup on CPU for 175s before timeout)
        cpu_rows = int(os.environ.get("BENCH_CPU_ROWS", 100_000))
        if rows > cpu_rows:
            sys.stderr.write(
                f"[bench] cpu fallback: downshifting rows {rows} -> "
                f"{cpu_rows}, trees {trees} -> {min(trees, 30)}\n"
            )
            rows = cpu_rows
            trees = min(trees, 30)
        # sitecustomize may have imported jax already — the env var alone
        # is read too early, set the config explicitly as well
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    # persistent XLA compilation cache: the 1M-row warmup compile costs
    # ~110 s on the TPU (BENCH_NOTES.md) and ~175 s on CPU — cache it so
    # a re-run (driver retry, back-to-back measurements) skips straight
    # to the timed loop. jax may already be imported (sitecustomize, or
    # the CPU-fallback import above) and reads the env at import time,
    # so set it at the config level as well.
    # fingerprint the cache by host CPU flags: XLA:CPU AOT entries embed
    # machine features the cache key omits — a cache written on another
    # host (the driver moves between machines) can SIGILL on this one
    sys.path.insert(0, REPO)
    from lightgbm_tpu._cache import machine_tag

    cache_dir = os.path.join(REPO, f".jax_cache_{machine_tag()}")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"[bench] compile cache not enabled: {e}\n")

    import lightgbm_tpu as lgb

    save_partial(stage="data", platform=platform, rows=rows, leaves=leaves,
                 growth_mode=growth_mode)

    rs = np.random.RandomState(17)
    X = rs.randn(rows, feats).astype(np.float32)
    w = rs.randn(feats)
    logits = X[:, : feats // 2] @ w[: feats // 2] + np.sin(X[:, feats // 2]) * 2.0
    y = (logits + rs.randn(rows) > 0).astype(np.float32)
    # held-out validation rows (NOT part of the training matrix)
    nv = min(rows // 10, 100_000)
    Xv = rs.randn(nv, feats).astype(np.float32)
    lv = Xv[:, : feats // 2] @ w[: feats // 2] + np.sin(Xv[:, feats // 2]) * 2.0
    yv = (lv + rs.randn(nv) > 0).astype(np.float32)

    params = {
        "objective": "binary",
        "num_leaves": leaves,
        "max_bin": max_bin,
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "metric": "auc",
        "verbosity": -1,
        "tpu_growth_mode": growth_mode,
    }
    if os.environ.get("BENCH_SLOTS"):
        params["tpu_round_slots"] = int(os.environ["BENCH_SLOTS"])
    if os.environ.get("BENCH_QUANT"):
        # quantized-gradient training (use_quantized_grad): int8 MXU
        # histograms, 48 slots/pass — the reference's quantized mode
        # with its recommended leaf renewal
        params.update(use_quantized_grad=True, num_grad_quant_bins=4,
                      quant_train_renew_leaf=True)
        save_partial(quantized=True)
    t0 = time.time()
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    ds.construct()
    vs = lgb.Dataset(Xv, label=yv, reference=ds, free_raw_data=False)
    sys.stderr.write(f"[bench] dataset built in {time.time()-t0:.1f}s\n")

    save_partial(stage="warmup")
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=warmup,
              valid_sets=[vs], valid_names=["v"])
    compile_s = time.time() - t0
    sys.stderr.write(f"[bench] warmup ({warmup} trees) in {compile_s:.1f}s\n")
    save_partial(stage="timed", warmup_s=round(compile_s, 2))

    # Callbacks replay at fused-loop chunk boundaries (engine chunk =
    # _check_every = 64), so consecutive callback wall times within one
    # chunk are compressed; chunk-boundary deltas are REAL sync points.
    # Steady-state trees/s = trees between the first and last boundary
    # over the wall time between them — this excludes the one-time jit
    # trace+lowering the first dispatch pays (the XLA compile itself is
    # served by the persistent cache). Both numbers are reported;
    # `value` is steady-state when >= 2 boundaries exist.
    def timed_train(run_params, n_trees, tag=""):
        """One timed training run; returns (steady, total_tps, auc, bst).

        Steady-state = trees between the first and last chunk-boundary
        callback burst over the wall time between them (excludes the
        one-time jit trace+lowering the first dispatch pays)."""
        marks = []  # (trees_done, wall_time) at observed callback bursts

        def progress(env):
            done = env.iteration + 1
            now = time.time()
            if not marks or done > marks[-1][0]:
                if marks and now - marks[-1][1] < 0.05:
                    marks[-1] = (done, now)  # same replay burst; keep last
                else:
                    marks.append((done, now))
            if done % 10 == 0 or done == n_trees or done <= 3:
                dt = now - t0
                tps = done / dt if dt > 0 else 0.0
                sys.stderr.write(
                    f"[bench] {tag}{done}/{n_trees} trees, {tps:.3f} trees/s\n"
                )
                if not tag:
                    save_partial(trees_done=done, elapsed_s=round(dt, 2),
                                 trees_per_sec=round(tps, 4))

        t0 = time.time()
        bst2 = lgb.train(dict(run_params), ds, num_boost_round=n_trees,
                         valid_sets=[vs], valid_names=["v"],
                         callbacks=[progress])
        dt = time.time() - t0
        total_tps = n_trees / dt
        steady = None
        if len(marks) >= 2:
            # collapse replay bursts: marks within 1 s of the previous
            # mark belong to the same chunk-boundary replay; the LAST
            # mark of each burst is the real sync point
            bursts = [marks[0]]
            for d, w in marks[1:]:
                if w - bursts[-1][1] < 1.0:
                    bursts[-1] = (d, w)
                else:
                    bursts.append((d, w))
            if len(bursts) >= 2:
                (d0, w0), (d1, w1) = bursts[0], bursts[-1]
                if d1 > d0 and w1 > w0:
                    steady = (d1 - d0) / (w1 - w0)
        auc = None
        try:
            from sklearn.metrics import roc_auc_score

            auc = round(float(roc_auc_score(yv, bst2.predict(Xv))), 5)
        except Exception:  # noqa: BLE001
            pass
        return steady, total_tps, auc, bst2

    steady, total_tps, auc, _ = timed_train(params, trees)
    save_partial(
        stage="scoring",
        trees_per_sec=round(steady if steady else total_tps, 4),
        total_trees_per_sec=round(total_tps, 4),
        trees_done=trees,
    )
    if auc is not None:
        save_partial(auc_valid=auc)

    # second segment: quantized training (use_quantized_grad int8 MXU
    # path — the reference's own "fast mode") as a first-class headline
    # alongside the default run. Skipped when the whole bench is already
    # quantized (BENCH_QUANT) or explicitly disabled.
    if (not os.environ.get("BENCH_QUANT")
            and not os.environ.get("BENCH_SKIP_QUANT")):
        qtrees = int(os.environ.get("BENCH_QUANT_TREES", trees))
        qparams = dict(params, use_quantized_grad=True,
                       num_grad_quant_bins=4, quant_train_renew_leaf=True)
        save_partial(stage="quantized")
        try:
            qsteady, qtotal, qauc, _ = timed_train(
                qparams, qtrees, tag="quant ")
            save_partial(
                quantized_trees_per_sec=round(qsteady or qtotal, 4),
                quantized_total_trees_per_sec=round(qtotal, 4),
            )
            if qauc is not None:
                save_partial(quantized_auc_valid=qauc)
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"[bench] quantized segment failed: {e}\n")

    # chunk-scan segment: the SAME training with rounds dispatched as
    # C-round lax.scan chunks (tpu_chunk_scan=auto, the default) vs one
    # executable launch per round (=off) — a same-run measurement of
    # what evicting the host from the inner loop buys. Alongside
    # trees/sec it reports the dispatch count (the probe the tests
    # assert on: chunks, not rounds) and host ms spent inside
    # fused_dispatch per tree; the device-side step math is identical
    # on both sides by construction (bit-parity tested).
    if not os.environ.get("BENCH_SKIP_CHUNK_SCAN"):
        ctrees = int(os.environ.get("BENCH_CHUNK_TREES", min(trees, 30)))
        save_partial(stage="chunk_scan")

        def _host_ms_per_tree(b, n):
            return round(1000.0 * b._gbdt._dispatch_host_s / max(n, 1), 3)

        try:
            csteady, ctotal, _, cbst = timed_train(
                dict(params, tpu_chunk_scan="auto"), ctrees, tag="chunk ")
            osteady, ototal, _, obst = timed_train(
                dict(params, tpu_chunk_scan="off"), ctrees,
                tag="chunk-off ")
            ctps, otps = csteady or ctotal, osteady or ototal
            save_partial(
                chunk_scan_trees_per_sec=round(ctps, 4),
                chunk_scan_off_trees_per_sec=round(otps, 4),
                chunk_scan_speedup=(
                    round(ctps / otps, 3) if otps else None),
                chunk_scan_dispatches=cbst._gbdt.fused_dispatch_count,
                chunk_scan_off_dispatches=obst._gbdt.fused_dispatch_count,
                chunk_scan_host_ms_per_tree=_host_ms_per_tree(
                    cbst, ctrees),
                chunk_scan_off_host_ms_per_tree=_host_ms_per_tree(
                    obst, ctrees),
            )
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"[bench] chunk_scan segment failed: {e}\n")

    # ingest segment: the out-of-core data plane (docs/DATA_PLANE.md) —
    # spool the bench matrix to a disk chunk store, stream the two-pass
    # binning, then fit with the double-buffered assembly under a RAM
    # budget far below the raw footprint. Reports spool and bin rows/sec
    # plus the per-chunk RSS spread the flat-memory contract promises.
    if not os.environ.get("BENCH_SKIP_INGEST"):
        irows = int(os.environ.get("BENCH_INGEST_ROWS", rows))
        itrees = int(os.environ.get("BENCH_INGEST_TREES", min(trees, 10)))
        ibudget = int(os.environ.get("BENCH_INGEST_BUDGET_MB", 256))
        save_partial(stage="ingest")
        try:
            from lightgbm_tpu.data import last_stats, reset_stats

            if irows <= rows:
                Xi, yi = X[:irows], y[:irows]
            else:
                # ingest is an I/O-plane measurement — it can (and on the
                # CPU fallback should) run far bigger than the training
                # matrix the trees/sec segments were downshifted to
                rsi = np.random.RandomState(29)
                Xi = rsi.randn(irows, feats).astype(np.float32)
                yi = (Xi[:, 0] + rsi.randn(irows) > 0).astype(np.float32)
            reset_stats()
            iparams = dict(params, data_source="chunked",
                           ram_budget_mb=ibudget)
            if os.environ.get("BENCH_INGEST_CHUNK_ROWS"):
                iparams["data_chunk_rows"] = int(
                    os.environ["BENCH_INGEST_CHUNK_ROWS"])
            ids = lgb.Dataset(Xi, label=yi, params=iparams,
                              free_raw_data=False)
            t0 = time.time()
            if itrees > 0:
                lgb.train(dict(iparams), ids, num_boost_round=itrees)
            else:
                # trees=0: measure the data plane alone — spool, two-pass
                # bin, and the prefetched device assembly — without a
                # training run (on the CPU fallback a 10M-row fit blows
                # the bench budget; the trees/sec segments above already
                # cover training throughput)
                ids.construct()
                ids._binned.device_arrays()
            fit_s = time.time() - t0
            st = last_stats() or {}
            asm = st.get("assemble", {})
            save_partial(
                ingest_rows=irows,
                ingest_features=feats,
                ingest_ram_budget_mb=ibudget,
                ingest_chunks=asm.get("chunks"),
                ingest_spool_rows_per_sec=st.get("spool", {}).get(
                    "rows_per_sec"),
                ingest_bin_rows_per_sec=st.get("pass2", {}).get(
                    "rows_per_sec"),
                ingest_peak_rss_mb=asm.get("peak_rss_mb"),
                ingest_rss_spread_mb=asm.get("rss_spread_mb"),
            )
            if itrees > 0:
                save_partial(
                    ingest_fit_trees_per_sec=round(itrees / fit_s, 4))
            del ids, Xi, yi
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"[bench] ingest segment failed: {e}\n")

    # third segment: voting-parallel (tree_learner=voting riding the
    # rounds grower) against the sequential exact oracle
    # (tpu_growth_mode=exact, permuted.py) on the SAME dataset and leaf
    # budget — so the reported speedup is a same-run measurement, not a
    # cross-artifact quote. The election is a cross-shard psum, so the
    # segment needs a device mesh (on CPU:
    # XLA_FLAGS=--xla_force_host_platform_device_count=8); both sides
    # downshift leaves (BENCH_VOTING_LEAVES) because the oracle pays one
    # dispatched step per SPLIT and would otherwise eat the budget.
    if not os.environ.get("BENCH_SKIP_VOTING"):
        import jax

        if jax.device_count() > 1:
            vtrees = int(os.environ.get("BENCH_VOTING_TREES",
                                        min(trees, 15)))
            etrees = int(os.environ.get("BENCH_VOTING_EXACT_TREES", 2))
            vleaves = int(os.environ.get("BENCH_VOTING_LEAVES",
                                         min(leaves, 63)))
            vparams = dict(params, tree_learner="voting",
                           top_k=int(os.environ.get("BENCH_VOTING_TOPK", 8)),
                           num_leaves=vleaves, tpu_growth_mode="rounds")
            save_partial(stage="voting", voting_leaves=vleaves,
                         voting_devices=jax.device_count())
            try:
                vsteady, vtotal, vauc, _ = timed_train(
                    vparams, vtrees, tag="voting ")
                vtps = vsteady or vtotal
                save_partial(voting_trees_per_sec=round(vtps, 4))
                if vauc is not None:
                    save_partial(voting_auc_valid=vauc)
                esteady, etotal, _, _ = timed_train(
                    dict(vparams, tpu_growth_mode="exact"), etrees,
                    tag="voting-exact ")
                etps = esteady or etotal
                save_partial(
                    voting_exact_trees_per_sec=round(etps, 4),
                    voting_speedup_vs_exact=(
                        round(vtps / etps, 2) if etps else None),
                )
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"[bench] voting segment failed: {e}\n")
        else:
            sys.stderr.write(
                "[bench] voting segment skipped: single-device run (set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8 for "
                "a host mesh)\n"
            )

    write_run_manifest(params)
    _STATE["stage"] = "done"
    _cleanup_partial()
    _emit_final()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"[bench] FAILED at stage {_STATE.get('stage')}: {e}\n")
        import traceback

        traceback.print_exc()
        _emit_final()
