"""Per-worker heartbeat files + fleet health reports.

The multi-host trainer (parallel/multihost.py run_distributed) is a
fleet of lockstep processes: when one dies, the survivors hang in the
next collective with no indication of WHICH rank failed. Heartbeats
make worker death observable through the same shared-directory channel
the fleet metrics snapshots already use — pure host-side file I/O,
deliberately not a jax collective, so the health report keeps working
when the training fabric itself is what broke (same posture as
write_metrics_snapshot, docs/DESIGN_DECISIONS.md).

Each worker runs a ``HeartbeatWriter``: a daemon thread that
atomically rewrites ``heartbeat_rank<NNNNN>.json`` (tmp + os.replace,
same protocol as the checkpoints) every ``interval_s`` with
``{rank, pid, seq, t_unix}``. Any process — rank 0 after training, or
an operator offline — calls ``health_report(dir, expected=N)`` to
classify every expected rank as alive / stale (file older than
``stale_after_s``) / missing (never wrote). run_distributed folds the
report into the merged fleet snapshot and warns on dead workers.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import time
from typing import Any, Dict, Optional


def heartbeat_path(out_dir: str, rank: int) -> str:
    return os.path.join(out_dir, f"heartbeat_rank{rank:05d}.json")


class HeartbeatWriter:
    """Background heartbeat for one worker; start()/stop() lifecycle.

    The writer thread owns all mutable state except the stop Event, so
    there is nothing to lock; stop() writes one final beat (seq
    included, so a clean shutdown is distinguishable from a crash that
    merely left a recent file behind)."""

    def __init__(self, out_dir: str, rank: int, interval_s: float = 5.0):
        self.out_dir = out_dir
        self.rank = int(rank)
        self.interval_s = float(interval_s)
        self.path = heartbeat_path(out_dir, rank)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0

    def _write(self, final: bool = False) -> None:
        beat = {
            "rank": self.rank,
            "pid": os.getpid(),
            "seq": self._seq,
            "t_unix": time.time(),
            "final": bool(final),
        }
        tmp = f"{self.path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(beat, f)
        os.replace(tmp, self.path)
        self._seq += 1

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._write()
            except OSError:
                # a full/vanished shared dir must not kill the worker;
                # the missing beat IS the signal the report surfaces
                pass

    def start(self) -> "HeartbeatWriter":
        os.makedirs(self.out_dir, exist_ok=True)
        self._write()  # beat 0 lands before training starts
        self._thread = threading.Thread(
            target=self._run, name=f"lgb-heartbeat-r{self.rank}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5)
            self._thread = None
        try:
            self._write(final=True)
        except OSError:
            pass


def read_heartbeats(out_dir: str) -> Dict[int, Dict[str, Any]]:
    """rank -> last beat, skipping torn/alien files (atomic replace
    makes torn files impossible from THIS module, but the dir is
    shared)."""
    out: Dict[int, Dict[str, Any]] = {}
    for p in glob.glob(os.path.join(out_dir, "heartbeat_rank*.json")):
        m = re.search(r"heartbeat_rank(\d+)\.json$", p)
        if not m:
            continue
        try:
            with open(p) as f:
                out[int(m.group(1))] = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            continue
    return out


def health_report(
    out_dir: str,
    expected: int,
    stale_after_s: float = 30.0,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """Classify every expected rank: ``alive`` (fresh beat or clean
    final), ``stale`` (last beat older than stale_after_s — wedged or
    dead mid-run), ``missing`` (never wrote — died before round 0 or
    can't reach the shared dir). Shape rides into the merged fleet
    snapshot under ``worker_health``."""
    now = time.time() if now is None else float(now)
    beats = read_heartbeats(out_dir)
    alive, stale, missing = [], [], []
    ages: Dict[str, float] = {}
    for rank in range(int(expected)):
        beat = beats.get(rank)
        if beat is None:
            missing.append(rank)
            continue
        age = now - float(beat.get("t_unix", 0.0))
        ages[str(rank)] = round(age, 3)
        if beat.get("final") or age <= stale_after_s:
            alive.append(rank)
        else:
            stale.append(rank)
    return {
        "expected": int(expected),
        "alive": alive,
        "stale": stale,
        "missing": missing,
        "ages_s": ages,
        "stale_after_s": float(stale_after_s),
        "healthy": not stale and not missing,
    }
