"""tree_learner=data through the PUBLIC API on the virtual 8-device mesh.

The reference selects a distributed learner by config
(tree_learner.cpp:17-59) and its data-parallel algorithm guarantees all
ranks grow identical trees from globally-reduced histograms
(data_parallel_tree_learner.cpp:286). Here the same config routes
lgb.train through the shard_map'd grower: rows sharded over the mesh,
histograms psum'd, trees replicated — predictions must match serial
training."""

from __future__ import annotations

import gc

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module", autouse=True)
def _fresh_heap():
    """Free every cached executable before this module's 8-device mesh
    compiles: late in the full suite the process heap holds hundreds of
    live executables, and serializing THIS module's large shard_map'd
    fused-step executable into the persistent compile cache has
    segfaulted inside jax's put_executable_and_time under that memory
    pressure (exit 139 at ~76% of the suite; standalone runs pass).
    Clearing first costs a few recompiles and removes the crash."""
    import jax

    from lightgbm_tpu.boosting import _FUSED_STEP_CACHE

    _FUSED_STEP_CACHE.clear()
    jax.clear_caches()
    gc.collect()
    yield


def _binary_problem(n=4096, f=10, seed=3):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    w = rs.randn(f)
    y = ((X @ w + 0.3 * rs.randn(n)) > 0).astype(np.float64)
    return X, y


def _train(params, X, y, rounds=15, **kw):
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    return lgb.train(dict(params), ds, num_boost_round=rounds, **kw)


BASE = {
    "objective": "binary",
    "num_leaves": 15,
    "learning_rate": 0.2,
    "metric": "auc",
    "verbosity": -1,
}


def test_data_parallel_matches_serial_binary():
    X, y = _binary_problem()
    b_serial = _train(BASE, X, y)
    b_data = _train({**BASE, "tree_learner": "data"}, X, y)
    assert b_data.num_trees() == b_serial.num_trees()
    np.testing.assert_allclose(
        b_data.predict(X), b_serial.predict(X), rtol=1e-4, atol=1e-5
    )


def test_data_parallel_matches_serial_regression_with_valid():
    rs = np.random.RandomState(5)
    X = rs.randn(4096, 8)
    w = rs.randn(8)
    y = X @ w + 0.1 * rs.randn(4096)
    Xv, yv = X[:512], y[:512]
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "learning_rate": 0.1,
        "metric": "l2",
        "verbosity": -1,
    }

    def go(extra):
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        vs = lgb.Dataset(Xv, label=yv, reference=ds, free_raw_data=False)
        return lgb.train({**params, **extra}, ds, num_boost_round=12,
                         valid_sets=[vs], valid_names=["v"])

    b_serial = go({})
    b_data = go({"tree_learner": "data"})
    np.testing.assert_allclose(
        b_data.predict(X[:200]), b_serial.predict(X[:200]), rtol=1e-4, atol=1e-5
    )


def test_voting_parallel_aliases_data():
    X, y = _binary_problem(n=2048)
    b = _train({**BASE, "tree_learner": "voting"}, X, y, rounds=5)
    assert b.num_trees() == 5


def test_data_parallel_multiclass():
    rs = np.random.RandomState(11)
    X = rs.randn(3000, 6)
    y = (X[:, 0] + 0.5 * rs.randn(3000) > 0).astype(int) + (
        X[:, 1] > 0.5
    ).astype(int)
    params = {
        "objective": "multiclass",
        "num_class": 3,
        "num_leaves": 7,
        "verbosity": -1,
    }
    b_serial = _train(params, X, y.astype(float), rounds=8)
    b_data = _train({**params, "tree_learner": "data"}, X, y.astype(float), rounds=8)
    ps, pd = b_serial.predict(X[:100]), b_data.predict(X[:100])
    np.testing.assert_allclose(pd, ps, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(pd.sum(axis=1), 1.0, rtol=1e-5)


def test_voting_parallel_trains():
    """tree_learner=voting: top-k election restricts the search and the
    psum payload; trees differ from tree_learner=data only by the
    election approximation (voting_parallel_tree_learner.cpp)."""
    from sklearn.metrics import roc_auc_score

    X, y = _binary_problem(n=4096, f=12, seed=9)
    b_vote = _train({**BASE, "tree_learner": "voting", "top_k": 4}, X, y)
    assert b_vote.num_trees() == 15
    auc = roc_auc_score(y, b_vote.predict(X))
    assert auc > 0.9

    # with top_k >= num_features the election is a no-op: identical to
    # tree_learner=data
    b_vote_full = _train({**BASE, "tree_learner": "voting", "top_k": 12}, X, y)
    b_data = _train({**BASE, "tree_learner": "data"}, X, y)
    np.testing.assert_allclose(
        b_vote_full.predict(X), b_data.predict(X), rtol=1e-4, atol=1e-5
    )


def test_voting_on_rounds_matches_data_saturated():
    """tree_learner=voting on the rounds grower (ISSUE 14): with
    top_k >= num_features every column wins election, so the per-round
    election is exact and predictions must match tree_learner=data on
    the same rounds path — the 8-mesh lockstep contract. With a small
    top_k the election restricts the search (and the wire) but the
    model must still learn."""
    from sklearn.metrics import roc_auc_score

    X, y = _binary_problem(n=4096, f=12, seed=9)
    r = {"tpu_growth_mode": "rounds"}
    b_vote = _train({**BASE, **r, "tree_learner": "voting", "top_k": 12},
                    X, y)
    b_data = _train({**BASE, **r, "tree_learner": "data"}, X, y)
    assert b_vote.num_trees() == b_data.num_trees()
    np.testing.assert_allclose(
        b_vote.predict(X), b_data.predict(X), rtol=1e-4, atol=1e-5
    )

    b_small = _train({**BASE, **r, "tree_learner": "voting", "top_k": 3},
                     X, y)
    assert b_small.num_trees() == 15
    assert roc_auc_score(y, b_small.predict(X)) > 0.9
    # provenance attrs the flight recorder / manifest read
    g = b_small._gbdt
    assert g.tree_learner_resolved == "voting"
    assert g.voting_elected_cols == 6  # 2 * top_k, no forced columns
    assert g.voting_wire_bytes_est and g.voting_wire_bytes_est > 0
    # the elected-only estimate must undercut the all-feature payload
    full = 3 * 12 * g.spec.num_bins * 4 * g.spec.num_leaves
    assert g.voting_wire_bytes_est < full


def test_voting_rounds_jaxpr_wire():
    """The voting grower's compiled program must contain NO full-width
    reduce-scatter: the election ships only elected columns, as an
    int16 psum payload when the quantized sums provably fit
    (rounds.vote_reduce + histogram.rs_wire_dtype). Asserted off the
    jaxpr with the same walkers the static audits use."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.cost_audit import collect_wire
    from lightgbm_tpu.analysis.jaxpr_audit import summarize
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, make_split_params
    from lightgbm_tpu.parallel.data_parallel import (
        DataParallelGrower,
        make_mesh,
    )

    X, _ = _binary_problem(seed=13)
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5})
    ds = BinnedDataset.from_numpy(X.astype(np.float32), cfg)
    d = ds.device_arrays()
    Np = ds.num_rows_padded()
    spec = GrowerSpec(num_leaves=15, num_bins=ds.max_num_bin,
                      max_depth=-1, rounds_slots=8, has_cat=False,
                      quant=True, quant_levels=4, voting_k=2)
    g = DataParallelGrower(make_mesh(), spec)
    gq = jnp.asarray(
        np.random.RandomState(0).randint(-2, 3, Np).astype(np.float32))
    hq = jnp.ones(Np, jnp.float32)
    closed = jax.make_jaxpr(lambda *a: g._fn(*a))(
        d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
        gq, hq, d["valid"], jnp.ones(ds.num_used_features, bool),
        make_split_params(cfg), d["valid"], None, None, None, None, None,
        jnp.asarray(np.float32([0.1, 0.1])),
    )
    s = summarize(closed)
    assert s.prim_counts.get("reduce_scatter", 0) == 0, (
        "full-width reduce-scatter wire survived under voting"
    )
    assert s.prim_counts.get("psum", 0) > 0
    wire = collect_wire(closed)
    assert any(w.prim == "psum" and w.dtype == "int16" for w in wire), (
        f"elected-column payload did not ride int16: {wire}"
    )


def test_rounds_and_efb_on_mesh():
    """Round-batched growth and EFB under shard_map: the rounds-body
    psums (global child counts, slot histograms) and the dense_visits
    slot budget only execute on a mesh — cover them here."""
    # sparse blocks so EFB actually bundles
    rs = np.random.RandomState(13)
    n = 4096
    Xs = np.zeros((n, 9))
    idx = rs.randint(0, 9, n)
    on = rs.rand(n) < 0.5
    Xs[np.arange(n)[on], idx[on]] = rs.rand(int(on.sum())) + 0.5
    Xd = rs.randn(n, 3)
    X = np.hstack([Xd, Xs])
    y = ((X[:, 0] + Xs.sum(1) + 0.3 * rs.randn(n)) > 0.7).astype(np.float64)
    serial = _train({**BASE, "tpu_growth_rounds": True}, X, y, rounds=8)
    mesh = _train(
        {**BASE, "tree_learner": "data", "tpu_growth_rounds": True}, X, y,
        rounds=8,
    )
    np.testing.assert_allclose(
        mesh.predict(X), serial.predict(X), rtol=1e-4, atol=1e-5
    )


def test_feature_parallel_matches_serial():
    """tree_learner=feature: features sharded over the mesh, every
    device holds all rows; the all-gathered winner records must
    reproduce serial trees exactly (feature_parallel_tree_learner.cpp:
    all ranks hold all data, so results equal serial by construction)."""
    X, y = _binary_problem(n=2048, f=10, seed=21)
    params = {**BASE, "enable_bundle": False}
    b_serial = _train(params, X, y, rounds=8)
    b_feat = _train({**params, "tree_learner": "feature"}, X, y, rounds=8)
    assert b_feat.num_trees() == b_serial.num_trees()
    np.testing.assert_allclose(
        b_feat.predict(X), b_serial.predict(X), rtol=1e-4, atol=1e-5
    )


def test_data_parallel_quant_reduce_scatter_wire():
    """Quantized data-parallel training rides the int32 reduce-scatter
    histogram wire with per-rank feature ownership (VERDICT r4 item 9;
    reference bin.h:63-81 + data_parallel_tree_learner.cpp:286).
    Lockstep contract: predictions match serial quantized training, and
    the compiled program actually contains an integer reduce-scatter."""
    X, y = _binary_problem(seed=11)
    q = {"use_quantized_grad": True, "num_grad_quant_bins": 4,
         "tpu_growth_mode": "rounds"}
    b_serial = _train({**BASE, **q}, X, y)
    b_data = _train({**BASE, **q, "tree_learner": "data"}, X, y)
    assert b_data.num_trees() == b_serial.num_trees()
    np.testing.assert_allclose(
        b_data.predict(X), b_serial.predict(X), rtol=1e-4, atol=1e-5
    )

    # wire-dtype assertion: the grower's jaxpr must reduce-scatter an
    # int32 histogram instead of full-psumming f32
    import jax

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, make_split_params
    from lightgbm_tpu.parallel.data_parallel import (
        DataParallelGrower,
        make_mesh,
    )

    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5})
    ds = BinnedDataset.from_numpy(X.astype(np.float32), cfg)
    d = ds.device_arrays()
    Np = ds.num_rows_padded()
    spec = GrowerSpec(num_leaves=15, num_bins=ds.max_num_bin, max_depth=-1,
                      rounds_slots=8, has_cat=False, quant=True,
                      quant_levels=4)
    g = DataParallelGrower(make_mesh(), spec)
    import jax.numpy as jnp

    gq = jnp.asarray(
        np.random.RandomState(0).randint(-2, 3, Np).astype(np.float32))
    hq = jnp.ones(Np, jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda *a: g._fn(*a)
    )(
        d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
        gq, hq, d["valid"], jnp.ones(ds.num_used_features, bool),
        make_split_params(cfg), d["valid"], None, None, None, None, None,
        jnp.asarray(np.float32([0.1, 0.1])),
    )
    txt = str(jaxpr)
    assert "reduce_scatter" in txt or "psum_scatter" in txt, (
        "integer reduce-scatter wire not found in the compiled grower"
    )
