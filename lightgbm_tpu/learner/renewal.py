"""Device-resident percentile leaf renewal for l1/huber/quantile/mape.

The reference refits each leaf's output to a weighted percentile of the
residuals of its in-bag rows (RegressionL1loss::RenewTreeOutput,
regression_objective.hpp:251; gbdt.cpp:418 RenewTreeOutput before
shrinkage). The host implementation loops leaves with numpy sorts; this
is the traced equivalent so renewal objectives can ride the fused
one-dispatch-per-iteration loop:

one `lax.sort` by (leaf, residual) groups every leaf's rows contiguously
in residual order; per-leaf cumulative weights come from the same
masked-fill trick as the device AUC; the percentile element is the first
row of each group whose in-group cumulative weight reaches
alpha * (group total), scattered back by leaf id.
"""

from __future__ import annotations


def renew_leaf_values(leaf_value, row_leaf, resid, w, alpha, num_leaves: int):
    """Weighted-percentile residual per leaf (traced).

    leaf_value: (L,) current outputs (kept where a leaf has no rows)
    row_leaf:   (N,) int32 leaf id per row; negative = not in any leaf
    resid:      (N,) f32 residuals (label - score)
    w:          (N,) f32 weights; 0 excludes a row (padding / out-of-bag)
    alpha:      percentile in [0, 1] (0.5 = median)
    """
    import jax.numpy as jnp
    from jax import lax

    N = row_leaf.shape[0]
    L = num_leaves
    incl = (w > 0) & (row_leaf >= 0)
    key_leaf = jnp.where(incl, row_leaf, L).astype(jnp.int32)
    sk, sr, sw = lax.sort(
        (key_leaf, resid.astype(jnp.float32), jnp.where(incl, w, 0.0)),
        num_keys=2,
    )
    start = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    end = jnp.concatenate([sk[1:] != sk[:-1], jnp.ones(1, bool)])

    # SEGMENTED inclusive cumsum: weight sums reset at each leaf group, so
    # magnitudes stay ~(leaf weight) instead of ~(total weight) — a global
    # f32 cumsum would stop resolving unit weights past 2^24 rows (the
    # host/reference equivalent accumulates per leaf in f64)
    def seg_op(a, b):
        fa, sa = a
        fb, sb = b
        return fa | fb, jnp.where(fb, sb, sa + sb)

    _, seg_cumw = lax.associative_scan(seg_op, (start, sw))
    # per-leaf total weight by direct segment-sum (pad group dropped)
    gtot_leaf = jnp.zeros(L, jnp.float32).at[sk].add(sw, mode="drop")
    gtotal = jnp.where(sk < L, gtot_leaf[jnp.minimum(sk, L - 1)], jnp.inf)
    # group end always counts as reached: the reference clamps the
    # percentile index to the last row (idx = min(searchsorted, len-1)),
    # and scan-vs-scatter rounding could otherwise leave alpha=1 unmet
    reached = (seg_cumw >= alpha * gtotal) | (end & (sk < L))
    reached_prev = jnp.concatenate([jnp.zeros(1, bool), reached[:-1]])
    first = reached & (start | ~reached_prev)
    # scatter: at most one `first` per leaf group; drop the pad group (L)
    idx = jnp.where(first & (sk < L), sk, L)
    return leaf_value.at[idx].set(sr, mode="drop")
