"""CLI: `python -m lightgbm_tpu.analysis [--strict] [...]`.

Runs the trace-safety lint over the package source, then the jaxpr
invariant audits, and prints a combined report. `--strict` (the CI /
tier-1 hook mode) exits 1 on any unsuppressed lint violation or failed
jaxpr contract; the default mode reports and exits 0.

The audits need a multi-device CPU mesh; this entry point forces
`jax_platforms=cpu` with 8 virtual devices (same as tests/conftest.py)
so a bare invocation never touches real accelerators.
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_cpu_mesh() -> None:
    """cpu + 8 virtual devices BEFORE any backend initializes (package
    import already loaded jax, but the backend is lazy — mirror the
    conftest.py override)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="trace-safety static analysis: AST lint + jaxpr "
        "invariant audit (docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation / failed contract")
    ap.add_argument("--lint-only", action="store_true",
                    help="skip the jaxpr audits (no jax backend needed)")
    ap.add_argument("--audit-only", action="store_true",
                    help="skip the AST lint")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed lint findings")
    ap.add_argument("--update-budget", action="store_true",
                    help="rewrite jaxpr_budget.json from current sizes "
                    "(+25%% headroom); review the diff before commit")
    ap.add_argument("--package", default=None,
                    help="package directory to lint (default: the "
                    "installed lightgbm_tpu package)")
    args = ap.parse_args(argv)

    failed = False

    if not args.audit_only:
        from .lint import format_findings, lint_package

        pkg = args.package
        if pkg is None:
            import lightgbm_tpu

            pkg = os.path.dirname(lightgbm_tpu.__file__)
        findings = lint_package(pkg)
        print(format_findings(findings,
                              show_suppressed=args.show_suppressed))
        if any(not f.suppressed for f in findings):
            failed = True

    if not args.lint_only:
        _force_cpu_mesh()
        from .jaxpr_audit import run_audits

        results = run_audits(update_budget=args.update_budget)
        for r in results:
            print(r.format())
        if not all(r.ok for r in results):
            failed = True
        if args.update_budget:
            print("jaxpr_budget.json updated")

    if failed:
        print("analysis: FAIL" if args.strict else
              "analysis: violations found (non-strict: exit 0)")
        return 1 if args.strict else 0
    print("analysis: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
