"""Quantized-gradient training (use_quantized_grad).

Reference: src/treelearner/gradient_discretizer.cpp:22 — per-iteration
gradient/hessian discretization to num_grad_quant_bins levels with
stochastic rounding (truncation toward zero of x/scale +- u), scales
g_scale = max|g| / (bins/2), h_scale = max|h| / bins, and optional
true-gradient leaf renewal (quant_train_renew_leaf,
RenewIntGradTreeOutput).

TPU formulation: the quantized levels flow through the standard
histogram kernel as DEQUANTIZED f32 values (level * scale) — the
accumulated sums equal the reference's int-histogram sums times the
scales up to f32 addition rounding, so split decisions match the
quantized semantics without new kernels. The deferred perf half
(int8 one-hot matmuls on the MXU + int16 psum payloads, the analog of
bin.h:63-81 wire reducers) slots in behind this same interface.

Randomness is keyed on (seed, iteration) — the reference's
pre-generated random value table with a rotating start offset
(gradient_discretizer.cpp:25-41) serves the same purpose.
"""

from __future__ import annotations


def discretize_gradients_int(
    grad,
    hess,
    key,
    num_bins: int,
    stochastic: bool,
):
    """(grad, hess) -> ((grad_q, hess_q) INTEGER levels, (2,) scales).

    Matches DiscretizeGradients: grad levels in [-bins/2, bins/2],
    hess levels in [0, bins]; stochastic rounding truncates toward zero
    after adding signed uniform noise, plain rounding truncates after
    adding 0.5. The integer levels feed the rounds grower's 3-channel
    exact-int histogram path (spec.quant)."""
    import jax
    import jax.numpy as jnp

    g_scale = jnp.maximum(jnp.max(jnp.abs(grad)), 1e-30) / (num_bins // 2)
    h_scale = jnp.maximum(jnp.max(jnp.abs(hess)), 1e-30) / num_bins
    if stochastic:
        kg, kh = jax.random.split(key)
        ug = jax.random.uniform(kg, grad.shape)
        uh = jax.random.uniform(kh, hess.shape)
    else:
        ug = 0.5
        uh = 0.5
    gq = jnp.trunc(grad / g_scale + jnp.sign(grad) * ug)
    hq = jnp.trunc(hess / h_scale + uh)  # hessians are non-negative
    return gq, hq, jnp.stack([g_scale, h_scale])


def discretize_gradients(
    grad,
    hess,
    key,
    num_bins: int,
    stochastic: bool,
):
    """(grad, hess) -> dequantized (grad_q, hess_q) at num_bins levels
    (level * scale), for the growers that consume plain f32 channels."""
    gq, hq, scale = discretize_gradients_int(
        grad, hess, key, num_bins, stochastic
    )
    return gq * scale[0], hq * scale[1]


def renew_leaf_with_true_gradients(leaf_value, row_leaf, grad, hess, mask,
                                   params, num_leaves: int):
    """quant_train_renew_leaf: recompute leaf outputs from the TRUE
    (unquantized) per-leaf gradient/hessian sums
    (gradient_discretizer RenewIntGradTreeOutput)."""
    import jax.numpy as jnp

    from .histogram import seg_sum
    from .split import leaf_output

    L = num_leaves
    idx = jnp.where((row_leaf >= 0) & (mask > 0), row_leaf, L)
    sums = seg_sum(jnp.stack([grad * mask, hess * mask]), idx, L)
    sum_g, sum_h = sums[0], sums[1]
    renewed = leaf_output(sum_g, sum_h, params)
    return jnp.where(sum_h > 0, renewed, leaf_value)
