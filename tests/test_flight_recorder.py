"""Flight recorder + anomaly sentinels (obs/recorder.py,
obs/anomaly.py, docs/OBSERVABILITY.md "Flight recorder & anomaly
policies"): JSONL stream round-trip, per-round records from both the
fused and eager loops, sentinel unit red-to-greens, the end-to-end
divergence abort, and the abort-path flush guarantees."""

from __future__ import annotations

import json
import math
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import boosting, timer
from lightgbm_tpu.obs import tracing
from lightgbm_tpu.obs.anomaly import AnomalyAbort, AnomalySentinel
from lightgbm_tpu.obs.metrics import default_registry
from lightgbm_tpu.obs.recorder import (
    SCHEMA,
    FlightRecorder,
    last_summary,
    read_stream,
)

REPO = Path(__file__).resolve().parents[1]


def _binary_sets(rng, n=400, nv=150, f=4):
    X = rng.randn(n, f)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    Xv = rng.randn(nv, f)
    yv = (Xv[:, 0] > 0).astype(np.float32)
    vs = lgb.Dataset(Xv, label=yv, reference=ds, free_raw_data=False)
    return ds, vs


# ------------------------------------------------------------ round-trip
def test_recorder_jsonl_roundtrip(tmp_path):
    path = tmp_path / "fr.jsonl"
    rec = FlightRecorder(str(path))
    rows = [
        {"round": 0, "evals": {"v l2": 1.0}},
        {"round": 1, "evals": {"v l2": 0.5}, "trees_per_sec": 3.0},
    ]
    for r in rows:
        rec.record(r)
    summary = rec.close()
    assert summary["rounds"] == 2
    assert summary["last_evals"] == {"v l2": 0.5}
    # first line is the schema header; read_stream skips it
    first = json.loads(path.read_text().splitlines()[0])
    assert first["schema"] == SCHEMA
    assert read_stream(str(path)) == rows
    # idempotent close; post-close records are dropped, not errors
    rec.record({"round": 2})
    assert rec.close()["rounds"] == 2
    assert last_summary()["rounds"] == 2


def test_recorder_memory_only():
    rec = FlightRecorder(None)
    rec.record({"round": 0})
    s = rec.close()
    assert s["rounds"] == 1 and s["path"] is None


# ------------------------------------------------------- training streams
def test_fused_loop_streams_full_records(rng, tmp_path):
    """The fused loop records round index, the per-round fused-step
    phase, chunk throughput, gh norms (from the eval-row tail — no
    extra readback), evals with higher-better flags, and tree stats."""
    ds, vs = _binary_sets(rng)
    path = tmp_path / "fused.jsonl"
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "record_file": str(path)},
              ds, num_boost_round=5, valid_sets=[vs], valid_names=["v"])
    recs = read_stream(str(path))
    assert [r["round"] for r in recs] == [0, 1, 2, 3, 4]
    for r in recs:
        assert boosting.FUSED_ROUND_PHASE in r["phases"]
        assert r["trees_per_sec"] > 0
        assert r["gnorm"] > 0 and r["hnorm"] > 0
        assert "v binary_logloss" in r["evals"]
        assert r["evals_hb"]["v binary_logloss"] is False
        assert len(r["trees"]) == 1
        t = r["trees"][0]
        assert t["leaves"] > 1 and t["depth"] >= 1 and t["leaf_finite"]
        assert t["best_gain"] > 0
    # chunk-level scopes ride the chunk's first record
    assert "fused dispatch" in recs[0]["chunk_phases"]


def test_fused_chunk_records_match_per_round_dispatch(rng, tmp_path):
    """Chunk-scan equivalence (ISSUE 18): with rounds dispatched as one
    lax.scan per chunk, the recorder must stream the SAME story as the
    per-round-dispatch loop — round indices, eval values, and gh norms
    bit-equal, and the apportioned FUSED_ROUND_PHASE span present in
    every record on both paths."""
    X = rng.randn(400, 4)
    y = (X[:, 0] > 0).astype(np.float32)
    Xv = rng.randn(150, 4)
    yv = (Xv[:, 0] > 0).astype(np.float32)

    def run(mode):
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        vs = lgb.Dataset(Xv, label=yv, reference=ds,
                         free_raw_data=False)
        path = tmp_path / f"fr_{mode}.jsonl"
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1, "record_file": str(path),
                   "tpu_chunk_scan": mode},
                  ds, num_boost_round=6, valid_sets=[vs],
                  valid_names=["v"])
        return read_stream(str(path))

    chunked, eager = run("auto"), run("off")
    assert [r["round"] for r in chunked] == [r["round"] for r in eager] \
        == list(range(6))
    assert [r["evals"] for r in chunked] == [r["evals"] for r in eager]
    assert [(r["gnorm"], r["hnorm"]) for r in chunked] == \
        [(r["gnorm"], r["hnorm"]) for r in eager]
    assert [[t["leaves"] for t in r["trees"]] for r in chunked] == \
        [[t["leaves"] for t in r["trees"]] for r in eager]
    for r in chunked + eager:
        assert boosting.FUSED_ROUND_PHASE in r["phases"]
        assert r["trees_per_sec"] > 0
    assert "fused dispatch" in chunked[0]["chunk_phases"]


def test_eager_fast_loop_streams_records(rng, tmp_path):
    """A pre-iteration callback forces the eager loop: every record
    carries the three ROUND_PHASES spans and gh norms (tree stats are
    deferred on the async fast path and legitimately absent)."""
    ds, vs = _binary_sets(rng)

    def cb(env):
        return None

    cb.before_iteration = True
    path = tmp_path / "eager.jsonl"
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "record_file": str(path)},
              ds, num_boost_round=3, valid_sets=[vs], valid_names=["v"],
              callbacks=[cb])
    recs = read_stream(str(path))
    assert len(recs) == 3
    for r in recs:
        for phase in boosting.ROUND_PHASES:
            assert phase in r["phases"], r["phases"]
        assert r["gnorm"] > 0 and r["hnorm"] > 0
        assert "v binary_logloss" in r["evals"]


@pytest.mark.slow
def test_eager_sync_loop_records_tree_stats(rng, tmp_path):
    """DART forces the per-iteration sync loop, whose host trees are
    materialized every round — tree stats appear in every record."""
    ds, vs = _binary_sets(rng)
    path = tmp_path / "dart.jsonl"
    lgb.train({"objective": "binary", "boosting": "dart",
               "num_leaves": 7, "verbosity": -1,
               "record_file": str(path)},
              ds, num_boost_round=3, valid_sets=[vs], valid_names=["v"])
    recs = read_stream(str(path))
    assert len(recs) == 3
    for r in recs:
        assert len(r["trees"]) == 1 and r["trees"][0]["leaves"] > 1


def test_record_evaluation_callback_matches_stream(rng, tmp_path):
    """Satellite contract: the recorder's learning curve and the
    reference record_evaluation callback see the SAME values."""
    ds, vs = _binary_sets(rng)
    result = {}
    path = tmp_path / "curve.jsonl"
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "record_file": str(path)},
              ds, num_boost_round=4, valid_sets=[vs], valid_names=["v"],
              callbacks=[lgb.record_evaluation(result)])
    recs = read_stream(str(path))
    curve = result["v"]["binary_logloss"]
    assert len(curve) == 4
    assert [r["evals"]["v binary_logloss"] for r in recs] == \
        pytest.approx(curve)


def test_eval_values_land_on_metrics_gauge(rng):
    ds, vs = _binary_sets(rng)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
              ds, num_boost_round=2, valid_sets=[vs], valid_names=["v"])
    snap = default_registry().snapshot()
    gauges = snap.get("lgbmtpu_eval_metric", {})
    key = '{dataset="v",metric="binary_logloss"}'
    assert key in gauges and math.isfinite(gauges[key])


# ------------------------------------------------------- sentinel units
def _rec(i, **kw):
    return dict({"round": i}, **kw)


def test_sentinel_nan_metric_and_policy():
    s = AnomalySentinel("warn")
    s.check(_rec(0, evals={"v l2": 1.0}, evals_hb={"v l2": False}))
    assert not s.trips
    s.check(_rec(1, evals={"v l2": float("nan")},
                 evals_hb={"v l2": False}))
    assert [t["kind"] for t in s.trips] == ["nan_metric"]

    hard = AnomalySentinel("abort")
    with pytest.raises(AnomalyAbort) as ei:
        hard.check(_rec(0, evals={"v l2": float("inf")},
                        evals_hb={"v l2": False}))
    assert ei.value.kind == "nan_metric" and ei.value.round_idx == 0

    off = AnomalySentinel("off")
    off.check(_rec(0, evals={"v l2": float("nan")}))
    assert not off.trips
    with pytest.raises(ValueError):
        AnomalySentinel("explode")


def test_sentinel_nan_leaf():
    s = AnomalySentinel("warn")
    s.check(_rec(0, trees=[{"leaves": 3, "best_gain": 1.0,
                            "leaf_finite": True}]))
    s.check(_rec(1, trees=[{"leaves": 3, "best_gain": 1.0,
                            "leaf_finite": False}]))
    assert [t["kind"] for t in s.trips] == ["nan_leaf"]


def test_sentinel_loss_spike_rolling_median():
    s = AnomalySentinel("warn")
    for i, v in enumerate([1.0, 1.1, 0.9]):
        s.check(_rec(i, evals={"v l2": v}, evals_hb={"v l2": False}))
    assert not s.trips
    s.check(_rec(3, evals={"v l2": 5.0}, evals_hb={"v l2": False}))
    assert [t["kind"] for t in s.trips] == ["loss_spike"]
    # higher-better metrics never spike-trip (NaN check only)
    s2 = AnomalySentinel("warn")
    for i, v in enumerate([0.5, 0.5, 0.5, 50.0]):
        s2.check(_rec(i, evals={"v auc": v}, evals_hb={"v auc": True}))
    assert not s2.trips


def test_sentinel_throughput_collapse():
    s = AnomalySentinel("warn")
    for i, tps in enumerate([10.0, 11.0, 10.0]):
        s.check(_rec(i, trees_per_sec=tps))
    assert not s.trips
    s.check(_rec(3, trees_per_sec=1.0))
    assert [t["kind"] for t in s.trips] == ["throughput_collapse"]


def test_sentinel_dead_rounds_streak():
    s = AnomalySentinel("warn", max_dead_rounds=3)
    dead = [{"leaves": 1, "best_gain": 0.0, "leaf_finite": True}]
    alive = [{"leaves": 5, "best_gain": 2.0, "leaf_finite": True}]
    s.check(_rec(0, trees=dead))
    s.check(_rec(1, trees=alive))  # streak resets
    for i in range(2, 5):
        s.check(_rec(i, trees=dead))
    assert [t["kind"] for t in s.trips] == ["dead_rounds"]


def test_sentinel_trip_emits_counter_and_trace_instant():
    reg = default_registry()
    c = reg.counter("lgbmtpu_anomaly_trips_total", labels=("kind",))
    before = c.value(kind="nan_metric")
    with tracing.tracing() as rec:
        s = AnomalySentinel("warn")
        s.check(_rec(7, evals={"v l2": float("nan")},
                     evals_hb={"v l2": False}))
    assert c.value(kind="nan_metric") == before + 1
    instants = [e for e in rec.events()
                if e.get("ph") == "i" and e["name"] == "anomaly: nan_metric"]
    assert instants and instants[0]["args"]["round"] == 7


# -------------------------------------------------------- end-to-end abort
def test_divergence_trips_loss_spike_within_bounded_rounds(rng, tmp_path):
    """ACCEPTANCE: a deliberately diverging config (learning_rate=5 on
    l2: the residual quadruples per round) trips the loss-spike
    sentinel within a bounded number of rounds under abort, the
    recorder JSONL + manifest survive the abort, and the trip is
    visible as a metrics counter."""
    X = rng.randn(400, 4)
    y = X[:, 0] + 0.1 * rng.randn(400)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    Xv = rng.randn(150, 4)
    vs = lgb.Dataset(Xv, label=Xv[:, 0], reference=ds,
                     free_raw_data=False)
    path = tmp_path / "diverge.jsonl"
    reg = default_registry()
    c = reg.counter("lgbmtpu_anomaly_trips_total", labels=("kind",))
    before = c.value(kind="loss_spike")
    sinks_before = len(timer._trace_sinks)

    with pytest.raises(AnomalyAbort) as ei:
        lgb.train({"objective": "regression", "metric": "l2",
                   "num_leaves": 7, "learning_rate": 5.0,
                   "verbosity": -1, "record_file": str(path),
                   "anomaly_policy": "abort"},
                  ds, num_boost_round=14,
                  valid_sets=[vs], valid_names=["v"])
    assert ei.value.kind == "loss_spike"
    assert ei.value.round_idx <= 10  # bounded: spike_min_rounds + slack
    # the trip is a metrics counter
    assert c.value(kind="loss_spike") == before + 1
    # flush-and-close is exception-safe: no torn timer sink...
    assert len(timer._trace_sinks) == sinks_before
    # ...every line of the stream parses, the tail is a complete record
    lines = path.read_text().splitlines()
    parsed = [json.loads(l) for l in lines]  # raises on a torn tail
    assert parsed[0]["schema"] == SCHEMA
    tail = parsed[-1]
    assert tail["round"] == ei.value.round_idx
    assert "evals" in tail
    # ...and the manifest written AFTER the abort carries the summary
    from lightgbm_tpu.obs.manifest import write_manifest

    m = write_manifest(str(tmp_path / "manifest.json"))
    fr = m["flight_recorder"]
    assert fr["path"] == str(path)
    assert fr["rounds"] == len(parsed) - 1
    assert fr["anomalies"]["loss_spike"] == 1
    on_disk = json.loads((tmp_path / "manifest.json").read_text())
    assert on_disk["flight_recorder"]["anomalies"]["loss_spike"] == 1


def test_unrecorded_run_clears_stale_summary(rng, tmp_path):
    """A manifest written after an UNRECORDED run must not carry the
    previous recorded run's flight-record section (regression: the
    module-global summary used to leak into every later manifest)."""
    from lightgbm_tpu.obs.manifest import build_manifest

    ds, vs = _binary_sets(rng)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "record_file": str(tmp_path / "one.jsonl")},
              ds, num_boost_round=2, valid_sets=[vs], valid_names=["v"])
    assert build_manifest().get("flight_recorder") is not None
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
              ds, num_boost_round=2, valid_sets=[vs], valid_names=["v"])
    assert build_manifest().get("flight_recorder") is None


def test_warn_policy_does_not_abort(rng, tmp_path):
    """Same diverging config under warn: training runs to completion,
    trips are counted into the recorder summary."""
    X = rng.randn(300, 4)
    y = X[:, 0]
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    vs = lgb.Dataset(rng.randn(100, 4), label=np.zeros(100),
                     reference=ds, free_raw_data=False)
    path = tmp_path / "warn.jsonl"
    bst = lgb.train({"objective": "regression", "metric": "l2",
                     "num_leaves": 7, "learning_rate": 5.0,
                     "verbosity": -1, "record_file": str(path),
                     "anomaly_policy": "warn"},
                    ds, num_boost_round=6,
                    valid_sets=[vs], valid_names=["v"])
    assert bst.num_trees() == 6
    assert last_summary()["anomalies"].get("loss_spike", 0) >= 1
