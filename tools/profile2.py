"""Careful in-jit loop timings to separate dispatch from device cost."""
import os, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N, F, B, L = 1_048_576, 28, 256, 255
from lightgbm_tpu.learner.histogram import HIST_BLK, build_gh8, histogram
from lightgbm_tpu.learner.split import best_split
from lightgbm_tpu.learner import make_split_params
from lightgbm_tpu.config import Config

rs = np.random.RandomState(0)
bins = jnp.asarray(rs.randint(0, B-1, size=(F, N)).astype(np.int32))
gh8 = jnp.asarray(rs.randn(8, N).astype(np.float32))
nan_bin = jnp.full(F, -1, jnp.int32); num_bins = jnp.full(F, B, jnp.int32)
mono = jnp.zeros(F, jnp.int32); is_cat = jnp.zeros(F, bool); fm = jnp.ones(F, bool)
params = make_split_params(Config({"num_leaves": L}))

def bench(name, jitted, *args, iters=1):
    r = jitted(*args); jax.block_until_ready(r)
    t0 = time.time(); r = jitted(*args); jax.block_until_ready(r)
    dt = time.time() - t0
    print(f"{name}: {dt/iters*1000:.3f} ms/iter  (total {dt*1000:.1f} ms / {iters})")

# pallas hist, 20 carry-dependent calls in one jit
@jax.jit
def hist20(b, g):
    def body(i, acc):
        h = histogram(b, g + acc[0,0,0]*0 + i*0.0, B)  # carry dep to defeat CSE
        return acc + h
    return lax.fori_loop(0, 20, body, jnp.zeros((3, F, B), jnp.float32))
bench("pallas hist full-N x20 in-jit", hist20, bins, gh8, iters=20)

# best_split, 100 carry-dependent calls
@jax.jit
def bs100(h):
    def body(i, acc):
        r = best_split(h + acc*0, jnp.float32(0.), jnp.float32(N), jnp.float32(N),
                       num_bins, nan_bin, mono, is_cat, params, fm)
        return acc + r.gain
    return lax.fori_loop(0, 100, body, jnp.float32(0.))
h0 = histogram(bins, gh8, B); jax.block_until_ready(h0)
bench("best_split x100 in-jit", bs100, h0, iters=100)

# gather along axis0 of (N, F) vs axis1 of (F, N)
bins_nm = jnp.asarray(np.ascontiguousarray(np.asarray(bins).T))  # (N, F)
perm = jnp.asarray(rs.permutation(N).astype(np.int32))
g0 = jax.jit(lambda b, p: jnp.take(b, p, axis=0))
bench("gather (N,F) axis0", g0, bins_nm, perm)
# 1-D gather
col = bins[0]
g1 = jax.jit(lambda c, p: jnp.take(c, p))
bench("gather 1-D (N,)", g1, col, perm)
# scatter 1-D
s1 = jax.jit(lambda c, p: jnp.zeros_like(c).at[p].set(c))
bench("scatter 1-D (N,)", s1, col, perm)
# cumsum full-N
cs = jax.jit(lambda m: jnp.cumsum(m))
bench("cumsum (N,) int32", cs, col)
# sort full-N with 1 payload
srt = jax.jit(lambda k, v: lax.sort((k, v), num_keys=1))
bench("sort (N,) key + 1 payload", srt, col, perm)

# empty-ish while_loop fixed overhead: 254 iterations of trivial bookkeeping
@jax.jit
def wl(x):
    def cond(s): return s[0] < 254
    def body(s):
        i, a = s
        return (i+1, a.at[i].set(a[i] + 1.0))
    return lax.while_loop(cond, body, (jnp.int32(0), x))
bench("while_loop 254 trivial iters", wl, jnp.zeros(L, jnp.float32), iters=254)
