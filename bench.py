"""Benchmark: Higgs-1M-like GBDT training throughput on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's published Higgs result — 500 iterations of
255-leaf trees over 10.5M x 28 in 130.094 s on 2xE5-2690v4
(reference docs/Experiments.rst:104-121, see BASELINE.md). Scaled
linearly to this bench's row count (histogram GBDT cost is ~linear in
rows), i.e. baseline trees/sec at R rows = (500 / 130.094) * (10.5e6 / R).

Robustness (the round-2 bench died on a TPU-backend init hang and left
no evidence): the accelerator backend is probed in a SUBPROCESS with a
hard timeout before jax is imported here; on probe failure the bench
falls back to JAX_PLATFORMS=cpu instead of hanging. Progress lines go
to stderr per iteration chunk, and partial results are persisted to
bench_partial.json as training advances, so even a killed run yields
data. The final stdout line is always the single JSON line.

The timed loop trains WITH per-iteration validation metrics enabled
(device-resident eval on a held-out set) — deliberately a heavier
workload than the baseline's bare training time, because sustained
trees/sec with live eval is the number that matters for users.

Env overrides: BENCH_ROWS, BENCH_FEATURES, BENCH_LEAVES, BENCH_TREES,
BENCH_WARMUP, BENCH_MAX_BIN, BENCH_PROBE_TIMEOUT (s), BENCH_FORCE_CPU.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))

_PROBE_SRC = r"""
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print(jax.devices()[0].platform)
"""


def probe_backend(timeout_s: float) -> str:
    """Run a tiny jit in a subprocess; return its platform or 'cpu'."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
        sys.stderr.write(
            f"[bench] backend probe rc={r.returncode}: "
            f"{r.stderr.strip()[-500:]}\n"
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"[bench] backend probe timed out ({timeout_s}s)\n")
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"[bench] backend probe failed: {e}\n")
    return "cpu"


def main() -> None:
    rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    leaves = int(os.environ.get("BENCH_LEAVES", 255))
    trees = int(os.environ.get("BENCH_TREES", 100))
    warmup = int(os.environ.get("BENCH_WARMUP", 2))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 255))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))
    partial_path = os.path.join(REPO, "bench_partial.json")

    if os.environ.get("BENCH_FORCE_CPU"):
        platform = "cpu"
    elif os.environ.get("JAX_PLATFORMS") == "cpu":
        platform = "cpu"
    else:
        # probe even when JAX_PLATFORMS=axon (the default env): the probe
        # exists precisely to detect a dead TPU tunnel before hanging
        t0 = time.time()
        platform = probe_backend(probe_timeout)
        sys.stderr.write(
            f"[bench] backend probe -> {platform} in {time.time()-t0:.0f}s\n"
        )
    if platform == "cpu":
        # sitecustomize may have imported jax already — the env var alone
        # is read too early, set the config explicitly as well
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, REPO)
    import lightgbm_tpu as lgb

    rs = np.random.RandomState(17)
    X = rs.randn(rows, feats).astype(np.float32)
    w = rs.randn(feats)
    logits = X[:, : feats // 2] @ w[: feats // 2] + np.sin(X[:, feats // 2]) * 2.0
    y = (logits + rs.randn(rows) > 0).astype(np.float32)
    # held-out validation rows (NOT part of the training matrix)
    nv = min(rows // 10, 100_000)
    Xv = rs.randn(nv, feats).astype(np.float32)
    lv = Xv[:, : feats // 2] @ w[: feats // 2] + np.sin(Xv[:, feats // 2]) * 2.0
    yv = (lv + rs.randn(nv) > 0).astype(np.float32)

    params = {
        "objective": "binary",
        "num_leaves": leaves,
        "max_bin": max_bin,
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "metric": "auc",
        "verbosity": -1,
    }
    t0 = time.time()
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    ds.construct()
    vs = lgb.Dataset(Xv, label=yv, reference=ds, free_raw_data=False)
    sys.stderr.write(f"[bench] dataset built in {time.time()-t0:.1f}s\n")

    state = {"platform": platform, "rows": rows, "leaves": leaves}

    def save_partial(**kw):
        state.update(kw)
        try:
            with open(partial_path, "w") as f:
                json.dump(state, f)
        except OSError:
            pass

    save_partial(stage="warmup")
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=warmup,
              valid_sets=[vs], valid_names=["v"])
    compile_s = time.time() - t0
    sys.stderr.write(f"[bench] warmup ({warmup} trees) in {compile_s:.1f}s\n")
    save_partial(stage="timed", warmup_s=round(compile_s, 2))

    def progress(env):
        done = env.iteration + 1
        if done % 10 == 0 or done == trees:
            dt = time.time() - t0
            tps = done / dt if dt > 0 else 0.0
            sys.stderr.write(f"[bench] {done}/{trees} trees, {tps:.3f} trees/s\n")
            save_partial(trees_done=done, elapsed_s=round(dt, 2),
                         trees_per_sec=round(tps, 4))

    t0 = time.time()
    bst2 = lgb.train(dict(params), ds, num_boost_round=trees,
                     valid_sets=[vs], valid_names=["v"],
                     callbacks=[progress])
    dt = time.time() - t0

    trees_per_sec = trees / dt
    baseline_tps = (500.0 / 130.094) * (10.5e6 / rows)
    auc = None
    try:
        from sklearn.metrics import roc_auc_score

        auc = float(roc_auc_score(yv, bst2.predict(Xv)))
    except Exception:  # noqa: BLE001
        pass

    out = {
        "metric": f"higgs_synth_{rows // 1000}k_{leaves}leaves_trees_per_sec",
        "value": round(trees_per_sec, 4),
        "unit": "trees/sec",
        "vs_baseline": round(trees_per_sec / baseline_tps, 4),
        "platform": platform,
    }
    if auc is not None:
        out["auc_valid"] = round(auc, 5)
    save_partial(stage="done", **out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
