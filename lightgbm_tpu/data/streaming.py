"""Streaming two-pass binning over a chunk store (docs/DATA_PLANE.md
"Two-pass protocol").

Pass 1 reads each raw chunk once and keeps only the sampled rows —
the SAME `data_random_seed` + `bin_construct_sample_cnt` draw the
in-RAM `BinnedDataset.from_numpy` makes, so the fitted bin mappers are
identical to the in-RAM path on the same data (and when the dataset is
small enough that the sample IS the data, the EFB layout is too, which
makes the whole fit bit-exact; at larger scale the layout derives from
the sample exactly like the Sequence streaming path).

Pass 2 re-reads chunks sequentially and spools the packed (G, rows)
bin representation into a second "binned" store with the SAME chunk
boundaries. At no point are two raw chunks resident: iteration holds
one chunk, `bin_chunk` emits the int matrix, and the raw chunk is
dropped before the next read.

The resulting :class:`StreamedBinnedDataset` never holds the full
(G, N) host matrix either — `device_arrays` assembles the device-
resident bin matrix chunk-by-chunk via the double-buffered prefetcher
(`prefetch.py`), recording per-chunk peak RSS for the run manifest.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import ram_budget_bytes, record_stats, warn_over_budget
from .. import log
from ..config import Config
from ..dataset import (
    BinnedDataset,
    Metadata,
    _choose_bin_dtype,
    bin_chunk,
)
from ..learner.histogram import HIST_BLK
from .prefetch import (
    ChunkPrefetcher,
    chunk_update_step,
    prefetch_depth,
    read_rss_mb,
)
from .store import ChunkStore, ChunkStoreError, SpooledData, spool_numpy

# bounds for the auto-derived chunk size (rows); both HIST_BLK multiples
_MIN_CHUNK_ROWS = HIST_BLK
_MAX_CHUNK_ROWS = 1 << 20


def resolve_chunk_rows(n_features: int, config: Config) -> int:
    """Chunk size in rows: explicit ``data_chunk_rows`` wins; otherwise
    size chunks so ~4 raw float64 chunks fit in ``ram_budget_mb``
    (1 resident + prefetch depth + slack), rounded to a HIST_BLK
    multiple and clamped."""
    if config.data_chunk_rows:
        rows = int(config.data_chunk_rows)
    else:
        budget = ram_budget_bytes(config.ram_budget_mb)
        per_row = max(1, int(n_features)) * 8
        rows = budget // (4 * per_row)
    rows = max(_MIN_CHUNK_ROWS, min(_MAX_CHUNK_ROWS, rows))
    return (rows // HIST_BLK) * HIST_BLK


# ---------------------------------------------------------------------------
# pass 1: fit mappers from the exact from_numpy sample draw
# ---------------------------------------------------------------------------
def _gather_sample(store: ChunkStore, config: Config) -> np.ndarray:
    """(sample_cnt, F) float64 drawn with the from_numpy RNG: same
    seed, same sorted choice over global row indices — chunk reads just
    slice the rows that landed in this chunk's range."""
    total = store.total_rows
    rng = np.random.RandomState(config.data_random_seed)
    sample_cnt = min(total, config.bin_construct_sample_cnt)
    if sample_cnt < total:
        idx = np.sort(rng.choice(total, sample_cnt, replace=False))
    else:
        idx = np.arange(total, dtype=np.int64)
    sample = np.empty((len(idx), store.n_features), dtype=np.float64)
    for _ci, row0, arrays in store.iter_chunks():
        rows = arrays["cols"].shape[1]
        lo = int(np.searchsorted(idx, row0))
        hi = int(np.searchsorted(idx, row0 + rows))
        if hi > lo:
            sample[lo:hi] = arrays["cols"].T[idx[lo:hi] - row0]
    return sample


def stream_bin(
    store: ChunkStore,
    config: Config,
    bin_root,
    categorical_feature: Optional[Sequence[int]] = None,
    feature_names: Optional[Sequence[str]] = None,
) -> Tuple[BinnedDataset, ChunkStore]:
    """Two-pass binning: returns (proto, binned store). The proto
    carries mappers/EFB/feature bookkeeping but an EMPTY bin matrix —
    the bins live on disk, chunked on the raw store's boundaries."""
    t0 = time.monotonic()
    if not store.complete:
        raise ChunkStoreError(
            f"spool at {store.root} is not finalized; resume + finalize "
            "it before binning"
        )
    if store.total_rows == 0:
        log.fatal("cannot construct Dataset from an empty spool")
    sample = _gather_sample(store, config)
    if not feature_names and store.manifest.get("feature_names"):
        feature_names = list(store.manifest["feature_names"])
    proto = BinnedDataset.from_numpy(
        sample, config,
        categorical_feature=categorical_feature,
        feature_names=feature_names,
    )
    dtype = proto.bins.dtype
    G = proto.bins.shape[0]
    # the sample's bin matrix is dead weight from here on
    proto.bins = np.empty((G, 0), dtype=dtype)
    proto.invalidate_device_cache()
    t1 = time.monotonic()
    record_stats("pass1", {
        "sample_rows": int(sample.shape[0]),
        "total_rows": int(store.total_rows),
        "seconds": round(t1 - t0, 3),
        "rss_mb": round(read_rss_mb(), 1),
    })
    del sample

    bin_store = ChunkStore.create(
        bin_root, n_features=G, chunk_rows=store.chunk_rows,
        kind="binned", value_dtype=str(np.dtype(dtype)),
        extra={"raw_spool": str(store.root)},
    )
    rss_per_chunk: List[float] = []
    for _ci, _row0, arrays in store.iter_chunks():
        chunk = np.ascontiguousarray(arrays["cols"].T)
        del arrays  # drop the raw chunk before the next read
        bin_store.append_binned(bin_chunk(proto, chunk, dtype))
        del chunk
        rss_per_chunk.append(round(read_rss_mb(), 1))
    bin_store.finalize()
    t2 = time.monotonic()
    record_stats("pass2", {
        "chunks": bin_store.num_chunks,
        "chunk_rows": store.chunk_rows,
        "seconds": round(t2 - t1, 3),
        "rows_per_sec": round(store.total_rows / max(1e-9, t2 - t1)),
        "rss_mb_per_chunk": rss_per_chunk,
        "binned_bytes": bin_store.spool_bytes(),
    })
    return proto, bin_store


# ---------------------------------------------------------------------------
# streamed dataset: disk-resident bins, chunk-wise device assembly
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _jitted_step(donate: bool):
    import jax

    # XLA:CPU donation on update-in-place steps has a history of
    # segfaults (see tests/conftest.py NOTE); gate it to accelerators
    return jax.jit(
        chunk_update_step, donate_argnums=(0,) if donate else ()
    )


@dataclass
class StreamedBinnedDataset(BinnedDataset):
    """BinnedDataset whose bin matrix lives in a binned chunk store.

    ``bins`` holds a (G, 0) placeholder; every consumer goes through
    ``device_arrays()`` (the single chokepoint in boosting/basic), which
    assembles the (G, Np) device matrix chunk-by-chunk behind the
    prefetcher instead of pushing one giant host array. Host-matrix
    consumers (save_binary, subset) use :meth:`materialize_bins` /
    :meth:`copy_subrow`, which stream and warn when the result exceeds
    the RAM budget."""

    bin_store: Optional[ChunkStore] = None
    ram_budget_mb: int = 0

    def device_arrays(self) -> Dict[str, Any]:
        if self._device is not None:
            return self._device
        import jax
        import jax.numpy as jnp

        assert self.bin_store is not None
        store = self.bin_store
        npad = self.num_rows_padded()
        G = store.n_features  # bundle columns
        chunk_rows = store.chunk_rows

        def load(idx: int) -> Tuple[np.ndarray, Dict[str, Any]]:
            # host-only (reader thread): read + verify + widen + pad
            arrays = store.read_chunk(idx)
            b = arrays["bins"].astype(np.int32)
            lo = int(store.chunk_meta(idx)["row0"])
            rows = b.shape[1]
            # pad to a constant width (tail pads to the buffer edge) so
            # the update step compiles at most twice: body + tail
            width = chunk_rows if idx < store.num_chunks - 1 \
                else max(npad - lo, rows)
            if rows != width:
                padded = np.zeros((G, width), dtype=np.int32)
                padded[:, :rows] = b
                b = padded
            return b, {"lo": lo, "rows": rows}

        chunk_bytes = G * chunk_rows * 4
        depth = prefetch_depth(
            chunk_bytes, ram_budget_bytes(self.ram_budget_mb)
        )
        donate = jax.default_backend() != "cpu"
        step = _jitted_step(donate)
        t0 = time.monotonic()
        buf = jnp.zeros((G, npad), dtype=jnp.int32)
        per_chunk: List[Dict[str, Any]] = []
        prev_rss = read_rss_mb()
        with ChunkPrefetcher(load, store.num_chunks, depth=depth) as pf:
            for idx, dev_chunk, info in pf:
                buf = step(buf, dev_chunk, np.int32(info["lo"]))
                buf.block_until_ready()
                rss = read_rss_mb()
                per_chunk.append({
                    "chunk": idx,
                    "rows": info["rows"],
                    "rss_mb": round(rss, 1),
                    "rss_delta_mb": round(rss - prev_rss, 1),
                })
                prev_rss = rss
        # flatness: spread of steady-state RSS (chunk 0 excluded — it
        # pays the one-time device buffer + compile cost)
        steady = [c["rss_mb"] for c in per_chunk[1:]] or \
                 [c["rss_mb"] for c in per_chunk]
        record_stats("assemble", {
            "chunks": len(per_chunk),
            "chunk_rows": chunk_rows,
            "prefetch_depth": depth,
            "donate": donate,
            "seconds": round(time.monotonic() - t0, 3),
            "per_chunk": per_chunk,
            "peak_rss_mb": round(max(c["rss_mb"] for c in per_chunk), 1),
            "rss_spread_mb": round(max(steady) - min(steady), 1),
        })

        um = self.used_mappers()
        from ..binning import BinType

        f = self.num_used_features
        nan_bin = np.array([m.nan_bin for m in um], dtype=np.int32)
        num_bins = np.array([m.num_bin for m in um], dtype=np.int32)
        is_cat = np.array([m.bin_type == BinType.CATEGORICAL for m in um])
        mono = (
            self.monotone_constraints.astype(np.int32)
            if self.monotone_constraints is not None
            else np.zeros(f, dtype=np.int32)
        )
        valid = np.zeros(npad, dtype=np.float32)
        valid[: self.num_data] = 1.0
        self._device = {
            "bins": buf,
            "valid": jnp.asarray(valid),
            "nan_bin": jnp.asarray(nan_bin),
            "num_bins": jnp.asarray(num_bins),
            "mono": jnp.asarray(mono),
            "is_cat": jnp.asarray(is_cat),
            "bundle": self._bundle_info(),
        }
        return self._device

    # ------------------------------------------------ host-matrix paths
    def materialize_bins(self) -> np.ndarray:
        """Stream the full (G, N) bin matrix back into host memory
        (save_binary etc.) — warns through the budget path first."""
        assert self.bin_store is not None
        store = self.bin_store
        dtype = _choose_bin_dtype(self.col_bins)
        nbytes = store.n_features * self.num_data * np.dtype(dtype).itemsize
        warn_over_budget(
            f"materializing the binned matrix of {self.num_data} rows",
            nbytes, self.ram_budget_mb,
            "prefer the chunked consumers (device_arrays/save chunked)",
        )
        out = np.empty((store.n_features, self.num_data), dtype=dtype)
        for _ci, row0, arrays in store.iter_chunks():
            b = arrays["bins"]
            out[:, row0: row0 + b.shape[1]] = b.astype(dtype)
        return out

    def copy_subrow(self, indices: np.ndarray) -> "BinnedDataset":
        """Subset by streaming only the chunks that hold selected rows;
        returns an ORDINARY in-RAM BinnedDataset (subsets are small —
        bagging/valid slices — by the time anyone calls this)."""
        idx = np.asarray(indices, dtype=np.int64)
        assert self.bin_store is not None
        store = self.bin_store
        dtype = _choose_bin_dtype(self.col_bins)
        sub = np.empty((store.n_features, len(idx)), dtype=dtype)
        order = np.argsort(idx, kind="stable")
        sidx = idx[order]
        pos = 0
        for ci in range(store.num_chunks):
            meta = store.chunk_meta(ci)
            row0, rows = int(meta["row0"]), int(meta["rows"])
            hi = int(np.searchsorted(sidx, row0 + rows))
            if hi <= pos:
                continue
            arrays = store.read_chunk(ci)
            local = sidx[pos:hi] - row0
            sub[:, order[pos:hi]] = arrays["bins"][:, local].astype(dtype)
            pos = hi
            if pos == len(sidx):
                break
        return BinnedDataset(
            bins=sub,
            mappers=self.mappers,
            used_features=self.used_features,
            num_data=len(idx),
            metadata=self._subset_metadata(idx),
            feature_names=self.feature_names,
            max_num_bin=self.max_num_bin,
            row_block=self.row_block,
            monotone_constraints=self.monotone_constraints,
            bundle_layout=self.bundle_layout,
            bundle_expand=self.bundle_expand,
        )


# ---------------------------------------------------------------------------
# entry point: raw input of any kind -> StreamedBinnedDataset
# ---------------------------------------------------------------------------
def construct_chunked(
    data: Any,
    config: Config,
    label: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    init_score: Optional[np.ndarray] = None,
    position: Optional[np.ndarray] = None,
    categorical_feature: Optional[Sequence[int]] = None,
    feature_names: Optional[Sequence[str]] = None,
) -> StreamedBinnedDataset:
    """data_source=chunked construct: spool `data` (numpy matrix,
    SpooledData handle, Sequence list, or delimited text path) into a
    raw chunk store, stream-bin it, and return the disk-backed
    dataset. Spool placement: ``data_spool_dir`` or a self-cleaning
    temp dir."""
    t0 = time.monotonic()
    owned, root = _spool_root(config)
    qid = None

    if isinstance(data, SpooledData):
        store = data.store
        if not store.complete:
            store.finalize()
    elif isinstance(data, (str, Path)):
        from .store import spool_text_file

        store, names = spool_text_file(
            data, root / "raw",
            chunk_rows=resolve_chunk_rows(1, config)
            if config.data_chunk_rows == 0 else int(config.data_chunk_rows),
            header=config.header,
            label_column=config.label_column or 0,
            weight_column=config.weight_column,
            group_column=config.group_column,
            ignore_column=config.ignore_column,
        )
        if names and feature_names is None:
            feature_names = names
        if label is None:
            label = store.gather_meta("label")
        if weight is None:
            weight = store.gather_meta("weight")
        qid = store.gather_meta("qid")
    elif isinstance(data, np.ndarray) or hasattr(data, "__array__"):
        X = np.asarray(data)
        store = spool_numpy(
            X, root / "raw",
            chunk_rows=resolve_chunk_rows(X.shape[1], config),
        )
    elif isinstance(data, (list, tuple)) or hasattr(data, "__getitem__"):
        seqs = data if isinstance(data, (list, tuple)) else [data]
        nf = int(np.asarray(seqs[0][0]).reshape(-1).shape[0])
        chunk_rows = resolve_chunk_rows(nf, config)
        store = ChunkStore.create(
            root / "raw", n_features=nf, chunk_rows=chunk_rows
        )
        for s in seqs:
            bs = int(getattr(s, "batch_size", 4096) or 4096)
            for lo in range(0, len(s), bs):
                block = np.asarray(s[lo: lo + bs], np.float64)
                if block.ndim == 1:
                    block = block.reshape(1, -1)
                store.append_rows(block)
        store.finalize()
    else:
        raise ChunkStoreError(
            f"data_source=chunked cannot ingest {type(data).__name__}"
        )

    t1 = time.monotonic()
    record_stats("spool", {
        "rows": store.total_rows,
        "features": store.n_features,
        "chunks": store.num_chunks,
        "chunk_rows": store.chunk_rows,
        "spool_bytes": store.spool_bytes(),
        "seconds": round(t1 - t0, 3),
        "rows_per_sec": round(store.total_rows / max(1e-9, t1 - t0)),
        "root": str(store.root),
        "owned_tmp": owned,
    })
    warn_over_budget(
        f"raw dataset of {store.total_rows} rows x {store.n_features} "
        "features", store.total_rows * store.n_features * 8,
        config.ram_budget_mb,
        "streaming it chunked from disk (data_source=chunked active)",
    )

    proto, bin_store = stream_bin(
        store, config, root / "binned",
        categorical_feature=categorical_feature,
        feature_names=feature_names,
    )
    if group is None and qid is not None:
        # qid column -> per-query sizes (contiguous qids, text convention)
        _vals, counts = np.unique(qid, return_counts=True)
        change = np.nonzero(np.diff(qid))[0]
        bounds = np.concatenate([[0], change + 1, [len(qid)]])
        group = np.diff(bounds).astype(np.int64)
        del counts
    meta = Metadata(
        label=None if label is None else np.asarray(label, np.float32).ravel(),
        weight=None if weight is None else np.asarray(weight, np.float32).ravel(),
        group=None if group is None else np.asarray(group, np.int64).ravel(),
        init_score=None if init_score is None
        else np.asarray(init_score, np.float64).ravel(),
        position=None if position is None
        else np.asarray(position, np.int32).ravel(),
    )
    meta.check(store.total_rows)
    return StreamedBinnedDataset(
        bins=proto.bins,  # (G, 0) placeholder
        mappers=proto.mappers,
        used_features=proto.used_features,
        num_data=store.total_rows,
        metadata=meta,
        feature_names=list(proto.feature_names),
        max_num_bin=proto.max_num_bin,
        row_block=proto.row_block,
        monotone_constraints=proto.monotone_constraints,
        bundle_layout=proto.bundle_layout,
        bundle_expand=proto.bundle_expand,
        bin_store=bin_store,
        ram_budget_mb=config.ram_budget_mb,
    )


def _spool_root(config: Config) -> Tuple[bool, Path]:
    if config.data_spool_dir:
        root = Path(config.data_spool_dir)
        root.mkdir(parents=True, exist_ok=True)
        return False, root
    import atexit
    import shutil
    import tempfile

    tmp = Path(tempfile.mkdtemp(prefix="lgbm_tpu_spool_"))
    atexit.register(shutil.rmtree, tmp, ignore_errors=True)
    return True, tmp
