"""Leaf-wise tree growth over a physically permuted bin matrix.

This is the TPU formulation of the reference's index-list partition
(src/treelearner/data_partition.hpp: rows stored grouped by leaf as one
permuted array + per-leaf (begin, count)): the bin matrix, channel
matrix, and a row-origin vector are kept PHYSICALLY reordered so every
leaf occupies a contiguous segment. Each split then costs O(parent
segment), not O(N):

- stable partition of the parent segment (ParallelPartitionRunner /
  cuda_data_partition.cu SplitInner): two `nonzero` compactions over a
  static-capacity slice + one gather + one dynamic_update_slice;
- the smaller child's histogram reads a CONTIGUOUS slice (no row
  gather, no full-N mask), the larger sibling comes from parent
  subtraction as in serial_tree_learner.cpp:411;
- total per-tree work matches the reference's sum-of-segment-sizes
  (~depth x N), where the flat row->leaf formulation pays O(N) per
  split (254x N for a 255-leaf tree).

Static shapes come from a capacity ladder (N, N/2, ..., HIST_BLK):
every segment operation runs at the smallest capacity that covers the
segment, with rows outside the segment masked / passed through
untouched.

With `axis_name` set, rows are sharded; histograms and the
smaller-child choice are psum'd (data_parallel_tree_learner.cpp:286)
while each shard stable-partitions its local segment in lockstep.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .bundle import BundleInfo, decode_feature_bins, expand_hist
from .histogram import HIST_BLK, build_gh8, hist_slots, histogram, root_sums
from .split import (
    BIG,
    NEG_INF,
    SplitParams,
    SplitRecord,
    best_split,
    feature_best_gains,
    leaf_gain,
    leaf_output,
)


class ForcedSplits(NamedTuple):
    """Traced forced-split plan (serial_tree_learner.cpp:627
    ForceSplits): BFS-ordered (leaf, feature, bin) triples applied
    before best-gain growth; `n` is the actual count (arrays padded to
    a static length)."""

    leaf: jax.Array  # (K,) int32 — leaf id at application time
    feature: jax.Array  # (K,) int32 — used-feature index
    bin: jax.Array  # (K,) int32 — threshold bin
    n: jax.Array  # scalar int32
from .grower import (
    CegbInfo,
    GrowerSpec,
    TreeArrays,
    _empty_best,
    _get_best,
    _set_best,
    make_node_candidates,
    monotone_child_intervals,
    split_leaf_outputs,
)


class _Extras(NamedTuple):
    """Per-node feature bookkeeping (interaction constraints + CEGB)."""

    leaf_groups: jax.Array  # (L, NG) bool — constraint groups still legal
    path_used: jax.Array  # (L, F) bool — features used on the leaf's path
    feat_used: jax.Array  # (F,) bool — used anywhere (CEGB coupled)


def segment_caps(n_rows: int) -> tuple:
    """Static ladder of segment capacities: N, N/2, ..., >= HIST_BLK,
    all HIST_BLK multiples when n_rows itself is one. A non-multiple
    n_rows (per-SHARD rows on a mesh whose count doesn't divide into
    HIST_BLK blocks) clamps the top cap to n_rows instead of rounding
    past the operand — the pallas kernel path needs multiples, but
    such a shard is already on the einsum fallback."""
    caps = []
    c = n_rows
    while c >= HIST_BLK:
        caps.append(min(((c + HIST_BLK - 1) // HIST_BLK) * HIST_BLK,
                        n_rows))
        c //= 2
    if not caps:
        caps.append(n_rows)
    return tuple(caps)


class _PState(NamedTuple):
    i: jax.Array
    pbins: jax.Array  # (F, N) int32, leaf-grouped along the row (lane) axis
    pgh: jax.Array  # (8, N) f32, leaf-grouped (build_gh8 channels)
    pperm: jax.Array  # (N,) int32 — original row index at each position
    seg_begin: jax.Array  # (L,) int32; unused leaves = N (sorts last)
    seg_count: jax.Array  # (L,) int32
    hist: jax.Array  # (L, 3, F, B) — channel-leading, bins on lanes
    leaf_g: jax.Array
    leaf_h: jax.Array
    leaf_c: jax.Array
    leaf_parent: jax.Array
    leaf_min: jax.Array  # (L,) monotone-constraint interval per leaf
    leaf_max: jax.Array
    best: SplitRecord
    tree: TreeArrays
    # (L, F) bool — features whose stored histogram holds GLOBAL sums.
    # Always all-True except under voting (spec.voting_k > 0), where
    # only elected features are reduced across the mesh
    # (voting_parallel_tree_learner.cpp: global hists exist only for
    # elected features); subtraction and search respect this mask.
    hist_valid: jax.Array
    extra: _Extras
    # ancestry matrices for mono_mode=1 (intermediate constraints):
    # anc_in[x, a] = leaf x lies in node a's subtree; anc_left[x, a] =
    # on its LEFT side. Zero-size placeholders when mono_mode == 0.
    anc_in: jax.Array  # (L, L-1) bool or (L, 0)
    anc_left: jax.Array


class _RState(NamedTuple):
    """Round-phase state: _PState plus an explicit row -> leaf vector."""

    p: _PState
    pleaf: jax.Array  # (N,) int32; padding rows carry L (sorts last)


def _go_left(fbins, rec, fnan):
    return jnp.where(
        rec.is_cat,
        rec.cat_mask[fbins],
        (fbins <= rec.bin) | (rec.default_left & (fbins == fnan) & (fnan >= 0)),
    )


def _excl_prefix(x: jax.Array, blk: int = 512) -> jax.Array:
    """(N,) f32 -> (N+1,) exclusive prefix sums.

    Two-level: strict-upper-triangular matmul for in-block prefixes
    (MXU, f32-exact for counts < 2^24) + a tiny cumsum over block
    totals — a plain 1M-element jnp.cumsum measured ~47 ms on TPU,
    this is ~1 GFLOP of matmul instead.
    """
    n = x.shape[0]
    nb2 = n // blk
    if nb2 * blk != n:  # fall back for odd sizes (CPU tests)
        cs = jnp.cumsum(x)
        return jnp.concatenate([jnp.zeros(1, x.dtype), cs])
    xb = x.reshape(nb2, blk)
    upper = jnp.triu(jnp.ones((blk, blk), jnp.float32), 1)
    intra = jnp.dot(xb, upper, preferred_element_type=jnp.float32)
    tot = jnp.sum(xb, axis=1)
    boff = jnp.concatenate([jnp.zeros(1, jnp.float32), jnp.cumsum(tot)])
    p = (intra + boff[:-1, None]).reshape(n)
    return jnp.concatenate([p, boff[-1:]])


@partial(jax.jit, static_argnames=("spec",))
def grow_tree_permuted(
    bins_fm: jax.Array,  # (F, N) int32
    nan_bin: jax.Array,
    num_bins: jax.Array,
    mono: jax.Array,
    is_cat: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,  # validity * bagging
    feat_mask: jax.Array,
    params: SplitParams,
    spec: GrowerSpec,
    valid: Optional[jax.Array] = None,
    bundle: Optional[BundleInfo] = None,
    rng_key: Optional[jax.Array] = None,
    group_mat: Optional[jax.Array] = None,  # (NG, F) bool
    cegb: Optional[CegbInfo] = None,
    forced: Optional[ForcedSplits] = None,
) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree; returns (tree arrays, natural-order row->leaf)."""
    L = spec.num_leaves
    B = spec.num_bins
    G, N = bins_fm.shape  # G = device columns (bundles when spec.efb)
    F = num_bins.shape[0]  # original features
    ax = spec.axis_name
    caps = segment_caps(N)
    Bc = spec.col_bins if (spec.efb and spec.col_bins) else B
    # This grower is the reference-exact parity ORACLE
    # (tpu_growth_mode=exact); production configs — including voting
    # and forced splits, ISSUE 14 — route to the rounds grower
    # (boosting.py mode resolution). The oracle keeps its narrower
    # capability matrix:
    if spec.voting_k and spec.n_forced:
        # the oracle's forced path reads s.hist[fl] at the prescribed
        # feature without pinning it into the election; the rounds
        # grower supports the combination (forced columns pinned into
        # every election, rounds.py vote_reduce)
        raise ValueError(
            "voting_k excludes forced splits on the sequential oracle; "
            "use tpu_growth_mode=rounds for the combination"
        )
    per_node = spec.extra_trees or spec.ff_bynode or spec.cegb or spec.n_groups
    if spec.rounds and (per_node or spec.n_forced):
        raise ValueError("tpu_growth_rounds excludes per-node extras")
    if spec.mono_mode and (per_node or spec.voting_k or spec.n_forced
                           or spec.rounds):
        # the intermediate re-search pass uses the plain feature mask
        # and assumes globally-valid histograms
        raise ValueError(
            "monotone intermediate/advanced excludes per-node extras / "
            "voting / forced splits / rounds"
        )

    # shared per-node machinery (grower.make_node_candidates): the
    # DeltaGain per-tree-path lazy approximation and its rationale are
    # documented there and in DESIGN_DECISIONS.md
    node_candidates = make_node_candidates(
        spec, params, feat_mask, num_bins, nan_bin, rng_key, group_mat,
        cegb, F,
    )

    def exp_hist(h, g_sum, h_sum, c_sum):
        """Bundle-space histogram -> per-feature for the split scan."""
        if spec.efb:
            return expand_hist(h, g_sum, h_sum, c_sum, bundle)
        return h

    gh8 = build_gh8(grad * mask, hess * mask, mask)  # (8, N)
    root = root_sums(gh8, ax)

    hist0 = histogram(bins_fm, gh8, Bc)
    if ax is not None:
        hist0 = lax.psum(hist0, ax)
    root_out = leaf_output(root[0], root[1], params)
    NG = max(1, spec.n_groups)
    extra0 = _Extras(
        leaf_groups=jnp.ones((L, NG), bool),
        path_used=jnp.zeros((L, F), bool),
        feat_used=(cegb.used if spec.cegb else jnp.zeros(F, bool)),
    )
    if per_node:
        fm0, rb0, pen0 = node_candidates(
            jnp.int32(0), extra0.leaf_groups[0], extra0.path_used[0],
            root[2], extra0.feat_used,
        )
    else:
        fm0, rb0, pen0 = feat_mask, None, None
    rec0 = best_split(exp_hist(hist0, root[0], root[1], root[2]),
                      root[0], root[1], root[2], num_bins, nan_bin,
                      mono, is_cat, params, fm0,
                      cat_subset=spec.cat_subset, parent_output=root_out,
                      penalty=pen0, rand_bin=rb0)

    hist = jnp.zeros((L, 3, G, Bc), jnp.float32).at[0].set(hist0)
    best = _set_best(_empty_best(L, B), jnp.int32(0), rec0, rec0.gain)

    tree = TreeArrays(
        num_nodes=jnp.int32(0),
        node_feature=jnp.zeros(L - 1, jnp.int32),
        node_bin=jnp.zeros(L - 1, jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_default_left=jnp.zeros(L - 1, bool),
        node_cat=jnp.zeros(L - 1, bool),
        node_cat_mask=jnp.zeros((L - 1, B), bool),
        node_left=jnp.zeros(L - 1, jnp.int32),
        node_right=jnp.zeros(L - 1, jnp.int32),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(leaf_output(root[0], root[1], params)),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_depth=jnp.zeros(L, jnp.int32),
    )

    valid_f = jnp.ones(N, jnp.float32) if valid is None else valid
    n_valid = jnp.sum(valid_f > 0).astype(jnp.int32)  # local (shard) count

    iota_L = jnp.arange(L, dtype=jnp.int32)
    S = L // 2 + 1  # max splits per round (budget guard caps at L/2)

    def _round_body(rs: _RState) -> _RState:
        """Split EVERY positive-gain leaf at once (multi-leaf batch)."""
        s = rs.p
        t = s.tree
        i = s.i
        mask = s.best.gain > 0.0  # (L,)
        n_split = jnp.sum(mask).astype(jnp.int32)
        rank = jnp.cumsum(mask.astype(jnp.int32)) - mask  # exclusive
        rank = jnp.minimum(rank, S - 1)
        node_id = i + rank  # node slot per split leaf
        new_id = i + 1 + rank  # right-child leaf id per split leaf
        drop_node = jnp.where(mask, node_id, L - 1)  # L-1 -> mode=drop
        drop_new = jnp.where(mask, new_id, L)

        rec = s.best  # per-leaf records, fields (L,)

        # ---- outputs / monotone intervals, vectorized over leaves ----
        pmin, pmax = s.leaf_min, s.leaf_max
        lo, ro = split_leaf_outputs(rec, params, num_bins, spec.cat_subset,
                                    t.leaf_value, pmin, pmax)
        lmin, lmax, rmin, rmax = monotone_child_intervals(
            rec, mono, lo, ro, pmin, pmax
        )
        depth_new = t.leaf_depth + 1

        # ---- tree bookkeeping (Tree::Split, batched) ----
        p = s.leaf_parent
        pc = jnp.maximum(p, 0)
        p_is_left = t.node_left[pc] == ~iota_L
        fix = mask & (p >= 0)
        node_left = t.node_left.at[
            jnp.where(fix & p_is_left, pc, L - 1)
        ].set(node_id, mode="drop")
        node_right = t.node_right.at[
            jnp.where(fix & ~p_is_left, pc, L - 1)
        ].set(node_id, mode="drop")
        node_left = node_left.at[drop_node].set(~iota_L, mode="drop")
        node_right = node_right.at[drop_node].set(~drop_new, mode="drop")

        tree_new = TreeArrays(
            num_nodes=i + n_split,
            node_feature=t.node_feature.at[drop_node].set(rec.feature, mode="drop"),
            node_bin=t.node_bin.at[drop_node].set(rec.bin, mode="drop"),
            node_gain=t.node_gain.at[drop_node].set(rec.gain, mode="drop"),
            node_default_left=t.node_default_left.at[drop_node].set(
                rec.default_left, mode="drop"
            ),
            node_cat=t.node_cat.at[drop_node].set(rec.is_cat, mode="drop"),
            node_cat_mask=t.node_cat_mask.at[drop_node].set(
                rec.cat_mask, mode="drop"
            ),
            node_left=node_left,
            node_right=node_right,
            node_value=t.node_value.at[drop_node].set(t.leaf_value, mode="drop"),
            node_weight=t.node_weight.at[drop_node].set(s.leaf_h, mode="drop"),
            node_count=t.node_count.at[drop_node].set(s.leaf_c, mode="drop"),
            leaf_value=jnp.where(mask, lo, t.leaf_value)
            .at[drop_new].set(ro, mode="drop"),
            leaf_weight=jnp.where(mask, rec.left_h, t.leaf_weight)
            .at[drop_new].set(rec.right_h, mode="drop"),
            leaf_count=jnp.where(mask, rec.left_c, t.leaf_count)
            .at[drop_new].set(rec.right_c, mode="drop"),
            leaf_depth=jnp.where(mask, depth_new, t.leaf_depth)
            .at[drop_new].set(depth_new, mode="drop"),
        )

        # ---- per-row split decision for ALL leaves at once ----
        pl_c = jnp.minimum(rs.pleaf, L - 1)  # padding rows -> dead lanes
        f_row = rec.feature[pl_c]
        col_row = bundle.bundle_of[f_row] if spec.efb else f_row
        # masked select of each row's split column (no 2D gather)
        sel = col_row[None, :] == jnp.arange(G, dtype=jnp.int32)[:, None]
        fbins = jnp.sum(jnp.where(sel, s.pbins, 0), axis=0)
        if spec.efb:
            fbins = decode_feature_bins(fbins, f_row, bundle)  # vector f
        fnan_row = nan_bin[f_row]
        cat_hit = rec.cat_mask.reshape(-1)[pl_c * B + jnp.minimum(fbins, B - 1)]
        go_left = jnp.where(
            rec.is_cat[pl_c],
            cat_hit,
            (fbins <= rec.bin[pl_c])
            | (rec.default_left[pl_c] & (fbins == fnan_row) & (fnan_row >= 0)),
        )
        in_split = mask[pl_c] & (rs.pleaf < L)
        pleaf_new = jnp.where(
            in_split & ~go_left, new_id[pl_c], rs.pleaf
        ).astype(jnp.int32)

        # ---- stable multi-leaf partition WITHOUT a sort (XLA TPU sort
        # is seconds at 1M rows): per-row destination = segment start +
        # stable rank within the destination child, via two-level
        # prefix sums; then one scatter to invert the permutation and
        # one gather to apply it to all channels.
        gl_in = in_split & go_left
        gr_in = in_split & ~go_left
        P_l = _excl_prefix(gl_in.astype(jnp.float32))  # (N+1,)
        P_r = _excl_prefix(gr_in.astype(jnp.float32))
        beg = s.seg_begin
        endp = jnp.minimum(beg + s.seg_count, N)
        n_l = (P_l[endp] - P_l[jnp.minimum(beg, N)]).astype(jnp.int32)
        n_l = jnp.where(mask, n_l, 0)

        pos = jnp.arange(N, dtype=jnp.int32)
        b_row = beg[pl_c]
        Pl_b = P_l[jnp.minimum(b_row, N)]
        Pr_b = P_r[jnp.minimum(b_row, N)]
        dst_l = b_row + (P_l[:-1] - Pl_b).astype(jnp.int32)
        dst_r = b_row + n_l[pl_c] + (P_r[:-1] - Pr_b).astype(jnp.int32)
        dst = jnp.where(gl_in, dst_l, jnp.where(gr_in, dst_r, pos))
        inv = jnp.zeros(N, jnp.int32).at[dst].set(pos)
        pbins = jnp.take(s.pbins, inv, axis=1)
        pgh = jnp.take(s.pgh, inv, axis=1)
        pperm = s.pperm[inv]
        pleaf_s = pleaf_new[inv]
        n_r = jnp.where(mask, s.seg_count - n_l, 0)
        if ax is not None:
            gn_l = lax.psum(n_l, ax)
            gn_r = lax.psum(n_r, ax)
        else:
            gn_l, gn_r = n_l, n_r
        left_smaller = gn_l <= gn_r  # (L,)

        seg_begin = s.seg_begin.at[drop_new].set(
            s.seg_begin + n_l, mode="drop"
        )
        seg_count = jnp.where(mask, n_l, s.seg_count).at[drop_new].set(
            n_r, mode="drop"
        )

        # ---- multi-slot histograms for all smaller children ----
        sm_begin_leaf = jnp.where(left_smaller, s.seg_begin, s.seg_begin + n_l)
        sm_cnt_leaf = jnp.where(left_smaller, n_l, n_r)
        slot_of = jnp.where(mask, rank, S)
        slot_begin = jnp.zeros(S, jnp.int32).at[slot_of].set(
            sm_begin_leaf, mode="drop"
        )
        slot_cnt = jnp.zeros(S, jnp.int32).at[slot_of].set(
            sm_cnt_leaf, mode="drop"
        )
        slot_hists = hist_slots(
            pbins, pgh, slot_begin, slot_cnt, Bc, S,
            dense_visits=ax is not None,
        )  # (S, 3, G, Bc)
        if ax is not None:
            slot_hists = lax.psum(slot_hists, ax)

        # ---- per-leaf child hists: smaller from slots, larger by
        # subtraction; write both into the pool
        small_leaf = slot_hists[jnp.minimum(rank, S - 1)]  # (L, 3, G, Bc)
        large_leaf = s.hist - small_leaf
        left_h_ = jnp.where(
            left_smaller[:, None, None, None], small_leaf, large_leaf
        )
        right_h_ = jnp.where(
            left_smaller[:, None, None, None], large_leaf, small_leaf
        )
        hist = jnp.where(mask[:, None, None, None], left_h_, s.hist)
        hist = hist.at[drop_new].set(right_h_, mode="drop")

        # ---- best splits for all 2*n_split children, batched ----
        def child_best(h, g_, h__, c_, po, cmn, cmx):
            return best_split(
                exp_hist(h, g_, h__, c_), g_, h__, c_, num_bins, nan_bin,
                mono, is_cat, params, feat_mask,
                cat_subset=spec.cat_subset, parent_output=po,
                cmin=cmn, cmax=cmx,
            )

        vbest = jax.vmap(child_best)
        ch_hist = jnp.concatenate([left_h_, right_h_])  # (2L, 3, G, Bc)
        ch_g = jnp.concatenate([rec.left_g, rec.right_g])
        ch_h = jnp.concatenate([rec.left_h, rec.right_h])
        ch_c = jnp.concatenate([rec.left_c, rec.right_c])
        ch_po = jnp.concatenate([lo, ro])
        ch_mn = jnp.concatenate([lmin, rmin])
        ch_mx = jnp.concatenate([lmax, rmax])
        ch_rec = vbest(ch_hist, ch_g, ch_h, ch_c, ch_po, ch_mn, ch_mx)
        depth_ok = (spec.max_depth <= 0) | (depth_new < spec.max_depth)
        ch_gain = jnp.where(
            jnp.concatenate([depth_ok, depth_ok]), ch_rec.gain, NEG_INF
        )
        ch_leaf = jnp.concatenate([jnp.where(mask, iota_L, L), drop_new])

        def scat(dst, val):
            return dst.at[ch_leaf].set(val, mode="drop")

        best2 = SplitRecord(
            gain=scat(s.best.gain, ch_gain),
            feature=scat(s.best.feature, ch_rec.feature),
            bin=scat(s.best.bin, ch_rec.bin),
            default_left=scat(s.best.default_left, ch_rec.default_left),
            is_cat=scat(s.best.is_cat, ch_rec.is_cat),
            cat_mask=scat(s.best.cat_mask, ch_rec.cat_mask),
            left_g=scat(s.best.left_g, ch_rec.left_g),
            left_h=scat(s.best.left_h, ch_rec.left_h),
            left_c=scat(s.best.left_c, ch_rec.left_c),
            right_g=scat(s.best.right_g, ch_rec.right_g),
            right_h=scat(s.best.right_h, ch_rec.right_h),
            right_c=scat(s.best.right_c, ch_rec.right_c),
        )

        p_new = _PState(
            i=i + n_split,
            pbins=pbins,
            pgh=pgh,
            pperm=pperm,
            seg_begin=seg_begin,
            seg_count=seg_count,
            hist=hist,
            leaf_g=jnp.where(mask, rec.left_g, s.leaf_g)
            .at[drop_new].set(rec.right_g, mode="drop"),
            leaf_h=jnp.where(mask, rec.left_h, s.leaf_h)
            .at[drop_new].set(rec.right_h, mode="drop"),
            leaf_c=jnp.where(mask, rec.left_c, s.leaf_c)
            .at[drop_new].set(rec.right_c, mode="drop"),
            leaf_parent=jnp.where(mask, node_id, s.leaf_parent)
            .at[drop_new].set(node_id, mode="drop"),
            leaf_min=jnp.where(mask, lmin, s.leaf_min)
            .at[drop_new].set(rmin, mode="drop"),
            leaf_max=jnp.where(mask, lmax, s.leaf_max)
            .at[drop_new].set(rmax, mode="drop"),
            best=best2,
            tree=tree_new,
            hist_valid=s.hist_valid,
            extra=s.extra,
            anc_in=s.anc_in,
            anc_left=s.anc_left,
        )
        return _RState(p=p_new, pleaf=pleaf_s)

    def _round_cond(rs: _RState) -> jax.Array:
        mask = rs.p.best.gain > 0.0
        n_split = jnp.sum(mask)
        # budget guard: after splitting every positive-gain leaf the
        # leaf count stays within num_leaves — identical to sequential
        # greedy (which would also split exactly these leaves)
        return (n_split > 0) & (rs.p.i + 1 + n_split <= L)

    state = _PState(
        i=jnp.int32(0),
        pbins=bins_fm,
        pgh=gh8,
        pperm=jnp.arange(N, dtype=jnp.int32),
        seg_begin=jnp.full(L, N, jnp.int32).at[0].set(0),
        seg_count=jnp.zeros(L, jnp.int32).at[0].set(n_valid),
        hist=hist,
        leaf_g=jnp.zeros(L, jnp.float32).at[0].set(root[0]),
        leaf_h=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_c=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_min=jnp.full(L, -BIG, jnp.float32),
        leaf_max=jnp.full(L, BIG, jnp.float32),
        best=best,
        tree=tree,
        hist_valid=jnp.ones((L, F), bool),
        extra=extra0,
        anc_in=jnp.zeros((L, L - 1 if spec.mono_mode else 0), bool),
        anc_left=jnp.zeros((L, L - 1 if spec.mono_mode else 0), bool),
    )

    if spec.rounds and L > 2:
        rstate = _RState(
            p=state,
            pleaf=jnp.where(valid_f > 0, 0, L).astype(jnp.int32),
        )
        rstate = lax.while_loop(_round_cond, _round_body, rstate)
        state = rstate.p

    def _forced_valid(s: _PState):
        """Is step s.i a forced split with both children non-empty?"""
        fi = jnp.minimum(s.i, spec.n_forced - 1)
        fl = forced.leaf[fi]
        ff = forced.feature[fi]
        fb = forced.bin[fi]
        fh = exp_hist(s.hist[fl], s.leaf_g[fl], s.leaf_h[fl], s.leaf_c[fl])
        lc = jnp.cumsum(fh[2, ff])[fb]
        return (s.i < forced.n) & (lc > 0) & (s.leaf_c[fl] - lc > 0)

    def cond(s: _PState) -> jax.Array:
        keep = jnp.max(s.best.gain) > 0.0
        if spec.n_forced:
            # only continue for a forced step that can actually split
            # (both children non-empty) — the body falls back to the
            # best-gain split otherwise, which `keep` already guards
            keep = keep | _forced_valid(s)
        return (s.i < L - 1) & keep

    def body(s: _PState) -> _PState:
        i = s.i
        t = s.tree
        l = jnp.argmax(s.best.gain).astype(jnp.int32)
        rec = _get_best(s.best, l)
        if spec.n_forced:
            # forced splits (ForceSplits, serial_tree_learner.cpp:627):
            # the first `forced.n` steps split prescribed leaves at
            # prescribed (feature, threshold-bin), skipping any that
            # would leave an empty child (the reference aborts invalid
            # forced branches)
            fi = jnp.minimum(i, spec.n_forced - 1)
            fl = forced.leaf[fi]
            ff = forced.feature[fi]
            fb = forced.bin[fi]
            fh = exp_hist(s.hist[fl], s.leaf_g[fl], s.leaf_h[fl],
                          s.leaf_c[fl])
            cg = jnp.cumsum(fh[0, ff])
            chs = jnp.cumsum(fh[1, ff])
            cc = jnp.cumsum(fh[2, ff])
            lg, lh, lc = cg[fb], chs[fb], cc[fb]
            pg, ph, pc = s.leaf_g[fl], s.leaf_h[fl], s.leaf_c[fl]
            gain_f = (
                leaf_gain(lg, lh, params) + leaf_gain(pg - lg, ph - lh, params)
                - leaf_gain(pg, ph, params)
            )
            # invalid forced entries (empty child / exhausted plan) fall
            # back to the best-gain split; the cond guarantees that
            # fallback has positive gain. NOTE: after a skipped invalid
            # entry, later forced entries still target their
            # PRE-COMPUTED leaf ids (the reference re-maps by aborting
            # the branch queue — documented deviation for invalid plans)
            use = (i < forced.n) & (lc > 0) & (pc - lc > 0)
            rec_f = SplitRecord(
                gain=gain_f, feature=ff, bin=fb,
                default_left=jnp.asarray(False),
                is_cat=jnp.asarray(False),
                cat_mask=jnp.zeros(B, bool),
                left_g=lg, left_h=lh, left_c=lc,
                right_g=pg - lg, right_h=ph - lh, right_c=pc - lc,
            )
            l = jnp.where(use, fl, l)
            rec = jax.tree.map(
                lambda a, b: jnp.where(use, a, b), rec_f, rec
            )
        new = i + 1

        # ---- tree bookkeeping (Tree::Split semantics, same as flat) ----
        p = s.leaf_parent[l]
        pc = jnp.maximum(p, 0)
        p_is_left = t.node_left[pc] == ~l
        node_left = t.node_left.at[pc].set(
            jnp.where((p >= 0) & p_is_left, i, t.node_left[pc])
        )
        node_right = t.node_right.at[pc].set(
            jnp.where((p >= 0) & ~p_is_left, i, t.node_right[pc])
        )
        node_left = node_left.at[i].set(~l)
        node_right = node_right.at[i].set(~new)

        pmin, pmax = s.leaf_min[l], s.leaf_max[l]
        lo, ro = split_leaf_outputs(rec, params, num_bins, spec.cat_subset,
                                    t.leaf_value[l], pmin, pmax)
        lmin, lmax, rmin, rmax = monotone_child_intervals(
            rec, mono, lo, ro, pmin, pmax
        )
        depth_new = t.leaf_depth[l] + 1

        tree_new = TreeArrays(
            num_nodes=new,
            node_feature=t.node_feature.at[i].set(rec.feature),
            node_bin=t.node_bin.at[i].set(rec.bin),
            node_gain=t.node_gain.at[i].set(rec.gain),
            node_default_left=t.node_default_left.at[i].set(rec.default_left),
            node_cat=t.node_cat.at[i].set(rec.is_cat),
            node_cat_mask=t.node_cat_mask.at[i].set(rec.cat_mask),
            node_left=node_left,
            node_right=node_right,
            node_value=t.node_value.at[i].set(t.leaf_value[l]),
            node_weight=t.node_weight.at[i].set(s.leaf_h[l]),
            node_count=t.node_count.at[i].set(s.leaf_c[l]),
            leaf_value=t.leaf_value.at[l].set(lo).at[new].set(ro),
            leaf_weight=t.leaf_weight.at[l].set(rec.left_h).at[new].set(rec.right_h),
            leaf_count=t.leaf_count.at[l].set(rec.left_c).at[new].set(rec.right_c),
            leaf_depth=t.leaf_depth.at[l].set(depth_new).at[new].set(depth_new),
        )

        b = s.seg_begin[l]
        c = s.seg_count[l]
        fnan = nan_bin[rec.feature]
        fcol_idx = bundle.bundle_of[rec.feature] if spec.efb else rec.feature

        # ---- stable partition of segment [b, b+c) at capacity cap ----
        # (XLA TPU sort is NOT an option here: a 1M-row multi-payload
        # stable sort measured 0.3-2s with minutes of per-shape compile
        # on this backend — nonzero+gather it is.)
        def mk_part(cap: int):
            def part(_):
                start = jnp.clip(b, 0, N - cap)
                off = b - start
                sbins = lax.dynamic_slice(s.pbins, (jnp.int32(0), start), (G, cap))
                sgh = lax.dynamic_slice(s.pgh, (jnp.int32(0), start), (8, cap))
                sperm = lax.dynamic_slice(s.pperm, (start,), (cap,))
                iota = jnp.arange(cap, dtype=jnp.int32)
                in_seg = (iota >= off) & (iota < off + c)
                fcol = lax.dynamic_slice(
                    sbins, (fcol_idx, jnp.int32(0)), (1, cap)
                ).reshape(cap)
                if spec.efb:
                    fcol = decode_feature_bins(fcol, rec.feature, bundle)
                gl = _go_left(fcol, rec, fnan)
                sel_l = in_seg & gl
                n_l = jnp.sum(sel_l).astype(jnp.int32)
                lidx = jnp.nonzero(sel_l, size=cap, fill_value=cap)[0]
                ridx = jnp.nonzero(in_seg & ~gl, size=cap, fill_value=cap)[0]
                rel = iota - off
                src = jnp.where(
                    rel < n_l,
                    jnp.take(lidx, jnp.clip(rel, 0, cap - 1), mode="clip"),
                    jnp.take(ridx, jnp.clip(rel - n_l, 0, cap - 1), mode="clip"),
                )
                src = jnp.where(in_seg, src, iota)
                nb = jnp.take(sbins, src, axis=1, mode="clip")
                ng = jnp.take(sgh, src, axis=1, mode="clip")
                npm = jnp.take(sperm, src, mode="clip")
                pbins = lax.dynamic_update_slice(s.pbins, nb, (jnp.int32(0), start))
                pgh = lax.dynamic_update_slice(s.pgh, ng, (jnp.int32(0), start))
                pperm = lax.dynamic_update_slice(s.pperm, npm, (start,))
                return pbins, pgh, pperm, n_l

            return part

        caps_arr = jnp.asarray(caps, jnp.int32)
        pidx = jnp.clip(jnp.sum(caps_arr >= c) - 1, 0, len(caps) - 1)
        pbins, pgh, pperm, n_l = lax.switch(
            pidx, [mk_part(cp) for cp in caps], None
        )
        n_r = c - n_l

        # ---- children segments; smaller child by GLOBAL count ----
        if ax is not None:
            left_smaller = lax.psum(n_l, ax) <= lax.psum(n_r, ax)
        else:
            left_smaller = n_l <= n_r
        # left child keeps leaf id l at [b, b+n_l); right child (id `new`)
        # occupies [b+n_l, b+c)
        seg_begin = s.seg_begin.at[l].set(b).at[new].set(b + n_l)
        seg_count = s.seg_count.at[l].set(n_l).at[new].set(n_r)

        small_begin = jnp.where(left_smaller, b, b + n_l)
        small_cnt = jnp.where(left_smaller, n_l, n_r)

        # ---- smaller-child histogram over its contiguous slice ----
        def mk_hist(cap: int):
            def h(_):
                start = jnp.clip(small_begin, 0, N - cap)
                off = small_begin - start
                hb = lax.dynamic_slice(pbins, (jnp.int32(0), start), (G, cap))
                hg = lax.dynamic_slice(pgh, (jnp.int32(0), start), (8, cap))
                iota = jnp.arange(cap, dtype=jnp.int32)
                m = ((iota >= off) & (iota < off + small_cnt)).astype(jnp.float32)
                hgm = hg * m[None, :]
                s8 = jnp.sum(hgm, axis=1)
                lsum = jnp.stack([s8[0] + s8[1], s8[2] + s8[3], s8[4]])
                return histogram(hb, hgm, Bc), lsum

            return h

        hidx = jnp.clip(jnp.sum(caps_arr >= small_cnt) - 1, 0, len(caps) - 1)
        small_hist, lsum3 = lax.switch(hidx, [mk_hist(cp) for cp in caps], None)
        valid_parent = s.hist_valid[l]  # (F,)
        if spec.voting_k and ax is not None:
            # ---- voting election (GlobalVoting, parallel_tree_learner
            # .h:152): each shard proposes its top-k COLUMNS by LOCAL
            # gain on the smaller child; votes + summed gains elect 2k;
            # only elected columns cross the mesh. Under EFB the unit of
            # election is the bundle column (a bundle's gain = the best
            # of its member features), so voting composes with bundling
            # — the reference elects features because its storage unit
            # is the feature group (voting_parallel_tree_learner.cpp).
            kG = min(spec.voting_k, G)
            k2 = min(2 * spec.voting_k, G)
            lgains = feature_best_gains(
                exp_hist(small_hist, lsum3[0], lsum3[1], lsum3[2]),
                lsum3[0], lsum3[1], lsum3[2], num_bins,
                nan_bin, mono, is_cat, params, feat_mask,
                cat_subset=spec.cat_subset,
            )  # (F,) local per-feature gains
            if spec.efb:
                col_gain = jnp.full(G, NEG_INF).at[bundle.bundle_of].max(
                    lgains
                )
            else:
                col_gain = lgains
            _, topi = lax.top_k(col_gain, kG)
            in_topk = jnp.zeros(G, bool).at[topi].set(True)
            votes = lax.psum(in_topk.astype(jnp.float32), ax)
            score = lax.psum(
                jnp.where(in_topk, jnp.maximum(col_gain, 0.0), 0.0), ax
            )
            _, eidx = lax.top_k(votes * 1e12 + score, k2)
            elected_cols = jnp.zeros(G, bool).at[eidx].set(True)
            comp = lax.psum(small_hist[:, eidx, :], ax)  # (3, 2k, B) wire
            small_hist = (
                jnp.zeros_like(small_hist).at[:, eidx, :].set(comp)
            )
            elected = (
                elected_cols[bundle.bundle_of] if spec.efb else elected_cols
            )
            valid_small = elected
            valid_large = elected & valid_parent
        else:
            if ax is not None:
                small_hist = lax.psum(small_hist, ax)
            valid_small = valid_parent
            valid_large = valid_parent

        parent_hist = s.hist[l]
        large_hist = parent_hist - small_hist
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        hist = s.hist.at[l].set(left_hist).at[new].set(right_hist)

        # ---- best splits for both children ----
        if spec.voting_k:
            valid_left = jnp.where(left_smaller, valid_small, valid_large)
            valid_right = jnp.where(left_smaller, valid_large, valid_small)
            fm_l = feat_mask & valid_left
            fm_r = feat_mask & valid_right
            hist_valid = s.hist_valid.at[l].set(valid_left).at[new].set(
                valid_right
            )
        else:
            fm_l = fm_r = feat_mask
            hist_valid = s.hist_valid
        if per_node:
            f_split = rec.feature
            onehot_f = jnp.arange(F, dtype=jnp.int32) == f_split
            child_groups = s.extra.leaf_groups[l]
            if spec.n_groups:
                # only groups containing EVERY feature on the path stay
                # legal (col_sampler.hpp interaction filtering)
                child_groups = child_groups & group_mat[:, f_split]
            pu_child = s.extra.path_used[l] | onehot_f
            feat_used_new = s.extra.feat_used | onehot_f
            cn_l = node_candidates(2 * i + 1, child_groups, pu_child,
                                   rec.left_c, feat_used_new)
            cn_r = node_candidates(2 * i + 2, child_groups, pu_child,
                                   rec.right_c, feat_used_new)
            fm_l = fm_l & cn_l[0]
            fm_r = fm_r & cn_r[0]
            rb_l, pen_l = cn_l[1], cn_l[2]
            rb_r, pen_r = cn_r[1], cn_r[2]
            extra_new = _Extras(
                leaf_groups=s.extra.leaf_groups.at[l].set(child_groups)
                .at[new].set(child_groups),
                path_used=s.extra.path_used.at[l].set(pu_child)
                .at[new].set(pu_child),
                feat_used=feat_used_new,
            )
        else:
            rb_l = rb_r = pen_l = pen_r = None
            extra_new = s.extra
        if not spec.mono_mode:
            # mono_mode=1 re-searches EVERY leaf below (the children
            # included) — computing bl/br here would be discarded work
            bl = best_split(
                exp_hist(left_hist, rec.left_g, rec.left_h, rec.left_c),
                rec.left_g, rec.left_h, rec.left_c,
                num_bins, nan_bin, mono, is_cat, params, fm_l,
                cat_subset=spec.cat_subset, parent_output=lo,
                cmin=lmin, cmax=lmax, penalty=pen_l, rand_bin=rb_l)
            br = best_split(
                exp_hist(right_hist, rec.right_g, rec.right_h, rec.right_c),
                rec.right_g, rec.right_h, rec.right_c,
                num_bins, nan_bin, mono, is_cat, params, fm_r,
                cat_subset=spec.cat_subset, parent_output=ro,
                cmin=rmin, cmax=rmax, penalty=pen_r, rand_bin=rb_r)
            depth_ok = (spec.max_depth <= 0) | (depth_new < spec.max_depth)
            best2 = _set_best(
                s.best, l, bl, jnp.where(depth_ok, bl.gain, NEG_INF)
            )
            best2 = _set_best(
                best2, new, br, jnp.where(depth_ok, br.gain, NEG_INF)
            )
        else:
            best2 = s.best  # replaced by the re-search below

        anc_in_new, anc_left_new = s.anc_in, s.anc_left
        if spec.mono_mode:
            # ---- intermediate constraints (monotone_constraints.hpp:516
            # GoUpToFindLeavesToUpdate semantics, batch formulation):
            # 1. extend the ancestry matrices with split i,
            # 2. recompute EVERY leaf's [min, max] from the actual
            #    output extrema of the opposite subtrees of its monotone
            #    ancestors (tightest valid bounds; basic freezes the
            #    midpoint instead),
            # 3. re-search every leaf's best split under the new bounds
            #    (the reference recomputes the leaves_to_update set; one
            #    vmapped pass here keeps shapes static).
            anc_in_new = (
                s.anc_in.at[new].set(s.anc_in[l])
                .at[l, i].set(True).at[new, i].set(True)
            )
            anc_left_new = (
                s.anc_left.at[new].set(s.anc_left[l]).at[l, i].set(True)
            )
            t2 = tree_new
            leaf_out2 = t2.leaf_value
            valid_leaf = iota_L <= new
            node_m = mono[t2.node_feature] * (
                ~t2.node_cat
            ).astype(jnp.int32)  # cat splits never constrain
            node_alive = jnp.arange(L - 1) <= i
            in_l = anc_in_new & anc_left_new & valid_leaf[:, None]
            in_r = anc_in_new & ~anc_left_new & valid_leaf[:, None]
            Lmax = jnp.max(jnp.where(in_l, leaf_out2[:, None], -BIG), axis=0)
            Lmin = jnp.min(jnp.where(in_l, leaf_out2[:, None], BIG), axis=0)
            Rmax = jnp.max(jnp.where(in_r, leaf_out2[:, None], -BIG), axis=0)
            Rmin = jnp.min(jnp.where(in_r, leaf_out2[:, None], BIG), axis=0)
            inc = (node_alive & (node_m > 0))[None, :]
            dec = (node_alive & (node_m < 0))[None, :]
            cmax_mat = jnp.where(in_l & inc, Rmin[None, :], BIG)
            cmax_mat = jnp.where(in_r & dec, Lmin[None, :], cmax_mat)
            cmin_mat = jnp.where(in_r & inc, Lmax[None, :], -BIG)
            cmin_mat = jnp.where(in_l & dec, Rmax[None, :], cmin_mat)
            nmax = jnp.min(cmax_mat, axis=1)  # (L,)
            nmin = jnp.max(cmin_mat, axis=1)
            lmin, lmax = nmin[l], nmax[l]
            rmin, rmax = nmin[new], nmax[new]

            def leaf_best(h_, g_, hh_, c_, po_, mn_, mx_):
                return best_split(
                    exp_hist(h_, g_, hh_, c_), g_, hh_, c_, num_bins,
                    nan_bin, mono, is_cat, params, feat_mask,
                    cat_subset=spec.cat_subset, parent_output=po_,
                    cmin=mn_, cmax=mx_,
                )

            lg_all = s.leaf_g.at[l].set(rec.left_g).at[new].set(rec.right_g)
            lh_all = s.leaf_h.at[l].set(rec.left_h).at[new].set(rec.right_h)
            lc_all = s.leaf_c.at[l].set(rec.left_c).at[new].set(rec.right_c)
            rec_all = jax.vmap(leaf_best)(
                hist, lg_all, lh_all, lc_all, leaf_out2, nmin, nmax
            )
            d_ok = (spec.max_depth <= 0) | (t2.leaf_depth < spec.max_depth)
            best2 = rec_all._replace(
                gain=jnp.where(valid_leaf & d_ok, rec_all.gain, NEG_INF)
            )

        return _PState(
            i=new,
            pbins=pbins,
            pgh=pgh,
            pperm=pperm,
            seg_begin=seg_begin,
            seg_count=seg_count,
            hist=hist,
            leaf_g=s.leaf_g.at[l].set(rec.left_g).at[new].set(rec.right_g),
            leaf_h=s.leaf_h.at[l].set(rec.left_h).at[new].set(rec.right_h),
            leaf_c=s.leaf_c.at[l].set(rec.left_c).at[new].set(rec.right_c),
            leaf_parent=s.leaf_parent.at[l].set(i).at[new].set(i),
            leaf_min=(nmin if spec.mono_mode
                      else s.leaf_min.at[l].set(lmin).at[new].set(rmin)),
            leaf_max=(nmax if spec.mono_mode
                      else s.leaf_max.at[l].set(lmax).at[new].set(rmax)),
            best=best2,
            tree=tree_new,
            hist_valid=hist_valid,
            extra=extra_new,
            anc_in=anc_in_new,
            anc_left=anc_left_new,
        )

    final = lax.while_loop(cond, body, state)

    # ---- natural-order row -> leaf from the leaf segments ----
    # order leaves by segment begin (unused slots and locally-EMPTY
    # leaves — possible on a shard — get begin == N so they sort last
    # and never shadow a sibling sharing their begin); position p then
    # belongs to the last leaf with begin <= p
    eff_begin = jnp.where(final.seg_count > 0, final.seg_begin, N)
    order = jnp.argsort(eff_begin)
    sorted_begin = eff_begin[order]
    pos = jnp.arange(N, dtype=jnp.int32)
    leaf_of_pos = order[
        jnp.clip(jnp.searchsorted(sorted_begin, pos, side="right") - 1, 0, L - 1)
    ].astype(jnp.int32)
    row_leaf = jnp.zeros(N, jnp.int32).at[final.pperm].set(leaf_of_pos)
    if valid is not None:
        row_leaf = jnp.where(valid > 0, row_leaf, -1)
    return final.tree, row_leaf
