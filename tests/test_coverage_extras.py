"""Coverage the reference suite has that ours lacked (VERDICT r2 weak
#9): weighted training, large-leaf (255) trees, multiclass through the
fused loop."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def test_weighted_training_shifts_model():
    rs = np.random.RandomState(2)
    n = 4000
    X = rs.randn(n, 5)
    y = ((X[:, 0] + 0.3 * rs.randn(n)) > 0).astype(np.float64)
    # upweight the positive class 10x — predictions must shift up
    w = np.where(y > 0, 10.0, 1.0)
    params = dict(objective="binary", num_leaves=15, verbosity=-1)
    b0 = lgb.train(params, lgb.Dataset(X, label=y, free_raw_data=False),
                   num_boost_round=10)
    b1 = lgb.train(params, lgb.Dataset(X, label=y, weight=w,
                                       free_raw_data=False),
                   num_boost_round=10)
    assert b1.predict(X).mean() > b0.predict(X).mean() + 0.05
    # weighted metric eval runs
    rec = {}
    ds = lgb.Dataset(X, label=y, weight=w, free_raw_data=False)
    lgb.train({**params, "metric": "binary_logloss"}, ds, num_boost_round=5,
              valid_sets=[ds], valid_names=["t"],
              callbacks=[lgb.record_evaluation(rec)])
    assert len(rec["t"]["binary_logloss"]) == 5


def test_large_leaf_255_tree():
    """One 255-leaf tree at the benchmark's leaf budget (the while_loop
    capacity ladder must handle deep growth)."""
    rs = np.random.RandomState(3)
    n = 20000
    X = rs.randn(n, 8)
    y = X[:, 0] * np.sin(X[:, 1] * 2) + 0.5 * X[:, 2] ** 2 + 0.05 * rs.randn(n)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 255,
         "min_data_in_leaf": 20, "learning_rate": 0.5, "verbosity": -1},
        ds, num_boost_round=3,
    )
    t = bst._gbdt.models[0]
    assert t.num_leaves > 200  # rich signal: near-full budget used
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < float(np.var(y)) * 0.4


def test_multiclass_fused_loop():
    rs = np.random.RandomState(4)
    n = 6000
    X = rs.randn(n, 6)
    logits = np.stack([X[:, 0], X[:, 1], -(X[:, 0] + X[:, 1])], 1)
    y = np.argmax(logits + 0.3 * rs.randn(n, 3), axis=1).astype(np.float64)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    vs = lgb.Dataset(X[:1000], label=y[:1000], reference=ds,
                     free_raw_data=False)
    rec = {}
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
         "metric": "multi_logloss", "verbosity": -1},
        ds, num_boost_round=8, valid_sets=[vs], valid_names=["v"],
        callbacks=[lgb.record_evaluation(rec)],
    )
    assert bst._gbdt.fused_eligible()  # device metric set covers this
    p = bst.predict(X)
    assert p.shape == (n, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)
    assert (np.argmax(p, 1) == y).mean() > 0.7
    assert rec["v"]["multi_logloss"][-1] < rec["v"]["multi_logloss"][0]


def test_sequence_streaming_construction():
    """lgb.Sequence streaming ingest (reference basic.py:905): binned
    matrix built in chunks matches the all-at-once numpy path."""
    rs = np.random.RandomState(9)
    X = rs.randn(3000, 5)
    y = ((X[:, 0] + 0.5 * X[:, 2]) > 0).astype(np.float64)

    class ArrSeq(lgb.Sequence):
        batch_size = 256

        def __init__(self, a):
            self._a = a

        def __len__(self):
            return len(self._a)

        def __getitem__(self, idx):
            return self._a[idx]

    params = dict(objective="binary", num_leaves=15, verbosity=-1)
    # split across two sequences to exercise multi-sequence concat
    ds_seq = lgb.Dataset([ArrSeq(X[:1000]), ArrSeq(X[1000:])], label=y)
    ds_np = lgb.Dataset(X, label=y, free_raw_data=False)
    ds_seq.construct()
    ds_np.construct()
    np.testing.assert_array_equal(ds_seq._binned.bins, ds_np._binned.bins)
    b1 = lgb.train(params, ds_seq, num_boost_round=5)
    b2 = lgb.train(params, ds_np, num_boost_round=5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X), rtol=1e-6)


def test_auc_mu_matches_bruteforce():
    """auc_mu (multiclass_metric.hpp:183) against a direct O(n^2)
    pairwise computation of the Kleiman-Page definition."""
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import AucMuMetric

    rs = np.random.RandomState(0)
    K, N = 3, 400
    y = rs.randint(0, K, N).astype(np.float64)
    score = rs.randn(K, N)
    cfg = Config({"objective": "multiclass", "num_class": K})
    m = AucMuMetric(cfg)
    m.init(y, None, None)
    (_, got, _), = m.eval(score.reshape(-1))

    W = np.ones((K, K)) - np.eye(K)
    total = 0.0
    for i in range(K):
        for j in range(i + 1, K):
            v = W[i] - W[j]
            t1 = v[i] - v[j]
            d = t1 * (v @ score)
            di = d[y == i]
            dj = d[y == j]
            wins = (di[:, None] > dj[None, :]).sum()
            ties = (np.abs(di[:, None] - dj[None, :]) < 1e-15).sum()
            total += (wins + 0.5 * ties) / (len(di) * len(dj))
    expect = 2.0 * total / K / (K - 1)
    assert abs(got - expect) < 1e-10, (got, expect)


def test_auc_mu_via_train_api():
    import numpy as np

    import lightgbm_tpu as lgb

    rs = np.random.RandomState(1)
    X = rs.randn(1500, 6)
    y = (X[:, 0] > 0.3).astype(int) + (X[:, 1] > 0.1).astype(int)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    evals = {}
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "metric": "auc_mu",
         "num_leaves": 15, "verbosity": -1},
        ds, num_boost_round=5, valid_sets=[ds], valid_names=["tr"],
        callbacks=[lgb.record_evaluation(evals)],
    )
    vals = evals["tr"]["auc_mu"]
    assert len(vals) == 5
    assert vals[-1] > 0.9  # separable-ish problem


def test_single_row_fast_predict_matches_batch():
    """The packed single-row predictor (c_api.cpp:66
    SingleRowPredictorInner analog) must agree exactly with the batch
    tree walk, including missing values and num_iteration slicing."""
    import numpy as np

    import lightgbm_tpu as lgb

    rs = np.random.RandomState(3)
    X = rs.randn(2000, 8)
    X[rs.rand(2000, 8) < 0.05] = np.nan
    w = rs.randn(8)
    y = ((np.nan_to_num(X) @ w) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=20)
    Xq = X[:6].copy()
    batch = bst.predict(Xq)  # 6 rows -> batch path
    single = np.array([bst.predict(Xq[i:i + 1])[0] for i in range(6)])
    np.testing.assert_allclose(single, batch, atol=1e-14)
    b5 = bst.predict(Xq[:1], num_iteration=5)
    s5 = bst.predict(np.vstack([Xq[:1]] * 6), num_iteration=5)[:1]
    np.testing.assert_allclose(b5, s5, atol=1e-14)


def test_debug_check_split_passes_and_detects():
    """tpu_debug_check_split (serial_tree_learner.h:174 CheckSplit):
    green on healthy training; a corrupted tree trips the fatal."""
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.log import LightGBMError

    rs = np.random.RandomState(4)
    X = rs.randn(3000, 6)
    y = ((X[:, 0] + X[:, 1]) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tpu_debug_check_split": True},
        ds, num_boost_round=3,
    )
    assert bst.num_trees() == 3

    # corrupt: a GBDT whose grower returns a wrong leaf_count
    g = bst._gbdt
    orig = g._grow_maybe_quantized

    def bad(*a, **k):
        arrays, rl = orig(*a, **k)
        return arrays._replace(leaf_count=arrays.leaf_count + 7.0), rl

    g._grow_maybe_quantized = bad
    import pytest as _pytest

    with _pytest.raises(LightGBMError, match="CheckSplit"):
        g.train_one_iter(None, None)


def test_xentropy_family_metrics():
    """kullback_leibler and cross_entropy_lambda eval metrics
    (xentropy_metric.hpp:249, :165 — the objectives existed, the
    metrics were missing; VERDICT r4 missing #6)."""
    rs = np.random.RandomState(3)
    n = 1200
    X = rs.randn(n, 6)
    w = rs.randn(6)
    y = 1.0 / (1.0 + np.exp(-(X @ w)))  # continuous labels in [0, 1]

    evals = {}
    def record(env):
        for item in env.evaluation_result_list:
            evals.setdefault(item[1], []).append(item[2])

    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    lgb.train({"objective": "cross_entropy", "num_leaves": 15,
               "metric": ["cross_entropy", "kullback_leibler"],
               "verbosity": -1},
              ds, num_boost_round=10, valid_sets=[ds], valid_names=["tr"],
              callbacks=[record])
    # KL = CE - H(y): the label-entropy offset is score-independent
    yent = np.where(y > 0, y * np.log(y), 0.0) \
        + np.where(1 - y > 0, (1 - y) * np.log(1 - y), 0.0)
    for ce, kl in zip(evals["cross_entropy"], evals["kullback_leibler"]):
        np.testing.assert_allclose(kl, ce + float(np.mean(yent)),
                                   rtol=1e-6, atol=1e-9)
    assert evals["kullback_leibler"][-1] < evals["kullback_leibler"][0]

    evals.clear()
    ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
    lgb.train({"objective": "cross_entropy_lambda", "num_leaves": 15,
               "metric": "cross_entropy_lambda", "verbosity": -1},
              ds2, num_boost_round=10, valid_sets=[ds2], valid_names=["tr"],
              callbacks=[record])
    vals = evals["cross_entropy_lambda"]
    assert vals[-1] < vals[0]  # the loss must improve under its objective


def test_r2_metric_reference_parity():
    """r2 (the one missing entry of the reference metric.cpp:21
    regression family, VERDICT r5): host and fused-device evals must
    both match the closed-form weighted 1 - SSres/SStot on the final
    scores, and agree with sklearn on the unweighted case."""
    from lightgbm_tpu.metrics import R2Metric
    from lightgbm_tpu.config import Config

    rs = np.random.RandomState(7)
    n = 2000
    X = rs.randn(n, 6)
    y = X @ rs.randn(6) + 0.1 * rs.randn(n)
    w = rs.uniform(0.5, 2.0, n)

    rec = {}
    ds = lgb.Dataset(X, label=y, weight=w, free_raw_data=False)
    booster = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "metric": ["l2", "r2"]},
        ds, num_boost_round=8, valid_sets=[ds], valid_names=["tr"],
        callbacks=[lgb.record_evaluation(rec)],
    )
    pred = booster.predict(X)
    ybar = np.sum(w * y) / np.sum(w)
    expect = 1.0 - np.sum(w * (y - pred) ** 2) / np.sum(w * (y - ybar) ** 2)
    got = rec["tr"]["r2"][-1]
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-7)
    assert rec["tr"]["r2"][-1] > rec["tr"]["r2"][0]  # higher_better

    # host Metric object parity vs sklearn (unweighted)
    from sklearn.metrics import r2_score

    m = R2Metric(Config({}))
    m.init(y, None, None)
    [(name, val, hb)] = m.eval(pred)
    assert name == "r2" and hb is True
    np.testing.assert_allclose(val, r2_score(y, pred), rtol=1e-9)


def test_device_eval_host_metric_fallback():
    """A valid metric string with no device implementation must NOT
    crash DeviceEvalSet (VERDICT r5 weak #6): it computes on host via
    metrics.py through a pure_callback, warns once, and matches the
    host metric exactly — padding rows masked out."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu import metrics as host_metrics
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.device_metrics import (
        DeviceEvalSet,
        _warned_host_fallback,
    )

    rs = np.random.RandomState(3)
    n, npad = 500, 512
    lab = (rs.rand(n) > 0.4).astype(np.float32)
    score = rs.randn(n).astype(np.float32)
    lab_pad = np.zeros(npad, np.float32)
    lab_pad[:n] = lab
    sc_pad = np.zeros(npad, np.float32)
    sc_pad[:n] = score
    valid = jnp.asarray(np.arange(npad) < n, jnp.float32)
    cfg = Config({})
    # average_precision is host-only; kullback_leibler too — both must
    # build, and device metrics in the same set keep their fast path
    _warned_host_fallback.clear()
    des = DeviceEvalSet(
        cfg, ["average_precision", "kullback_leibler", "l2"],
        [True, False, False], jnp.asarray(lab_pad), None, valid, 1,
    )
    vals = np.asarray(jax.jit(des)(jnp.asarray(sc_pad)[None, :]))
    m = host_metrics.AveragePrecisionMetric(cfg)
    m.init(lab, None, None)
    np.testing.assert_allclose(
        vals[0], m.eval(score.astype(np.float64))[0][1], rtol=1e-6
    )
    m2 = host_metrics.KullbackLeiblerMetric(cfg)
    m2.init(lab, None, None)
    np.testing.assert_allclose(
        vals[1], m2.eval(score.astype(np.float64))[0][1], rtol=1e-5
    )
    assert _warned_host_fallback == {"average_precision",
                                     "kullback_leibler"}
    # a genuinely invalid name still raises
    import pytest

    with pytest.raises(NotImplementedError):
        DeviceEvalSet(cfg, ["no_such_metric"], [False],
                      jnp.asarray(lab_pad), None, valid, 1)


def test_bench_stale_flag_marks_carried_numbers():
    """BENCH json: carried-forward chip numbers must carry stale=true
    whenever the run itself did not execute on the TPU (VERDICT r5
    weak #3) — a dead tunnel can no longer ship old numbers as fresh."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_mod",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._STATE.update(platform="cpu", rows=1000, leaves=31)
    out = bench._final_json()
    assert out["last_tpu_verified"]["stale"] is True
    bench._STATE["platform"] = "tpu"
    assert bench._final_json()["last_tpu_verified"]["stale"] is False
    # unknown platform (probe never ran) is stale too
    bench._STATE.pop("platform")
    assert bench._final_json()["last_tpu_verified"]["stale"] is True


def test_device_eval_host_metric_fallback_traced_construction():
    """The memoized fused step constructs DeviceEvalSet INSIDE the
    trace with label/valid as jit arguments — the host fallback must
    build from tracers (operands ride the callback) instead of
    crashing on np.asarray(tracer)."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.device_metrics import DeviceEvalSet
    from lightgbm_tpu import metrics as host_metrics

    rs = np.random.RandomState(4)
    n = 256
    lab = (rs.rand(n) > 0.5).astype(np.float32)
    score = rs.randn(n).astype(np.float32)
    cfg = Config({})

    @jax.jit
    def step(lab_t, valid_t, score_t):
        des = DeviceEvalSet(cfg, ["average_precision"], [True],
                            lab_t, None, valid_t, 1)
        return des(score_t[None, :])

    vals = np.asarray(step(jnp.asarray(lab), jnp.ones(n, jnp.float32),
                           jnp.asarray(score)))
    m = host_metrics.AveragePrecisionMetric(cfg)
    m.init(lab, None, None)
    np.testing.assert_allclose(
        vals[0], m.eval(score.astype(np.float64))[0][1], rtol=1e-6
    )
