"""Dask-compatible estimators (reference python-package/lightgbm/dask.py:
DaskLGBMClassifier:1159, DaskLGBMRegressor:1421, DaskLGBMRanker:1646).

TPU-first redesign, not a port: the reference parallelizes by running
one socket-connected LightGBM rank inside each Dask worker
(`_train`, dask.py:415 — ports, machine lists, per-worker concat).
On TPU the distributed substrate is the XLA device mesh: rows are
sharded over ICI by the data-parallel tree learner
(``tree_learner=data``, parallel/data_parallel.py), and multi-host
clusters are assembled by ``lightgbm_tpu.run_distributed``
(parallel/multihost.py) over ``jax.distributed`` — not by a Dask
scheduler. These classes therefore keep the reference's API shape
(``client=`` accepted, Dask collections accepted) but *materialize*
the collection and hand it to the mesh-sharded trainer: the cluster
the training actually runs on is the TPU mesh, which Dask cannot see.

They work with or without dask installed — any object exposing
``.compute()`` (dask.array/dataframe) is materialized, plain
numpy/pandas passes through untouched.
"""

from __future__ import annotations

from typing import Any, Optional

from .sklearn import LGBMClassifier, LGBMRanker, LGBMRegressor

__all__ = ["DaskLGBMClassifier", "DaskLGBMRegressor", "DaskLGBMRanker"]


def _materialize(obj: Any):
    """Dask collection -> concrete array/frame; anything else unchanged."""
    if obj is None:
        return None
    compute = getattr(obj, "compute", None)
    if callable(compute):
        return compute()
    return obj


def _spool_partitions(X: Any, params: dict):
    """Dask collection -> SpooledData, one partition at a time.

    The out-of-core alternative to `_materialize`'s whole-collection
    gather (docs/DATA_PLANE.md): each delayed partition is computed and
    appended to a disk-backed chunk store, so host memory holds one
    partition + one buffered chunk instead of the full collection.
    Returns None when X is not partition-aware (plain arrays, or the
    store is off) — callers then keep the legacy single-process
    materialize semantics."""
    to_delayed = getattr(X, "to_delayed", None)
    if not callable(to_delayed):
        return None
    import numpy as np

    from .config import Config
    from .data.store import ChunkStore, SpooledData
    from .data.streaming import _spool_root, resolve_chunk_rows

    cfg = Config({
        k: params[k] for k in
        ("data_source", "ram_budget_mb", "data_chunk_rows",
         "data_spool_dir")
        if params.get(k) is not None
    })
    # dask.array -> (row_chunks, col_chunks) object grid;
    # dask.dataframe -> flat list of partitions
    grid = np.asarray(to_delayed(), dtype=object)
    if grid.ndim == 0:
        grid = grid.reshape(1, 1)
    elif grid.ndim == 1:
        grid = grid.reshape(-1, 1)
    _owned, root = _spool_root(cfg)
    store = None
    for row in grid:
        blocks = [np.asarray(_materialize(b)) for b in row]
        block = (
            blocks[0] if len(blocks) == 1
            else np.concatenate(
                [b.reshape(b.shape[0], -1) for b in blocks], axis=1
            )
        )
        if block.ndim == 1:
            block = block.reshape(-1, 1)
        if store is None:
            store = ChunkStore.create(
                root / "raw", n_features=block.shape[1],
                chunk_rows=resolve_chunk_rows(block.shape[1], cfg),
            )
        store.append_rows(block)
    if store is None:
        return None
    return SpooledData(store.finalize())


class _DaskMixin:
    """client= plumbing shared by the three estimators.

    sklearn's get_params introspects ``__init__`` and rejects varargs,
    so each estimator restates the explicit LGBMModel signature
    (sklearn.py:88) plus ``client`` — same approach as the reference's
    Dask classes."""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective: Optional[Any] = None,
        class_weight: Optional[Any] = None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state: Optional[int] = None,
        n_jobs: Optional[int] = None,
        importance_type: str = "split",
        client: Optional[Any] = None,
        **kwargs: Any,
    ):
        self.client = client
        super().__init__(
            boosting_type=boosting_type,
            num_leaves=num_leaves,
            max_depth=max_depth,
            learning_rate=learning_rate,
            n_estimators=n_estimators,
            subsample_for_bin=subsample_for_bin,
            objective=objective,
            class_weight=class_weight,
            min_split_gain=min_split_gain,
            min_child_weight=min_child_weight,
            min_child_samples=min_child_samples,
            subsample=subsample,
            subsample_freq=subsample_freq,
            colsample_bytree=colsample_bytree,
            reg_alpha=reg_alpha,
            reg_lambda=reg_lambda,
            random_state=random_state,
            n_jobs=n_jobs,
            importance_type=importance_type,
            **kwargs,
        )

    @property
    def client_(self) -> Any:
        """The Dask client passed at construction (reference
        dask.py `client_`; informational here — training runs on the
        TPU mesh, see module docstring)."""
        if self.client is None:
            raise AttributeError("no Dask client was provided")
        return self.client

    def _materialize_fit_args(self, kwargs):
        es = kwargs.get("eval_set")
        if es is not None:
            kwargs["eval_set"] = [
                (_materialize(a), _materialize(b)) for a, b in es
            ]
        for key in ("sample_weight", "init_score", "group"):
            if kwargs.get(key) is not None:
                kwargs[key] = _materialize(kwargs[key])
        for key in ("eval_sample_weight", "eval_init_score", "eval_group"):
            val = kwargs.get(key)
            if val is None:
                continue
            # standard form: one entry per eval set — materialize each;
            # a bare collection is materialized whole
            if isinstance(val, (list, tuple)):
                kwargs[key] = [_materialize(v) for v in val]
            else:
                kwargs[key] = _materialize(val)
        return kwargs

    def fit(self, X, y, **kwargs):  # noqa: D102 - see class docstring
        if self._other_params.get("data_source") == "chunked":
            spooled = _spool_partitions(X, self.get_params())
            if spooled is not None:
                X = spooled
        return super().fit(
            _materialize(X), _materialize(y),
            **self._materialize_fit_args(dict(kwargs)),
        )

    def predict(self, X, *args, **kwargs):  # noqa: D102
        return super().predict(_materialize(X), *args, **kwargs)


class DaskLGBMClassifier(_DaskMixin, LGBMClassifier):
    """Classifier accepting Dask collections (reference dask.py:1159)."""

    def predict_proba(self, X, *args, **kwargs):  # noqa: D102
        return super().predict_proba(_materialize(X), *args, **kwargs)


class DaskLGBMRegressor(_DaskMixin, LGBMRegressor):
    """Regressor accepting Dask collections (reference dask.py:1421)."""


class DaskLGBMRanker(_DaskMixin, LGBMRanker):
    """Ranker accepting Dask collections (reference dask.py:1646)."""
