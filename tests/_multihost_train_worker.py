"""Worker for the 2-process FULL-API multi-host test: run_distributed
(the dask _train analog) drives lgb.train end-to-end — global binning,
tree_learner=data over the 2-process mesh, per-iteration device metric
eval, early stopping, rank-0 model save. Both ranks must converge to
byte-identical models."""

import hashlib
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    out_model = sys.argv[4]

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import lightgbm_tpu as lgb
    from lightgbm_tpu.parallel.multihost import run_distributed

    # one logical dataset; each rank holds a DIFFERENT, uneven shard
    rs = np.random.RandomState(7)
    n, f = 6000, 8
    X = rs.randn(n, f)
    w = rs.randn(f)
    y = ((X @ w + 0.5 * rs.randn(n)) > 0).astype(np.float64)
    cut = 2600  # deliberately uneven: 2600 vs 3400 rows
    sl = slice(0, cut) if rank == 0 else slice(cut, n)
    Xv = rs.randn(1000, f)
    yv = ((Xv @ w + 0.5 * rs.randn(1000)) > 0).astype(np.float64)
    vcut = 500
    vsl = slice(0, vcut) if rank == 0 else slice(vcut, None)

    evals = {}
    bst = run_distributed(
        {
            "objective": "binary",
            "num_leaves": 15,
            "learning_rate": 0.2,
            "metric": "auc",
            "min_data_in_leaf": 5,
            "verbosity": -1,
            "seed": 3,
        },
        X[sl], y[sl],
        machines=",".join(f"127.0.0.1:{int(port) + i}" for i in range(nproc)),
        machine_rank=rank,
        num_boost_round=30,
        valid=(Xv[vsl], yv[vsl]),
        callbacks=[
            lgb.early_stopping(stopping_rounds=5, verbose=False),
            lgb.record_evaluation(evals),
        ],
    )

    model_str = bst.model_to_string(num_iteration=-1)
    digest = hashlib.sha256(model_str.encode()).hexdigest()[:16]
    if rank == 0:
        bst.save_model(out_model)
    auc = list(evals["valid"].values())[0][-1]
    print(
        f"MULTIHOST_TRAIN_OK rank={rank} trees={bst.num_trees()} "
        f"best_it={bst.best_iteration} auc={auc:.4f} model={digest}",
        flush=True,
    )

    # renewal objective (regression_l1): boost_from_average percentile
    # + host leaf refit must use GLOBAL rows (lazy gathers cached before
    # the device arrays go global)
    rs3 = np.random.RandomState(11)
    yl1 = (X @ w + 0.3 * rs3.randn(n)).astype(np.float64)
    bst_l1 = run_distributed(
        {
            "objective": "regression_l1",
            "num_leaves": 15,
            "learning_rate": 0.2,
            "min_data_in_leaf": 5,
            "verbosity": -1,
        },
        X[sl], yl1[sl],
        machines=",".join(f"127.0.0.1:{int(port) + i}" for i in range(nproc)),
        machine_rank=rank,
        num_boost_round=5,
    )
    d_l1 = hashlib.sha256(
        bst_l1.model_to_string(num_iteration=-1).encode()
    ).hexdigest()[:16]
    print(f"MULTIHOST_L1_OK rank={rank} model={d_l1}", flush=True)


if __name__ == "__main__":
    main()
