"""Sparse CSR ingestion (dataset.from_csr): binning from column
indices without densifying the raw matrix (reference sparse_bin.hpp:73
delta-encoded columns, dataset_loader.cpp:210 two_round streaming)."""

import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset


def _sparse_problem(n=6000, f=30, density=0.04, seed=0):
    rs = np.random.RandomState(seed)
    X = np.zeros((n, f))
    for j in range(f):
        m = rs.rand(n) < density
        X[m, j] = rs.randn(int(m.sum())) + (j % 3)
    y = ((X[:, :8].sum(axis=1) + 0.3 * rs.randn(n)) > 0).astype(float)
    return X, y


def test_csr_bins_match_dense():
    """The sparse path must produce the same mappers and the same
    per-row bin content as the dense path (modulo EFB grouping, which
    is compared post-expansion through training below)."""
    X, _ = _sparse_problem()
    cfg = Config({"max_bin": 255, "enable_bundle": False})
    dense = BinnedDataset.from_numpy(np.ascontiguousarray(X), cfg)
    sparse = BinnedDataset.from_csr(scipy_sparse.csr_matrix(X), cfg)
    assert len(dense.mappers) == len(sparse.mappers)
    for md, ms in zip(dense.mappers, sparse.mappers):
        np.testing.assert_allclose(md.upper_bounds, ms.upper_bounds)
        assert md.most_freq_bin == ms.most_freq_bin
        assert md.num_bin == ms.num_bin
    np.testing.assert_array_equal(
        np.asarray(dense.bins), np.asarray(sparse.bins)
    )


def test_csr_training_matches_dense():
    """lgb.train on a scipy CSR must produce the same model as on the
    dense array (EFB on: the sparse conflict search and the dense one
    must agree on this exclusive-ish data)."""
    X, y = _sparse_problem(seed=2)
    preds = {}
    for name, data in (("dense", X),
                       ("csr", scipy_sparse.csr_matrix(X))):
        ds = lgb.Dataset(data, label=y, free_raw_data=False)
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "min_data_in_leaf": 5},
            ds, num_boost_round=10,
        )
        preds[name] = bst.predict(X)
    from sklearn.metrics import roc_auc_score

    assert roc_auc_score(y, preds["csr"]) > 0.7
    np.testing.assert_allclose(preds["csr"], preds["dense"], atol=1e-6)


def test_csr_bundled_training_matches_dense():
    """Mutually-exclusive one-hot-ish blocks DO bundle on both paths;
    the sparse conflict search must yield a lossless grouping whose
    trained model matches the dense path's predictions."""
    rs = np.random.RandomState(7)
    n, blocks, width = 5000, 5, 6
    cols = []
    for b in range(blocks):
        z = np.zeros((n, width))
        idx = rs.randint(0, width, n)
        z[np.arange(n), idx] = rs.rand(n) + 0.5
        on = rs.rand(n) < 0.3
        z[~on] = 0.0
        cols.append(z)
    X = np.hstack(cols)
    w = rs.randn(X.shape[1])
    y = ((X @ w + 0.3 * rs.randn(n)) > 0).astype(float)

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset

    s = BinnedDataset.from_csr(scipy_sparse.csr_matrix(X),
                               Config({"max_bin": 255}))
    assert s.bundle_layout is not None  # sparse path really bundles
    assert s.bins.shape[0] < X.shape[1]

    preds = {}
    for name, data in (("dense", X),
                       ("csr", scipy_sparse.csr_matrix(X))):
        ds = lgb.Dataset(data, label=y, free_raw_data=False)
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "min_data_in_leaf": 5},
            ds, num_boost_round=10,
        )
        preds[name] = bst.predict(X)
    np.testing.assert_allclose(preds["csr"], preds["dense"], atol=1e-6)


def test_csr_valid_set_reference():
    X, y = _sparse_problem(seed=3)
    Xv, yv = _sparse_problem(seed=4)
    ds = lgb.Dataset(scipy_sparse.csr_matrix(X), label=y,
                     free_raw_data=False)
    vs = lgb.Dataset(scipy_sparse.csr_matrix(Xv), label=yv, reference=ds,
                     free_raw_data=False)
    evals = {}
    import lightgbm_tpu.callback as cbm

    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "metric": "auc", "min_data_in_leaf": 5},
        ds, num_boost_round=8, valid_sets=[vs], valid_names=["v"],
        callbacks=[cbm.record_evaluation(evals)],
    )
    assert len(evals["v"]["auc"]) == 8
    assert evals["v"]["auc"][-1] > 0.7


def test_csr_never_densifies(monkeypatch):
    """Guard: the sparse path must not call .toarray() on the input."""
    X, y = _sparse_problem(n=2000, f=10, seed=5)
    sp = scipy_sparse.csr_matrix(X)

    def boom(*a, **k):  # pragma: no cover
        raise AssertionError("sparse input was densified")

    monkeypatch.setattr(sp.__class__, "toarray", boom)
    ds = lgb.Dataset(sp, label=y, free_raw_data=False)
    ds.construct()
    assert ds._binned is not None
