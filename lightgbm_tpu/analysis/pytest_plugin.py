"""Pytest plugin: trace-safety fixtures for any suite using this
package. Opt-in (NOT a pytest11 entry point — auto-load would tax
every pytest run in the venv with the full package+jax import): run
`pytest -p lightgbm_tpu.analysis.pytest_plugin`, or declare
`pytest_plugins = ["lightgbm_tpu.analysis.pytest_plugin"]` in a root
conftest. The in-repo tests import these fixtures from conftest.py.

- `retrace_guard`: factory for the jit-cache-miss guard
  (analysis/retrace.py), with `jax.checking_leaks` opt-in.
- `jaxpr_audit`: run named invariant audits inline and assert green.
- `cost_audit`: run named cost/memory/wire-bytes audits inline and
  assert green (compiles the entries on the CPU backend).
- `scale_audit`: run named SPMD scaling-contract audits inline and
  assert green. Defaults to the tiny tier-1 D in {1, 2} ladder (the
  full {1, 2, 4, 8} ladder is `--strict` / tools/analysis.sh
  territory); pass `ladder=` to widen.
- `concurrency_lint`: lint source text (or the installed package) with
  the serving lock-discipline rules and assert no unsuppressed
  findings.
"""

from __future__ import annotations

import pytest


@pytest.fixture
def retrace_guard():
    from .retrace import retrace_guard as guard

    return guard


@pytest.fixture
def jaxpr_audit():
    """fixture(names=None) -> list[AuditResult], asserting all green."""
    from .jaxpr_audit import run_audits

    def run(names=None):
        results = run_audits(names=names)
        bad = [r.format() for r in results if not r.ok]
        assert not bad, "\n".join(bad)
        return results

    return run


@pytest.fixture
def cost_audit():
    """fixture(names=None) -> list[AuditResult], asserting all green."""
    from .cost_audit import run_cost_audits

    def run(names=None):
        results = run_cost_audits(names=names)
        bad = [r.format() for r in results if not r.ok]
        assert not bad, "\n".join(bad)
        return results

    return run


@pytest.fixture
def scale_audit():
    """fixture(names=None, ladder=None) -> list[AuditResult],
    asserting all green. ladder=None runs the tier-1 D in {1, 2}
    subset (budget pins still checked EXACT at those rungs)."""
    from .scale_audit import TIER1_LADDER, run_scale_audits

    def run(names=None, ladder=None):
        results = run_scale_audits(
            names=names, ladder=ladder or TIER1_LADDER)
        bad = [r.format() for r in results if not r.ok]
        assert not bad, "\n".join(bad)
        return results

    return run


@pytest.fixture
def concurrency_lint():
    """fixture(src=None) -> findings; None lints the installed package.
    Asserts no unsuppressed findings either way."""
    from .concurrency_lint import (
        concurrency_lint_package,
        concurrency_lint_source,
    )
    from .lint import format_findings

    def run(src=None):
        findings = (concurrency_lint_package() if src is None
                    else concurrency_lint_source(src))
        bad = [f for f in findings if not f.suppressed]
        assert not bad, format_findings(bad, label="concurrency")
        return findings

    return run
