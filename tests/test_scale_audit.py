"""SPMD scaling-contract auditor (analysis/scale_audit.py, Pass 7):
red paths driven through synthetic per-rung summaries — a collective
count that grows with D fails the census, a widened per-device payload
fails the declared wire law, and a per-row array silently falling back
to replication fails the sharding-spec table — plus the real tier-1
green path via the `scale_audit` fixture. The heavy real-trace
coverage (full D-ladder, all entries) lives in `--strict` /
tools/analysis.sh, not here: the tier-1 suite runs ~770-860 s of its
870 s budget already."""

import pytest

from lightgbm_tpu.analysis.scale_audit import (
    SCALE_ENTRIES,
    ScaleSpec,
    ScaleSummary,
    ShardRule,
    audit_scale,
    run_scale_audits,
)

_ROW_LEAF_RULES = (
    ShardRule("per_row_sharded", r"in/0/float32\[N\]", "P(data)"),
    ShardRule("row_leaf_sharded", r"out/0/int32\[N\]", "P(data)"),
    ShardRule("rest_replicated", r"(in|out)/.*", "replicated"),
)


def _summary(census, send=100, rs_shard=0, eqns=50, shardings=(
        ("in/0/float32[N]", "P(data)"),
        ("out/0/int32[N]", "P(data)"),
        ("in/1/float32[]", "replicated"),
)) -> ScaleSummary:
    return ScaleSummary(census=dict(census), send_bytes=send,
                        rs_shard_bytes=rs_shard, eqn_count=eqns,
                        shardings=tuple(shardings))


def _pins(summaries):
    from lightgbm_tpu.analysis.scale_audit import _pins_from

    return _pins_from(summaries)


def _spec(**kw) -> ScaleSpec:
    base = dict(law="const", rules=_ROW_LEAF_RULES)
    base.update(kw)
    return ScaleSpec(**base)


# --------------------------------------------------------- green base
def test_synthetic_const_entry_green():
    summaries = {1: _summary({"psum": 2}), 2: _summary({"psum": 2}),
                 4: _summary({"psum": 2})}
    r = audit_scale("fixture", _spec(), summaries, _pins(summaries))
    assert r.ok, r.format()


# ---------------------------------------------------------- red paths
def test_collective_count_growing_with_d_fails_census():
    """ACCEPTANCE red path (a): one psum per DEVICE instead of one per
    step — the census is no longer D-invariant and the gate names the
    offending rungs."""
    summaries = {1: _summary({"psum": 1}), 2: _summary({"psum": 2}),
                 4: _summary({"psum": 4})}
    r = audit_scale("growing", _spec(), summaries, _pins(summaries))
    assert not r.ok
    bad = {c.name: c for c in r.contracts if not c.ok}
    assert "census_D_invariant" in bad, r.format()
    assert "varies with D" in bad["census_D_invariant"].detail


def test_undeclared_all_gather_fails():
    """An all_gather appearing where the entry declares none — even
    D-invariantly — fails (gathering un-shards an array everywhere)."""
    summaries = {d: _summary({"psum": 2, "all_gather": 1})
                 for d in (1, 2, 4)}
    r = audit_scale("gathered", _spec(allows_all_gather=False),
                    summaries, _pins(summaries))
    assert not r.ok
    assert any(c.name == "no_undeclared_all_gather" and not c.ok
               for c in r.contracts), r.format()


def test_widened_payload_fails_wire_law():
    """ACCEPTANCE red path (b): per-device payload that grows with D
    fails `const`; a reduce-scatter shard that stops shrinking fails
    `1/D`; an elected wire that stops undercutting its baseline fails
    `elected`."""
    # const law, payload doubles with the mesh
    grow = {1: _summary({"psum": 2}, send=100),
            2: _summary({"psum": 2}, send=200),
            4: _summary({"psum": 2}, send=400)}
    r = audit_scale("widened", _spec(), grow, _pins(grow))
    assert not r.ok
    assert any(c.name == "wire_law_const" and not c.ok
               for c in r.contracts), r.format()

    # 1/D law, shard bytes flat (someone dropped the scatter)
    flat = {d: _summary({"reduce_scatter": 1}, send=100, rs_shard=64)
            for d in (2, 4, 8)}
    r2 = audit_scale("unscattered", _spec(law="1/D", floor=2),
                     flat, _pins(flat))
    assert not r2.ok
    assert any(c.name == "wire_law_1/D" and not c.ok
               for c in r2.contracts), r2.format()
    # ...and the true 1/D shape passes
    good = {d: _summary({"reduce_scatter": 1}, send=100,
                        rs_shard=512 // d) for d in (2, 4, 8)}
    r3 = audit_scale("scattered", _spec(law="1/D", floor=2),
                     good, _pins(good))
    assert r3.ok, r3.format()

    # elected law: flat but NOT under the baseline wire
    elected = {d: _summary({"psum": 3}, send=500) for d in (2, 4)}
    baseline = {d: _summary({"reduce_scatter": 1}, send=400)
                for d in (2, 4)}
    r4 = audit_scale(
        "bloated_election",
        _spec(law="elected", floor=2, baseline="rounds_quant_rs"),
        elected, _pins(elected), baseline=baseline,
    )
    assert not r4.ok
    assert any(c.name == "elected_undercuts_baseline" and not c.ok
               for c in r4.contracts), r4.format()


def test_eqn_count_scaling_with_d_fails():
    summaries = {1: _summary({"psum": 1}, eqns=50),
                 2: _summary({"psum": 1}, eqns=90),
                 4: _summary({"psum": 1}, eqns=170)}
    r = audit_scale("unrolled", _spec(eqn_tol=8), summaries,
                    _pins(summaries))
    assert not r.ok
    assert any(c.name == "eqns_D_invariant" and not c.ok
               for c in r.contracts), r.format()


def test_replicated_per_row_output_fails_sharding_rules():
    """ACCEPTANCE red path (c): the per-row leaf output silently falls
    back to full replication (the 8x-memory failure the
    match_partition_rules table exists to catch)."""
    summaries = {d: _summary({"psum": 1}, shardings=(
        ("in/0/float32[N]", "P(data)"),
        ("out/0/int32[N]", "replicated"),   # <- the silent fallback
        ("in/1/float32[]", "replicated"),
    )) for d in (1, 2)}
    r = audit_scale("replicated", _spec(), summaries, _pins(summaries))
    assert not r.ok
    bad = {c.name: c for c in r.contracts if not c.ok}
    assert "sharding_rules" in bad, r.format()
    assert "row_leaf_sharded" in bad["sharding_rules"].detail

    # an array no rule covers fails too (the table must stay total)
    uncovered = {1: _summary({"psum": 1}, shardings=(
        ("smap1/in/0/float32[N]", "P(data)"),
    ))}
    r2 = audit_scale("uncovered", _spec(), uncovered, _pins(uncovered))
    assert any(c.name == "sharding_rules" and not c.ok
               and "matches no sharding rule" in c.detail
               for c in r2.contracts), r2.format()

    # a rule matching nothing is a stale table, not a free pass
    assert any(
        c.name == "sharding_rules" and "matched nothing" in c.detail
        for c in r2.contracts if not c.ok
    ), r2.format()


def test_missing_or_stale_budget_fails():
    summaries = {1: _summary({"psum": 2}), 2: _summary({"psum": 2})}
    r = audit_scale("nobudget", _spec(), summaries, None)
    assert any(c.name == "scale_budget" and not c.ok
               for c in r.contracts), r.format()
    stale = _pins(summaries)
    stale["2"]["send_bytes"] = 1  # drifted pin
    r2 = audit_scale("stale", _spec(), summaries, stale)
    assert not r2.ok
    assert any(c.name == "scale_budget" and "send_bytes" in c.detail
               for c in r2.contracts if not c.ok), r2.format()


# ----------------------------------------------------- real entries
def test_unknown_entry_name_raises():
    with pytest.raises(KeyError, match="typo_entry"):
        run_scale_audits(names=["typo_entry"])


def test_specs_declare_every_law_archetype():
    """The declared table covers all four laws (the docs' contract),
    and the voting baseline is a real entry."""
    laws = {s.law for s in SCALE_ENTRIES.values()}
    assert laws == {"const", "1/D", "elected", "bounded"}
    for name, s in SCALE_ENTRIES.items():
        if s.baseline is not None:
            assert s.baseline in SCALE_ENTRIES, (name, s.baseline)
        assert s.rules, f"{name} declares no sharding rules"


def test_tier1_ladder_green_via_fixture(scale_audit):
    """The real tier-1 hook: D in {1, 2} on the elected entry and its
    1/D baseline — exact budget pins at both rungs, sharding table
    verified against the real shard_map in/out names. (The fixture
    shares build_entry's memo with test_static_analysis's strict-
    equivalent run, so the traces are paid once per process.)"""
    results = scale_audit(names=["rounds_voting"])
    assert [r.name for r in results] == ["rounds_voting"]
    by_contract = {c.name: c for c in results[0].contracts}
    assert "elected_undercuts_baseline" in by_contract
    assert "scale_budget" in by_contract
