"""User-facing Dataset and Booster (reference python-package/lightgbm/basic.py).

The reference Dataset (basic.py:1746) and Booster (basic.py:3543) wrap C
handles over a ctypes ABI; here they wrap the host BinnedDataset and the
GBDT driver directly — the "ABI" is the jit boundary. Construction is
lazy like the reference: `Dataset.construct()` runs binning on first use
so that `reference=` mapper sharing and `free_raw_data` semantics hold.
"""

from __future__ import annotations

import copy
import os
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import log
from .boosting import GBDT
from .config import Config
from .dataset import BinnedDataset
from .log import LightGBMError

_ArrayLike = Union[np.ndarray, "list", "tuple"]


def set_network(
    machines: Any,
    local_listen_port: int = 12400,
    listen_time_out: int = 120,
    num_machines: int = 1,
    *,
    machine_list_file: str = "",
    machine_rank: "int | None" = None,
) -> None:
    """Join the multi-host training cluster (reference
    basic.py:3773 set_network -> LGBM_NetworkInit; positional order
    matches: machines, local_listen_port, listen_time_out,
    num_machines). On the TPU build this forms the JAX multi-controller
    cluster (parallel/multihost.py); collectives then ride ICI/DCN
    through the same grower code as single-host. listen_time_out is
    accepted for API parity (the cluster handshake timeout is managed
    by jax.distributed)."""
    del listen_time_out
    from .parallel import multihost

    if machines is not None and not isinstance(machines, str):
        machines = ",".join(str(m) for m in machines)
    multihost.init_distributed(
        machines=machines or None,
        machine_list_file=machine_list_file or None,
        num_machines=num_machines if num_machines > 1 else None,
        local_listen_port=local_listen_port,
        machine_rank=machine_rank,
    )


class Sequence:
    """Generic random-access data sequence for streaming Dataset
    construction (reference basic.py:905 Sequence ABC). Subclass with
    `__len__` and `__getitem__` (int row or slice -> numpy rows) and
    optionally set `batch_size`; pass one Sequence or a list of them as
    `Dataset(data=...)` — the binned matrix is built in two streaming
    passes without ever materializing the full float64 matrix."""

    batch_size: int = 4096

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError


def _is_sequence_input(data: Any) -> bool:
    if isinstance(data, Sequence):
        return True
    return (
        isinstance(data, list)
        and len(data) > 0
        and all(isinstance(s, Sequence) for s in data)
    )


def _to_2d_numpy(data: Any) -> Tuple[np.ndarray, Optional[List[str]]]:
    feature_name = None
    try:  # pandas support without importing pandas eagerly
        import pandas as pd  # type: ignore

        if isinstance(data, pd.DataFrame):
            feature_name = [str(c) for c in data.columns]
            return data.to_numpy(dtype=np.float64), feature_name
        if isinstance(data, pd.Series):
            return data.to_numpy(dtype=np.float64).reshape(-1, 1), None
    except ImportError:
        pass
    # Arrow ingest (reference include/LightGBM/arrow.h + c_api.cpp:1645
    # LGBM_DatasetCreateFromArrow): accept pyarrow Table / RecordBatch
    # column-wise; nulls -> NaN
    tname = type(data).__module__ + "." + type(data).__name__
    if tname.startswith("pyarrow."):
        import pyarrow as pa  # already imported: data IS a pyarrow object

        def _col64(col):
            # cast first so nullable bool/int columns become float64
            # with nulls -> NaN (a raw to_numpy would yield an object
            # array of None that np.asarray cannot float)
            return np.asarray(
                col.cast(pa.float64()).to_numpy(zero_copy_only=False)
            )

        if isinstance(data, pa.RecordBatch):
            data = pa.Table.from_batches([data])
        if isinstance(data, pa.Table):
            feature_name = [str(c) for c in data.column_names]
            cols = [_col64(data.column(i)) for i in range(data.num_columns)]
            return np.column_stack(cols), feature_name
        if isinstance(data, (pa.ChunkedArray, pa.Array)):
            return _col64(data).reshape(-1, 1), None
    if hasattr(data, "toarray"):  # scipy sparse
        return np.asarray(data.toarray(), dtype=np.float64), None
    arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr.astype(np.float64, copy=False), feature_name


def _to_1d(v: Any) -> Optional[np.ndarray]:
    if v is None:
        return None
    try:
        import pandas as pd  # type: ignore

        if isinstance(v, (pd.Series, pd.DataFrame)):
            return v.to_numpy().ravel()
    except ImportError:
        pass
    if (type(v).__module__ + "." + type(v).__name__).startswith("pyarrow."):
        import pyarrow as pa  # already imported: v IS a pyarrow object

        if isinstance(v, (pa.ChunkedArray, pa.Array)):
            return np.asarray(
                v.cast(pa.float64()).to_numpy(zero_copy_only=False)
            ).ravel()
        if isinstance(v, pa.Table):
            if v.num_columns != 1:
                raise ValueError(
                    f"expected a 1-column table, got {v.num_columns} columns"
                )
            return np.asarray(
                v.column(0).cast(pa.float64()).to_numpy(zero_copy_only=False)
            ).ravel()
    return np.asarray(v).ravel()


class Dataset:
    """Dataset wrapper (reference basic.py:1746)."""

    def __init__(
        self,
        data: Any,
        label: Any = None,
        reference: Optional["Dataset"] = None,
        weight: Any = None,
        group: Any = None,
        init_score: Any = None,
        feature_name: Union[str, List[str]] = "auto",
        categorical_feature: Union[str, List[Union[int, str]]] = "auto",
        params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = True,
        position: Any = None,
    ):
        self.data = data
        self.label = _to_1d(label)
        self.reference = reference
        self.weight = _to_1d(weight)
        self.group = _to_1d(group)
        self.position = _to_1d(position)
        self.init_score = _to_1d(init_score)
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = copy.deepcopy(params) or {}
        self.free_raw_data = free_raw_data
        self._binned: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self.pandas_categorical = None

    # ------------------------------------------------------------------
    def _resolve_categorical(self, feature_names: List[str]) -> List[int]:
        cf = self.categorical_feature
        if cf == "auto" or cf is None:
            return []
        out = []
        for c in cf:
            if isinstance(c, str):
                if c in feature_names:
                    out.append(feature_names.index(c))
                else:
                    log.warning(f"Unknown categorical feature {c}")
            else:
                out.append(int(c))
        return out

    def _construct_chunked(self, cfg, _gt):
        """data_source=chunked construct. Returns the streamed binned
        dataset, or None when this input must use a legacy path."""
        from .data.store import ChunkStoreError, SpooledData

        if self.reference is not None:
            log.warning(
                "data_source=chunked: valid sets with reference= must "
                "bin with the training set's mappers; using the in-RAM "
                "path"
            )
            return None
        if cfg.linear_tree:
            log.warning(
                "data_source=chunked does not retain raw feature "
                "values required by linear_tree; using the in-RAM path"
            )
            return None
        data = self.data
        if isinstance(data, (str, Path)):
            from .parsers import is_binary_file

            if is_binary_file(str(data)):
                return None  # .bin caches load pre-binned as-is
        elif hasattr(data, "tocsc") and hasattr(data, "tocsr"):
            log.warning(
                "data_source=chunked does not ingest scipy sparse "
                "matrices; using the sparse in-RAM path"
            )
            return None
        names = (
            [str(n) for n in self.feature_name]
            if isinstance(self.feature_name, list)
            else None
        )
        cat = self._resolve_categorical(names or [])
        if _is_sequence_input(data):
            if not isinstance(data, list):
                data = [data]
        elif not isinstance(data, (str, Path, SpooledData, np.ndarray)):
            arr, pandas_names = _to_2d_numpy(data)
            data = arr
            if names is None and pandas_names is not None:
                names = pandas_names
        from .data.streaming import construct_chunked

        try:
            with _gt.scope("dataset construct (chunked stream)"):
                return construct_chunked(
                    data, cfg,
                    label=self.label,
                    weight=self.weight,
                    group=self.group,
                    init_score=self.init_score,
                    position=self.position,
                    categorical_feature=cat,
                    feature_names=names,
                )
        except ChunkStoreError as e:
            log.warning(
                f"data_source=chunked ingestion failed ({e}); falling "
                "back to the in-RAM path"
            )
            return None

    def construct(self) -> "Dataset":
        if self._binned is not None:
            return self
        if self.data is None:
            log.fatal("Cannot construct Dataset: raw data was freed")
        from .timer import global_timer as _gt

        from .data.store import SpooledData

        cfg_src = Config(self.params)
        if (cfg_src.data_source == "chunked"
                or isinstance(self.data, SpooledData)):
            # out-of-core construct (docs/DATA_PLANE.md): spool to a
            # chunk store, stream two-pass binning, assemble the device
            # matrix chunk-wise. Ineligible inputs warn and fall
            # through to the legacy paths below.
            binned = self._construct_chunked(cfg_src, _gt)
            if binned is not None:
                self._binned = binned
                if self.feature_name == "auto" and binned.feature_names:
                    self.feature_name = list(binned.feature_names)
                if self.free_raw_data:
                    self.data = None
                return self

        if _is_sequence_input(self.data):
            # streaming two-pass path (reference Sequence / push APIs)
            seqs = self.data if isinstance(self.data, list) else [self.data]
            cfg = Config(self.params)
            names = (
                [str(n) for n in self.feature_name]
                if isinstance(self.feature_name, list)
                else None
            )
            cat = self._resolve_categorical(names or [])
            if cfg.linear_tree:
                log.fatal(
                    "linear_tree needs raw feature values; Sequence "
                    "streaming does not retain them"
                )
            with _gt.scope("dataset construct (streaming binning)"):
                self._binned = BinnedDataset.from_sequences(
                    seqs,
                    cfg,
                    label=self.label,
                    weight=self.weight,
                    group=self.group,
                    init_score=self.init_score,
                    position=self.position,
                    categorical_feature=cat,
                    feature_names=names,
                )
            if self.free_raw_data:
                self.data = None
            return self
        if isinstance(self.data, (str, Path)):
            # file-path input (reference Dataset accepts text or binary
            # data files directly; DatasetLoader::LoadFromFile): .bin
            # caches load pre-binned, text files parse CSV/TSV/LibSVM
            from .config import resolve_alias as _ra
            from .parsers import is_binary_file, load_binary, load_text_file

            path = str(self.data)
            fp = {_ra(k): v for k, v in self.params.items()}
            cfg_file = Config(self.params)
            # two_round streaming (dataset_loader.cpp:210): EXPLICIT
            # config only, matching the reference (it streams only on
            # two_round=true) — host memory stays O(chunk) + the binned
            # matrix instead of O(file). Streamed bin boundaries come
            # from reservoir-sampled rows, so auto-switching at a size
            # threshold would silently change model output when a file
            # crosses 1 GB (ADVICE r5 low); large files get a warning
            # instead. Ineligible cases fall through to the whole-file
            # loader: linear_tree (needs raw values), reference=
            # datasets (must bin with the TRAINING set's mappers),
            # constructor-level categorical_feature (column names
            # unknown pre-parse).
            stream_ok = (
                not is_binary_file(path)
                and not cfg_file.linear_tree
                and self.reference is None
                and self.categorical_feature in ("auto", None, "")
            )
            want_stream = cfg_file.two_round
            if not want_stream and stream_ok:
                # single memory-budget warning path (data plane knob):
                # ram_budget_mb=0 keeps the legacy 1 GB threshold
                from .data import warn_over_budget

                warn_over_budget(
                    f"text file {path}", os.path.getsize(path),
                    cfg_file.ram_budget_mb,
                    "pass two_round=true or data_source=chunked to "
                    "stream it with bounded host memory (streamed "
                    "binning samples rows, so results may differ "
                    "slightly from the whole-file loader; parity "
                    "deviation documented in docs/DESIGN_DECISIONS.md)",
                )
            if want_stream and not stream_ok:
                log.warning(
                    "two_round streaming skipped: linear_tree / "
                    "reference= / constructor categorical_feature need "
                    "the whole-file loader"
                )
            if want_stream and stream_ok:
                from .parsers import load_text_file_two_round

                with _gt.scope("dataset construct (two_round stream)"):
                    res = load_text_file_two_round(
                        path, cfg_file,
                        header=str(fp.get("header", "false")).lower()
                        in ("true", "1"),
                        label_column=fp.get("label_column", 0),
                        weight_column=fp.get("weight_column", ""),
                        group_column=fp.get("group_column", ""),
                        ignore_column=fp.get("ignore_column", ""),
                        categorical_feature=fp.get(
                            "categorical_feature", ""),
                    )
                if res is not None:  # None = LibSVM fallback
                    self._binned = res["binned"]
                    md = self._binned.metadata
                    if self.label is not None:
                        md.label = np.asarray(self.label, np.float32)
                    if self.weight is not None:
                        md.weight = np.asarray(self.weight, np.float32)
                    if self.group is not None:
                        md.group = np.asarray(self.group, np.int64)
                    if self.init_score is not None:
                        md.init_score = np.asarray(
                            self.init_score, np.float64)
                    if self.position is not None:
                        md.position = np.asarray(self.position, np.int32)
                    if (self.feature_name == "auto"
                            and res["feature_names"]):
                        self.feature_name = res["feature_names"]
                    if self.free_raw_data:
                        self.data = None
                    return self
            with _gt.scope("dataset construct (file)"):
                if is_binary_file(path):
                    self._binned = load_binary(path)
                    md = self._binned.metadata
                    if self.label is not None:
                        md.label = np.asarray(self.label, np.float32)
                    if self.weight is not None:
                        md.weight = np.asarray(self.weight, np.float32)
                    if self.group is not None:
                        md.group = np.asarray(self.group, np.int64)
                    if self.init_score is not None:
                        md.init_score = np.asarray(self.init_score,
                                                   np.float64)
                    if self.position is not None:
                        md.position = np.asarray(self.position, np.int32)
                    if self.free_raw_data:
                        self.data = None
                    return self
                loaded = load_text_file(
                    path,
                    header=str(fp.get("header", "false")).lower()
                    in ("true", "1"),
                    label_column=fp.get("label_column", 0),
                    weight_column=fp.get("weight_column", ""),
                    group_column=fp.get("group_column", ""),
                    ignore_column=fp.get("ignore_column", ""),
                    categorical_feature=fp.get("categorical_feature", ""),
                )
                self.data = loaded["X"]
                if self.label is None and loaded["label"] is not None:
                    self.label = np.asarray(loaded["label"])
                if self.weight is None and loaded["weight"] is not None:
                    self.weight = np.asarray(loaded["weight"])
                if self.group is None and loaded["group"] is not None:
                    self.group = np.asarray(loaded["group"])
                if (self.init_score is None
                        and loaded.get("init_score") is not None):
                    self.init_score = np.asarray(loaded["init_score"])
                if (self.feature_name == "auto"
                        and loaded["feature_names"]):
                    self.feature_name = loaded["feature_names"]
                if (self.categorical_feature == "auto"
                        and loaded["categorical_feature"]):
                    self.categorical_feature = loaded[
                        "categorical_feature"
                    ]
            # fall through to the numpy path below with the parsed matrix
        cfg0 = Config(self.params)
        _sparse_names = (
            [str(n) for n in self.feature_name]
            if isinstance(self.feature_name, list)
            else []
        )
        if (hasattr(self.data, "tocsc") and hasattr(self.data, "tocsr")
                and not self._resolve_categorical(_sparse_names)
                and not cfg0.linear_tree):
            # scipy sparse: bin from column indices, never densify
            # (sparse_bin.hpp:73 / dataset_loader.cpp:210 two_round)
            names = _sparse_names or None
            ref_binned = None
            if self.reference is not None:
                self.reference.construct()
                ref_binned = self.reference._binned
            with _gt.scope("dataset construct (sparse binning)"):
                self._binned = BinnedDataset.from_csr(
                    self.data,
                    cfg0,
                    label=self.label,
                    weight=self.weight,
                    group=self.group,
                    init_score=self.init_score,
                    position=self.position,
                    feature_names=names,
                    reference=ref_binned,
                )
            if self.free_raw_data:
                self.data = None
            return self
        arr, pandas_names = _to_2d_numpy(self.data)
        if isinstance(self.feature_name, list):
            names = [str(n) for n in self.feature_name]
        elif pandas_names is not None:
            names = pandas_names
        else:
            names = [f"Column_{i}" for i in range(arr.shape[1])]
        cfg = Config(self.params)
        ref_binned = None
        if self.reference is not None:
            self.reference.construct()
            ref_binned = self.reference._binned
        cat = self._resolve_categorical(names)
        keep_raw = bool(cfg.linear_tree)
        with _gt.scope("dataset construct (binning)"):
            self._binned = BinnedDataset.from_numpy(
                arr,
                cfg,
                label=self.label,
                weight=self.weight,
                group=self.group,
                init_score=self.init_score,
                position=self.position,
                categorical_feature=cat,
                feature_names=names,
                reference=ref_binned,
                keep_raw=keep_raw,
            )
        if self.free_raw_data:
            self.data = None
        return self

    # ------------------------------------------------------------------
    @classmethod
    def from_binned(cls, binned) -> "Dataset":
        """Wrap an already-binned dataset (the .bin cache fast path,
        reference dataset_loader.cpp:424 LoadFromBinFile)."""
        ds = cls(data=None, free_raw_data=True)
        ds.label = binned.metadata.label
        ds.weight = binned.metadata.weight
        ds.group = binned.metadata.group
        ds.init_score = binned.metadata.init_score
        ds.feature_name = binned.feature_names
        ds._binned = binned
        return ds

    # ------------------------------------------------------------------
    def create_valid(
        self, data, label=None, weight=None, group=None, init_score=None,
        params=None, position=None,
    ) -> "Dataset":
        return Dataset(
            data, label=label, reference=self, weight=weight, group=group,
            init_score=init_score, params=params or self.params, position=position,
        )

    def set_label(self, label) -> "Dataset":
        self.label = _to_1d(label)
        if self._binned is not None:
            self._binned.metadata.label = np.asarray(self.label, dtype=np.float32)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = _to_1d(weight)
        if self._binned is not None:
            self._binned.metadata.weight = (
                np.asarray(self.weight, dtype=np.float32) if weight is not None else None
            )
        return self

    def set_group(self, group) -> "Dataset":
        self.group = _to_1d(group)
        if self._binned is not None:
            self._binned.metadata.group = (
                np.asarray(self.group, dtype=np.int64) if group is not None else None
            )
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = _to_1d(init_score)
        if self._binned is not None:
            self._binned.metadata.init_score = (
                np.asarray(self.init_score, dtype=np.float64)
                if init_score is not None
                else None
            )
        return self

    def set_position(self, position) -> "Dataset":
        self.position = _to_1d(position)
        if self._binned is not None:
            self._binned.metadata.position = (
                np.asarray(self.position, dtype=np.int32)
                if position is not None else None
            )
        return self

    def get_label(self):
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def get_position(self):
        return self.position

    _FIELDS = ("label", "weight", "group", "init_score", "position")

    def set_field(self, field_name: str, data) -> "Dataset":
        """Generic metadata setter (LGBM_DatasetSetField;
        reference basic.py Dataset.set_field)."""
        if field_name not in self._FIELDS:
            raise KeyError(f"unknown field {field_name!r}")
        return getattr(self, f"set_{field_name}")(data)

    def get_field(self, field_name: str):
        """Generic metadata getter (LGBM_DatasetGetField)."""
        if field_name not in self._FIELDS:
            raise KeyError(f"unknown field {field_name!r}")
        return getattr(self, f"get_{field_name}")()

    def get_data(self):
        """The raw data this Dataset was built from (reference
        basic.py Dataset.get_data). Unavailable once raw data was
        freed (free_raw_data=True after construct)."""
        if self.data is None:
            raise LightGBMError(
                "Cannot call get_data after freeing raw data; "
                "set free_raw_data=False when constructing the Dataset"
            )
        return self.data

    def get_params(self) -> Dict[str, Any]:
        """The Dataset-relevant parameters this Dataset carries
        (reference basic.py Dataset.get_params)."""
        from .config import DATASET_PARAMS, resolve_alias

        return {
            k: v for k, v in self.params.items()
            if resolve_alias(k) in DATASET_PARAMS
        }

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """Bin this Dataset with another Dataset's bin mappers
        (reference basic.py Dataset.set_reference)."""
        if self._binned is not None and self.reference is not reference:
            raise LightGBMError(
                "Cannot set reference after the Dataset was constructed; "
                "pass reference= at creation"
            )
        self.reference = reference
        return self

    def get_ref_chain(self, ref_limit: int = 100):
        """Set of Datasets reachable through .reference links
        (reference basic.py Dataset.get_ref_chain)."""
        head = self
        chain = set()
        while len(chain) < ref_limit:
            if isinstance(head, Dataset):
                chain.add(head)
                if head.reference is not None:
                    head = head.reference
                else:
                    break
            else:
                break
        return chain

    def set_feature_name(self, feature_name) -> "Dataset":
        """Set feature names; after construction renames in place
        (reference basic.py Dataset.set_feature_name)."""
        self.feature_name = feature_name
        if self._binned is not None and feature_name != "auto":
            names = list(feature_name)
            if len(names) != self._binned.num_total_features:
                raise LightGBMError(
                    f"Length of feature names {len(names)} does not match "
                    f"number of features {self._binned.num_total_features}"
                )
            self._binned.feature_names = names
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """Set categorical features; binding happens at construct
        (reference basic.py Dataset.set_categorical_feature)."""
        if self.categorical_feature == categorical_feature:
            return self
        if self._binned is not None:
            raise LightGBMError(
                "Cannot set categorical feature after the Dataset was "
                "constructed; set it at creation"
            )
        self.categorical_feature = categorical_feature
        return self

    def feature_num_bin(self, feature: Union[int, str]) -> int:
        """Number of bins for a feature (LGBM_DatasetGetFeatureNumBin)."""
        self.construct()
        if isinstance(feature, str):
            feature = self._binned.feature_names.index(feature)
        return int(self._binned.mappers[feature].num_bin)

    def save_binary(self, filename: Union[str, Path]) -> "Dataset":
        """Persist the binned form to a fast-reload binary file
        (Dataset::SaveBinaryFile, dataset.h:700; reload by passing the
        path as Dataset(data=...) — parsers.py binary cache format)."""
        from .parsers import save_binary as _save

        self.construct()
        _save(self._binned, str(filename))
        return self

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Horizontally stack another Dataset's features into this one
        (reference basic.py Dataset.add_features_from /
        LGBM_DatasetAddFeaturesFrom). TPU deviation: the reference
        splices the other dataset's FeatureGroups into this one's bin
        structure; here both raw matrices are concatenated and binning
        re-runs at next construct — requires raw data on both sides
        (free_raw_data=False)."""
        if self.data is None or other.data is None:
            raise LightGBMError(
                "add_features_from requires raw data on both Datasets "
                "(free_raw_data=False)"
            )
        a, a_names = _to_2d_numpy(self.data)
        b, b_names = _to_2d_numpy(other.data)
        if a.shape[0] != b.shape[0]:
            raise LightGBMError(
                f"Cannot add features from a Dataset with {b.shape[0]} "
                f"rows to one with {a.shape[0]} rows"
            )
        self.data = np.concatenate([a, b], axis=1)
        if (isinstance(self.feature_name, list)
                and isinstance(other.feature_name, list)):
            self.feature_name = list(self.feature_name) + list(
                other.feature_name
            )
        else:
            self.feature_name = "auto"
        cf_a = self.categorical_feature
        cf_b = other.categorical_feature
        if cf_a != "auto" or cf_b != "auto":
            # string names survive the merge (feature-name lists were
            # concatenated above); integer indices from `other` shift by
            # this dataset's original width
            merged = [] if cf_a == "auto" else list(cf_a)
            if cf_b != "auto":
                merged += [
                    c if isinstance(c, str) else c + a.shape[1]
                    for c in cf_b
                ]
            self.categorical_feature = merged
        self._binned = None  # re-bin with the widened matrix
        return self

    def num_data(self) -> int:
        if self._binned is not None:
            return self._binned.num_data
        if isinstance(self.data, (str, Path)):
            self.construct()  # file input: shape is unknown until parsed
            return self._binned.num_data
        arr, _ = _to_2d_numpy(self.data)
        return arr.shape[0]

    def num_feature(self) -> int:
        if self._binned is not None:
            return self._binned.num_total_features
        if isinstance(self.data, (str, Path)):
            self.construct()
            return self._binned.num_total_features
        arr, _ = _to_2d_numpy(self.data)
        return arr.shape[1]

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._binned.feature_names)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        idx = np.asarray(used_indices)
        if self._binned is not None:
            # binned-level subset (Dataset::CopySubrow): shares mappers,
            # keeps all metadata incl. group/position
            sub = Dataset.__new__(Dataset)
            sub.__dict__.update(
                data=None,
                label=None if self.label is None else self.label[idx],
                reference=self,
                weight=None if self.weight is None else self.weight[idx],
                group=None,
                position=None if self.position is None else self.position[idx],
                init_score=None if self.init_score is None else self.init_score[idx],
                feature_name=self.feature_name,
                categorical_feature=self.categorical_feature,
                params=copy.deepcopy(params or self.params),
                free_raw_data=self.free_raw_data,
                _binned=self._binned.copy_subrow(idx),
                used_indices=idx,
                pandas_categorical=self.pandas_categorical,
            )
            sub.group = (
                None if sub._binned.metadata.group is None
                else np.asarray(sub._binned.metadata.group)
            )
            return sub
        if self.data is None:
            log.fatal("Cannot subset: raw data was freed")
        arr, _ = _to_2d_numpy(self.data)
        sub = Dataset(
            arr[idx],
            label=None if self.label is None else self.label[idx],
            reference=self,
            weight=None if self.weight is None else self.weight[idx],
            position=None if self.position is None else self.position[idx],
            init_score=None if self.init_score is None else self.init_score[idx],
            feature_name=self.feature_name,
            categorical_feature=self.categorical_feature,
            params=params or self.params,
            free_raw_data=self.free_raw_data,
        )
        sub.used_indices = idx
        return sub


class Booster:
    """Booster wrapper (reference basic.py:3543)."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        train_set: Optional[Dataset] = None,
        model_file: Optional[Union[str, Path]] = None,
        model_str: Optional[str] = None,
    ):
        self.params = copy.deepcopy(params) or {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_data_name = "training"
        self.pandas_categorical = None

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError(f"Training data should be Dataset instance, met {type(train_set).__name__}")
            # distributed network params join the multi-host cluster
            # BEFORE any backend touch (reference basic.py:3606: Booster
            # calls set_network when machines/num_machines are present).
            # Aliases resolve through the config table (num_machine,
            # machine_list/mlist, local_port, workers, ...).
            from .config import resolve_alias as _ra

            net = {}
            for k, v in self.params.items():
                net.setdefault(_ra(k), v)
            nm = int(net.get("num_machines", 1))
            if nm > 1:
                set_network(
                    machines=net.get("machines", ""),
                    local_listen_port=int(net.get("local_listen_port", 12400)),
                    num_machines=nm,
                    machine_list_file=net.get("machine_list_filename", ""),
                )
            # params relevant to dataset CONSTRUCTION merge into the
            # dataset (binding at first construct); the booster's config
            # takes only dataset-relevant keys from the dataset so one
            # training's params never leak into the next booster using
            # the same Dataset
            from .config import DATASET_PARAMS, resolve_alias

            train_set.params = {**train_set.params, **self.params}
            train_set.construct()
            ds_part = {
                k: v
                for k, v in train_set.params.items()
                if resolve_alias(k) in DATASET_PARAMS
            }
            self.config = Config({**ds_part, **self.params})
            from .boosting import create_boosting

            self._gbdt = create_boosting(self.config, train_set._binned)
            self.train_set = train_set
            self._valid_sets: List[Dataset] = []
            self._name_valid_sets: List[str] = []
        elif model_file is not None or model_str is not None:
            from .model_io import load_model_string

            if model_file is not None:
                model_str = Path(model_file).read_text()
            self.config, self._gbdt = load_model_string(model_str)
            self.train_set = None
            self._valid_sets = []
            self._name_valid_sets = []
        else:
            raise TypeError("At least one of train_set, model_file or model_str should be not None.")

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if not isinstance(data, Dataset):
            raise TypeError(f"Validation data should be Dataset instance, met {type(data).__name__}")
        if data.reference is not self.train_set:
            data.reference = self.train_set
        data.construct()
        self._gbdt.add_valid(data._binned, name)
        self._valid_sets.append(data)
        self._name_valid_sets.append(name)
        return self

    def _continue_from(self, init_booster: "Booster") -> None:
        """Continued training (reference input_model / python init_model,
        boosting.h:311): adopt the loaded model's trees and seed every
        score set with their binned-traversal predictions, then keep
        appending trees. Call after add_valid."""
        from .tree import tree_to_arrays

        from . import log

        gb = self._gbdt
        src = init_booster._gbdt
        K = gb.num_class
        if src.num_class != K:
            log.fatal(
                f"init_model has {src.num_class} models per iteration, "
                f"training config has {K}"
            )
        if gb.config.boosting in ("dart", "rf"):
            # DART drop bookkeeping and RF's running-average score have
            # no stored state for the loaded trees — refuse rather than
            # silently corrupt (reference keeps full state in-process)
            log.fatal(
                f"init_model with boosting={gb.config.boosting} is not "
                "supported yet; use boosting=gbdt for continued training"
            )
        models = list(src.models)
        gb._models = list(models)
        gb.iter_ = len(models) // K
        gb._init_iters = gb.iter_  # iteration origin for truncate/snapshot
        for mi, t in enumerate(models):
            arrays = tree_to_arrays(t, gb.train_set)
            gb.device_trees.append((arrays, None))
            k = mi % K
            for ss in [gb.train] + gb.valids:
                dev = gb.dev if ss is gb.train else ss.dataset.device_arrays()
                if t.num_leaves > 1:
                    leaf = gb._traverse(arrays, dev["bins"], dev["nan_bin"], dev.get("bundle"))
                    ss.score = ss.score.at[k].add(arrays.leaf_value[leaf])
                else:
                    ss.score = ss.score.at[k].add(float(t.leaf_value[0]))

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration (basic.py:4052). Returns True if
        training stopped (cannot split any more)."""
        if train_set is not None and train_set is not self.train_set:
            raise LightGBMError("Resetting train_set is not supported")
        if fobj is None:
            return self._gbdt.train_one_iter()
        # DART applies its dropout lazily before the score is read
        # (reference GetTrainingScore, dart.hpp:80)
        if hasattr(self._gbdt, "before_gradients"):
            self._gbdt.before_gradients()
        grad, hess = fobj(self.__inner_predict_raw(0), self.train_set)
        return self._gbdt.train_one_iter(np.asarray(grad), np.asarray(hess))

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return self._gbdt.current_iteration()

    def num_trees(self) -> int:
        return self._gbdt.num_trees()

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_class

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self.config.update(params)
        self._gbdt.shrinkage_rate = self.config.learning_rate
        self._gbdt.params = None  # force re-derive
        from .learner import make_split_params

        self._gbdt.params = make_split_params(self.config)
        return self

    # ------------------------------------------------------------------
    def __inner_predict_raw(self, data_idx: int) -> np.ndarray:
        g = self._gbdt
        ss = g.train if data_idx == 0 else g.valids[data_idx - 1]
        score = g.get_score(ss)
        return score if g.num_class > 1 else score[0]

    def eval(self, data: Dataset, name: str, feval=None):
        raise NotImplementedError("use eval_train/eval_valid")

    def eval_train(self, feval=None) -> List[Tuple[str, str, float, bool]]:
        out = self._gbdt.eval_train()
        out = [(self._train_data_name, n, v, hb) for (_dn, n, v, hb) in out]
        if feval is not None:
            out.extend(self._run_feval(feval, 0, self._train_data_name))
        return out

    def eval_valid(self, feval=None) -> List[Tuple[str, str, float, bool]]:
        out = self._gbdt.eval_valid()
        if feval is not None:
            for i, name in enumerate(self._name_valid_sets):
                out.extend(self._run_feval(feval, i + 1, name))
        return out

    def _run_feval(self, feval, data_idx: int, name: str):
        ds = self.train_set if data_idx == 0 else self._valid_sets[data_idx - 1]
        preds = self.__inner_predict_raw(data_idx)
        # the reference converts scores before handing them to feval
        # (GetPredictAt -> ConvertOutput, gbdt.cpp:709); custom-objective
        # training has objective none -> identity
        if self._gbdt.objective is not None:
            preds = self._gbdt.objective.convert_output(preds)
        fevals = feval if isinstance(feval, (list, tuple)) else [feval]
        results = []
        for f in fevals:
            res = f(preds, ds)
            results.extend(res if isinstance(res, list) else [res])
        return [(name, rn, rv, rhb) for rn, rv, rhb in results]

    # ------------------------------------------------------------------
    def predict(
        self,
        data: Any,
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        raw_score: bool = False,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        validate_features: bool = False,
        device: Optional[str] = None,
        **kwargs: Any,
    ) -> np.ndarray:
        arr, _ = _to_2d_numpy(data)
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        if device not in (None, "", "cpu", "host"):
            # TPU-resident scoring (serving.TensorForest): the forest is
            # packed to device tables and traversed rows x trees under
            # jit. Tables are rebuilt per call (same posture as
            # _packed_model: models mutate in place through refit /
            # set_leaf_output and packing is ~ms); the jitted traversal
            # itself is shared module-level, so no recompile per call.
            if pred_contrib:
                log.warning(
                    "pred_contrib has no device implementation; using "
                    "the host SHAP path"
                )
            elif kwargs.get("pred_early_stop",
                            self.params.get("pred_early_stop", False)):
                log.warning(
                    "pred_early_stop has no device implementation; "
                    "using the host predictor"
                )
            else:
                from .serving import TensorForest

                forest = TensorForest.from_booster(self)
                if pred_leaf:
                    return forest.predict_leaf(
                        arr, start_iteration, num_iteration
                    )
                raw = forest.predict_raw(arr, start_iteration, num_iteration)
                g = self._gbdt
                if not raw_score and g.objective is not None:
                    raw = g.objective.convert_output(raw)
                return raw[0] if g.num_class == 1 else raw.T
        if pred_leaf:
            return self._gbdt.predict_leaf_index(arr, start_iteration, num_iteration)
        if pred_contrib:
            if any(t.is_linear for t in self._gbdt.models):
                from . import log

                log.fatal(
                    "pred_contrib (SHAP) is not supported for models "
                    "with linear trees"
                )
            return self._gbdt.predict_contrib(arr, start_iteration, num_iteration)
        # prediction early stop (reference c_api predict parameter
        # parsing; kwargs mirror the parameter names)
        early_stop = None
        if kwargs.get("pred_early_stop", self.params.get("pred_early_stop", False)):
            # classification only (reference Predictor picks CreateNone
            # for everything else, prediction_early_stop.cpp:18)
            is_cls = self._gbdt.num_class > 1 or getattr(
                self.config, "objective", ""
            ) in ("binary", "cross_entropy", "cross_entropy_lambda")
            if is_cls:
                early_stop = (
                    int(kwargs.get("pred_early_stop_freq",
                                   self.params.get("pred_early_stop_freq", 10))),
                    float(kwargs.get("pred_early_stop_margin",
                                     self.params.get("pred_early_stop_margin", 10.0))),
                )
            else:
                log.warning(
                    "pred_early_stop only applies to classification; ignored"
                )
        return self._gbdt.predict(arr, start_iteration, num_iteration,
                                  raw_score=raw_score, early_stop=early_stop)

    # ------------------------------------------------------------------
    def model_to_string(
        self, num_iteration: Optional[int] = None, start_iteration: int = 0,
        importance_type: str = "split",
    ) -> str:
        from .model_io import save_model_string

        ni = num_iteration
        if ni is None:
            ni = self.best_iteration if self.best_iteration > 0 else -1
        return save_model_string(self._gbdt, self.config, ni, start_iteration)

    def save_model(
        self, filename: Union[str, Path], num_iteration: Optional[int] = None,
        start_iteration: int = 0, importance_type: str = "split",
    ) -> "Booster":
        Path(filename).write_text(
            self.model_to_string(num_iteration, start_iteration, importance_type)
        )
        return self

    def dump_model(
        self, num_iteration: Optional[int] = None, start_iteration: int = 0,
        importance_type: str = "split", object_hook=None,
    ) -> Dict[str, Any]:
        """JSON model representation (LGBM_BoosterDumpModel)."""
        from .model_io import dump_model_dict

        ni = num_iteration
        if ni is None:
            ni = self.best_iteration if self.best_iteration > 0 else -1
        d = dump_model_dict(
            self._gbdt, self.config, ni, start_iteration, importance_type
        )
        if object_hook is not None:
            # apply like json.loads(..., object_hook=...): bottom-up over
            # every dict in the structure
            import json

            d = json.loads(json.dumps(d), object_hook=object_hook)
        return d

    def refit(
        self, data: Any, label: Any, decay_rate: float = 0.9, **kwargs: Any
    ) -> "Booster":
        """Refit existing tree structures on new data
        (Booster.refit / LGBM_BoosterRefit)."""
        import copy

        arr, _ = _to_2d_numpy(data)
        new_booster = copy.copy(self)
        # shallow-copy the GBDT: refit only rewrites host tree leaf values
        # and replaces device_trees entries, so sharing the (possibly
        # device-resident) dataset buffers avoids doubling memory
        new_booster._gbdt = copy.copy(self._gbdt)
        new_booster._gbdt.models = [copy.deepcopy(t) for t in self._gbdt.models]
        new_booster._gbdt.device_trees = list(self._gbdt.device_trees)
        # un-alias the remaining mutable members so future mutations on the
        # refitted booster can never corrupt the source booster (the score
        # arrays themselves are immutable jax arrays — the _ScoreSet
        # containers and valids list are what must not be shared)
        import dataclasses as _dc

        if hasattr(self._gbdt, "train"):
            new_booster._gbdt.train = _dc.replace(self._gbdt.train)
            new_booster._gbdt.valids = [
                _dc.replace(v) for v in self._gbdt.valids
            ]
        new_params = dict(self.config.explicit_params())
        new_params["refit_decay_rate"] = decay_rate
        new_booster.config = Config(new_params)
        new_booster._gbdt.config = new_booster.config
        new_booster._gbdt.refit(
            arr, _to_1d(label), weight=kwargs.get("weight"),
            group=kwargs.get("group"),
        )
        return new_booster

    def get_split_value_histogram(
        self,
        feature,
        bins=None,
        xgboost_style: bool = False,
    ):
        """Histogram of the numeric split thresholds the model chose for
        one feature (reference basic.py:5065). Returns
        ``numpy.histogram``-style ``(hist, bin_edges)``, or the XGBoost
        matrix/DataFrame form when ``xgboost_style=True``."""
        from .plotting import _split_values

        values = _split_values(self, feature)
        n_unique = len(set(values))
        if bins is None or (
            isinstance(bins, int) and xgboost_style and bins > n_unique
        ):
            bins = max(n_unique, 1)
        hist, edges = np.histogram(np.asarray(values, dtype=np.float64),
                                   bins=bins)
        if not xgboost_style:
            return hist, edges
        keep = hist != 0
        out = np.column_stack((edges[1:][keep], hist[keep]))
        try:
            import pandas as pd

            return pd.DataFrame(out, columns=["SplitValue", "Count"])
        except ImportError:
            return out

    def feature_importance(self, importance_type: str = "split", iteration=None) -> np.ndarray:
        return self._gbdt.feature_importance(importance_type)

    def feature_name(self) -> List[str]:
        if self.train_set is not None:
            return self.train_set.get_feature_name()
        return list(self._gbdt.feature_names)

    def num_feature(self) -> int:
        if self._gbdt.train_set is not None:
            return self._gbdt.train_set.num_total_features
        return len(self._gbdt.feature_names)

    def free_dataset(self) -> "Booster":
        self.train_set = None
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """Name used for the training set in eval output (reference
        basic.py Booster.set_train_data_name)."""
        self._train_data_name = name
        return self

    def model_from_string(self, model_str: str) -> "Booster":
        """Load a model from its text-format string in place
        (reference basic.py Booster.model_from_string)."""
        from .model_io import load_model_string

        self.config, self._gbdt = load_model_string(model_str)
        self.train_set = None
        self._valid_sets = []
        self._name_valid_sets = []
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """Output value of one leaf (LGBM_BoosterGetLeafValue)."""
        return float(self._gbdt.models[tree_id].leaf_value[leaf_id])

    def set_leaf_output(self, tree_id: int, leaf_id: int,
                        value: float) -> "Booster":
        """Overwrite one leaf's output value (LGBM_BoosterSetLeafValue;
        Tree::SetLeafOutput). Updates the device-resident copy used by
        fused validation scoring as well as the host tree; like the
        reference, already-accumulated train/valid scores are not
        retroactively adjusted."""
        t = self._gbdt.models[tree_id]
        t.leaf_value[leaf_id] = float(value)
        if tree_id < len(self._gbdt.device_trees):
            arrays, aux = self._gbdt.device_trees[tree_id]
            if arrays is not None:
                arrays = arrays._replace(
                    leaf_value=arrays.leaf_value.at[leaf_id].set(
                        float(value)
                    )
                )
                self._gbdt.device_trees[tree_id] = (arrays, aux)
        return self

    def lower_bound(self) -> float:
        """Lower bound of the raw score over all possible inputs
        (LGBM_BoosterGetLowerBoundValue: sum of per-tree minima)."""
        return float(sum(
            float(np.min(t.leaf_value[: t.num_leaves]))
            for t in self._gbdt.models
        ))

    def upper_bound(self) -> float:
        """Upper bound of the raw score (LGBM_BoosterGetUpperBoundValue)."""
        return float(sum(
            float(np.max(t.leaf_value[: t.num_leaves]))
            for t in self._gbdt.models
        ))

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """Randomly permute the tree order in [start, end) iterations
        (LGBM_BoosterShuffleModels; predictions are order-invariant)."""
        K = self.num_model_per_iteration()
        n_iter = self._gbdt.num_trees() // K
        end = n_iter if end_iteration < 0 else min(end_iteration, n_iter)
        idx = np.arange(start_iteration, end)
        np.random.shuffle(idx)
        order = np.concatenate([
            np.arange(start_iteration),
            idx,
            np.arange(end, n_iter),
        ])
        models, dev = self._gbdt.models, self._gbdt.device_trees
        self._gbdt.models = [
            models[i * K + k] for i in order for k in range(K)
        ]
        if len(dev) == len(models):
            self._gbdt.device_trees = [
                dev[i * K + k] for i in order for k in range(K)
            ]
        return self

    def trees_to_dataframe(self):
        """All trees flattened to one pandas DataFrame, one row per
        node/leaf (reference basic.py Booster.trees_to_dataframe —
        same column set)."""
        import pandas as pd

        if self._gbdt.num_trees() == 0:
            raise LightGBMError(
                "There are no trees in this Booster and thus nothing "
                "to parse"
            )

        rows: List[Dict[str, Any]] = []

        def node_ix(tree_index: int, node: Dict[str, Any]) -> str:
            if "split_index" in node:
                return f"{tree_index}-S{node['split_index']}"
            return f"{tree_index}-L{node.get('leaf_index', 0)}"

        model = self.dump_model()
        for t in model["tree_info"]:
            tree_index = t["tree_index"]
            # explicit preorder stack: chain-shaped deep trees must not
            # hit the interpreter recursion limit
            stack = [(t["tree_structure"], 1, None)]
            while stack:
                node, depth, parent = stack.pop()
                ix = node_ix(tree_index, node)
                is_split = "split_index" in node
                left = node.get("left_child")
                right = node.get("right_child")
                rows.append({
                    "tree_index": tree_index,
                    "node_depth": depth,
                    "node_index": ix,
                    "left_child": (
                        node_ix(tree_index, left) if left else None
                    ),
                    "right_child": (
                        node_ix(tree_index, right) if right else None
                    ),
                    "parent_index": parent,
                    "split_feature": (
                        self._feature_display_name(node["split_feature"])
                        if is_split else None
                    ),
                    "split_gain": node.get("split_gain"),
                    "threshold": node.get("threshold"),
                    "decision_type": node.get("decision_type"),
                    "missing_direction": (
                        ("left" if node.get("default_left") else "right")
                        if is_split else None
                    ),
                    "missing_type": node.get("missing_type"),
                    "value": node.get("internal_value",
                                      node.get("leaf_value")),
                    "weight": node.get("internal_weight",
                                       node.get("leaf_weight")),
                    "count": node.get("internal_count",
                                      node.get("leaf_count")),
                })
                if is_split:
                    stack.append((right, depth + 1, ix))
                    stack.append((left, depth + 1, ix))
        return pd.DataFrame(rows)

    def _feature_display_name(self, fidx: int) -> str:
        names = self.feature_name()
        return names[fidx] if fidx < len(names) else f"Column_{fidx}"

    def set_network(
        self,
        machines: Any,
        local_listen_port: int = 12400,
        listen_time_out: int = 120,
        num_machines: int = 1,
    ) -> "Booster":
        """Join a multi-host cluster from an existing Booster (reference
        basic.py Booster.set_network; module-level set_network applies)."""
        set_network(machines, local_listen_port, listen_time_out,
                    num_machines)
        self._network = True
        return self

    def free_network(self) -> "Booster":
        return self
