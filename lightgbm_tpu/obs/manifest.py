"""Run manifests: one JSON record describing what ran, where, and what
it cost.

A BENCH json answers "how fast"; a manifest answers "what exactly was
this run" — resolved config, device topology, software versions,
compile counts (from the retrace guard's process-lifetime counters),
phase-timer totals, the metrics snapshot, and runtime collective
wire-byte estimates side by side with the static budgets pinned in
``analysis/cost_budget.json``. Written per training run through the
``run_manifest`` / ``profile_dir`` CLI params (cli.py), or directly
via :func:`write_manifest`.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional

SCHEMA = "lightgbm-tpu/run-manifest/v1"

# config keys always recorded resolved (beyond the explicit params):
# the ones that change what the run computes or how it is distributed
_CORE_KEYS = (
    "task", "objective", "boosting", "num_iterations", "num_leaves",
    "learning_rate", "max_bin", "tree_learner", "num_class",
    "use_quantized_grad", "tpu_growth_mode", "tpu_growth_rounds",
    "tpu_hist_dtype",
)


def _device_info() -> Dict[str, Any]:
    import jax

    devs = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "device_kinds": sorted({getattr(d, "device_kind", "?")
                                for d in devs}),
    }


def _versions() -> Dict[str, str]:
    import jax
    import numpy as np

    out = {
        "python": sys.version.split()[0],
        "jax": jax.__version__,
        "numpy": np.__version__,
    }
    try:
        import jaxlib

        out["jaxlib"] = jaxlib.__version__
    except Exception:  # noqa: BLE001 — jaxlib version is best-effort
        pass
    return out


def _static_wire_budget() -> Dict[str, int]:
    """wire_bytes per audited entry from analysis/cost_budget.json (the
    exact static pins the runtime counter is compared against)."""
    from pathlib import Path

    from ..analysis import cost_audit

    path = Path(cost_audit.__file__).parent / "cost_budget.json"
    if not path.exists():
        return {}
    budgets = json.loads(path.read_text())
    return {
        name: int(d.get("wire_bytes", 0))
        for name, d in budgets.items()
    }


def build_manifest(config: Optional[Any] = None,
                   booster: Optional[Any] = None,
                   extra: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """Assemble the manifest dict (JSON-serializable).

    config: a Config (or plain params dict); booster: a trained
    Booster (model summary section); extra: caller payload merged in
    under "extra"."""
    from ..analysis.retrace import compile_counters
    from ..timer import global_timer
    from .metrics import default_registry

    cfg_section: Dict[str, Any] = {}
    if config is not None:
        if hasattr(config, "explicit_params"):
            cfg_section["explicit"] = dict(config.explicit_params())
            cfg_section["resolved"] = {
                k: getattr(config, k) for k in _CORE_KEYS if k in config
            }
        else:
            cfg_section["explicit"] = dict(config)

    reg = default_registry()
    snap = reg.snapshot()
    runtime_wire = sum(
        snap.get("lgbmtpu_collective_wire_bytes_total", {}).values()
    )
    manifest: Dict[str, Any] = {
        "schema": SCHEMA,
        "created_unix": time.time(),
        "argv": list(sys.argv),
        "config": cfg_section,
        "devices": _device_info(),
        "versions": _versions(),
        "compile": compile_counters(),
        "phase_timers": {
            name: {"seconds": round(acc, 6), "calls": cnt}
            for name, (acc, cnt) in global_timer.summary().items()
        },
        "metrics": snap,
        "collectives": {
            "runtime_wire_bytes_estimate": int(runtime_wire),
            "static_budget_wire_bytes": _static_wire_budget(),
        },
    }
    # fold in the most recent flight record (docs/OBSERVABILITY.md):
    # rounds recorded, stream path, final evals, anomaly trip counts —
    # the longitudinal run summary next to the point-in-time snapshot
    from .recorder import last_summary

    fr = last_summary()
    if fr is not None:
        manifest["flight_recorder"] = fr
    # fold in the most recent chunked-ingestion record (spool/bin rates
    # and per-chunk peak RSS — the flat-memory proof for out-of-core
    # runs, docs/DATA_PLANE.md)
    from ..data import last_stats

    dp = last_stats()
    if dp is not None:
        manifest["data_plane"] = dp
    if booster is not None:
        try:
            manifest["model"] = {
                "num_trees": booster.num_trees(),
                "best_iteration": getattr(booster, "best_iteration", -1),
                "num_class": getattr(
                    getattr(booster, "_gbdt", None), "num_class", 1
                ),
                # RESOLVED histogram channel layout (may differ from
                # the requested tpu_hist_dtype — e.g. auto, or the
                # off-rounds-path fallback): the numerics provenance a
                # reproduction needs
                "hist_dtype": getattr(
                    getattr(booster, "_gbdt", None), "hist_dtype", None
                ),
                # RESOLVED tree learner after mode resolution plus the
                # voting election footprint (elected columns and the
                # per-tree wire estimate) — distinguishes the
                # elected-columns-only reduce from a full-histogram run
                "tree_learner": getattr(
                    getattr(booster, "_gbdt", None),
                    "tree_learner_resolved", None
                ),
                "voting_elected_cols": getattr(
                    getattr(booster, "_gbdt", None),
                    "voting_elected_cols", None
                ),
                "voting_wire_bytes_est": getattr(
                    getattr(booster, "_gbdt", None),
                    "voting_wire_bytes_est", None
                ),
            }
        except Exception:  # noqa: BLE001 — model summary is best-effort
            pass
    if extra:
        manifest["extra"] = dict(extra)
    return manifest


def write_manifest(path: str, config: Optional[Any] = None,
                   booster: Optional[Any] = None,
                   extra: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """Build and write the manifest; returns the dict. Tuples and other
    non-JSON values in config params degrade to strings rather than
    failing the run they describe."""
    m = build_manifest(config=config, booster=booster, extra=extra)
    with open(path, "w") as f:
        json.dump(m, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    from .. import log

    log.info(f"run manifest written to {path}")
    return m
