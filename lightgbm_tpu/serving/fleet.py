"""Multi-tenant model fleet: hundreds of models behind one process,
resident as stacked forest tables with LRU HBM paging.

The registry (registry.py) keeps one TensorForest — one set of device
tables and one executable family — per loaded version: exactly right
for a handful of models, hopeless for a fleet of hundreds (HBM fills,
and every distinct table shape compiles its own ladder). The fleet
changes the residency unit:

- models group into SHAPE FAMILIES by their power-of-two-quantized
  table dims; each family owns one or more ``(S, ...)``-stacked device
  table sets (:class:`ForestStack`). Scoring slot ``s`` goes through
  ``stacked_forest_apply`` with the slot as a TRACED index, so the
  whole family shares one executable per bucket — paging never
  recompiles.
- an LRU pager moves models between host tables (always held, numpy)
  and a stack slot (HBM). Page-in writes the slot via one jitted
  functional update and warms the smallest bucket; eviction just
  releases the slot. A PIN COUNT per model keeps every model of an
  in-flight request resident until its last chunk lands — a request
  can never observe a torn slot or another tenant's trees.
- per-model QoS: each tenant carries its own queue deadline and
  admission cap (falling back to the fleet defaults), applied to its
  lazily-built MicroBatcher; per-model ``lgbmtpu_*{model=...}`` series
  land on /metrics through the dispatcher's latency ring.
- hot-swap/rollback keep registry semantics: versions are independent
  residency entries and the active pointer moves atomically under the
  fleet lock; in-flight requests pinned to the old version finish on
  the old slot.
- ``pred_contrib`` serves device TreeSHAP (forest.py contrib_apply)
  from per-model contrib tables packed lazily on first request and
  dropped on eviction — explanation traffic pays for its own HBM.

Locking: ONE condition variable guards all fleet state (names,
versions, stacks, pins, residency counts) — there is no second fleet
lock to order against. Device work (table uploads, stack writes,
warm-up, scoring) always happens OUTSIDE the condition; readers take
a stack/slot snapshot under it and score on the snapshot, which stays
valid because stack writes are functional updates and pinned slots
are never reassigned.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import log
from ..obs.metrics import (
    record_fleet_page,
    record_fleet_resident,
    record_registry_event,
    record_serve_rejection,
)
from ..resilience.errors import QueueOverflow
from ..resilience.faultinject import fault_point
from .dispatch import DEFAULT_BUCKETS, BucketDispatcher
from .forest import (
    _pow2,
    _stacked_apply_jit,
    pack_contrib_tables,
    pack_forest_tables,
    pad_forest_tables,
)
from .registry import _booster_from, _make_host_fallback

_STACK_WRITE_JIT = None


def _stack_write_jit():
    """Jitted functional slot write: one executable per stack shape.

    NEVER donates the input stack: a concurrent reader scoring another
    slot holds the previous arrays — donation would invalidate the
    buffers under it (and XLA:CPU donation has crashed before; see
    ROADMAP history). The transient 2x stack during a write is the
    price of torn-free paging."""
    global _STACK_WRITE_JIT
    if _STACK_WRITE_JIT is None:
        import jax

        def write(arrays, slot, new):
            return {k: arrays[k].at[slot].set(new[k]) for k in arrays}

        _STACK_WRITE_JIT = jax.jit(write)
    return _STACK_WRITE_JIT


def _family_key(meta: Dict[str, Any],
                tables: Dict[str, np.ndarray]) -> Tuple:
    """Quantized shape-family key: models padding to the same key share
    one stacked executable. Power-of-two quantization trades a bounded
    amount of padding waste for far fewer families (= fewer compiles,
    denser stacks)."""
    d = max(int(meta["max_depth"]), 1)
    return (
        _pow2(meta["num_trees"]),
        _pow2(meta["max_nodes"]),
        _pow2(meta["max_leaves"]),
        int(meta["num_class"]),
        _pow2(tables["catw"].shape[0]),
        _pow2(tables["leaf_feat"].shape[2]),
        1 << (d - 1).bit_length(),
        bool(meta["has_cat"]),
        bool(meta["linear"]),
    )


class ForestStack:
    """One family's stacked device tables: (S, ...) arrays plus a
    slot -> entry occupancy map. All mutation happens under the owning
    fleet's condition; the arrays themselves are replaced wholesale by
    functional jit writes, so readers of a previous arrays dict are
    never torn."""

    def __init__(self, key: Tuple, slots: int):
        self.key = key
        self.slots = int(slots)
        self.arrays: Optional[Dict[str, Any]] = None
        self.occupant: List[Optional[Any]] = [None] * self.slots
        # one page-in at a time per stack: the functional write reads
        # self.arrays, so two concurrent writers would each start from
        # the same snapshot and the later assignment would silently
        # drop the earlier model. The fleet serializes writers on this
        # flag under its condition (readers are unaffected).
        self.writing = False

    def ensure_arrays(self, template: Dict[str, np.ndarray]) -> None:
        """Allocate the zeroed (S, ...) stack from a padded template's
        shapes (first page-in of the family). Device allocation — call
        OUTSIDE the fleet condition."""
        import jax.numpy as jnp

        if self.arrays is None:
            self.arrays = {
                k: jnp.zeros((self.slots,) + np.asarray(v).shape,
                             jnp.asarray(v).dtype)
                for k, v in template.items()
            }

    def write(self, slot: int, padded: Dict[str, np.ndarray]) -> None:
        """Upload one model into its slot (device work; outside the
        fleet condition). Functional: readers keep the old arrays."""
        import jax.numpy as jnp

        self.ensure_arrays(padded)
        new = {k: jnp.asarray(v) for k, v in padded.items()}
        self.arrays = _stack_write_jit()(
            self.arrays, jnp.int32(slot), new
        )


class _SlotForest:
    """TensorForest-protocol adapter over a fleet residency entry, so
    BucketDispatcher (ladder, chunking, metrics, host fallback) works
    unchanged for fleet tenants. ``apply`` snapshots (stack arrays,
    slot) under the fleet condition and scores outside it; callers
    hold a pin for the duration of the request, so the slot cannot be
    reassigned mid-request."""

    mesh = None
    num_devices = 1

    def __init__(self, fleet: "ModelFleet", entry: "_FleetEntry"):
        self._fleet = fleet
        self._entry = entry
        meta = entry.meta
        self.meta = meta
        self.num_class = meta["num_class"]
        self.num_trees = meta["num_trees"]  # TRUE tree count
        self.average_output = bool(entry.average_output)
        self.max_feature = meta["max_feature"]
        # family-quantized while_loop bound (part of the family key)
        self._depth_bound = entry.family[6]
        self._stack_trees = entry.family[0]

    @property
    def jit_entry(self):
        return _stacked_apply_jit()

    def _tree_weights(self, start_iteration: int,
                      num_iteration: int) -> Tuple[np.ndarray, int, int]:
        K = self.num_class
        n_iters = self.num_trees // K
        end = n_iters if num_iteration <= 0 else min(
            n_iters, start_iteration + num_iteration
        )
        # padded to the stack's tree count: padding trees have zeroed
        # class-onehot rows, so any weight there scores 0 anyway
        tw = np.zeros(self._stack_trees, np.float32)
        tw[start_iteration * K: end * K] = 1.0
        return tw, start_iteration, end

    def _check_width(self, X: np.ndarray) -> None:
        if X.shape[1] <= self.max_feature:
            raise IndexError(
                f"input has {X.shape[1]} features but the model "
                f"references feature {self.max_feature}"
            )

    def apply(self, X, tw):
        import jax.numpy as jnp

        e = self._entry
        with self._fleet._cond:
            if e.state != "ready":
                raise RuntimeError(
                    f"fleet model {e.name!r} v{e.version} applied "
                    "while not resident (missing pin)"
                )
            arrays, slot = e.stack.arrays, e.slot
        fam = e.family
        return _stacked_apply_jit()(
            arrays, jnp.int32(slot), X, jnp.asarray(tw, jnp.float32),
            has_cat=fam[7], linear=fam[8], max_depth=fam[6],
        )

    def apply_contrib(self, X, tw):
        import jax.numpy as jnp

        main, ct, _ = self._fleet._contrib_tables(self._entry)
        from .forest import _contrib_apply_jit

        # contrib runs on the entry's own (unpadded) tables: the tw
        # the dispatcher built is stack-width, the true prefix is ours
        T = self._entry.meta["num_trees"]
        return _contrib_apply_jit()(
            main, ct, X, jnp.asarray(tw[:T], jnp.float32),
            has_cat=self._entry.family[7],
        )


@dataclass
class _FleetEntry:
    """One (name, version): host tables always, a stack slot when hot."""

    name: str
    version: int
    booster: Any
    host_tables: Dict[str, np.ndarray]  # unpadded numpy (the cold copy)
    meta: Dict[str, Any]
    source: str
    family: Tuple
    average_output: bool
    deadline_s: float
    queue_cap: int
    loaded_at: float = field(default_factory=time.time)
    state: str = "cold"  # cold | loading | ready
    stack: Optional[ForestStack] = None
    slot: int = -1
    pins: int = 0
    last_used: float = 0.0
    retired: bool = False
    forest: Any = None          # _SlotForest
    dispatcher: Any = None      # BucketDispatcher
    batcher: Any = None         # lazy MicroBatcher (via_queue)
    ctables: Any = None         # lazy (main_dev, contrib_dev, cmeta)


class ModelFleet:
    """Registry-compatible multi-tenant model store (docs/SERVING.md
    "Fleet serving"): same load / swap / rollback / unload / models /
    stats / predict surface as ModelRegistry, so ScoringServer and the
    HTTP transport work unchanged — but capacity-bounded HBM residency
    instead of a device table set per model."""

    # online-loop attachment points — same duck-typed surface as
    # ModelRegistry (OnlineLoop.attach works against either store)
    ingest_sink = None
    health_probe = None

    def __init__(self, mesh=None, buckets=DEFAULT_BUCKETS,
                 warmup: bool = False, deadline_s: float = 0.0,
                 queue_cap: int = 0, host_fallback: bool = True,
                 capacity: int = 32, slots_per_family: int = 8,
                 page_timeout_s: float = 30.0):
        if mesh is not None:
            log.warning("fleet serving ignores the mesh: stacked "
                        "tables live on one device per stack")
        self.buckets = tuple(int(b) for b in buckets)
        self.default_warmup = bool(warmup)
        self.deadline_s = float(deadline_s)
        self.queue_cap = int(queue_cap)
        self.host_fallback = bool(host_fallback)
        self.capacity = max(int(capacity), 1)
        self.slots_per_family = max(int(slots_per_family), 1)
        self.page_timeout_s = float(page_timeout_s)
        self._cond = threading.Condition()
        self._names: Dict[str, Dict[str, Any]] = {}
        self._stacks: Dict[Tuple, List[ForestStack]] = {}
        self._resident = 0
        self._pages_in = 0
        self._evictions = 0

    # ---------------------------------------------------------- load
    def load(self, name: str, source: Any, *, activate: bool = True,
             warmup: Optional[bool] = None,
             num_features: Optional[int] = None,
             deadline_ms: Optional[float] = None,
             queue_cap: Optional[int] = None) -> int:
        """Register a model version: pack host tables (outside the
        lock — loading must never stall scoring), record QoS, and
        optionally page it in eagerly (``warmup``). ``deadline_ms`` /
        ``queue_cap`` are the tenant's QoS overrides; omitted fields
        inherit the fleet defaults."""
        booster, src = _booster_from(source)
        g = booster._gbdt
        tables, meta = pack_forest_tables(list(g.models), g.num_class)
        fam = _family_key(meta, tables)
        entry = _FleetEntry(
            name=name, version=0, booster=booster,
            host_tables=tables, meta=meta, source=src, family=fam,
            average_output=bool(getattr(g, "average_output", False)),
            deadline_s=(self.deadline_s if deadline_ms is None
                        else float(deadline_ms) / 1000.0),
            queue_cap=(self.queue_cap if queue_cap is None
                       else int(queue_cap)),
        )
        with self._cond:
            rec = self._names.setdefault(
                name, {"versions": [], "active": 0}
            )
            v = (rec["versions"][-1].version + 1) if rec["versions"] \
                else 1
            entry.version = v
            rec["versions"].append(entry)
            if activate or rec["active"] == 0:
                rec["active"] = v
        entry.forest = _SlotForest(self, entry)
        entry.dispatcher = BucketDispatcher(
            entry.forest, self.buckets,
            name=f"fleet:{name}" if v == 1 else f"fleet:{name}:v{v}",
            model=name,
        )
        if self.host_fallback:
            entry.dispatcher.host_fallback = _make_host_fallback(
                booster, entry.forest
            )
        record_registry_event("load", name)
        do_warm = self.default_warmup if warmup is None else warmup
        if do_warm:
            self._acquire(entry)
            self._release(entry)
        log.info(f"fleet: loaded {name!r} v{v} from {src} "
                 f"(family {fam})")
        return v

    # ------------------------------------------------------ residency
    def _find_slot_locked(
        self, family: Tuple
    ) -> Optional[Tuple[ForestStack, int]]:
        """A free slot in the family's stacks, growing a new stack if
        the family has none free (global capacity still applies —
        callers check ``_resident`` first)."""
        stacks = self._stacks.setdefault(family, [])
        for st in stacks:
            for s, occ in enumerate(st.occupant):
                if occ is None:
                    return st, s
        st = ForestStack(family, self.slots_per_family)
        stacks.append(st)
        return st, 0

    def _evict_locked(self, entry: "_FleetEntry", event: str) -> None:
        entry.state = "cold"
        if entry.stack is not None and entry.slot >= 0:
            entry.stack.occupant[entry.slot] = None
        entry.stack, entry.slot = None, -1
        entry.ctables = None  # contrib HBM goes with the slot
        # callers hold self._cond (the _locked suffix contract; the
        # per-function lint cannot see the call sites)
        self._resident -= 1  # lint: allow[unlocked-write]
        self._evictions += 1  # lint: allow[unlocked-write]
        record_fleet_page(entry.name, event)

    def _evict_lru_locked(self) -> bool:
        """Evict the least-recently-used unpinned ready entry; False
        when every resident entry is pinned (caller waits)."""
        victim: Optional[_FleetEntry] = None
        for rec in self._names.values():
            for e in rec["versions"]:
                if e.state == "ready" and e.pins == 0:
                    if victim is None or e.last_used < victim.last_used:
                        victim = e
        if victim is None:
            return False
        self._evict_locked(victim, "evict")
        return True

    def _acquire(self, entry: "_FleetEntry") -> None:
        """Pin ``entry`` resident, paging it in if cold. Blocks while
        another thread is paging it; raises QueueOverflow when the
        fleet's residency is exhausted by pinned models for longer
        than ``page_timeout_s`` (the HTTP transport maps that to 503 —
        overload, not failure)."""
        deadline = time.monotonic() + self.page_timeout_s
        with self._cond:
            while True:
                if entry.retired:
                    raise KeyError(
                        f"model {entry.name!r} v{entry.version} was "
                        "unloaded"
                    )
                if entry.state == "ready":
                    entry.pins += 1
                    entry.last_used = time.monotonic()
                    return
                if entry.state == "loading":
                    self._wait_or_reject_locked(entry, deadline)
                    continue
                # cold: make room, claim a slot, and page in
                if self._resident >= self.capacity:
                    if not self._evict_lru_locked():
                        self._wait_or_reject_locked(entry, deadline)
                        continue
                st, slot = self._find_slot_locked(entry.family)
                if st.writing:
                    # another tenant is paging into this stack — the
                    # functional write must not race it
                    self._wait_or_reject_locked(entry, deadline)
                    continue
                st.writing = True
                st.occupant[slot] = entry
                entry.stack, entry.slot = st, slot
                entry.state = "loading"
                self._resident += 1
                break
        # ---- device work outside the condition ----
        try:
            fault_point("fleet_page")
            padded, _ = pad_forest_tables(
                entry.host_tables, entry.meta,
                num_trees=entry.family[0], max_nodes=entry.family[1],
                max_leaves=entry.family[2], cat_words=entry.family[4],
                lin_feats=entry.family[5],
            )
            entry.stack.write(entry.slot, padded)
            self._warm_slot(entry)
        except Exception:
            with self._cond:
                entry.stack.writing = False
                self._evict_locked(entry, "page_fail")
                self._cond.notify_all()
            raise
        with self._cond:
            entry.stack.writing = False
            entry.state = "ready"
            entry.pins += 1
            entry.last_used = time.monotonic()
            resident = self._resident
            self._pages_in += 1
            self._cond.notify_all()
        record_fleet_page(entry.name, "page_in")
        record_fleet_resident(resident, self.capacity)

    def _wait_or_reject_locked(self, entry: "_FleetEntry",
                               deadline: float) -> None:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            record_serve_rejection(f"fleet:{entry.name}", "overloaded")
            raise QueueOverflow(
                "fleet residency exhausted: "
                f"{self._resident}/{self.capacity} resident, all "
                "pinned"
            )
        self._cond.wait(min(remaining, 0.1))

    def _warm_slot(self, entry: "_FleetEntry") -> None:
        """Smallest-bucket warm-up after a page-in: first page-in of a
        family compiles the shared executable; later ones just touch
        the slot so the first real request is pure scoring."""
        import jax.numpy as jnp

        F = max(entry.meta["max_feature"] + 1, 1)
        tw = np.ones(entry.family[0], np.float32)
        fam = entry.family
        score, _ = _stacked_apply_jit()(
            entry.stack.arrays, jnp.int32(entry.slot),
            jnp.zeros((self.buckets[0], F), jnp.float32),
            jnp.asarray(tw),
            has_cat=fam[7], linear=fam[8], max_depth=fam[6],
        )
        score.block_until_ready()
        record_fleet_page(entry.name, "warmup")

    def _release(self, entry: "_FleetEntry") -> None:
        with self._cond:
            entry.pins -= 1
            if entry.retired and entry.pins == 0 \
                    and entry.state == "ready":
                # unload arrived while this request was in flight
                self._evict_locked(entry, "evict")
            self._cond.notify_all()

    def _contrib_tables(self, entry: "_FleetEntry"):
        """Lazy device TreeSHAP tables for one tenant: the entry's own
        unpadded main tables plus the packed contrib tables. Dropped
        on eviction; a later explanation request re-packs."""
        with self._cond:
            if entry.ctables is not None:
                return entry.ctables
        import jax.numpy as jnp

        g = entry.booster._gbdt
        ct, cmeta = pack_contrib_tables(
            list(g.models), entry.meta["num_class"]
        )
        main = {k: jnp.asarray(v) for k, v in entry.host_tables.items()}
        ctd = {k: jnp.asarray(v) for k, v in ct.items()}
        with self._cond:
            # two racing packers both built valid tables; keep one
            if entry.ctables is None:
                entry.ctables = (main, ctd, cmeta)
            return entry.ctables

    # ------------------------------------------------------- registry
    def _entry_locked(self, name: str,
                      version: Optional[int] = None) -> "_FleetEntry":
        if name not in self._names:
            raise KeyError(f"unknown model {name!r}")
        rec = self._names[name]
        v = rec["active"] if version is None else int(version)
        for e in rec["versions"]:
            if e.version == v:
                return e
        raise KeyError(f"model {name!r} has no version {v}")

    def swap(self, name: str, version: int) -> None:
        with self._cond:
            e = self._entry_locked(name, version)
            self._names[name]["active"] = e.version
        record_registry_event("swap", name)

    def rollback(self, name: str) -> int:
        with self._cond:
            if name not in self._names:
                raise KeyError(f"unknown model {name!r}")
            rec = self._names[name]
            cur = rec["active"]
            older = [e.version for e in rec["versions"]
                     if e.version < cur]
            if not older:
                raise KeyError(
                    f"model {name!r} has no version below {cur}"
                )
            rec["active"] = max(older)
            active = rec["active"]
        record_registry_event("rollback", name)
        return active

    def unload(self, name: str,
               version: Optional[int] = None) -> None:
        dropped: List[_FleetEntry] = []
        with self._cond:
            if version is None:
                rec = self._names.pop(name, None)
                if rec:
                    dropped = rec["versions"]
            else:
                rec = self._names.get(name)
                if rec is None:
                    return
                if rec["active"] == int(version):
                    raise ValueError(
                        f"version {version} of {name!r} is active; "
                        "swap first or unload the whole name"
                    )
                kept = []
                for e in rec["versions"]:
                    (kept if e.version != int(version)
                     else dropped).append(e)
                rec["versions"] = kept
            for e in dropped:
                e.retired = True
                if e.state == "ready" and e.pins == 0:
                    self._evict_locked(e, "evict")
                # pinned entries evict in _release when the last
                # in-flight request lands
            self._cond.notify_all()
        for e in dropped:  # outside the lock: close() joins workers
            if e.batcher is not None:
                e.batcher.close()
        if dropped:
            record_registry_event("unload", name)

    def models(self) -> Dict[str, Dict[str, Any]]:
        with self._cond:
            return {
                name: {
                    "active": rec["active"],
                    "versions": [
                        {"version": e.version, "source": e.source,
                         "num_trees": e.meta["num_trees"],
                         "num_class": e.meta["num_class"],
                         "loaded_at": e.loaded_at,
                         "resident": e.state == "ready"}
                        for e in rec["versions"]
                    ],
                }
                for name, rec in self._names.items()
            }

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {
                name: self._entry_locked(name).dispatcher.stats()
                for name in self._names
            }

    def fleet_stats(self) -> Dict[str, Any]:
        with self._cond:
            families = {
                str(k): sum(
                    1 for st in v for o in st.occupant if o is not None
                )
                for k, v in self._stacks.items()
            }
            return {
                "resident": self._resident,
                "capacity": self.capacity,
                "models": len(self._names),
                "pages_in": self._pages_in,
                "evictions": self._evictions,
                "families": families,
            }

    def close(self) -> None:
        """Fail-safe shutdown: close every tenant's batcher."""
        with self._cond:
            entries = [e for rec in self._names.values()
                       for e in rec["versions"]]
        for e in entries:
            if e.batcher is not None:
                e.batcher.close()

    # -------------------------------------------------------- predict
    def predict(self, name: str, X, *, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False,
                via_queue: bool = False,
                version: Optional[int] = None,
                deadline_s: Optional[float] = None) -> np.ndarray:
        """ModelRegistry.predict semantics over the fleet: resolve the
        active version, pin it resident for the whole request (paging
        it in if cold), score through its dispatcher, release. The pin
        spans submit AND result for queued requests, so every request
        coalesced into a device call holds its model in place."""
        with self._cond:
            entry = self._entry_locked(name, version)
        self._acquire(entry)
        try:
            if pred_leaf:
                return entry.dispatcher.predict_leaf(
                    X, start_iteration, num_iteration
                )
            if pred_contrib:
                return entry.dispatcher.predict_contrib(
                    X, start_iteration, num_iteration
                )
            batcher = None
            if via_queue and start_iteration == 0 \
                    and num_iteration == -1:
                with self._cond:
                    if not entry.retired:
                        if entry.batcher is None:
                            from .dispatch import MicroBatcher

                            entry.batcher = MicroBatcher(
                                entry.dispatcher,
                                deadline_s=entry.deadline_s,
                                queue_cap=entry.queue_cap,
                            )
                        batcher = entry.batcher
            if batcher is not None:
                raw = batcher.submit(
                    X, deadline_s=deadline_s
                ).result().T
            else:
                raw = entry.dispatcher.score_raw(
                    X, start_iteration, num_iteration
                )
            g = entry.booster._gbdt
            if not raw_score and g.objective is not None:
                raw = g.objective.convert_output(raw)
            K = entry.meta["num_class"]
            return raw[0] if K == 1 else raw.T
        finally:
            self._release(entry)
