"""Jaxpr invariant auditor: machine-checkable contracts on hot paths.

Abstractly traces the fused round kernel, the data-parallel grower and
the quantized reduce-scatter wire (no data, no compile — jaxpr
construction only, a couple of seconds on CPU) and asserts contracts
that every perf/correctness regression so far would have tripped:

- the quantized wire: `reduce_scatter` present, every wire operand
  exactly `QUANT_WIRE_DTYPE` (int16 — the narrowest exact payload,
  histogram.rs_wire_dtype; a second entry pins the int32 step-down
  when the int16 bound trips);
- the overflow gate (ADVICE r5, histogram.rs_exact_ok): past the
  2^31 global / 2^24 per-shard exactness bounds the wire must VANISH
  and the f32 psum fallback take over;
- no host callbacks (`pure_callback`/`io_callback`/...) inside device
  loops — a silent ~100 ms sync per iteration on the axon runtime;
- no float64 anywhere (dtype widening guard — the package is f32/
  int32 end to end);
- flattened jaxpr size stays under a checked-in budget
  (`jaxpr_budget.json`) — the executable-bloat guard (a 152 MB
  jit_step once shipped because a bin matrix became a constant).

Also hosts the `_OBJ_FOLD_ATTRS` exhaustiveness audit (ADVICE r5
item 3): a static scan proving no objective class stores a device
array outside the fused step's rebind list.

Importing this module imports jax; run on CPU with
`--xla_force_host_platform_device_count=8` (the CLI sets this up).
"""

from __future__ import annotations

import json
import math
from collections import Counter
from pathlib import Path
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence

_BUDGET_PATH = Path(__file__).with_name("jaxpr_budget.json")
# a fresh entry's budget = ceil(current size * this headroom)
_BUDGET_HEADROOM = 1.25

_CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback", "outside_call",
}


class JaxprSummary(NamedTuple):
    prim_counts: Dict[str, int]
    eqn_count: int
    dtypes: frozenset
    # operand dtype of every reduce_scatter eqn (the collective wire)
    wire_dtypes: tuple


class Contract(NamedTuple):
    name: str
    ok: bool
    detail: str


class AuditResult(NamedTuple):
    name: str
    ok: bool
    contracts: List[Contract]
    eqn_count: int

    def format(self) -> str:
        head = "PASS" if self.ok else "FAIL"
        size = f" ({self.eqn_count} eqns)" if self.eqn_count else ""
        lines = [f"[{head}] {self.name}{size}"]
        for c in self.contracts:
            mark = "ok " if c.ok else "XX "
            lines.append(f"    {mark}{c.name}: {c.detail}")
        return "\n".join(lines)


def _core_modules():
    """jax core module candidates across versions: jax.core on 0.4.x,
    jax.extend.core where the old aliases were removed."""
    import jax

    return [
        mod for mod in (getattr(jax, "core", None),
                        getattr(getattr(jax, "extend", None), "core", None))
        if mod is not None
    ]


def _jaxpr_types():
    """(ClosedJaxpr, Jaxpr) across jax versions."""
    for mod in _core_modules():
        if hasattr(mod, "ClosedJaxpr"):
            return mod.ClosedJaxpr, mod.Jaxpr
    raise RuntimeError("cannot locate jax ClosedJaxpr/Jaxpr types")


def iter_eqns(closed):
    """Every equation of a ClosedJaxpr, recursing into call/
    control-flow/pallas sub-jaxprs discovered through eqn params. The
    ONE flattening walker — summarize() here and cost_audit's wire
    accounting both consume it, so sub-jaxpr discovery cannot drift
    between the structural and the byte-accounting views."""
    ClosedJaxpr, Jaxpr = _jaxpr_types()
    stack = [closed.jaxpr]
    while stack:
        jaxpr = stack.pop()
        for eqn in jaxpr.eqns:
            yield eqn
            for p in eqn.params.values():
                for sub in (p if isinstance(p, (list, tuple)) else [p]):
                    if isinstance(sub, ClosedJaxpr):
                        stack.append(sub.jaxpr)
                    elif isinstance(sub, Jaxpr):
                        stack.append(sub)


def summarize(closed) -> JaxprSummary:
    """Flatten a ClosedJaxpr into the primitive/dtype statistics the
    contracts read."""
    prims: Counter = Counter()
    dtypes: set = set()
    wire: List[str] = []
    for eqn in iter_eqns(closed):
        prims[eqn.primitive.name] += 1
        for v in list(eqn.invars) + list(eqn.outvars):
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None:
                dtypes.add(str(dt))
        if eqn.primitive.name == "reduce_scatter":
            wire.append(str(eqn.invars[0].aval.dtype))
    return JaxprSummary(
        dict(prims), sum(prims.values()), frozenset(dtypes), tuple(wire)
    )


# ---------------------------------------------------------------- contracts
ContractFn = Callable[[JaxprSummary], Contract]


def has_prim(name: str, why: str = "") -> ContractFn:
    def check(s: JaxprSummary) -> Contract:
        n = s.prim_counts.get(name, 0)
        return Contract(
            f"has_{name}", n > 0,
            f"{n} {name} eqn(s)" + (f" — {why}" if why else ""),
        )
    return check


def lacks_prim(name: str, why: str = "") -> ContractFn:
    def check(s: JaxprSummary) -> Contract:
        n = s.prim_counts.get(name, 0)
        return Contract(
            f"no_{name}", n == 0,
            (f"absent" if n == 0 else f"{n} present")
            + (f" — {why}" if why else ""),
        )
    return check


def wire_dtype(dtype: str) -> ContractFn:
    """Every reduce_scatter operand has exactly this dtype: the
    quantized histogram wire must never widen (f32/f64 would double the
    ICI/DCN payload) NOR silently narrow without the budget flip. The
    expected dtype is `QUANT_WIRE_DTYPE` below — ROADMAP 3a's int16
    wire lands by flipping that one constant and refreshing the
    wire-bytes budget (cost_audit.py)."""
    def check(s: JaxprSummary) -> Contract:
        bad = [d for d in s.wire_dtypes if d != dtype]
        return Contract(
            f"wire_{dtype}", not bad,
            f"wire dtypes {list(s.wire_dtypes)}"
            + (f" — expected {dtype}, got: {bad}" if bad else ""),
        )
    return check


def no_host_callbacks() -> ContractFn:
    def check(s: JaxprSummary) -> Contract:
        found = {
            k: v for k, v in s.prim_counts.items() if k in _CALLBACK_PRIMS
        }
        return Contract(
            "no_host_callbacks", not found,
            "none" if not found else f"host callbacks in trace: {found}",
        )
    return check


def no_f64() -> ContractFn:
    def check(s: JaxprSummary) -> Contract:
        bad = sorted(d for d in s.dtypes if "64" in d and d != "int64")
        return Contract(
            "no_f64", not bad,
            "f32/int32 end to end" if not bad else f"widened dtypes: {bad}",
        )
    return check


def within_budget(budget: Optional[int]) -> ContractFn:
    def check(s: JaxprSummary) -> Contract:
        if budget is None:
            return Contract(
                "eqn_budget", False,
                f"{s.eqn_count} eqns but no checked-in budget — run "
                "`python -m lightgbm_tpu.analysis --update-budget`",
            )
        return Contract(
            "eqn_budget", s.eqn_count <= budget,
            f"{s.eqn_count} eqns <= budget {budget}"
            if s.eqn_count <= budget
            else f"{s.eqn_count} eqns EXCEEDS budget {budget} "
            "(executable bloat — did a constant get baked in, or a "
            "loop unroll?)",
        )
    return check


def audit_jaxpr(closed, contracts: Sequence[ContractFn],
                name: str = "adhoc") -> AuditResult:
    """Run contracts against an already-built ClosedJaxpr (tests use
    this to prove each contract red-to-green on broken fixtures)."""
    s = summarize(closed)
    results = [c(s) for c in contracts]
    return AuditResult(
        name, all(c.ok for c in results), results, s.eqn_count
    )


# ---------------------------------------------------------------- entries
# the forced host platform every audit mesh is carved from (the ONE
# place the XLA_FLAGS bootstrap size is declared — __main__ and
# tests/conftest.py both force this count before jax initializes)
HOST_DEVICE_COUNT = 8


def _mesh(n: int = HOST_DEVICE_COUNT, axis_name: str = "data"):
    """1-D audit mesh over the first `n` of the forced 8 host CPU
    devices — sub-meshes are how scale_audit re-traces every
    mesh-bearing entry at the D ∈ {1, 2, 4, 8} ladder without touching
    the backend bootstrap. Loud error below n devices: a silently
    smaller mesh would re-pin every scaling budget at the wrong D."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"jaxpr audit needs a {n}-device mesh but the backend has "
            f"{len(devs)}; run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={HOST_DEVICE_COUNT} "
            "(python -m lightgbm_tpu.analysis and tests/conftest.py "
            "both set this up)"
        )
    return Mesh(np.asarray(devs[:n]), (axis_name,))


def _trace_rounds_dp(quant: bool, levels: int, local_rows: int,
                     voting_k: int = 0,
                     n_devices: int = HOST_DEVICE_COUNT):
    """Abstract shard_map trace of the rounds grower over the data
    mesh — the exact wiring DataParallelGrower builds (shapes only; no
    arrays exist, so `local_rows` can model pod scale for free).
    voting_k>0 turns on the per-round GlobalVoting election
    (tree_learner=voting): only the elected columns cross the mesh.
    `n_devices` carves a sub-mesh of the forced host platform; LOCAL
    rows are held fixed so global rows scale with the mesh — the
    weak-scaling axis the scale auditor's wire laws are written
    against."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..config import Config
    from ..learner.grower import GrowerSpec, make_split_params
    from ..learner.rounds import grow_tree_rounds
    from ..parallel.data_parallel import (
        _tree_arrays_structure,
        shard_map_compat,
    )

    mesh = _mesh(n_devices)
    n = int(mesh.devices.size)
    L, B, G = 31, 64, 8
    N = local_rows * n
    spec = GrowerSpec(
        num_leaves=L, num_bins=B, max_depth=-1, axis_name="data",
        axis_size=n, rounds_slots=8, quant=quant,
        quant_levels=levels if quant else 0, has_cat=False,
        voting_k=voting_k,
    )
    params = make_split_params(Config({}))
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731

    def fn(bins_fm, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
           feat_mask, params, gh_scale):
        return grow_tree_rounds(
            bins_fm, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
            feat_mask, params, spec,
            gh_scale=gh_scale if quant else None,
        )

    row, rep = P("data"), P()
    sm = shard_map_compat(
        fn, mesh=mesh,
        in_specs=(P(None, "data"), rep, rep, rep, rep, row, row, row,
                  rep, rep, rep),
        out_specs=(
            jax.tree.map(lambda _: rep, _tree_arrays_structure(spec)),
            row,
        ),
        check_vma=False,
    )
    return jax.make_jaxpr(sm)(
        mk((G, N), jnp.int32), mk((G,), jnp.int32), mk((G,), jnp.int32),
        mk((G,), jnp.int32), mk((G,), jnp.bool_), mk((N,), jnp.float32),
        mk((N,), jnp.float32), mk((N,), jnp.float32), mk((G,), jnp.bool_),
        params, mk((2,), jnp.float32),
    )


def _trace_rounds_serial():
    import jax
    import jax.numpy as jnp

    from ..config import Config
    from ..learner.grower import GrowerSpec, make_split_params
    from ..learner.rounds import grow_tree_rounds

    L, B, G, N = 31, 64, 8, 4096
    spec = GrowerSpec(num_leaves=L, num_bins=B, max_depth=-1,
                      rounds_slots=8, has_cat=False)
    params = make_split_params(Config({}))
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    return jax.make_jaxpr(
        lambda b, nb, numb, mono, cat, g, h, m, fm, p: grow_tree_rounds(
            b, nb, numb, mono, cat, g, h, m, fm, p, spec
        )
    )(
        mk((G, N), jnp.int32), mk((G,), jnp.int32), mk((G,), jnp.int32),
        mk((G,), jnp.int32), mk((G,), jnp.bool_), mk((N,), jnp.float32),
        mk((N,), jnp.float32), mk((N,), jnp.float32), mk((G,), jnp.bool_),
        params,
    )


def _trace_rounds_serial_packed():
    """The int-packed DEFAULT training path (ISSUE 12 tentpole):
    serial rounds grower with quant=True / 256 internal levels and a
    gh_scale input — exactly what boosting._grow_int_packed builds when
    tpu_hist_dtype resolves to int16. 3 histogram channels instead of
    bf16x2's 5; cost_audit pins the bytes-accessed DROP vs
    rounds_serial."""
    import jax
    import jax.numpy as jnp

    from ..config import Config
    from ..learner.grower import GrowerSpec, make_split_params
    from ..learner.rounds import grow_tree_rounds

    L, B, G, N = 31, 64, 8, 4096
    spec = GrowerSpec(num_leaves=L, num_bins=B, max_depth=-1,
                      rounds_slots=8, quant=True, quant_levels=256,
                      has_cat=False)
    params = make_split_params(Config({}))
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    return jax.make_jaxpr(
        lambda b, nb, numb, mono, cat, g, h, m, fm, p, sc:
        grow_tree_rounds(
            b, nb, numb, mono, cat, g, h, m, fm, p, spec, gh_scale=sc
        )
    )(
        mk((G, N), jnp.int32), mk((G,), jnp.int32), mk((G,), jnp.int32),
        mk((G,), jnp.int32), mk((G,), jnp.bool_), mk((N,), jnp.float32),
        mk((N,), jnp.float32), mk((N,), jnp.float32), mk((G,), jnp.bool_),
        params, mk((2,), jnp.float32),
    )


def _trace_hist_round(quant: bool = True):
    """The fused partition+histogram pallas kernel (_round_kernel) —
    traced abstractly; pallas_call jaxpr construction is platform-free
    even though compilation needs a TPU. quant=True is the 3-channel
    int-packed layout, quant=False the 5-channel bf16x2 hi/lo split —
    cost_audit pins the bytes-accessed DROP between the pair."""
    import jax
    import jax.numpy as jnp

    from ..learner.histogram import HIST_BLK, hist_round

    S, G, B, N = 8, 8, 64, HIST_BLK * 2
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    return jax.make_jaxpr(
        lambda b, g, p, prm, coh: hist_round(
            b, g, p, prm, coh, S, B, quant=quant
        )
    )(
        mk((G, N), jnp.int32), mk((8, N), jnp.float32), mk((N,), jnp.int32),
        mk((S, 16), jnp.int32), mk((S, G), jnp.float32),
    )


def _trace_serving_forest():
    """Abstract trace of the serving predictor (serving/forest.py
    forest_apply) — the scoring entry point's jaxpr from shapes alone:
    8 trees x 31 nodes, categorical path on, 256 rows x 16 features."""
    import jax
    import jax.numpy as jnp

    from ..serving.forest import forest_apply

    T, M, L, W, Ck, K, N, F = 8, 31, 32, 4, 1, 1, 256, 16
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    tables = {
        "pack": mk((9, T * M), jnp.float32),
        "catw": mk((W,), jnp.int32),
        "leaf_value": mk((T, L), jnp.float32),
        "leaf_const": mk((T, L), jnp.float32),
        "leaf_nf": mk((T, L), jnp.int32),
        "leaf_feat": mk((T, L, Ck), jnp.int32),
        "leaf_coeff": mk((T, L, Ck), jnp.float32),
        "init_node": mk((T,), jnp.int32),
        "class_onehot": mk((T, K), jnp.float32),
    }
    return jax.make_jaxpr(
        lambda t, X, w: forest_apply(t, X, w, has_cat=True, linear=False)
    )(tables, mk((N, F), jnp.float32), mk((T,), jnp.float32))


def _forest_table_shapes(T, M, L, W, Ck, K):
    import jax
    import jax.numpy as jnp

    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    return {
        "pack": mk((9, T * M), jnp.float32),
        "catw": mk((W,), jnp.int32),
        "leaf_value": mk((T, L), jnp.float32),
        "leaf_const": mk((T, L), jnp.float32),
        "leaf_nf": mk((T, L), jnp.int32),
        "leaf_feat": mk((T, L, Ck), jnp.int32),
        "leaf_coeff": mk((T, L, Ck), jnp.float32),
        "init_node": mk((T,), jnp.int32),
        "class_onehot": mk((T, K), jnp.float32),
    }


def _trace_serving_stack():
    """Abstract trace of the fleet's stacked predictor
    (serving/forest.py stacked_forest_apply): 4 resident slots of the
    serving_forest family, the slot a traced scalar — the executable
    every tenant of a shape family shares."""
    import jax
    import jax.numpy as jnp

    from ..serving.forest import stacked_forest_apply

    S, T, M, L, W, Ck, K, N, F = 4, 8, 31, 32, 4, 1, 1, 256, 16
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    tables = _forest_table_shapes(T, M, L, W, Ck, K)
    stack = {
        k: jax.ShapeDtypeStruct((S,) + v.shape, v.dtype)
        for k, v in tables.items()
    }
    return jax.make_jaxpr(
        lambda st, s, X, w: stacked_forest_apply(
            st, s, X, w, has_cat=True, linear=False
        )
    )(stack, mk((), jnp.int32), mk((N, F), jnp.float32),
      mk((T,), jnp.float32))


def _trace_serving_contrib():
    """Abstract trace of the device TreeSHAP entry (serving/forest.py
    contrib_apply): 8 trees x 15 nodes, path dims quantized to 8 edges
    / 4 unique features, 64 rows x 16 features."""
    import jax
    import jax.numpy as jnp

    from ..serving.forest import contrib_apply

    T, M, L, W, Ck, K, N, F = 8, 15, 16, 4, 1, 1, 64, 16
    E, P = 8, 4
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    tables = _forest_table_shapes(T, M, L, W, Ck, K)
    ctables = {
        "nodes": mk((T, L, E), jnp.int32),
        "dirs": mk((T, L, E), jnp.float32),
        "slot_oh": mk((T, L, E, P), jnp.float32),
        "zero": mk((T, L, P), jnp.float32),
        "feat": mk((T, L, P), jnp.int32),
        "expect": mk((T,), jnp.float32),
        "tree_class": mk((T,), jnp.int32),
    }
    return jax.make_jaxpr(
        lambda t, c, X, w: contrib_apply(t, c, X, w, has_cat=True)
    )(tables, ctables, mk((N, F), jnp.float32), mk((T,), jnp.float32))


def _trace_feature_parallel(n_devices: int = HOST_DEVICE_COUNT):
    """Abstract shard_map trace of the feature-parallel flat grower
    over a ("feature",) mesh — the exact wiring FeatureParallelGrower
    builds (parallel/feature_parallel.py): rows replicated, the bin
    matrix and per-feature tables sharded on the feature axis, split
    records all-gathered (SyncUpGlobalBestSplit) and the winning
    shard's per-row decision broadcast with one psum. 16 features pad
    evenly onto every rung of the D ladder."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..config import Config
    from ..learner.grower import GrowerSpec, grow_tree, make_split_params
    from ..parallel.data_parallel import (
        _tree_arrays_structure,
        shard_map_compat,
    )

    mesh = _mesh(n_devices, axis_name="feature")
    L, B, F, N = 15, 64, 16, 512
    spec = GrowerSpec(num_leaves=L, num_bins=B, max_depth=-1,
                      partition="flat", feature_axis="feature",
                      rounds_slots=0, has_cat=False)
    params = make_split_params(Config({}))
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731

    def fn(bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
           feat_mask, params, valid):
        tree, row_leaf = grow_tree(
            bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
            feat_mask, params, spec, valid=valid,
        )
        tree = jax.tree.map(
            lambda a: jax.lax.pmean(a, "feature")
            if jnp.issubdtype(a.dtype, jnp.floating) else a,
            tree,
        )
        return tree, row_leaf

    fshard, rep = P("feature"), P()
    sm = shard_map_compat(
        fn, mesh=mesh,
        in_specs=(P("feature", None), fshard, fshard, fshard, fshard,
                  rep, rep, rep, fshard, rep, rep),
        out_specs=(
            jax.tree.map(lambda _: rep, _tree_arrays_structure(spec)),
            rep,
        ),
        check_vma=False,
    )
    return jax.make_jaxpr(sm)(
        mk((F, N), jnp.int32), mk((F,), jnp.int32), mk((F,), jnp.int32),
        mk((F,), jnp.int32), mk((F,), jnp.bool_), mk((N,), jnp.float32),
        mk((N,), jnp.float32), mk((N,), jnp.float32), mk((F,), jnp.bool_),
        params, mk((N,), jnp.float32),
    )


def _trace_online_holdout():
    """Online promotion gate holdout evaluator (online/gate.py):
    auc + binary_logloss DeviceEvalSet over a 256-row shard with
    deterministic arange-parity labels — the gate's verdict arithmetic
    as one traced fn(score)->(m,)."""
    from ..online.gate import trace_holdout_eval

    return trace_holdout_eval(n=256, num_class=1)


class _Entry(NamedTuple):
    builder: Callable[[], Any]
    contracts: Callable[[Optional[int]], List[ContractFn]]
    doc: str
    # expected collective wire payload dtype (None: entry has no
    # quantized histogram wire). The one-line flip for ROADMAP 3a.
    wire_dtype: Optional[str] = None
    # entry contains pallas kernels: the cost auditor must trace it
    # under the pallas interpreter to compile on the CPU backend
    pallas_interpret: bool = False
    # mesh-bearing entries: builder parameterized by device count, so
    # scale_audit (Pass 7) can re-trace the same wiring at the
    # D ∈ {1, 2, 4, 8} ladder. `builder` stays the full-mesh (D=8)
    # trace every other pass reads; build_entry shares the memo.
    mesh_builder: Optional[Callable[[int], Any]] = None


# the quantized data-parallel histogram wire dtype (reference halves
# socket bytes with int16/int32 packing, include/LightGBM/bin.h:63-81;
# ROADMAP 3a landed: histogram.rs_wire_dtype picks the NARROWEST exact
# payload — int16 while the mesh-wide hessian worst case stays under
# 2^15, int32 up to the 2^31/2^24 bounds, f32 psum past those. The
# wire-bytes halving is pinned by cost_audit's exact wire budget.)
QUANT_WIRE_DTYPE = "int16"

# levels=16, 128 local rows: 128*8*16 = 16384 < 2^15 — the int16 wire
# must engage (256 local rows would hit exactly 2^15 and step down)
_RS_OK = dict(quant=True, levels=16, local_rows=128)
# levels=16, 2048 local rows: 2048*8*16 = 262k >= 2^15 but < 2^31 and
# 2048*16 = 32k < 2^24 — the wire steps down to int32, not psum
_RS_INT32 = dict(quant=True, levels=16, local_rows=2048)
# levels=256, 131072 local rows: 131072*256 = 33.5M > 2^24 — the
# per-shard exactness bound trips and the wire must fall back to psum
_RS_OVERFLOW = dict(quant=True, levels=256, local_rows=131072)

# chunk length traced for the fused_chunk_scan entry, and the second
# length the C-invariance audit compares against. Both must be real
# config.DEFAULT_CHUNK_LADDER rungs so the audited executables are the
# ones training actually dispatches.
_CHUNK_SCAN_C = 4
_CHUNK_SCAN_C_ALT = 16


def _trace_chunk_scan(length: int = _CHUNK_SCAN_C):
    """One C-round fused chunk dispatch (boosting.trace_fused_chunk):
    the whole boosting inner loop — gradients, growth, score updates,
    device metrics — scanned on device. The mega-entry of ROADMAP item
    2; budgets must NOT scale with C (scan body counted once)."""
    from ..boosting import trace_fused_chunk

    return trace_fused_chunk(length)


def _trace_streamed_construct():
    """The per-chunk device step of the out-of-core construct
    (data/prefetch.py chunk_update_step): dynamic_update_slice of one
    (G, chunk_rows) int32 chunk into the (G, Np) resident bin matrix
    at a traced row offset. Everything else on that path (spool reads,
    crc checks, binning, padding) is host work on the reader thread —
    this is the entire device-side surface, so it must stay
    callback-free and f64-free."""
    import jax
    import jax.numpy as jnp

    from ..data.prefetch import chunk_update_step

    G, NP, CR = 8, 8192, 2048
    mk = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
    return jax.make_jaxpr(chunk_update_step)(
        mk((G, NP), jnp.int32), mk((G, CR), jnp.int32),
        mk((), jnp.int32),
    )


ENTRIES: Dict[str, _Entry] = {
    "fused_chunk_scan": _Entry(
        _trace_chunk_scan,
        lambda budget: [
            has_prim("scan",
                     "the C-round boosting loop is device control flow"),
            no_host_callbacks(),
            no_f64(),
            lacks_prim("reduce_scatter",
                       "single device; the chunk carries no mesh wire"),
            within_budget(budget),
        ],
        "chunk-scan fused boosting dispatch (boosting.fused_dispatch): "
        f"{_CHUNK_SCAN_C} rounds of gradients+growth+score+metrics as "
        "one lax.scan — the host-evicted inner loop, held to the same "
        "callback/f64/budget contracts as every other entry",
    ),
    "rounds_quant_rs": _Entry(
        lambda: _trace_rounds_dp(**_RS_OK),
        lambda budget: [
            has_prim("reduce_scatter",
                     "the quantized histogram wire (bin.h:63-81)"),
            wire_dtype(QUANT_WIRE_DTYPE),
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "quantized data-parallel grower inside the exactness bounds: "
        f"{QUANT_WIRE_DTYPE} reduce-scatter wire end to end",
        wire_dtype=QUANT_WIRE_DTYPE,
        mesh_builder=lambda d: _trace_rounds_dp(**_RS_OK, n_devices=d),
    ),
    "rounds_quant_rs_int32": _Entry(
        lambda: _trace_rounds_dp(**_RS_INT32),
        lambda budget: [
            has_prim("reduce_scatter",
                     "the wire survives past the int16 bound"),
            wire_dtype("int32"),
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "quantized grower past the int16 bound but inside int32 "
        "exactness: wire steps down to int32, not psum",
        wire_dtype="int32",
        mesh_builder=lambda d: _trace_rounds_dp(**_RS_INT32, n_devices=d),
    ),
    "rounds_quant_rs_overflow": _Entry(
        lambda: _trace_rounds_dp(**_RS_OVERFLOW),
        lambda budget: [
            lacks_prim("reduce_scatter",
                       "past 2^24 per-shard the int32 wire would be "
                       "inexact; rs_exact_ok must disable it"),
            has_prim("psum", "the f32 fallback wire"),
            no_host_callbacks(),
        ],
        "quantized grower past the exactness bound: overflow gate "
        "engaged, f32 psum fallback",
        mesh_builder=lambda d: _trace_rounds_dp(**_RS_OVERFLOW,
                                                n_devices=d),
    ),
    "rounds_voting": _Entry(
        lambda: _trace_rounds_dp(**_RS_OK, voting_k=2),
        lambda budget: [
            has_prim("psum",
                     "vote tally + elected-column payload cross the "
                     "mesh (rounds.vote_reduce)"),
            lacks_prim("reduce_scatter",
                       "voting replaces the full-width owned-block "
                       "wire; the elected ~2k columns ride psum"),
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "voting-parallel rounds grower (tree_learner=voting): per-round "
        "top-k election, only the elected bundle columns cross the mesh "
        "— int16 payload while the quantized sums provably fit; "
        "cost_audit pins the wire-bytes DROP vs rounds_quant_rs",
        mesh_builder=lambda d: _trace_rounds_dp(**_RS_OK, voting_k=2,
                                                n_devices=d),
    ),
    "feature_parallel": _Entry(
        _trace_feature_parallel,
        lambda budget: [
            has_prim("all_gather",
                     "SyncUpGlobalBestSplit: per-rank best records "
                     "gathered, winner picked identically everywhere"),
            has_prim("psum",
                     "the winning shard broadcasts its per-row split "
                     "decision (one bit-vector per split)"),
            lacks_prim("reduce_scatter",
                       "feature-parallel moves NO histograms — only "
                       "split records and one row bit-vector"),
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "feature-parallel flat grower (tree_learner=feature, "
        "parallel_tree_learner.h:26): rows replicated, features "
        "sharded, record-only wire — the second mesh axis ROADMAP 5's "
        "2D rows x features sharding composes from",
        mesh_builder=_trace_feature_parallel,
    ),
    "rounds_serial": _Entry(
        _trace_rounds_serial,
        lambda budget: [
            no_host_callbacks(),
            no_f64(),
            lacks_prim("reduce_scatter", "no mesh, no collective"),
            within_budget(budget),
        ],
        "single-device rounds grower: pure device loop",
    ),
    "rounds_serial_packed": _Entry(
        _trace_rounds_serial_packed,
        lambda budget: [
            no_host_callbacks(),
            no_f64(),
            lacks_prim("reduce_scatter", "no mesh, no collective"),
            within_budget(budget),
        ],
        "int-packed default path (tpu_hist_dtype=int16): 3-channel "
        "integer histograms + scale recovery, single device",
    ),
    "hist_round_fused": _Entry(
        _trace_hist_round,
        lambda budget: [
            has_prim("pallas_call", "the fused _round_kernel"),
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "fused partition+histogram kernel (pallas_hist._round_kernel), "
        "3-channel int-packed layout",
        pallas_interpret=True,
    ),
    "hist_round_fused_bf16": _Entry(
        lambda: _trace_hist_round(quant=False),
        lambda budget: [
            has_prim("pallas_call", "the fused _round_kernel"),
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "fused round kernel, 5-channel bf16x2 hi/lo layout — the "
        "baseline the int-packed pair must undercut",
        pallas_interpret=True,
    ),
    "serving_forest": _Entry(
        _trace_serving_forest,
        lambda budget: [
            no_host_callbacks(),
            no_f64(),
            has_prim("while", "depth-stepped lockstep traversal"),
            within_budget(budget),
        ],
        "serving predictor (serving/forest.py): f32/int32 scoring "
        "jaxpr, no callbacks, bounded size",
    ),
    "serving_fleet_stack": _Entry(
        _trace_serving_stack,
        lambda budget: [
            no_host_callbacks(),
            no_f64(),
            has_prim("while", "depth-stepped lockstep traversal"),
            within_budget(budget),
        ],
        "fleet stacked predictor (serving/forest.py "
        "stacked_forest_apply): slot-indexed scoring over (S, ...) "
        "stacked tables, the executable a shape family shares",
    ),
    "serving_contrib": _Entry(
        _trace_serving_contrib,
        lambda budget: [
            no_host_callbacks(),
            no_f64(),
            has_prim("scatter-add",
                     "per-leaf deltas land on feature columns"),
            within_budget(budget),
        ],
        "device TreeSHAP (serving/forest.py contrib_apply): "
        "extend/unwind permutation-weight DP over (row, tree, leaf) "
        "lanes, host shap.py parity",
    ),
    "online_holdout_eval": _Entry(
        _trace_online_holdout,
        lambda budget: [
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "online promotion-gate holdout evaluator (online/gate.py): "
        "device metrics over the candidate's raw margins — the gate "
        "verdict must stay callback-free and f32",
    ),
    "streamed_construct": _Entry(
        _trace_streamed_construct,
        lambda budget: [
            has_prim("dynamic_update_slice",
                     "each chunk lands at its row offset in the "
                     "resident bin matrix"),
            no_host_callbacks(),
            no_f64(),
            within_budget(budget),
        ],
        "out-of-core per-chunk device step (data/prefetch.py "
        "chunk_update_step): one int32 chunk written into the "
        "(G, Np) resident matrix — the only device work on the "
        "streamed construct path; the disk reads/binning stay on the "
        "prefetch reader thread (docs/DATA_PLANE.md)",
    ),
}


# ------------------------------------------------------- fold-attr audit
def audit_fold_attrs() -> AuditResult:
    """_OBJ_FOLD_ATTRS exhaustiveness (ADVICE r5 item 3): statically
    prove no objective class assigns a device array to an attribute
    outside the fused step's rebind list — an unlisted one would be
    baked into the memoized executable and silently shared across cv
    folds. Pure AST; no jax import."""
    import ast

    from .. import objectives as _obj_mod
    from ..boosting import _OBJ_FOLD_ATTRS, _OBJ_FOLD_EXEMPT

    src = Path(_obj_mod.__file__).read_text()
    tree = ast.parse(src)

    def is_device_expr(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                parts: List[str] = []
                f = n.func
                while isinstance(f, ast.Attribute):
                    parts.append(f.attr)
                    f = f.value
                if isinstance(f, ast.Name):
                    parts.append(f.id)
                d = ".".join(reversed(parts))
                if d.startswith("jnp.") or d.startswith("jax.numpy."):
                    return True
                if d in ("jax.device_put",) or d.startswith("jax.random."):
                    return True
        return False

    device_attrs: Dict[str, int] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and is_device_expr(n.value)
            ):
                device_attrs.setdefault(t.attr, n.lineno)
    unlisted = {
        a: ln for a, ln in sorted(device_attrs.items())
        if a not in _OBJ_FOLD_ATTRS and a not in _OBJ_FOLD_EXEMPT
    }
    ok = not unlisted
    detail = (
        f"device attrs {sorted(device_attrs)} all in _OBJ_FOLD_ATTRS "
        f"(+exempt {sorted(_OBJ_FOLD_EXEMPT)})"
        if ok
        else "objective attrs hold device arrays OUTSIDE the fused "
        "rebind list (would silently share fold data across cached "
        "steps): "
        + ", ".join(f"{a} (objectives.py:{ln})" for a, ln in unlisted.items())
        + " — add to _OBJ_FOLD_ATTRS or _OBJ_FOLD_EXEMPT (with a "
        "gating reason)"
    )
    return AuditResult(
        "obj_fold_attrs", ok,
        [Contract("fold_attrs_exhaustive", ok, detail)], 0,
    )


# -------------------------------------------------- fault-injection audit
def audit_faultinject() -> AuditResult:
    """Fault injection must cost nothing when disarmed and stay
    invisible to traced code when armed (docs/RESILIENCE.md):

    1. pure-AST: every ``fault_point()`` call site lives in a
       whitelisted HOST-side module (engine loop, serving dispatcher /
       transport) — a call in kernel or traced code would bake a host
       callback (or a retrace) into the hot path;
    2. trace proof: building the serving entry with a fault plan ARMED
       (cache bypassed) yields a jaxpr with the identical equation
       count and no host callbacks — arming adds zero device work.
    """
    import ast

    from ..resilience import faultinject as _fi

    pkg_root = Path(__file__).resolve().parents[1]
    allowed = {
        "resilience/faultinject.py",  # the definition itself
        "engine.py",                  # per-round host loop
        "serving/dispatch.py",        # host side of the device call
        "serving/server.py",          # request transport
        "serving/fleet.py",           # HBM paging (fleet_page site)
        "serving/gateway.py",         # gw_* request/drain sites
        "online/loop.py",             # loop_* phase sites per cycle
    }
    sites: List[str] = []
    offenders: List[str] = []
    for py in sorted(pkg_root.rglob("*.py")):
        rel = py.relative_to(pkg_root).as_posix()
        src = py.read_text()
        if "fault_point" not in src:
            continue
        for n in ast.walk(ast.parse(src)):
            if isinstance(n, ast.Call):
                f = n.func
                fname = (f.attr if isinstance(f, ast.Attribute)
                         else getattr(f, "id", ""))
                if fname == "fault_point":
                    sites.append(f"{rel}:{n.lineno}")
                    if rel not in allowed:
                        offenders.append(f"{rel}:{n.lineno}")
    c_sites = Contract(
        "fault_sites_host_only", not offenders,
        f"{len(sites)} fault_point site(s) all in host-side modules "
        f"{sorted(allowed)}" if not offenders else
        "fault_point called outside the host-side whitelist (would "
        "put a fault hook into traced/kernel code): "
        + ", ".join(offenders),
    )

    baseline = summarize(build_entry("serving_forest"))
    prev_plan = _fi._PLAN
    _fi.arm("device_put:999999:raise;serve_request:999999:raise")
    try:
        armed = summarize(ENTRIES["serving_forest"].builder())
    finally:
        _fi._PLAN = prev_plan  # restore whatever the caller had armed
    c_eqns = Contract(
        "armed_trace_identical", armed.eqn_count == baseline.eqn_count,
        f"serving trace has {armed.eqn_count} eqns armed vs "
        f"{baseline.eqn_count} disarmed"
        + ("" if armed.eqn_count == baseline.eqn_count else
           " — an armed fault plan must not change the traced program"),
    )
    c_cb = no_host_callbacks()(armed)
    ok = all(c.ok for c in (c_sites, c_eqns, c_cb))
    return AuditResult(
        "faultinject", ok, [c_sites, c_eqns, c_cb], armed.eqn_count
    )


# ------------------------------------------- chunk-scan C-invariance audit
def audit_chunk_invariance() -> AuditResult:
    """The scan body is traced ONCE: the chunk jaxpr's flattened eqn
    count must be identical across ladder rungs (scan length is a jaxpr
    param). Accidental unrolling — a Python loop over rounds, a
    shape-dependent branch on the rung — would scale eqns with C and
    silently void the committed fused_chunk_scan budgets, which are
    pinned at C=%d and must cover every rung.""" % _CHUNK_SCAN_C
    from ..boosting import trace_fused_chunk

    a = summarize(trace_fused_chunk(_CHUNK_SCAN_C))
    b = summarize(trace_fused_chunk(_CHUNK_SCAN_C_ALT))
    ok = a.eqn_count == b.eqn_count
    c = Contract(
        "eqns_independent_of_C", ok,
        f"{a.eqn_count} eqns at C={_CHUNK_SCAN_C} vs {b.eqn_count} at "
        f"C={_CHUNK_SCAN_C_ALT}"
        + ("" if ok else
           " — the scan body unrolled; budgets no longer cover all "
           "ladder rungs"),
    )
    return AuditResult("chunk_c_invariance", ok, [c], a.eqn_count)


# ------------------------------------------------------------------ runner
# entry traces are pure functions of checked-in shapes, and the strict
# gate reads each one at least twice (jaxpr pass + cost pass, several
# seconds per rounds trace) — memoize per (entry, interpret-mode,
# mesh size) so the scale auditor's D=8 rung shares the trace the
# jaxpr/cost passes already paid for
_CLOSED_CACHE: Dict[Any, Any] = {}


def mesh_entry_names() -> List[str]:
    """Entries that trace through a device mesh (the scale auditor's
    universe: anything whose collectives/shardings can vary with D)."""
    return [n for n, e in ENTRIES.items() if e.mesh_builder is not None]


def build_entry(name: str, pallas_interpret: bool = False,
                n_devices: Optional[int] = None):
    """Entry ClosedJaxpr, memoized. With pallas_interpret the trace
    runs under the pallas interpreter (histogram._interpret_pallas
    reads the env var at trace time) so XLA:CPU can later compile it —
    the cost auditor's path for pallas entries. The env var is forced
    BOTH ways: an ambient LGBM_TPU_PALLAS_INTERPRET=1 (the pallas
    debugging knob) must not leak an interpreted trace into the
    non-interpreted budget comparison.

    n_devices retraces a mesh-bearing entry on a sub-mesh of the
    forced host platform (the scale auditor's D-ladder). None means
    the entry's default mesh; for mesh entries that is
    HOST_DEVICE_COUNT, and the cache key normalizes the two spellings
    to one slot so passes share the full-mesh trace."""
    import os

    entry = ENTRIES[name]
    if n_devices is not None and entry.mesh_builder is None:
        raise ValueError(
            f"entry {name!r} has no mesh; n_devices={n_devices} is "
            "meaningless (only mesh_entry_names() entries retrace on "
            "the D-ladder)")
    n = n_devices
    if entry.mesh_builder is not None and n is None:
        n = HOST_DEVICE_COUNT
    key = (name, bool(pallas_interpret), n)
    if key in _CLOSED_CACHE:
        return _CLOSED_CACHE[key]
    env_key = "LGBM_TPU_PALLAS_INTERPRET"
    old = os.environ.get(env_key)
    if pallas_interpret:
        os.environ[env_key] = "1"
    else:
        os.environ.pop(env_key, None)
    try:
        if n is not None and n != HOST_DEVICE_COUNT:
            closed = entry.mesh_builder(n)
        else:
            closed = entry.builder()
    finally:
        if old is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = old
    _CLOSED_CACHE[key] = closed
    return closed


def load_budgets() -> Dict[str, int]:
    if _BUDGET_PATH.exists():
        return {
            k: int(v) for k, v in json.loads(_BUDGET_PATH.read_text()).items()
        }
    return {}


def run_audits(names: Optional[Sequence[str]] = None,
               update_budget: bool = False) -> List[AuditResult]:
    _standalone = ("obj_fold_attrs", "faultinject", "chunk_c_invariance")
    if names is not None:
        unknown = set(names) - set(ENTRIES) - set(_standalone)
        if unknown:
            # a typoed entry name must not pass vacuously ("no silent
            # caps" — same posture as within_budget failing on a
            # missing budget)
            raise KeyError(
                f"unknown audit entr{'y' if len(unknown) == 1 else 'ies'} "
                f"{sorted(unknown)}; known: "
                f"{sorted(ENTRIES) + sorted(_standalone)}"
            )
    budgets = load_budgets()
    out: List[AuditResult] = []
    new_budgets = dict(budgets)
    for name, entry in ENTRIES.items():
        if names is not None and name not in names:
            continue
        closed = build_entry(name)
        s = summarize(closed)
        if update_budget:
            new_budgets[name] = int(math.ceil(s.eqn_count * _BUDGET_HEADROOM))
        contracts = entry.contracts(new_budgets.get(name))
        results = [c(s) for c in contracts]
        out.append(AuditResult(
            name, all(c.ok for c in results), results, s.eqn_count
        ))
    if names is None or "obj_fold_attrs" in (names or ()):
        out.append(audit_fold_attrs())
    if names is None or "faultinject" in (names or ()):
        out.append(audit_faultinject())
    if names is None or "chunk_c_invariance" in (names or ()):
        out.append(audit_chunk_invariance())
    if update_budget:
        _BUDGET_PATH.write_text(
            json.dumps(new_budgets, indent=2, sort_keys=True) + "\n"
        )
    return out
