"""Sweep the slot-packed nat histogram kernel over (S, blk) on a live
chip, plus an int8-MXU feasibility probe. Prints one JSON line per
measurement.

Methodology: `block_until_ready` does NOT synchronize under the axon
tunnel runtime (BENCH_NOTES.md), so each config is timed as R
data-dependent kernel calls inside ONE jit followed by a scalar
device_get; per-call time = (t - t_baseline) / R where the baseline jit
carries the same dependency chain without the kernel."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbm_tpu.learner.histogram import build_gh8, build_gh8_quant
    from lightgbm_tpu.learner.pallas_hist import hist_nat_tpu

    print(json.dumps({"platform": jax.devices()[0].platform}), flush=True)

    rs = np.random.RandomState(0)
    F, B = 28, 256
    N = 61 * 16384  # 999424: divisible by 2048 / 8192 / 16384
    bins = jnp.asarray(rs.randint(0, 255, (F, N)).astype(np.int32))
    g = jnp.asarray(rs.randn(N).astype(np.float32))
    h = jnp.asarray((rs.rand(N) * 0.25).astype(np.float32))
    ones = jnp.ones(N, jnp.float32)
    gh8 = build_gh8(g, h, ones)
    gh8q = build_gh8_quant(
        jnp.asarray(rs.randint(-2, 3, N).astype(np.float32)),
        jnp.asarray(rs.randint(0, 5, N).astype(np.float32)),
        ones,
    )
    R = 20

    def timed(make_body):
        """make_body(acc_scalar) -> new acc_scalar, run R times in-jit."""

        def loop():
            def body(_, acc):
                return make_body(acc)

            return lax.fori_loop(0, R, body, jnp.float32(0.0))

        f = jax.jit(loop)
        float(f())  # compile + run once
        t0 = time.time()
        out = float(f())
        t = time.time() - t0
        del out
        return t / R

    # baseline: dependency-chain cost alone (gh8 materialization)
    def base_body(acc):
        gh = gh8 + acc * 0.0
        return acc + gh[0, 0]

    t_base = timed(base_body)
    print(json.dumps({"metric": "baseline_chain_ms",
                      "value": round(t_base * 1e3, 3)}), flush=True)

    def run(S, blk, ghx, nat_ch, tag):
        slot = jnp.asarray(rs.randint(0, S + 1, N).astype(np.int32))

        def body(acc):
            gh = ghx + acc * 0.0
            out = hist_nat_tpu(bins, gh, slot, S, B, blk=blk,
                               nat_ch=nat_ch)
            return acc + out[0, 0]

        try:
            t = timed(body) - t_base
            flops = 2.0 * S * nat_ch * N * B * F
            print(json.dumps({
                "metric": f"{tag}_S{S}_blk{blk}_ms",
                "value": round(t * 1e3, 2),
                "tf_s": round(flops / max(t, 1e-9) / 1e12, 1),
                "per_split_ms": round(t * 1e3 / S, 3),
            }), flush=True)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({
                "metric": f"{tag}_S{S}_blk{blk}_ms",
                "error": str(e)[-400:],
            }), flush=True)

    for S in (1, 8, 25, 50):
        for blk in (2048, 8192):
            run(S, blk, gh8, 5, "nat")
    for S in (25, 42, 80):
        for blk in (2048, 8192):
            run(S, blk, gh8q, 3, "natq")

    # ---- int8 MXU probe: does Mosaic lower s8 x s8 -> s32 dot? ----
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _k(a_ref, b_ref, o_ref):
        o_ref[...] = lax.dot_general(
            a_ref[...], b_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

    M, K, Nn = 256, 2048, 1024
    a = jnp.asarray(rs.randint(-4, 5, (M, K)).astype(np.int8))
    b = jnp.asarray(rs.randint(0, 2, (K, Nn)).astype(np.int8))
    try:
        pc = pl.pallas_call(
            _k,
            out_shape=jax.ShapeDtypeStruct((M, Nn), jnp.int32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
        out = np.asarray(jax.jit(pc)(a, b))
        ref = np.asarray(a, np.int32) @ np.asarray(b, np.int32)
        print(json.dumps({
            "metric": "int8_dot_probe", "exact": bool((out == ref).all()),
        }), flush=True)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({
            "metric": "int8_dot_probe", "error": str(e)[-300:],
        }), flush=True)


if __name__ == "__main__":
    main()
