"""Online train-and-serve loop (lightgbm_tpu/online, docs/RESILIENCE.md
"Online loop").

The contract under test, end to end: the loop serves v(n) from a
ModelRegistry while microbatches stream through the serving ``ingest``
op into a durable spool; each verdict cycle refits a warm-started
candidate (``init_score`` = v(n)'s raw margins, spliced with
``boosting.splice_continued`` so v(n) is a bit-exact prefix of v(n+1)),
judges it on a fixed holdout shard with device metrics, and atomically
promotes — or rejects a regression, or auto-reverts a poisoned
microbatch — while concurrent scorers only ever see a complete version.
Crash consistency: a fault injected at ANY loop phase
(``loop_ingest`` / ``loop_refit`` / ``loop_eval`` / ``loop_promote``,
resilience/faultinject.py) leaves a restart serving the last PERSISTED
promotion, in-process (raise) and for the real CLI process (SIGKILL).
The ``chaos`` marker ties the fault matrix to tools/chaos.sh."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.metrics import default_registry
from lightgbm_tpu.online import (
    IngestSpool,
    OnlineLoop,
    decide,
    fresh_state,
    load_state,
    model_path,
    save_state,
    spool_path,
    stack_batches,
    state_path,
)
from lightgbm_tpu.resilience import faultinject
from lightgbm_tpu.resilience.errors import CheckpointError, InjectedFault

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _disarm_fault_plan():
    """Chaos tests arm process-global fault plans; none may leak."""
    yield
    faultinject.disarm()


# ------------------------------------------------------------- fixtures
def _xy(seed: int, n: int):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 4)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    return X, y


_CORE = {
    "objective": "binary", "metric": "auc", "num_leaves": 7,
    "min_data_in_leaf": 5, "learning_rate": 0.2, "verbosity": -1,
    "seed": 7,
}


def _train_v0():
    X, y = _xy(5, 300)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    return lgb.train(dict(_CORE), ds, num_boost_round=6)


def _holdout():
    return _xy(9, 200)


def _params(tmp_path, **over):
    p = dict(_CORE)
    p.update({
        "loop_dir": str(tmp_path / "loop"), "loop_min_rows": 64,
        "loop_rounds": 4, "loop_poll_s": 0.05,
    })
    p.update(over)
    return p


def _batch(seed: int, n: int = 40):
    X, y = _xy(seed, n)
    return X.tolist(), y.tolist()


# ========================================================= ingest spool
def test_spool_roundtrip_and_torn_tail(tmp_path):
    sp = IngestSpool(spool_path(str(tmp_path)))
    rows, labels = _batch(20, 3)
    out = sp.append(rows, labels)
    assert out["rows"] == 3 and out["offset"] == sp.size()
    out2 = sp.append(rows, labels, weights=[1.0, 2.0, 3.0])
    batches, end = sp.read_from(0)
    assert len(batches) == 2 and end == out2["offset"] == sp.size()
    X, y, w = stack_batches(batches)
    assert X.shape == (6, 4) and y.shape == (6,)
    # mixed weighted/unweighted batches: missing weights become 1.0
    np.testing.assert_array_equal(w, [1, 1, 1, 1, 2, 3])
    # resuming from the end sees nothing new
    assert sp.read_from(end) == ([], end)

    # a torn tail (crash mid-append: no trailing newline) is left
    # unconsumed — the offset never advances past the tear
    with open(sp.path, "a") as f:
        f.write('{"rows": [[1.0')
    batches2, end2 = sp.read_from(0)
    assert len(batches2) == 2 and end2 == end

    # validation: bad microbatches are rejected before touching disk
    for bad in (lambda: sp.append([], []),
                lambda: sp.append(rows, labels[:-1]),
                lambda: sp.append([[1.0], [1.0, 2.0]], [0.0, 1.0]),
                lambda: sp.append(rows, labels, weights=[1.0])):
        with pytest.raises(ValueError):
            bad()
    assert sp.size() == end + len('{"rows": [[1.0')


def test_state_roundtrip_and_errors(tmp_path):
    sp = state_path(str(tmp_path))
    st = fresh_state()
    st["version"] = 3
    st["model_path"] = model_path(str(tmp_path), 3)
    save_state(sp, st)
    assert load_state(sp) == st
    assert not os.path.exists(sp + ".tmp")  # atomic publish, no residue

    torn = tmp_path / "torn.json"
    torn.write_text('{"schema": "lightgbm-tpu/online-loop/v1", "ver')
    with pytest.raises(CheckpointError, match="corrupt"):
        load_state(str(torn))
    alien = tmp_path / "alien.json"
    alien.write_text(json.dumps({"schema": "something/else"}))
    with pytest.raises(CheckpointError, match="schema"):
        load_state(str(alien))
    inc = tmp_path / "inc.json"
    inc.write_text(json.dumps(
        {"schema": "lightgbm-tpu/online-loop/v1", "version": 1}))
    with pytest.raises(CheckpointError, match="missing"):
        load_state(str(inc))
    with pytest.raises(CheckpointError, match="cannot read"):
        load_state(str(tmp_path / "absent.json"))


# ======================================================= promotion gate
def test_gate_decide():
    # anomaly trips veto before any metric comparison
    out, why = decide([0.9], [0.5], ["auc"], [True], 0.0,
                      {"loss_spike": 1})
    assert out == "rolled_back" and "loss_spike" in why
    # zero-count trips do not
    assert decide([0.9], [0.5], ["auc"], [True], 0.0,
                  {"loss_spike": 0})[0] == "promoted"
    # higher_better: candidate must not fall below incumbent - margin
    assert decide([0.84], [0.85], ["auc"], [True], 0.0, {})[0] == \
        "rejected"
    assert decide([0.84], [0.85], ["auc"], [True], 0.02, {})[0] == \
        "promoted"
    # lower-better metrics compare the other way
    assert decide([0.50], [0.40], ["binary_logloss"], [False],
                  0.0, {})[0] == "rejected"
    assert decide([0.39], [0.40], ["binary_logloss"], [False],
                  0.0, {})[0] == "promoted"
    # only the FIRST metric gates; a fresh start has no incumbent
    assert decide([0.9, 9.9], [0.5, 0.1], ["auc", "binary_logloss"],
                  [True, False], 0.0, {})[0] == "promoted"
    assert decide([0.2], None, ["auc"], [True], 0.0, {})[0] == \
        "promoted"


# ================================= end-to-end: promote under scoring
@pytest.mark.chaos
@pytest.mark.slow
def test_promote_splice_exact_and_concurrent_swap(tmp_path):
    """Serve v0, stream microbatches, refit v1, gate, auto-promote:
    v0 is a bit-exact prefix of v1 (splice_continued), the registry
    swap is atomic under concurrent scoring (every prediction matches
    v0 or v1, never a torn mix), and the verdict lands in the durable
    state + /metrics counters + the loop's event log."""
    from lightgbm_tpu.serving import ModelRegistry

    v0 = _train_v0()
    HX, Hy = _holdout()
    loop = OnlineLoop(_params(tmp_path), (HX, Hy), initial_model=v0)
    registry = ModelRegistry()
    loop.attach(registry)
    assert registry.ingest_sink is loop.spool
    assert registry.health_probe == loop.health

    # ingest through the registry attachment, as the serving op does
    for seed in (31, 32):
        registry.ingest_sink.append(*_batch(seed, 40))

    probe = HX[:16]
    pred_v0 = v0.predict(probe)
    stop = threading.Event()
    seen, errs = [], []

    def scorer():
        try:
            while not stop.is_set():
                seen.append(np.asarray(registry.predict("default", probe)))
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=scorer) for _ in range(2)]
    for t in threads:
        t.start()
    promo = default_registry().counter(
        "lgbmtpu_promotion_events_total", labels=("outcome",))
    before = promo.value(outcome="promoted")
    try:
        outcome = loop.cycle()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errs, errs
    assert outcome == "promoted"
    assert promo.value(outcome="promoted") == before + 1

    st = load_state(state_path(loop.loop_dir))
    assert st["version"] == 1 and st["counts"]["promoted"] == 1
    assert st["last_outcome"] == "promoted"
    assert st["ingest_offset"] == loop.spool.size()
    v1 = lgb.Booster(model_file=st["model_path"])
    assert v1.num_trees() == v0.num_trees() + loop.rounds

    # warm-start splice exactness: the first num_trees(v0) trees of v1
    # ARE v0 — raw scores bit-match
    np.testing.assert_array_equal(
        v1.predict(HX, raw_score=True, num_iteration=v0.num_trees()),
        v0.predict(HX, raw_score=True),
    )

    # atomicity under swap: every concurrent prediction is exactly one
    # whole version's output
    pred_v1 = v1.predict(probe)
    assert len(seen) > 0
    for p in seen:
        ok_v0 = np.allclose(p, pred_v0, rtol=1e-5, atol=1e-6)
        ok_v1 = np.allclose(p, pred_v1, rtol=1e-5, atol=1e-6)
        assert ok_v0 or ok_v1, "scored a torn model version"
    # and the registry now serves v1
    np.testing.assert_allclose(registry.predict("default", probe),
                               pred_v1, rtol=1e-5, atol=1e-6)

    # provenance: event log + health reflect the verdict
    events = [json.loads(l) for l in
              open(os.path.join(loop.loop_dir, "loop_events.jsonl"))]
    assert events[-1]["outcome"] == "promoted"
    assert events[-1]["serving_version"] == 1
    h = loop.health()
    assert h["loop"]["version"] == 1
    assert h["loop"]["spool_backlog_bytes"] == 0


@pytest.mark.chaos
@pytest.mark.slow
def test_poison_reverts_regression_rejects_then_recovers(tmp_path):
    """The gate's three verdicts in sequence on one loop: a poisoned
    microbatch (labels the trainer rejects) auto-reverts, label-flipped
    rows regress the holdout metric and are rejected, and a clean batch
    then promotes — the spool offset advances past EVERY verdict so bad
    data is discarded, never re-consumed."""
    v0 = _train_v0()
    HX, Hy = _holdout()
    loop = OnlineLoop(_params(tmp_path), (HX, Hy), initial_model=v0)

    # poison: NaN labels fail objective label validation inside refit
    rows, labels = _batch(41, 80)
    loop.spool.append(rows, [float("nan")] * len(labels))
    assert loop.cycle() == "rolled_back"
    st = load_state(state_path(loop.loop_dir))
    assert st["version"] == 0 and st["counts"]["rolled_back"] == 1
    off_after_poison = st["ingest_offset"]
    assert off_after_poison == loop.spool.size()  # poison discarded

    # regression: flipped labels train a candidate whose holdout auc
    # falls below the incumbent's -> rejected, v0 keeps serving
    rows, labels = _batch(42, 80)
    loop.spool.append(rows, [1.0 - v for v in labels])
    assert loop.cycle() == "rejected"
    st = load_state(state_path(loop.loop_dir))
    assert st["version"] == 0 and st["counts"]["rejected"] == 1
    assert st["ingest_offset"] > off_after_poison

    # a clean batch after the bad ones promotes normally
    loop.spool.append(*_batch(43, 80))
    assert loop.cycle() == "promoted"
    st = load_state(state_path(loop.loop_dir))
    assert st["version"] == 1 and st["counts"] == \
        {"promoted": 1, "rejected": 1, "rolled_back": 1}
    # below loop_min_rows new bytes: no verdict
    loop.spool.append(*_batch(44, 8))
    assert loop.cycle() is None


# ==================================== fault matrix: raise + restart
@pytest.mark.chaos
@pytest.mark.slow
def test_loop_fault_matrix_inprocess(tmp_path):
    """A fault at EVERY loop phase leaves a restart serving the last
    persisted promotion: state untouched (version 0, offset 0), the
    spool replayable, and the re-attached registry scoring v0 exactly.
    A delay clause only stretches the cycle."""
    from lightgbm_tpu.serving import ModelRegistry

    v0 = _train_v0()
    HX, Hy = _holdout()
    params = _params(tmp_path)
    loop = OnlineLoop(params, (HX, Hy), initial_model=v0)
    for seed in (51, 52):
        loop.spool.append(*_batch(seed, 40))
    probe = HX[:8]
    pred_v0 = v0.predict(probe)

    for site in ("loop_ingest", "loop_refit", "loop_eval",
                 "loop_promote"):
        plan = f"{site}:0:raise"
        faultinject.configure(plan)
        crash = OnlineLoop(dict(params, fault_plan=plan), (HX, Hy))
        with pytest.raises(InjectedFault):
            crash.cycle()
        faultinject.disarm()
        # "restart": a fresh loop over the same durable directory
        re = OnlineLoop(params, (HX, Hy))
        st = re.state
        assert st["version"] == 0, site
        assert st["ingest_offset"] == 0, site  # cycle will replay
        assert st["counts"] == {"promoted": 0, "rejected": 0,
                                "rolled_back": 0}, site
        reg = ModelRegistry(warmup=False)
        re.attach(reg)
        np.testing.assert_allclose(reg.predict("default", probe),
                                   pred_v0, rtol=1e-5, atol=1e-6)

    # delayed ingest: the cycle completes, just late
    plan = "loop_ingest:0:delay:0.2"
    faultinject.configure(plan)
    slow = OnlineLoop(dict(params, fault_plan=plan), (HX, Hy))
    t0 = time.monotonic()
    assert slow.cycle() == "promoted"
    assert time.monotonic() - t0 >= 0.2
    assert slow.state["version"] == 1
    # the loop_eval crash left an orphan candidate file; the completed
    # cycle overwrote it with the promoted v1
    v1 = lgb.Booster(model_file=model_path(slow.loop_dir, 1))
    np.testing.assert_array_equal(
        v1.predict(HX, raw_score=True, num_iteration=v0.num_trees()),
        v0.predict(HX, raw_score=True))


# ============================== fault matrix: SIGKILL'd CLI process
@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("site", ["loop_ingest", "loop_refit",
                                  "loop_eval", "loop_promote"])
def test_sigkill_cli_loop_restart(tmp_path, site):
    """The real thing, per loop phase: a ``task=loop`` CLI process
    SIGKILLed by fault plan ``<site>:0:kill`` (no cleanup, no flush)
    restarts with the last promoted version serving, replays the
    spooled microbatches, and promotes v1 — scored through the
    restarted process's own transport."""
    v0 = _train_v0()
    (tmp_path / "model.txt").write_text(v0.model_to_string())
    HX, Hy = _holdout()
    np.savetxt(tmp_path / "holdout.csv",
               np.column_stack([Hy, HX]), delimiter=",", fmt="%.8g")
    loop_dir = str(tmp_path / "loop")
    args = [
        sys.executable, "-m", "lightgbm_tpu", "task=loop",
        f"input_model={tmp_path}/model.txt",
        f"valid_data={tmp_path}/holdout.csv",
        "objective=binary", "metric=auc", "num_leaves=7",
        "min_data_in_leaf=5", "learning_rate=0.2", "seed=7",
        f"loop_dir={loop_dir}", "loop_min_rows=64", "loop_rounds=4",
        # v0 nearly saturates this holdout (auc ~0.987): allow the
        # usual tiny refit jitter or the near-tie gate rejects forever
        "loop_gate_margin=0.02",
        "loop_poll_s=0.1", "verbosity=-1",
    ]
    # cwd=REPO (not PYTHONPATH) so the package resolves: any PYTHONPATH
    # value breaks discovery of the axon TPU backend plugin
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(faultinject.ENV_VAR, None)
    ingest_lines = "".join(
        json.dumps({"op": "ingest", "rows": r, "labels": l}) + "\n"
        for r, l in (_batch(61, 40), _batch(62, 40)))

    # phase 1: arm the kill, feed the spool, watch the process die -9
    proc = subprocess.Popen(
        args + [f"fault_plan={site}:0:kill"], cwd=str(REPO),
        env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        proc.stdin.write(ingest_lines)
        proc.stdin.flush()
    except BrokenPipeError:
        pass  # loop_ingest kills on the first poll, before any ingest
    try:
        proc.wait(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -9, (site, proc.stderr.read()[-2000:])

    # the kill left the durable floor intact: v0 promoted, offset 0
    st = load_state(state_path(loop_dir))
    assert st["version"] == 0 and st["ingest_offset"] == 0, site
    assert Path(st["model_path"]).exists()

    # phase 2: restart WITHOUT the plan; replay/ingest, await the
    # promotion in the durable state, then score through the server
    proc = subprocess.Popen(
        args, cwd=str(REPO), env=env, stdin=subprocess.PIPE,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        proc.stdin.write(ingest_lines)
        proc.stdin.flush()
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"restart died: {proc.stderr.read()[-2000:]}")
            try:
                if load_state(state_path(loop_dir))["version"] >= 1:
                    break
            except CheckpointError:
                pass
            time.sleep(0.2)
        else:
            raise AssertionError(f"{site}: restart never promoted v1")
        probe = HX[:8]
        proc.stdin.write(json.dumps(
            {"op": "score", "model": "default",
             "rows": probe.tolist()}) + "\n")
        proc.stdin.write(json.dumps({"op": "quit"}) + "\n")
        proc.stdin.flush()
        out, err = proc.communicate(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, err[-2000:]
    resp = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    scored = next(r for r in resp if "pred" in r)
    st = load_state(state_path(loop_dir))
    assert st["version"] == 1 and st["counts"]["promoted"] == 1
    v1 = lgb.Booster(model_file=st["model_path"])
    np.testing.assert_allclose(np.asarray(scored["pred"]),
                               v1.predict(probe), rtol=1e-5, atol=1e-6)
