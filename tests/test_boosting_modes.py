"""DART and RF boosting-mode tests (reference test_engine.py dart/rf cases)."""

import numpy as np
import pytest

from conftest import make_synthetic_binary, make_synthetic_regression

import lightgbm_tpu as lgb


def test_dart_trains_and_improves():
    X, y = make_synthetic_regression(n=600, n_features=8)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {
        "objective": "regression", "boosting": "dart", "num_leaves": 15,
        "drop_rate": 0.5, "skip_drop": 0.3, "verbosity": -1, "metric": "l2",
    }
    res = {}
    bst = lgb.train(
        params, ds, num_boost_round=30, valid_sets=[ds], valid_names=["t"],
        callbacks=[lgb.record_evaluation(res)],
    )
    l2 = res["t"]["l2"]
    # dropout slows convergence vs plain gbdt; just require steady progress
    assert l2[-1] < l2[0] * 0.75
    pred = bst.predict(X)
    assert float(np.mean((pred - y) ** 2)) == pytest.approx(l2[-1], rel=1e-4)


def test_dart_score_consistency():
    """After training, internal train score must equal prediction from the
    saved (renormalized) trees — the DART normalize bookkeeping check."""
    X, y = make_synthetic_regression(n=400, n_features=6)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {
        "objective": "regression", "boosting": "dart", "num_leaves": 7,
        "drop_rate": 0.6, "skip_drop": 0.0, "max_drop": 3, "verbosity": -1,
        "boost_from_average": False,
    }
    bst = lgb.train(params, ds, num_boost_round=12)
    internal = bst._gbdt.get_score(bst._gbdt.train)[0]
    from_trees = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, from_trees, rtol=2e-4, atol=2e-5)


def test_dart_xgboost_mode():
    X, y = make_synthetic_binary(n=400, n_features=6)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {
        "objective": "binary", "boosting": "dart", "num_leaves": 7,
        "xgboost_dart_mode": True, "drop_rate": 0.5, "skip_drop": 0.0,
        "verbosity": -1,
    }
    bst = lgb.train(params, ds, num_boost_round=10)
    pred = bst.predict(X)
    acc = float(np.mean((pred > 0.5) == y))
    assert acc > 0.8


def test_rf_mode():
    X, y = make_synthetic_binary(n=600, n_features=8)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {
        "objective": "binary", "boosting": "rf", "num_leaves": 31,
        "bagging_freq": 1, "bagging_fraction": 0.7, "verbosity": -1,
    }
    bst = lgb.train(params, ds, num_boost_round=20)
    pred = bst.predict(X)
    # averaged probabilities, not boosted: still a decent classifier
    acc = float(np.mean((pred > 0.5) == y))
    assert acc > 0.85
    # averaging keeps prediction in a sane probability range
    assert 0.0 < pred.min() and pred.max() < 1.0


def test_rf_score_is_average():
    X, y = make_synthetic_regression(n=400, n_features=6)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {
        "objective": "regression", "boosting": "rf", "num_leaves": 15,
        "bagging_freq": 1, "bagging_fraction": 0.6, "verbosity": -1,
    }
    bst = lgb.train(params, ds, num_boost_round=8)
    internal = bst._gbdt.get_score(bst._gbdt.train)[0]
    from_trees = bst.predict(X)  # average_output divides by #trees
    np.testing.assert_allclose(internal, from_trees, rtol=2e-4, atol=2e-5)


def test_rf_save_load_round_trip(tmp_path):
    X, y = make_synthetic_regression(n=300, n_features=6)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {
        "objective": "regression", "boosting": "rf", "num_leaves": 7,
        "bagging_freq": 1, "bagging_fraction": 0.6, "verbosity": -1,
    }
    bst = lgb.train(params, ds, num_boost_round=5)
    path = tmp_path / "rf.txt"
    bst.save_model(path)
    b2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(b2.predict(X), bst.predict(X), rtol=1e-6)


def test_rf_requires_bagging():
    X, y = make_synthetic_regression(n=200, n_features=4)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    with pytest.raises(Exception):
        lgb.train({"objective": "regression", "boosting": "rf", "verbosity": -1}, ds, 3)


def test_boosting_goss_alias_still_works():
    X, y = make_synthetic_regression(n=300, n_features=6)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "boosting": "goss", "num_leaves": 7,
         "learning_rate": 0.2, "verbosity": -1},
        ds, num_boost_round=10,
    )
    assert bst.num_trees() == 10


def test_dart_custom_objective_sees_dropout():
    """DART + fobj: gradients must be computed after dropout is applied."""
    X, y = make_synthetic_regression(n=300, n_features=6)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    seen_preds = []

    def l2_obj(preds, dataset):
        seen_preds.append(np.asarray(preds).copy())
        lbl = dataset.get_label()
        return preds - lbl, np.ones_like(lbl)

    params = {
        "objective": "none", "boosting": "dart", "num_leaves": 7,
        "drop_rate": 1.0, "skip_drop": 0.0, "verbosity": -1,
    }
    bst = lgb.train(params, ds, num_boost_round=5, fobj=l2_obj)
    # with drop_rate=1/skip_drop=0 every past iteration drops each round:
    # the preds handed to fobj must stay near zero (ensemble fully dropped)
    assert np.abs(seen_preds[-1]).max() < np.abs(y).max()
    internal = bst._gbdt.get_score(bst._gbdt.train)[0]
    raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, raw, rtol=2e-4, atol=2e-5)


def test_bagging_exact_count():
    """Bag sizes are exact (reference samples exactly frac*N rows, not a
    Bernoulli draw)."""
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.sample_strategy import BaggingStrategy

    c = Config({"bagging_fraction": 0.5, "bagging_freq": 1})
    n = 10000
    st = BaggingStrategy(c, n)
    valid = jnp.ones(n, jnp.float32)
    g = jnp.zeros(n)
    for it in (0, 1, 5):
        mask, _, _ = st.sample(it, g, g, valid, None)
        assert int(mask.sum()) == 5000, int(mask.sum())


def test_bagging_by_query():
    import numpy as np

    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.sample_strategy import BaggingStrategy

    group = np.asarray([10, 20, 5, 15, 30, 20])
    n = int(group.sum())
    c = Config({"bagging_fraction": 0.5, "bagging_freq": 1,
                "bagging_by_query": True})
    st = BaggingStrategy(c, n, group=group)
    valid = jnp.ones(n, jnp.float32)
    g = jnp.zeros(n)
    mask, _, _ = st.sample(0, g, g, valid, None)
    m = np.asarray(mask)
    qb = np.concatenate([[0], np.cumsum(group)])
    picked = [m[qb[q]:qb[q + 1]] for q in range(len(group))]
    # whole queries in or out, exactly half the queries selected
    assert all((p == p[0]).all() for p in picked)
    assert sum(int(p[0]) for p in picked) == 3
