"""Concurrency linter: lock-discipline rules for the threaded serving
layer (serving/dispatch.py, registry.py, server.py — and anything else
in the package that grows threads).

Pure stdlib AST, same architecture and suppression syntax as the
trace-safety linter (`# lint: allow[rule-id]`, file-wide
`# lint: allow-file[rule-id]` in the first 10 lines — lint.py owns the
comment scanner). The serving layer scores requests from
ThreadingHTTPServer request threads plus the MicroBatcher worker, so
lock-discipline regressions are production incidents (a swap that
tears, a registry stats call that deadlocks a scoring thread), and —
like the trace hazards — every one of them is visible in the source
AST before any traffic exists.

Lock model: a class OWNS the threading primitives it assigns to
attributes (``self._lock = threading.Lock()``); a module owns its
module-level primitives. Within a function, ``with <lock>:`` tracks
the held set lexically; calls to sibling methods / module functions
propagate both "locks this call may acquire" and "this call may
block" one call graph deep (to a fixpoint).

Rules:

- ``unlocked-write`` — an attribute written under the class lock in
  some methods is shared mutable state; writing it elsewhere without
  the lock (outside ``__init__``, where the object is still
  thread-private) is a torn-state hazard.
- ``lock-order`` — two locks acquired in opposite nesting orders
  across the module's call graph (classic AB/BA deadlock), or a plain
  non-reentrant ``Lock``/``Semaphore`` re-acquired while already held
  (self-deadlock; ``RLock``/``Condition`` are reentrant and exempt).
- ``per-call-lock`` — a threading primitive constructed inside a
  regular function/method (anything but ``__init__``-likes and
  module/class scope): a lock created per call guards nothing.
- ``blocking-under-lock`` — a blocking call (``sleep``, thread/process
  ``join``, ``Future.result``, ``subprocess`` waits,
  ``block_until_ready``, ``serve_forever``, socket accept/recv, or a
  local call that transitively blocks) made while holding a lock:
  every other thread needing that lock stalls behind the wait.
  ``cond.wait()`` on the very condition being held is the coalescing
  idiom and exempt (wait releases the lock).
- ``unbounded-producer-queue`` — a module spawns a thread whose target
  ``.put``s into a queue inside a loop (a streaming producer, e.g. the
  data-plane prefetch reader), yet constructs a queue without a
  positive ``maxsize``: the producer can outrun the consumer without
  backpressure and host memory grows with the input. Put-once targets
  (the gateway's hedged-attempt threads) don't trip this.
- ``jax-in-reader-thread`` — a queue-producer thread target calls into
  ``jax.*``/``jnp.*`` beyond the ``jax.device_put`` transfer: tracing
  or compiling off the main thread races the global trace state, and
  dispatch from two threads serializes on the backend anyway
  (docs/DATA_PLANE.md prefetch contract).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

from .lint import Finding, Rule, _dotted, scan_allow_comments

CONCURRENCY_RULES: Dict[str, Rule] = {}


def _register(rule_id: str, summary: str) -> str:
    CONCURRENCY_RULES[rule_id] = Rule(rule_id, summary)
    return rule_id


UNLOCKED_WRITE = _register(
    "unlocked-write",
    "shared mutable attribute (written under the owning lock elsewhere "
    "in the class) written without the lock — torn state under "
    "concurrent serving threads",
)
LOCK_ORDER = _register(
    "lock-order",
    "lock acquisition-order inversion across methods (AB/BA deadlock), "
    "or a non-reentrant Lock re-acquired while already held",
)
PER_CALL_LOCK = _register(
    "per-call-lock",
    "threading primitive created inside a per-call function instead of "
    "per-instance (__init__) or module scope — a fresh lock guards "
    "nothing",
)
BLOCKING_UNDER_LOCK = _register(
    "blocking-under-lock",
    "blocking call while holding a lock — every thread needing the "
    "lock stalls behind the wait (move the slow work outside the "
    "critical section)",
)
UNBOUNDED_PRODUCER_QUEUE = _register(
    "unbounded-producer-queue",
    "unbounded queue in a module whose thread target puts inside a "
    "loop — the producer can run arbitrarily far ahead of the "
    "consumer, unbounding host memory (give the queue a maxsize)",
)
JAX_IN_READER_THREAD = _register(
    "jax-in-reader-thread",
    "JAX call other than jax.device_put on a queue-producer thread — "
    "tracing/compiling off the main thread races the trace state and "
    "serializes on the backend; producer threads stay host-only "
    "except for the transfer itself",
)

# primitive constructors; value = reentrant? (safe to re-acquire)
_LOCK_KINDS: Dict[str, bool] = {
    "Lock": False,
    "RLock": True,
    "Condition": True,   # wraps an RLock by default
    "Semaphore": False,
    "BoundedSemaphore": False,
}
_PRIMITIVE_CTORS = set(_LOCK_KINDS) | {"Event", "Barrier"}
_INIT_METHODS = {"__init__", "__new__", "__post_init__", "__set_name__"}
# method calls that mutate their receiver (self.attr.append(...) is a
# write to attr just like self.attr = ...)
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popleft",
    "sort", "reverse",
}
# dotted-leaf names that block the calling thread
_BLOCKING_LEAVES = {
    "sleep", "result", "communicate", "serve_forever",
    "block_until_ready", "accept", "recv", "recvfrom", "select",
    "check_call", "check_output",
}
# subprocess.<leaf> that wait for the child
_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output"}
# queue constructors (queue module / multiprocessing); SimpleQueue has
# no maxsize parameter at all
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_PUT_LEAVES = {"put", "put_nowait"}


class _FnSummary(NamedTuple):
    qualname: str
    node: ast.AST
    cls: Optional[str]
    acquires: Set[str]        # lock ids `with`-acquired anywhere inside
    blocking_other: bool      # contains a non-wait blocking call
    waits: Set[str]           # known locks/conditions this fn waits on
    calls: Set[str]           # local callee keys (resolved later)


class _ConcurrencyLinter:
    """One module at a time; the lock namespace (self.X per class,
    module-level names) does not usefully cross modules."""

    def __init__(self, tree: ast.Module, src: str, path: str):
        self.tree = tree
        self.path = path
        self.allow_lines, self.allow_file = scan_allow_comments(src)
        self.findings: List[Finding] = []
        # lock id -> reentrant? ; ids are "self.X" scoped per class
        # ("Cls::self.X") and bare module names ("name")
        self.locks: Dict[str, bool] = {}
        self.fns: Dict[str, _FnSummary] = {}   # key "Cls.meth" | "fn"
        # class -> attr -> [(node, fn_key, held frozenset, in_init)]
        self.writes: Dict[str, Dict[str, List[tuple]]] = {}
        # acquisition edges: (held, acquired) -> first (node, fn_key)
        self.edges: Dict[Tuple[str, str], tuple] = {}

    # ------------------------------------------------------------ utils
    def _lock_kind(self, call: ast.AST) -> Optional[str]:
        """'Lock' / 'Condition' / ... when `call` constructs a
        threading primitive (threading.X() or bare imported X())."""
        if not isinstance(call, ast.Call):
            return None
        d = _dotted(call.func)
        if d is None:
            return None
        parts = d.split(".")
        leaf = parts[-1]
        if leaf not in _PRIMITIVE_CTORS:
            return None
        if len(parts) == 1 or parts[0] in ("threading", "multiprocessing"):
            return leaf
        return None

    def _lock_id(self, node: ast.AST, cls: Optional[str]) -> Optional[str]:
        """Known-lock id for an expression used in `with <expr>:` —
        class locks are scoped so same-named attrs in two classes stay
        distinct."""
        d = _dotted(node)
        if d is None:
            return None
        if d.startswith("self.") and cls is not None:
            lid = f"{cls}::{d}"
            return lid if lid in self.locks else None
        return d if d in self.locks else None

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        sup = rule in self.allow_file or any(
            rule in self.allow_lines.get(ln, ())
            for ln in (line, line - 1)
        )
        self.findings.append(
            Finding(rule, self.path, line, col, message, sup)
        )

    # ------------------------------------------------------- collection
    def _collect_locks(self) -> None:
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                kind = self._lock_kind(stmt.value)
                if kind in _LOCK_KINDS:
                    self.locks[stmt.targets[0].id] = _LOCK_KINDS[kind]
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for n in ast.walk(node):
                if isinstance(n, ast.Assign) and len(n.targets) == 1:
                    t = n.targets[0]
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        kind = self._lock_kind(n.value)
                        if kind in _LOCK_KINDS:
                            self.locks[f"{node.name}::self.{t.attr}"] = \
                                _LOCK_KINDS[kind]

    def _collect_fns(self) -> None:
        def visit(node: ast.AST, cls: Optional[str], prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    key = f"{prefix}{child.name}"
                    self.fns[key] = self._summarize_fn(child, cls, key)
                    visit(child, cls, key + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name, child.name + ".")

        visit(self.tree, None, "")

    def _classify_call(self, call: ast.Call, cls: Optional[str]):
        """None for non-blocking calls, else (kind, lock_id, message):
        kind "wait" with lock_id set when the receiver is a known
        lock/condition (exempt while that lock is held — wait releases
        it), kind "block" otherwise."""
        d = _dotted(call.func)
        if d is None:
            return None
        parts = d.split(".")
        leaf = parts[-1]
        if leaf == "wait":
            recv = d.rsplit(".", 1)[0] if len(parts) > 1 else None
            lid = None
            if recv is not None:
                if cls is not None and f"{cls}::{recv}" in self.locks:
                    lid = f"{cls}::{recv}"
                elif recv in self.locks:
                    lid = recv
            return ("wait", lid, f"{d}() waits while the lock is held")
        if leaf == "join":
            # str.join is everywhere: only flag thread/process-style
            # joins — zero positional args (or a timeout kwarg), and
            # never on a string constant
            if isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Constant):
                return None
            if not call.args or any(k.arg == "timeout"
                                    for k in call.keywords):
                return ("block", None,
                        f"{d}() joins a thread/process under the lock")
            return None
        if leaf in _SUBPROCESS_BLOCKING and len(parts) > 1 \
                and parts[0] == "subprocess":
            return ("block", None,
                    f"{d}() waits for a subprocess under the lock")
        if leaf in _BLOCKING_LEAVES:
            return ("block", None, f"{d}() blocks while the lock is held")
        return None

    def _is_blocking_call(self, call: ast.Call, cls: Optional[str],
                          held: Sequence[str]) -> Optional[str]:
        """Reason string when `call` blocks given the held set; None
        otherwise (a wait on a held condition is the coalescing
        idiom)."""
        k = self._classify_call(call, cls)
        if k is None:
            return None
        kind, lid, msg = k
        if kind == "wait" and lid is not None and lid in held:
            return None
        return msg

    def _summarize_fn(self, fn: ast.AST, cls: Optional[str],
                      key: str) -> _FnSummary:
        acquires: Set[str] = set()
        waits: Set[str] = set()
        blocking_other = False
        calls: Set[str] = set()
        for n in self._walk_scope(fn):
            if isinstance(n, ast.With):
                for item in n.items:
                    lid = self._lock_id(item.context_expr, cls)
                    if lid is not None:
                        acquires.add(lid)
            elif isinstance(n, ast.Call):
                k = self._classify_call(n, cls)
                if k is not None:
                    kind, lid, _msg = k
                    if kind == "wait" and lid is not None:
                        waits.add(lid)
                    else:
                        blocking_other = True
                f = n.func
                if isinstance(f, ast.Name):
                    calls.add(f.id)
                elif isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self" and cls is not None:
                    calls.add(f"{cls}.{f.attr}")
        return _FnSummary(key, fn, cls, acquires, blocking_other, waits,
                          calls)

    @staticmethod
    def _walk_scope(fn_node: ast.AST):
        """Walk WITHOUT descending into nested defs/classes (each is
        summarized separately; a worker closure's waits are its own)."""
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _resolve(self, caller: _FnSummary, name: str) -> Optional[_FnSummary]:
        """Callee summary for a call recorded by _summarize_fn
        ("Cls.meth" from self.meth calls, bare module-function names)."""
        return self.fns.get(name)

    def _callee_for(self, s: _FnSummary, call: ast.Call
                    ) -> Optional[_FnSummary]:
        """Callee summary for a call expression inside `s`."""
        d = _dotted(call.func)
        if d is None:
            return None
        if d.startswith("self.") and s.cls is not None:
            return self.fns.get(f"{s.cls}.{d[len('self.'):]}")
        if "." not in d:
            return self.fns.get(d)
        return None

    def _close_summaries(self) -> None:
        """Propagate acquires/blocking through local calls to fixpoint
        (native.get_lib -> _build -> subprocess.run is two hops)."""
        changed = True
        while changed:
            changed = False
            for key, s in list(self.fns.items()):
                acq, waits = set(s.acquires), set(s.waits)
                blk = s.blocking_other
                for cname in s.calls:
                    callee = self._resolve(s, cname)
                    if callee is None:
                        continue
                    acq |= callee.acquires
                    waits |= callee.waits
                    blk = blk or callee.blocking_other
                if acq != s.acquires or blk != s.blocking_other \
                        or waits != s.waits:
                    self.fns[key] = s._replace(
                        acquires=acq, blocking_other=blk, waits=waits
                    )
                    changed = True

    # ----------------------------------------------------------- rules
    def _scan_fn(self, s: _FnSummary) -> None:
        """Single lexical pass with a held-lock stack, firing
        per-call-lock / blocking-under-lock / lock-order self+cross
        edges and recording attribute writes."""
        cls = s.cls
        is_init = s.qualname.split(".")[-1] in _INIT_METHODS

        def record_write(attr: str, node: ast.AST, held: Tuple[str, ...]):
            if cls is None:
                return
            self.writes.setdefault(cls, {}).setdefault(attr, []).append(
                (node, s.qualname, frozenset(held), is_init)
            )

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return  # nested scopes are scanned as their own fns
            if isinstance(node, ast.With):
                new_held = list(held)
                for item in node.items:
                    lid = self._lock_id(item.context_expr, cls)
                    if lid is None:
                        visit(item.context_expr, tuple(held))
                        continue
                    if lid in new_held:
                        if not self.locks.get(lid, True):
                            self._emit(
                                LOCK_ORDER, item.context_expr,
                                f"non-reentrant lock {lid.split('::')[-1]} "
                                "re-acquired while already held — "
                                "self-deadlock",
                            )
                    else:
                        for h in new_held:
                            self.edges.setdefault(
                                (h, lid), (item.context_expr, s.qualname)
                            )
                        new_held.append(lid)
                for stmt in node.body:
                    visit(stmt, tuple(new_held))
                return
            if isinstance(node, ast.Call):
                kind = self._lock_kind(node)
                if kind is not None and not is_init:
                    self._emit(
                        PER_CALL_LOCK, node,
                        f"threading.{kind}() created in "
                        f"{s.qualname!r} — per-call primitives "
                        "synchronize nothing; create in __init__ "
                        "or at module scope",
                    )
                if held:
                    why = self._is_blocking_call(node, cls, held)
                    callee = self._callee_for(s, node)
                    if why is None and callee is not None:
                        # a callee waiting ONLY on a condition the
                        # caller holds is the coalescing idiom moved
                        # into a helper — still exempt
                        pending = callee.waits - set(held)
                        if callee.blocking_other:
                            why = (f"call to {_dotted(node.func)}() "
                                   "which blocks (transitively)")
                        elif pending:
                            locks = ", ".join(
                                sorted(p.split("::")[-1] for p in pending)
                            )
                            why = (f"call to {_dotted(node.func)}() "
                                   f"which waits on {locks} "
                                   "(transitively)")
                    if why is not None:
                        self._emit(
                            BLOCKING_UNDER_LOCK, node,
                            f"{why} [holding "
                            f"{', '.join(h.split('::')[-1] for h in held)}]",
                        )
                    # cross-method acquisition edges
                    if callee is not None:
                        for lid in callee.acquires:
                            if lid in held:
                                if not self.locks.get(lid, True):
                                    self._emit(
                                        LOCK_ORDER, node,
                                        f"non-reentrant lock "
                                        f"{lid.split('::')[-1]} "
                                        f"re-acquired via "
                                        f"{callee.qualname}() — "
                                        "self-deadlock",
                                    )
                            else:
                                for h in held:
                                    self.edges.setdefault(
                                        (h, lid), (node, s.qualname)
                                    )
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS:
                    recv = node.func.value
                    if (isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == "self"):
                        record_write(recv.attr, node, held)
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if (isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"):
                        record_write(base.attr, node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in ast.iter_child_nodes(s.node):
            visit(child, ())

    def _check_unlocked_writes(self) -> None:
        for cls, attrs in self.writes.items():
            class_locks = {
                lid for lid in self.locks if lid.startswith(f"{cls}::")
            }
            if not class_locks:
                continue
            for attr, events in attrs.items():
                owners = set()
                for _node, _fn, held, in_init in events:
                    if not in_init:
                        owners |= held & class_locks
                if not owners:
                    continue
                for node, fn, held, in_init in events:
                    if in_init or held & owners:
                        continue
                    names = ", ".join(
                        sorted(o.split("::")[-1] for o in owners)
                    )
                    self._emit(
                        UNLOCKED_WRITE, node,
                        f"self.{attr} is written under {names} elsewhere "
                        f"in {cls} but written here ({fn}) without it",
                    )

    # -------------------------------------------- prefetch-thread rules
    def _queue_ctor(self, call: ast.AST) -> Optional[str]:
        """Queue-class leaf when `call` constructs a queue (queue.X(),
        multiprocessing.X(), or a bare imported X())."""
        if not isinstance(call, ast.Call):
            return None
        d = _dotted(call.func)
        if d is None:
            return None
        parts = d.split(".")
        leaf = parts[-1]
        if leaf not in _QUEUE_CTORS:
            return None
        if len(parts) == 1 or parts[0] in ("queue", "multiprocessing"):
            return leaf
        return None

    @staticmethod
    def _queue_bounded(call: ast.Call, leaf: str) -> bool:
        """True when the constructor pins a positive maxsize.
        Non-constant expressions (max(1, depth), a parameter) count as
        bounded — the author made capacity a decision; only a missing
        or literal-0 maxsize is structurally unbounded."""
        if leaf == "SimpleQueue":
            return False
        arg: Optional[ast.AST] = call.args[0] if call.args else None
        for k in call.keywords:
            if k.arg == "maxsize":
                arg = k.value
        if arg is None:
            return False
        if isinstance(arg, ast.Constant) and isinstance(arg.value, int):
            return arg.value > 0
        return True

    @staticmethod
    def _puts_in_scope(fn_node: ast.AST) -> Tuple[bool, bool]:
        """(has_put, put_in_loop) for a function body, not descending
        into nested defs."""
        has_put = False
        in_loop_put = False

        def visit(node: ast.AST, in_loop: bool) -> None:
            nonlocal has_put, in_loop_put
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None and d.split(".")[-1] in _PUT_LEAVES:
                    has_put = True
                    if in_loop:
                        in_loop_put = True
            nxt = in_loop or isinstance(
                node, (ast.For, ast.AsyncFor, ast.While)
            )
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                visit(child, nxt)

        visit(fn_node, False)
        return has_put, in_loop_put

    def _thread_targets(self) -> Dict[str, ast.AST]:
        """fn key -> Thread(...) call node, for every
        threading.Thread(target=<name>|self.<meth>) whose target
        resolves to a module function or sibling method."""
        targets: Dict[str, ast.AST] = {}
        for s in self.fns.values():
            for n in self._walk_scope(s.node):
                if not isinstance(n, ast.Call):
                    continue
                d = _dotted(n.func)
                if d not in ("threading.Thread", "Thread"):
                    continue
                for k in n.keywords:
                    if k.arg != "target":
                        continue
                    td = _dotted(k.value)
                    if td is None:
                        continue
                    if td.startswith("self.") and s.cls is not None:
                        key = f"{s.cls}.{td[len('self.'):]}"
                    else:
                        key = td
                    if key in self.fns:
                        targets.setdefault(key, n)
        return targets

    def _check_prefetch_threads(self) -> None:
        """The two data-plane rules (docs/DATA_PLANE.md prefetch
        contract): a module whose thread target `.put`s inside a loop
        must not construct unbounded queues, and any queue-producer
        thread target must stay JAX-free except for the device_put
        transfer itself."""
        targets = self._thread_targets()
        looping_producer = False
        for key in targets:
            s = self.fns[key]
            has_put, in_loop = self._puts_in_scope(s.node)
            if in_loop:
                looping_producer = True
            if has_put:
                for n in self._walk_scope(s.node):
                    if not isinstance(n, ast.Call):
                        continue
                    d = _dotted(n.func)
                    if d is None:
                        continue
                    if (
                        (d.startswith("jax.") or d.startswith("jnp."))
                        and d != "jax.device_put"
                    ):
                        self._emit(
                            JAX_IN_READER_THREAD, n,
                            f"{d}() on producer thread target "
                            f"{s.qualname!r} — only jax.device_put is "
                            "safe off the main thread",
                        )
        if not looping_producer:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = self._queue_ctor(node)
            if leaf is not None and not self._queue_bounded(node, leaf):
                self._emit(
                    UNBOUNDED_PRODUCER_QUEUE, node,
                    f"{leaf}() constructed without a positive maxsize "
                    "in a module with a looping producer thread — "
                    "bound it so the producer backpressures",
                )

    def _check_lock_order(self) -> None:
        seen: Set[Tuple[str, str]] = set()
        for (a, b), (node, fn) in sorted(
            self.edges.items(), key=lambda kv: kv[1][0].lineno
        ):
            if (b, a) in self.edges and (b, a) not in seen:
                seen.add((a, b))
                other_node, other_fn = self.edges[(b, a)]
                self._emit(
                    LOCK_ORDER, node,
                    f"{a.split('::')[-1]} -> {b.split('::')[-1]} here "
                    f"({fn}) but {b.split('::')[-1]} -> "
                    f"{a.split('::')[-1]} at line {other_node.lineno} "
                    f"({other_fn}) — AB/BA deadlock under concurrent "
                    "callers",
                )

    # ------------------------------------------------------------- run
    def run(self) -> List[Finding]:
        self._collect_locks()
        self._collect_fns()
        self._close_summaries()
        for s in self.fns.values():
            self._scan_fn(s)
        self._check_unlocked_writes()
        self._check_lock_order()
        self._check_prefetch_threads()
        # dedupe (nested walk can visit a call twice through With items)
        uniq: Dict[Tuple[str, int, int, str], Finding] = {}
        for f in self.findings:
            uniq.setdefault((f.rule, f.line, f.col, f.message), f)
        return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.col))


# ----------------------------------------------------------------------
# public API (mirrors lint.py)
def concurrency_lint_paths(paths: Sequence[Path]) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        src = p.read_text()
        tree = ast.parse(src, filename=str(p))
        findings.extend(_ConcurrencyLinter(tree, src, str(p)).run())
    return sorted(findings, key=lambda f: (f.path, f.line, f.col))


def concurrency_lint_package(pkg_root: Optional[str] = None,
                             exclude=("analysis",)) -> List[Finding]:
    """Concurrency-lint every module of the package (root resolution
    and exclusion shared with lint.lint_package via
    iter_package_modules — the two AST passes always scan the same
    file set)."""
    from .lint import iter_package_modules

    files, _root = iter_package_modules(pkg_root, exclude)
    return concurrency_lint_paths(files)


def concurrency_lint_source(src: str, name: str = "fixture"
                            ) -> List[Finding]:
    """Lint a single in-memory module (test fixtures)."""
    tree = ast.parse(src, filename=name)
    return _ConcurrencyLinter(tree, src, name).run()
