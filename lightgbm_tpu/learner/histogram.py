"""Feature-histogram construction.

The reference builds per-(leaf, feature) histograms of (sum_grad,
sum_hess, count) with sequential scatter loops on CPU
(src/io/dense_bin.hpp:99-174 ConstructHistogram) and shared-memory
atomics on CUDA (src/treelearner/cuda/cuda_histogram_constructor.cu).
A TPU has no vector scatter, so scatter-add becomes a one-hot
contraction. Two backends share one data layout:

- **Pallas TPU kernel** (`pallas_hist.hist_tpu`): the one-hot tile only
  ever lives in VMEM, the contraction rides the MXU. Requires the row
  count to be a multiple of `HIST_BLK`.
- **XLA einsum fallback** (CPU tests, virtual meshes, odd row counts):
  same math, one-hot materialized per small row block under `lax.scan`.

Layout: bins are row-major `(N, F)` int32 (rows on sublanes — the
pallas kernel's one-hot compare then needs no lane->sublane relayout);
per-row channels are `(8, N)` f32 rows `(g_hi, g_lo, h_hi, h_lo, count,
0, 0, 0)`. The bf16x2 split (hi = bf16(x), lo = x - hi) lets the MXU run
in bf16 while the recombined histogram keeps ~f32 accuracy — the padded
channel slots are free because the matmul M dim pads 3 -> 8 anyway.
Gradient/hessian are summed per bin exactly like the reference's f64
histograms (hist_t), at float precision like its GPU path (gpu_hist_t,
docs/GPU-Performance.rst accuracy table).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

HIST_BLK = 2048  # pallas row-block; device row padding is a multiple of this
CH = 8


def _use_pallas() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def build_gh8(grad: jax.Array, hess: jax.Array, count: jax.Array) -> jax.Array:
    """(N,) grad/hess/count (already masked) -> (8, N) bf16x2-split channels."""
    g_hi = grad.astype(jnp.bfloat16).astype(jnp.float32)
    g_lo = grad - g_hi
    h_hi = hess.astype(jnp.bfloat16).astype(jnp.float32)
    h_lo = hess - h_hi
    z = jnp.zeros_like(count)
    return jnp.stack([g_hi, g_lo, h_hi, h_lo, count, z, z, z])


def combine_ch(hist8: jax.Array) -> jax.Array:
    """(F, CH, B) accumulated channels -> (F, B, 3) (grad, hess, count)."""
    g = hist8[:, 0, :] + hist8[:, 1, :]
    h = hist8[:, 2, :] + hist8[:, 3, :]
    c = hist8[:, 4, :]
    return jnp.stack([g, h, c], axis=-1)


def _hist_fallback(bins_rm: jax.Array, gh8: jax.Array, num_bins: int,
                   blk: int = 512) -> jax.Array:
    """One-hot einsum under lax.scan; any N (pads to a block multiple)."""
    N, F = bins_rm.shape
    gh3 = jnp.stack(
        [gh8[0] + gh8[1], gh8[2] + gh8[3], gh8[4]], axis=-1
    )  # (N, 3)
    if N % blk != 0:
        pad = blk - N % blk
        bins_rm = jnp.pad(bins_rm, ((0, pad), (0, 0)))
        gh3 = jnp.pad(gh3, ((0, pad), (0, 0)))
        N += pad
    nb = N // blk
    bb = bins_rm.reshape(nb, blk, F)
    gg = gh3.reshape(nb, blk, 3)
    iota = jnp.arange(num_bins, dtype=bins_rm.dtype)

    def body(acc, xs):
        b, g = xs  # (blk, F), (blk, 3)
        onehot = (b[:, :, None] == iota).astype(jnp.float32)  # (blk, F, B)
        acc = acc + jnp.einsum(
            "rfb,rc->fbc", onehot, g, preferred_element_type=jnp.float32
        )
        return acc, None

    init = jnp.zeros((F, num_bins, 3), dtype=jnp.float32)
    hist, _ = lax.scan(body, init, (bb, gg))
    return hist


def histogram(bins_rm: jax.Array, gh8: jax.Array, num_bins: int) -> jax.Array:
    """(N, F) int32 bins + (8, N) channels -> (F, B, 3) f32 histogram."""
    N, F = bins_rm.shape
    if _use_pallas() and N % HIST_BLK == 0 and N >= HIST_BLK:
        from .pallas_hist import hist_tpu

        return combine_ch(hist_tpu(bins_rm, gh8, num_bins))
    return _hist_fallback(bins_rm, gh8, num_bins)


def gather_rows(bins_rm: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather rows by index -> (len(idx), F). Out-of-range idx (pad
    slots) fill with bin 0; callers zero their gh so those rows
    contribute nothing."""
    return jnp.take(bins_rm, idx, axis=0, mode="fill", fill_value=0)


def gather_gh8(gh8: jax.Array, idx: jax.Array) -> jax.Array:
    return jnp.take(gh8, idx, axis=1, mode="fill", fill_value=0.0)


def hist_capacities(n_rows: int, min_cap: int = HIST_BLK) -> tuple:
    """Static ladder of gather-buffer sizes: N/2, N/4, ... >= min_cap,
    each rounded up to a HIST_BLK multiple. The smaller child always
    fits in N/2; deep (small) leaves use the small buffers so histogram
    cost tracks leaf size."""

    def _round(c: int) -> int:
        return ((c + HIST_BLK - 1) // HIST_BLK) * HIST_BLK

    caps = []
    c = n_rows // 2
    while c >= min_cap:
        caps.append(_round(c))
        c //= 2
    if not caps:
        caps.append(_round(max(n_rows // 2, 1)))
    return tuple(caps)


def root_sums(gh8: jax.Array, axis_name: Optional[str] = None) -> jax.Array:
    """(sum_grad, sum_hess, count) over all in-bag rows. Globally reduced
    over the data mesh axis when present (reference
    data_parallel_tree_learner.cpp:169-221 root allreduce)."""
    s8 = jnp.sum(gh8, axis=1)
    s = jnp.stack([s8[0] + s8[1], s8[2] + s8[3], s8[4]])
    if axis_name is not None:
        s = lax.psum(s, axis_name)
    return s
