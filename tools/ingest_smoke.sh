#!/usr/bin/env bash
# End-to-end out-of-core ingestion smoke test (docs/DATA_PLANE.md):
# generate synthetic data whose raw footprint exceeds a deliberately
# tiny ram_budget_mb, fit it through data_source=chunked (disk spool →
# streaming two-pass binning → double-buffered device assembly), and
# assert from the run manifest that (1) per-chunk host RSS stayed FLAT
# across the assembly (the bounded-memory contract), (2) the fit is
# bit-identical to the in-RAM path on the same data, and (3) the
# text-file spool path works without loading the file. Runs on the
# CPU backend so it is safe anywhere.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

python - "$WORK" <<'EOF'
import json
import sys

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.data import last_stats, reset_stats
from lightgbm_tpu.obs.manifest import build_manifest

work = sys.argv[1]
rs = np.random.RandomState(7)
n, f = 300_000, 12
X = rs.randn(n, f)
y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + rs.randn(n) * 0.1
raw_mb = X.nbytes / (1 << 20)
budget_mb = 8
assert raw_mb > budget_mb, (raw_mb, budget_mb)

base = dict(objective="regression", num_leaves=31, verbosity=-1,
            seed=3, deterministic=True)

# in-RAM reference
ref = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=10)

# chunked fit under a budget ~1/10 of the raw data
reset_stats()
p = dict(base, data_source="chunked", ram_budget_mb=budget_mb,
         data_spool_dir=f"{work}/spool")
got = lgb.train(p, lgb.Dataset(X, label=y, params=p), num_boost_round=10)

# (2) bit-exact: predictions identical, model text identical modulo
# the parameters: section recording the data-plane params themselves
pr, pg = ref.predict(X[:4096]), got.predict(X[:4096])
assert np.array_equal(pr, pg), "chunked predictions diverged from in-RAM"
strip = lambda s: "\n".join(
    l for l in s.splitlines()
    if not l.startswith(("[data_source", "[ram_budget_mb",
                         "[data_chunk_rows", "[data_spool_dir")))
assert strip(got.model_to_string()) == strip(ref.model_to_string()), \
    "chunked model text diverged from in-RAM"

# (1) flat per-chunk RSS, read back through the run manifest
man = build_manifest(config=p)
dp = man["data_plane"]
asm = dp["assemble"]
assert asm["chunks"] >= 4, asm
spread = asm["rss_spread_mb"]
assert spread <= 64.0, f"steady-state RSS spread {spread} MB is not flat"
print(json.dumps({
    "raw_mb": round(raw_mb, 1),
    "ram_budget_mb": budget_mb,
    "chunks": asm["chunks"],
    "chunk_rows": asm["chunk_rows"],
    "peak_rss_mb": asm["peak_rss_mb"],
    "rss_spread_mb": spread,
    "spool_rows_per_sec": dp["spool"]["rows_per_sec"],
    "bin_rows_per_sec": dp["pass2"]["rows_per_sec"],
}))

# (3) text-file spool: fit a CSV through the chunked path without
# ever holding the parsed matrix
np.savetxt(f"{work}/train.csv",
           np.column_stack([y[:50_000], X[:50_000]]),
           delimiter=",", fmt="%.6g")
reset_stats()
pt = dict(base, data_source="chunked", ram_budget_mb=budget_mb,
          data_chunk_rows=8192, header=False, label_column="0")
bst = lgb.train(pt, lgb.Dataset(f"{work}/train.csv", params=pt),
                num_boost_round=3)
st = last_stats()
assert st["spool"]["rows"] == 50_000, st["spool"]
assert bst.predict(X[:16]).shape == (16,)
print("text-file spool ok:", st["spool"]["chunks"], "chunks")
EOF

echo "ingest smoke: OK"
