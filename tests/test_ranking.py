"""Device LambdaRank + device NDCG (learner/ranking.py).

Gradient values are checked against a literal numpy transcription of
the reference GetGradientsForOneQuery (rank_objective.hpp:182-271,
including the norm path's (0.01+|ds|) regularization and the
log2(1+sum)/sum rescale); NDCG against the host metric; end-to-end
ranking trains through the FUSED loop and learns."""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
import lightgbm_tpu.callback as cbm
from lightgbm_tpu.learner.ranking import (
    build_query_layout,
    default_label_gain,
    inverse_max_dcg,
    lambdarank_gradients,
    ndcg_at,
)


def _oracle_one_query(score, label, lg, imd, sigmoid, trunc, norm):
    """Literal port of GetGradientsForOneQuery."""
    cnt = len(score)
    lam = np.zeros(cnt)
    hes = np.zeros(cnt)
    order = sorted(range(cnt), key=lambda a: -score[a])
    best, worst = score[order[0]], score[order[cnt - 1]]
    sum_lambdas = 0.0
    for i in range(min(cnt - 1, trunc)):
        for j in range(i + 1, cnt):
            if label[order[i]] == label[order[j]]:
                continue
            hr, lr = (i, j) if label[order[i]] > label[order[j]] else (j, i)
            high, low = order[hr], order[lr]
            ds = score[high] - score[low]
            dndcg = (
                abs(lg[int(label[high])] - lg[int(label[low])])
                * abs(1 / np.log2(hr + 2.0) - 1 / np.log2(lr + 2.0))
                * imd
            )
            if norm and best != worst:
                dndcg /= 0.01 + abs(ds)
            p = 1.0 / (1.0 + np.exp(sigmoid * ds))
            ph = p * (1.0 - p)
            pl = -sigmoid * dndcg * p
            ph = sigmoid * sigmoid * dndcg * ph
            lam[low] -= pl
            hes[low] += ph
            lam[high] += pl
            hes[high] += ph
            sum_lambdas -= 2 * pl
    if norm and sum_lambdas > 0:
        f = np.log2(1 + sum_lambdas) / sum_lambdas
        lam *= f
        hes *= f
    return lam, hes


@pytest.mark.parametrize("norm", [True, False])
def test_lambdarank_gradients_match_reference_oracle(norm):
    rs = np.random.RandomState(0)
    group = np.asarray([7, 3, 12, 1, 5])
    n = int(group.sum())
    npad = 32
    label = np.zeros(npad)
    label[:n] = rs.randint(0, 4, n)
    score = np.zeros(npad, np.float32)
    score[:n] = rs.randn(n)
    lg = default_label_gain(3)
    layout = build_query_layout(group, npad)
    imd = inverse_max_dcg(label, layout, lg, trunc := 20)

    g, h = lambdarank_gradients(
        layout, jnp.asarray(score), jnp.asarray(label, jnp.float32),
        jnp.asarray(lg, jnp.float32), jnp.asarray(imd, jnp.float32),
        sigmoid=2.0, truncation_level=trunc, norm=norm,
    )
    g, h = np.asarray(g), np.asarray(h)

    qb = np.concatenate([[0], np.cumsum(group)])
    for q in range(len(group)):
        lo, hi = qb[q], qb[q + 1]
        eg, eh = _oracle_one_query(
            score[lo:hi].astype(np.float64), label[lo:hi], lg, imd[q],
            2.0, trunc, norm,
        )
        np.testing.assert_allclose(g[lo:hi], eg, rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(h[lo:hi], eh, rtol=2e-4, atol=1e-6)
    assert np.all(g[n:] == 0) and np.all(h[n:] == 0)


def test_device_ndcg_matches_host_metric():
    rs = np.random.RandomState(1)
    group = np.asarray([10, 4, 8, 6])
    n = int(group.sum())
    npad = 32
    label = np.zeros(npad)
    label[:n] = rs.randint(0, 3, n)
    score = np.zeros(npad, np.float32)
    score[:n] = rs.randn(n)
    lg = default_label_gain(2)
    layout = build_query_layout(group, npad)

    vals = np.asarray(ndcg_at(
        layout, jnp.asarray(score), jnp.asarray(label, jnp.float32),
        jnp.asarray(lg, jnp.float32), [1, 3, 5],
    ))

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import NDCGMetric

    m = NDCGMetric(Config({"eval_at": [1, 3, 5]}))
    m.init(label[:n], None, group)
    host = m.eval(score[:n].astype(np.float64))
    for (nm, hv, _), dv in zip(host, vals):
        np.testing.assert_allclose(dv, hv, rtol=1e-5, atol=1e-6)


def _rank_problem(nq=60, seed=3):
    rs = np.random.RandomState(seed)
    sizes = rs.randint(5, 25, nq)
    n = int(sizes.sum())
    X = rs.randn(n, 6)
    w = rs.randn(6)
    rel = X @ w + 0.5 * rs.randn(n)
    label = np.zeros(n)
    # per-query relevance quartiles -> graded labels 0..3
    qb = np.concatenate([[0], np.cumsum(sizes)])
    for q in range(nq):
        r = rel[qb[q]:qb[q + 1]]
        label[qb[q]:qb[q + 1]] = np.digitize(r, np.quantile(r, [0.5, 0.75, 0.9]))
    return X, label, sizes


def test_lambdarank_end_to_end_fused():
    X, y, group = _rank_problem()
    ds = lgb.Dataset(X, label=y, group=group, free_raw_data=False)
    bst = lgb.train(
        {"objective": "lambdarank", "metric": "ndcg", "eval_at": [5],
         "num_leaves": 15, "learning_rate": 0.1, "verbosity": -1,
         "min_data_in_leaf": 5},
        ds, num_boost_round=20,
        valid_sets=[ds], valid_names=["t"],
    )
    # ranking must now be fused-eligible (device grads + device ndcg)
    assert bst._gbdt.fused_eligible()
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import NDCGMetric

    m = NDCGMetric(Config({"eval_at": [5]}))
    m.init(y, None, group)
    before = m.eval(np.zeros(len(y)))[0][1]
    after = m.eval(bst.predict(X))[0][1]
    assert after > before + 0.15, (before, after)


def test_lambdarank_document_weights_scale_gradients():
    """RankingObjective::GetGradients multiplies lambdas/hessians by the
    per-document weights (rank_objective.hpp:84-90)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objectives import create_objective

    X, y, group = _rank_problem(nq=10, seed=5)
    rs = np.random.RandomState(6)
    w = 0.5 + rs.rand(len(y))

    def grads(weight):
        ds = lgb.Dataset(X, label=y, group=group, weight=weight,
                         free_raw_data=False)
        ds.construct()
        obj = create_objective(Config({"objective": "lambdarank"}))
        obj.init(ds._binned)
        npad = ds._binned.num_rows_padded()
        import jax.numpy as jnp

        return obj.get_gradients(jnp.zeros(npad, jnp.float32))

    g0, h0 = grads(None)
    gw, hw = grads(w)
    n = len(y)
    wp = np.zeros(np.asarray(g0).shape)
    wp[:n] = w
    np.testing.assert_allclose(np.asarray(gw), np.asarray(g0) * wp,
                               rtol=1e-5, atol=1e-7)
    # hessians: compare where the pre-floor value dominates (docs in no
    # pair sit at the 2e-7 floor in both runs regardless of weight)
    h0n, hwn = np.asarray(h0)[:n], np.asarray(hw)[:n]
    live = h0n > 1e-6
    assert live.any()
    np.testing.assert_allclose(hwn[live], h0n[live] * w[live],
                               rtol=1e-5, atol=1e-7)


def test_lambdarank_sklearn():
    X, y, group = _rank_problem(seed=9)
    rk = lgb.LGBMRanker(n_estimators=8, num_leaves=7, verbosity=-1,
                        min_data_in_leaf=5)
    rk.fit(X, y, group=group)
    assert np.isfinite(rk.predict(X)).all()


def test_rank_xendcg_trains_and_learns():
    X, y, group = _rank_problem(nq=50, seed=13)
    ds = lgb.Dataset(X, label=y, group=group, free_raw_data=False)
    bst = lgb.train(
        {"objective": "rank_xendcg", "metric": "ndcg", "eval_at": [5],
         "num_leaves": 15, "learning_rate": 0.1, "verbosity": -1,
         "min_data_in_leaf": 5},
        ds, num_boost_round=25,
        valid_sets=[ds], valid_names=["t"],
    )
    assert bst._gbdt.fused_eligible()
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import NDCGMetric

    m = NDCGMetric(Config({"eval_at": [5]}))
    m.init(y, None, group)
    before = m.eval(np.zeros(len(y)))[0][1]
    after = m.eval(bst.predict(X))[0][1]
    assert after > before + 0.1, (before, after)


def test_xentlambda_weighted_and_unweighted():
    rs = np.random.RandomState(4)
    X = rs.randn(1500, 5)
    w = rs.randn(5)
    y = 1.0 / (1.0 + np.exp(-(X @ w)))  # probabilistic labels in [0,1]
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "xentlambda", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=15)
    pred = bst.predict(X)  # normalized exponential parameter (>0)
    assert (pred > 0).all()
    # with unit weights the gradient reduces to plain cross-entropy:
    # implied probability 1-exp(-pred) should track the labels
    p = 1.0 - np.exp(-pred)
    assert np.corrcoef(p, y)[0, 1] > 0.9

    wts = 0.5 + rs.rand(1500)
    ds2 = lgb.Dataset(X, label=y, weight=wts, free_raw_data=False)
    b2 = lgb.train({"objective": "xentlambda", "num_leaves": 15,
                    "verbosity": -1}, ds2, num_boost_round=5)
    assert np.isfinite(b2.predict(X)).all()


def test_lambdarank_position_bias():
    """Position debiasing (rank_objective.hpp:302): with click-style
    labels biased toward early positions, the learned per-position bias
    factors must be (roughly) decreasing in position."""
    rs = np.random.RandomState(3)
    n_q, docs = 120, 8
    n = n_q * docs
    rel = rs.randint(0, 3, n).astype(np.float64)  # true relevance
    pos = np.tile(np.arange(docs), n_q)
    # observed label: relevance observed only when the position is seen
    seen = rs.rand(n) < (1.0 / (1.0 + 0.7 * pos))
    label = np.where(seen, rel, 0.0)
    X = rs.randn(n, 5)
    X[:, 0] += rel  # informative feature
    group = np.full(n_q, docs)

    ds = lgb.Dataset(X, label=label, group=group, position=pos,
                     free_raw_data=False)
    bst = lgb.train(
        {"objective": "lambdarank", "num_leaves": 7, "min_data_in_leaf": 3,
         "lambdarank_position_bias_regularization": 0.5, "verbosity": -1},
        ds, num_boost_round=10,
    )
    biases = np.asarray(bst._gbdt.objective.position_biases)
    assert biases.shape == (docs,)
    assert np.any(biases != 0.0)
    # later positions get lower (more negative) bias factors
    assert biases[0] > biases[-1]


def test_device_map_matches_host_metric():
    from lightgbm_tpu.learner.ranking import map_at

    rs = np.random.RandomState(2)
    group = np.asarray([10, 4, 8, 6])
    n = int(group.sum())
    npad = 32
    label = np.zeros(npad)
    label[:n] = (rs.rand(n) > 0.6).astype(float)
    score = np.zeros(npad, np.float32)
    score[:n] = rs.randn(n)
    layout = build_query_layout(group, npad)

    vals = np.asarray(map_at(
        layout, jnp.asarray(score), jnp.asarray(label, jnp.float32),
        [1, 3, 5],
    ))

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import MapMetric

    m = MapMetric(Config({"eval_at": [1, 3, 5]}))
    m.init(label[:n], None, group)
    host = m.eval(score[:n].astype(np.float64))
    for (nm, hv, _), dv in zip(host, vals):
        np.testing.assert_allclose(dv, hv, rtol=1e-5, atol=1e-6,
                                   err_msg=nm)


def test_map_metric_stays_fused():
    """metric=map must keep lambdarank configs on the fused device loop
    (VERDICT r3: host-only metrics silently fell off it)."""
    X, y, group = _rank_problem()
    params = dict(objective="lambdarank", num_leaves=15, min_data_in_leaf=3,
                  metric="map", eval_at=[3, 5], verbosity=-1,
                  lambdarank_position_bias=False)
    params = {k: v for k, v in params.items()
              if k != "lambdarank_position_bias"}
    ds = lgb.Dataset(X, label=y, group=group, free_raw_data=False)
    evals = {}
    bst = lgb.train(params, ds, num_boost_round=8,
                    valid_sets=[ds], valid_names=["tr"],
                    callbacks=[cbm.record_evaluation(evals)])
    assert bst._gbdt.fused_eligible()
    assert "map@3" in evals["tr"] and len(evals["tr"]["map@3"]) == 8
    assert evals["tr"]["map@5"][-1] > evals["tr"]["map@5"][0] - 1e-9
