"""Vectorized best-split search over (feature, threshold, missing-direction).

Reimplements the split-gain math of the reference threshold scan
(src/treelearner/feature_histogram.hpp:832 FindBestThresholdSequentially,
CUDA analog src/treelearner/cuda/cuda_best_split_finder.cu) as cumulative
sums over the bin axis plus a masked argmax — no sequential per-bin loop:

- L1/L2 regularization via ThresholdL1 soft-thresholding
  (feature_histogram.hpp GetLeafGain/CalculateSplittedLeafOutput),
- missing-value handling: NaN bin is the last bin of a feature; both
  default directions are evaluated (the reference's double scan),
- categorical features use one-vs-rest splits (bin == t goes left);
  the sorted-subset search (feature_histogram.hpp:449) is a later
  milestone,
- min_data_in_leaf / min_sum_hessian_in_leaf / min_gain_to_split masks,
- monotone-constraint candidate masking (basic method),
- tie-break: argmax over arrays laid out (dir, F, B) flattened picks the
  lowest flat index, matching the reference's first-feature-wins
  strictly-greater update order.

Gains are stored shifted by (parent_gain + min_gain_to_split) so that
"> 0" means a valid improving split, as in the reference SplitInfo.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

# plain float (NOT jnp.float32): a module-level device constant would
# initialize the jax backend at import time — which contacts the TPU
# tunnel before the CLI can steer the run onto another platform
NEG_INF = -1e30
K_EPSILON = 1e-15  # reference kEpsilon (meta.h)


class SplitParams(NamedTuple):
    """Dynamic (traced) split hyper-parameters."""

    lambda_l1: jax.Array
    lambda_l2: jax.Array
    min_data_in_leaf: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    min_gain_to_split: jax.Array
    max_delta_step: jax.Array
    path_smooth: jax.Array
    # categorical sorted-subset params (feature_histogram.hpp:449+)
    cat_smooth: jax.Array
    cat_l2: jax.Array
    max_cat_threshold: jax.Array  # int32
    max_cat_to_onehot: jax.Array  # int32
    min_data_per_group: jax.Array
    # CEGB (cost_effective_gradient_boosting.hpp:79 DeltaGain)
    cegb_tradeoff: jax.Array
    cegb_penalty_split: jax.Array
    # per-node feature sampling rate (ColSampler feature_fraction_bynode)
    feature_fraction_bynode: jax.Array


class SplitRecord(NamedTuple):
    """Best split for one leaf (reference split_info.hpp:22 SplitInfo)."""

    gain: jax.Array  # f32, shifted; <=0 means no valid split
    feature: jax.Array  # int32, used-feature index
    bin: jax.Array  # int32 threshold bin (or category bin for 1-vs-rest)
    default_left: jax.Array  # bool
    is_cat: jax.Array  # bool
    cat_mask: jax.Array  # (B,) bool — cat bins going LEFT (subset splits)
    left_g: jax.Array
    left_h: jax.Array
    left_c: jax.Array
    right_g: jax.Array
    right_h: jax.Array
    right_c: jax.Array


def threshold_l1(s: jax.Array, l1: jax.Array) -> jax.Array:
    """reference feature_histogram.hpp ThresholdL1."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


BIG = 1e29  # constraint sentinel (comfortably inside f32)


def leaf_output(
    g: jax.Array,
    h: jax.Array,
    p: SplitParams,
    count: Optional[jax.Array] = None,
    parent_output: Optional[jax.Array] = None,
    cmin: Optional[jax.Array] = None,
    cmax: Optional[jax.Array] = None,
) -> jax.Array:
    """CalculateSplittedLeafOutput (feature_histogram.hpp): -T(G)/(H+l2),
    clipped by max_delta_step, then path smoothing
    out*n/(n+ps) + parent*ps/(n+ps) when count/parent are given, then
    clamped to the leaf's monotone-constraint interval [cmin, cmax]."""
    out = -threshold_l1(g, p.lambda_l1) / (h + p.lambda_l2 + K_EPSILON)
    out = jnp.where(
        p.max_delta_step > 0.0,
        jnp.clip(out, -p.max_delta_step, p.max_delta_step),
        out,
    )
    if count is not None and parent_output is not None:
        denom = count + p.path_smooth
        sm = (out * count + parent_output * p.path_smooth) / jnp.maximum(
            denom, K_EPSILON
        )
        out = jnp.where(p.path_smooth > 0.0, sm, out)
    if cmin is not None:
        out = jnp.clip(out, cmin, cmax)
    return out


def leaf_gain_given_output(g, h, p: SplitParams, output) -> jax.Array:
    """GetLeafGainGivenOutput: -(2 T(G) o + (H+l2) o^2)."""
    t = threshold_l1(g, p.lambda_l1)
    return -(2.0 * t * output + (h + p.lambda_l2) * output * output)


def leaf_gain(
    g: jax.Array,
    h: jax.Array,
    p: SplitParams,
    count: Optional[jax.Array] = None,
    parent_output: Optional[jax.Array] = None,
    cmin: Optional[jax.Array] = None,
    cmax: Optional[jax.Array] = None,
) -> jax.Array:
    """GetLeafGain: the closed form T(G)^2/(H+l2) when no output
    modifier is active; otherwise GetLeafGainGivenOutput at the
    clipped/smoothed/clamped output (the reference's USE_MAX_OUTPUT /
    USE_SMOOTHING / constraint template branches)."""
    t = threshold_l1(g, p.lambda_l1)
    free = t * t / (h + p.lambda_l2 + K_EPSILON)
    o = leaf_output(g, h, p, count, parent_output, cmin, cmax)
    given = leaf_gain_given_output(g, h, p, o)
    active = p.max_delta_step > 0.0
    if count is not None and parent_output is not None:
        active = active | (p.path_smooth > 0.0)
    if cmin is not None:
        active = active | (cmin > -BIG) | (cmax < BIG)
    return jnp.where(active, given, free)


def _cat_subset_scan(g, h, c, num_bins, nan_bin, is_cat, sum_g, sum_h, sum_c,
                     params, parent_output, cmin, cmax):
    """Sorted-subset categorical split search (feature_histogram.cpp:246+
    FindBestThresholdCategoricalInner, non-onehot branch), vectorized over
    features with the per-bin scan expressed as cumulative sums:

    - valid bins: count >= cat_smooth (the reference compares the
      hessian-estimated count; we have exact counts),
    - stable sort by g/(h + cat_smooth) ascending,
    - two scans (ascending / descending prefixes), prefix length capped
      at max_num_cat = min(max_cat_threshold, (used+1)/2),
    - l2 + cat_l2 regularization,
    - min_data_per_group batching: gain is only evaluated when at least
      min_data_per_group rows accumulated since the last evaluation
      (sequential reset -> lax.scan over the bin axis),
    - break conditions (right side too small) are monotone in the prefix
      length, so they become masks.

    Returns (gains (F, B, 2), ok (F, B, 2), sums (3, F, B, 2),
    inv_rank (F, B), valid_bin (F, B)); direction 0 = ascending prefix,
    1 = descending. The left set for candidate (f, i, dir) is
    {b : valid_bin[f,b] and (inv_rank[f,b] <= i if dir==0 else
    inv_rank[f,b] >= used[f]-1-i)}.
    """
    from jax import lax

    F, B = g.shape
    bidx = jnp.arange(B)[None, :]
    valid_bin = (
        (c >= params.cat_smooth)
        & is_cat[:, None]
        & (bidx < num_bins[:, None])
        # the NaN bin is not a category: prediction (host Tree / device
        # traversal via the same mask) always routes missing right
        & (bidx != nan_bin[:, None])
    )
    ratio = jnp.where(valid_bin, g / (h + params.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1, stable=True)  # (F, B) invalid last
    inv_rank = jnp.argsort(order, axis=1)  # rank of each bin in the sort
    used = jnp.sum(valid_bin, axis=1).astype(jnp.int32)  # (F,)

    vf = jnp.take_along_axis(valid_bin, order, axis=1)
    sg = jnp.where(vf, jnp.take_along_axis(g, order, axis=1), 0.0)
    sh = jnp.where(vf, jnp.take_along_axis(h, order, axis=1), 0.0)
    sc = jnp.where(vf, jnp.take_along_axis(c, order, axis=1), 0.0)

    # direction 0: ascending prefixes; direction 1: descending prefixes
    sg2 = jnp.stack([sg, sg[:, ::-1]], axis=-1)  # (F, B, 2)
    sh2 = jnp.stack([sh, sh[:, ::-1]], axis=-1)
    sc2 = jnp.stack([sc, sc[:, ::-1]], axis=-1)
    # descending prefixes start from the END of the VALID region: roll the
    # reversed arrays so sorted-last valid bins come first
    shift = (B - used)[:, None, None]
    idx = (jnp.arange(B)[None, :, None] + shift) % B
    sg2 = sg2.at[:, :, 1].set(jnp.take_along_axis(sg2[:, :, 1:2], idx, axis=1)[:, :, 0])
    sh2 = sh2.at[:, :, 1].set(jnp.take_along_axis(sh2[:, :, 1:2], idx, axis=1)[:, :, 0])
    sc2 = sc2.at[:, :, 1].set(jnp.take_along_axis(sc2[:, :, 1:2], idx, axis=1)[:, :, 0])

    lg = jnp.cumsum(sg2, axis=1)
    lh = jnp.cumsum(sh2, axis=1) + K_EPSILON
    lc = jnp.cumsum(sc2, axis=1)
    rg = sum_g - lg
    rh = sum_h - lh
    rc = sum_c - lc

    i_idx = jnp.arange(B, dtype=jnp.int32)[None, :, None]
    max_num_cat = jnp.minimum(params.max_cat_threshold, (used[:, None, None] + 1) // 2)
    pos_ok = (i_idx < max_num_cat) & (i_idx < used[:, None, None])

    # continue conditions (skip eval, keep accumulating group)
    c2 = (lc < params.min_data_in_leaf) | (lh < params.min_sum_hessian_in_leaf)
    # break conditions (monotone in i): stop this direction entirely
    brk = (
        (rc < params.min_data_in_leaf)
        | (rc < params.min_data_per_group)
        | (rh < params.min_sum_hessian_in_leaf)
    )
    brk = jnp.cumsum(brk.astype(jnp.int32), axis=1) > 0

    # min_data_per_group batching: sequential reset per (feature, dir)
    def step(grp, x):
        sc_i, skip_i, brk_i = x
        grp = grp + sc_i
        do_eval = (~skip_i) & (~brk_i) & (grp >= params.min_data_per_group)
        return jnp.where(do_eval, 0.0, grp), do_eval

    xs = (
        jnp.moveaxis(sc2, 1, 0),  # (B, F, 2)
        jnp.moveaxis(c2, 1, 0),
        jnp.moveaxis(brk, 1, 0),
    )
    _, do_eval = lax.scan(step, jnp.zeros((F, 2)), xs)
    do_eval = jnp.moveaxis(do_eval, 0, 1)  # (F, B, 2)

    cat_params = params._replace(lambda_l2=params.lambda_l2 + params.cat_l2)
    gains = leaf_gain(
        lg, lh, cat_params, lc, parent_output, cmin, cmax
    ) + leaf_gain(rg, rh, cat_params, rc, parent_output, cmin, cmax)
    ok = do_eval & pos_ok
    return gains, ok, jnp.stack([lg, lh, lc]), inv_rank, valid_bin, used


def best_split(
    hist: jax.Array,  # (3, F, B) f32 — (grad, hess, count) channels
    sum_g: jax.Array,
    sum_h: jax.Array,
    sum_c: jax.Array,
    num_bins: jax.Array,  # (F,) int32
    nan_bin: jax.Array,  # (F,) int32, -1 if feature has no NaN bin
    mono: jax.Array,  # (F,) int32 in {-1, 0, 1}
    is_cat: jax.Array,  # (F,) bool
    params: SplitParams,
    feat_mask: Optional[jax.Array] = None,  # (F,) bool — ColSampler feature_fraction
    cat_subset: bool = False,  # static: dataset has large-cardinality cats
    parent_output: jax.Array = 0.0,  # the leaf's current output (smoothing)
    cmin: jax.Array = -BIG,  # monotone-constraint interval of the leaf
    cmax: jax.Array = BIG,
    penalty: Optional[jax.Array] = None,  # (F,) — CEGB DeltaGain subtraction
    rand_bin: Optional[jax.Array] = None,  # (F,) — extra_trees: the single
    # numerical threshold candidate per feature (random per node)
) -> SplitRecord:
    """Find the best split of a leaf with given histogram and totals."""
    return _best_split_impl(
        hist, sum_g, sum_h, sum_c, num_bins, nan_bin, mono, is_cat, params,
        feat_mask, cat_subset, parent_output, cmin, cmax, penalty, rand_bin,
    )[0]


def feature_best_gains(
    hist, sum_g, sum_h, sum_c, num_bins, nan_bin, mono, is_cat, params,
    feat_mask=None, cat_subset: bool = False, parent_output=0.0,
    cmin=-BIG, cmax=BIG,
):
    """Per-feature best (shifted) gain: max over thresholds/directions.

    The local-gain vote of the voting-parallel learner
    (voting_parallel_tree_learner.cpp:353 local top-k proposals) —
    computed on the LOCAL (un-reduced) histogram."""
    return _best_split_impl(
        hist, sum_g, sum_h, sum_c, num_bins, nan_bin, mono, is_cat, params,
        feat_mask, cat_subset, parent_output, cmin, cmax,
    )[1]


def _best_split_impl(
    hist, sum_g, sum_h, sum_c, num_bins, nan_bin, mono, is_cat, params,
    feat_mask, cat_subset: bool, parent_output, cmin, cmax,
    penalty=None, rand_bin=None,
):
    _, F, B = hist.shape
    g = hist[0]
    h = hist[1]
    c = hist[2]
    bin_idx = jnp.arange(B, dtype=jnp.int32)[None, :]  # (1, B)

    has_nan = (nan_bin >= 0)[:, None]  # (F, 1)
    nan_g = jnp.where(has_nan[:, 0], jnp.take_along_axis(g, jnp.maximum(nan_bin, 0)[:, None], axis=1)[:, 0], 0.0)[:, None]
    nan_h = jnp.where(has_nan[:, 0], jnp.take_along_axis(h, jnp.maximum(nan_bin, 0)[:, None], axis=1)[:, 0], 0.0)[:, None]
    nan_c = jnp.where(has_nan[:, 0], jnp.take_along_axis(c, jnp.maximum(nan_bin, 0)[:, None], axis=1)[:, 0], 0.0)[:, None]

    # ---- numerical: cumulative left sums, threshold t keeps bins <= t left.
    cg = jnp.cumsum(g, axis=1)
    ch = jnp.cumsum(h, axis=1)
    cc = jnp.cumsum(c, axis=1)

    def eval_lr(lg, lh, lc):
        rg = sum_g - lg
        rh = sum_h - lh
        rc = sum_c - lc
        gains = leaf_gain(
            lg, lh, params, lc, parent_output, cmin, cmax
        ) + leaf_gain(rg, rh, params, rc, parent_output, cmin, cmax)
        ok = (
            (lc >= params.min_data_in_leaf)
            & (rc >= params.min_data_in_leaf)
            & (lh >= params.min_sum_hessian_in_leaf)
            & (rh >= params.min_sum_hessian_in_leaf)
        )
        # monotone basic: candidate-level output ordering
        lo = leaf_output(lg, lh, params, lc, parent_output, cmin, cmax)
        ro = leaf_output(rg, rh, params, rc, parent_output, cmin, cmax)
        m = mono[:, None]
        ok &= jnp.where(m > 0, lo <= ro, True)
        ok &= jnp.where(m < 0, lo >= ro, True)
        return gains, ok, (lg, lh, lc)

    # NaN bin (last bin) is never <= t for valid t, so cum excludes it.
    # default right: missing stays right.
    gain_dr, ok_dr, _ = eval_lr(cg, ch, cc)
    # default left: NaN bin mass joins the left side.
    gain_dl, ok_dl, _ = eval_lr(cg + nan_g, ch + nan_h, cc + nan_c)
    # only evaluate the default-left variant when the feature has a NaN bin
    ok_dl &= has_nan

    # threshold validity: t in [0, num_bin-2], excluding the NaN bin itself
    last_real = jnp.where(nan_bin[:, None] >= 0, num_bins[:, None] - 2, num_bins[:, None] - 1)
    t_ok = bin_idx < last_real
    num_mask = (~is_cat)[:, None] & t_ok
    ok_dr &= num_mask
    ok_dl &= num_mask

    # ---- categorical one-vs-rest: bin t alone goes left. With the
    # sorted-subset path enabled, one-hot applies only to features with
    # num_bin <= max_cat_to_onehot (feature_histogram.cpp:182 use_onehot);
    # without it (legacy callers) every categorical stays one-vs-rest.
    gain_cat, ok_cat, _ = eval_lr(g, h, c)
    ok_cat &= (
        is_cat[:, None]
        & (bin_idx < num_bins[:, None])
        & (bin_idx != nan_bin[:, None])
    )
    if cat_subset:
        ok_cat &= (num_bins <= params.max_cat_to_onehot)[:, None]

    if rand_bin is not None:
        # extra_trees: one random numerical threshold per feature per
        # node (col_sampler / feature_histogram extra-trees scan); the
        # categorical directions keep their full search. Applied in
        # ORIGINAL bin space, before the tie-break reindexing below.
        rb_ok = bin_idx == rand_bin[:, None]
        ok_dr &= rb_ok
        ok_dl &= rb_ok

    parent_gain_plain = leaf_gain(sum_g, sum_h, params)
    parent_gain = jnp.where(
        params.path_smooth > 0.0,
        leaf_gain_given_output(sum_g, sum_h, params, parent_output),
        parent_gain_plain,
    )
    shift = parent_gain + params.min_gain_to_split

    # ---- tie-breaking mirrors the reference scan order exactly
    # (feature_histogram.hpp:396-441 FindBestThresholdSequentially):
    # the REVERSE scan runs first (t descending -> on equal gain the
    # HIGHEST threshold wins, and it owns the default-left direction),
    # the forward scan second and replacing only on strictly greater
    # gain; missing-type-None features run ONLY the reverse scan. We
    # express this inside one argmax by reindexing the bin axis so the
    # preferred candidate of any tie has the lowest flat index: the
    # default-left direction is stored bin-flipped and stacked first,
    # and the default-right direction is bin-flipped for features with
    # no NaN bin (whose single reference scan is the reverse one).
    no_nan = ~has_nan  # (F, 1)
    bin_rev = jnp.clip(last_real - 1 - bin_idx, 0, B - 1)  # (F, B)

    def flipb(a):
        return jnp.take_along_axis(a, bin_rev, axis=1)

    gain_dl_s = flipb(gain_dl)
    ok_dl_s = flipb(ok_dl)
    gain_dr_s = jnp.where(no_nan, flipb(gain_dr), gain_dr)
    ok_dr_s = jnp.where(no_nan, flipb(ok_dr), ok_dr)

    # stack: dir axis LAST in flat order (F, B, D) so ties break on
    # feature, then (reindexed) bin, then
    # (dl, dr, cat[, cat_asc, cat_desc]). Categorical-subset deviation
    # from the reference on EXACT float ties only: it scans all
    # ascending subset prefixes before any descending one
    # (feature_histogram.cpp:276), while this order interleaves
    # directions per prefix length.
    dirs = [gain_dl_s, gain_dr_s, gain_cat]
    oks = [ok_dl_s, ok_dr_s, ok_cat]
    if cat_subset:
        big = is_cat & (num_bins > params.max_cat_to_onehot)
        cs_gain, cs_ok, cs_sums, inv_rank, valid_bin, cs_used = _cat_subset_scan(
            g, h, c, num_bins, nan_bin, big, sum_g, sum_h, sum_c, params,
            parent_output, cmin, cmax,
        )
        dirs += [cs_gain[:, :, 0], cs_gain[:, :, 1]]
        oks += [cs_ok[:, :, 0], cs_ok[:, :, 1]]
    D = len(dirs)
    gains = jnp.stack(dirs, axis=-1) - shift  # (F, B, D)
    ok = jnp.stack(oks, axis=-1)
    if feat_mask is not None:
        ok &= feat_mask[:, None, None]
    gains = jnp.where(ok, gains, NEG_INF)
    if penalty is not None:
        # CEGB DeltaGain (cost_effective_gradient_boosting.hpp:79):
        # per-feature acquisition cost subtracted from every candidate
        gains = gains - penalty[:, None, None]

    flat = gains.reshape(-1)
    idx = jnp.argmax(flat)
    best_gain = flat[idx]
    f = (idx // (B * D)).astype(jnp.int32)
    b = ((idx // D) % B).astype(jnp.int32)
    d = (idx % D).astype(jnp.int32)
    default_left = d == 0
    cat = d >= 2
    # undo the tie-break bin reindexing (numerical dirs only)
    lr_f = last_real[f, 0]
    was_flipped = (d == 0) | ((d == 1) & (nan_bin[f] < 0))
    b = jnp.where(
        was_flipped & ~cat, jnp.clip(lr_f - 1 - b, 0, B - 1), b
    ).astype(jnp.int32)

    lg_num = cg[f, b] + jnp.where(default_left, nan_g[f, 0], 0.0)
    lh_num = ch[f, b] + jnp.where(default_left, nan_h[f, 0], 0.0)
    lc_num = cc[f, b] + jnp.where(default_left, nan_c[f, 0], 0.0)
    lg = jnp.where(cat, g[f, b], lg_num)
    lh = jnp.where(cat, h[f, b], lh_num)
    lc = jnp.where(cat, c[f, b], lc_num)
    # one-hot left set: the single winning bin
    cat_mask = (jnp.arange(B, dtype=jnp.int32) == b) & cat

    if cat_subset:
        is_sub = d >= 3
        asc = d == 3
        lg = jnp.where(is_sub, cs_sums[0, f, b, d - 3], lg)
        lh = jnp.where(is_sub, cs_sums[1, f, b, d - 3], lh)
        lc = jnp.where(is_sub, cs_sums[2, f, b, d - 3], lc)
        rank_f = inv_rank[f]
        sub_mask = jnp.where(
            asc, rank_f <= b, rank_f >= cs_used[f] - 1 - b
        ) & valid_bin[f]
        cat_mask = jnp.where(is_sub, sub_mask, cat_mask)

    rec = SplitRecord(
        gain=best_gain,
        feature=f,
        bin=b,
        default_left=default_left,
        is_cat=cat,
        cat_mask=cat_mask,
        left_g=lg,
        left_h=lh,
        left_c=lc,
        right_g=sum_g - lg,
        right_h=sum_h - lh,
        right_c=sum_c - lc,
    )
    return rec, jnp.max(gains, axis=(1, 2))
