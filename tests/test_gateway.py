"""Serving gateway (lightgbm_tpu/serving/gateway.py, ``task=gateway``,
docs/RESILIENCE.md "Serving gateway").

The tier-1 half of this file is deliberately socket- and sleep-free:
the circuit breaker, hedge budget, jitter schedule, pool ranking,
deadline shed, and /readyz verdict are pure state machines driven by a
fake clock, so they run in milliseconds inside the gate. Everything
that opens a socket, spawns a backend process, or sleeps is marked
``slow``; the fault matrix (kill -9 a backend under concurrent load,
SIGTERM drain with a request in flight, hedging past a stalled
attempt) is additionally ``chaos`` and runs via tools/chaos.sh.
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.metrics import default_registry, record_queue_depth
from lightgbm_tpu.resilience import faultinject
from lightgbm_tpu.resilience.backoff import backoff_delay, full_jitter_delay
from lightgbm_tpu.serving import (
    BackendPool,
    CircuitBreaker,
    Gateway,
    HedgePolicy,
    ModelRegistry,
    gateway_http,
    readiness,
    serve_http,
)
from lightgbm_tpu.serving.gateway import FANOUT_OPS, HEDGED_OPS, IDEMPOTENT_OPS

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _disarm_fault_plan():
    """Chaos tests arm process-global fault plans; none may leak."""
    yield
    faultinject.disarm()


class _Clock:
    """Injectable monotonic clock for the breaker state machine."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ----------------------------------------------------- circuit breaker
def test_breaker_consecutive_trip_and_probe_cycle():
    clk = _Clock()
    seen = []
    br = CircuitBreaker(failures=3, cooldown_s=2.0, now=clk,
                        on_transition=lambda o, n: seen.append((o, n)))
    assert br.state == "closed" and br.allow()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # 2 consecutive < 3
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clk.advance(1.9)
    assert br.state == "open"  # cooldown not elapsed
    clk.advance(0.2)
    assert br.allow()  # aged into half_open, probe slot claimed
    br.record_success()  # probe succeeded
    assert br.state == "closed" and br.allow()
    assert seen == [("closed", "open"), ("open", "half_open"),
                    ("half_open", "closed")]


def test_breaker_half_open_probe_bound_and_reopen():
    clk = _Clock()
    br = CircuitBreaker(failures=1, cooldown_s=1.0, half_open_max=1,
                        now=clk)
    br.record_failure()
    assert br.state == "open"
    clk.advance(1.0)
    assert br.allow()       # the one probe slot
    assert not br.allow()   # bounded: no second concurrent probe
    br.record_failure()     # probe failed -> open, cooldown restarts
    assert br.state == "open"
    clk.advance(0.6)
    assert br.state == "open"  # restarted cooldown not elapsed
    clk.advance(0.6)
    assert br.state == "half_open"


def test_breaker_error_rate_trip():
    clk = _Clock()
    br = CircuitBreaker(failures=100, error_rate=0.5, window=10,
                        cooldown_s=1.0, now=clk)
    # alternate fail/success: consecutive never accumulates, and the
    # window is not full until the 10th sample, so the breaker holds
    for _ in range(5):
        br.record_failure()
        br.record_success()
    assert br.state == "closed"
    # 11th sample evicts the oldest; window is now 5/10 failed >= 0.5
    br.record_failure()
    assert br.state == "open"


def test_breaker_cancel_is_neutral():
    clk = _Clock()
    br = CircuitBreaker(failures=1, cooldown_s=1.0, now=clk)
    br.record_failure()
    clk.advance(1.5)
    assert br.allow()       # half_open, slot claimed
    assert not br.allow()
    br.record_cancel()      # hedged loser: releases the slot only
    assert br.state == "half_open"  # no verdict either way
    assert br.allow()       # slot free again
    br.record_success()
    assert br.state == "closed"


# ----------------------------------------------------------- hedging
def test_hedge_budget_burst_plus_fraction():
    hp = HedgePolicy(budget_frac=0.1, burst=2)
    for _ in range(5):
        hp.note_request()
    # cap = burst 2 + 0.1 * 5 requests = 2.5 -> exactly two grants
    grants = sum(hp.try_hedge() for _ in range(10))
    assert grants == 2
    # budget refills as real traffic flows
    for _ in range(100):
        hp.note_request()
    assert hp.try_hedge()
    c = hp.counters()
    assert c["requests"] == 105 and c["hedges"] == 3


def test_hedge_disabled_by_zero_budget():
    hp = HedgePolicy(budget_frac=0.0, burst=8)
    for _ in range(50):
        hp.note_request()
    assert not hp.try_hedge()


def test_hedge_delay_quantile_and_floor():
    hp = HedgePolicy(quantile=0.5, default_delay_s=0.07,
                     min_delay_s=0.01)
    assert hp.delay_s() == pytest.approx(0.07)  # cold ring: default
    for v in (0.02, 0.04, 0.06, 0.08, 0.10):
        hp.observe(v)
    assert hp.delay_s() == pytest.approx(0.06)  # median of the ring
    floor = HedgePolicy(min_delay_s=0.05, default_delay_s=0.001)
    assert floor.delay_s() == pytest.approx(0.05)


# ----------------------------------------------------------- backoff
def test_full_jitter_bounds_and_schedule():
    rng = random.Random(0)
    for attempt in (1, 2, 3, 6):
        ceil = backoff_delay(attempt, 0.05, 1.0)
        for _ in range(50):
            d = full_jitter_delay(attempt, 0.05, 1.0, rand=rng.random)
            assert 0.0 <= d <= ceil
    # degenerate rands pin the endpoints of the jitter interval
    assert full_jitter_delay(3, 0.05, 1.0, rand=lambda: 1.0) == (
        pytest.approx(backoff_delay(3, 0.05, 1.0)))
    assert full_jitter_delay(1, 0.05, 1.0, rand=lambda: 0.0) == 0.0


# ------------------------------------------------------- backend pool
def _pool(n: int, **breaker_kw) -> BackendPool:
    pool = BackendPool(
        [f"http://127.0.0.1:{9000 + i}" for i in range(n)],
        lambda url: CircuitBreaker(**breaker_kw),
    )
    for b in pool.backends:
        pool.set_health(b, alive=True, ready=True)
    return pool


def test_pool_least_outstanding_and_exclusion():
    pool = _pool(3)
    first = [pool.acquire() for _ in range(3)]
    # one slot each before anyone gets a second request
    assert {b.url for b in first} == {b.url for b in pool.backends}
    a = first[0]
    pool.release(a)
    assert pool.acquire() is a  # least outstanding wins
    pool.release(a)
    assert pool.acquire(exclude=(a,)) is not a


def test_pool_breaker_and_readiness_gate():
    pool = _pool(2, failures=1, cooldown_s=60.0)
    b0, b1 = pool.backends
    b0.breaker.record_failure()  # open: b0 admits nothing
    for _ in range(4):
        got = pool.acquire()
        assert got is b1
        pool.release(got)
    pool.set_health(b1, alive=True, ready=False)
    assert pool.acquire() is None  # b0 open, b1 not ready


def test_pool_rejects_bad_urls():
    with pytest.raises(ValueError):
        BackendPool([], lambda u: CircuitBreaker())
    with pytest.raises(ValueError):
        # same backend after trailing-slash normalization
        BackendPool(["http://h:1", "http://h:1/"],
                    lambda u: CircuitBreaker())


# ------------------------------------------------- gateway state machine
def test_gateway_op_classes():
    assert HEDGED_OPS <= IDEMPOTENT_OPS
    assert not (FANOUT_OPS & IDEMPOTENT_OPS)  # load/swap/rollback never auto-retry


def test_gateway_sheds_expired_deadline():
    gw = Gateway(["http://127.0.0.1:1"])
    status, resp, outcome = gw._single("score", {},
                                       time.monotonic() - 1.0)
    assert (status, outcome) == (503, "shed")
    assert resp["error_kind"] == "shed" and resp["retry_after_s"] > 0


def test_gateway_drain_rejects_new_work():
    gw = Gateway(["http://127.0.0.1:1"])
    assert not gw.draining
    gw.begin_drain()
    status, resp = gw.handle("score", {"rows": [[0.0]]})
    assert status == 503 and resp["error_kind"] == "shutdown"
    assert gw.drain(timeout_s=0.5)  # already idle -> immediate
    assert gw.inflight() == 0
    st = gw.status()
    assert st["draining"] and not st["ok"]


def test_gateway_unavailable_without_ready_backends():
    # never probed -> nothing ready; retries=0 keeps this sleep-free
    gw = Gateway(["http://127.0.0.1:1"], retries=0)
    status, resp = gw.handle("score", {"rows": [[0.0]]})
    assert status == 503 and resp["error_kind"] == "overloaded"
    status, resp = gw.handle("load", {"path": "x"})  # fanout: none alive
    assert status == 503 and resp["error_kind"] == "overloaded"


def test_gateway_merged_metrics_exposition():
    gw = Gateway(["http://127.0.0.1:1"], retries=0)
    gw.handle("ping", {})  # moves the request counter (outcome counted)
    merged = gw.merged_metrics()
    assert merged["processes"] >= 1  # gateway's own snapshot, no backends
    text = gw.merged_metrics_text()
    assert "lgbmtpu_gateway_requests_total" in text
    assert text.endswith("\n")


# ------------------------------------------------------------ readiness
class _FakeRegistry:
    """Duck-typed registry: readiness() needs models()/queue_cap and
    the optional health_probe attachment point only."""

    def __init__(self, models=None, queue_cap=0, probe=None):
        self._models = dict(models or {})
        self.queue_cap = queue_cap
        self.health_probe = probe

    def models(self):
        return dict(self._models)


def test_readiness_verdict_matrix():
    assert not readiness(_FakeRegistry())["ok"]  # no models
    assert readiness(_FakeRegistry({"m": {}}))["ok"]

    ev = threading.Event()
    ev.set()
    out = readiness(_FakeRegistry({"m": {}}), draining=ev)
    assert not out["ok"] and out["reason"] == "draining"

    # queue over the admission cap -> not ready (depth is the max over
    # the gauge's entries, so cap relative to whatever earlier tests
    # left behind)
    depths = default_registry().snapshot().get(
        "lgbmtpu_serve_queue_depth") or {}
    base = int(max(depths.values(), default=0))
    record_queue_depth("gwtest", base + 5)
    try:
        out = readiness(_FakeRegistry({"m": {}}, queue_cap=base + 5))
        assert not out["ok"] and out["reason"] == "queue at admission cap"
        record_queue_depth("gwtest", 0)
        assert readiness(_FakeRegistry({"m": {}},
                                       queue_cap=base + 6))["ok"]
    finally:
        record_queue_depth("gwtest", 0)

    out = readiness(_FakeRegistry({"m": {}},
                                  probe=lambda: {"healthy": False}))
    assert not out["ok"] and out["reason"] == "loop heartbeat stale"


def test_gateway_fault_sites_registered():
    assert {"gw_connect", "gw_backend_5xx", "gw_slow_backend",
            "gw_drain"} <= set(faultinject.SITES)


# ======================================================================
# slow / chaos: real sockets, real processes
# ======================================================================
@pytest.fixture(scope="module")
def model_and_data(tmp_path_factory):
    rs = np.random.RandomState(7)
    X = rs.randn(200, 5).astype(np.float32)
    y = (X @ rs.randn(5)).astype(np.float32)
    bst = lgb.train(
        {"objective": "regression", "verbosity": -1,
         "min_data_in_leaf": 5, "num_leaves": 15},
        lgb.Dataset(X, label=y, free_raw_data=False),
        num_boost_round=5,
    )
    path = tmp_path_factory.mktemp("gwmodel") / "model.txt"
    bst.save_model(str(path))
    return str(path), X


class _InProcBackend:
    """A real serve_http backend inside the test process."""

    def __init__(self, model_path: str):
        self.registry = ModelRegistry(warmup=False)
        self.registry.load("default", model_path)
        self.httpd = serve_http(self.registry, 0, block=False)
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()
        host, port = self.httpd.server_address[:2]
        self.url = f"http://{host}:{port}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.thread.join(timeout=10)


def _post(url: str, op: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        f"{url}/v1/{op}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


@pytest.mark.slow
def test_http_stalled_client_gets_408():
    """Satellite hardening: a client that sends headers then stalls
    mid-body hits the per-connection socket timeout and gets 408 —
    the handler thread is freed, other clients keep being served."""
    reg = ModelRegistry(warmup=False)
    httpd = serve_http(reg, 0, block=False, socket_timeout_s=0.5)
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    host, port = httpd.server_address[:2]
    try:
        s = socket.create_connection((host, port), timeout=5)
        try:
            s.sendall(b"POST /v1/score HTTP/1.1\r\nHost: t\r\n"
                      b"Content-Length: 4096\r\n\r\n")  # body never sent
            s.settimeout(10)
            status_line = s.recv(4096).split(b"\r\n", 1)[0]
            assert b"408" in status_line
        finally:
            s.close()
        # the stall did not wedge the server
        with urllib.request.urlopen(f"http://{host}:{port}/healthz",
                                    timeout=5) as r:
            assert r.status == 200
    finally:
        httpd.shutdown()
        httpd.server_close()
        th.join(timeout=10)


@pytest.mark.slow
def test_gateway_http_front_end_and_fanout(model_and_data):
    model_path, X = model_and_data
    backends = [_InProcBackend(model_path), _InProcBackend(model_path)]
    gw = Gateway([b.url for b in backends], retries=2,
                 backoff_base_s=0.01, health_interval_s=0.2,
                 hedge_budget=0.0)
    httpd = None
    th = None
    try:
        gw.start(wait_ready_s=10.0)
        assert gw.pool.counts() == (2, 2)
        httpd = gateway_http(gw, 0, block=False)
        th = threading.Thread(target=httpd.serve_forever, daemon=True)
        th.start()
        host, port = httpd.server_address[:2]
        url = f"http://{host}:{port}"
        for path, want in (("/healthz", 200), ("/readyz", 200)):
            with urllib.request.urlopen(url + path, timeout=10) as r:
                assert r.status == want
        st, resp = _post(url, "score", {"rows": X[:3].tolist(),
                                        "deadline_ms": 30000})
        assert st == 200 and resp["ok"] and len(resp["pred"]) == 3
        # fan-out load to every alive backend, then score the new name
        st, resp = _post(url, "load", {"path": model_path, "model": "m2"})
        assert st == 200 and resp["ok"] and resp["fanout"] == 2
        assert len(resp["results"]) == 2
        st, resp = _post(url, "score", {"model": "m2",
                                        "rows": X[:2].tolist()})
        assert st == 200 and resp["ok"]
        # single-pane /metrics: gateway families + backend families in
        # one merged exposition
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "lgbmtpu_gateway_requests_total" in text
        assert "lgbmtpu_gateway_backends_ready" in text
        # quit stays local-only even through the gateway front end
        try:
            _post(url, "quit", {})
            raise AssertionError("quit must be rejected")
        except urllib.error.HTTPError as e:
            assert e.code in (400, 404)
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if th is not None:
            th.join(timeout=10)
        gw.stop()
        for b in backends:
            b.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_hedge_overtakes_stalled_attempt(model_and_data):
    """gw_slow_backend stalls the primary attempt; the hedge fires at
    the (default) trigger delay on the other backend and wins long
    before the stall clears."""
    model_path, X = model_and_data
    backends = [_InProcBackend(model_path), _InProcBackend(model_path)]
    gw = Gateway([b.url for b in backends], retries=2,
                 backoff_base_s=0.01, health_interval_s=0.2,
                 hedge_budget=1.0, hedge_default_delay_s=0.05,
                 attempt_timeout_s=20.0)
    try:
        gw.start(wait_ready_s=10.0)
        # warm BOTH backends (first score pays the predict compile)
        for b in backends:
            st, _ = _post(b.url, "score", {"rows": X[:2].tolist()})
            assert st == 200
        faultinject.arm("gw_slow_backend:1:delay:3.0")
        t0 = time.monotonic()
        st, resp = gw.handle("score", {"rows": X[:2].tolist(),
                                       "deadline_ms": 15000})
        dt = time.monotonic() - t0
        assert st == 200 and resp["ok"], resp
        assert dt < 2.0, f"hedge did not overtake the stall ({dt:.2f}s)"
        assert gw.hedge.counters()["hedges"] >= 1
    finally:
        faultinject.disarm()
        gw.stop()
        for b in backends:
            b.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_gateway_drain_waits_for_inflight(model_and_data):
    """SIGTERM semantics without the signal: begin_drain sheds new
    work immediately while drain() blocks until the stalled in-flight
    request finishes — then the gateway is idle."""
    model_path, X = model_and_data
    backend = _InProcBackend(model_path)
    gw = Gateway([backend.url], retries=0, health_interval_s=0.2,
                 hedge_budget=0.0, attempt_timeout_s=20.0)
    try:
        gw.start(wait_ready_s=10.0)
        st, _ = gw.handle("score", {"rows": X[:2].tolist()})
        assert st == 200
        faultinject.arm("gw_slow_backend:1:delay:1.0")
        done = {}

        def call():
            done["r"] = gw.handle("score", {"rows": X[:2].tolist()})

        th = threading.Thread(target=call, daemon=True)
        th.start()
        deadline = time.monotonic() + 5.0
        while gw.inflight() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert gw.inflight() == 1
        t0 = time.monotonic()
        assert gw.drain(timeout_s=15.0)
        waited = time.monotonic() - t0
        assert gw.inflight() == 0
        th.join(timeout=10)
        assert done["r"][0] == 200  # the in-flight request finished
        assert waited > 0.2  # drain actually waited for it
        st, resp = gw.handle("score", {"rows": X[:2].tolist()})
        assert st == 503 and resp["error_kind"] == "shutdown"
    finally:
        faultinject.disarm()
        gw.stop()
        backend.close()


# ------------------------------------------------- subprocess backends
def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_serve(model_path: str, port: int, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(faultinject.ENV_VAR, None)
    # logs to a spill file, not a PIPE: a filled pipe buffer would
    # block the backend mid-test
    logf = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "task=serve",
         f"input_model={model_path}", f"serve_port={port}",
         "serve_warmup=false", "device_type=cpu", "verbosity=-1",
         *extra],
        cwd=str(REPO), env=env, stdin=subprocess.DEVNULL,
        stdout=logf, stderr=logf, text=True)
    proc._test_log = logf  # closed by _stop_proc
    return proc


def _proc_log(proc) -> str:
    logf = getattr(proc, "_test_log", None)
    if logf is None:
        return ""
    logf.seek(0)
    return logf.read()[-2000:]


def _wait_ready(url: str, proc, timeout: float = 300.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"backend died rc={proc.returncode}:\n{_proc_log(proc)}")
        try:
            with urllib.request.urlopen(url + "/readyz", timeout=2) as r:
                if r.status == 200:
                    return
        except Exception:  # noqa: BLE001 — not up yet
            pass
        time.sleep(0.2)
    raise AssertionError(f"backend at {url} never became ready")


def _stop_proc(proc) -> None:
    if proc.poll() is None:
        proc.kill()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        pass
    logf = getattr(proc, "_test_log", None)
    if logf is not None:
        logf.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_kill9_backend_zero_client_failures(model_and_data):
    """The ISSUE 17 chaos proof: kill -9 one of two real backend
    processes under concurrent client load — no client sees a failure
    (retry + exclusion absorb it), the victim's breaker opens, and
    after a restart on the same port the breaker recovers through
    half_open back to closed on real traffic."""
    model_path, X = model_and_data
    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    procs = [_spawn_serve(model_path, p) for p in ports]
    gw = None
    stop = threading.Event()
    threads = []
    try:
        for u, p in zip(urls, procs):
            _wait_ready(u, p)
        gw = Gateway(urls, retries=3, backoff_base_s=0.02,
                     backoff_cap_s=0.2, breaker_failures=1,
                     breaker_cooldown_s=0.4, health_interval_s=0.5,
                     hedge_budget=0.2, attempt_timeout_s=15.0)
        transitions = []
        orig = gw._on_breaker
        gw._on_breaker = lambda name, old, new: (
            transitions.append((name, old, new)), orig(name, old, new))
        gw.start(wait_ready_s=15.0)
        assert gw.pool.counts()[1] == 2

        rows = X[:3].tolist()
        # warm each backend directly: the first score pays the predict
        # compile, which must not eat into the chaos phase's deadlines
        for u in urls:
            st, _ = _post(u, "score", {"rows": rows}, timeout=300)
            assert st == 200
        failures = []
        flock = threading.Lock()

        def client():
            while not stop.is_set():
                st, resp = gw.handle(
                    "score", {"rows": rows, "deadline_ms": 30000})
                if st != 200:
                    with flock:
                        failures.append((st, resp))

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # traffic flowing through both backends

        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=30)
        assert procs[0].returncode == -9
        time.sleep(2.0)  # keep hammering the survivor
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert failures == [], failures[:3]

        victim = gw.pool.backends[0]
        victim_name = victim.name
        # the raced/refused attempts tripped the breaker (failures=1),
        # and/or the health loop pulled the backend from the pool
        assert victim.breaker.state != "closed" or not victim.ready

        # restart on the same port; health loop re-readies it and real
        # traffic walks the breaker open -> half_open -> closed
        procs[0] = _spawn_serve(model_path, ports[0])
        _wait_ready(urls[0], procs[0])
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st, resp = gw.handle(
                "score", {"rows": rows, "deadline_ms": 30000})
            assert st == 200, resp
            if victim.ready and victim.breaker.state == "closed":
                break
            time.sleep(0.05)
        assert victim.breaker.state == "closed"
        mine = [(o, n) for (b, o, n) in transitions if b == victim_name]
        assert ("closed", "open") in mine
        assert ("open", "half_open") in mine
        assert ("half_open", "closed") in mine
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if gw is not None:
            gw.stop()
        for p in procs:
            _stop_proc(p)


@pytest.mark.slow
@pytest.mark.chaos
def test_backend_sigterm_drain_finishes_inflight(model_and_data):
    """SIGTERM to a real task=serve backend while a request is stalled
    in flight: the request still completes (server_close joins handler
    threads) and the process exits 0 — the backend half of
    tools/gateway_rolling.sh."""
    model_path, X = model_and_data
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    # hit 1 = the warm request; hit 2 = the stalled in-flight request
    # (/readyz and /healthz probes do not consume fault-plan hits)
    proc = _spawn_serve(model_path, port,
                        extra=("fault_plan=serve_request:2:delay:1.5",))
    try:
        _wait_ready(url, proc)
        st, _ = _post(url, "score", {"rows": X[:2].tolist()},
                      timeout=120)
        assert st == 200

        result = {}

        def slow_call():
            try:
                result["resp"] = _post(url, "score",
                                       {"rows": X[:2].tolist()},
                                       timeout=60)
            except Exception as e:  # noqa: BLE001 — reported below
                result["error"] = repr(e)

        th = threading.Thread(target=slow_call, daemon=True)
        th.start()
        time.sleep(0.5)  # request is in flight, stalled at the fault
        proc.send_signal(signal.SIGTERM)
        th.join(timeout=60)
        assert "error" not in result, result
        st, resp = result["resp"]
        assert st == 200 and resp["ok"]
        assert proc.wait(timeout=60) == 0  # clean exit after the drain
    finally:
        _stop_proc(proc)
