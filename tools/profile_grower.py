"""Per-component timing of the tree grower on the real device."""
import os, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

N = int(os.environ.get("N", 1_000_000))
F = int(os.environ.get("F", 28))
B = int(os.environ.get("B", 256))
L = int(os.environ.get("L", 255))

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params
from lightgbm_tpu.learner.histogram import HIST_BLK, build_gh8, histogram

rs = np.random.RandomState(0)
Npad = ((N + HIST_BLK - 1) // HIST_BLK) * HIST_BLK
bins = rs.randint(0, B - 1, size=(F, Npad)).astype(np.int32)
grad = rs.randn(Npad).astype(np.float32)
hess = np.ones(Npad, np.float32)
mask = np.ones(Npad, np.float32); mask[N:] = 0

bins_d = jnp.asarray(bins)
grad_d = jnp.asarray(grad); hess_d = jnp.asarray(hess); mask_d = jnp.asarray(mask)
nan_bin = jnp.full(F, -1, jnp.int32)
num_bins = jnp.full(F, B, jnp.int32)
mono = jnp.zeros(F, jnp.int32)
is_cat = jnp.zeros(F, bool)
feat_mask = jnp.ones(F, bool)
cfg = Config({"num_leaves": L, "min_data_in_leaf": 20})
params = make_split_params(cfg)

def timeit(name, fn, n=3):
    fn()  # compile
    jax.block_until_ready(fn())
    t0 = time.time()
    for _ in range(n):
        r = fn()
    jax.block_until_ready(r)
    dt = (time.time() - t0) / n
    print(f"{name}: {dt*1000:.2f} ms")
    return dt

# 1. full-N histogram (pallas kernel)
gh8 = build_gh8(grad_d * mask_d, hess_d * mask_d, mask_d)
gh8 = jax.block_until_ready(gh8)
hist_j = jax.jit(lambda b, g: histogram(b, g, B))
timeit("hist full-N (pallas)", lambda: hist_j(bins_d, gh8))

# 2. best_split alone
from lightgbm_tpu.learner.split import best_split
h0 = hist_j(bins_d, gh8)
bs_j = jax.jit(lambda h: best_split(h, jnp.float32(0.), jnp.float32(Npad), jnp.float32(Npad),
                                    num_bins, nan_bin, mono, is_cat, params, feat_mask))
timeit("best_split", lambda: bs_j(h0))

# 3. the partition-style gather: take along lane axis at full N
perm = jnp.asarray(rs.permutation(Npad).astype(np.int32))
gat_j = jax.jit(lambda b, p: jnp.take(b, p, axis=1))
timeit("gather (F,N) lane axis", lambda: gat_j(bins_d, perm))
gat8_j = jax.jit(lambda g, p: jnp.take(g, p, axis=1))
timeit("gather (8,N) lane axis", lambda: gat8_j(gh8, perm))

# 4. nonzero compaction at full N
nz_j = jax.jit(lambda m: jnp.nonzero(m > 0.5, size=Npad, fill_value=Npad)[0])
timeit("nonzero full-N", lambda: nz_j(mask_d))

# 5. full tree: permuted vs flat
for part in ["permuted", "flat"]:
    spec = GrowerSpec(num_leaves=L, num_bins=B, max_depth=-1, axis_name=None, partition=part)
    def run():
        t, rl = grow_tree(bins_d, nan_bin, num_bins, mono, is_cat,
                          grad_d, hess_d, mask_d, feat_mask, params, spec, valid=mask_d)
        return rl
    print(f"-- compiling {part} ...")
    t0 = time.time()
    jax.block_until_ready(run())
    print(f"   compile+first: {time.time()-t0:.1f} s")
    timeit(f"grow_tree[{part}] {L} leaves", run, n=2)
