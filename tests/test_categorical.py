"""Categorical sorted-subset splits (feature_histogram.cpp:246
FindBestThresholdCategoricalInner, non-onehot branch).

Checks the vectorized scan against a literal numpy transcription of the
reference algorithm, end-to-end training quality on data whose signal
one-vs-rest splits cannot capture, and model-file interop (multi-category
bitsets) with the reference CLI."""

from __future__ import annotations

import subprocess
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.learner.split import best_split

from test_learner import _params

REPO = Path(__file__).resolve().parent.parent
CLI = REPO / ".refbuild" / "lightgbm"


def _oracle_cat_subset(g, h, c, params):
    """Literal numpy port of the reference sorted-subset scan for ONE
    categorical feature. Returns (best_gain_unshifted, left_bins)."""
    B = len(g)
    cat_smooth = params["cat_smooth"]
    l2 = params["lambda_l2"] + params["cat_l2"]
    l1 = params["lambda_l1"]
    eps = 1e-15

    def leaf_gain(G, H):
        t = np.sign(G) * max(abs(G) - l1, 0.0)
        return t * t / (H + l2 + eps)

    valid = [b for b in range(B) if c[b] >= cat_smooth]
    order = sorted(valid, key=lambda b: g[b] / (h[b] + cat_smooth))
    used = len(order)
    max_num_cat = min(params["max_cat_threshold"], (used + 1) // 2)
    sum_g, sum_h, sum_c = g.sum(), h.sum(), c.sum()

    best_gain, best_set = -np.inf, []
    for dir_, start in ((1, 0), (-1, used - 1)):
        lg, lh, lc = 0.0, eps, 0.0
        grp = 0.0
        pos = start
        chosen = []
        for i in range(min(used, max_num_cat)):
            t = order[pos]
            pos += dir_
            chosen = chosen + [t]
            lg += g[t]
            lh += h[t]
            lc += c[t]
            grp += c[t]
            if lc < params["min_data_in_leaf"] or lh < params["min_sum_hessian_in_leaf"]:
                continue
            rc = sum_c - lc
            if rc < params["min_data_in_leaf"] or rc < params["min_data_per_group"]:
                break
            rh = sum_h - lh
            if rh < params["min_sum_hessian_in_leaf"]:
                break
            if grp < params["min_data_per_group"]:
                continue
            grp = 0.0
            gain = leaf_gain(lg, lh) + leaf_gain(sum_g - lg, rh)
            if gain > best_gain:
                best_gain, best_set = gain, list(chosen)
    return best_gain, sorted(best_set)


def test_cat_subset_matches_reference_oracle():
    rs = np.random.RandomState(0)
    B = 32
    F = 1
    g = rs.randn(B).astype(np.float64) * 5
    h = (1.0 + rs.rand(B) * 50).astype(np.float64)
    c = np.round(h).astype(np.float64)

    pd = dict(
        lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=1.0,
        min_sum_hessian_in_leaf=0.0, cat_smooth=10.0, cat_l2=10.0,
        max_cat_threshold=32, max_cat_to_onehot=4, min_data_per_group=25.0,
    )
    params = _params(**pd)

    hist = jnp.asarray(
        np.stack([g, h, c])[:, None, :], dtype=jnp.float32
    )  # (3, F, B)
    rec = best_split(
        hist,
        jnp.float32(g.sum()), jnp.float32(h.sum()), jnp.float32(c.sum()),
        jnp.asarray([B], jnp.int32),
        jnp.asarray([-1], jnp.int32),
        jnp.zeros(F, jnp.int32),
        jnp.ones(F, bool),
        params,
        cat_subset=True,
    )
    oracle_gain, oracle_set = _oracle_cat_subset(g, h, c, pd)
    parent = g.sum() ** 2 / (h.sum() + 1e-15)
    assert float(rec.gain) > 0
    np.testing.assert_allclose(
        float(rec.gain), oracle_gain - parent, rtol=2e-4, atol=1e-3
    )
    got_set = sorted(np.nonzero(np.asarray(rec.cat_mask))[0].tolist())
    assert got_set == oracle_set


def _cat_problem(n=4000, n_cat=24, seed=7):
    """Binary target driven by membership in a scattered category subset —
    invisible to any single one-vs-rest split."""
    rs = np.random.RandomState(seed)
    cats = rs.randint(0, n_cat, size=n)
    good = set(rs.choice(n_cat, size=n_cat // 2, replace=False).tolist())
    base = np.isin(cats, list(good)).astype(float)
    y = (base + 0.2 * rs.randn(n) > 0.5).astype(float)
    X = np.column_stack([cats.astype(float), rs.randn(n)])
    return X, y


def test_categorical_training_quality():
    X, y = _cat_problem()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0], free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "min_data_per_group": 10, "learning_rate": 0.5},
        ds, num_boost_round=10,
    )
    from sklearn.metrics import roc_auc_score

    auc = roc_auc_score(y, bst.predict(X))
    # subset splits separate the good categories in one or two splits;
    # one-vs-rest with 7 leaves cannot reach this
    assert auc > 0.97, auc
    # the model must contain a multi-category bitset node
    dumped = bst.dump_model()
    found_multi = False
    for tree in dumped["tree_info"]:
        stack = [tree["tree_structure"]]
        while stack:
            node = stack.pop()
            if "split_feature" in node:
                if node.get("decision_type") == "==" and "||" in str(
                    node.get("threshold", "")
                ):
                    found_multi = True
                stack.extend(
                    node[k] for k in ("left_child", "right_child") if k in node
                )
    assert found_multi, "no sorted-subset (multi-category) split in model"


def test_categorical_save_load_roundtrip():
    X, y = _cat_problem(seed=9)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0], free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "min_data_per_group": 10},
        ds, num_boost_round=5,
    )
    p1 = bst.predict(X)
    s = bst.model_to_string()
    b2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(b2.predict(X), p1, rtol=1e-6)


@pytest.mark.skipif(not CLI.exists(), reason="reference CLI not built")
def test_categorical_model_predicts_same_in_reference_cli(tmp_path):
    X, y = _cat_problem(seed=11)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0], free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "min_data_per_group": 10},
        ds, num_boost_round=5,
    )
    ours = bst.predict(X)
    bst.save_model(tmp_path / "model.txt")
    data = np.column_stack([y, X])
    np.savetxt(tmp_path / "data.tsv", data, delimiter="\t", fmt="%.6f")
    r = subprocess.run(
        [str(CLI), "task=predict", "data=data.tsv", "input_model=model.txt",
         "output_result=pred.txt", "header=false"],
        cwd=tmp_path, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    theirs = np.loadtxt(tmp_path / "pred.txt")
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)
