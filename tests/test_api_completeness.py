"""Reference-parity convenience APIs added for user-switch completeness:
Dataset get/set_field, get_data, get_params, set_reference/get_ref_chain,
set_feature_name/set_categorical_feature, feature_num_bin, save_binary,
add_features_from; Booster get/set_leaf_output, lower/upper_bound,
model_from_string, shuffle_models, trees_to_dataframe,
set_train_data_name (reference basic.py surface)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.log import LightGBMError


@pytest.fixture(scope="module")
def data():
    rs = np.random.RandomState(11)
    X = rs.randn(400, 5)
    y = (X[:, 0] + 0.3 * rs.randn(400) > 0).astype(float)
    return X, y


@pytest.fixture(scope="module")
def booster(data):
    X, y = data
    return lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=y, free_raw_data=False),
        num_boost_round=6,
    )


def test_dataset_fields(data):
    X, y = data
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    ds.set_field("weight", np.ones(400))
    assert ds.get_field("weight").shape == (400,)
    ds.set_field("position", np.zeros(400, np.int32))
    assert ds.get_field("position").shape == (400,)
    with pytest.raises(KeyError):
        ds.set_field("nope", y)
    assert ds.get_data().shape == (400, 5)
    ds2 = lgb.Dataset(X, label=y)  # free_raw_data=True default
    ds2.construct()
    ds2.data = None  # what free-after-construct leaves behind
    with pytest.raises(LightGBMError):
        ds2.get_data()


def test_dataset_params_and_bins(data):
    X, y = data
    ds = lgb.Dataset(X, label=y,
                     params={"max_bin": 63, "learning_rate": 0.5})
    assert ds.get_params() == {"max_bin": 63}  # non-dataset params dropped
    ds.construct()
    assert 2 <= ds.feature_num_bin(0) <= 64


def test_ref_chain_and_reference(data):
    X, y = data
    ds = lgb.Dataset(X, label=y)
    vs = lgb.Dataset(X[:100], label=y[:100], reference=ds)
    chain = vs.get_ref_chain()
    assert ds in chain and vs in chain
    ds.construct()
    with pytest.raises(LightGBMError):
        ds.set_reference(vs)  # constructed with a different reference


def test_set_names_and_categorical(data):
    X, y = data
    ds = lgb.Dataset(X, label=y)
    ds.set_feature_name([f"f{i}" for i in range(5)])
    ds.construct()
    assert ds.get_feature_name() == [f"f{i}" for i in range(5)]
    ds.set_feature_name([f"g{i}" for i in range(5)])  # rename in place
    assert ds.get_feature_name() == [f"g{i}" for i in range(5)]
    with pytest.raises(LightGBMError):
        ds.set_feature_name(["too", "short"])
    with pytest.raises(LightGBMError):
        ds.set_categorical_feature([0])  # after construct
    ds2 = lgb.Dataset(X, label=y)
    ds2.set_categorical_feature([1])
    assert ds2.categorical_feature == [1]


def test_save_binary_roundtrip(tmp_path, data):
    X, y = data
    ds = lgb.Dataset(X, label=y)
    path = tmp_path / "train.bin"
    ds.save_binary(path)
    ds2 = lgb.Dataset(str(path))
    ds2.construct()
    assert ds2.num_data() == 400
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, ds2, num_boost_round=2)
    assert bst.num_trees() == 2


def test_dataset_from_text_file(tmp_path, data):
    X, y = data
    path = tmp_path / "train.csv"
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    ds = lgb.Dataset(str(path))
    ds.construct()
    assert ds.num_data() == 400 and ds.num_feature() == 5
    assert ds.get_label().shape == (400,)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, ds, num_boost_round=2)
    assert bst.num_trees() == 2


def test_dataset_from_text_file_params(tmp_path, data):
    """header= and label_column= params reach the parser; the .init
    sidecar loads as init_score (code-review r4 findings)."""
    X, y = data
    path = tmp_path / "tr.csv"
    with open(path, "w") as f:
        f.write("target," + ",".join(f"c{i}" for i in range(5)) + "\n")
        np.savetxt(f, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    with open(str(path) + ".init", "w") as f:
        f.write("0.25\n" * 400)
    ds = lgb.Dataset(str(path), params={"header": True})
    ds.construct()
    assert ds.num_data() == 400 and ds.num_feature() == 5
    assert ds.get_init_score() is not None
    assert float(np.unique(ds.get_init_score())[0]) == pytest.approx(0.25)


def test_num_data_on_unconstructed_file(tmp_path, data):
    X, y = data
    path = tmp_path / "t.csv"
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.5f")
    ds = lgb.Dataset(str(path))
    assert ds.num_data() == 400  # constructs on demand, no IndexError
    assert lgb.Dataset(str(path)).num_feature() == 5


def test_add_features_from_string_categoricals(data):
    X, y = data
    a = lgb.Dataset(X, label=y, free_raw_data=False,
                    feature_name=[f"a{i}" for i in range(5)],
                    categorical_feature=["a2"])
    b = lgb.Dataset(X[:, :2], free_raw_data=False,
                    feature_name=["b0", "b1"], categorical_feature=["b1", 0])
    a.add_features_from(b)
    assert a.categorical_feature == ["a2", "b1", 5]  # names kept, ints shifted


def test_add_features_from(data):
    X, y = data
    a = lgb.Dataset(X, label=y, free_raw_data=False,
                    feature_name=[f"a{i}" for i in range(5)])
    b = lgb.Dataset(X[:, :2] * 2.0, free_raw_data=False,
                    feature_name=["b0", "b1"], categorical_feature=[])
    a.add_features_from(b)
    a.construct()
    assert a.num_feature() == 7
    assert a.get_feature_name()[:5] == [f"a{i}" for i in range(5)]
    mismatched = lgb.Dataset(X[:10], free_raw_data=False)
    with pytest.raises(LightGBMError):
        a.add_features_from(mismatched)


def test_leaf_output_roundtrip(booster, data):
    X, _ = data
    p0 = booster.predict(X[:20], raw_score=True)
    v = booster.get_leaf_output(0, 0)
    booster.set_leaf_output(0, 0, v + 2.0)
    assert booster.get_leaf_output(0, 0) == pytest.approx(v + 2.0)
    booster.set_leaf_output(0, 0, v)
    np.testing.assert_allclose(
        booster.predict(X[:20], raw_score=True), p0, atol=1e-12
    )


def test_bounds_contain_predictions(booster, data):
    X, _ = data
    raw = booster.predict(X, raw_score=True)
    assert booster.lower_bound() <= raw.min()
    assert booster.upper_bound() >= raw.max()


def test_shuffle_models_invariant(booster, data):
    X, _ = data
    p0 = booster.predict(X[:50], raw_score=True)
    np.random.seed(3)
    booster.shuffle_models()
    np.testing.assert_allclose(
        booster.predict(X[:50], raw_score=True), p0, atol=1e-10
    )
    booster.shuffle_models(start_iteration=2, end_iteration=5)
    np.testing.assert_allclose(
        booster.predict(X[:50], raw_score=True), p0, atol=1e-10
    )


def test_trees_to_dataframe(booster):
    df = booster.trees_to_dataframe()
    expected = {
        "tree_index", "node_depth", "node_index", "left_child",
        "right_child", "parent_index", "split_feature", "split_gain",
        "threshold", "decision_type", "missing_direction", "missing_type",
        "value", "weight", "count",
    }
    assert expected <= set(df.columns)
    assert df["tree_index"].nunique() == booster.num_trees()
    # splits have children; leaves have values
    splits = df[df["split_feature"].notna()]
    assert (splits["left_child"].notna()).all()
    leaves = df[df["split_feature"].isna()]
    assert (leaves["value"].notna()).all()
    # every parent_index refers to an existing node
    known = set(df["node_index"])
    parents = set(df["parent_index"].dropna())
    assert parents <= known


def test_model_from_string_and_train_name(booster, data):
    X, _ = data
    s = booster.model_to_string()
    b = lgb.Booster(model_str=s)
    b.model_from_string(s)
    np.testing.assert_allclose(b.predict(X[:20]), booster.predict(X[:20]))
    assert booster.set_train_data_name("tr2") is booster
    assert booster._train_data_name == "tr2"
