"""Probe per-row small-table gather strategies on a live chip.

grow_tree_rounds' partition update gathers several (N,) values from
(L,)-sized tables (row's leaf -> split feature/bin/default/new id).
tools/tpu_rounds_profile.py measured the whole update at ~33 ms/round —
dominant over the 12.4 ms histogram pass. Candidates:

  take_L     — jnp.take from the (L,) table (current code)
  onehot_S   — rows belong to <= S selected leaves: mask (N, S) =
               (pleaf == sel_leaf) once, then ALL per-row scalars come
               from one (N,S)@(S,k) MXU matmul
  fori_S     — fori over S slots of masked scalar adds (VPU only)
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    print(json.dumps({"platform": jax.devices()[0].platform}), flush=True)

    rs = np.random.RandomState(0)
    N, L, S = 999424, 255, 25
    pleaf = jnp.asarray(rs.randint(0, L, N).astype(np.int32))
    sel_leaf = jnp.asarray(rs.choice(L, S, replace=False).astype(np.int32))
    # k=4 per-leaf scalars to fetch per row (feature, bin, default, new_id)
    tabs = jnp.asarray(rs.randint(0, 255, (L, 4)).astype(np.float32))

    def timed(make_body, R=20):
        def loop():
            def body(_, acc):
                return make_body(acc)

            return lax.fori_loop(0, R, body, jnp.float32(0.0))

        f = jax.jit(loop)
        float(f())
        t0 = time.time()
        float(f())
        return (time.time() - t0) / R

    t_base = timed(lambda acc: acc + (pleaf + jnp.int32(acc)).astype(jnp.float32)[0])
    print(json.dumps({"metric": "baseline_ms",
                      "value": round(t_base * 1e3, 2)}), flush=True)

    def take_body(acc):
        p = pleaf + jnp.int32(acc * 0.0)
        out = tabs[p]  # (N, 4) gather
        return acc + out[0, 0]

    t = timed(take_body) - t_base
    print(json.dumps({"metric": "take_L_x4_ms", "value": round(t * 1e3, 2)}),
          flush=True)

    def onehot_body(acc):
        p = pleaf + jnp.int32(acc * 0.0)
        m = (p[:, None] == sel_leaf[None, :]).astype(jnp.bfloat16)  # (N, S)
        st = tabs[sel_leaf].astype(jnp.bfloat16)  # (S, 4) small gather
        out = jax.lax.dot_general(
            m, st, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (N, 4)
        return acc + out[0, 0]

    t = timed(onehot_body) - t_base
    print(json.dumps({"metric": "onehot_S_x4_ms", "value": round(t * 1e3, 2)}),
          flush=True)

    def fori_body(acc):
        p = pleaf + jnp.int32(acc * 0.0)
        st = tabs[sel_leaf]  # (S, 4)

        def inner(s, o):
            m = (p == sel_leaf[s]).astype(jnp.float32)
            return o + m[:, None] * st[s][None, :]

        out = lax.fori_loop(0, S, inner, jnp.zeros((N, 4), jnp.float32))
        return acc + out[0, 0]

    t = timed(fori_body) - t_base
    print(json.dumps({"metric": "fori_S_x4_ms", "value": round(t * 1e3, 2)}),
          flush=True)

    # the (G, N) masked bin select (fbins) for comparison
    G = 28
    bins = jnp.asarray(rs.randint(0, 255, (G, N)).astype(np.int32))
    col_row = jnp.asarray(rs.randint(0, G, N).astype(np.int32))

    def fbins_body(acc):
        cr = col_row + jnp.int32(acc * 0.0)
        col_sel = cr[None, :] == jnp.arange(G, dtype=jnp.int32)[:, None]
        fb = jnp.sum(jnp.where(col_sel, bins, 0), axis=0)
        return acc + fb[0].astype(jnp.float32)

    t = timed(fbins_body) - t_base
    print(json.dumps({"metric": "fbins_select_ms", "value": round(t * 1e3, 2)}),
          flush=True)

    # cat-mask flat gather (the (L*B,) table path)
    B = 256
    cmask = jnp.asarray((rs.rand(L * B) > 0.5).astype(np.float32))
    fbins_c = jnp.asarray(rs.randint(0, B, N).astype(np.int32))

    def cat_body(acc):
        p = pleaf + jnp.int32(acc * 0.0)
        hit = cmask[p * B + fbins_c]
        return acc + hit[0]

    t = timed(cat_body) - t_base
    print(json.dumps({"metric": "catmask_gather_ms", "value": round(t * 1e3, 2)}),
          flush=True)


if __name__ == "__main__":
    main()
