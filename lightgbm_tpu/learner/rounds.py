"""Natural-order round-batched leaf-wise growth — the TPU fast path.

The permuted grower (permuted.py) keeps rows physically leaf-grouped so
each split costs O(segment) — but maintaining that layout costs one
full-array gather per split or round (75-120 ms at 1M x 36 channels:
TPUs have no vector-gather hardware, see BENCH_NOTES.md). This grower
never moves a row:

- the partition is a per-row leaf-id vector updated with elementwise
  `where` (the reference CUDA data_index_to_leaf_index,
  src/treelearner/cuda/cuda_data_partition.cu:113);
- per round, the top-k positive-gain leaves split AT ONCE
  (k = min(round_slots, remaining leaf budget)); the smaller child of
  every split gets its histogram from ONE slot-packed MXU pass
  (histogram.hist_nat_slots — the multi-leaf batching of the reference
  CUDA kernel, cuda_histogram_constructor.cu:20), the larger sibling
  by parent subtraction (serial_tree_learner.cpp:411);
- per-tree device work is ~#rounds histogram passes plus O(N)
  elementwise updates — no gathers, no sorts, no prefix sums.

Semantics vs the reference's sequential best-first growth: splitting
the top-k leaves of a round in parallel yields the SAME final tree as
sequential greedy whenever the leaf budget does not bind (a leaf's best
split is independent of every other leaf), and the same set of splits
ordered differently otherwise — except near the budget boundary, where
children created by this round's splits never compete against this
round's remaining candidates. `tpu_growth_mode=exact` keeps the
reference-exact sequential grower; this mode is the default on TPU
hardware where the round batching is worth ~an order of magnitude
(config.h has no analog — the reference CUDA learner batches histogram
construction but still splits one leaf at a time).

This grower is the single production path (ISSUE 14): voting-parallel
(PV-Tree election, only elected bundle columns cross the mesh — one
election per ROUND covering all slots jointly), forced splits (one
prescribed split per round during the forced phase so Tree::Split leaf
numbering matches the BFS plan), and all three monotone methods (basic
/ intermediate / advanced) ride it; the permuted sequential grower
remains only as the reference-exact parity oracle behind
`tpu_growth_mode=exact`.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .bundle import BundleInfo, decode_feature_bins, expand_hist
from .histogram import (
    build_gh8,
    build_gh8_quant,
    can_hist_round,
    hist_nat_slots,
    hist_round,
    histogram,
    int8_oh_shift,
    root_sums,
    rs_exact_ok,
    rs_wire_dtype,
)
from .grower import (
    GrowerSpec,
    TreeArrays,
    _empty_best,
    _set_best,
    make_node_candidates,
    monotone_child_intervals,
    split_leaf_outputs,
)
from .split import (
    NEG_INF,
    BIG,
    SplitParams,
    SplitRecord,
    best_split,
    feature_best_gains,
    leaf_gain,
    leaf_output,
)


class _NState(NamedTuple):
    i: jax.Array  # splits performed so far
    r: jax.Array  # (W+1,) int32 — rounds executed, by ladder width
    # (r[w] = rounds run at widths[w]; r[-1] = total). Scalar counters,
    # free at runtime; surfaced by grow_tree_rounds(..., with_stats=True)
    # for profiling the ladder on real gain landscapes.
    pleaf: jax.Array  # (N,) int32 row -> leaf; invalid rows carry L
    hist: jax.Array  # (L, 3, G, Bc) histogram pool
    leaf_g: jax.Array
    leaf_h: jax.Array
    leaf_c: jax.Array
    leaf_parent: jax.Array
    leaf_min: jax.Array  # monotone interval per leaf
    leaf_max: jax.Array
    # ancestry matrices for mono_mode=1 (intermediate constraints),
    # zero-width when mono_mode == 0: anc_in[leaf, node] = node is an
    # ancestor; anc_left[leaf, node] = leaf hangs on its LEFT side
    anc_in: jax.Array  # (L, L-1 | 0) bool
    anc_left: jax.Array  # (L, L-1 | 0) bool
    # per-node feature bookkeeping (interaction constraints + CEGB),
    # zero-width when no per-node extras are active
    leaf_groups: jax.Array  # (L, NG | 0) bool — legal constraint groups
    path_used: jax.Array  # (L, F | 0) bool — features on the leaf's path
    feat_used: jax.Array  # (F | 0,) bool — used anywhere (CEGB coupled)
    # voting-parallel: hist_valid[leaf, f] = the stored histogram column
    # holds GLOBAL (mesh-reduced) sums for feature f — all-True except
    # under voting, where only elected columns cross the mesh. Child
    # search and parent subtraction are masked to valid columns
    # (permuted.py hist_valid, lifted onto the round-batched state).
    # Zero-width when voting is off.
    hist_valid: jax.Array  # (L, F | 0) bool
    # advanced monotone constraints: per-leaf per-feature bin range
    # (lo, hi], refined at each numeric split (left keeps hi=min(hi,
    # bin); right lo=max(lo, bin)). Two leaves can form a violating
    # monotone pair through ancestor a only if their ranges intersect
    # in every feature EXCEPT a's split feature. Zero-width unless
    # mono_mode == 2.
    leaf_flo: jax.Array  # (L, F | 0) int32
    leaf_fhi: jax.Array  # (L, F | 0) int32
    best: SplitRecord  # per-leaf best splits, fields (L,)
    tree: TreeArrays


@partial(jax.jit, static_argnames=("spec", "with_stats"))
def grow_tree_rounds(
    bins_fm: jax.Array,  # (G, N) int32, natural row order
    nan_bin: jax.Array,
    num_bins: jax.Array,
    mono: jax.Array,
    is_cat: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,  # validity * bagging
    feat_mask: jax.Array,
    params: SplitParams,
    spec: GrowerSpec,
    valid: Optional[jax.Array] = None,
    bundle: Optional[BundleInfo] = None,
    gh_scale: Optional[jax.Array] = None,  # (2,) [g_scale, h_scale]
    rng_key: Optional[jax.Array] = None,  # extra_trees / ff_bynode draws
    group_mat: Optional[jax.Array] = None,  # (NG, F) bool — interaction
    cegb=None,  # CegbInfo penalty tables
    forced=None,  # ForcedSplits plan (permuted.ForcedSplits) when
    # spec.n_forced > 0: (leaf, feature, bin) per step, leaf ids
    # precomputed under Tree::Split numbering
    with_stats: bool = False,  # also return per-width round counters
):
    """Grow one tree; returns (tree arrays, natural-order row->leaf),
    plus a {"widths", "rounds"} stats dict when with_stats=True.

    With spec.quant, grad/hess are INTEGER quantization levels and
    gh_scale carries the per-iteration dequantization scales: histogram
    sums stay exact integers (bf16 products, f32 accumulation) and are
    multiplied by the scales once per histogram before split search —
    the reference's int-histogram arithmetic (gradient_discretizer.cpp,
    feature_histogram.hpp:1062) mapped onto the MXU.

    Trace-safety contract: this function is the workhorse inside the
    fused step, which since round 18 is the BODY of a `lax.scan` chunk
    (boosting.fused_dispatch, tpu_chunk_scan). Everything here must
    therefore stay traceable with abstract operands — no host branching
    on data values (python `if` only on static spec/params fields), no
    `.item()`/`float()` coercions, shapes independent of the round
    index. The per-round variation (bagging masks, rng_key, gh scales)
    arrives as traced ARGUMENTS; violating this turns one chunk
    executable into a retrace per round and trips
    analysis/retrace.py's guard in tests/test_chunk_scan.py."""
    L = spec.num_leaves
    B = spec.num_bins
    G, N = bins_fm.shape  # G = device columns (bundles when spec.efb)
    F = num_bins.shape[0]
    S = min(spec.rounds_slots, max(L - 1, 1))  # top_k needs k <= L
    ax = spec.axis_name
    Bc = spec.col_bins if (spec.efb and spec.col_bins) else B
    # voting-parallel on the rounds path (ISSUE 14): the per-round
    # election below replaces the full-histogram mesh reduce; only
    # elected bundle columns cross the mesh. Single-host (ax is None)
    # voting degenerates to the plain path — there is no wire to save.
    use_voting = bool(spec.voting_k and ax is not None)
    # per-node extras (VERDICT r4 item 4: extra_trees, ff_bynode, CEGB,
    # interaction constraints used to fall off the fast path onto the
    # ~30x-slower sequential permuted grower)
    per_node = bool(spec.extra_trees or spec.ff_bynode or spec.cegb
                    or spec.n_groups)
    if per_node and spec.mono_mode:
        raise ValueError(
            "monotone intermediate/advanced excludes per-node extras "
            "(boosting downgrades the combination to method=basic)"
        )
    if spec.mono_mode and (spec.voting_k or spec.n_forced):
        raise ValueError(
            "monotone intermediate/advanced excludes voting / forced "
            "splits (boosting downgrades the combination to method=basic)"
        )
    if spec.n_forced and forced is None:
        raise ValueError("spec.n_forced requires the forced= split plan")
    if spec.quant and gh_scale is None:
        raise ValueError("spec.quant requires gh_scale (level scales)")
    if per_node and (spec.extra_trees or spec.ff_bynode) \
            and rng_key is None:
        raise ValueError("extra_trees / ff_bynode need rng_key")
    NG = max(1, spec.n_groups)

    # SWAR one-hot scale for the int8 kernels (histogram.int8_oh_shift);
    # int8 itself is gated on the policy finding ANY safe shift
    oh_shift = int8_oh_shift(N, spec.quant_levels) if spec.quant_int8 else 0
    use_int8 = bool(spec.quant_int8 and oh_shift is not None)
    oh_shift = oh_shift or 0
    # fused partition+histogram kernel (VERDICT r4 item 2): one pass
    # computes the slot-packed child histograms AND the new row->leaf
    # vector; the separate (G, N) split-column select, membership
    # matmul and partition update disappear. Categorical splits ride
    # the kernel too: the row's own split-column bin gets a
    # single-feature SWAR one-hot contracted against the per-slot
    # category masks.
    use_fused = can_hist_round(N, S, G, Bc, spec.quant, int8=use_int8)
    # ---- reduce-scatter histogram wire (VERDICT r4 item 9): the full
    # psum ships every rank the whole f32 histogram; the reference
    # ships INTEGER histograms through ReduceScatter with per-rank
    # feature ownership (bin.h:63-81, data_parallel_tree_learner
    # .cpp:286) — each rank reduces only its own feature block (wire
    # and histogram-pool memory both /n_ranks, int32 payload), finds
    # the best split among owned features, and the global winner is an
    # all-gather argmax (SyncUpGlobalBestSplit). Quantized sums are
    # exact integers, so the int32 wire is lossless. Irrelevant on ICI
    # where psum is near-free; 4-8x wire on DCN at pod scale.
    # exactness gate (ADVICE r5 medium): the int32 wire is only
    # lossless while the worst-case integer sums fit — global cell sum
    # under 2^31 (int32 wrap) and per-rank f32 accumulation under 2^24
    # (exact-integer range) — else fall back to the f32 psum path.
    # histogram.rs_exact_ok; contract enforced by the jaxpr auditor
    # (analysis/jaxpr_audit.py rounds_quant_rs / _overflow entries).
    n_rs = spec.axis_size
    use_rs = bool(
        ax is not None and n_rs > 1 and spec.quant
        and not spec.efb and not spec.has_cat and not spec.cat_subset
        and not spec.mono_mode and not per_node
        # voting ships a NARROWER payload than reduce-scatter (2k
        # elected columns vs G/n owned); forced splits read arbitrary
        # feature columns of arbitrary leaves and need full-width
        # per-leaf histogram pools, not owned blocks
        and not spec.voting_k and not spec.n_forced
        and rs_exact_ok(N, n_rs, spec.quant_levels)
    )
    if use_voting:
        kG = min(spec.voting_k, G)
        k2 = min(2 * spec.voting_k, G)
        # narrowest exact integer wire for the elected-column psum:
        # partial sums en route can only shrink below the worst-case
        # global bound rs_wire_dtype checks, so the same policy applies
        vote_dt = (
            rs_wire_dtype(N, max(n_rs, 1), spec.quant_levels)
            if spec.quant else None
        )
    if use_rs:
        Gp = -(-G // n_rs) * n_rs  # feature axis padded to the mesh
        Gn = Gp // n_rs  # features owned per rank
        # narrowest exact wire payload (ROADMAP 3a / ISSUE 12 satellite):
        # int16 halves the off-chip reduce-scatter bytes whenever the
        # worst-case integer sums fit (histogram.rs_wire_dtype); the
        # jaxpr/cost auditors pin the chosen dtype and the exact bytes
        wire_dt = jnp.dtype(rs_wire_dtype(N, n_rs, spec.quant_levels))

        def _pad_tables(t, fill):
            return jnp.concatenate(
                [t, jnp.full((Gp - G,) + t.shape[1:], fill, t.dtype)]
            ) if Gp != G else t

        num_bins_p = _pad_tables(num_bins, 0)  # 0 bins -> no candidates
        nan_bin_p = _pad_tables(nan_bin, -1)
        mono_p = _pad_tables(mono, 0)
        is_cat_p = _pad_tables(is_cat, False)
        feat_mask_p = _pad_tables(feat_mask, False)
        ridx = lax.axis_index(ax)

        def my_block(t):
            """This rank's (Gn,) slice of a padded (Gp,) feature table."""
            return lax.dynamic_slice_in_dim(t, ridx * Gn, Gn)

        def rs_hist(h):
            """(..., G, Bc) local f32 integer sums -> this rank's owned
            (..., Gn, Bc) block, reduced over the mesh in the narrowest
            exact integer dtype (int16 when the sums fit, else int32)."""
            if Gp != G:
                pad = [(0, 0)] * (h.ndim - 2) + [(0, Gp - G), (0, 0)]
                h = jnp.pad(h, pad)
            out = lax.psum_scatter(
                h.astype(wire_dt), ax,
                scatter_dimension=h.ndim - 2, tiled=True,
            )
            return out.astype(jnp.float32)

        def select_global_rec(rec: SplitRecord) -> SplitRecord:
            """All-gather each rank's best and keep the max-gain winner
            (per child when fields are vectors; ties -> lowest rank,
            matching parallel_tree_learner.h:209)."""
            rec = rec._replace(feature=rec.feature + ridx * Gn)
            stacked = jax.tree.map(lambda a: lax.all_gather(a, ax), rec)
            if stacked.gain.ndim == 1:  # root: scalar fields
                w = jnp.argmax(stacked.gain)
                return jax.tree.map(lambda a: a[w], stacked)
            w = jnp.argmax(stacked.gain, axis=0)  # (children,)

            def pick(a):  # (n, children, ...) -> (children, ...)
                return jax.vmap(lambda col, wi: col[wi],
                                in_axes=(1, 0))(a, w)

            return jax.tree.map(pick, stacked)
    else:
        Gn = G

    def exp_hist(h, g_sum, h_sum, c_sum):
        if spec.efb:
            return expand_hist(h, g_sum, h_sum, c_sum, bundle)
        return h

    # shared per-node machinery (grower.make_node_candidates), vmapped
    # over each round's children; the draw ORDER differs from
    # sequential growth, which is fine — round batching already grows a
    # different-but-equivalent greedy tree
    node_candidates = make_node_candidates(
        spec, params, feat_mask, num_bins, nan_bin, rng_key, group_mat,
        cegb, F,
    )

    if spec.quant:
        gh8 = build_gh8_quant(grad * mask, hess * mask, mask)  # (8, N)
        scale3 = jnp.stack(
            [gh_scale[0], gh_scale[1], jnp.float32(1.0)]
        )  # (3,)
        s8 = jnp.sum(gh8, axis=1)
        root = jnp.stack([s8[0], s8[1], s8[2]])
        if ax is not None:
            root = lax.psum(root, ax)
        root = root * scale3
        hist0 = hist_nat_slots(
            bins_fm, gh8, jnp.zeros(N, jnp.int32), 1, Bc, quant=True,
            int8=use_int8, oh_shift=oh_shift,
        )[0]
        if use_rs:
            hist0 = rs_hist(hist0)  # (3, Gn, Bc) owned block, int wire
        elif ax is not None:
            hist0 = lax.psum(hist0, ax)
        hist0 = hist0 * scale3[:, None, None]
    else:
        scale3 = None
        gh8 = build_gh8(grad * mask, hess * mask, mask)  # (8, N)
        root = root_sums(gh8, ax)
        hist0 = histogram(bins_fm, gh8, Bc)
        if ax is not None:
            hist0 = lax.psum(hist0, ax)
    root_out = leaf_output(root[0], root[1], params)
    if per_node:
        lg0 = jnp.ones((L, NG), bool)
        pu0 = jnp.zeros((L, F), bool)
        fu0 = cegb.used if spec.cegb else jnp.zeros(F, bool)
        fm0, rb0, pen0 = node_candidates(jnp.int32(0), lg0[0], pu0[0],
                                         root[2], fu0)
    else:
        lg0 = jnp.zeros((L, 0), bool)
        pu0 = jnp.zeros((L, 0), bool)
        fu0 = jnp.zeros(0, bool)
        fm0, rb0, pen0 = feat_mask, None, None
    if use_rs:
        # owned-feature search + global winner (local feature ids
        # shifted to global inside select_global_rec)
        nb_t, nan_t = my_block(num_bins_p), my_block(nan_bin_p)
        mono_t, iscat_t = my_block(mono_p), my_block(is_cat_p)
        fm_t = my_block(feat_mask_p)
        rec0 = select_global_rec(best_split(
            hist0, root[0], root[1], root[2], nb_t, nan_t, mono_t,
            iscat_t, params, fm_t, cat_subset=spec.cat_subset,
            parent_output=root_out))
    else:
        nb_t, nan_t, mono_t, iscat_t, fm_t = (
            num_bins, nan_bin, mono, is_cat, feat_mask)
        rec0 = best_split(exp_hist(hist0, root[0], root[1], root[2]),
                          root[0], root[1], root[2], num_bins, nan_bin,
                          mono, is_cat, params, fm0,
                          cat_subset=spec.cat_subset,
                          parent_output=root_out,
                          penalty=pen0, rand_bin=rb0)

    Gc = Gn if use_rs else G  # pool feature width (owned block under rs)
    hist = jnp.zeros((L, 3, Gc, Bc), jnp.float32).at[0].set(hist0)
    best = _set_best(_empty_best(L, B), jnp.int32(0), rec0, rec0.gain)

    tree = TreeArrays(
        num_nodes=jnp.int32(0),
        node_feature=jnp.zeros(L - 1, jnp.int32),
        node_bin=jnp.zeros(L - 1, jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_default_left=jnp.zeros(L - 1, bool),
        node_cat=jnp.zeros(L - 1, bool),
        node_cat_mask=jnp.zeros((L - 1, B), bool),
        node_left=jnp.zeros(L - 1, jnp.int32),
        node_right=jnp.zeros(L - 1, jnp.int32),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_weight=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32).at[0].set(root_out),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_depth=jnp.zeros(L, jnp.int32),
    )

    valid_f = jnp.ones(N, jnp.float32) if valid is None else valid
    iota_L = jnp.arange(L, dtype=jnp.int32)

    # ---- S-ladder: early rounds are candidate-limited (1, 2, 4, ...
    # leaves have positive gain), yet the slot-packed kernel's matmul
    # costs M = S x channels rows REGARDLESS of how many slots are
    # live — a full-width S=48 pass for a 1-candidate round wastes
    # ~4 ms of MXU time. The while body therefore switches between
    # narrow/mid/full kernel widths by live candidate count. Selection
    # is unchanged (top-k of a wider k picks the same set), so the
    # grown tree is bit-identical to the single-width formulation.
    widths = tuple(w for w in (8, 32) if w < S) + (S,)

    # ---- budget-aware tail (small data): round batching deviates from
    # best-first greedy once the leaf budget binds — children created
    # this round never compete against this round's remaining
    # candidates. Capping a round's splits at HALF the remaining budget
    # makes the tail approach exact greedy (the last splits go one at a
    # time). Extra tail rounds cost ~a histogram pass each, so the cap
    # is enabled only where passes are cheap (small N) and the quality
    # effect is measurable: at bench scale (1M x 28, 255 leaves) the
    # boundary effect is statistically negligible while ~5 extra rounds
    # would cost ~15% throughput. Measured on examples/binary (7k rows,
    # 63 leaves): closes most of the rounds-vs-exact AUC gap.
    tail_exact = N <= 32 * 8192  # 262144 device rows

    def body(s: _NState) -> _NState:
        budget0 = (L - 1) - s.i
        n_pos = jnp.sum(s.best.gain > 0.0).astype(jnp.int32)
        n_cand = jnp.minimum(budget0, n_pos)
        if spec.n_forced:
            # forced phase: ONE split per round so Tree::Split leaf
            # numbering matches the BFS plan's precomputed ids (the
            # plan was laid out for sequential growth); n_pos can be 0
            # here — the forced split doesn't need positive gain
            n_cand = jnp.where(
                s.i < forced.n, jnp.int32(1), n_cand
            )
        if tail_exact:
            n_cand = jnp.minimum(n_cand, jnp.maximum((budget0 + 1) // 2, 1))
        bidx = jnp.sum(
            n_cand > jnp.asarray(widths[:-1], jnp.int32)
        ).astype(jnp.int32)
        s = s._replace(r=s.r.at[bidx].add(1).at[-1].add(1))
        return lax.switch(
            bidx, [partial(round_step, Sk=w, n_max=n_cand) for w in widths],
            s
        )

    def round_step(s: _NState, Sk: int, n_max=None) -> _NState:
        t = s.tree
        i = s.i
        S = Sk  # kernel width for this round (see the ladder above)
        iota_S = jnp.arange(S, dtype=jnp.int32)

        # ---- select this round's splits: top-k by gain within budget.
        # depth limits were already folded into best.gain when the
        # children were scored. top_k returns gains sorted descending,
        # so active slots form the prefix 0..n_split-1.
        budget = (L - 1) - i
        cap = jnp.minimum(budget, S)
        if n_max is not None:
            cap = jnp.minimum(cap, n_max)  # budget-aware tail (above)
        rec = s.best  # per-leaf records, fields (L,)
        gain_sel = s.best.gain
        if spec.n_forced:
            # ---- forced splits (ForceSplits, serial_tree_learner
            # .cpp:627) on the round-batched grower: while i < forced.n
            # the round splits exactly ONE prescribed leaf at the
            # prescribed (feature, threshold-bin) — body() caps the
            # round budget at 1 during the forced phase so Tree::Split
            # leaf numbering matches the plan's precomputed ids. The
            # per-leaf best record is overwritten at the forced leaf and
            # its selection gain raised to BIG so top_k picks it first;
            # invalid entries (empty child / exhausted plan) fall back
            # to the best-gain split, same documented deviation as the
            # permuted oracle (later entries keep PRE-COMPUTED leaf ids)
            fi = jnp.minimum(i, spec.n_forced - 1)
            fl = forced.leaf[fi]
            ff = forced.feature[fi]
            fb = forced.bin[fi]
            fh = exp_hist(s.hist[fl], s.leaf_g[fl], s.leaf_h[fl],
                          s.leaf_c[fl])
            cg_f = jnp.cumsum(fh[0, ff])
            chs_f = jnp.cumsum(fh[1, ff])
            cc_f = jnp.cumsum(fh[2, ff])
            flg, flh, flc = cg_f[fb], chs_f[fb], cc_f[fb]
            fpg, fph, fpn = s.leaf_g[fl], s.leaf_h[fl], s.leaf_c[fl]
            gain_f = (
                leaf_gain(flg, flh, params)
                + leaf_gain(fpg - flg, fph - flh, params)
                - leaf_gain(fpg, fph, params)
            )
            use_f = (i < forced.n) & (flc > 0) & (fpn - flc > 0)

            def put(a, v):
                return jnp.where(use_f, a.at[fl].set(v), a)

            rec = SplitRecord(
                gain=put(rec.gain, gain_f),
                feature=put(rec.feature, ff),
                bin=put(rec.bin, fb),
                default_left=put(rec.default_left, False),
                is_cat=put(rec.is_cat, False),
                cat_mask=put(rec.cat_mask, jnp.zeros(B, bool)),
                left_g=put(rec.left_g, flg),
                left_h=put(rec.left_h, flh),
                left_c=put(rec.left_c, flc),
                right_g=put(rec.right_g, fpg - flg),
                right_h=put(rec.right_h, fph - flh),
                right_c=put(rec.right_c, fpn - flc),
            )
            gain_sel = put(gain_sel, BIG)
        topv, topl = lax.top_k(gain_sel, S)
        take = (iota_S < cap) & (topv > 0.0)
        if spec.mono_mode:
            # ---- same-round conflict guard (intermediate constraints):
            # two selected leaves on OPPOSITE sides of a shared monotone
            # ancestor may not both split this round — their bounds were
            # computed from each other's PRE-round extrema, so
            # simultaneous updates could cross. Defer every candidate
            # that conflicts with ANY higher-gain candidate (slots are
            # gain-sorted); deferred leaves split next round under
            # refreshed bounds. The sequential reference
            # (monotone_constraints.hpp:516) never faces this because it
            # recomputes bounds after every single split.
            tl_c = jnp.minimum(topl, L - 1)
            a_in = s.anc_in[tl_c]  # (S, L-1)
            a_lf = s.anc_left[tl_c]
            node_m = (mono[t.node_feature] != 0) & ~t.node_cat
            node_alive = jnp.arange(L - 1, dtype=jnp.int32) < i
            mono_n = (node_m & node_alive)[None, None, :]
            conf = jnp.any(
                a_in[:, None, :] & a_in[None, :, :]
                & (a_lf[:, None, :] ^ a_lf[None, :, :]) & mono_n,
                axis=2,
            )  # (S, S) — shares a live monotone ancestor, opposite sides
            earlier = iota_S[None, :] < iota_S[:, None]
            take = take & ~jnp.any(conf & earlier & take[None, :], axis=1)
        sel_leaf = jnp.where(take, topl, L)  # (S,) L = inactive slot
        sel = jnp.zeros(L, bool).at[sel_leaf].set(True, mode="drop")
        n_split = jnp.sum(take).astype(jnp.int32)
        # node rank = cumulative count of TAKEN slots before this one:
        # node ids must stay consecutive even when the monotone conflict
        # guard punches holes in the gain-sorted prefix (without holes
        # this equals the slot index)
        rank_s = (jnp.cumsum(take.astype(jnp.int32)) - 1).astype(jnp.int32)
        rank = jnp.zeros(L, jnp.int32).at[sel_leaf].set(rank_s, mode="drop")
        node_id = i + rank
        new_id = i + 1 + rank
        drop_node = jnp.where(sel, node_id, L - 1)  # L-1 -> mode=drop
        drop_new = jnp.where(sel, new_id, L)

        # ---- outputs / monotone intervals, vectorized over leaves ----
        pmin, pmax = s.leaf_min, s.leaf_max
        lo, ro = split_leaf_outputs(rec, params, num_bins, spec.cat_subset,
                                    t.leaf_value, pmin, pmax)
        lmin, lmax, rmin, rmax = monotone_child_intervals(
            rec, mono, lo, ro, pmin, pmax
        )
        depth_new = t.leaf_depth + 1

        # ---- tree bookkeeping (Tree::Split, batched) ----
        p = s.leaf_parent
        pc = jnp.maximum(p, 0)
        p_is_left = t.node_left[pc] == ~iota_L
        fix = sel & (p >= 0)
        node_left = t.node_left.at[
            jnp.where(fix & p_is_left, pc, L - 1)
        ].set(node_id, mode="drop")
        node_right = t.node_right.at[
            jnp.where(fix & ~p_is_left, pc, L - 1)
        ].set(node_id, mode="drop")
        node_left = node_left.at[drop_node].set(~iota_L, mode="drop")
        node_right = node_right.at[drop_node].set(~drop_new, mode="drop")

        tree_new = TreeArrays(
            num_nodes=i + n_split,
            node_feature=t.node_feature.at[drop_node].set(rec.feature, mode="drop"),
            node_bin=t.node_bin.at[drop_node].set(rec.bin, mode="drop"),
            node_gain=t.node_gain.at[drop_node].set(rec.gain, mode="drop"),
            node_default_left=t.node_default_left.at[drop_node].set(
                rec.default_left, mode="drop"
            ),
            node_cat=t.node_cat.at[drop_node].set(rec.is_cat, mode="drop"),
            node_cat_mask=t.node_cat_mask.at[drop_node].set(
                rec.cat_mask, mode="drop"
            ),
            node_left=node_left,
            node_right=node_right,
            node_value=t.node_value.at[drop_node].set(t.leaf_value, mode="drop"),
            node_weight=t.node_weight.at[drop_node].set(s.leaf_h, mode="drop"),
            node_count=t.node_count.at[drop_node].set(s.leaf_c, mode="drop"),
            leaf_value=jnp.where(sel, lo, t.leaf_value)
            .at[drop_new].set(ro, mode="drop"),
            leaf_weight=jnp.where(sel, rec.left_h, t.leaf_weight)
            .at[drop_new].set(rec.right_h, mode="drop"),
            leaf_count=jnp.where(sel, rec.left_c, t.leaf_count)
            .at[drop_new].set(rec.right_c, mode="drop"),
            leaf_depth=jnp.where(sel, depth_new, t.leaf_depth)
            .at[drop_new].set(depth_new, mode="drop"),
        )

        # ---- per-row split decision for all selected leaves at once ----
        # Every per-row leaf-dependent scalar (split column, threshold
        # bin, default direction, slot rank, smaller side, membership)
        # comes from ONE (N, S) @ (S, k) MXU contraction against the
        # selected leaves' parameters. A (N,) jnp.take from an (L,)
        # table costs ~1 ms each on TPU (no vector-gather hardware) and
        # the old (L*B,) category-mask flat gather ~10 ms; the one-hot
        # matmul is ~20 us for all of them together
        # (tools/tpu_gather_probe.py). The contraction runs in f32:
        # packed values include feature/column ids and bin thresholds,
        # which exceed bf16's exact-integer range (256) on wide or
        # deep-binned datasets; f32 is exact to 2^24 and the (N,S)@(S,9)
        # matmul is far too small for the precision to cost wall time.
        # On the fused-kernel path all of this happens INSIDE the
        # histogram pass (pallas_hist._round_kernel) — see use_fused.
        left_smaller = rec.left_c <= rec.right_c  # (L,) — GLOBAL counts,
        # shard-consistent under data parallelism (derived from the
        # psum'd parent histogram during split search)
        sl_i = jnp.minimum(sel_leaf, L - 1)  # (S,) clipped for indexing
        live = (sel_leaf < L).astype(jnp.float32)  # (S,) pad slots drop
        feat_s = rec.feature[sl_i]  # (S,) tiny gathers from (L,) tables
        col_s = bundle.bundle_of[feat_s] if spec.efb else feat_s
        nan_s = nan_bin[feat_s]
        new_id_s = jnp.where(take, i + 1 + rank_s, L)

        def vote_reduce(sh):
            # ---- GlobalVoting election (parallel_tree_learner.h:152 /
            # voting_parallel_tree_learner.cpp), per ROUND: each shard
            # proposes its top-k columns by LOCAL gain over this round's
            # smaller children (max over live slots), votes + summed
            # gains elect 2k columns, and ONLY those columns cross the
            # mesh (gather-by-index psum, int16/int32 payload when the
            # quantized sums are exact — histogram.rs_wire_dtype). The
            # election unit is the bundle column, so voting composes
            # with EFB. Unlike the permuted oracle's per-SPLIT election
            # this elects once per round for all slots jointly — the
            # same PV-Tree approximation at one wire round per
            # histogram pass (documented deviation; parity tests pin
            # the saturated-election case where both coincide).
            local = sh * scale3[:, None, None] if spec.quant else sh
            # per-slot (g, h, count) totals from column 0's bin sums:
            # bins_fm is dense, so every device column partitions the
            # slot's rows
            lsum = jnp.sum(local[:, :, 0, :], axis=-1)  # (S, 3)

            def slot_gains(h, g_, h__, c_):
                return feature_best_gains(
                    exp_hist(h, g_, h__, c_), g_, h__, c_, num_bins,
                    nan_bin, mono, is_cat, params, feat_mask,
                    cat_subset=spec.cat_subset,
                )

            lg_s = jax.vmap(slot_gains)(
                local, lsum[:, 0], lsum[:, 1], lsum[:, 2]
            )  # (S, F) local per-feature gains
            lg_s = jnp.where(take[:, None], lg_s, NEG_INF)  # dead slots
            fgain = jnp.max(lg_s, axis=0)  # (F,) best over live slots
            if spec.efb:
                col_gain = jnp.full(G, NEG_INF).at[bundle.bundle_of].max(
                    fgain
                )
            else:
                col_gain = fgain
            _, topi = lax.top_k(col_gain, kG)
            in_topk = jnp.zeros(G, bool).at[topi].set(True)
            votes = lax.psum(in_topk.astype(jnp.float32), ax)
            score = lax.psum(
                jnp.where(in_topk, jnp.maximum(col_gain, 0.0), 0.0), ax
            )
            _, eidx = lax.top_k(votes * 1e12 + score, k2)
            if spec.n_forced:
                # pin the forced plan's columns into every election:
                # forced splits read their prescribed feature's column
                # unconditionally, so it must always carry global sums
                # (this lifts the old voting_k-excludes-forced guard;
                # duplicate indices scatter identical psum'd slices)
                fcols = (bundle.bundle_of[forced.feature] if spec.efb
                         else forced.feature)
                eidx = jnp.concatenate([eidx, fcols])
            elected_cols = jnp.zeros(G, bool).at[eidx].set(True)
            payload = sh[:, :, eidx, :]  # (S, 3, 2k[+n_forced], Bc)
            if vote_dt is not None:
                comp = lax.psum(payload.astype(vote_dt), ax).astype(
                    jnp.float32
                )
            else:
                comp = lax.psum(payload, ax)
            sh = jnp.zeros_like(sh).at[:, :, eidx, :].set(comp)
            el = elected_cols[bundle.bundle_of] if spec.efb else elected_cols
            return sh, el  # el: (F,) feature-space elected mask

        def reduce_slots(sh):
            """Mesh reduce of the (S, 3, G|Gn, Bc) local slot histograms
            — elected-columns-only under voting, reduce-scatter or psum
            otherwise — then the dequantization scale. Returns the
            reduced hists and the elected (F,) mask (None off voting)."""
            el = None
            if use_voting:
                sh, el = vote_reduce(sh)
            elif use_rs:
                sh = rs_hist(sh)  # int wire, owned block
            elif ax is not None:
                sh = lax.psum(sh, ax)
            if spec.quant:
                sh = sh * scale3[:, None, None]
            return sh, el

        if use_fused:
            zs = jnp.zeros(S, jnp.int32)
            if spec.efb:
                efb_cols = [bundle.off_lo[feat_s], bundle.mfb[feat_s],
                            bundle.width[feat_s]]
            else:
                efb_cols = [zs, jnp.full(S, -1, jnp.int32), zs]
            params16 = jnp.stack(
                [
                    sel_leaf, col_s,
                    rec.bin[sl_i],
                    rec.default_left[sl_i].astype(jnp.int32),
                    nan_s,
                    left_smaller[sl_i].astype(jnp.int32),
                    new_id_s,
                ] + efb_cols + [
                    rec.is_cat[sl_i].astype(jnp.int32),  # col 10
                ] + [zs] * 5,
                axis=1,
            ).astype(jnp.int32)  # (S, 16)
            coh = (
                col_s[:, None] == jnp.arange(G, dtype=jnp.int32)[None, :]
            ).astype(jnp.float32)  # (S, G)
            if spec.has_cat:
                cm_s = rec.cat_mask[sl_i].astype(jnp.int8)  # (S, B)
                if Bc > B:  # kernel bin space is the bundle width
                    cm_s = jnp.pad(cm_s, ((0, 0), (0, Bc - B)))
            else:
                cm_s = None
            slot_hists, pleaf_new = hist_round(
                bins_fm, gh8, s.pleaf, params16, coh, S, Bc,
                quant=spec.quant, int8=use_int8, oh_shift=oh_shift,
                efb=spec.efb, cat_mask=cm_s,
            )
            slot_hists, elected = reduce_slots(slot_hists)
        else:
            pack_cols = [
                col_s.astype(jnp.float32),  # 0: device bin column
                rec.bin[sl_i].astype(jnp.float32),  # 1: threshold bin
                rec.default_left[sl_i].astype(jnp.float32),  # 2
                rec.is_cat[sl_i].astype(jnp.float32),  # 3
                nan_s.astype(jnp.float32),  # 4: NaN bin (-1 = none)
                iota_S.astype(jnp.float32),  # 5: histogram slot index
                left_smaller[sl_i].astype(jnp.float32),  # 6
                jnp.ones(S, jnp.float32),  # 7: membership indicator
                feat_s.astype(jnp.float32),  # 8: true feature id (EFB)
                new_id_s.astype(jnp.float32),  # 9: new (right) leaf id
            ]
            pack = jnp.stack(pack_cols, axis=1) * live[:, None]  # (S, 10)
            memb = (s.pleaf[:, None] == sel_leaf[None, :])  # (N, S)
            # HIGHEST precision: the default TPU matmul multiplies f32
            # in bf16, which would corrupt packed ids above 256 — the
            # exact case the f32 pack exists for
            vals = lax.dot_general(
                memb.astype(jnp.float32), pack, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST,
            )  # (N, 10); rows outside every selected leaf are all-zero
            in_split = vals[:, 7] > 0.5
            col_row = vals[:, 0].astype(jnp.int32)
            bin_row = vals[:, 1].astype(jnp.int32)
            dl_row = vals[:, 2] > 0.5
            cat_row = vals[:, 3] > 0.5
            nan_row = vals[:, 4].astype(jnp.int32)
            rank_row = vals[:, 5].astype(jnp.int32)
            small_row = vals[:, 6] > 0.5
            # masked select of each row's split column (no 2D gather)
            col_sel = (col_row[None, :]
                       == jnp.arange(G, dtype=jnp.int32)[:, None])
            fbins = jnp.sum(jnp.where(col_sel, bins_fm, 0), axis=0)
            if spec.efb:
                f_row = vals[:, 8].astype(jnp.int32)
                fbins = decode_feature_bins(fbins, f_row, bundle)
            if spec.has_cat:
                # category-set membership as a bin-one-hot contraction:
                # hit[r] = cat_mask[slot(r), fbins[r]] without the
                # (L*B,) flat gather
                ob = (fbins[:, None]
                      == jnp.arange(B, dtype=jnp.int32)[None, :])
                cm_sel = (rec.cat_mask[sl_i].astype(jnp.bfloat16)
                          * live[:, None])  # (S, B)
                hits = lax.dot_general(
                    ob.astype(jnp.bfloat16), cm_sel,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # (N, S)
                cat_hit = jnp.sum(hits * memb, axis=1) > 0.5
            else:
                cat_hit = jnp.zeros_like(in_split)
            go_left = jnp.where(
                cat_row,
                cat_hit,
                (fbins <= bin_row)
                | (dl_row & (fbins == nan_row) & (nan_row >= 0)),
            )
            new_id_row = vals[:, 9].astype(jnp.int32)
            pleaf_new = jnp.where(
                in_split & ~go_left, new_id_row, s.pleaf
            ).astype(jnp.int32)

            # ---- smaller-child histograms: one slot-packed pass ----
            go_small = go_left == small_row
            hslot = jnp.where(
                in_split & go_small, rank_row, S
            ).astype(jnp.int32)
            slot_hists = hist_nat_slots(
                bins_fm, gh8, hslot, S, Bc, quant=spec.quant,
                int8=use_int8, oh_shift=oh_shift,
            )  # (S, 3, G, Bc)
            slot_hists, elected = reduce_slots(slot_hists)

        # ---- per-slot child hists: smaller from the pass, larger by
        # subtraction; scatter both into the pool. Work stays O(S), not
        # O(L) — only the <= S split leaves are touched.
        sl_c = sl_i  # (S,) clipped for gathers (computed above)
        parent_s = s.hist[sl_c]  # (S, 3, G, Bc)
        large_s = parent_s - slot_hists
        ls_s = left_smaller[sl_c][:, None, None, None]
        left_s = jnp.where(ls_s, slot_hists, large_s)
        right_s = jnp.where(ls_s, large_s, slot_hists)
        hist = s.hist.at[sel_leaf].set(left_s, mode="drop")
        hist = hist.at[new_id_s].set(right_s, mode="drop")

        hist_valid2 = s.hist_valid
        if use_voting:
            # the smaller child's histogram holds global sums exactly at
            # the elected columns; the larger sibling's subtraction is
            # additionally only sound where the PARENT's stored column
            # was global (permuted.py valid_small / valid_large)
            valid_parent_s = s.hist_valid[sl_c]  # (S, F)
            valid_small = jnp.broadcast_to(
                elected[None, :], valid_parent_s.shape
            )
            valid_large = valid_small & valid_parent_s
            ls_v = left_smaller[sl_c][:, None]
            valid_left = jnp.where(ls_v, valid_small, valid_large)
            valid_right = jnp.where(ls_v, valid_large, valid_small)
            hist_valid2 = (
                s.hist_valid.at[sel_leaf].set(valid_left, mode="drop")
                .at[new_id_s].set(valid_right, mode="drop")
            )

        # ---- best splits for the new children, batched over 2S ----
        def child_best(h, g_, h__, c_, po, cmn, cmx, fm=None, rb=None,
                       pen=None):
            # under use_rs the tables are this rank's owned block and
            # the winner is elected globally by the caller
            return best_split(
                exp_hist(h, g_, h__, c_), g_, h__, c_, nb_t, nan_t,
                mono_t, iscat_t, params, fm_t if fm is None else fm,
                cat_subset=spec.cat_subset, parent_output=po,
                cmin=cmn, cmax=cmx, penalty=pen, rand_bin=rb,
            )

        leaf_g2 = jnp.where(sel, rec.left_g, s.leaf_g) \
            .at[drop_new].set(rec.right_g, mode="drop")
        leaf_h2 = jnp.where(sel, rec.left_h, s.leaf_h) \
            .at[drop_new].set(rec.right_h, mode="drop")
        leaf_c2 = jnp.where(sel, rec.left_c, s.leaf_c) \
            .at[drop_new].set(rec.right_c, mode="drop")

        anc_in2, anc_left2 = s.anc_in, s.anc_left
        flo2, fhi2 = s.leaf_flo, s.leaf_fhi
        lg2, pu2, fu2 = s.leaf_groups, s.path_used, s.feat_used
        if not spec.mono_mode:
            ch_hist = jnp.concatenate([left_s, right_s])  # (2S, 3, G, Bc)
            ch_g = jnp.concatenate([rec.left_g[sl_c], rec.right_g[sl_c]])
            ch_h = jnp.concatenate([rec.left_h[sl_c], rec.right_h[sl_c]])
            ch_c = jnp.concatenate([rec.left_c[sl_c], rec.right_c[sl_c]])
            ch_po = jnp.concatenate([lo[sl_c], ro[sl_c]])
            ch_mn = jnp.concatenate([lmin[sl_c], rmin[sl_c]])
            ch_mx = jnp.concatenate([lmax[sl_c], rmax[sl_c]])
            if use_voting:
                # only columns whose stored sums are global may be
                # searched — unelected columns hold local/garbage sums
                ch_valid = jnp.concatenate([valid_left, valid_right])
            if per_node:
                # per-node candidate machinery for this round's 2S
                # children (permuted.py node_candidates semantics)
                f_split_s = rec.feature[sl_c]  # (S,)
                onehot_f = (jnp.arange(F, dtype=jnp.int32)[None, :]
                            == f_split_s[:, None])  # (S, F)
                child_groups = s.leaf_groups[sl_c]  # (S, NG)
                if spec.n_groups:
                    child_groups = child_groups & group_mat[:, f_split_s].T
                pu_child = s.path_used[sl_c] | onehot_f  # (S, F)
                fu2 = s.feat_used | jnp.any(
                    onehot_f & take[:, None], axis=0
                )
                node_id_sl2 = i + rank_s  # (S,)
                salts = jnp.concatenate(
                    [2 * node_id_sl2 + 1, 2 * node_id_sl2 + 2])
                cg2 = jnp.concatenate([child_groups, child_groups])
                puc2 = jnp.concatenate([pu_child, pu_child])
                ch_fm, ch_rb, ch_pen = jax.vmap(
                    node_candidates, in_axes=(0, 0, 0, 0, None)
                )(salts, cg2, puc2, ch_c, fu2)
                if use_voting:
                    ch_fm = ch_fm & ch_valid
                ch_rec = jax.vmap(child_best)(
                    ch_hist, ch_g, ch_h, ch_c, ch_po, ch_mn, ch_mx,
                    ch_fm, ch_rb, ch_pen,
                )
                lg2 = s.leaf_groups.at[sel_leaf].set(
                    child_groups, mode="drop"
                ).at[new_id_s].set(child_groups, mode="drop")
                pu2 = s.path_used.at[sel_leaf].set(
                    pu_child, mode="drop"
                ).at[new_id_s].set(pu_child, mode="drop")
            elif use_voting:
                ch_rec = jax.vmap(child_best)(
                    ch_hist, ch_g, ch_h, ch_c, ch_po, ch_mn, ch_mx,
                    feat_mask[None, :] & ch_valid,
                )
            else:
                ch_rec = jax.vmap(child_best)(
                    ch_hist, ch_g, ch_h, ch_c, ch_po, ch_mn, ch_mx
                )
            if use_rs:
                # global winner per child across feature owners
                ch_rec = select_global_rec(ch_rec)
            depth_ok_s = (spec.max_depth <= 0) | (
                depth_new[sl_c] < spec.max_depth)
            ch_gain = jnp.where(
                jnp.concatenate([depth_ok_s, depth_ok_s]), ch_rec.gain,
                NEG_INF
            )
            ch_leaf = jnp.concatenate([sel_leaf, new_id_s])

            def scat(dst, val):
                return dst.at[ch_leaf].set(val, mode="drop")

            best2 = SplitRecord(
                gain=scat(s.best.gain, ch_gain),
                feature=scat(s.best.feature, ch_rec.feature),
                bin=scat(s.best.bin, ch_rec.bin),
                default_left=scat(s.best.default_left, ch_rec.default_left),
                is_cat=scat(s.best.is_cat, ch_rec.is_cat),
                cat_mask=scat(s.best.cat_mask, ch_rec.cat_mask),
                left_g=scat(s.best.left_g, ch_rec.left_g),
                left_h=scat(s.best.left_h, ch_rec.left_h),
                left_c=scat(s.best.left_c, ch_rec.left_c),
                right_g=scat(s.best.right_g, ch_rec.right_g),
                right_h=scat(s.best.right_h, ch_rec.right_h),
                right_c=scat(s.best.right_c, ch_rec.right_c),
            )
            nmin = jnp.where(sel, lmin, s.leaf_min) \
                .at[drop_new].set(rmin, mode="drop")
            nmax = jnp.where(sel, lmax, s.leaf_max) \
                .at[drop_new].set(rmax, mode="drop")
        else:
            # ---- intermediate constraints, round-batched (the
            # permuted grower's batch formulation of
            # monotone_constraints.hpp:516 GoUpToFindLeavesToUpdate):
            # 1. extend the ancestry matrices with this round's splits,
            # 2. recompute EVERY leaf's [min, max] from the actual
            #    output extrema of the opposite subtrees of its
            #    monotone ancestors,
            # 3. re-search every live leaf's best split under the new
            #    bounds (one vmapped pass keeps shapes static; the
            #    reference recomputes a leaves_to_update set).
            # left child keeps the parent's leaf id (bit set in place,
            # anc_left too); the right child copies the parent's
            # pre-round ancestry row (slot-indexed scatter, pads drop)
            iota_n = jnp.arange(L - 1, dtype=jnp.int32)
            node_id_sl = i + rank_s  # (S,) this round's node per slot
            rows_in = s.anc_in[sl_c] | (
                (iota_n[None, :] == node_id_sl[:, None]) & take[:, None]
            )  # (S, L-1)
            rows_lf = s.anc_left[sl_c]
            nm_leaf = (iota_n[None, :] == node_id[:, None]) & sel[:, None]
            anc_in2 = (s.anc_in | nm_leaf).at[new_id_s].set(
                rows_in, mode="drop")
            anc_left2 = (s.anc_left | nm_leaf).at[new_id_s].set(
                rows_lf, mode="drop")
            i_new = i + n_split
            leaf_out2 = tree_new.leaf_value
            valid_leaf = iota_L <= i_new
            node_m = mono[tree_new.node_feature] * (
                ~tree_new.node_cat).astype(jnp.int32)
            node_alive = jnp.arange(L - 1, dtype=jnp.int32) < i_new
            in_l = anc_in2 & anc_left2 & valid_leaf[:, None]
            in_r = anc_in2 & ~anc_left2 & valid_leaf[:, None]
            if spec.mono_mode == 2:
                # ---- advanced constraints (monotone_constraints
                # .hpp:858 AdvancedLeafConstraints): the opposite-
                # subtree extremum bounding leaf x through monotone
                # ancestor a is taken only over leaves r whose feature-
                # domain can actually meet x's — i.e. their bin ranges
                # intersect in every feature EXCEPT a's split feature
                # (x and r always differ there; a violating pair needs
                # a point equal in all other features, and two leaves
                # whose (lo, hi] bin intervals are disjoint in some
                # other feature admit no such point). Bin-interval
                # overlap over-approximates value equality, so the
                # refinement never drops a needed constraint; it is
                # strictly no looser than the intermediate broadcast.
                # 1. refine per-(leaf, feature) ranges with this
                # round's splits: numeric splits shrink the split
                # feature's interval (left hi=min(hi, bin); right
                # lo=max(lo, bin)); categorical splits and features
                # with a NaN bin keep the full range — their rows
                # don't partition by bin interval (conservative).
                refine = sel & ~rec.is_cat & (nan_bin[rec.feature] < 0)
                f_oh = (
                    jnp.arange(F, dtype=jnp.int32)[None, :]
                    == rec.feature[:, None]
                ) & refine[:, None]  # (L, F)
                hi_l = jnp.where(
                    f_oh, jnp.minimum(s.leaf_fhi, rec.bin[:, None]),
                    s.leaf_fhi,
                )
                lo_r = jnp.where(
                    f_oh, jnp.maximum(s.leaf_flo, rec.bin[:, None]),
                    s.leaf_flo,
                )
                # left child keeps the parent id in place; right child
                # scatters the parent's pre-round row, lo raised
                flo2 = s.leaf_flo.at[new_id_s].set(
                    lo_r[sl_c], mode="drop")
                fhi2 = jnp.where(sel[:, None], hi_l, s.leaf_fhi).at[
                    new_id_s].set(s.leaf_fhi[sl_c], mode="drop")
                # 2. pairwise per-feature (lo, hi] intersection and the
                # per-ancestor comparability mask ok_pair[x, r, a]:
                # ranges overlap everywhere except possibly on a's
                # split feature
                ivf = (
                    jnp.maximum(flo2[:, None, :], flo2[None, :, :])
                    < jnp.minimum(fhi2[:, None, :], fhi2[None, :, :])
                )  # (L, L, F)
                n_bad = jnp.sum(~ivf, axis=2)  # (L, L)
                bad_fa = ~jnp.take(
                    ivf,
                    jnp.minimum(tree_new.node_feature, F - 1),
                    axis=2,
                )  # (L, L, L-1) — disjoint on node a's split feature?
                ok_pair = (
                    n_bad[:, :, None] - bad_fa.astype(jnp.int32)
                ) <= 0
                # 3. per-(x, a) refined opposite-subtree extrema
                # replacing the intermediate method's broadcast rows

                def _ext(in_m, red, init):
                    sel_m = in_m[None, :, :] & ok_pair  # (L, L, L-1)
                    return red(
                        jnp.where(sel_m, leaf_out2[None, :, None], init),
                        axis=1,
                    )  # (L, L-1)

                Lmax = _ext(in_l, jnp.max, -BIG)
                Lmin = _ext(in_l, jnp.min, BIG)
                Rmax = _ext(in_r, jnp.max, -BIG)
                Rmin = _ext(in_r, jnp.min, BIG)
            else:
                Lmax = jnp.max(
                    jnp.where(in_l, leaf_out2[:, None], -BIG), axis=0
                )[None, :]
                Lmin = jnp.min(
                    jnp.where(in_l, leaf_out2[:, None], BIG), axis=0
                )[None, :]
                Rmax = jnp.max(
                    jnp.where(in_r, leaf_out2[:, None], -BIG), axis=0
                )[None, :]
                Rmin = jnp.min(
                    jnp.where(in_r, leaf_out2[:, None], BIG), axis=0
                )[None, :]
            inc = (node_alive & (node_m > 0))[None, :]
            dec = (node_alive & (node_m < 0))[None, :]
            cmax_mat = jnp.where(in_l & inc, Rmin, BIG)
            cmax_mat = jnp.where(in_r & dec, Lmin, cmax_mat)
            cmin_mat = jnp.where(in_r & inc, Lmax, -BIG)
            cmin_mat = jnp.where(in_l & dec, Rmax, cmin_mat)
            nmax = jnp.min(cmax_mat, axis=1)  # (L,)
            nmin = jnp.max(cmin_mat, axis=1)

            rec_all = jax.vmap(child_best)(
                hist, leaf_g2, leaf_h2, leaf_c2, leaf_out2, nmin, nmax
            )
            d_ok = (spec.max_depth <= 0) | (
                tree_new.leaf_depth < spec.max_depth)
            best2 = rec_all._replace(
                gain=jnp.where(valid_leaf & d_ok, rec_all.gain, NEG_INF)
            )

        return _NState(
            i=i + n_split,
            r=s.r,
            pleaf=pleaf_new,
            hist=hist,
            leaf_g=leaf_g2,
            leaf_h=leaf_h2,
            leaf_c=leaf_c2,
            leaf_parent=jnp.where(sel, node_id, s.leaf_parent)
            .at[drop_new].set(node_id, mode="drop"),
            leaf_min=nmin,
            leaf_max=nmax,
            anc_in=anc_in2,
            anc_left=anc_left2,
            leaf_groups=lg2,
            path_used=pu2,
            feat_used=fu2,
            hist_valid=hist_valid2,
            leaf_flo=flo2,
            leaf_fhi=fhi2,
            best=best2,
            tree=tree_new,
        )

    def _forced_valid(s: _NState):
        """Is step s.i a forced split with both children non-empty?"""
        fi = jnp.minimum(s.i, spec.n_forced - 1)
        fl = forced.leaf[fi]
        ff = forced.feature[fi]
        fb = forced.bin[fi]
        fh = exp_hist(s.hist[fl], s.leaf_g[fl], s.leaf_h[fl], s.leaf_c[fl])
        lc = jnp.cumsum(fh[2, ff])[fb]
        return (s.i < forced.n) & (lc > 0) & (s.leaf_c[fl] - lc > 0)

    def cond(s: _NState) -> jax.Array:
        keep = jnp.max(s.best.gain) > 0.0
        if spec.n_forced:
            # only continue for a forced step that can actually split
            # (both children non-empty) — the round body falls back to
            # the best-gain split otherwise, which `keep` already guards
            keep = keep | _forced_valid(s)
        return (s.i < L - 1) & keep

    state = _NState(
        i=jnp.int32(0),
        r=jnp.zeros(len(widths) + 1, jnp.int32),
        pleaf=jnp.where(valid_f > 0, 0, L).astype(jnp.int32),
        hist=hist,
        leaf_g=jnp.zeros(L, jnp.float32).at[0].set(root[0]),
        leaf_h=jnp.zeros(L, jnp.float32).at[0].set(root[1]),
        leaf_c=jnp.zeros(L, jnp.float32).at[0].set(root[2]),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        leaf_min=jnp.full(L, -BIG, jnp.float32),
        leaf_max=jnp.full(L, BIG, jnp.float32),
        anc_in=jnp.zeros((L, L - 1 if spec.mono_mode else 0), bool),
        anc_left=jnp.zeros((L, L - 1 if spec.mono_mode else 0), bool),
        leaf_groups=lg0,
        path_used=pu0,
        feat_used=fu0,
        # root histogram always crosses the mesh in full, so every
        # column starts globally valid
        hist_valid=jnp.ones((L, F if use_voting else 0), bool),
        leaf_flo=jnp.full(
            (L, F if spec.mono_mode == 2 else 0), -1, jnp.int32
        ),
        leaf_fhi=jnp.full(
            (L, F if spec.mono_mode == 2 else 0), B, jnp.int32
        ),
        best=best,
        tree=tree,
    )
    final = lax.while_loop(cond, body, state)

    row_leaf = final.pleaf
    if valid is not None:
        row_leaf = jnp.where(valid > 0, row_leaf, -1)
    if with_stats:
        return final.tree, row_leaf, {"widths": widths, "rounds": final.r}
    return final.tree, row_leaf
