"""LightGBM text model format: writer and parser.

Byte-compatible with the reference format (src/boosting/gbdt_model_text.cpp
SaveModelToString :314 / LoadModelFromString :424, per-tree blocks
src/io/tree.cpp Tree::ToString :343): versioned header, space-joined
feature_names / feature_infos, `Tree=N` blocks with num_leaves-1 node
arrays and num_leaves leaf arrays, `end of trees`, feature importances and
an echoed parameter block. This is the interop surface: models written
here load in reference LightGBM and vice versa.
"""

from __future__ import annotations

import io
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import log
from .boosting import GBDT
from .config import Config
from .tree import Tree

MODEL_VERSION = "v4"


def _fmt_d(values) -> str:
    return " ".join(str(int(v)) for v in values)


def _fmt_f(values, precision: int = 6) -> str:
    return " ".join(f"{float(v):g}" for v in values)


def _fmt_hp(values) -> str:
    """High-precision doubles (ArrayToString<true>)."""
    return " ".join(repr(float(v)) for v in values)


def _objective_to_string(cfg: Config) -> str:
    o = cfg.objective
    if o == "binary":
        return f"binary sigmoid:{cfg.sigmoid:g}"
    if o == "multiclass":
        return f"multiclass num_class:{cfg.num_class}"
    if o == "multiclassova":
        return f"multiclassova num_class:{cfg.num_class} sigmoid:{cfg.sigmoid:g}"
    if o == "lambdarank":
        return "lambdarank"
    if o == "quantile":
        return f"quantile alpha:{cfg.alpha:g}"
    if o == "huber":
        return f"huber alpha:{cfg.alpha:g}"
    if o == "fair":
        return f"fair c:{cfg.fair_c:g}"
    if o == "tweedie":
        return f"tweedie tweedie_variance_power:{cfg.tweedie_variance_power:g}"
    return o


def tree_to_string(t: Tree) -> str:
    n = t.num_leaves
    buf = io.StringIO()
    buf.write(f"num_leaves={n}\n")
    buf.write(f"num_cat={t.num_cat}\n")
    buf.write("split_feature=" + _fmt_d(t.split_feature) + "\n")
    buf.write("split_gain=" + _fmt_f(t.split_gain) + "\n")
    buf.write("threshold=" + _fmt_hp(t.threshold) + "\n")
    buf.write("decision_type=" + _fmt_d(t.decision_type) + "\n")
    buf.write("left_child=" + _fmt_d(t.left_child) + "\n")
    buf.write("right_child=" + _fmt_d(t.right_child) + "\n")
    buf.write("leaf_value=" + _fmt_hp(t.leaf_value) + "\n")
    buf.write("leaf_weight=" + _fmt_hp(t.leaf_weight) + "\n")
    buf.write("leaf_count=" + _fmt_d(t.leaf_count) + "\n")
    buf.write("internal_value=" + _fmt_f(t.internal_value) + "\n")
    buf.write("internal_weight=" + _fmt_f(t.internal_weight) + "\n")
    buf.write("internal_count=" + _fmt_d(t.internal_count) + "\n")
    if t.num_cat > 0:
        buf.write("cat_boundaries=" + _fmt_d(t.cat_boundaries) + "\n")
        buf.write("cat_threshold=" + _fmt_d(t.cat_threshold) + "\n")
    buf.write(f"is_linear={1 if t.is_linear else 0}\n")
    if t.is_linear:
        # linear-leaf blocks (tree.cpp:381-405 Tree::ToString is_linear)
        buf.write("leaf_const=" + _fmt_hp(t.leaf_const) + "\n")
        nfeat = [len(f) for f in t.leaf_features]
        buf.write("num_features=" + " ".join(str(x) for x in nfeat) + "\n")
        buf.write(
            "leaf_features="
            + " ".join(
                " ".join(str(f) for f in feats) for feats in t.leaf_features if feats
            )
            + "\n"
        )
        buf.write(
            "leaf_coeff="
            + " ".join(
                " ".join(repr(float(c)) for c in cs) for cs in t.leaf_coeff if cs
            )
            + "\n"
        )
    buf.write(f"shrinkage={t.shrinkage:g}\n")
    buf.write("\n")
    return buf.getvalue()


def save_model_string(
    gbdt: GBDT, cfg: Config, num_iteration: int = -1, start_iteration: int = 0
) -> str:
    ds = gbdt.train_set
    feature_names = ds.feature_names if ds is not None else getattr(gbdt, "feature_names", [])
    feature_infos = ds.feature_infos() if ds is not None else getattr(gbdt, "feature_infos_", ["none"] * len(feature_names))
    K = gbdt.num_class

    buf = io.StringIO()
    buf.write("tree\n")
    buf.write(f"version={MODEL_VERSION}\n")
    buf.write(f"num_class={cfg.num_class}\n")
    buf.write(f"num_tree_per_iteration={K}\n")
    buf.write("label_index=0\n")
    buf.write(f"max_feature_idx={len(feature_names) - 1}\n")
    buf.write(f"objective={_objective_to_string(cfg)}\n")
    buf.write("feature_names=" + " ".join(feature_names) + "\n")
    mc = list(cfg.monotone_constraints)
    if mc:
        buf.write("monotone_constraints=" + " ".join(str(int(v)) for v in mc) + "\n")
    buf.write("feature_infos=" + " ".join(feature_infos) + "\n")
    if gbdt.average_output:
        buf.write("average_output\n")

    total_iteration = len(gbdt.models) // K
    start_iteration = max(0, min(start_iteration, total_iteration))
    num_used = len(gbdt.models)
    if num_iteration > 0:
        num_used = min((start_iteration + num_iteration) * K, num_used)
    start_model = start_iteration * K

    tree_strs = []
    for i in range(start_model, num_used):
        tree_strs.append(f"Tree={i - start_model}\n" + tree_to_string(gbdt.models[i]) + "\n")
    buf.write("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs) + "\n")
    buf.write("\n")
    for s in tree_strs:
        buf.write(s)
    buf.write("end of trees\n")

    # feature importances (split counts) over exactly the dumped tree
    # range, sorted desc (gbdt_model_text.cpp:380 FeatureImportance
    # takes num_iteration). Summing over ALL models would let a sliced
    # save (snapshot / training checkpoint) leak later trees into the
    # footer — a checkpointed model must bit-match a run that stopped
    # at that round (docs/RESILIENCE.md).
    imp = np.zeros(len(feature_names))
    for t in gbdt.models[start_model:num_used]:
        imp += t.feature_importance_split(len(feature_names))
    pairs = [(int(imp[i]), feature_names[i]) for i in range(len(feature_names)) if imp[i] > 0]
    pairs.sort(key=lambda p: -p[0])
    buf.write("\nfeature_importances:\n")
    for v, name in pairs:
        buf.write(f"{name}={v}\n")

    buf.write("\nparameters:\n")
    for k, v in cfg.explicit_params().items():
        buf.write(f"[{k}: {v}]\n")
    buf.write("end of parameters\n")
    buf.write("\npandas_categorical:null\n")
    return buf.getvalue()


# ----------------------------------------------------------------------
def _node_to_dict(t: Tree, index: int) -> Dict[str, Any]:
    """Nested node dict (src/io/tree.cpp:462 NodeToJSON)."""
    if index >= 0:
        dt = int(t.decision_type[index])
        d: Dict[str, Any] = {
            "split_index": index,
            "split_feature": int(t.split_feature[index]),
            "split_gain": float(t.split_gain[index]),
        }
        if dt & 1:  # categorical
            ci = int(t.threshold[index])
            lo, hi = int(t.cat_boundaries[ci]), int(t.cat_boundaries[ci + 1])
            words = t.cat_threshold[lo:hi]
            cats = [
                32 * w + b
                for w in range(len(words))
                for b in range(32)
                if (int(words[w]) >> b) & 1
            ]
            d["threshold"] = "||".join(str(cv) for cv in cats)
            d["decision_type"] = "=="
        else:
            d["threshold"] = float(t.threshold[index])
            d["decision_type"] = "<="
        d["default_left"] = bool(dt & 2)
        d["missing_type"] = ("None", "Zero", "NaN")[min((dt >> 2) & 3, 2)]
        d["internal_value"] = float(t.internal_value[index]) if index < len(t.internal_value) else 0.0
        d["internal_weight"] = float(t.internal_weight[index]) if index < len(t.internal_weight) else 0.0
        d["internal_count"] = int(t.internal_count[index]) if index < len(t.internal_count) else 0
        d["left_child"] = _node_to_dict(t, int(t.left_child[index]))
        d["right_child"] = _node_to_dict(t, int(t.right_child[index]))
        return d
    leaf = ~index
    d = {
        "leaf_index": leaf,
        "leaf_value": float(t.leaf_value[leaf]),
        "leaf_weight": float(t.leaf_weight[leaf]) if leaf < len(t.leaf_weight) else 0.0,
        "leaf_count": int(t.leaf_count[leaf]) if leaf < len(t.leaf_count) else 0,
    }
    if t.is_linear:
        # linear-leaf model terms (extension: the reference ToJSON emits
        # none, so its dumps cannot round-trip linear trees; ours can —
        # keys only appear on linear models, non-linear dumps unchanged)
        d["leaf_const"] = (
            float(t.leaf_const[leaf]) if leaf < len(t.leaf_const) else 0.0
        )
        d["leaf_features"] = (
            [int(f) for f in t.leaf_features[leaf]]
            if leaf < len(t.leaf_features) else []
        )
        d["leaf_coeff"] = (
            [float(c) for c in t.leaf_coeff[leaf]]
            if leaf < len(t.leaf_coeff) else []
        )
    return d


def tree_to_dict(t: Tree, tree_index: int) -> Dict[str, Any]:
    """(src/io/tree.cpp:415 ToJSON)"""
    d: Dict[str, Any] = {
        "tree_index": tree_index,
        "num_leaves": t.num_leaves,
        "num_cat": t.num_cat,
        "shrinkage": t.shrinkage,
    }
    if t.is_linear:
        d["is_linear"] = True
    if t.num_leaves == 1:
        d["tree_structure"] = {
            "leaf_value": float(t.leaf_value[0]),
            "leaf_count": int(t.leaf_count[0]) if len(t.leaf_count) else 0,
        }
        if t.is_linear and len(t.leaf_const):
            d["tree_structure"]["leaf_const"] = float(t.leaf_const[0])
            d["tree_structure"]["leaf_features"] = []
            d["tree_structure"]["leaf_coeff"] = []
    else:
        d["tree_structure"] = _node_to_dict(t, 0)
    return d


def dump_model_dict(
    gbdt: GBDT, cfg: Config, num_iteration: int = -1, start_iteration: int = 0,
    importance_type: str = "split",
) -> Dict[str, Any]:
    """JSON model dump (gbdt_model_text.cpp:24 DumpModel), as returned by
    Booster.dump_model()."""
    ds = gbdt.train_set
    feature_names = ds.feature_names if ds is not None else getattr(gbdt, "feature_names", [])
    feature_infos = ds.feature_infos() if ds is not None else getattr(
        gbdt, "feature_infos_", ["none"] * len(feature_names))
    K = gbdt.num_class

    total_iteration = len(gbdt.models) // K
    start_iteration = max(0, min(start_iteration, total_iteration))
    num_used = len(gbdt.models)
    if num_iteration > 0:
        num_used = min((start_iteration + num_iteration) * K, num_used)
    start_model = start_iteration * K

    infos = []
    for s in feature_infos:
        if s.startswith("["):
            lo, hi = s[1:-1].split(":")
            infos.append({"min_value": float(lo), "max_value": float(hi), "values": []})
        elif s and s != "none":
            infos.append({
                "min_value": 0, "max_value": 0,
                "values": [int(v) for v in s.split(":")],
            })
        else:
            infos.append({"min_value": 0, "max_value": 0, "values": []})

    # importances over exactly the dumped tree range
    imp = np.zeros(len(feature_names))
    for i in range(start_model, num_used):
        t = gbdt.models[i]
        if importance_type == "gain":
            imp += t.feature_importance_gain(len(feature_names))
        else:
            imp += t.feature_importance_split(len(feature_names))
    cast = float if importance_type == "gain" else int
    pairs = [(cast(imp[i]), feature_names[i]) for i in range(len(feature_names)) if imp[i] > 0]
    pairs.sort(key=lambda p: -p[0])

    return {
        "name": "tree",
        "version": MODEL_VERSION,
        "num_class": cfg.num_class,
        "num_tree_per_iteration": K,
        "label_index": 0,
        "max_feature_idx": len(feature_names) - 1,
        "objective": _objective_to_string(cfg),
        "average_output": bool(gbdt.average_output),
        "feature_names": list(feature_names),
        "monotone_constraints": list(cfg.monotone_constraints),
        "feature_infos": dict(zip(feature_names, infos)),
        "tree_info": [
            tree_to_dict(gbdt.models[i], i - start_model)
            for i in range(start_model, num_used)
        ],
        "feature_importances": {name: v for v, name in pairs},
        "pandas_categorical": None,
    }


def _parse_array(s: str, typ) -> np.ndarray:
    s = s.strip()
    if not s:
        return np.asarray([], dtype=typ)
    return np.asarray([typ(x) for x in s.split(" ")], dtype=typ)


def parse_tree_block(lines: Dict[str, str]) -> Tree:
    n = int(lines["num_leaves"])
    t = Tree(num_leaves=n)
    t.num_cat = int(lines.get("num_cat", "0"))
    t.split_feature = _parse_array(lines.get("split_feature", ""), np.int32)
    t.split_gain = _parse_array(lines.get("split_gain", ""), np.float64)
    t.threshold = _parse_array(lines.get("threshold", ""), np.float64)
    t.decision_type = _parse_array(lines.get("decision_type", ""), np.int32)
    t.left_child = _parse_array(lines.get("left_child", ""), np.int32)
    t.right_child = _parse_array(lines.get("right_child", ""), np.int32)
    t.leaf_value = _parse_array(lines.get("leaf_value", "0"), np.float64)
    if len(t.leaf_value) == 0:
        t.leaf_value = np.zeros(n, np.float64)
    t.leaf_weight = _parse_array(lines.get("leaf_weight", ""), np.float64)
    t.leaf_count = _parse_array(lines.get("leaf_count", ""), np.int64)
    t.internal_value = _parse_array(lines.get("internal_value", ""), np.float64)
    t.internal_weight = _parse_array(lines.get("internal_weight", ""), np.float64)
    t.internal_count = _parse_array(lines.get("internal_count", ""), np.int64)
    if t.num_cat > 0:
        t.cat_boundaries = _parse_array(lines["cat_boundaries"], np.int64)
        t.cat_threshold = _parse_array(lines["cat_threshold"], np.uint32).astype(np.uint32)
    t.is_linear = lines.get("is_linear", "0").strip() == "1"
    if t.is_linear:
        t.leaf_const = _parse_array(lines.get("leaf_const", ""), np.float64)
        if len(t.leaf_const) < n:
            t.leaf_const = np.concatenate(
                [t.leaf_const, np.zeros(n - len(t.leaf_const))]
            )
        nfeat = _parse_array(lines.get("num_features", ""), np.int64)
        flat_f = _parse_array(lines.get("leaf_features", ""), np.int64)
        flat_c = _parse_array(lines.get("leaf_coeff", ""), np.float64)
        t.leaf_features, t.leaf_coeff = [], []
        pos = 0
        for li in range(n):
            k = int(nfeat[li]) if li < len(nfeat) else 0
            t.leaf_features.append([int(x) for x in flat_f[pos : pos + k]])
            t.leaf_coeff.append([float(x) for x in flat_c[pos : pos + k]])
            pos += k
    t.shrinkage = float(lines.get("shrinkage", "1"))
    return t


def _parse_objective(s: str) -> Dict[str, Any]:
    parts = s.strip().split(" ")
    out: Dict[str, Any] = {"objective": parts[0]}
    for p in parts[1:]:
        if ":" in p:
            k, v = p.split(":", 1)
            out[k] = v
    return out


def load_model_string(model_str: str) -> Tuple[Config, GBDT]:
    """Parse a text model (reference LoadModelFromString) into a
    prediction-capable GBDT."""
    lines = model_str.split("\n")
    header: Dict[str, str] = {}
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            break
        if line == "average_output":
            header["average_output"] = "1"
        elif "=" in line:
            k, v = line.split("=", 1)
            header[k.strip()] = v
        i += 1

    params: Dict[str, Any] = {}
    if "objective" in header:
        obj = _parse_objective(header["objective"])
        params["objective"] = obj["objective"]
        if "num_class" in obj:
            params["num_class"] = int(obj["num_class"])
        if "sigmoid" in obj:
            params["sigmoid"] = float(obj["sigmoid"])
        if "alpha" in obj:
            params["alpha"] = float(obj["alpha"])
        if "c" in obj:
            params["fair_c"] = float(obj["c"])
        if "tweedie_variance_power" in obj:
            params["tweedie_variance_power"] = float(obj["tweedie_variance_power"])
    cfg = Config(params)
    gbdt = GBDT(cfg, None)
    gbdt.num_class = int(header.get("num_tree_per_iteration", "1"))
    gbdt.average_output = header.get("average_output") == "1"
    gbdt.feature_names = header.get("feature_names", "").split(" ") if header.get("feature_names") else []
    gbdt.feature_infos_ = header.get("feature_infos", "").split(" ") if header.get("feature_infos") else []

    # tree blocks
    trees: List[Tree] = []
    cur: Optional[Dict[str, str]] = None
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            if cur is not None:
                trees.append(parse_tree_block(cur))
            cur = {}
        elif line == "end of trees":
            if cur is not None:
                trees.append(parse_tree_block(cur))
                cur = None
            break
        elif "=" in line and cur is not None:
            k, v = line.split("=", 1)
            cur[k] = v
        i += 1
    if cur is not None:
        trees.append(parse_tree_block(cur))
    gbdt.models = trees
    return cfg, gbdt


# ---------------------------------------------------------------------------
# JSON model loading: the inverse of dump_model_dict, so a Booster
# round-trips through its dump_model() JSON (the registry's second
# interop surface next to the text format; the reference only WRITES
# JSON — DumpModel has no C++ loader — so this is a deliberate
# extension for the serving registry).

_MISSING_TYPE_BITS = {"None": 0, "Zero": 1, "NaN": 2}


def tree_from_dict(d: Dict[str, Any]) -> Tree:
    """Nested tree_structure dict (tree_to_dict output) -> Tree."""
    n = int(d["num_leaves"])
    t = Tree(num_leaves=n, shrinkage=float(d.get("shrinkage", 1.0)))
    t.is_linear = bool(d.get("is_linear", False))
    root = d.get("tree_structure", {})
    if t.is_linear:
        t.leaf_const = np.zeros(n, np.float64)
        t.leaf_features = [[] for _ in range(n)]
        t.leaf_coeff = [[] for _ in range(n)]
    if n <= 1:
        t.leaf_value = np.asarray([float(root.get("leaf_value", 0.0))])
        t.leaf_count = np.asarray([int(root.get("leaf_count", 0))], np.int64)
        t.leaf_weight = np.zeros(1, np.float64)
        if t.is_linear:
            t.leaf_const[0] = float(
                root.get("leaf_const", root.get("leaf_value", 0.0))
            )
        return t
    m = n - 1
    t.split_feature = np.zeros(m, np.int32)
    t.split_gain = np.zeros(m, np.float64)
    t.threshold = np.zeros(m, np.float64)
    t.decision_type = np.zeros(m, np.int32)
    t.left_child = np.zeros(m, np.int32)
    t.right_child = np.zeros(m, np.int32)
    t.internal_value = np.zeros(m, np.float64)
    t.internal_weight = np.zeros(m, np.float64)
    t.internal_count = np.zeros(m, np.int64)
    t.leaf_value = np.zeros(n, np.float64)
    t.leaf_weight = np.zeros(n, np.float64)
    t.leaf_count = np.zeros(n, np.int64)
    cat_boundaries = [0]
    cat_threshold: List[int] = []
    n_cat = 0

    def child_ix(node: Dict[str, Any]) -> int:
        if "split_index" in node:
            return int(node["split_index"])
        return ~int(node.get("leaf_index", 0))

    stack = [root]
    while stack:
        node = stack.pop()
        if "split_index" not in node:  # leaf
            li = int(node.get("leaf_index", 0))
            t.leaf_value[li] = float(node.get("leaf_value", 0.0))
            t.leaf_weight[li] = float(node.get("leaf_weight", 0.0))
            t.leaf_count[li] = int(node.get("leaf_count", 0))
            if t.is_linear:
                t.leaf_const[li] = float(
                    node.get("leaf_const", node.get("leaf_value", 0.0))
                )
                t.leaf_features[li] = [
                    int(f) for f in node.get("leaf_features", [])
                ]
                t.leaf_coeff[li] = [
                    float(c) for c in node.get("leaf_coeff", [])
                ]
            continue
        i = int(node["split_index"])
        t.split_feature[i] = int(node["split_feature"])
        t.split_gain[i] = float(node.get("split_gain", 0.0))
        dt = 0
        if node.get("decision_type") == "==":  # categorical bitset
            dt |= 1
            cats = [int(c) for c in str(node["threshold"]).split("||") if c]
            n_words = (max(cats) // 32 + 1) if cats else 1
            words = [0] * n_words
            for cv in cats:
                words[cv // 32] |= 1 << (cv % 32)
            t.threshold[i] = float(n_cat)
            cat_threshold.extend(words)
            cat_boundaries.append(len(cat_threshold))
            n_cat += 1
        else:
            t.threshold[i] = float(node["threshold"])
        if node.get("default_left"):
            dt |= 2
        dt |= _MISSING_TYPE_BITS.get(str(node.get("missing_type")), 0) << 2
        t.decision_type[i] = dt
        t.internal_value[i] = float(node.get("internal_value", 0.0))
        t.internal_weight[i] = float(node.get("internal_weight", 0.0))
        t.internal_count[i] = int(node.get("internal_count", 0))
        left, right = node["left_child"], node["right_child"]
        t.left_child[i] = child_ix(left)
        t.right_child[i] = child_ix(right)
        stack.append(right)
        stack.append(left)
    t.num_cat = n_cat
    t.cat_boundaries = np.asarray(cat_boundaries, np.int64)
    t.cat_threshold = np.asarray(cat_threshold, np.uint32)
    return t


def load_model_dict(d: Dict[str, Any]) -> Tuple[Config, GBDT]:
    """dump_model_dict output -> prediction-capable (Config, GBDT)."""
    params: Dict[str, Any] = {}
    obj = _parse_objective(str(d.get("objective", "regression")))
    params["objective"] = obj["objective"]
    for src, dst, typ in (("num_class", "num_class", int),
                          ("sigmoid", "sigmoid", float),
                          ("alpha", "alpha", float),
                          ("c", "fair_c", float),
                          ("tweedie_variance_power",
                           "tweedie_variance_power", float)):
        if src in obj:
            params[dst] = typ(obj[src])
    cfg = Config(params)
    gbdt = GBDT(cfg, None)
    gbdt.num_class = int(d.get("num_tree_per_iteration", 1))
    gbdt.average_output = bool(d.get("average_output", False))
    gbdt.feature_names = list(d.get("feature_names", []))
    infos = []
    for name in gbdt.feature_names:
        fi = (d.get("feature_infos") or {}).get(name)
        if not fi:
            infos.append("none")
        elif fi.get("values"):
            infos.append(":".join(str(int(v)) for v in fi["values"]))
        elif fi.get("min_value") or fi.get("max_value"):
            infos.append(f"[{fi['min_value']:g}:{fi['max_value']:g}]")
        else:
            infos.append("none")
    gbdt.feature_infos_ = infos
    gbdt.models = [tree_from_dict(td) for td in d.get("tree_info", [])]
    return cfg, gbdt


# ---------------------------------------------------------------------------
# convert_model: if-else C++ export (reference GBDT::SaveModelToIfElse,
# src/boosting/gbdt_model_text.cpp:289 + Tree::ToIfElse, src/io/tree.cpp:566).
# Deviation (deliberate): the reference emits member-function snippets
# that only compile inside its own build tree; this emits a SELF-CONTAINED
# translation unit with the same PredictTree{i} functions plus an
# `extern "C" Predict` entry, so the artifact is usable standalone. The
# ByMap variants are not emitted.

def _node_if_else(t: Tree, node: int, indent: str) -> str:
    from .tree import _CAT_MASK, _DEFAULT_LEFT_MASK

    if node < 0:  # leaf
        return f"{indent}return {float(t.leaf_value[~node])!r};\n"
    dt = int(t.decision_type[node])
    f = int(t.split_feature[node])
    out = [f"{indent}fval = arr[{f}];\n"]
    if dt & _CAT_MASK:
        ci = int(t.threshold[node])
        lo = int(t.cat_boundaries[ci])
        hi = int(t.cat_boundaries[ci + 1])
        out.append(
            f"{indent}ifv = std::isnan(fval) ? -1 : (int)fval;\n"
            f"{indent}if (ifv >= 0 && ifv < {32 * (hi - lo)} && "
            f"((cat_threshold[{lo} + ifv / 32] >> (ifv & 31)) & 1)) {{\n"
        )
    else:
        mt = (dt >> 2) & 3
        dl = bool(dt & _DEFAULT_LEFT_MASK)
        thr = repr(float(t.threshold[node]))
        if mt != 2:  # missing != NaN: NaN behaves as 0.0 (tree.h Decision)
            out.append(f"{indent}if (std::isnan(fval)) fval = 0.0;\n")
        if mt == 2:
            cond = (f"std::isnan(fval) || fval <= {thr}" if dl
                    else f"!std::isnan(fval) && fval <= {thr}")
        elif mt == 1:
            z = "std::fabs(fval) <= 1e-35"
            cond = (f"({z}) || fval <= {thr}" if dl
                    else f"!({z}) && fval <= {thr}")
        else:
            cond = f"fval <= {thr}"
        out.append(f"{indent}if ({cond}) {{\n")
    out.append(_node_if_else(t, int(t.left_child[node]), indent + "  "))
    out.append(f"{indent}}} else {{\n")
    out.append(_node_if_else(t, int(t.right_child[node]), indent + "  "))
    out.append(f"{indent}}}\n")
    return "".join(out)


def model_to_if_else(models: List[Tree], num_class: int,
                     average_output: bool = False) -> str:
    """The full if-else translation unit for a trained model."""
    import sys

    if any(t.is_linear for t in models):
        from . import log

        log.fatal(
            "convert_model does not support linear trees (leaf_coeff "
            "terms have no if-else form in the reference either)"
        )
    # chain-shaped trees recurse once per level; bound is num_leaves.
    # Raise the interpreter limit only for the duration of the walk —
    # it is process-global state and must not outlive this call.
    max_leaves = max((t.num_leaves for t in models), default=1)
    old_limit = sys.getrecursionlimit()
    parts = [
        "// generated by lightgbm_tpu convert_model "
        "(reference: GBDT::SaveModelToIfElse)\n",
        "#include <cmath>\n#include <cstring>\n\n",
    ]
    try:
        sys.setrecursionlimit(max(old_limit, 4 * max_leaves + 1000))
        for i, t in enumerate(models):
            parts.append(f"double PredictTree{i}(const double* arr) {{\n")
            if t.num_leaves <= 1:
                parts.append(f"  return {float(t.leaf_value[0])!r};\n}}\n\n")
                continue
            if len(t.cat_threshold):
                words = ",".join(str(int(w)) for w in t.cat_threshold)
                parts.append(
                    f"  static const unsigned int cat_threshold[] = "
                    f"{{{words}}};\n"
                )
            parts.append("  double fval = 0.0; (void)fval;\n")
            if len(t.cat_threshold):
                parts.append("  int ifv = 0; (void)ifv;\n")
            parts.append(_node_if_else(t, 0, "  "))
            parts.append("}\n\n")
    finally:
        sys.setrecursionlimit(old_limit)

    n = len(models)
    ptrs = ", ".join(f"PredictTree{i}" for i in range(n))
    parts.append(
        f"double (*PredictTreePtr[])(const double*) = {{ {ptrs} }};\n\n"
        f"static const int num_tree_per_iteration_ = {num_class};\n"
        f"static const int num_iteration_for_pred_ = {n // max(num_class, 1)};\n\n"
        "extern \"C\" void Predict(const double* features, double* output) {\n"
        "  std::memset(output, 0, sizeof(double) * num_tree_per_iteration_);\n"
        "  for (int i = 0; i < num_iteration_for_pred_; ++i)\n"
        "    for (int k = 0; k < num_tree_per_iteration_; ++k)\n"
        "      output[k] += (*PredictTreePtr[i * num_tree_per_iteration_ + k])(features);\n"
    )
    if average_output:  # boosting=rf reports the MEAN of the trees
        parts.append(
            "  for (int k = 0; k < num_tree_per_iteration_; ++k)\n"
            "    output[k] /= num_iteration_for_pred_;\n"
        )
    parts.append("}\n")
    return "".join(parts)
