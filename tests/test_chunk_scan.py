"""Chunk-scan fused boosting (ISSUE 18): `fused_dispatch` runs rounds
as C-round `lax.scan` chunks — one executable launch per chunk — and
must be BIT-identical to the per-round-dispatch loop
(`tpu_chunk_scan=off`) on the same seed: model text, eval records,
early-stop truncation, and the no-splittable-leaf stop. The chunk
ladder bounds distinct scan executables at len(DEFAULT_CHUNK_LADDER)
for any round count (retrace-guard contract)."""

import re

import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.callback as cbm
from lightgbm_tpu.boosting import _FUSED_STEP_CACHE, _pick_chunk
from lightgbm_tpu.config import DEFAULT_CHUNK_LADDER


def _norm(model_str: str) -> str:
    # the echoed parameter block necessarily differs between the paths
    return re.sub(r"\[tpu_chunk_scan: \w+\]\n", "", model_str)


def _expected_dispatches(n: int) -> int:
    d, left = 0, n
    while left > 0:
        left -= min(_pick_chunk(left, DEFAULT_CHUNK_LADDER), left)
        d += 1
    return d


def _train(params, X, y, rounds, mode, Xv=None, yv=None):
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    valid_sets = valid_names = None
    if Xv is not None:
        valid_sets = [lgb.Dataset(Xv, label=yv, reference=ds,
                                  free_raw_data=False)]
        valid_names = ["va"]
    res = {}
    bst = lgb.train(dict(params, tpu_chunk_scan=mode), ds,
                    num_boost_round=rounds, valid_sets=valid_sets,
                    valid_names=valid_names,
                    callbacks=[cbm.record_evaluation(res)])
    return bst, res


def _assert_bit_identical(params, X, y, rounds, Xv=None, yv=None):
    bc, rc = _train(params, X, y, rounds, "auto", Xv, yv)
    bp, rp = _train(params, X, y, rounds, "off", Xv, yv)
    assert _norm(bc.model_to_string()) == _norm(bp.model_to_string())
    assert rc == rp  # eval records, exact float equality
    return bc, bp


def test_chunk_vs_per_round_regression_bit_identical():
    rs = np.random.RandomState(7)
    X = rs.randn(800, 6)
    y = X @ rs.randn(6) + 0.3 * rs.randn(800)
    bc, bp = _assert_bit_identical(
        {"objective": "regression", "num_leaves": 7, "metric": "l2",
         "verbosity": -1},
        X[:600], y[:600], 8, X[600:], y[600:],
    )
    # dispatch-count probe: one _f_step-equivalent launch per CHUNK on
    # the scan path, one per round on the baseline
    assert bc._gbdt.fused_dispatch_count == _expected_dispatches(8)
    assert bc._gbdt.fused_dispatch_count < 8
    assert bp._gbdt.fused_dispatch_count == 8


def test_chunk_vs_per_round_binary_sampled_bit_identical():
    """Bagging + feature_fraction exercise the fold_in(seed, it*K+k)
    RNG keying: frozen-`it` masked tail rounds must not consume the
    streams the next chunk replays."""
    rs = np.random.RandomState(13)
    X = rs.randn(900, 8)
    y = ((X @ rs.randn(8) + 0.3 * rs.randn(900)) > 0).astype(float)
    _assert_bit_identical(
        {"objective": "binary", "num_leaves": 7, "metric": "auc",
         "bagging_fraction": 0.6, "bagging_freq": 2,
         "feature_fraction": 0.7, "verbosity": -1},
        X[:700], y[:700], 7, X[700:], y[700:],
    )


def test_chunk_vs_per_round_multiclass_bit_identical():
    rs = np.random.RandomState(9)
    X = rs.randn(600, 6)
    y = np.argmax(X[:, :3] + 0.5 * rs.randn(600, 3), axis=1).astype(float)
    bc, _bp = _assert_bit_identical(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "metric": "multi_logloss", "verbosity": -1},
        X[:450], y[:450], 6, X[450:], y[450:],
    )
    assert bc._gbdt.fused_dispatch_count == _expected_dispatches(6)


@pytest.mark.slow  # 40-round pair of trainings — over the fast-tier budget
def test_early_stop_mid_chunk_truncates_bit_exactly():
    """Early stop fires inside a dispatched chunk: fused_truncate must
    leave model text, round count, and best_iteration identical to the
    unscanned loop (reference stop-timing semantics)."""
    rs = np.random.RandomState(5)
    X = rs.randn(900, 5)
    y = (X[:, 0] + 0.5 * rs.randn(900) > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "metric": "auc",
              "verbosity": -1, "early_stopping_round": 3}
    bc, rc = _train(params, X[:600], y[:600], 40, "auto",
                    X[600:], y[600:])
    bp, rp = _train(params, X[:600], y[:600], 40, "off",
                    X[600:], y[600:])
    assert bc.best_iteration == bp.best_iteration >= 1
    assert bc.num_trees() == bp.num_trees() == bc.best_iteration + 3
    assert bc.num_trees() < 40  # actually stopped mid-chunk
    assert _norm(bc.model_to_string()) == _norm(bp.model_to_string())
    assert rc == rp


def test_no_splittable_leaf_stop_matches():
    """The device `stopped` mask must reproduce the host loop's
    no-splittable-leaf stop (gbdt.cpp:429-452): post-stop rounds are
    algebraic no-ops and the model truncates at the stop round."""
    rs = np.random.RandomState(1)
    X = rs.randn(200, 4)
    y = X[:, 0] + 0.1 * rs.randn(200)
    params = {"objective": "regression", "num_leaves": 7,
              "verbosity": -1, "min_data_in_leaf": 120}
    bc, _ = _train(params, X, y, 8, "auto")
    bp, _ = _train(params, X, y, 8, "off")
    assert bc.num_trees() == bp.num_trees() == 1  # the kept bias tree
    assert _norm(bc.model_to_string()) == _norm(bp.model_to_string())


@pytest.mark.slow  # 100/13/64-round trainings warm the whole ladder
def test_retrace_guard_mixed_chunk_sizes(retrace_guard):
    """13, 64, and 100 rounds force mixed ladder rungs plus masked-tail
    chunks; across all of it at most len(DEFAULT_CHUNK_LADDER) scan
    executables exist and repeat trainings never retrace them."""
    rs = np.random.RandomState(2)
    X = rs.randn(1000, 5)
    y = X @ rs.randn(5) + 0.2 * rs.randn(1000)
    params = {"objective": "regression", "num_leaves": 4,
              "verbosity": -1, "min_data_in_leaf": 2}

    def train(n):
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        return lgb.train(dict(params), ds, num_boost_round=n)

    _FUSED_STEP_CACHE.clear()
    b100 = train(100)
    assert b100.num_trees() == 100
    assert len(_FUSED_STEP_CACHE) == 1
    prog = next(iter(_FUSED_STEP_CACHE.values()))
    rungs = set(prog.chunks)
    assert rungs <= set(DEFAULT_CHUNK_LADDER)
    assert len(rungs) <= len(DEFAULT_CHUNK_LADDER)
    chunk_fns = list(prog.chunks.values())
    with retrace_guard(entry_points=chunk_fns, max_retraces=0,
                       what="mixed chunk sizes over a warm ladder"):
        assert train(13).num_trees() == 13
        assert train(64).num_trees() == 64
    # repeat trainings introduced no rungs beyond the ladder either
    assert set(prog.chunks) == rungs
    assert b100._gbdt.fused_dispatch_count == _expected_dispatches(64) \
        + _expected_dispatches(36)  # driver chunks at _check_every=64
