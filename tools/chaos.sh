#!/usr/bin/env bash
# Chaos suite runner (docs/RESILIENCE.md): every test marked `chaos` —
# deterministic fault injection (resilience/faultinject.py) driving
# crash-at-round-N + resume bit-match, SIGKILL'd subprocess resume,
# serving deadline expiry / queue admission 503s / device-fault host
# fallback, and anomaly rollback recovery.
#
# The fast chaos tests also run inside the tier-1 gate (they carry no
# `slow` mark); this entry point runs the FULL chaos set, including the
# slow SIGKILL subprocess test, in isolation:
#
#   tools/chaos.sh                 # all chaos tests
#   tools/chaos.sh -k sigkill      # extra pytest args pass through
#
# Forced onto the CPU backend: fault injection and recovery must work
# exactly when the accelerator is the thing that broke.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
  -p no:cacheprovider "$@"
