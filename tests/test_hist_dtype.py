"""Int-packed histogram channels on the DEFAULT path (ISSUE 12
tentpole): the tpu_hist_dtype policy resolution, training parity of the
int16/int8 channel layouts against bf16x2 across tasks, stochastic-
rounding determinism under a fixed seed, the narrowest-exact
reduce-scatter wire dtype policy, hist_dtype provenance through the run
manifest and the flight recorder, and the bench backend-probe
fail-fast."""

from __future__ import annotations

import importlib.util
import json
import subprocess
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.learner.histogram import rs_wire_dtype
from lightgbm_tpu.learner.quantize import (
    HIST_DTYPE_LEVELS,
    resolve_hist_dtype,
)

REPO = Path(__file__).resolve().parents[1]


# --------------------------------------------------- policy resolution
def test_resolve_hist_dtype_default_path():
    # auto: int-packed on the on-chip rounds path, bf16x2 off it
    assert resolve_hist_dtype("auto", False, 16, True) == ("int16", 256,
                                                           None)
    assert resolve_hist_dtype("auto", False, 16, False) == ("bf16x2", 0,
                                                            None)
    # auto stays bit-exact bf16x2 on non-TPU backends (same contract as
    # tpu_growth_mode=auto); an EXPLICIT request is honored anywhere
    assert resolve_hist_dtype("auto", False, 16, True,
                              on_tpu=False)[0] == "bf16x2"
    assert resolve_hist_dtype("int16", False, 16, True,
                              on_tpu=False)[0] == "int16"
    # float32 is the legacy synonym for the f32 hi/lo split
    assert resolve_hist_dtype("float32", False, 16, True)[0] == "bf16x2"
    # explicit narrow layouts carry their level counts
    assert resolve_hist_dtype("int16", False, 16, True) == ("int16", 256,
                                                            None)
    assert resolve_hist_dtype("int8", False, 16, True) == ("int8", 127,
                                                           None)
    assert HIST_DTYPE_LEVELS == {"int16": 256, "int8": 127}


def test_resolve_hist_dtype_off_rounds_falls_back_with_warning():
    resolved, levels, warn = resolve_hist_dtype("int16", False, 16, False)
    assert (resolved, levels) == ("bf16x2", 0)
    assert warn is not None and "rounds" in warn


def test_resolve_hist_dtype_quant_api_governs():
    # under use_quantized_grad the PUBLIC quant levels decide; the
    # internal policy must not override them (levels stays 0)
    assert resolve_hist_dtype("auto", True, 16, True) == ("int8", 0, None)
    assert resolve_hist_dtype("auto", True, 200, True) == ("int16", 0,
                                                           None)
    assert resolve_hist_dtype("auto", True, 16, False) == ("bf16x2", 0,
                                                           None)
    # even an explicit narrow request defers to the quant API
    assert resolve_hist_dtype("int16", True, 16, True)[1] == 0


# ------------------------------------------------------ rs wire policy
def test_rs_wire_dtype_narrowest_exact():
    # 128 rows * 8 ranks * 16 levels = 16384 < 2^15: int16
    assert rs_wire_dtype(128, 8, 16) == "int16"
    # 256 rows hits exactly 2^15 — one short of exact, steps to int32
    assert rs_wire_dtype(256, 8, 16) == "int32"
    # inside the int32 bounds (2048*8*16 < 2^31, 2048*16 < 2^24)
    assert rs_wire_dtype(2048, 8, 16) == "int32"
    # past the per-rank f32 exactness bound (131072*256 > 2^24): None
    assert rs_wire_dtype(131072, 8, 256) is None


# ----------------------------------------------------- training parity
def _train(X, y, params, hd, n_rounds, **ds_kw):
    ds = lgb.Dataset(X, label=y, free_raw_data=False, **ds_kw)
    return lgb.train(
        dict(params, tpu_hist_dtype=hd, tpu_growth_mode="rounds",
             verbose=-1, seed=3, deterministic=True),
        ds, num_boost_round=n_rounds,
    )


@pytest.mark.parametrize("hd", ["int16", "int8"])
def test_binary_parity_int_packed(hd):
    from sklearn.datasets import make_classification
    from sklearn.metrics import roc_auc_score

    X, y = make_classification(2000, 10, random_state=7)
    X = X.astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1}
    auc_ref = roc_auc_score(y, _train(X, y, params, "bf16x2",
                                      12).predict(X))
    b = _train(X, y, params, hd, 12)
    assert b._gbdt.hist_dtype == hd
    assert b._gbdt._int_packed
    auc = roc_auc_score(y, b.predict(X))
    # stochastic rounding perturbs individual splits; the model-level
    # metric must stay within noise of the bf16x2 channels
    assert abs(auc - auc_ref) < 2e-3
    assert auc > 0.95


def test_regression_parity_int_packed():
    from sklearn.datasets import make_regression

    X, y = make_regression(2000, 8, noise=10.0, random_state=1)
    X, y = X.astype(np.float32), y.astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.1}
    p_ref = _train(X, y, params, "bf16x2", 12).predict(X)
    p = _train(X, y, params, "int16", 12).predict(X)
    rmse_ref = float(np.sqrt(np.mean((p_ref - y) ** 2)))
    rmse = float(np.sqrt(np.mean((p - y) ** 2)))
    assert abs(rmse - rmse_ref) / rmse_ref < 0.01


def test_multiclass_parity_int_packed():
    from sklearn.datasets import make_classification
    from sklearn.metrics import log_loss

    X, y = make_classification(1500, 10, n_informative=6, n_classes=3,
                               random_state=5)
    X = X.astype(np.float32)
    params = {"objective": "multiclass", "num_class": 3,
              "num_leaves": 15, "learning_rate": 0.1}
    ll_ref = log_loss(y, _train(X, y, params, "bf16x2", 8).predict(X))
    ll = log_loss(y, _train(X, y, params, "int16", 8).predict(X))
    assert abs(ll - ll_ref) < 5e-3


def test_int_packed_deterministic_under_fixed_seed():
    """Stochastic rounding is keyed on (data_random_seed, iteration):
    two identical runs must produce bit-identical predictions."""
    from sklearn.datasets import make_classification

    X, y = make_classification(800, 8, random_state=2)
    X = X.astype(np.float32)
    params = {"objective": "binary", "num_leaves": 11,
              "learning_rate": 0.1}
    p1 = _train(X, y, params, "int16", 6).predict(X)
    p2 = _train(X, y, params, "int16", 6).predict(X)
    np.testing.assert_array_equal(p1, p2)


def test_int_packed_off_rounds_path_resolves_bf16x2():
    """Explicit int16 off the rounds growth path (CPU auto mode) must
    fall back to bf16x2 — the sequential growers have no integer
    channels — and still train."""
    from sklearn.datasets import make_classification

    X, y = make_classification(600, 6, random_state=4)
    ds = lgb.Dataset(X.astype(np.float32), label=y, free_raw_data=False)
    b = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                   "tpu_hist_dtype": "int16", "tpu_growth_mode": "auto"},
                  ds, num_boost_round=3)
    assert b._gbdt.hist_dtype == "bf16x2"
    assert not b._gbdt._int_packed


# ------------------------------------------------- provenance round-trip
def test_hist_dtype_in_manifest_and_flight_recorder(tmp_path):
    from sklearn.datasets import make_classification

    from lightgbm_tpu.obs.manifest import build_manifest
    from lightgbm_tpu.obs.recorder import read_stream

    X, y = make_classification(800, 6, random_state=9)
    ds = lgb.Dataset(X.astype(np.float32), label=y, free_raw_data=False)
    fr = tmp_path / "fr.jsonl"
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "tpu_hist_dtype": "int16", "tpu_growth_mode": "rounds",
              "record_file": str(fr)}
    bst = lgb.train(params, ds, num_boost_round=3)

    # the explicit request sticks on the rounds path (auto only flips
    # on TPU hardware); the booster reports the RESOLVED layout
    assert bst._gbdt.hist_dtype == "int16"
    from lightgbm_tpu.config import Config

    m = build_manifest(config=Config(params), booster=bst)
    assert m["config"]["resolved"]["tpu_hist_dtype"] == "int16"
    assert m["model"]["hist_dtype"] == "int16"

    recs = read_stream(str(fr))
    assert recs and all(r.get("hist_dtype") == "int16" for r in recs)
    # and the stream survives a JSON round-trip with the new key
    assert json.loads(json.dumps(recs))[0]["hist_dtype"] == "int16"


# ------------------------------------------------- bench probe fail-fast
def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "_bench_under_test", REPO / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_backend_times_out_fail_fast(monkeypatch):
    """A probe TIMEOUT must fall back to cpu after ONE attempt — the
    old behaviour burned retries x timeout_s of driver budget on a
    wedged tunnel (two serial 300 s waits in BENCH_r05)."""
    bench = _load_bench()
    calls = []

    def fake_run(*a, **kw):
        calls.append(kw.get("timeout"))
        raise subprocess.TimeoutExpired(cmd="probe", timeout=kw["timeout"])

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: pytest.fail("slept on a timeout"))
    assert bench.probe_backend(0.01, retries=3) == "cpu"
    assert len(calls) == 1


def test_probe_backend_still_retries_hard_failures(monkeypatch):
    """Non-timeout probe failures (tunnel resets clear on later
    attempts) keep the backoff-retry schedule."""
    bench = _load_bench()
    attempts = []

    def fake_run(*a, **kw):
        attempts.append(1)
        if len(attempts) < 2:
            raise OSError("transient tunnel reset")

        class R:
            returncode = 0
            stdout = "tpu\n"
            stderr = ""

        return R()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.probe_backend(5, retries=3) == "tpu"
    assert len(attempts) == 2
